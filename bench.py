#!/usr/bin/env python
"""Benchmark: scheduler-session latency on the BASELINE north-star config
(50k pods × 10k nodes, gang + predicates) — device kernel vs the native
(C++ 16-thread) greedy allocate, the stand-in for the reference's stock Go
allocate hot loop (no Go toolchain in this image; see
volcano_tpu/native/__init__.py).

Prints ONE JSON line:
  {"metric": ..., "value": <device session ms>, "unit": "ms",
   "vs_baseline": <baseline_ms / device_ms>}  (>1 ⇒ faster than reference)

Flags: default runs ALL BASELINE configs (headline last on stdout, the
rest on stderr); --config NAME runs one; --quick (1k×100 smoke);
--check runs the formulation-equivalence gates and exits.
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import sys
import time

import numpy as np

# the bench/prof_* scripts and bench_action share one setup module
# (binder, tier config, cache builder) — see bench/_profsetup.py
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench"))


def _gc_quiesce() -> None:
    """Thaw-collect-freeze (volcano_tpu.utils.gcutil — shared with the
    scheduler daemon's --gc-quiesce-period).  Each config leaves
    megabytes of live long-lived state; without freezing, every gen-2
    collection inside the NEXT timed region re-traverses all of it, and
    the measured action latency grows with how many configs ran before
    it (observed 2.1s standalone → 6.5s after four configs at the 50k
    shape).  The bench applies it so numbers reflect the framework, not
    the harness's accumulated garbage."""
    from volcano_tpu.utils.gcutil import gc_quiesce

    gc_quiesce()


def _time(fn, warmup: int = 1, iters: int = 3) -> float:
    """Median wall seconds over ``iters`` after ``warmup`` runs."""
    return _time_r(fn, warmup=warmup, iters=iters)[0]


def _time_r(fn, warmup: int = 1, iters: int = 3):
    """(median wall seconds, last result) — callers that need the output
    reuse a timed run instead of paying an extra full execution."""
    result = None
    for _ in range(warmup):
        result = fn()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        result = fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times)), result


def _relay_probe(in_bytes: int = 0, out_elems: int = 1024):
    """Harness device-link floor probe: a warmed callable timing one
    push of ``in_bytes`` of fresh input + trivial kernel + fetch of
    ``out_elems`` int32 — i.e. the cost any session of this shape pays
    before computing anything.  The dev tunnel adds ~80-110ms of
    round-trip latency per session; production colocates scheduler and
    device (PCIe, <1ms for these volumes).  The headline ``value`` stays
    the UNADJUSTED e2e; the floor and the floor-adjusted compute are
    reported alongside.  Returned as a probe (not a one-shot
    measurement) so callers can INTERLEAVE floor samples with session
    samples — the link is jittery, and floor/session medians from
    disjoint time windows routinely cross, making compute unmeasurable
    (r4: config 3/4 compute_ms null)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def trivial(x, y):
        return y[:out_elems].astype(jnp.int32) + jnp.int32(x.shape[0] % 2)

    payload = np.zeros(max(in_bytes // 4, out_elems), dtype=np.float32)
    out = np.zeros(out_elems, dtype=np.float32)
    np.asarray(trivial(jnp.asarray(payload), jnp.asarray(out)))  # warm

    def probe() -> float:
        t0 = time.perf_counter()
        np.asarray(trivial(jnp.asarray(payload), jnp.asarray(out)))
        return time.perf_counter() - t0

    return probe


def _relay_components(in_bytes: int, out_elems: int, iters: int = 5):
    """Break the synchronous relay floor into the ISSUE-6 components,
    sampled interleaved so all medians share one link-jitter window:

      * ``bus_rtt_ms``   — the bare link round trip (no payload): the
        cost of ANY synchronous device exchange.
      * ``bind_ms``      — the result-delivery leg (fetching an
        assignment of ``out_elems`` over the bare RTT): the leg the
        pipelined commit plane's bind workers drain off-cycle.
      * ``writeback_ms`` — the session-payload staging leg (pushing
        ``in_bytes`` over the result fetch): already overlappable via
        the PR-2 prestage path, now also behind the pipeline.

    Returns (full_s, rtt_s, bind_s, writeback_s); components clamp at 0
    (link jitter can invert adjacent medians)."""
    full = _relay_probe(in_bytes, out_elems)
    bare = _relay_probe(0, 8)
    outp = _relay_probe(0, out_elems)
    fs, bs, os_ = [], [], []
    for _ in range(iters):
        fs.append(full())
        bs.append(bare())
        os_.append(outp())
    f = float(np.median(fs))
    b = float(np.median(bs))
    o = float(np.median(os_))
    return f, b, max(o - b, 0.0), max(f - o, 0.0)


def _serde_legs(n_objs: int, iters: int = 5, codec: "str | None" = None):
    """The serialization share of the relay floor, reported as its own
    pair of legs (``encode_ms`` / ``decode_ms``) so codec wins are
    visible separately from the link (``bus_rtt_ms``): median time to
    encode and decode a commit_batch-shaped body carrying ``n_objs``
    bind writes under the codec a v8 connection would negotiate
    (binary when msgpack is importable, JSON otherwise).  Returns
    ``(encode_s, decode_s)``."""
    from volcano_tpu.bus import protocol

    if codec is None:
        codec = (protocol.CODEC_BINARY if protocol.HAS_BINARY
                 else protocol.CODEC_JSON)
    body = {
        "op": "commit_batch",
        "binds": [
            {"kind": "Pod", "namespace": "default", "name": f"pod-{i}",
             "hostname": f"node-{i % 64}", "rv": i}
            for i in range(max(n_objs, 1))
        ],
    }
    es, ds = [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        wire = protocol.encode_payload(body, codec=codec)
        es.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        protocol.decode_payload(wire, codec=codec)
        ds.append(time.perf_counter() - t0)
    return float(np.median(es)), float(np.median(ds))


def _pipelined_cycle_s(dispatch, k: int = 8, iters: int = 3) -> "float | None":
    """Steady-state per-cycle session latency with the PIPELINED commit
    plane: cycle N's result is drained (the bind workers' device→host
    fetch + commit) while cycle N+1's session is already dispatching —
    the bench-level twin of jax_allocate handing proposals off and
    returning.  Total wall time over k cycles divided by k: the fixed
    link round trip amortizes across the pipeline exactly as it does in
    the running scheduler, leaving per-cycle ≈ compute + dispatch.  min
    over ``iters`` suppresses link-jitter tails (the
    _pipelined_compute_s discipline)."""

    def run() -> float:
        prev = None
        t0 = time.perf_counter()
        for _ in range(k):
            cur = dispatch()          # cycle N+1 dispatches...
            if prev is not None:
                np.asarray(prev)      # ...while cycle N's result commits
            prev = cur
        np.asarray(prev)
        return (time.perf_counter() - t0) / k

    run()  # warm any remaining dispatch setup
    out = min(run() for _ in range(iters))
    return out if out > 0 else None


def _pipelined_compute_s(dispatch, k: int = 16, iters: int = 3) -> "float | None":
    """Pure device-compute estimate for one kernel dispatch (None when
    jitter swamps even the pipelined estimate).

    Enqueue N dispatches back-to-back (async — only the last sync pays
    the link round trip), time N=1 and N=k, and take the slope
    ``(t_k - t_1)/(k - 1)``: fixed costs (RTT, dispatch latency, the
    final fetch) cancel, leaving per-dispatch device compute.  min over
    ``iters`` suppresses link-jitter tails.  Subtracting a separately
    measured floor from e2e (the previous decomposition) fails whenever
    compute ≪ jitter — medians from even interleaved windows cross and
    the estimate goes null (r4/r5 configs 3-4)."""

    def run_n(n):
        out = None
        for _ in range(n):
            out = dispatch()
        out.block_until_ready()

    run_n(1)  # warm any remaining compile/dispatch setup
    t1 = min(_time_once(run_n, 1) for _ in range(iters))
    tk = min(_time_once(run_n, k) for _ in range(iters))
    slope = (tk - t1) / (k - 1)
    # a non-positive slope means jitter swamped even the pipelined
    # estimate — report unmeasurable, not a claimed zero compute
    return slope if slope > 0 else None


def _time_once(fn, *args) -> float:
    t0 = time.perf_counter()
    fn(*args)
    return time.perf_counter() - t0


def _time_interleaved(fn, probe, iters: int = 5):
    """(median fn seconds, median probe seconds), samples alternating
    fn/probe so both medians come from the same link-jitter window."""
    fn_times, probe_times = [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        fn_times.append(time.perf_counter() - t0)
        probe_times.append(probe())
    return float(np.median(fn_times)), float(np.median(probe_times))


def bench_config(name: str, kwargs: dict, iters: int = 5) -> dict:
    from volcano_tpu.ops.dispatch import run_packed_auto as run_packed
    from volcano_tpu.ops.dispatch import select_executor
    from volcano_tpu.ops.synthetic import generate_snapshot
    from volcano_tpu import native

    snap = generate_snapshot(**kwargs)
    # Which executor the framework's auto-dispatch actually runs for this
    # shape — 'native' means the session never touches the device (small
    # sessions use the host C++ path), so vs_baseline is parity by design.
    executor = select_executor(snap)

    # ms-scale sessions need more samples: at ~2ms/session a single
    # scheduler tick of background load swings the 5-iter median 2-4x
    # (observed 0.5x-2.8x across runs of the 1k config)
    area = snap.n_tasks * snap.n_nodes
    if area <= 1_000_000:
        iters = max(iters, 25)

    # Session input volume = what the executor actually ships per
    # steady-state session (pallas: the deduplicated session buffer —
    # cluster planes are device-resident across sessions).
    if executor == "pallas":
        from volcano_tpu.ops.pallas_session import pallas_session_payload_bytes

        in_bytes = pallas_session_payload_bytes(snap)
    else:
        in_bytes = int(
            snap.task_resreq.nbytes
            + snap.task_resreq.shape[0] * 8
            + snap.node_idle.nbytes * 4
        )
    # Device path: end-to-end host→device→assignment latency.  The
    # headline value and vs_baseline use the UNADJUSTED e2e time; the
    # relay floor is reported alongside (compute_ms) for interpretation.
    device_assign = run_packed(snap)  # compile warmup + result
    interleaved_baseline_s = None
    if executor == "native":
        # no device involved: interleave OUR path with the baseline
        # itself so load spikes hit both sides — at ms scale, disjoint
        # sampling windows swing the ratio 0.5x-2.8x run to run while
        # the two sides execute the same C++ loop (parity by design).
        # The baseline keeps its best-of-{1,16}-threads selection (the
        # pooled sweep only wins on some shapes): race once, then
        # interleave with the winner.
        t1t = _time(lambda: native.baseline_allocate(snap, n_threads=1),
                    warmup=1, iters=3)
        t16 = _time(lambda: native.baseline_allocate(snap, n_threads=16),
                    warmup=1, iters=3)
        best_threads = 1 if t1t <= t16 else 16

        def probe_native() -> float:
            t0 = time.perf_counter()
            native.baseline_allocate(snap, n_threads=best_threads)
            return time.perf_counter() - t0

        try:
            e2e_s, interleaved_baseline_s = _time_interleaved(
                lambda: run_packed(snap), probe_native, iters=iters)
        except RuntimeError:
            # baseline died mid-probe; keep the session number, let the
            # baseline block below report null (run_packed_auto itself
            # degrades to the XLA scan on this error)
            e2e_s = _time(lambda: run_packed(snap), warmup=0, iters=iters)
        relay_s = 0.0
    else:
        probe = _relay_probe(in_bytes=in_bytes, out_elems=snap.n_tasks)
        e2e_s, relay_s = _time_interleaved(
            lambda: run_packed(snap), probe, iters=iters)
    # Compute decomposition.  native: the whole e2e IS host compute.
    # pallas: measure device compute directly by pipelining K dispatches
    # before one sync (fixed link costs cancel in the slope) — the
    # earlier e2e-minus-floor subtraction goes null whenever compute is
    # smaller than link jitter.  Other executors (blocked/sharded XLA):
    # fall back to the floor subtraction.
    pipelined_s = None
    if executor == "native":
        compute_s = e2e_s
    elif executor == "pallas":
        from volcano_tpu.ops.pallas_session import make_session_dispatch

        try:
            dispatch, _ = make_session_dispatch(snap, prestage=True)
            compute_s = _pipelined_compute_s(dispatch)
            # steady-state cycle latency with the pipelined commit
            # plane: cycle N's result commit overlaps cycle N+1's
            # dispatch (the framework's bind-worker handoff; session
            # payload staging already overlaps ORDER via the PR-2
            # prestage path)
            pipelined_s = _pipelined_cycle_s(dispatch)
        except Exception:  # noqa: BLE001 — run_packed_auto degrades on
            # the same failure (pallas → blocked); the e2e number above
            # then measured the fallback, so report compute unmeasurable
            compute_s = None
    elif relay_s < e2e_s:
        compute_s = e2e_s - relay_s
    else:
        compute_s = None
    # Relay-floor decomposition (ISSUE 6): the synchronous floor broken
    # into the link RTT, the result-delivery (bind) leg, and the
    # session-payload (writeback) leg — attribution for what the
    # pipeline collapses.  Native sessions never touch the device.
    if executor == "native":
        rtt_s = bind_leg_s = writeback_leg_s = 0.0
        encode_leg_s = decode_leg_s = 0.0
    else:
        _full, rtt_s, bind_leg_s, writeback_leg_s = _relay_components(
            in_bytes, snap.n_tasks
        )
        encode_leg_s, decode_leg_s = _serde_legs(snap.n_tasks)

    # Native baseline — best of 1-thread and 16-thread (the pooled sweep
    # only wins on some shapes; the reference would use whichever is
    # faster).  Single measured run for the big configs.
    # single-sample baselines swing 2x with load (config 3's baseline
    # read 186ms and 361ms in adjacent runs); only the really big shapes
    # (multi-second baselines) stay at one sample
    base_iters = iters if area <= 5_000_000 else (3 if area <= 50_000_000 else 1)
    try:
        if interleaved_baseline_s is not None:
            baseline_s = interleaved_baseline_s
        else:
            baseline_s = min(
                _time(lambda: native.baseline_allocate(snap, n_threads=1),
                      warmup=0, iters=base_iters),
                _time(lambda: native.baseline_allocate(snap, n_threads=16),
                      warmup=0, iters=base_iters),
            )
        baseline_assign = native.baseline_allocate(snap)
        identical = bool(np.array_equal(device_assign, baseline_assign))
    except RuntimeError:
        baseline_s = float("nan")
        identical = False

    placed = int((device_assign >= 0).sum())
    # headline value: the pipelined steady-state cycle when the plane
    # could measure one (pallas sessions), else the synchronous e2e.
    # The synchronous number stays alongside as sync_ms, and the
    # residual relay floor is what the pipeline did NOT hide.
    value_s = pipelined_s if pipelined_s is not None else e2e_s
    if pipelined_s is not None and compute_s is not None:
        resid_relay_s = max(value_s - compute_s, 0.0)
    elif pipelined_s is not None:
        resid_relay_s = None
    else:
        resid_relay_s = relay_s
    return {
        "metric": f"session_latency_{name}",
        "value": round(value_s * 1e3, 3),
        "unit": "ms",
        "vs_baseline": round(baseline_s / value_s, 2)
        if baseline_s == baseline_s
        else None,
        "baseline_ms": round(baseline_s * 1e3, 3) if baseline_s == baseline_s else None,
        "compute_ms": round(compute_s * 1e3, 3) if compute_s is not None else None,
        "relay_floor_ms": round(resid_relay_s * 1e3, 3)
        if resid_relay_s is not None else None,
        "sync_ms": round(e2e_s * 1e3, 3),
        "relay_sync_ms": round(relay_s * 1e3, 3),
        "bus_rtt_ms": round(rtt_s * 1e3, 3),
        "bind_ms": round(bind_leg_s * 1e3, 3),
        "writeback_ms": round(writeback_leg_s * 1e3, 3),
        "encode_ms": round(encode_leg_s * 1e3, 3),
        "decode_ms": round(decode_leg_s * 1e3, 3),
        "pipelined": pipelined_s is not None,
        "vs_baseline_compute": round(baseline_s / compute_s, 2)
        if baseline_s == baseline_s and compute_s
        else None,
        "pods_per_sec": round(placed / value_s),
        "executor": executor,
        "placed": placed,
        "tasks": snap.n_tasks,
        "nodes": snap.n_nodes,
        "identical_bindings": identical,
    }


def bench_preempt_config(name: str, kwargs: dict, iters: int = 5) -> dict:
    """BASELINE config 5: the preempt pass measured end-to-end — device
    preempt replay (ops/preempt_pallas, ≡ host PreemptAction) vs the
    native C++ greedy preempt baseline (the reference preempt.go
    stand-in).  ``identical_bindings`` = evicted victim sets AND
    pipelined placements match exactly."""
    from volcano_tpu import native
    from volcano_tpu.ops.dispatch import select_preempt_executor
    from volcano_tpu.ops.preempt_pack import preempt_dense
    from volcano_tpu.ops.synthetic import generate_preempt_packed

    pk = generate_preempt_packed(**kwargs)
    executor = select_preempt_executor(pk)

    in_bytes = int(
        pk.base.task_resreq.nbytes
        + pk.vic_resreq.nbytes
        + pk.vic_node.nbytes * 3
        + pk.base.node_used.nbytes * 5
    )
    probe = _relay_probe(in_bytes=in_bytes, out_elems=pk.base.n_tasks)

    if executor == "pallas":
        from volcano_tpu.ops.preempt_pallas import run_preempt_pallas

        run = lambda: run_preempt_pallas(pk)
    else:
        run = lambda: preempt_dense(pk)
    dev_ev, dev_pipe = run()  # compile warmup + result
    e2e_s, relay_s = _time_interleaved(run, probe, iters=iters)
    pipelined_s = None
    if executor == "pallas":
        from volcano_tpu.ops.preempt_pallas import make_preempt_dispatch

        try:
            made = make_preempt_dispatch(pk, prestage=True)
            compute_s = _pipelined_compute_s(made[0]) if made else e2e_s
            if made:
                # steady-state preempt cycle with the commit plane
                # draining cycle N's eviction/placement result while
                # cycle N+1 dispatches
                pipelined_s = _pipelined_cycle_s(made[0])
        except Exception:  # noqa: BLE001 — mirror run_preempt_auto's
            # pallas → dense degradation; compute is unmeasurable then
            compute_s = None
    else:
        compute_s = e2e_s  # dense: the whole e2e is compute
    if executor == "pallas":
        _full, rtt_s, bind_leg_s, writeback_leg_s = _relay_components(
            in_bytes, pk.base.n_tasks
        )
        encode_leg_s, decode_leg_s = _serde_legs(pk.base.n_tasks)
    else:
        rtt_s = bind_leg_s = writeback_leg_s = 0.0
        encode_leg_s = decode_leg_s = 0.0

    base_iters = 1
    try:
        s1, (nat_ev, nat_pipe) = _time_r(
            lambda: native.baseline_preempt(pk, n_threads=1),
            warmup=0, iters=base_iters,
        )
        s16, _ = _time_r(
            lambda: native.baseline_preempt(pk, n_threads=16),
            warmup=0, iters=base_iters,
        )
        baseline_s = min(s1, s16)
        identical = bool(
            np.array_equal(dev_ev, nat_ev) and np.array_equal(dev_pipe, nat_pipe)
        )
    except RuntimeError:
        baseline_s = float("nan")
        identical = False

    placed = int((dev_pipe >= 0).sum())
    value_s = pipelined_s if pipelined_s is not None else e2e_s
    if pipelined_s is not None and compute_s is not None:
        resid_relay_s = max(value_s - compute_s, 0.0)
    elif pipelined_s is not None:
        resid_relay_s = None
    else:
        resid_relay_s = relay_s
    return {
        "metric": f"session_latency_{name}",
        "value": round(value_s * 1e3, 3),
        "unit": "ms",
        "vs_baseline": round(baseline_s / value_s, 2)
        if baseline_s == baseline_s
        else None,
        "baseline_ms": round(baseline_s * 1e3, 3) if baseline_s == baseline_s else None,
        "compute_ms": round(compute_s * 1e3, 3) if compute_s is not None else None,
        "relay_floor_ms": round(resid_relay_s * 1e3, 3)
        if resid_relay_s is not None else None,
        "sync_ms": round(e2e_s * 1e3, 3),
        "relay_sync_ms": round(relay_s * 1e3, 3),
        "bus_rtt_ms": round(rtt_s * 1e3, 3),
        "bind_ms": round(bind_leg_s * 1e3, 3),
        "writeback_ms": round(writeback_leg_s * 1e3, 3),
        "encode_ms": round(encode_leg_s * 1e3, 3),
        "decode_ms": round(decode_leg_s * 1e3, 3),
        "pipelined": pipelined_s is not None,
        "vs_baseline_compute": round(baseline_s / compute_s, 2)
        if baseline_s == baseline_s and compute_s
        else None,
        "pods_per_sec": round(placed / value_s),
        "executor": executor,
        "placed": placed,
        "victims_evicted": int(dev_ev.sum()),
        "tasks": pk.base.n_tasks,
        "victims": pk.n_victims,
        "nodes": pk.base.n_nodes,
        "identical_bindings": identical,
    }


def bench_action(name: str, kwargs: dict, iters: int = 3) -> dict:
    """The REAL jax-allocate action through a live Session: cache feed →
    open → ORDER/KERNEL/APPLY → bindings through the cache.  This is the
    number the kernel-only configs cannot show — the whole framework's
    session latency, host machinery included (VERDICT r4 item 1).

    ``value`` is the WARM-CYCLE action execute() wall time: one
    persistent cache + pack cache, with binds reverted between cycles
    through status-only churn (bench/_profsetup.revert_binds) — "the
    cluster is unchanged modulo prior binds".  Task rows stay
    pack-cached, node planes delta-repack, the device planes scatter
    dirty rows, and session open reuses whatever clones the previous
    session left untouched.  The cold numbers (fresh cache, fresh pack)
    are reported alongside as ``action_cold_ms``/``session_open_cold_ms``
    so the cold→warm split is visible per config.  The native baseline
    is the C++ 16-thread loop on the identical packed session — the
    stand-in for the reference's in-action hot loop."""
    from volcano_tpu import native
    from volcano_tpu.actions import jax_allocate as ja_mod
    from volcano_tpu.actions.jax_allocate import JaxAllocateAction, compute_task_order
    from volcano_tpu.framework import close_session, open_session
    from volcano_tpu.ops.packing import pack_session

    # one copy of the binder/tier/cache-builder setup, shared with the
    # bench/prof_* scripts so their numbers line up with this metric
    # (bench/ is put on sys.path once at module import)
    from _profsetup import TIERS as tier_conf
    from _profsetup import capture_task_infos, make_cache_builder, revert_binds

    fresh_cache = make_cache_builder(**kwargs)
    action = JaxAllocateAction()

    # ---- cold: fresh cache per cycle (first iteration compiles) ----
    baseline_s = None
    cold_open = cold_exec = None
    for it in range(2):
        cache = fresh_cache()
        # the cluster graph is live for the whole action — take it out
        # of the collector's working set before the timed region
        _gc_quiesce()
        t0 = time.perf_counter()
        ssn = open_session(cache, tier_conf, [])
        t1 = time.perf_counter()
        if it == 0:
            # native baseline on the identical packed session
            ordered = compute_task_order(ssn)
            jobs = {}
            for t in ordered:
                job = ssn.jobs.get(t.job)
                if job is not None and job.uid not in jobs:
                    jobs[job.uid] = job
            snap = pack_session(
                ordered, list(jobs.values()),
                [ssn.nodes[n] for n in sorted(ssn.nodes)],
            )
            try:
                baseline_s = min(
                    _time(lambda: native.baseline_allocate(snap, n_threads=1),
                          warmup=0, iters=1),
                    _time(lambda: native.baseline_allocate(snap, n_threads=16),
                          warmup=0, iters=1),
                )
            except RuntimeError:
                baseline_s = None
            t1 = time.perf_counter()
        action.execute(ssn)
        t2 = time.perf_counter()
        close_session(ssn)
        if it > 0:
            cold_open, cold_exec = t1 - t0, t2 - t1

    # ---- warm: ONE persistent cache; binds reverted between cycles ----
    # The warm cache runs with the PIPELINED commit plane: the action
    # hands bind effects to the bind workers and returns, so exec time
    # measures what the scheduler thread actually blocks on.  The
    # untimed flush below drains the plane before binds are counted and
    # reverted (the commit barrier the next snapshot would impose).
    cache = fresh_cache()
    cache.snapshot_reuse = True
    cache.enable_pipelined_commit()
    orig_tis = capture_task_infos(cache)
    open_times, exec_times = [], []
    phase = {}
    warm_binds = 0
    commit_stats = {}
    for it in range(iters + 1):  # iteration 0 seeds the pack cache
        _gc_quiesce()
        binds0 = len(cache.binder.binds)
        t0 = time.perf_counter()
        ssn = open_session(cache, tier_conf, [])
        t1 = time.perf_counter()
        action.execute(ssn)
        t2 = time.perf_counter()
        close_session(ssn)
        cache.flush()  # untimed: the next cycle's commit barrier
        if it > 0:
            open_times.append(t1 - t0)
            exec_times.append(t2 - t1)
            phase = dict(ja_mod.last_phase_stats)
            warm_binds = len(cache.binder.binds) - binds0
            commit_stats = dict(cache._commit_plane.last_barrier)
        revert_binds(cache, orig_tis)
    cache.stop_commit_plane()

    action_s = float(np.median(exec_times))
    rnd = lambda v: round(v, 3) if isinstance(v, float) else v
    return {
        "metric": f"action_latency_{name}",
        "value": round(action_s * 1e3, 3),
        "unit": "ms",
        "vs_baseline": round(baseline_s / action_s, 2) if baseline_s else None,
        "baseline_ms": round(baseline_s * 1e3, 3) if baseline_s else None,
        "session_open_ms": round(float(np.median(open_times)) * 1e3, 3),
        "action_cold_ms": round(cold_exec * 1e3, 3),
        "session_open_cold_ms": round(cold_open * 1e3, 3),
        "pack_delta_ms": rnd(phase.get("pack_ms")),
        "relay_overlap_ms": rnd(phase.get("relay_overlap_ms")),
        "order_ms": rnd(phase.get("order_ms")),
        "pack_mode": phase.get("mode"),
        "reused_tasks": phase.get("reused_tasks"),
        "repacked_nodes": phase.get("repacked_nodes"),
        "pods_per_sec": round(warm_binds / action_s) if action_s else None,
        "binds": warm_binds,
        "commit_handoff_ms": rnd(phase.get("commit_handoff_ms")),
        "commit_busy_ms": rnd(commit_stats.get("busy_ms")),
        "commit_wait_ms": rnd(commit_stats.get("wait_ms")),
        "commit_overlap_ratio": rnd(commit_stats.get("overlap_ratio")),
        "tasks": kwargs["n_tasks"],
        "nodes": kwargs["n_nodes"],
    }


def run_equivalence_check() -> int:
    """--check: compiled-backend equivalence gates (ADVICE r2: the
    compiled Mosaic path needs coverage beyond interpret mode — this
    runs the REAL backend, wherever bench runs).  Exit 0 iff every
    formulation agrees exactly on seeded mid-scale sessions."""
    import jax

    from volcano_tpu import native
    from volcano_tpu.ops.blocked import run_packed_blocked
    from volcano_tpu.ops.kernels import run_packed
    from volcano_tpu.ops.preempt_pack import preempt_dense
    from volcano_tpu.ops.preempt_pallas import run_preempt_pallas
    from volcano_tpu.ops.synthetic import generate_preempt_packed, generate_snapshot

    backend = jax.default_backend()
    failures = []

    snap = generate_snapshot(
        n_tasks=4_096, n_nodes=1_000, gang_size=8, seed=42,
        label_classes=4, taint_fraction=0.1,
    )
    plain = run_packed(snap)
    if not np.array_equal(plain, run_packed_blocked(snap)):
        failures.append("blocked != plain")
    if backend == "tpu":
        from volcano_tpu.ops.pallas_session import run_packed_pallas

        if not np.array_equal(plain, run_packed_pallas(snap)):
            failures.append("pallas(compiled) != plain")
    native_checked = native.load() is not None
    if native_checked:
        # RuntimeError from an AVAILABLE library is a failure, not a skip
        try:
            if not np.array_equal(plain, native.baseline_allocate(snap)):
                failures.append("native != plain")
        except RuntimeError as e:
            failures.append(f"native allocate errored: {e}")

    pk = generate_preempt_packed(n_victims=9_000, n_nodes=1_000,
                                 n_preemptors=1_000, seed=42)
    ev_d, pipe_d = preempt_dense(pk)
    if backend == "tpu":
        ev_p, pipe_p = run_preempt_pallas(pk)
        if not (np.array_equal(ev_d, ev_p) and np.array_equal(pipe_d, pipe_p)):
            failures.append("preempt pallas(compiled) != dense")
    if native_checked:
        try:
            ev_n, pipe_n = native.baseline_preempt(pk)
            if not (np.array_equal(ev_d, ev_n) and np.array_equal(pipe_d, pipe_n)):
                failures.append("preempt native != dense")
        except RuntimeError as e:
            failures.append(f"native preempt errored: {e}")

    print(json.dumps({
        "check": "formulation_equivalence",
        "backend": backend,
        "compiled_pallas_checked": backend == "tpu",
        "native_checked": native_checked,
        "failures": failures,
        "ok": not failures,
    }))
    return 1 if failures else 0


def main() -> int:
    from volcano_tpu.ops.synthetic import BASELINE_CONFIGS

    parser = argparse.ArgumentParser()
    parser.add_argument("--config", default=None, help="run one named config")
    parser.add_argument("--quick", action="store_true")
    parser.add_argument(
        "--check", action="store_true",
        help="run compiled-backend equivalence gates and exit",
    )
    parser.add_argument(
        "--all", action="store_true",
        help="(default) run every BASELINE config, headline last",
    )
    args = parser.parse_args()
    if args.check:
        return run_equivalence_check()

    headline = "50k_pods_10k_nodes_gang_predicates"
    if args.quick:
        configs = {"1k_pods_100_nodes_binpack": BASELINE_CONFIGS["1k_pods_100_nodes_binpack"]}
    elif args.config:
        configs = {args.config: BASELINE_CONFIGS[args.config]}
    else:
        # Default: ALL configs, headline printed last → lands on stdout;
        # the others go to stderr (one JSON line each).
        configs = {k: v for k, v in BASELINE_CONFIGS.items() if k != headline}
        configs[headline] = BASELINE_CONFIGS[headline]

    results = []
    for name, kw in configs.items():
        r = (
            bench_preempt_config(name, {k: v for k, v in kw.items() if k != "preempt"})
            if kw.get("preempt")
            else bench_config(name, kw)
        )
        _gc_quiesce()  # this config's survivors must not tax the next one
        # Full-framework WARM-CYCLE action latency for every allocate
        # config (real Session, host machinery, persistent pack cache) —
        # detailed line on stderr, key fields folded into the config's
        # result so BENCH consumers track the user-visible cycle, not
        # just the session kernel.
        if not kw.get("preempt"):
            action = bench_action(name, kw)
            print(json.dumps(action), file=sys.stderr)
            r["action_ms"] = action["value"]
            r["action_vs_baseline"] = action["vs_baseline"]
            r["action_session_open_ms"] = action["session_open_ms"]
            r["action_cold_ms"] = action["action_cold_ms"]
            r["pack_delta_ms"] = action["pack_delta_ms"]
            r["relay_overlap_ms"] = action["relay_overlap_ms"]
            _gc_quiesce()
        results.append(r)

    for r in results[:-1]:
        print(json.dumps(r), file=sys.stderr)
    print(json.dumps(results[-1]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
