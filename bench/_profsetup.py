"""Shared setup for the bench/prof_* scripts: the headline cluster
shape, tier config, binder, and cache builder — one copy, kept in sync
with bench.py's action bench so profiling numbers line up with the
action_latency_* metrics."""

from __future__ import annotations

import sys

sys.path.insert(0, ".")

import volcano_tpu.actions  # noqa: F401 — registers actions
import volcano_tpu.plugins  # noqa: F401 — registers plugin builders
from volcano_tpu.cache import SchedulerCache
from volcano_tpu.conf import PluginOption, Tier
from volcano_tpu.ops.synthetic import BASELINE_CONFIGS, generate_cluster_objects

HEADLINE_KWARGS = dict(BASELINE_CONFIGS["50k_pods_10k_nodes_gang_predicates"])

TIERS = [
    Tier(plugins=[PluginOption(name=n) for n in ("priority", "gang")]),
    Tier(plugins=[
        PluginOption(name=n)
        for n in ("drf", "predicates", "proportion", "nodeorder", "binpack")
    ]),
]


class ListBinder:
    def __init__(self):
        self.binds = []

    def bind(self, task, hostname):
        self.binds.append((f"{task.namespace}/{task.name}", hostname))


def capture_task_infos(cache):
    """uid → pristine pending TaskInfo clone, captured right after the
    cache feed — the revert pool for warm-cycle benching."""
    return {
        t.uid: t.clone()
        for job in cache.jobs.values()
        for t in job.tasks.values()
    }


def revert_binds(cache, orig_tis):
    """Return every bound task to Pending through the cache's internal
    event mutations — exactly what a status-only update_pod pair does
    (node accounting re-derives and is marked dirty; the task's packed
    row stays clean because the pod SPEC never changed).  The bench's
    stand-in for 'last cycle's pods finished and an identical batch
    arrived', which is what makes a warm cycle measurable at full
    session width."""
    with cache._mutex:
        for job in list(cache.jobs.values()):
            for t in list(job.tasks.values()):
                if t.node_name:
                    orig = orig_tis.get(t.uid)
                    if orig is None:
                        continue
                    cache._delete_task(t)
                    cache._add_task(orig.clone())


def make_cache_builder(**overrides):
    """Returns a zero-arg callable building a fresh fed cache of the
    headline shape (or the shape given by overrides)."""
    kwargs = dict(HEADLINE_KWARGS)
    kwargs.update(overrides)
    nodes, pods, pgs, queues = generate_cluster_objects(**kwargs)

    def fresh():
        cache = SchedulerCache(binder=ListBinder())
        for n in nodes:
            cache.add_node(n)
        for p in pods:
            cache.add_pod(p)
        for pg in pgs:
            cache.add_pod_group(pg)
        for q in queues:
            cache.add_queue(q)
        return cache

    return fresh
