"""loadgen — the sustained-load SLO harness (ROADMAP item 2).

Batch cycle latency stopped being the user-visible number once the
relay floor collapsed (BENCH_r05): under sustained churn what a user
feels is **submit→bind latency** — how long a freshly-created pod waits
before its binding lands back on the bus.  This harness measures
exactly that, over the REAL bus topology (TCP BusServer, RemoteAPIServer
informers, pipelined commit plane, event-driven micro-cycle scheduler):

  * an **open-loop** arrival stream — job arrival times are fixed by
    the offered rate up front, never gated on the system keeping up, so
    saturation shows up as growing latency instead of a politely
    slowed-down generator;
  * per-pod submit→bind latency observed from store truth (an audit
    watch on the in-process server, outside the measured path);
  * p50/p95/p99/max, achieved throughput, the micro-vs-full cycle mix,
    and the full-cycle fallback causes;
  * optionally (``--find-saturation``) a rate ramp that reports the
    highest offered rate whose p99 still meets the SLO.

This is the regression gate for subsequent perf PRs: CI runs
``--quick`` and uploads the JSON next to the relay-breakdown artifact.

Usage::

    JAX_PLATFORMS=cpu python bench/loadgen.py --quick
    python bench/loadgen.py --rate 2000 --duration 30 --nodes 1000
    python bench/loadgen.py --find-saturation --slo-ms 100

The O(pending) resident drill (``--resident-sweep``; CI runs it at
``--quick`` shape and uploads the JSON as the ``resident-slo``
artifact) grows the already-Running job population 10× while the
pending stream stays constant, and gates p99 submit→bind within 1.2×
and the restricted session-open mean within 2×::

    JAX_PLATFORMS=cpu python bench/loadgen.py --quick --resident-sweep

The full 100k-node / 1M-resident-job campaign is a slow/bench recipe,
not a CI job — run it on a real machine with ~1h and tens of GB of
RAM.  Preloading 1M pods through the store dominates setup time;
budget ~20 min before the measured stream starts::

    JAX_PLATFORMS=cpu python bench/loadgen.py \\
        --nodes 100000 --node-cpu 64 \\
        --resident 100000 --resident-sweep \\
        --rate 200 --duration 60 --drain-timeout 600 \\
        --warmup-timeout 1200 --period 30

(``--resident 100000`` sweeps 100k → 1M resident jobs; ``--period
30`` keeps the periodic full-session re-equilibration — which stays
O(resident) by design — from swamping the run.  Track ``rss_bytes``
per member across the sweep for the memory half of the headline; a
federated variant adds ``--shards 4`` and reads per-process RSS.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
from typing import Dict, List

import numpy as np

# run from the repo root OR as bench/loadgen.py — same bootstrap the
# other bench/prof_* scripts use
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

CONF = """
actions: "enqueue, jax-allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


class LoadgenTopology:
    """The full control loop, every seam real: in-process store behind a
    TCP BusServer, the scheduler cache fed by RemoteAPIServer informers,
    binds riding the pipelined commit plane, and the event-driven
    micro-cycle loop doing the scheduling.  The audit watch runs on the
    in-process store — store truth, off the measured path."""

    def __init__(self, n_nodes: int, node_cpu: int, conf_path: str,
                 period: float, debounce_ms: float,
                 micro_cycles: bool = True, restricted: bool = False,
                 resident: int = 0):
        self._init_store(n_nodes, node_cpu, resident=resident)
        self._start_scheduler(conf_path, period, debounce_ms, micro_cycles,
                              restricted=restricted)

    def _init_store(self, n_nodes: int, node_cpu: int,
                    resident: int = 0) -> None:
        from volcano_tpu.bus.server import BusServer
        from volcano_tpu.client import (
            ADDED,
            APIServer,
            KubeClient,
            MODIFIED,
            VolcanoClient,
        )

        self.api = APIServer()
        self.bus = BusServer(self.api).start()
        self.bus_address = f"tcp://127.0.0.1:{self.bus.port}"
        # arrivals land on the in-process store (the generator is
        # colocated with the apiserver, off the measured path) and reach
        # the SCHEDULER over the real TCP watch stream — the measured
        # leg.  Submitting over a third TCP connection would serialize
        # the open-loop generator on round-trips it is not supposed to
        # be measuring.
        self.kube = KubeClient(self.api)
        self.vc = VolcanoClient(self.api)

        self.vc.create_queue(_build_queue("default"))
        for i in range(n_nodes):
            self.kube.create_node(
                _build_node(_node_name(i), {"cpu": str(node_cpu),
                                            "memory": "256Gi"})
            )

        #: ``--resident``: preload N already-Running single-task jobs
        #: (pods pre-bound round-robin, 1m/1Mi requests so they occupy
        #: jobs, not capacity) BEFORE the scheduler attaches — the
        #: resident ballast the incremental-session plane claims not to
        #: pay per cycle.  The reaper skips them (fixed population).
        self.n_resident = resident
        if resident:
            from volcano_tpu.apis import scheduling

        for i in range(resident):
            name = f"resident-r{i:06d}"
            pg = _build_pod_group("ns", name, 1)
            # already Running at store truth — a fresh Inqueue phase
            # would make the first full cycle write back O(resident)
            # phase migrations, which no real resident population pays
            pg.status.phase = scheduling.POD_GROUP_RUNNING
            self.vc.create_pod_group(pg)
            pod = _build_pod("ns", f"{name}-t0",
                             {"cpu": "1m", "memory": "1Mi"}, group=name)
            pod.spec.node_name = _node_name(i % n_nodes)
            pod.status.phase = "Running"
            self.kube.create_pod(pod)

        #: ns/name → wall-clock the bind landed at store truth
        self.bind_ts: Dict[str, float] = {}
        self._bind_lock = threading.Lock()

        def audit(event, old, new):
            if event not in (ADDED, MODIFIED) or new is None:
                return
            if not new.spec.node_name:
                return
            key = f"{new.metadata.namespace}/{new.metadata.name}"
            with self._bind_lock:
                self.bind_ts.setdefault(key, time.time())

        self.api.watch("Pod", audit, send_initial=False)

        #: completion churn: bound pods finish ``complete_after_s`` after
        #: their bind and their job objects are deleted — sustained load
        #: means arrivals AND departures, and without departures the
        #: resident job count (and with it the O(jobs) session cost of
        #: every cycle) grows without bound, which is a different
        #: experiment.  0 disables (short drains / saturation probes).
        self.complete_after_s = 0.0
        self._group_size: Dict[str, int] = {}
        self._reaper_stop = threading.Event()
        self._reaper = threading.Thread(
            target=self._reap_loop, name="loadgen-reaper", daemon=True
        )
        self._reaper.start()

    def _start_scheduler(self, conf_path: str, period: float,
                         debounce_ms: float, micro_cycles: bool,
                         restricted: bool = False) -> None:
        from volcano_tpu.bus.remote import RemoteAPIServer
        from volcano_tpu.cache import SchedulerCache
        from volcano_tpu.client import SchedulerClient
        from volcano_tpu.scheduler.scheduler import Scheduler

        self.sched_remote = RemoteAPIServer(self.bus_address, timeout=10.0)
        assert self.sched_remote.wait_ready(10.0)
        self.cache = SchedulerCache(
            client=SchedulerClient(self.sched_remote),
            scheduler_name="volcano-tpu",
            pipelined_commit=True,
            snapshot_reuse=True,
        )
        self.scheduler = Scheduler(
            self.cache, scheduler_conf_path=conf_path, period=period,
            micro_cycles=micro_cycles, micro_debounce_ms=debounce_ms,
            restricted_sessions=restricted,
        )
        self._thread = threading.Thread(
            target=self.scheduler.run, name="loadgen-scheduler", daemon=True
        )
        self._thread.start()

    def _reap_loop(self) -> None:
        from volcano_tpu.client.apiserver import ApiError

        reaped = set()
        done_per_group: Dict[str, int] = {}
        while not self._reaper_stop.wait(0.1):
            if self.complete_after_s <= 0:
                continue
            cutoff = time.time() - self.complete_after_s
            with self._bind_lock:
                due = [
                    k for k, ts in self.bind_ts.items()
                    if ts <= cutoff and k not in reaped
                    # resident ballast never completes — its population
                    # is the controlled variable of --resident runs
                    and not k.partition("/")[2].startswith("resident-")
                ]
            for key in due:
                ns, name = key.split("/", 1)
                group = name.rsplit("-t", 1)[0]
                try:
                    self.api.delete("Pod", ns, name)
                except ApiError:
                    pass
                reaped.add(key)
                done_per_group[group] = done_per_group.get(group, 0) + 1
                if done_per_group[group] >= self._group_size.get(group, 1):
                    try:
                        self.api.delete("PodGroup", ns, group)
                    except ApiError:
                        pass

    def submit_job(self, name: str, tasks: int, cpu: str) -> List[str]:
        """One job: PodGroup + its pods, onto the store.  Returns the
        pod keys whose binds the audit watch will stamp."""
        self.vc.create_pod_group(_build_pod_group("ns", name, tasks))
        self._group_size[name] = tasks
        keys = []
        for i in range(tasks):
            pod_name = f"{name}-t{i}"
            self.kube.create_pod(
                _build_pod("ns", pod_name, {"cpu": cpu, "memory": "1Gi"},
                           group=name)
            )
            keys.append(f"ns/{pod_name}")
        return keys

    def bound_count(self, keys) -> int:
        with self._bind_lock:
            return sum(1 for k in keys if k in self.bind_ts)

    def rss_report(self) -> Dict[str, int]:
        """Resident-set size per scheduling member, bytes.  The
        in-process topology's scheduler shares the harness process."""
        return {"scheduler": _rss_bytes()}

    def close(self):
        self._reaper_stop.set()
        self._reaper.join(timeout=5)
        self.scheduler.stop()
        self._thread.join(timeout=15)
        self.cache.stop_commit_plane()
        self.sched_remote.close()
        self.bus.stop()


def _free_port() -> int:
    import socket as _socket

    with _socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class FederatedTopology(LoadgenTopology):
    """The sharded federation under load, topology fully real: the same
    in-process store + TCP bus + audit watch, but scheduling is done by
    ``--shards N`` **separate OS processes** running the actual
    ``vtpu-scheduler`` binary — shard-assignment leases, filtered
    informers, spillover CAS binds, pipelined commits, micro-cycles,
    the lot.  This is the harness behind the 1M-pods/100k-nodes
    aggregate headline and the near-linear 1→4 shard throughput claim.
    """

    def __init__(self, n_nodes: int, node_cpu: int, conf_path: str,
                 period: float, debounce_ms: float, n_shards: int,
                 lease_duration: float = 2.0,
                 micro_cycles: bool = True,
                 startup_timeout: float = 180.0,
                 log_dir: str = "",
                 n_members: int = 0,
                 extra_flags=(), resident: int = 0):
        import subprocess

        self._init_store(n_nodes, node_cpu, resident=resident)
        self.n_shards = n_shards
        #: with ``n_members > n_shards`` the extra schedulers run as
        #: warm STANDBYS: registered members that hold no slice until
        #: the map grows (fair share hands them nothing) — the ramp
        #: drill's pre-provisioned pool, so the rebalance gate measures
        #: the lease plane, not Python process startup
        self.n_members = n_members or n_shards
        self.procs = []
        self._logs = []
        url = f"tcp://127.0.0.1:{self.bus.port}"
        for i in range(self.n_members):
            cmd = [
                sys.executable, "-m", "volcano_tpu.cmd.scheduler",
                "--bus", url,
                "--shards", str(n_shards),
                "--shard-identity", f"shard{i}",
                "--shard-lease-duration", str(lease_duration),
                "--schedule-period", str(period),
                "--micro-debounce-ms", str(debounce_ms),
                "--pipelined-commit", "--snapshot-reuse",
                "--scheduler-conf", conf_path,
                "--listen-port", "0",
                *extra_flags,
            ]
            if micro_cycles:
                cmd.append("--micro-cycles")
            log_path = os.path.join(
                log_dir or tempfile.gettempdir(), f"loadgen-shard{i}.log"
            )
            logf = open(log_path, "w")  # noqa: SIM115 — held for the proc
            self._logs.append(logf)
            self.procs.append(subprocess.Popen(
                cmd, stdout=logf, stderr=subprocess.STDOUT,
                env=dict(os.environ),
            ))
        self._wait_federation(startup_timeout)

    def _wait_federation(self, timeout: float) -> None:
        from volcano_tpu.federation import read_shard_map

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for p in self.procs:
                rc = p.poll()
                if rc is not None:
                    raise RuntimeError(
                        f"shard scheduler exited rc={rc} during startup"
                    )
            rec = read_shard_map(self.api)
            if rec is not None:
                holders = {
                    e.get("holder")
                    for e in rec.get("shards", {}).values()
                }
                if "" not in holders and None not in holders and len(
                    rec.get("members", {})
                ) >= self.n_members:
                    return
            time.sleep(0.1)
        raise RuntimeError(
            f"federation did not form within {timeout}s "
            f"(map: {read_shard_map(self.api)})"
        )

    def kill_member(self, index: int) -> str:
        """SIGKILL one shard scheduler process mid-run — the loadgen
        face of the shard-kill chaos scenario.  Survivors must absorb
        its slices within one lease TTL and the drain still requires
        every pod to bind."""
        proc = self.procs[index]
        proc.kill()
        proc.wait(timeout=10)
        return f"shard{index}"

    def rss_report(self) -> Dict[str, int]:
        """RSS per member PROCESS — the resident-memory-per-member
        number the 1M-job campaign tracks."""
        return {
            f"shard{i}": _rss_bytes(p.pid)
            for i, p in enumerate(self.procs)
            if p.poll() is None
        }

    def shard_report(self) -> dict:
        from volcano_tpu.federation import read_shard_map

        rec = read_shard_map(self.api) or {}
        return {
            "shards": self.n_shards,
            "holders": {
                i: e.get("holder")
                for i, e in rec.get("shards", {}).items()
            },
            "members": sorted(rec.get("members", {})),
            "stats": rec.get("stats", {}),
        }

    def close(self):
        self._reaper_stop.set()
        self._reaper.join(timeout=5)
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001 — escalate to SIGKILL
                p.kill()
                p.wait(timeout=5)
        for f in self._logs:
            f.close()
        self.bus.stop()


class _ScaleWatcher(threading.Thread):
    """Ramp-drill observer: polls the shard map, records every shard-
    count change the autoscaler commits, and stamps how long the fleet
    took to REBALANCE after it (every slice of the new partition held
    by an unexpired lease) — the `rebalance within K lease TTLs` gate's
    measurement, taken from store truth off the measured path."""

    def __init__(self, api, lease_duration: float):
        super().__init__(name="loadgen-scale-watcher", daemon=True)
        self.api = api
        self.lease_duration = lease_duration
        # NOT `_stop`: threading.Thread uses a private `_stop()` METHOD
        # internally (tstate-lock cleanup) — shadowing it with an Event
        # crashes join()
        self._stop_evt = threading.Event()
        self._lock = threading.Lock()
        #: committed scale events: {"from", "target", "direction",
        #: "reason", "rebalance_s" (None until every slice is held)}
        self.events = []  # guarded-by: self._lock

    def run(self) -> None:
        from volcano_tpu.client.apiserver import ApiError
        from volcano_tpu.federation import read_shard_map

        last_n = None
        pending = []  # [t0, event] awaiting full coverage
        while not self._stop_evt.wait(0.05):
            try:
                rec = read_shard_map(self.api)
            except ApiError:
                continue
            if rec is None:
                continue
            n = int(rec.get("nShards", 0) or 0)
            if last_n is None:
                last_n = n
            elif n != last_n:
                blob = rec.get("autoscale", {}) or {}
                event = {
                    "from": last_n, "target": n,
                    "direction": blob.get("direction", "?"),
                    "reason": blob.get("reason", ""),
                    "rebalance_s": None,
                }
                with self._lock:
                    self.events.append(event)
                pending.append([time.monotonic(), event])
                last_n = n
            if pending:
                now_wall = time.time()
                covered = all(
                    e.get("holder")
                    and now_wall - float(e.get("renewTime", 0.0))
                    <= float(e.get("leaseDurationSeconds", 0.0) or 0.0)
                    for e in rec.get("shards", {}).values()
                ) and len(rec.get("shards", {})) == n
                if covered:
                    now = time.monotonic()
                    with self._lock:
                        for t0, event in pending:
                            event["rebalance_s"] = round(now - t0, 3)
                    pending = []

    def report(self) -> list:
        with self._lock:
            return [dict(e) for e in self.events]

    def stop(self) -> None:
        self._stop_evt.set()
        self.join(timeout=5)


class ReplicatedBusTopology(LoadgenTopology):
    """The replicated persistent bus under load: N real
    ``vtpu-apiserver`` OS processes (WAL dirs, leader election, quorum
    commit) instead of the in-process store, with the harness's own
    clients — submission, audit watch, the scheduler — dialing the full
    endpoint list.  ``--kill-apiserver-after`` SIGKILLs the LEADER mid
    open-loop stream; the drill passes only if a follower promotes,
    every submitted pod still binds (zero lost acknowledged binds), and
    no pod is ever re-bound."""

    def __init__(self, n_nodes: int, node_cpu: int, conf_path: str,
                 period: float, debounce_ms: float, n_replicas: int = 3,
                 lease_ttl: float = 1.0, micro_cycles: bool = True,
                 startup_timeout: float = 120.0):
        from volcano_tpu.bus.remote import RemoteAPIServer
        from volcano_tpu.client import ADDED, KubeClient, MODIFIED, VolcanoClient
        from volcano_tpu.client.apiserver import ApiError

        self.n_replicas = n_replicas
        self.lease_ttl = lease_ttl
        ports = [_free_port() for _ in range(n_replicas)]
        self.endpoints = [f"tcp://127.0.0.1:{p}" for p in ports]
        self.bus_address = ",".join(self.endpoints)
        self._data_root = tempfile.mkdtemp(prefix="loadgen-bus-")
        self.procs = []
        self._logs = []
        #: membership-drill forensics ({"op", "url", "ok", "error"})
        self.membership_events = []
        for i in range(n_replicas):
            self._spawn_apiserver(i, self.bus_address)

        # the audit/submission client dials the endpoint list REVERSED:
        # the staggered election makes replica 0 the bootstrap leader
        # (the kill target), and an audit watch riding the killed
        # replica would stamp every pre-kill bind at watch-RESUME time
        # — a measurement artifact, not system latency.  Watching from
        # a follower measures honestly: followers stream commit-gated
        # events continuously through the failover.
        self.api = RemoteAPIServer(
            ",".join(reversed(self.endpoints)), timeout=15.0
        )
        if not self.api.wait_ready(startup_timeout):
            raise RuntimeError("replicated apiserver group never came up")
        self.kube = KubeClient(self.api)
        self.vc = VolcanoClient(self.api)

        # seeding waits out the election + quorum window
        deadline = time.monotonic() + startup_timeout
        while True:
            try:
                self.vc.create_queue(_build_queue("default"))
                break
            except ApiError as e:
                if "already exists" in str(e):
                    break
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.25)
        for i in range(n_nodes):
            self.kube.create_node(
                _build_node(_node_name(i), {"cpu": str(node_cpu),
                                            "memory": "256Gi"})
            )

        self.bind_ts: Dict[str, float] = {}
        self.rebinds = 0
        self._bind_lock = threading.Lock()

        def audit(event, old, new):
            if event not in (ADDED, MODIFIED) or new is None:
                return
            if not new.spec.node_name:
                return
            key = f"{new.metadata.namespace}/{new.metadata.name}"
            with self._bind_lock:
                self.bind_ts.setdefault(key, time.time())
                if (
                    old is not None and old.spec.node_name
                    and old.spec.node_name != new.spec.node_name
                ):
                    self.rebinds += 1

        self.api.watch("Pod", audit, send_initial=False)

        self.complete_after_s = 0.0
        self._group_size: Dict[str, int] = {}
        self._reaper_stop = threading.Event()
        self._reaper = threading.Thread(
            target=self._reap_loop, name="loadgen-reaper", daemon=True
        )
        self._reaper.start()
        self._start_scheduler(conf_path, period, debounce_ms, micro_cycles)

    def _spawn_apiserver(self, index: int, replicas: str):
        """Start one real ``vtpu-apiserver`` process.  ``replicas`` is
        the endpoint list IT is told (a joiner gets the new full list;
        the original members keep theirs — the replicated membership
        config reconciles them after the add commits)."""
        import subprocess

        log_path = os.path.join(tempfile.gettempdir(),
                                f"loadgen-apiserver{index}.log")
        logf = open(log_path, "w")  # noqa: SIM115 — held for the proc
        self._logs.append(logf)
        port = int(self.endpoints[index].rsplit(":", 1)[1])
        proc = subprocess.Popen(
            [sys.executable, "-m", "volcano_tpu.cmd.apiserver",
             "--listen-host", "127.0.0.1", "--port", str(port),
             "--listen-port", "0",
             "--data-dir", os.path.join(self._data_root, f"r{index}"),
             "--replicas", replicas,
             "--replica-index", str(index),
             "--repl-lease-ttl", str(self.lease_ttl)],
            stdout=logf, stderr=subprocess.STDOUT,
            env=dict(os.environ),
        )
        if index < len(self.procs):
            self.procs[index] = proc
        else:
            self.procs.append(proc)
        return proc

    # ---- the membership add-then-remove drill ----

    def add_replica_member(self) -> dict:
        """Grow the group by ONE mid-stream: spawn a fresh apiserver
        told the whole NEW endpoint list (itself last), let it attach
        as a learner, then ask the group (through whichever replica we
        are connected to — a follower proxies) to admit it.  Retried
        across the catch-up window; the event record lands in
        ``membership_events`` for the report."""
        from volcano_tpu.client.apiserver import ApiError

        index = len(self.endpoints)
        url = f"tcp://127.0.0.1:{_free_port()}"
        self.endpoints.append(url)
        self._spawn_apiserver(index, ",".join(self.endpoints))
        event = {"op": "add", "url": url, "ok": False, "error": ""}
        deadline = time.monotonic() + max(self.lease_ttl * 30, 60.0)
        while time.monotonic() < deadline:
            try:
                res = self.api.bus_add_replica(url)
                event.update(ok=True, epoch=res.get("epoch"),
                             endpoints=res.get("endpoints"))
                break
            except ApiError as e:
                event["error"] = str(e)
                if "already a member" in str(e):
                    event["ok"] = True  # an earlier ambiguous try won
                    break
                time.sleep(0.5)
        self.membership_events.append(event)
        return event

    def remove_replica_member(self) -> dict:
        """Shrink the group by ONE mid-stream: retire the first
        ORIGINAL follower (never the leader — the op refuses that) and
        terminate its process once the config commits."""
        from volcano_tpu.client.apiserver import ApiError

        event = {"op": "remove", "url": "", "ok": False, "error": ""}
        deadline = time.monotonic() + max(self.lease_ttl * 30, 60.0)
        while time.monotonic() < deadline:
            lidx = self.leader_index()
            victims = [
                i for i in range(self.n_replicas)
                if i != lidx and self.procs[i].poll() is None
            ]
            if lidx is None or not victims:
                time.sleep(0.5)
                continue
            url = self.endpoints[victims[0]]
            event["url"] = url
            try:
                res = self.api.bus_remove_replica(url)
                event.update(ok=True, epoch=res.get("epoch"),
                             endpoints=res.get("endpoints"))
                # the retired replica stood down; take its process out
                # so the end-state probe proves the group is healthy
                # WITHOUT it
                self.procs[victims[0]].terminate()
                break
            except ApiError as e:
                event["error"] = str(e)
                if "is not a member" in str(e):
                    # an earlier ambiguous attempt committed (the
                    # answer was lost to a failover/proxy teardown) —
                    # the config no longer lists the victim, which is
                    # the outcome the drill wanted
                    event["ok"] = True
                    self.procs[victims[0]].terminate()
                    break
                time.sleep(0.5)
        self.membership_events.append(event)
        return event

    def membership_report(self) -> dict:
        """End-state membership truth: every live replica's epoch and
        endpoint list (the `exactly one surviving config` gate reads
        this), plus the drill's event log."""
        from volcano_tpu.bus.replication import probe_status

        epochs = {}
        configs = set()
        for i, url in enumerate(self.endpoints):
            if i < len(self.procs) and self.procs[i].poll() is not None:
                continue
            st = probe_status(url)
            if st is None or st.get("role") == "removed":
                continue
            epochs[url] = st.get("membership_epoch")
            members = st.get("membership")
            if members is not None:
                configs.add(tuple(members))
        return {
            "events": list(self.membership_events),
            "epochs": epochs,
            "distinct_configs": len(configs),
            "config": sorted(configs.pop()) if len(configs) == 1 else None,
        }

    def submit_job(self, name: str, tasks: int, cpu: str):
        """Bounded, IDEMPOTENT retry across the failover window: an
        arrival landing mid-election is retried rather than crashing
        the open-loop generator (its lag still counts as system latency
        — the clock started at the scheduled arrival instant), and a
        retry after an ambiguous failure treats AlreadyExists as
        success (the earlier attempt committed)."""
        from volcano_tpu.client.apiserver import AlreadyExistsError, ApiError

        # the budget must cover one full client timeout (a call parked
        # on a mid-reconnect connection) PLUS the election window
        deadline = time.monotonic() + max(self.lease_ttl * 10, 30.0)

        def create(fn, *args):
            while True:
                try:
                    fn(*args)
                    return
                except AlreadyExistsError:
                    return  # an ambiguous earlier attempt committed
                except ApiError:
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.1)

        create(self.vc.create_pod_group, _build_pod_group("ns", name, tasks))
        self._group_size[name] = tasks
        keys = []
        for i in range(tasks):
            pod_name = f"{name}-t{i}"
            create(
                self.kube.create_pod,
                _build_pod("ns", pod_name,
                           {"cpu": cpu, "memory": "1Gi"}, group=name),
            )
            keys.append(f"ns/{pod_name}")
        return keys

    def leader_index(self):
        from volcano_tpu.bus.replication import probe_status

        for i, url in enumerate(self.endpoints):
            st = probe_status(url)
            if st is not None and st.get("role") == "leader":
                return i
        return None

    def kill_leader(self) -> str:
        idx = self.leader_index()
        if idx is None:
            return "<no leader found>"
        self.procs[idx].kill()
        self.procs[idx].wait(timeout=10)
        return f"replica-{idx}"

    def bus_report(self) -> dict:
        from volcano_tpu.bus.replication import probe_status

        roles = {}
        for i, url in enumerate(self.endpoints):
            st = probe_status(url)
            roles[f"replica-{i}"] = (
                st.get("role") if st is not None else "dead"
            )
        with self._bind_lock:
            rebinds = self.rebinds
        return {"replicas": self.n_replicas, "roles": roles,
                "rebinds": rebinds}

    def close(self):
        self._reaper_stop.set()
        self._reaper.join(timeout=5)
        self.scheduler.stop()
        self._thread.join(timeout=15)
        self.cache.stop_commit_plane()
        self.sched_remote.close()
        self.api.close()
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001 — escalate to SIGKILL
                p.kill()
                p.wait(timeout=5)
        for f in self._logs:
            f.close()


# ---- builders (bench is standalone: no tests/ import) ----

def _node_name(i: int) -> str:
    """The topology's node naming — ONE copy, because `_gang_plan`
    recomputes per-shard node counts from these names via the same
    crc32 hash the schedulers use: a rename here that missed the
    sizing would silently stop gang auto-sizing being oversized."""
    return f"n{i:04d}"


def _build_node(name, alloc):
    from volcano_tpu.apis import core

    alloc = dict(alloc)
    alloc.setdefault("pods", 1024)
    return core.Node(
        metadata=core.ObjectMeta(name=name, namespace=""),
        spec=core.NodeSpec(),
        status=core.NodeStatus(allocatable=alloc, capacity=dict(alloc)),
    )


def _build_pod(namespace, name, req, group):
    from volcano_tpu.apis import core, scheduling

    return core.Pod(
        metadata=core.ObjectMeta(
            name=name, namespace=namespace,
            annotations={scheduling.GROUP_NAME_ANNOTATION_KEY: group},
        ),
        spec=core.PodSpec(
            containers=[core.Container(
                name="main", resources={"requests": dict(req)}
            )],
        ),
        status=core.PodStatus(phase="Pending"),
    )


def _build_pod_group(namespace, name, min_member):
    from volcano_tpu.apis import core, scheduling

    return scheduling.PodGroup(
        metadata=core.ObjectMeta(name=name, namespace=namespace),
        spec=scheduling.PodGroupSpec(min_member=min_member, queue="default"),
        status=scheduling.PodGroupStatus(phase=scheduling.POD_GROUP_INQUEUE),
    )


def _build_queue(name):
    from volcano_tpu.apis import core, scheduling

    return scheduling.Queue(
        metadata=core.ObjectMeta(name=name, namespace=""),
        spec=scheduling.QueueSpec(weight=1),
    )


def _rss_bytes(pid="self") -> int:
    """Resident-set size of a process in bytes (0 when unreadable —
    e.g. a member that exited, or a non-/proc platform)."""
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGESIZE")
    except (OSError, ValueError, IndexError):
        return 0


# ---- the measured phase ----

def run_phase(topo: LoadgenTopology, rate: float, duration: float,
              tasks_per_job: int, cpu: str, drain_timeout: float,
              label: str = "run", gang_every: int = 0,
              gang_size: int = 0, gang_cpu: str = "") -> dict:
    """Open-loop arrivals at ``rate`` jobs/sec for ``duration`` seconds;
    returns the phase's latency/throughput report.  With ``gang_every``
    set, every Nth arrival is an OVERSIZED gang (``gang_size`` tasks of
    ``gang_cpu`` each, minMember == size — sized larger than any one
    shard can hold, so binding it requires a cross-shard txn_commit
    assembly); the report then carries per-gang full-assembly latency
    (submit → LAST member bound) and the partial-gang count, which the
    exit gate requires to be zero."""
    n_jobs = max(int(rate * duration), 1)
    interval = 1.0 / rate
    submit_ts: Dict[str, float] = {}
    all_keys: List[str] = []
    gangs: Dict[str, tuple] = {}
    late = 0

    start = time.monotonic()
    wall0 = time.time()
    for i in range(n_jobs):
        due = start + i * interval
        now = time.monotonic()
        if now < due:
            time.sleep(due - now)
        elif now - due > interval:
            late += 1  # generator fell behind the open-loop schedule
        # the latency clock starts at the SCHEDULED arrival instant, not
        # the actual create call — open-loop discipline: if the
        # generator falls behind, the lag counts as system latency
        # instead of being silently absorbed (coordinated omission)
        t_submit = wall0 + (due - start)
        if gang_every and gang_size > 1 and i % gang_every == 0:
            name = f"{label}-g{i:06d}"
            keys = topo.submit_job(name, gang_size, gang_cpu)
            gangs[name] = (keys, t_submit)
        else:
            keys = topo.submit_job(f"{label}-j{i:06d}", tasks_per_job, cpu)
        for k in keys:
            submit_ts[k] = t_submit
        all_keys.extend(keys)

    # drain: every submitted pod must bind (or the run reports the loss)
    deadline = time.monotonic() + drain_timeout
    while time.monotonic() < deadline:
        if topo.bound_count(all_keys) == len(all_keys):
            break
        time.sleep(0.05)

    with topo._bind_lock:
        pairs = [
            (k, (topo.bind_ts[k] - submit_ts[k]) * 1e3)
            for k in all_keys if k in topo.bind_ts
        ]
        last_bind = max(
            (topo.bind_ts[k] for k in all_keys if k in topo.bind_ts),
            default=wall0,
        )
    lat = [v for _k, v in pairs]
    bound = len(lat)
    lat_arr = np.asarray(lat) if lat else np.asarray([float("nan")])
    span = max(last_bind - wall0, 1e-9)
    report = {
        "offered_rate_jobs_per_s": rate,
        "jobs": n_jobs,
        "tasks_per_job": tasks_per_job,
        "submitted_pods": len(all_keys),
        "bound_pods": bound,
        "late_arrivals": late,
        "p50_ms": round(float(np.percentile(lat_arr, 50)), 3),
        "p95_ms": round(float(np.percentile(lat_arr, 95)), 3),
        "p99_ms": round(float(np.percentile(lat_arr, 99)), 3),
        "max_ms": round(float(lat_arr.max()), 3),
        "achieved_pods_per_s": round(bound / span, 1),
    }
    if gangs:
        assembly: List[float] = []
        partial = 0
        with topo._bind_lock:
            for _name, (keys, t0) in gangs.items():
                binds = [topo.bind_ts.get(k) for k in keys]
                done = [t for t in binds if t is not None]
                if len(done) == len(keys):
                    # full-assembly latency: the gang is usable only
                    # when its LAST member is bound
                    assembly.append((max(done) - t0) * 1e3)
                elif done:
                    partial += 1  # the state txn_commit exists to forbid
        asm_arr = (
            np.asarray(assembly) if assembly else np.asarray([float("nan")])
        )
        report["gang_mix"] = {
            "gangs": len(gangs),
            "gang_size": gang_size,
            "gang_cpu": gang_cpu,
            "assembled": len(assembly),
            "partial_gangs": partial,
            "assembly_p50_ms": round(float(np.percentile(asm_arr, 50)), 3),
            "assembly_p99_ms": round(float(np.percentile(asm_arr, 99)), 3),
            "assembly_max_ms": round(float(asm_arr.max()), 3),
        }
    n_shards = getattr(topo, "n_shards", 0)
    if n_shards > 1:
        # per-shard percentiles, grouped by each pod's HOME shard (the
        # scheduler accountable for it — spillover binds still count
        # toward the home shard's latency, which is the user-visible
        # attribution)
        from volcano_tpu.federation.sharding import home_shard

        by_shard: Dict[int, List[float]] = {}
        for key, v in pairs:
            ns, name = key.split("/", 1)
            group = name.rsplit("-t", 1)[0]
            by_shard.setdefault(
                home_shard(ns, group, n_shards), []
            ).append(v)
        report["per_shard"] = {
            str(s): {
                "bound_pods": len(vals),
                "p50_ms": round(float(np.percentile(vals, 50)), 3),
                "p95_ms": round(float(np.percentile(vals, 95)), 3),
                "p99_ms": round(float(np.percentile(vals, 99)), 3),
            }
            for s, vals in sorted(by_shard.items())
        }
    return report


def _stage_breakdown(topo: LoadgenTopology, cap: int = 500) -> dict:
    """Attribute submit→bind latency to pipeline stages from the
    flight-recorder spans collected during the run (volcano_tpu/obs):
    per-stage count / mean / p99 over up to ``cap`` bound pods, plus
    the telemetry channel's own health (exported vs dropped).  The
    ``--stage-breakdown`` report CI uploads next to the SLO JSON."""
    from volcano_tpu import obs

    exp = obs.get_exporter()
    if exp is not None:
        exp.flush_all()
    spans = obs.collect_spans(topo.api)
    with topo._bind_lock:
        pods = [
            tuple(k.split("/", 1)) for k in list(topo.bind_ts)[:cap]
            if "-warm-" not in k
        ]
    out = obs.stage_breakdown(spans, pods)
    out["spans_collected"] = len(spans)
    if exp is not None:
        out["spans_exported"] = exp.exported
        out["spans_dropped"] = exp.dropped
    obs.disable()
    return out


def _cycle_mix(topo: LoadgenTopology) -> dict:
    from volcano_tpu.metrics import metrics

    micro = topo.scheduler.micro_cycles_run
    full = topo.scheduler.full_cycles_run
    fallbacks = {}
    with metrics.registry._lock:
        for (name, labels), v in metrics.registry._counters.items():
            if name.endswith("full_cycle_fallbacks_total"):
                fallbacks[dict(labels).get("cause", "?")] = v
    return {
        "micro_cycles": micro,
        "full_cycles": full,
        "micro_mix": round(micro / max(micro + full, 1), 3),
        "full_cycle_fallbacks": fallbacks,
    }


def _session_stats(topo: LoadgenTopology) -> dict:
    """Session-open cost + incremental-plane counters from the
    in-process scheduler — the numbers the --resident-sweep gates."""
    s = topo.scheduler
    return {
        "sessions_opened": s.sessions_opened,
        "session_open_mean_ms": round(
            s.session_open_seconds / max(s.sessions_opened, 1) * 1e3, 3),
        "restricted_cycles": s.restricted_cycles_run,
        "restricted_open_mean_ms": round(
            s.restricted_open_seconds
            / max(s.restricted_open_cycles, 1) * 1e3, 3),
        # median: the steady-cycle cost — one GC/contention stall in a
        # short CI run must not read as an O(resident) regression
        "restricted_open_p50_ms": round(float(np.median(
            s.restricted_open_samples)) * 1e3, 3)
        if s.restricted_open_samples else 0.0,
        "shadow_checks": s.shadow_checks_run,
        "shadow_divergences": s.shadow_divergences,
    }


def _warm_names(label: str, n_shards: int):
    """Warm job names covering every home shard (so each federation
    member compiles its kernels off the clock, not on the first
    measured arrival)."""
    from volcano_tpu.federation.sharding import home_shard

    out = []
    for shard in range(max(n_shards, 1)):
        k = 0
        while True:
            name = f"{label}-warm-s{shard}-{k}"
            if n_shards <= 1 or home_shard("ns", name, n_shards) == shard:
                out.append(name)
                break
            k += 1
    return out


def _gang_plan(args) -> tuple:
    """(gang_every, gang_size, gang_cpu) for ``--gang-mix``.  The auto
    size is deliberately OVERSIZED: larger than the task capacity of
    the biggest single shard (per-shard node counts come from the same
    crc32 hash every member uses), so no home shard can ever fit it and
    every gang exercises the cross-shard txn_commit assembly path."""
    if args.gang_mix <= 0:
        return 0, 0, ""
    gang_every = max(int(round(1.0 / args.gang_mix)), 1)
    gang_cpu = args.gang_cpu or str(max(args.node_cpu // 2, 1))
    # gang_cpu is a k8s cpu quantity like --cpu ("500m" or "2") — parse
    # with the store's own quantity parser so sizing cannot drift from
    # how the schedulers account the same string
    from volcano_tpu.apis.quantity import milli_value

    cores = milli_value(gang_cpu) / 1e3
    slots_per_node = max(int(args.node_cpu / max(cores, 1e-9)), 1)
    gang_size = args.gang_size
    if gang_size <= 0:
        if args.shards > 1:
            from volcano_tpu.federation.sharding import shard_of_node

            per_shard: Dict[int, int] = {}
            for i in range(args.nodes):
                s = shard_of_node(_node_name(i), args.shards)
                per_shard[s] = per_shard.get(s, 0) + 1
            gang_size = max(per_shard.values()) * slots_per_node + 1
        else:
            gang_size = min(8, args.nodes * slots_per_node)
    # an infeasible gang (bigger than the whole cluster) would wedge
    # the drain by design — clamp to what the fleet can ever hold
    gang_size = min(gang_size, args.nodes * slots_per_node)
    return gang_every, gang_size, gang_cpu


def run_loadgen(args) -> dict:
    with tempfile.NamedTemporaryFile("w", suffix=".yaml", delete=False) as f:
        f.write(CONF)
        conf_path = f.name

    gang_every, gang_size, gang_cpu = _gang_plan(args)

    def fresh_topo():
        if args.shards > 0:
            ramp_flags = []
            n_members = 0
            if args.ramp:
                # the scale-up-under-load drill: every member runs the
                # autoscale controller with a CI-tight policy (short
                # sustain/cooldown, queue-depth trigger) and the member
                # pool is pre-provisioned to the ceiling so the
                # rebalance gate measures the LEASE PLANE, not Python
                # process startup.  Scale-down is disabled for the
                # drill (down-pending 0 can never be breached): the
                # drill gates the up transition; the drain must not
                # race a shrink re-key.
                n_members = args.ramp_max_shards
                ramp_flags = [
                    "--shard-autoscale", "on",
                    "--autoscale-min", str(args.shards),
                    "--autoscale-max", str(args.ramp_max_shards),
                    "--autoscale-up-pending", str(args.ramp_up_pending),
                    "--autoscale-up-p99-ms", "1500",
                    "--autoscale-down-pending", "0",
                    "--autoscale-sustain", "2",
                    "--autoscale-cooldown-s", "3.0",
                    "--autoscale-period-s", "0.5",
                ]
            if args.restricted_sessions:
                ramp_flags = [*ramp_flags, "--restricted-sessions"]
            topo = FederatedTopology(
                n_nodes=args.nodes, node_cpu=args.node_cpu,
                conf_path=conf_path, period=args.period,
                debounce_ms=args.debounce_ms,
                n_shards=args.shards,
                lease_duration=args.shard_lease_duration,
                micro_cycles=not args.no_micro_cycles,
                n_members=n_members,
                extra_flags=ramp_flags,
                resident=args.resident,
            )
        elif args.apiserver_replicas > 0:
            topo = ReplicatedBusTopology(
                n_nodes=args.nodes, node_cpu=args.node_cpu,
                conf_path=conf_path, period=args.period,
                debounce_ms=args.debounce_ms,
                n_replicas=args.apiserver_replicas,
                lease_ttl=args.repl_lease_ttl,
                micro_cycles=not args.no_micro_cycles,
            )
        else:
            topo = LoadgenTopology(
                n_nodes=args.nodes, node_cpu=args.node_cpu,
                conf_path=conf_path, period=args.period,
                debounce_ms=args.debounce_ms,
                micro_cycles=not args.no_micro_cycles,
                restricted=args.restricted_sessions,
                resident=args.resident,
            )
        topo.complete_after_s = args.complete_after_s
        return topo

    def one_run(rate: float, label: str) -> dict:
        topo = fresh_topo()
        killers = []
        drill_done = threading.Event()
        drill_done.set()  # only the membership drill clears it
        scale_watcher = None
        if args.ramp:
            scale_watcher = _ScaleWatcher(
                topo.api, args.shard_lease_duration
            )
            scale_watcher.start()
        try:
            # warmup: prime the jit cache + watch streams off the clock,
            # so the first measured pod doesn't pay a kernel compile.
            # Two bursts of different sizes walk the scatter/kernel
            # shape buckets a churning run will actually hit; federated
            # runs warm EVERY member (one name per home shard).
            deadline = time.monotonic() + args.warmup_timeout
            for wi, burst in enumerate((4, 24)):
                warm = []
                for name in _warm_names(f"{label}w{wi}", args.shards):
                    warm.extend(topo.submit_job(name, burst, args.cpu))
                while time.monotonic() < deadline:
                    if topo.bound_count(warm) == len(warm):
                        break
                    time.sleep(0.05)
                if topo.bound_count(warm) != len(warm):
                    raise RuntimeError("warmup pods never bound")
            if args.shards > 0 and args.kill_shard_after > 0:
                # the shard-kill scenario under load: SIGKILL member 0
                # mid-stream; survivors must absorb its slices and the
                # drain still requires every pod to bind
                killer = threading.Timer(
                    args.kill_shard_after,
                    lambda: topo.kill_member(0),
                )
                killer.daemon = True
                killer.start()
                killers.append(killer)
            if args.apiserver_replicas > 0 and args.membership_drill:
                # the elastic-membership drill: grow the replication
                # group by one mid-stream (spawn + learner catch-up +
                # add-replica), then retire an original follower — all
                # while the open-loop arrivals keep landing.  Gates:
                # both changes commit, exactly ONE surviving config,
                # zero lost acked binds, zero re-binds.
                drill_done.clear()

                def _membership_drill():
                    try:
                        topo.add_replica_member()
                        time.sleep(1.0)
                        topo.remove_replica_member()
                    finally:
                        drill_done.set()

                killer = threading.Timer(args.membership_after,
                                         _membership_drill)
                killer.daemon = True
                killer.start()
                killers.append(killer)
            if args.apiserver_replicas > 0 and args.kill_apiserver_after > 0:
                # the bus-HA drill: SIGKILL the apiserver LEADER
                # mid-stream; a follower must promote within one lease
                # TTL and the drain still requires every pod to bind
                # (zero lost acknowledged binds, zero re-binds)
                killed = {}
                killer = threading.Timer(
                    args.kill_apiserver_after,
                    lambda: killed.setdefault("id", topo.kill_leader()),
                )
                killer.daemon = True
                killer.start()
                killers.append(killer)
            if args.stage_breakdown and hasattr(topo, "scheduler"):
                # flight recorder on the in-process scheduler: spans
                # batch to the topology's store; attribution runs AFTER
                # the drain, off the measured path.  (Federated runs
                # spawn real daemons — pass --flight-recorder there via
                # VTPU_FLIGHT_RECORDER instead.)
                from volcano_tpu import obs as _obs

                _obs.enable(topo.api, identity=f"loadgen-{label}")
            report = run_phase(
                topo, rate, args.duration, args.tasks_per_job, args.cpu,
                args.drain_timeout, label=label,
                gang_every=gang_every, gang_size=gang_size,
                gang_cpu=gang_cpu,
            )
            if hasattr(topo, "scheduler"):
                report.update(_cycle_mix(topo))
                report.update(_session_stats(topo))
            report["resident_jobs"] = getattr(topo, "n_resident", 0)
            report["rss_bytes"] = topo.rss_report()
            if args.stage_breakdown and hasattr(topo, "scheduler"):
                report["stage_breakdown"] = _stage_breakdown(topo)
            if args.apiserver_replicas > 0:
                report["bus_ha"] = topo.bus_report()
                if args.kill_apiserver_after > 0:
                    report["bus_ha"]["killed_leader"] = killed.get(
                        "id", "<kill timer never fired>"
                    )
                if args.membership_drill:
                    # the drill thread may still be mid-change when the
                    # drain finishes — the report must show END state
                    drill_done.wait(120.0)
                    report["bus_ha"]["membership"] = (
                        topo.membership_report()
                    )
            if args.shards > 0:
                report["federation"] = topo.shard_report()
                if scale_watcher is not None:
                    # give a mid-flight rebalance a bounded window to
                    # complete before stamping the report — the gate
                    # itself is judged in main()
                    gate_s = (args.ramp_rebalance_ttls
                              * args.shard_lease_duration)
                    deadline = time.monotonic() + gate_s
                    while time.monotonic() < deadline:
                        events = scale_watcher.report()
                        if events and all(
                            e["rebalance_s"] is not None for e in events
                        ):
                            break
                        time.sleep(0.2)
                    report["elastic"] = {
                        "events": scale_watcher.report(),
                        "lease_ttl_s": args.shard_lease_duration,
                        "gate_ttls": args.ramp_rebalance_ttls,
                    }
                if args.kill_shard_after > 0:
                    report["killed_member"] = "shard0"
                from volcano_tpu.federation import verify_federation

                policy = verify_federation(topo.api, args.shards)
                report["policy_equivalent"] = policy["ok"]
                if not policy["ok"]:
                    report["policy_violations"] = policy["violations"][:20]
            return report
        finally:
            for killer in killers:
                killer.cancel()
            if scale_watcher is not None:
                scale_watcher.stop()
            if args.stage_breakdown:
                from volcano_tpu import obs as _obs

                _obs.disable()  # idempotent; guards the error paths
            topo.close()

    out = {
        "harness": "loadgen",
        "config": {
            "nodes": args.nodes,
            "node_cpu": args.node_cpu,
            "duration_s": args.duration,
            "debounce_ms": args.debounce_ms,
            "schedule_period_s": args.period,
            "micro_cycles": not args.no_micro_cycles,
            "shards": args.shards,
            "gang_mix": args.gang_mix,
            "quick": args.quick,
        },
    }
    out["run"] = one_run(args.rate, "run")

    if args.find_saturation:
        # ramp the offered rate until p99 breaks the SLO (or pods stop
        # binding); each step runs on a FRESH topology so earlier
        # backlogs can't poison later steps
        rate = args.rate
        best = None
        steps = []
        for _ in range(args.saturation_steps):
            rate = rate * 1.5
            r = one_run(rate, f"sat{int(rate)}")
            steps.append(r)
            ok = (
                r["bound_pods"] == r["submitted_pods"]
                and r["p99_ms"] <= args.slo_ms
            )
            if not ok:
                break
            best = r
        out["saturation_steps"] = steps
        out["saturation_throughput_pods_per_s"] = (
            best["achieved_pods_per_s"] if best is not None
            else out["run"]["achieved_pods_per_s"]
        )
    return out


def run_resident_sweep(args) -> dict:
    """The O(pending) flagship drill: hold the pending stream constant
    (same rate, duration, fleet) while the RESIDENT (already-Running)
    job population grows 10×, and require the restricted-session
    scheduler's user-visible numbers to stay put.

    Three runs on fresh topologies:

      1. ``full_baseline``  — full sessions,       ``--resident`` jobs
      2. ``restricted_1x``  — restricted sessions, ``--resident`` jobs
      3. ``restricted_10x`` — restricted sessions, 10 × ``--resident``

    Gates (judged in main, printed as ``LOADGEN FAIL:``):

      * every submitted pod bound, in all three runs;
      * zero shadow-cross-check divergences in the restricted runs
        (and the restricted runs must actually run restricted cycles);
      * p99 submit→bind: restricted_10x ≤ 1.2 × restricted_1x
        (+ a small absolute grace for timer noise at CI shape);
      * steady-cycle open cost: restricted-cycle session-open MEDIAN
        of restricted_10x ≤ 2 × restricted_1x (+0.25ms timer-noise
        floor).  Periodic FULL cycles stay O(resident) by design, so
        the gate reads the restricted-only samples, not the blended
        mean — and the median, so one GC stall in a short CI run
        doesn't read as an O(resident) regression.

    Two O(resident)-BY-DESIGN costs are deliberately kept off the
    measured clock, in both the 1x and 10x runs, so the gates read the
    steady-state plane and not the amortized maintenance:

      * periodic full-session re-equilibration — run the sweep with
        ``--period`` longer than the stream (the quick preset and the
        campaign recipe both do);
      * shadow cross-check audits (each one opens a FULL session over
        the same snapshot) — sampling is disabled during the stream,
        then forced to EVERY cycle for a burst of post-drain audit
        jobs, so each restricted run still proves zero divergence on
        live traffic (``shadow_checks`` ≥ 1 is itself gated).
    """
    with tempfile.NamedTemporaryFile("w", suffix=".yaml", delete=False) as f:
        f.write(CONF)
        conf_path = f.name

    # periodic full re-equilibration is O(resident) by design — one
    # tick landing mid-stream adds a full-session stall to whatever
    # pods collide with it, which is maintenance cost, not the
    # steady-state plane the gates read.  Push it past the run window
    # (identically in all three runs; full_baseline still measures
    # full-session cost, every micro wake opens one there).
    period = max(args.period,
                 4.0 * (args.duration + args.drain_timeout
                        + args.warmup_timeout))

    def one(resident: int, restricted: bool, label: str) -> dict:
        topo = LoadgenTopology(
            n_nodes=args.nodes, node_cpu=args.node_cpu,
            conf_path=conf_path, period=period,
            debounce_ms=args.debounce_ms,
            micro_cycles=not args.no_micro_cycles,
            restricted=restricted, resident=resident,
        )
        topo.complete_after_s = args.complete_after_s
        try:
            if restricted:
                # shadow sampling off during the measured stream (see
                # the docstring); the audit burst below re-enables it
                topo.scheduler.shadow_every = 0
            # same warmup contract as run_loadgen: kernels compile off
            # the clock, so the first measured pod pays scheduling, not
            # jit
            deadline = time.monotonic() + args.warmup_timeout
            for wi, burst in enumerate((4, 24)):
                warm = []
                for name in _warm_names(f"{label}w{wi}", 0):
                    warm.extend(topo.submit_job(name, burst, args.cpu))
                while time.monotonic() < deadline:
                    if topo.bound_count(warm) == len(warm):
                        break
                    time.sleep(0.05)
                if topo.bound_count(warm) != len(warm):
                    raise RuntimeError("warmup pods never bound")
            # quiesce: the gang warmup breaks a cycle window, and the
            # NEXT window opens with an unconditional full cycle whose
            # commit barrier drains the warm binds — all O(resident)
            # effluent that must finish off the measured clock
            settle = time.monotonic()
            last = -1
            while time.monotonic() < deadline:
                n = topo.scheduler.sessions_opened
                if n != last:
                    last, settle = n, time.monotonic()
                elif time.monotonic() - settle >= 0.6:
                    break
                time.sleep(0.1)
            # GC off for the measured window (all three runs alike): a
            # gen-2 collection over a 10x-resident heap is a ~100ms
            # stop-the-world stall that lands on whatever pod is in
            # flight — allocator noise, not scheduler behavior.  The
            # window is short; refcounting still frees the bulk.
            import gc

            gc.collect()
            gc.disable()
            try:
                report = run_phase(
                    topo, args.rate, args.duration, args.tasks_per_job,
                    args.cpu, args.drain_timeout, label=label,
                )
            finally:
                gc.enable()
            if restricted:
                # forced-audit burst: every cycle now runs the shadow
                # full-session cross-check, so the zero-divergence gate
                # is proven on live traffic, off the measured clock
                topo.scheduler.shadow_every = 1
                audit = []
                for i in range(3):
                    audit.extend(
                        topo.submit_job(f"{label}-audit-{i}", 1, args.cpu)
                    )
                deadline = time.monotonic() + args.drain_timeout
                while time.monotonic() < deadline:
                    if topo.bound_count(audit) == len(audit):
                        break
                    time.sleep(0.05)
                if topo.bound_count(audit) != len(audit):
                    raise RuntimeError("audit pods never bound")
            report.update(_cycle_mix(topo))
            report.update(_session_stats(topo))
            report["resident_jobs"] = resident
            report["restricted_sessions"] = restricted
            report["rss_bytes"] = topo.rss_report()
            return report
        finally:
            topo.close()

    base = args.resident
    return {
        "harness": "loadgen-resident",
        "config": {
            "nodes": args.nodes,
            "node_cpu": args.node_cpu,
            "rate": args.rate,
            "duration_s": args.duration,
            "resident_base": base,
            "p99_ratio_gate": 1.2,
            "p99_grace_ms": args.resident_p99_grace_ms,
            "open_cost_ratio_gate": 2.0,
            "quick": args.quick,
        },
        "full_baseline": one(base, False, "f1x"),
        "restricted_1x": one(base, True, "r1x"),
        "restricted_10x": one(base * 10, True, "r10x"),
    }


def _resident_gates(report, grace_ms: float) -> list:
    """Gate messages for a --resident-sweep report ([] = pass)."""
    fails = []
    for key in ("full_baseline", "restricted_1x", "restricted_10x"):
        r = report[key]
        if r["bound_pods"] != r["submitted_pods"]:
            fails.append(
                f"{key}: {r['submitted_pods'] - r['bound_pods']} pods "
                "never bound"
            )
    r1 = report["restricted_1x"]
    r10 = report["restricted_10x"]
    for key, r in (("restricted_1x", r1), ("restricted_10x", r10)):
        if r["restricted_cycles"] == 0:
            fails.append(f"{key}: no restricted cycles ran — the sweep "
                         "never exercised the incremental plane")
        if r["shadow_checks"] == 0:
            fails.append(f"{key}: no shadow cross-checks ran — the "
                         "zero-divergence gate is vacuous")
        if r["shadow_divergences"]:
            fails.append(f"{key}: {r['shadow_divergences']} shadow "
                         "cross-check divergences (ledger unsound)")
    p99_gate = 1.2 * r1["p99_ms"] + grace_ms
    if r10["p99_ms"] > p99_gate:
        fails.append(
            f"p99 regressed with 10x resident jobs: {r10['p99_ms']}ms > "
            f"1.2 x {r1['p99_ms']}ms + {grace_ms}ms grace"
        )
    # median, not mean: the steady-cycle cost.  +0.25ms absolute
    # grace — the timer-noise floor at CI shape.
    open_gate = 2.0 * r1["restricted_open_p50_ms"] + 0.25
    if r10["restricted_open_p50_ms"] > open_gate:
        fails.append(
            "restricted session-open cost is not O(pending): "
            f"{r10['restricted_open_p50_ms']}ms median at 10x resident "
            f"> 2 x {r1['restricted_open_p50_ms']}ms + 0.25ms grace"
        )
    return fails


def run_slo_burn_drill(args) -> dict:
    """The black-box drill (ISSUE 19): run the in-process topology with
    the diagnostics plane armed — tail-sampled flight recorder, a
    burn-rate watchdog over a TimeSeriesRing of the live registry, and
    an IncidentManager — then inject a seeded ``commit.delay`` burst so
    submit→bind p99 burns through its objective.  The breach must
    edge-trigger EXACTLY ONE incident bundle, the bundle must land
    while the cluster capture boost it CAS'd is still live and carry
    the breach-window bind traces, and the watchdog must CLEAR once the
    burst rolls out of its windows (main() gates all of it)."""
    from volcano_tpu import faults, obs
    from volcano_tpu.metrics.timeseries import TimeSeriesRing
    from volcano_tpu.obs.incident import IncidentManager
    from volcano_tpu.obs.slo import BurnRateWatchdog, resolve_slos

    with tempfile.NamedTemporaryFile("w", suffix=".yaml", delete=False) as f:
        f.write(CONF)
        conf_path = f.name
    incident_dir = args.incident_dir or tempfile.mkdtemp(
        prefix="vtpu-incidents-")

    topo = LoadgenTopology(
        n_nodes=args.nodes, node_cpu=args.node_cpu, conf_path=conf_path,
        period=args.period, debounce_ms=args.debounce_ms,
        micro_cycles=not args.no_micro_cycles,
    )
    topo.complete_after_s = args.complete_after_s
    # the diagnostics plane, exactly as a daemon wires it: tail-mode
    # exporter (steady traces drop, evidence keeps), ring + watchdog,
    # breach → incident manager (bundle + capture boost CAS)
    obs.enable(topo.api, identity="loadgen-sched", flush_interval=0.1,
               sample=0.05, tail=True)
    ring = TimeSeriesRing()
    mgr = IncidentManager(
        topo.api, "loadgen-sched", incident_dir,
        cooldown_s=300.0,  # one bundle per episode, guaranteed
        boost_ttl_s=args.burn_boost_ttl, settle_s=1.5, metrics_ring=ring,
    )
    fast_s, slow_s = 3.0, 9.0
    breach_ts: List[float] = []

    def on_breach(alert):
        breach_ts.append(time.time())
        mgr.on_alert(alert)

    # only the SLO the burst targets: the default set also watches
    # micro-cycle latency etc., which CI-shape load can breach on its
    # own and would double the episode count
    slos = [s for s in resolve_slos(
        f"submit-bind-p99={args.burn_objective_ms:g}")
        if s.name == "submit-bind-p99"]
    wd = BurnRateWatchdog(
        ring, slos=slos,
        fast_window_s=fast_s, slow_window_s=slow_s, on_breach=on_breach,
    )
    wd_stop = threading.Event()

    def _wd_loop():
        while not wd_stop.wait(0.5):
            try:
                wd.run_once()
            except Exception:  # noqa: BLE001 — the drill gates on
                pass           # outcomes, not watchdog uptime

    degraded_during = False
    cleared = False
    try:
        # warmup off the clock (jit compile latencies must not reach
        # the ring — the watchdog only ever sees steady-state samples)
        warm = topo.submit_job("burnwarm", 8, args.cpu)
        deadline = time.monotonic() + args.warmup_timeout
        while time.monotonic() < deadline:
            if topo.bound_count(warm) == len(warm):
                break
            time.sleep(0.05)
        if topo.bound_count(warm) != len(warm):
            raise RuntimeError("warmup pods never bound")

        threading.Thread(target=_wd_loop, name="burn-watchdog",
                         daemon=True).start()
        # both burn windows need history before they can confirm a
        # breach — idle until the ring spans the slow window
        deadline = time.monotonic() + 4.0 * slow_s
        while time.monotonic() < deadline and ring.span_seconds() < slow_s:
            time.sleep(0.25)

        faults.configure(
            f"seed=19;commit.delay=1.0:ms={int(args.burn_delay_ms)}")
        phase = run_phase(topo, args.rate, args.duration,
                          args.tasks_per_job, args.cpu,
                          args.drain_timeout, label="burn")
        degraded_during = bool(wd.degraded_reasons()) or bool(breach_ts)
        faults.configure(None)
        # the burst is over: the alert must CLEAR as the windows roll
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if not wd.degraded_reasons():
                cleared = True
                break
            time.sleep(0.5)
    finally:
        wd_stop.set()
        faults.configure(None)
        obs.disable()
        topo.close()
        os.unlink(conf_path)

    bundles = sorted(
        d for d in (os.listdir(incident_dir)
                    if os.path.isdir(incident_dir) else [])
        if d.startswith("incident-")
    )
    bundle = {}
    bundle_within_boost = False
    bundle_has_bind_trace = False
    if bundles:
        bdir = os.path.join(incident_dir, bundles[0])
        with open(os.path.join(bdir, "meta.json")) as f:
            meta = json.load(f)
        # captured while the boost it armed was still live
        boost_until = float((meta.get("boost") or {}).get("until", 0.0))
        bundle_within_boost = meta["ts"] <= boost_until
        try:
            with open(os.path.join(bdir, "spans.json")) as f:
                spans = json.load(f)
        except (OSError, ValueError):
            spans = []
        bundle_has_bind_trace = any(
            s.get("name") == "bind:landed" for s in spans)
        bundle = {
            "path": bdir,
            "reason": meta.get("reason"),
            "alerts": meta.get("alerts"),
            "span_count": meta.get("spanCount"),
            "files": meta.get("files"),
            "errors": meta.get("errors"),
        }
    return {
        "config": {
            "topology": "in-process",
            "nodes": args.nodes,
            "burn_delay_ms": args.burn_delay_ms,
            "burn_objective_ms": args.burn_objective_ms,
            "burn_boost_ttl_s": args.burn_boost_ttl,
            "fast_window_s": fast_s,
            "slow_window_s": slow_s,
            "incident_dir": incident_dir,
            "quick": args.quick,
        },
        "run": phase,
        "drill": {
            "breaches": len(breach_ts),
            "degraded_during": degraded_during,
            "degraded_cleared": cleared,
            "bundles": len(bundles),
            "bundle": bundle,
            "bundle_within_boost": bundle_within_boost,
            "bundle_has_bind_trace": bundle_has_bind_trace,
            "suppressed_triggers": mgr.suppressed_triggers,
        },
    }


def _burn_gates(report) -> list:
    """Gate messages for a --slo-burn-drill report ([] = pass)."""
    fails = []
    r = report["run"]
    d = report["drill"]
    if r["bound_pods"] != r["submitted_pods"]:
        fails.append(f"{r['submitted_pods'] - r['bound_pods']} pods "
                     "never bound under the commit.delay burst")
    if not d["breaches"]:
        fails.append("the watchdog never fired — the seeded burst did "
                     "not breach the burn threshold")
    if not d["degraded_during"]:
        fails.append("the breach never surfaced as a degraded reason")
    if d["bundles"] != 1:
        fails.append(f"{d['bundles']} incident bundles captured — the "
                     "episode must produce exactly one")
    elif not d["bundle_within_boost"]:
        fails.append("the bundle landed after its capture boost "
                     "expired (settle/TTL misconfigured)")
    elif not d["bundle_has_bind_trace"]:
        fails.append("the bundle carries no bind:landed span — the "
                     "boost did not retain the breach-window traces")
    if not d["degraded_cleared"]:
        fails.append("the alert never cleared after the burst (stuck "
                     "degraded state)")
    return fails


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="loadgen", description="sustained-load submit→bind SLO harness"
    )
    p.add_argument("--rate", type=float, default=100.0,
                   help="offered arrival rate, jobs/sec (open-loop)")
    p.add_argument("--duration", type=float, default=10.0,
                   help="measured arrival-stream length, seconds")
    p.add_argument("--tasks-per-job", type=int, default=1)
    p.add_argument("--cpu", default="100m", help="per-pod cpu request")
    p.add_argument("--nodes", type=int, default=64)
    p.add_argument("--node-cpu", type=int, default=64)
    p.add_argument("--period", type=float, default=1.0,
                   help="full-cycle re-equilibration period, seconds")
    p.add_argument("--debounce-ms", type=float, default=5.0)
    p.add_argument("--no-micro-cycles", action="store_true",
                   help="baseline: the fixed-period loop (what the SLO "
                   "numbers look like without event-driven scheduling)")
    p.add_argument("--complete-after-s", type=float, default=0.75,
                   help="bound pods complete (pod + podgroup deleted) "
                   "this long after their bind — sustained churn means "
                   "departures too, keeping the resident job count (and "
                   "the O(jobs) session cost) steady.  0 = never")
    p.add_argument("--drain-timeout", type=float, default=30.0)
    p.add_argument("--warmup-timeout", type=float, default=120.0)
    p.add_argument("--find-saturation", action="store_true")
    p.add_argument("--saturation-steps", type=int, default=4)
    p.add_argument("--slo-ms", type=float, default=100.0,
                   help="p99 submit→bind SLO the saturation ramp gates on")
    p.add_argument("--shards", type=int, default=0,
                   help="sharded scheduler federation: spawn N real "
                   "vtpu-scheduler OS processes over the TCP bus, each "
                   "owning a node shard via CAS leases, and report "
                   "per-shard + aggregate percentiles (0 = the "
                   "single-scheduler topology)")
    p.add_argument("--shard-lease-duration", type=float, default=2.0)
    p.add_argument("--apiserver-replicas", type=int, default=0,
                   help="replicated persistent bus: spawn N real "
                   "vtpu-apiserver OS processes (WAL dirs, leader "
                   "election, quorum-acked writes) instead of the "
                   "in-process store (0 = in-process)")
    p.add_argument("--repl-lease-ttl", type=float, default=1.0,
                   help="apiserver leader-liveness lease TTL")
    p.add_argument("--kill-apiserver-after", type=float, default=0.0,
                   help="SIGKILL the apiserver LEADER this many seconds "
                   "into the measured stream (bus HA drill: a follower "
                   "must promote within one lease TTL, every pod must "
                   "still bind, and no pod may be re-bound)")
    p.add_argument("--gang-mix", type=float, default=0.0,
                   help="fraction of arrivals submitted as OVERSIZED "
                   "gangs (minMember == size, auto-sized LARGER than "
                   "any single shard's task capacity) — each one must "
                   "bind via a cross-shard txn_commit assembly; the "
                   "exit gate requires zero partial gangs and the "
                   "report carries full-assembly latency percentiles "
                   "(0 = none; meant for --shards >= 2)")
    p.add_argument("--gang-size", type=int, default=0,
                   help="gang task count (0 = auto: biggest shard's "
                   "task capacity + 1)")
    p.add_argument("--gang-cpu", default="",
                   help="per-gang-task cpu request (default: half a "
                   "node, so each node holds two gang tasks)")
    p.add_argument("--gang-slo-ms", type=float, default=0.0,
                   help="gate: fail when gang full-assembly p99 "
                   "exceeds this (0 = report only)")
    p.add_argument("--ramp", action="store_true",
                   help="elastic scale-up-under-load drill (needs "
                   "--shards >= 1): members run the SLO-driven shard "
                   "autoscaler with a CI-tight policy and the member "
                   "pool is pre-provisioned to --ramp-max-shards; the "
                   "offered stream oversubscribes the fleet so a "
                   "sustained pending backlog forms, the controller "
                   "grows the shard count, and the exit gates require "
                   "zero lost acked binds plus every committed scale "
                   "event rebalanced within --ramp-rebalance-ttls "
                   "lease TTLs")
    p.add_argument("--ramp-max-shards", type=int, default=2,
                   help="autoscaler ceiling (and pre-provisioned "
                   "member-pool size) for the ramp drill")
    p.add_argument("--ramp-up-pending", type=int, default=8,
                   help="per-shard pending-task bar the drill's "
                   "scale-up trigger uses")
    p.add_argument("--ramp-rebalance-ttls", type=float, default=8.0,
                   help="gate: every committed scale event must have "
                   "every slice of the new partition re-held within "
                   "this many lease TTLs")
    p.add_argument("--membership-drill", action="store_true",
                   help="dynamic-membership drill (needs "
                   "--apiserver-replicas >= 2): grow the replication "
                   "group by one mid-stream (spawn + learner catch-up "
                   "+ add-replica), then retire an original follower "
                   "— exit gates: both changes commit, exactly ONE "
                   "surviving config, zero lost acked binds, zero "
                   "re-binds")
    p.add_argument("--membership-after", type=float, default=1.0,
                   help="seconds into the measured stream the "
                   "membership drill starts")
    p.add_argument("--kill-shard-after", type=float, default=0.0,
                   help="SIGKILL shard member 0 this many seconds into "
                   "the measured stream (federation chaos: survivors "
                   "must absorb its slices within one lease TTL and "
                   "every pod must still bind)")
    p.add_argument("--stage-breakdown", action="store_true",
                   help="enable the flight recorder during the run and "
                   "attribute submit→bind latency to stages (cycle, "
                   "kernel, commit flush, bus op, WAL fsync, quorum "
                   "wait, bind landing) from collected spans — the "
                   "per-stage report CI uploads next to the SLO JSON")
    p.add_argument("--restricted-sessions", action="store_true",
                   help="open RESTRICTED sessions (O(pending) "
                   "micro-cycles over the share ledger, with sampled "
                   "shadow full-session cross-checks) — in-process "
                   "topologies flip the Scheduler flag, --shards "
                   "members get the daemon flag (ignored by "
                   "--apiserver-replicas runs)")
    p.add_argument("--resident", type=int, default=0,
                   help="preload this many already-Running single-task "
                   "jobs before the scheduler attaches — the resident "
                   "ballast the incremental-session plane must not pay "
                   "per cycle (the reaper never completes them)")
    p.add_argument("--resident-sweep", action="store_true",
                   help="the O(pending) flagship drill: three runs at "
                   "identical offered load — full sessions at "
                   "--resident jobs, restricted at --resident, "
                   "restricted at 10x --resident — gating p99 within "
                   "1.2x and restricted session-open mean within 2x "
                   "across the 10x resident growth, with zero shadow "
                   "divergences")
    p.add_argument("--resident-p99-grace-ms", type=float, default=10.0,
                   help="absolute grace added to the sweep's 1.2x p99 "
                   "gate (timer noise at CI shape)")
    p.add_argument("--slo-burn-drill", action="store_true",
                   help="black-box diagnostics drill: arm the burn-rate "
                   "watchdog + incident manager over the in-process "
                   "topology, inject a seeded commit.delay burst, and "
                   "gate that the breach produces exactly one incident "
                   "bundle within the capture-boost TTL carrying the "
                   "breach-window traces, then clears")
    p.add_argument("--incident-dir", default="",
                   help="where the drill's incident bundles land "
                   "(default: a fresh temp dir; CI points this at the "
                   "artifact upload path)")
    p.add_argument("--burn-delay-ms", type=float, default=150.0,
                   help="per-commit injected delay during the burst")
    p.add_argument("--burn-objective-ms", type=float, default=50.0,
                   help="submit-bind-p99 objective the drill burns "
                   "through")
    p.add_argument("--burn-boost-ttl", type=float, default=15.0,
                   help="capture-boost TTL the breach arms")
    p.add_argument("--quick", action="store_true",
                   help="CI smoke preset: small fleet, short stream")
    args = p.parse_args(argv)

    if args.ramp and args.shards < 1:
        args.shards = 1  # the drill starts from a 1-shard federation
    if args.membership_drill and args.apiserver_replicas < 2:
        p.error("--membership-drill needs --apiserver-replicas >= 2")

    if args.quick:
        args.rate = 25.0
        args.duration = 4.0
        args.nodes = 16
        args.node_cpu = 64
        args.drain_timeout = 60.0
        if args.ramp:
            # the scale-up drill needs a SUSTAINED backlog: offered
            # residency (rate × complete_after_s × slots-per-pod) must
            # exceed the fleet's slot capacity, so pending depth holds
            # above the trigger bar until the stream ends.  8 nodes ×
            # 8 cpu at 1-cpu pods = 64 slots; 90 pods/s × 1s residency
            # ≈ 90 resident demand → a steady ~25-task queue.
            args.nodes = 8
            args.node_cpu = 8
            args.cpu = "1"
            args.rate = 75.0
            args.duration = 5.0
            args.complete_after_s = 1.0
            args.drain_timeout = 180.0
        if args.gang_mix > 0:
            # gang arrivals are node-sized: 25 jobs/s of half-node
            # tasks would oversubscribe the 16-node quick fleet many
            # times over before churn can free it
            args.rate = 5.0
            args.drain_timeout = 120.0
        if args.slo_burn_drill:
            # the burn windows need the burst to SPAN them: a longer,
            # gentler stream so the breach, the settled capture, and
            # post-breach binds all land inside the measured phase
            args.rate = 15.0
            args.duration = 8.0
        if args.resident_sweep and args.resident == 0:
            # 100 → 1000 resident jobs across the sweep: enough that an
            # O(resident) open cost would blow the 2x gate, small
            # enough for CI
            args.resident = 100

    if args.slo_burn_drill:
        report = run_slo_burn_drill(args)
        json.dump(report, sys.stdout, indent=2)
        sys.stdout.write("\n")
        fails = _burn_gates(report)
        for msg in fails:
            print(f"LOADGEN FAIL: {msg}", file=sys.stderr)
        return 1 if fails else 0

    if args.resident_sweep:
        if args.resident <= 0:
            p.error("--resident-sweep needs --resident > 0 (or --quick)")
        report = run_resident_sweep(args)
        json.dump(report, sys.stdout, indent=2)
        sys.stdout.write("\n")
        fails = _resident_gates(report, args.resident_p99_grace_ms)
        for msg in fails:
            print(f"LOADGEN FAIL: {msg}", file=sys.stderr)
        return 1 if fails else 0

    report = run_loadgen(args)
    json.dump(report, sys.stdout, indent=2)
    sys.stdout.write("\n")
    # the acceptance gate: every pod bound, and (micro mode) the quick
    # config meets the p99 SLO
    r = report["run"]
    if r["bound_pods"] != r["submitted_pods"]:
        print(f"LOADGEN FAIL: {r['submitted_pods'] - r['bound_pods']} pods "
              f"never bound", file=sys.stderr)
        return 1
    if args.shards > 0 and not r.get("policy_equivalent", True):
        print("LOADGEN FAIL: federation run is not policy-equivalent: "
              f"{r.get('policy_violations')}", file=sys.stderr)
        return 1
    gm = r.get("gang_mix")
    if gm is not None:
        if gm["partial_gangs"]:
            print(f"LOADGEN FAIL: {gm['partial_gangs']} gangs are "
                  "PARTIALLY placed — the txn_commit atomicity "
                  "invariant is broken", file=sys.stderr)
            return 1
        if args.gang_slo_ms > 0 and gm["assembly_p99_ms"] > args.gang_slo_ms:
            print(f"LOADGEN FAIL: gang assembly p99 "
                  f"{gm['assembly_p99_ms']}ms > SLO {args.gang_slo_ms}ms",
                  file=sys.stderr)
            return 1
    if args.ramp:
        el = r.get("elastic", {})
        ups = [e for e in el.get("events", ())
               if e.get("direction") == "up"]
        if not ups:
            print("LOADGEN FAIL: the ramp drill committed no scale-up "
                  f"(events: {el.get('events')})", file=sys.stderr)
            return 1
        gate_s = args.ramp_rebalance_ttls * args.shard_lease_duration
        for e in el.get("events", ()):
            if e.get("rebalance_s") is None or e["rebalance_s"] > gate_s:
                print("LOADGEN FAIL: scale event "
                      f"{e['from']}->{e['target']} rebalanced in "
                      f"{e.get('rebalance_s')}s > gate {gate_s}s "
                      f"({args.ramp_rebalance_ttls} lease TTLs)",
                      file=sys.stderr)
                return 1
    if args.membership_drill:
        mem = r.get("bus_ha", {}).get("membership", {})
        bad = [e for e in mem.get("events", ()) if not e.get("ok")]
        if bad or len(mem.get("events", ())) != 2:
            print(f"LOADGEN FAIL: membership drill events: "
                  f"{mem.get('events')}", file=sys.stderr)
            return 1
        if mem.get("distinct_configs") != 1:
            print("LOADGEN FAIL: live replicas disagree on the "
                  f"membership config ({mem.get('distinct_configs')} "
                  f"distinct; epochs {mem.get('epochs')})",
                  file=sys.stderr)
            return 1
    if args.apiserver_replicas > 0:
        ha = r.get("bus_ha", {})
        if ha.get("rebinds", 0) != 0:
            print(f"LOADGEN FAIL: {ha['rebinds']} pods were re-bound "
                  "across the failover (duplicate acknowledged binds)",
                  file=sys.stderr)
            return 1
        if args.kill_apiserver_after > 0:
            roles = list(ha.get("roles", {}).values())
            if roles.count("leader") != 1:
                print(f"LOADGEN FAIL: no single promoted leader after "
                      f"the kill (roles: {ha.get('roles')})",
                      file=sys.stderr)
                return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
