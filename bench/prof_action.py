"""Profile the REAL jax-allocate action through a live Session at scale:
session open (snapshot deep copy), ORDER replay, KERNEL, APPLY loop.

Usage: python bench/prof_action.py [n_tasks] [n_nodes] [gang]
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")
sys.path.insert(0, "tests")

import numpy as np

from tests.builders import build_node, build_pod, build_pod_group, build_queue
from tests.scheduler_helpers import make_cache, tiers
from volcano_tpu.actions.jax_allocate import JaxAllocateAction, compute_task_order
from volcano_tpu.framework import close_session, open_session

n_tasks = int(sys.argv[1]) if len(sys.argv) > 1 else 5_000
n_nodes = int(sys.argv[2]) if len(sys.argv) > 2 else 1_000
gang = int(sys.argv[3]) if len(sys.argv) > 3 else 8

TIERS = tiers(
    ["priority", "gang"],
    ["drf", "predicates", "proportion", "nodeorder", "binpack"],
)

rng = np.random.RandomState(0)
t0 = time.perf_counter()
nodes = [build_node(f"n{i}", {"cpu": "64", "memory": "256Gi"}) for i in range(n_nodes)]
n_jobs = max(1, n_tasks // gang)
pods, pgs = [], []
cpus = rng.choice(["250m", "500m", "1", "2", "4"], size=n_tasks)
mems = rng.choice(["256Mi", "512Mi", "1Gi", "2Gi", "4Gi", "8Gi"], size=n_tasks)
for j in range(n_jobs):
    pgs.append(build_pod_group("ns", f"pg{j}", gang, queue="q"))
for i in range(n_tasks):
    j = min(i // gang, n_jobs - 1)
    pods.append(
        build_pod("ns", f"j{j}-t{i}", "", {"cpu": cpus[i], "memory": mems[i]}, group=f"pg{j}")
    )
build_s = time.perf_counter() - t0

t0 = time.perf_counter()
cache = make_cache(nodes=nodes, pods=pods, pod_groups=pgs, queues=[build_queue("q")])
cache_s = time.perf_counter() - t0

t0 = time.perf_counter()
ssn = open_session(cache, TIERS, [])
open_s = time.perf_counter() - t0

t0 = time.perf_counter()
order = compute_task_order(ssn)
order_s = time.perf_counter() - t0

action = JaxAllocateAction()
t0 = time.perf_counter()
proposals, _snap = action._kernel_proposals(ssn, order)
kernel_s = time.perf_counter() - t0

t0 = time.perf_counter()
action.execute(ssn)
full_s = time.perf_counter() - t0
t0 = time.perf_counter()
close_session(ssn)
close_s = time.perf_counter() - t0

binds = len(cache.binder.binds)
print(f"tasks={n_tasks} nodes={n_nodes} jobs={n_jobs} binds={binds}")
print(f"build_objects_s   {build_s:8.3f}")
print(f"cache_feed_s      {cache_s:8.3f}")
print(f"session_open_s    {open_s:8.3f}")
print(f"order_s           {order_s:8.3f}  ({order_s/n_tasks*1e6:.1f} us/task)")
print(f"kernel_s          {kernel_s:8.3f}")
print(f"apply(full2nd)_s  {full_s:8.3f}  (order+kernel+apply; {full_s/n_tasks*1e6:.1f} us/task)")
print(f"close_s           {close_s:8.3f}")
