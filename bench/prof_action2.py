"""Instrumented apply-phase profile: counts kernel-proposal hits,
validation failures, and host fallbacks inside the real action."""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")
sys.path.insert(0, "tests")

import numpy as np

from tests.builders import build_node, build_pod, build_pod_group, build_queue
from tests.scheduler_helpers import make_cache, tiers
from volcano_tpu.actions.allocate import (
    drive_allocate_loop,
    gang_end_job,
    host_node_chooser,
    make_place_task,
    make_predicate_fn,
)
from volcano_tpu.actions.jax_allocate import JaxAllocateAction, compute_task_order
from volcano_tpu.api import FitError
from volcano_tpu.framework import close_session, open_session

n_tasks = int(sys.argv[1]) if len(sys.argv) > 1 else 5_000
n_nodes = int(sys.argv[2]) if len(sys.argv) > 2 else 1_000
gang = int(sys.argv[3]) if len(sys.argv) > 3 else 8

TIERS = tiers(
    ["priority", "gang"],
    ["drf", "predicates", "proportion", "nodeorder", "binpack"],
)

rng = np.random.RandomState(0)
nodes = [build_node(f"n{i}", {"cpu": "64", "memory": "256Gi"}) for i in range(n_nodes)]
n_jobs = max(1, n_tasks // gang)
pods, pgs = [], []
cpus = rng.choice(["250m", "500m", "1", "2", "4"], size=n_tasks)
mems = rng.choice(["256Mi", "512Mi", "1Gi", "2Gi", "4Gi", "8Gi"], size=n_tasks)
for j in range(n_jobs):
    pgs.append(build_pod_group("ns", f"pg{j}", gang, queue="q"))
for i in range(n_tasks):
    j = min(i // gang, n_jobs - 1)
    pods.append(
        build_pod("ns", f"j{j}-t{i}", "", {"cpu": cpus[i], "memory": mems[i]}, group=f"pg{j}")
    )
cache = make_cache(nodes=nodes, pods=pods, pod_groups=pgs, queues=[build_queue("q")])
ssn = open_session(cache, TIERS, [])

action = JaxAllocateAction()
t0 = time.perf_counter()
ordered = compute_task_order(ssn)
order_s = time.perf_counter() - t0
t0 = time.perf_counter()
proposals, _snap = action._kernel_proposals(ssn, ordered)
kernel_s = time.perf_counter() - t0

stats = dict(hit=0, miss=0, vfail=0, fallback_s=0.0, validate_s=0.0, place_s=0.0)
predicate_fn = make_predicate_fn(ssn)
host_choose = host_node_chooser(ssn)


def choose_node(task, job):
    name = proposals.get(task.uid)
    if name is not None:
        node = ssn.nodes.get(name)
        if node is not None:
            t0 = time.perf_counter()
            try:
                predicate_fn(task, node)
                stats["validate_s"] += time.perf_counter() - t0
                stats["hit"] += 1
                return node
            except FitError:
                stats["validate_s"] += time.perf_counter() - t0
                stats["vfail"] += 1
    else:
        stats["miss"] += 1
    t0 = time.perf_counter()
    n = host_choose(task, job)
    stats["fallback_s"] += time.perf_counter() - t0
    return n


t0 = time.perf_counter()
drive_allocate_loop(
    ssn,
    begin_job=lambda job: ssn.statement(),
    place_task=make_place_task(ssn, choose_node),
    end_job=gang_end_job(ssn),
)
apply_s = time.perf_counter() - t0
close_session(ssn)

binds = len(cache.binder.binds)
print(f"tasks={n_tasks} binds={binds} proposals={len(proposals)}")
print(f"order_s     {order_s:8.3f}")
print(f"kernel_s    {kernel_s:8.3f}")
print(f"apply_s     {apply_s:8.3f}")
print(f"  hits={stats['hit']} vfail={stats['vfail']} miss={stats['miss']}")
print(f"  validate_s {stats['validate_s']:8.3f}")
print(f"  fallback_s {stats['fallback_s']:8.3f}")
print(f"  loop_overhead_s {apply_s - stats['validate_s'] - stats['fallback_s']:8.3f}")
