"""Warm-path decomposition of the real jax-allocate action at scale:
order / pack / device / proposals / apply-loop breakdown, second run."""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")
sys.path.insert(0, "tests")

import numpy as np

from tests.builders import build_node, build_pod, build_pod_group, build_queue
from tests.scheduler_helpers import make_cache, tiers
from volcano_tpu.actions.allocate import (
    drive_allocate_loop,
    gang_end_job,
    host_node_chooser,
    make_place_task,
    make_predicate_fn,
)
from volcano_tpu.actions.jax_allocate import JaxAllocateAction, compute_task_order
from volcano_tpu.api import FitError
from volcano_tpu.framework import close_session, open_session
from volcano_tpu.ops.dispatch import run_packed_auto, select_executor
from volcano_tpu.ops.packing import pack_session

n_tasks = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
n_nodes = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
gang = int(sys.argv[3]) if len(sys.argv) > 3 else 8

TIERS = tiers(
    ["priority", "gang"],
    ["drf", "predicates", "proportion", "nodeorder", "binpack"],
)

rng = np.random.RandomState(0)
nodes = [build_node(f"n{i}", {"cpu": "64", "memory": "256Gi"}) for i in range(n_nodes)]
n_jobs = max(1, n_tasks // gang)
pods, pgs = [], []
cpus = rng.choice(["250m", "500m", "1", "2", "4"], size=n_tasks)
mems = rng.choice(["256Mi", "512Mi", "1Gi", "2Gi", "4Gi", "8Gi"], size=n_tasks)
for j in range(n_jobs):
    pgs.append(build_pod_group("ns", f"pg{j}", gang, queue="q"))
for i in range(n_tasks):
    j = min(i // gang, n_jobs - 1)
    pods.append(
        build_pod("ns", f"j{j}-t{i}", "", {"cpu": cpus[i], "memory": mems[i]}, group=f"pg{j}")
    )
# warm run: compile everything once (bindings mutate the cache, so the
# measured run gets a freshly-built cache)
cache = make_cache(nodes=nodes, pods=pods, pod_groups=pgs, queues=[build_queue("q")])
ssn = open_session(cache, TIERS, [])
JaxAllocateAction().execute(ssn)
close_session(ssn)

# measured run
cache = make_cache(nodes=nodes, pods=pods, pod_groups=pgs, queues=[build_queue("q")])
t0 = time.perf_counter()
ssn = open_session(cache, TIERS, [])
open_s = time.perf_counter() - t0

t0 = time.perf_counter()
ordered = compute_task_order(ssn)
order_s = time.perf_counter() - t0

jobs = {}
for t in ordered:
    job = ssn.jobs.get(t.job)
    if job is not None and job.uid not in jobs:
        jobs[job.uid] = job
node_list = [ssn.nodes[name] for name in sorted(ssn.nodes)]

t0 = time.perf_counter()
snap = pack_session(ordered, list(jobs.values()), node_list,
                    enforce_pod_count="predicates" in ssn.predicate_fns)
pack_s = time.perf_counter() - t0

print("executor:", select_executor(snap))
t0 = time.perf_counter()
assignment = run_packed_auto(snap)
device_s = time.perf_counter() - t0

t0 = time.perf_counter()
proposals = {}
for i, task in enumerate(ordered):
    if assignment[i] >= 0 and not snap.task_has_preferences[i]:
        proposals[task.uid] = node_list[assignment[i]].name
prop_s = time.perf_counter() - t0

predicate_fn = make_predicate_fn(ssn)
host_choose = host_node_chooser(ssn)
stats = dict(hit=0, vfail=0, miss=0)


def choose_node(task, job):
    name = proposals.get(task.uid)
    if name is not None:
        node = ssn.nodes.get(name)
        if node is not None:
            try:
                predicate_fn(task, node)
                stats["hit"] += 1
                return node
            except FitError:
                stats["vfail"] += 1
    else:
        stats["miss"] += 1
    return host_choose(task, job)


t0 = time.perf_counter()
drive_allocate_loop(
    ssn,
    begin_job=lambda job: ssn.statement(),
    place_task=make_place_task(ssn, choose_node),
    end_job=gang_end_job(ssn),
)
apply_s = time.perf_counter() - t0
t0 = time.perf_counter()
close_session(ssn)
close_s = time.perf_counter() - t0

total = open_s + order_s + pack_s + device_s + prop_s + apply_s
print(f"tasks={n_tasks} stats={stats}")
print(f"open_s     {open_s:8.3f}")
print(f"order_s    {order_s:8.3f}")
print(f"pack_s     {pack_s:8.3f}")
print(f"device_s   {device_s:8.3f}")
print(f"prop_s     {prop_s:8.3f}")
print(f"apply_s    {apply_s:8.3f}")
print(f"close_s    {close_s:8.3f}")
print(f"TOTAL(open..apply) {total:8.3f}")
