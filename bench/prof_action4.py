"""Phase breakdown of the CURRENT jax-allocate action (fast_order +
fast_apply) at the headline shape, warm run, through the bench harness's
cluster generator so numbers line up with action_latency_* metrics."""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import volcano_tpu.actions  # noqa: F401
import volcano_tpu.plugins  # noqa: F401
from volcano_tpu.actions.jax_allocate import JaxAllocateAction, compute_task_order
from volcano_tpu.cache import SchedulerCache
from volcano_tpu.conf import PluginOption, Tier
from volcano_tpu.framework import close_session, open_session
from volcano_tpu.ops.synthetic import generate_cluster_objects

n_tasks = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
n_nodes = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000

kwargs = dict(n_tasks=n_tasks, n_nodes=n_nodes, gang_size=8,
              label_classes=8, taint_fraction=0.1)
nodes, pods, pgs, queues = generate_cluster_objects(**kwargs)

TIERS = [
    Tier(plugins=[PluginOption(name=n) for n in ("priority", "gang")]),
    Tier(plugins=[
        PluginOption(name=n)
        for n in ("drf", "predicates", "proportion", "nodeorder", "binpack")
    ]),
]


class _ListBinder:
    def __init__(self):
        self.binds = []

    def bind(self, task, hostname):
        self.binds.append((f"{task.namespace}/{task.name}", hostname))


def fresh_cache():
    cache = SchedulerCache(binder=_ListBinder())
    for n in nodes:
        cache.add_node(n)
    for p in pods:
        cache.add_pod(p)
    for pg in pgs:
        cache.add_pod_group(pg)
    for q in queues:
        cache.add_queue(q)
    return cache


action = JaxAllocateAction()

for run in range(2):  # run 0 = compile warmup
    t_feed0 = time.perf_counter()
    cache = fresh_cache()
    t_feed = time.perf_counter() - t_feed0

    t0 = time.perf_counter()
    ssn = open_session(cache, TIERS, [])
    open_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    ordered = compute_task_order(ssn)
    order_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    proposals, snap = action._kernel_proposals(ssn, ordered)
    kern_s = time.perf_counter() - t0

    from volcano_tpu.actions.fast_apply import try_fast_apply

    t0 = time.perf_counter()
    ok = try_fast_apply(ssn, ordered, proposals, snap)
    apply_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    close_session(ssn)
    close_s = time.perf_counter() - t0

    total = order_s + kern_s + apply_s
    print(f"run{run}: feed={t_feed:.3f}s open={open_s:.3f}s "
          f"order={order_s:.3f}s kernel={kern_s:.3f}s "
          f"fast_apply={apply_s:.3f}s(ok={ok}) close={close_s:.3f}s "
          f"action_total={total:.3f}s binds={len(cache.binder.binds)}")
