"""Phase breakdown of the CURRENT jax-allocate action (fast_order +
fast_apply) at the headline shape, warm run (shape args: [tasks [nodes]])."""

from __future__ import annotations

import sys
import time

sys.path.insert(0, "bench")
sys.path.insert(0, ".")

from _profsetup import TIERS, make_cache_builder  # noqa: E402

from volcano_tpu.actions.fast_apply import try_fast_apply  # noqa: E402
from volcano_tpu.actions.jax_allocate import (  # noqa: E402
    JaxAllocateAction,
    compute_task_order,
)
from volcano_tpu.framework import close_session, open_session  # noqa: E402

overrides = {}
if len(sys.argv) > 1:
    overrides["n_tasks"] = int(sys.argv[1])
if len(sys.argv) > 2:
    overrides["n_nodes"] = int(sys.argv[2])
fresh_cache = make_cache_builder(**overrides)
action = JaxAllocateAction()

for run in range(2):  # run 0 = compile warmup
    t_feed0 = time.perf_counter()
    cache = fresh_cache()
    t_feed = time.perf_counter() - t_feed0

    t0 = time.perf_counter()
    ssn = open_session(cache, TIERS, [])
    open_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    ordered = compute_task_order(ssn)
    order_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    proposals, snap = action._kernel_proposals(ssn, ordered)
    kern_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    ok = try_fast_apply(ssn, ordered, proposals, snap)
    apply_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    close_session(ssn)
    close_s = time.perf_counter() - t0

    total = order_s + kern_s + apply_s
    print(f"run{run}: feed={t_feed:.3f}s open={open_s:.3f}s "
          f"order={order_s:.3f}s kernel={kern_s:.3f}s "
          f"fast_apply={apply_s:.3f}s(ok={ok}) close={close_s:.3f}s "
          f"action_total={total:.3f}s binds={len(cache.binder.binds)}")
