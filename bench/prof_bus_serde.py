"""prof_bus_serde — pin the once-per-event VBUS encode under fan-out.

The federation topology multiplies watch subscribers: every store
mutation fans out to N scheduler processes (plus controllers), and
before this PR the server re-ran ``json.dumps`` on the same event entry
once per subscriber — encode cost scaled O(subscribers), the named
prerequisite (ROADMAP item 4) for scaling the scheduler count.  Now the
entry body is serialized once (``bus/server.py::_CachedPayload``) and
the cached bytes are shared by every per-connection writer and spliced
into ``watch_batch`` frames.

This profile counts both sides of the cache — ``raw()``/``raw_bin()``
*calls* (the per-subscriber fan-out, whichever codec the connections
negotiated) vs actual *encodes* — while M real TCP subscribers drain
K store mutations, and fails when encodes stop being O(events).

Since VBUS v8 it also emits the codec-floor comparison the CI
``serde-floor`` artifact pins: encode + decode ns/frame and
bytes/frame for a watch-event body of every registered kind, JSON vs
binary (msgpack), so a codec regression shows up as a number, not a
feeling.

Usage::

    JAX_PLATFORMS=cpu python bench/prof_bus_serde.py
    python bench/prof_bus_serde.py --subscribers 8 --events 2000
    python bench/prof_bus_serde.py --codecs-only   # just the comparison
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def run(subscribers: int, events: int, timeout: float) -> dict:
    from volcano_tpu.apis import core
    from volcano_tpu.bus import server as server_mod
    from volcano_tpu.bus.remote import RemoteAPIServer
    from volcano_tpu.bus.server import BusServer
    from volcano_tpu.client import APIServer

    counts = {"fanout_calls": 0, "encodes": 0}
    lock = threading.Lock()
    original_raw = server_mod._CachedPayload.raw
    original_raw_bin = server_mod._CachedPayload.raw_bin

    # count BOTH cache slots: v8 connections negotiate binary and fan
    # out through raw_bin(); a JSON-pinned run still rides raw()
    def counting_raw(self):
        with lock:
            counts["fanout_calls"] += 1
            if self._raw is None:
                counts["encodes"] += 1
        return original_raw(self)

    def counting_raw_bin(self):
        with lock:
            counts["fanout_calls"] += 1
            if self._raw_bin is None:
                counts["encodes"] += 1
        return original_raw_bin(self)

    server_mod._CachedPayload.raw = counting_raw
    server_mod._CachedPayload.raw_bin = counting_raw_bin
    api = APIServer()
    bus = BusServer(api).start()
    clients = []
    seen = [0] * subscribers
    done = threading.Event()

    def handler_for(i):
        def handler(event, old, new):
            seen[i] += 1
            if all(s >= events for s in seen):
                done.set()
        return handler

    try:
        for i in range(subscribers):
            c = RemoteAPIServer(f"tcp://127.0.0.1:{bus.port}", timeout=10.0)
            assert c.wait_ready(10.0)
            c.watch("Pod", handler_for(i), send_initial=False)
            clients.append(c)
        time.sleep(0.2)  # let every watch land before the clock starts
        start = time.perf_counter()
        for n in range(events):
            api.create(core.Pod(
                metadata=core.ObjectMeta(name=f"p{n:06d}", namespace="ns"),
                spec=core.PodSpec(),
                status=core.PodStatus(phase="Pending"),
            ))
        if not done.wait(timeout):
            raise RuntimeError(
                f"subscribers drained only {seen} of {events} events "
                f"within {timeout}s"
            )
        elapsed = time.perf_counter() - start
    finally:
        server_mod._CachedPayload.raw = original_raw
        server_mod._CachedPayload.raw_bin = original_raw_bin
        for c in clients:
            c.close()
        bus.stop()

    delivered = sum(seen)
    # bookmarks also ride cached payloads — allow their small overhead
    # in the encode budget, but the per-subscriber fan-out must not
    # re-encode: encodes must track events, not events × subscribers
    encodes_per_event = counts["encodes"] / max(events, 1)
    return {
        "harness": "prof_bus_serde",
        "subscribers": subscribers,
        "events": events,
        "delivered_frames_worth": delivered,
        "elapsed_s": round(elapsed, 4),
        "delivered_per_s": round(delivered / max(elapsed, 1e-9), 1),
        "encodes": counts["encodes"],
        "fanout_raw_calls": counts["fanout_calls"],
        "encodes_per_event": round(encodes_per_event, 4),
        "legacy_encodes_would_be": events * subscribers,
        "ok": encodes_per_event <= 1.5,  # 1 + bookmark slack
    }


def _exemplar_corpus() -> dict:
    """kind → encoded exemplar dict.  The canonical corpus lives in
    ``tests/test_bus.py::SERDE_EXEMPLARS`` (the SRD001/SRD006 fixture);
    outside a repo checkout fall back to a representative Pod so the
    profile still runs against an installed package."""
    from volcano_tpu.bus import protocol

    try:
        from tests.test_bus import SERDE_EXEMPLARS
        return {
            kind: protocol.encode_obj(make())
            for kind, make in sorted(SERDE_EXEMPLARS.items())
        }
    except ImportError:
        from volcano_tpu.apis import core

        pod = core.Pod(
            metadata=core.ObjectMeta(name="p0", namespace="ns"),
            spec=core.PodSpec(),
            status=core.PodStatus(phase="Pending"),
        )
        return {"Pod": protocol.encode_obj(pod)}


def codec_compare(iters: int = 300) -> list:
    """The serde floor per kind per codec: median-free simple mean of
    ``iters`` encode and decode passes over a watch-event body (the
    fan-out hot path's frame shape), plus the wire size.  One row per
    (kind, codec)."""
    from volcano_tpu.bus import protocol

    codecs = [protocol.CODEC_JSON]
    if protocol.HAS_BINARY:
        codecs.append(protocol.CODEC_BINARY)
    rows = []
    for kind, data in _exemplar_corpus().items():
        body = {"watch_id": 7, "seq": 1, "kind": kind, "event": "ADDED",
                "old": None, "new": data, "ts": 0.0}
        for codec in codecs:
            wire = protocol.encode_payload(body, codec=codec)
            t0 = time.perf_counter()
            for _ in range(iters):
                protocol.encode_payload(body, codec=codec)
            enc_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            for _ in range(iters):
                protocol.decode_payload(wire, codec=codec)
            dec_s = time.perf_counter() - t0
            rows.append({
                "kind": kind,
                "codec": codec,
                "bytes_per_frame": len(wire),
                "encode_ns_per_frame": round(enc_s / iters * 1e9),
                "decode_ns_per_frame": round(dec_s / iters * 1e9),
            })
    return rows


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="prof_bus_serde")
    p.add_argument("--subscribers", type=int, default=4)
    p.add_argument("--events", type=int, default=1000)
    p.add_argument("--timeout", type=float, default=60.0)
    p.add_argument("--codec-iters", type=int, default=300)
    p.add_argument("--codecs-only", action="store_true",
                   help="emit only the JSON-vs-binary serde floor "
                   "(no live bus fan-out run)")
    args = p.parse_args(argv)
    if args.codecs_only:
        report = {"harness": "prof_bus_serde", "ok": True}
    else:
        report = run(args.subscribers, args.events, args.timeout)
    report["codec_compare"] = codec_compare(args.codec_iters)
    json.dump(report, sys.stdout, indent=2)
    sys.stdout.write("\n")
    if not report["ok"]:
        print(
            f"PROF_BUS_SERDE FAIL: {report['encodes_per_event']} encodes "
            f"per event (expected ~1 regardless of "
            f"{args.subscribers} subscribers)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
