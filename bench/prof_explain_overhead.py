"""Explain-mode overhead: jax-allocate action latency with device-derived
unschedulability explanations off vs on, on the 10k-pod synthetic config
plus one permanently-unplaceable gang (so the explain path actually
runs — a fully-placed session computes nothing either way).

Acceptance gate (ISSUE 4): explain-mode warm-cycle overhead must stay
under 10% of action_ms.  The overhead is the on-device reason-count
reduction only; two scenarios are measured:

  * warm   — the backlog re-places every cycle (revert_binds protocol,
             like bench.py's warm action bench).  Placements touch node
             state, so the stuck tasks take the host predicate sweep in
             BOTH modes and the on-off delta isolates the reduction.
  * steady — nothing new places; the stuck gang is the whole session.
             With explain on, the device proof replaces the O(N) host
             sweep per stuck task — this mode shows the win, not a cost.

Emits one JSON line per (scenario, mode) plus summary lines, like the
other bench/prof_*.py scripts.
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, "bench")
sys.path.insert(0, ".")

from _profsetup import (  # noqa: E402
    TIERS,
    capture_task_infos,
    make_cache_builder,
    revert_binds,
)

from volcano_tpu.actions.jax_allocate import (  # noqa: E402
    JaxAllocateAction,
    last_phase_stats,
)
from volcano_tpu.apis import core, scheduling  # noqa: E402
from volcano_tpu.framework import close_session, open_session  # noqa: E402

ITERS = 5
STUCK_TASKS = 8

fresh = make_cache_builder(n_tasks=10_000, n_nodes=1_000, gang_size=4)


def add_stuck_gang(cache) -> None:
    """One gang whose pods request more cpu than any node allocates —
    pending forever, explained every cycle."""
    cache.add_pod_group(
        scheduling.PodGroup(
            metadata=core.ObjectMeta(
                name="pgstuck", namespace="bench", uid="pg-stuck",
                creation_timestamp=0.0,
            ),
            spec=scheduling.PodGroupSpec(
                min_member=STUCK_TASKS, queue="default", min_resources={},
            ),
            status=scheduling.PodGroupStatus(
                phase=scheduling.POD_GROUP_INQUEUE
            ),
        )
    )
    for i in range(STUCK_TASKS):
        cache.add_pod(
            core.Pod(
                metadata=core.ObjectMeta(
                    name=f"stuck-{i}", namespace="bench",
                    uid=f"pod-stuck-{i}",
                    annotations={
                        scheduling.GROUP_NAME_ANNOTATION_KEY: "pgstuck"
                    },
                    creation_timestamp=0.0,
                ),
                spec=core.PodSpec(
                    containers=[
                        core.Container(
                            name="main",
                            resources={
                                "requests": {
                                    "cpu": "256000m", "memory": "1024Mi",
                                }
                            },
                        )
                    ],
                    node_name="", node_selector={}, tolerations=[],
                    affinity={},
                ),
                status=core.PodStatus(phase="Pending"),
            )
        )


def run_action(cache, action) -> float:
    """One session through the action; returns action ms."""
    ssn = open_session(cache, TIERS, [])
    try:
        t0 = time.perf_counter()
        action.execute(ssn)
        return (time.perf_counter() - t0) * 1e3
    finally:
        close_session(ssn)


def median(samples) -> float:
    samples = sorted(samples)
    return samples[len(samples) // 2]


cache = fresh()
add_stuck_gang(cache)
orig_tis = capture_task_infos(cache)

# jit warmup (allocate + explain kernels) outside every measurement
run_action(cache, JaxAllocateAction(explain=True))

results = {}
for mode, explain in (("off", False), ("on", True)):
    action = JaxAllocateAction(explain=explain)
    warm, steady, explain_ms = [], [], []
    for _ in range(ITERS):
        revert_binds(cache, orig_tis)
        warm.append(run_action(cache, action))
        if explain:
            explain_ms.append(last_phase_stats.get("explain_ms", 0.0))
        steady.append(run_action(cache, action))
    results[("warm", mode)] = median(warm)
    results[("steady", mode)] = median(steady)
    for scenario in ("warm", "steady"):
        print(json.dumps({
            "metric": "explain_action_latency", "scenario": scenario,
            "mode": mode, "value": round(results[(scenario, mode)], 3),
            "unit": "ms",
        }))
    if explain and explain_ms:
        print(json.dumps({
            "metric": "explain_reduction_latency",
            "value": round(median(explain_ms), 3), "unit": "ms",
        }))

warm_off, warm_on = results[("warm", "off")], results[("warm", "on")]
steady_off, steady_on = results[("steady", "off")], results[("steady", "on")]
warm_pct = (warm_on - warm_off) / warm_off * 100 if warm_off else 0.0
print(json.dumps({
    "metric": "explain_warm_overhead", "value": round(warm_pct, 2),
    "unit": "%", "budget": 10.0, "pass": warm_pct < 10.0,
}))
print(json.dumps({
    "metric": "explain_steady_delta",
    "value": round(
        (steady_on - steady_off) / steady_off * 100 if steady_off else 0.0, 2
    ),
    "unit": "%",
    "note": "negative = explain replaces the host sweep and wins",
}))
sys.exit(0 if warm_pct < 10.0 else 1)
