"""cProfile of the warm fast_apply + fast_order phases at the headline
shape (run after one warmup action)."""

from __future__ import annotations

import cProfile
import pstats
import sys

sys.path.insert(0, "bench")
sys.path.insert(0, ".")

from _profsetup import TIERS, make_cache_builder  # noqa: E402

from volcano_tpu.actions.fast_apply import try_fast_apply  # noqa: E402
from volcano_tpu.actions.jax_allocate import (  # noqa: E402
    JaxAllocateAction,
    compute_task_order,
)
from volcano_tpu.framework import close_session, open_session  # noqa: E402

fresh = make_cache_builder()
action = JaxAllocateAction()

# warmup (compile)
cache = fresh()
ssn = open_session(cache, TIERS, [])
action.execute(ssn)
close_session(ssn)

# profiled warm run, phase by phase
cache = fresh()
ssn = open_session(cache, TIERS, [])
ordered = compute_task_order(ssn)
proposals, snap = action._kernel_proposals(ssn, ordered)

pr = cProfile.Profile()
pr.enable()
ok = try_fast_apply(ssn, ordered, proposals, snap)
pr.disable()
print("fast_apply ok:", ok)
pstats.Stats(pr).sort_stats("cumulative").print_stats(25)

# and profile fast_order on a fresh session
close_session(ssn)
cache = fresh()
ssn = open_session(cache, TIERS, [])
pr = cProfile.Profile()
pr.enable()
ordered = compute_task_order(ssn)
pr.disable()
print("ordered:", len(ordered))
pstats.Stats(pr).sort_stats("cumulative").print_stats(15)
