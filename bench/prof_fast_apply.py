"""cProfile of the warm fast_apply + fast_order phases at the headline
shape (run after one warmup action)."""

from __future__ import annotations

import cProfile
import pstats
import sys
import time

sys.path.insert(0, ".")

import volcano_tpu.actions  # noqa: F401
import volcano_tpu.plugins  # noqa: F401
from volcano_tpu.actions.fast_apply import try_fast_apply
from volcano_tpu.actions.jax_allocate import JaxAllocateAction, compute_task_order
from volcano_tpu.cache import SchedulerCache
from volcano_tpu.conf import PluginOption, Tier
from volcano_tpu.framework import close_session, open_session
from volcano_tpu.ops.synthetic import generate_cluster_objects

kwargs = dict(n_tasks=50_000, n_nodes=10_000, gang_size=8,
              label_classes=8, taint_fraction=0.1)
nodes, pods, pgs, queues = generate_cluster_objects(**kwargs)

TIERS = [
    Tier(plugins=[PluginOption(name=n) for n in ("priority", "gang")]),
    Tier(plugins=[
        PluginOption(name=n)
        for n in ("drf", "predicates", "proportion", "nodeorder", "binpack")
    ]),
]


class _ListBinder:
    def __init__(self):
        self.binds = []

    def bind(self, task, hostname):
        self.binds.append((f"{task.namespace}/{task.name}", hostname))


def fresh():
    cache = SchedulerCache(binder=_ListBinder())
    for n in nodes:
        cache.add_node(n)
    for p in pods:
        cache.add_pod(p)
    for pg in pgs:
        cache.add_pod_group(pg)
    for q in queues:
        cache.add_queue(q)
    return cache


action = JaxAllocateAction()

# warmup (compile)
cache = fresh()
ssn = open_session(cache, TIERS, [])
action.execute(ssn)
close_session(ssn)

# profiled warm run, phase by phase
cache = fresh()
ssn = open_session(cache, TIERS, [])
ordered = compute_task_order(ssn)
proposals, snap = action._kernel_proposals(ssn, ordered)

pr = cProfile.Profile()
pr.enable()
ok = try_fast_apply(ssn, ordered, proposals, snap)
pr.disable()
print("fast_apply ok:", ok)
pstats.Stats(pr).sort_stats("cumulative").print_stats(25)

# and profile fast_order on a fresh session
close_session(ssn)
cache = fresh()
ssn = open_session(cache, TIERS, [])
pr = cProfile.Profile()
pr.enable()
ordered = compute_task_order(ssn)
pr.disable()
print("ordered:", len(ordered))
pstats.Stats(pr).sort_stats("cumulative").print_stats(15)
