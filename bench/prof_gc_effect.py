"""Quantify GC's share of the warm action latency: run the warm action
with (a) default gc, (b) gc.freeze() of all pre-action survivors, and
report both plus collection counts."""

from __future__ import annotations

import gc
import sys
import time

sys.path.insert(0, "bench")
sys.path.insert(0, ".")

from _profsetup import TIERS, make_cache_builder  # noqa: E402

from volcano_tpu.actions.jax_allocate import JaxAllocateAction  # noqa: E402
from volcano_tpu.framework import close_session, open_session  # noqa: E402

fresh = make_cache_builder()
action = JaxAllocateAction()


def one(tag):
    cache = fresh()
    t0 = time.perf_counter()
    ssn = open_session(cache, TIERS, [])
    t1 = time.perf_counter()
    action.execute(ssn)
    t2 = time.perf_counter()
    close_session(ssn)
    c0 = gc.get_count()
    print(f"{tag}: open={t1-t0:.3f}s exec={t2-t1:.3f}s gc_count={c0} "
          f"collections={[s['collections'] for s in gc.get_stats()]}")


one("warmup")
one("warm-default-gc")
one("warm-default-gc2")

# simulate accumulated survivors: keep several big caches alive (what the
# earlier bench configs leave behind), then measure again
ballast = [fresh() for _ in range(2)]
one("ballast-default-gc")

gc.collect()
gc.freeze()
one("ballast-frozen")
one("ballast-frozen2")
del ballast
