"""Quantify GC's share of the warm action latency: run the warm action
with (a) default gc, (b) gc.freeze() of all pre-action survivors, and
report both plus collection counts."""

from __future__ import annotations

import gc
import sys
import time

sys.path.insert(0, ".")

import volcano_tpu.actions  # noqa: F401
import volcano_tpu.plugins  # noqa: F401
from volcano_tpu.actions.jax_allocate import JaxAllocateAction
from volcano_tpu.cache import SchedulerCache
from volcano_tpu.conf import PluginOption, Tier
from volcano_tpu.framework import close_session, open_session
from volcano_tpu.ops.synthetic import generate_cluster_objects

kwargs = dict(n_tasks=50_000, n_nodes=10_000, gang_size=8,
              label_classes=8, taint_fraction=0.1)
nodes, pods, pgs, queues = generate_cluster_objects(**kwargs)

TIERS = [
    Tier(plugins=[PluginOption(name=n) for n in ("priority", "gang")]),
    Tier(plugins=[
        PluginOption(name=n)
        for n in ("drf", "predicates", "proportion", "nodeorder", "binpack")
    ]),
]


class _ListBinder:
    def __init__(self):
        self.binds = []

    def bind(self, task, hostname):
        self.binds.append((f"{task.namespace}/{task.name}", hostname))


def fresh():
    cache = SchedulerCache(binder=_ListBinder())
    for n in nodes:
        cache.add_node(n)
    for p in pods:
        cache.add_pod(p)
    for pg in pgs:
        cache.add_pod_group(pg)
    for q in queues:
        cache.add_queue(q)
    return cache


action = JaxAllocateAction()


def one(tag):
    cache = fresh()
    t0 = time.perf_counter()
    ssn = open_session(cache, TIERS, [])
    t1 = time.perf_counter()
    action.execute(ssn)
    t2 = time.perf_counter()
    close_session(ssn)
    c0 = gc.get_count()
    print(f"{tag}: open={t1-t0:.3f}s exec={t2-t1:.3f}s gc_count={c0} "
          f"collections={[s['collections'] for s in gc.get_stats()]}")


one("warmup")
one("warm-default-gc")
one("warm-default-gc2")

# simulate accumulated survivors: keep several big caches alive (what the
# earlier bench configs leave behind), then measure again
ballast = [fresh() for _ in range(2)]
one("ballast-default-gc")

gc.collect()
gc.freeze()
one("ballast-frozen")
one("ballast-frozen2")
del ballast
