"""Scratch: profile the headline-config device path, isolating
(1) pure kernel device time with pre-staged arrays,
(2) single-pass vs fused while_loop session,
(3) host packing cost, (4) full run_packed_pallas e2e.
"""
import time
import numpy as np
import jax
import jax.numpy as jnp

from volcano_tpu.ops.synthetic import generate_snapshot, BASELINE_CONFIGS
from volcano_tpu.ops.pallas_session import (
    prepare_pallas_arrays,
    schedule_pass_pallas,
    schedule_session_pallas_packed,
    run_packed_pallas,
)

snap = generate_snapshot(**BASELINE_CONFIGS["50k_pods_10k_nodes_gang_predicates"])


def t(fn, n=5, warmup=2):
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return np.median(ts) * 1e3


# Host packing cost
t0 = time.perf_counter()
arrays, T_act, NK = prepare_pallas_arrays(snap)
pack_ms = (time.perf_counter() - t0) * 1e3

# Build the packed taskrow_ext exactly like run_packed_pallas
T_rows = arrays["taskrow"].shape[0]
taskrow_ext = np.zeros((T_rows, arrays["taskrow"].shape[1] + 1), np.float32)
taskrow_ext[:, :-1] = arrays["taskrow"]
n_act = min(snap.n_tasks, T_act)
taskrow_ext[:n_act, -2] = 1.0
n_tj = min(T_act, snap.task_job.shape[0])
taskrow_ext[:n_tj, -1] = snap.task_job[:n_tj].astype(np.float32)
jobs2 = np.stack([
    snap.job_min_available.astype(np.int32),
    snap.job_ready_count.astype(np.int32),
])

# Pre-stage on device
d_ext = jax.device_put(jnp.asarray(taskrow_ext))
d_cf = jax.device_put(jnp.asarray(arrays["cf_u8"]))
d_nd = jax.device_put(jnp.asarray(arrays["nd"]))
d_tol = jax.device_put(jnp.asarray(arrays["tol"]))
d_jobs2 = jax.device_put(jnp.asarray(jobs2))
jax.block_until_ready([d_ext, d_cf, d_nd, d_tol, d_jobs2])

R = taskrow_ext.shape[1] - 3
taskrow1 = taskrow_ext[:, : R + 2].copy()
taskrow1[:n_act, R + 1] = 1.0
d_tr1 = jax.device_put(jnp.asarray(taskrow1))
jax.block_until_ready(d_tr1)

# 1. single pass, device-resident
single = t(lambda: jax.block_until_ready(
    schedule_pass_pallas(d_tr1, d_cf, d_nd, d_tol)))
# 2. fused session while_loop, device-resident
fused = t(lambda: jax.block_until_ready(
    schedule_session_pallas_packed(d_ext, d_cf, d_nd, d_tol, d_jobs2)))
# 2b. fused with gang_rounds=1
fused1 = t(lambda: jax.block_until_ready(
    schedule_session_pallas_packed(d_ext, d_cf, d_nd, d_tol, d_jobs2,
                                   gang_rounds=1)))
# 3. full e2e (pack + transfer + run + fetch)
e2e = t(lambda: run_packed_pallas(snap), n=3, warmup=1)

print(f"pack_ms           {pack_ms:8.2f}")
print(f"single_pass_ms    {single:8.2f}  (device-resident)")
print(f"fused_session_ms  {fused:8.2f}  (device-resident, gang_rounds=3)")
print(f"fused_rounds1_ms  {fused1:8.2f}  (device-resident, gang_rounds=1)")
print(f"full_e2e_ms       {e2e:8.2f}")
