"""Scratch: break the 200ms e2e into pack / transfer / kernel / fetch,
forcing a real device fetch (np.asarray) since the tunnel's
block_until_ready may not round-trip."""
import time
import numpy as np
import jax
import jax.numpy as jnp

from volcano_tpu.ops.synthetic import generate_snapshot, BASELINE_CONFIGS
from volcano_tpu.ops.pallas_session import (
    prepare_pallas_arrays,
    schedule_session_pallas_packed,
    run_packed_pallas,
)

snap = generate_snapshot(**BASELINE_CONFIGS["50k_pods_10k_nodes_gang_predicates"])


def t(fn, n=5, warmup=2):
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return np.median(ts) * 1e3, ts


arrays, T_act, NK = prepare_pallas_arrays(snap)
T_rows = arrays["taskrow"].shape[0]
taskrow_ext = np.zeros((T_rows, arrays["taskrow"].shape[1] + 1), np.float32)
taskrow_ext[:, :-1] = arrays["taskrow"]
n_act = min(snap.n_tasks, T_act)
taskrow_ext[:n_act, -2] = 1.0
n_tj = min(T_act, snap.task_job.shape[0])
taskrow_ext[:n_tj, -1] = snap.task_job[:n_tj].astype(np.float32)
jobs2 = np.stack([
    snap.job_min_available.astype(np.int32),
    snap.job_ready_count.astype(np.int32),
])

sizes = dict(
    taskrow_ext=taskrow_ext.nbytes,
    cf_u8=arrays["cf_u8"].nbytes,
    nd=arrays["nd"].nbytes,
    tol=arrays["tol"].nbytes,
    jobs2=jobs2.nbytes,
)
print("transfer bytes:", {k: f"{v/1e6:.2f}MB" for k, v in sizes.items()},
      "total", f"{sum(sizes.values())/1e6:.2f}MB")

# device-resident + REAL fetch of the [T] result
d_ext = jax.device_put(jnp.asarray(taskrow_ext))
d_cf = jax.device_put(jnp.asarray(arrays["cf_u8"]))
d_nd = jax.device_put(jnp.asarray(arrays["nd"]))
d_tol = jax.device_put(jnp.asarray(arrays["tol"]))
d_jobs2 = jax.device_put(jnp.asarray(jobs2))
_ = np.asarray(schedule_session_pallas_packed(d_ext, d_cf, d_nd, d_tol, d_jobs2))

m, _ = t(lambda: np.asarray(
    schedule_session_pallas_packed(d_ext, d_cf, d_nd, d_tol, d_jobs2)))
print(f"kernel+fetch (device-resident inputs): {m:8.2f} ms")

# transfer-only: put all five buffers fresh + tiny roundtrip to sync
def put_all():
    a = jnp.asarray(taskrow_ext)
    b = jnp.asarray(arrays["cf_u8"])
    c = jnp.asarray(arrays["nd"])
    d = jnp.asarray(arrays["tol"])
    e = jnp.asarray(jobs2)
    return np.asarray(a[0, :1])  # force sync

m, _ = t(put_all)
print(f"transfer all inputs + sync:           {m:8.2f} ms")

# single roundtrip: tiny put + tiny fetch
m, _ = t(lambda: np.asarray(jnp.asarray(np.zeros(8, np.float32)) + 1))
print(f"tiny RTT:                              {m:8.2f} ms")

# full e2e again for reference
m, ts = t(lambda: run_packed_pallas(snap), n=5, warmup=1)
print(f"run_packed_pallas e2e:                 {m:8.2f} ms  {['%.0f' % (x*1e3) for x in ts]}")
