"""Decompose the in-action KERNEL phase at the headline shape:
pack_session vs prepare/dedup vs device dispatch+fetch (warm)."""

from __future__ import annotations

import sys
import time

sys.path.insert(0, "bench")
sys.path.insert(0, ".")

from _profsetup import TIERS, make_cache_builder  # noqa: E402

import numpy as np  # noqa: E402

from volcano_tpu.actions.jax_allocate import (  # noqa: E402
    JaxAllocateAction,
    compute_task_order,
)
from volcano_tpu.framework import close_session, open_session  # noqa: E402
from volcano_tpu.ops.packing import pack_session  # noqa: E402

fresh = make_cache_builder()
action = JaxAllocateAction()

cache = fresh()
ssn = open_session(cache, TIERS, [])
ordered = compute_task_order(ssn)

jobs = {}
for t in ordered:
    job = ssn.jobs.get(t.job)
    if job is not None and job.uid not in jobs:
        jobs[job.uid] = job
nodes = [ssn.nodes[name] for name in sorted(ssn.nodes)]

for run in range(3):
    t0 = time.perf_counter()
    snap = pack_session(
        ordered, list(jobs.values()), nodes,
        enforce_pod_count="predicates" in ssn.predicate_fns,
    )
    pack_s = time.perf_counter() - t0

    from volcano_tpu.ops.pallas_session import make_session_dispatch

    t0 = time.perf_counter()
    dispatch, T_act = make_session_dispatch(snap)
    mk_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    out = np.asarray(dispatch())
    dev_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    proposals = {}
    for i, task in enumerate(ordered):
        if out[i] >= 0 and not snap.task_has_preferences[i]:
            proposals[task.uid] = nodes[out[i]].name
    prop_s = time.perf_counter() - t0
    print(f"run{run}: pack={pack_s:.3f}s make_dispatch={mk_s:.3f}s "
          f"device+fetch={dev_s:.3f}s proposals={prop_s:.3f}s")

import cProfile  # noqa: E402
import pstats  # noqa: E402

pr = cProfile.Profile()
pr.enable()
snap = pack_session(
    ordered, list(jobs.values()), nodes,
    enforce_pod_count="predicates" in ssn.predicate_fns,
)
pr.disable()
pstats.Stats(pr).sort_stats("cumulative").print_stats(20)
close_session(ssn)
