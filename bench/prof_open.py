"""cProfile of open_session at the headline shape (warm second open)."""

from __future__ import annotations

import cProfile
import pstats
import sys

sys.path.insert(0, "bench")
sys.path.insert(0, ".")

from _profsetup import TIERS, make_cache_builder  # noqa: E402

from volcano_tpu.framework import close_session, open_session  # noqa: E402

cache = make_cache_builder()()

ssn = open_session(cache, TIERS, [])
close_session(ssn)

pr = cProfile.Profile()
pr.enable()
ssn = open_session(cache, TIERS, [])
pr.disable()
close_session(ssn)
pstats.Stats(pr).sort_stats("cumulative").print_stats(30)
