"""Cold-vs-warm pack profile: pack_session from scratch vs
PackCache.pack after bind + status-only-revert churn (the warm-cycle
protocol bench.py measures), with a cProfile of the warm pack.

Usage: python bench/prof_pack_delta.py [n_tasks] [n_nodes]
Defaults to a sub-headline 10k×2k shape so the profile finishes fast;
pass 50000 10000 for the headline.
"""

from __future__ import annotations

import cProfile
import pstats
import sys
import time

sys.path.insert(0, "bench")
sys.path.insert(0, ".")

from _profsetup import (  # noqa: E402
    TIERS,
    capture_task_infos,
    make_cache_builder,
    revert_binds,
)

from volcano_tpu.actions.jax_allocate import (  # noqa: E402
    JaxAllocateAction,
    compute_task_order,
)
from volcano_tpu.framework import close_session, open_session  # noqa: E402
from volcano_tpu.ops.packing import pack_session  # noqa: E402
from volcano_tpu.utils.gcutil import gc_quiesce  # noqa: E402

n_tasks = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
n_nodes = int(sys.argv[2]) if len(sys.argv) > 2 else 2_000

cache = make_cache_builder(n_tasks=n_tasks, n_nodes=n_nodes)()
orig_tis = capture_task_infos(cache)
pc = cache.pack_cache


def session_inputs(ssn):
    ordered = compute_task_order(ssn)
    jobs = {}
    for t in ordered:
        j = ssn.jobs.get(t.job)
        if j is not None and j.uid not in jobs:
            jobs[j.uid] = j
    nodes = [ssn.nodes[n] for n in sorted(ssn.nodes)]
    return ordered, list(jobs.values()), nodes


# ---- cycle 1: cold pack + full action (binds land) ----
gc_quiesce()
ssn = open_session(cache, TIERS, [])
ordered, jobs, nodes = session_inputs(ssn)
t0 = time.perf_counter()
cold_snap = pack_session(ordered, jobs, nodes)
cold_s = time.perf_counter() - t0
t0 = time.perf_counter()
pc.pack(ordered, jobs, nodes, ssn.pack_epoch)
seed_s = time.perf_counter() - t0
print(f"cold pack_session: {cold_s * 1e3:8.2f} ms")
print(f"pack cache (cold): {seed_s * 1e3:8.2f} ms  {pc.last_stats}")
JaxAllocateAction().execute(ssn)
close_session(ssn)

# ---- churn: binds reverted via status-only events ----
revert_binds(cache, orig_tis)

# ---- cycle 2: warm delta pack ----
gc_quiesce()
ssn = open_session(cache, TIERS, [])
ordered, jobs, nodes = session_inputs(ssn)
pr = cProfile.Profile()
pr.enable()
t0 = time.perf_counter()
warm_snap = pc.pack(ordered, jobs, nodes, ssn.pack_epoch)
warm_s = time.perf_counter() - t0
pr.disable()
close_session(ssn)

print(f"warm delta pack:   {warm_s * 1e3:8.2f} ms  {pc.last_stats}")
print(f"cold/warm ratio:   {cold_s / warm_s:8.1f}x")
changed = sorted(warm_snap.delta.planes) if warm_snap.delta else "(wholesale)"
print(f"delta planes:      {changed}")
pstats.Stats(pr).sort_stats("cumulative").print_stats(15)
