"""Current cost of the GENERIC (non-bulk) apply path: drive the real
statement/heap/event machinery with kernel proposals but fast_apply
disabled, at a mid shape (20k x 2k), and cProfile the loop."""

from __future__ import annotations

import cProfile
import pstats
import sys
import time

sys.path.insert(0, "bench")
sys.path.insert(0, ".")

from _profsetup import TIERS, make_cache_builder  # noqa: E402

from volcano_tpu.actions import jax_allocate as ja  # noqa: E402
from volcano_tpu.framework import close_session, open_session  # noqa: E402

fresh = make_cache_builder(n_tasks=20_000, n_nodes=2_000)
action = ja.JaxAllocateAction()

# disable the bulk path so execute() runs the real loop
import volcano_tpu.actions.fast_apply as fa  # noqa: E402

fa_orig = fa.try_fast_apply
fa.try_fast_apply = lambda *a, **k: False

for run in range(2):
    cache = fresh()
    ssn = open_session(cache, TIERS, [])
    t0 = time.perf_counter()
    if run == 1:
        pr = cProfile.Profile()
        pr.enable()
    action.execute(ssn)
    if run == 1:
        pr.disable()
    t = time.perf_counter() - t0
    n = len(cache.binder.binds)
    print(f"run{run}: execute={t:.3f}s binds={n} -> {t/max(n,1)*1e6:.1f}us/task")
    close_session(ssn)

pstats.Stats(pr).sort_stats("tottime").print_stats(22)
fa.try_fast_apply = fa_orig
