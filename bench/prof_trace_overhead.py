"""Trace recording overhead: Scheduler.run_once latency with the cycle
recorder disabled vs enabled at event granularity, on the 10k-pod
synthetic config.  Acceptance gate (ISSUE 1): enabled-at-event-
granularity must stay under +5%.

Snapshot capture is measured separately (snapshot_every=1, the worst
case) — it's the sampled knob, not the always-on path.

ISSUE 12 extends the gate to the flight-recorder SPAN path
(volcano_tpu/obs): steady-state micro-cycle p99 with span recording on
at default sampling must stay under +5%, and tracing OFF must cost
zero (the null-span fast path) — both measured here.

ISSUE 19 extends it once more to TAIL mode (keep/drop decided at
trace completion): spans buffer in the exporter's pending pool
instead of dropping at the head coin, so the measured cost now
includes the per-span offer + per-kind duration bookkeeping.  Same
budget: under +5% over the spans-off baseline.

Emits one JSON line per mode plus a summary line with the delta, like
the other bench/prof_*.py scripts.
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time

sys.path.insert(0, "bench")
sys.path.insert(0, ".")

from _profsetup import TIERS, make_cache_builder  # noqa: E402

from volcano_tpu import trace  # noqa: E402
from volcano_tpu.conf import SchedulerConf  # noqa: E402
from volcano_tpu.scheduler.scheduler import Scheduler  # noqa: E402

ITERS = 5

fresh = make_cache_builder(n_tasks=10_000, n_nodes=1_000, gang_size=4)


class _FixedConfScheduler(Scheduler):
    """Pin the tier config to the profsetup tiers (no conf file I/O in
    the measured loop)."""

    def _load_conf(self):
        conf = SchedulerConf()
        conf.actions = ["jax-allocate"]
        conf.tiers = TIERS
        conf.configurations = []
        return conf


def cycle_ms(iters: int = ITERS) -> float:
    """Median run_once latency over fresh caches (each cycle binds the
    whole backlog, so the cache must be rebuilt per iteration)."""
    samples = []
    for _ in range(iters):
        cache = fresh()
        sched = _FixedConfScheduler(cache)
        t0 = time.perf_counter()
        sched.run_once()
        samples.append((time.perf_counter() - t0) * 1e3)
    samples.sort()
    return samples[len(samples) // 2]


# warm the jit caches so compile time doesn't pollute either mode
cycle_ms(iters=1)

trace.disable()
disabled_ms = cycle_ms()
print(json.dumps({"metric": "trace_cycle_latency", "mode": "disabled",
                  "value": round(disabled_ms, 3), "unit": "ms"}))

journal_dir = tempfile.mkdtemp(prefix="vtpu-trace-bench-")
try:
    trace.enable(journal_dir, snapshot_every=0)
    enabled_ms = cycle_ms()
    print(json.dumps({"metric": "trace_cycle_latency", "mode": "events",
                      "value": round(enabled_ms, 3), "unit": "ms"}))

    trace.enable(journal_dir, snapshot_every=1)
    snapshot_ms = cycle_ms()
    print(json.dumps({"metric": "trace_cycle_latency", "mode": "events+snapshot",
                      "value": round(snapshot_ms, 3), "unit": "ms"}))
finally:
    trace.disable()
    shutil.rmtree(journal_dir, ignore_errors=True)

overhead_pct = (enabled_ms - disabled_ms) / disabled_ms * 100.0
print(json.dumps({
    "metric": "trace_overhead",
    "value": round(overhead_pct, 2),
    "unit": "%",
    "disabled_ms": round(disabled_ms, 3),
    "events_ms": round(enabled_ms, 3),
    "events_snapshot_ms": round(snapshot_ms, 3),
    "budget_pct": 5.0,
    "within_budget": overhead_pct < 5.0,
    "tasks": 10_000,
    "nodes": 1_000,
}))

# ---- flight-recorder span path (ISSUE 12) ----

from volcano_tpu import obs  # noqa: E402
from volcano_tpu.client import APIServer  # noqa: E402

# spans off: MUST be the disabled baseline (null-span fast path)
spans_off_ms = cycle_ms()
print(json.dumps({"metric": "span_cycle_latency", "mode": "disabled",
                  "value": round(spans_off_ms, 3), "unit": "ms"}))

sink = APIServer()
exporter = obs.enable(sink, identity="prof-trace-overhead")
try:
    spans_on_ms = cycle_ms()
finally:
    obs.disable()
print(json.dumps({"metric": "span_cycle_latency", "mode": "spans",
                  "value": round(spans_on_ms, 3), "unit": "ms",
                  "spans_exported": exporter.exported,
                  "spans_dropped": exporter.dropped}))

span_overhead_pct = (spans_on_ms - spans_off_ms) / spans_off_ms * 100.0
span_off_delta_pct = (spans_off_ms - disabled_ms) / disabled_ms * 100.0
print(json.dumps({
    "metric": "span_overhead",
    "value": round(span_overhead_pct, 2),
    "unit": "%",
    "spans_off_ms": round(spans_off_ms, 3),
    "spans_on_ms": round(spans_on_ms, 3),
    "off_vs_baseline_pct": round(span_off_delta_pct, 2),
    "budget_pct": 5.0,
    "within_budget": span_overhead_pct < 5.0,
}))

# ---- tail-based retention path (ISSUE 19) ----

sink = APIServer()
exporter = obs.enable(sink, identity="prof-trace-overhead",
                      sample=0.01, tail=True)
try:
    tail_ms = cycle_ms()
    tail_stats = {
        "pending_traces": exporter.tail.pending_count(),
        "kept_traces": exporter.tail.kept_traces,
        "dropped_traces": exporter.tail.dropped_traces,
        "evicted_traces": exporter.tail.evicted_traces,
    }
finally:
    obs.disable()
print(json.dumps({"metric": "span_cycle_latency", "mode": "tail",
                  "value": round(tail_ms, 3), "unit": "ms",
                  **tail_stats}))

tail_overhead_pct = (tail_ms - spans_off_ms) / spans_off_ms * 100.0
print(json.dumps({
    "metric": "tail_overhead",
    "value": round(tail_overhead_pct, 2),
    "unit": "%",
    "spans_off_ms": round(spans_off_ms, 3),
    "tail_ms": round(tail_ms, 3),
    "budget_pct": 5.0,
    "within_budget": tail_overhead_pct < 5.0,
}))
