"""Test object builders.

Mirrors the reference's test fixtures (pkg/scheduler/util/test_utils.go:
BuildPod/BuildNode/BuildResourceList) so scheduler tests read the same way.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional

from volcano_tpu.apis import core, scheduling

_uid = itertools.count(1)
_ts = itertools.count(1)


def build_node(
    name: str,
    alloc: Dict[str, object],
    labels: Optional[Dict[str, str]] = None,
    taints: Optional[List[core.Taint]] = None,
    capacity: Optional[Dict[str, object]] = None,
    unschedulable: bool = False,
) -> core.Node:
    alloc = dict(alloc)
    alloc.setdefault("pods", 110)
    return core.Node(
        metadata=core.ObjectMeta(
            name=name,
            namespace="",
            uid=f"node-{next(_uid)}",
            labels=labels or {},
            creation_timestamp=float(next(_ts)),
        ),
        spec=core.NodeSpec(taints=taints or [], unschedulable=unschedulable),
        status=core.NodeStatus(allocatable=alloc, capacity=dict(capacity or alloc)),
    )


def build_pod(
    namespace: str,
    name: str,
    node_name: str,
    req: Dict[str, object],
    phase: str = "Pending",
    group: str = "",
    labels: Optional[Dict[str, str]] = None,
    selector: Optional[Dict[str, str]] = None,
    priority: Optional[int] = None,
    tolerations: Optional[List[core.Toleration]] = None,
    affinity: Optional[Dict[str, object]] = None,
    ports: Optional[List[int]] = None,
) -> core.Pod:
    annotations = {}
    if group:
        annotations[scheduling.GROUP_NAME_ANNOTATION_KEY] = group
    container = core.Container(
        name="main",
        resources={"requests": dict(req)} if req else {},
        ports=[core.ContainerPort(container_port=p, host_port=p) for p in ports or []],
    )
    return core.Pod(
        metadata=core.ObjectMeta(
            name=name,
            namespace=namespace,
            uid=f"pod-{next(_uid)}",
            labels=labels or {},
            annotations=annotations,
            creation_timestamp=float(next(_ts)),
        ),
        spec=core.PodSpec(
            containers=[container],
            node_name=node_name,
            node_selector=selector or {},
            tolerations=tolerations or [],
            affinity=affinity or {},
            priority=priority,
        ),
        status=core.PodStatus(phase=phase),
    )


def build_pod_group(
    namespace: str,
    name: str,
    min_member: int,
    queue: str = "default",
    phase: str = scheduling.POD_GROUP_INQUEUE,
    min_resources: Optional[Dict[str, object]] = None,
    priority_class_name: str = "",
) -> scheduling.PodGroup:
    return scheduling.PodGroup(
        metadata=core.ObjectMeta(
            name=name,
            namespace=namespace,
            uid=f"pg-{next(_uid)}",
            creation_timestamp=float(next(_ts)),
        ),
        spec=scheduling.PodGroupSpec(
            min_member=min_member,
            queue=queue,
            min_resources=min_resources or {},
            priority_class_name=priority_class_name,
        ),
        status=scheduling.PodGroupStatus(phase=phase),
    )


def build_priority_class(name: str, value: int) -> core.PriorityClass:
    return core.PriorityClass(
        metadata=core.ObjectMeta(name=name, uid=f"pc-{next(_uid)}"), value=value
    )


def build_queue(name: str, weight: int = 1, capability: Optional[Dict] = None) -> scheduling.Queue:
    return scheduling.Queue(
        metadata=core.ObjectMeta(
            name=name, namespace="", uid=f"q-{next(_uid)}", creation_timestamp=float(next(_ts))
        ),
        spec=scheduling.QueueSpec(weight=weight, capability=capability or {}),
    )
