"""Test env: force JAX onto CPU with 8 virtual devices so multi-chip
sharding paths are exercised without TPU hardware.

The axon TPU plugin (when present) registers itself via sitecustomize and
overrides JAX_PLATFORMS, so the env var alone is not enough — the config
update after import is what actually pins the CPU backend."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
