"""Test env: force JAX onto CPU with 8 virtual devices so multi-chip
sharding paths are exercised without TPU hardware.

The axon TPU plugin (when present) registers itself via sitecustomize and
overrides JAX_PLATFORMS, so the env var alone is not enough — the config
update after import is what actually pins the CPU backend.

Suite-wide guards live here too:

* **Thread-leak guard** — every test asserts it left no new
  *non-daemon* threads behind (a small named allowlist excepted).  An
  abandoned bind worker or watchdog thread fails the test that leaked
  it, loudly and with the thread names, instead of wedging the exit of
  some unrelated later test.
* **Fd/socket-leak guard** — the thread guard's twin, one layer down:
  a ``/proc/self/fd`` snapshot diff asserts no new sockets or
  real-file descriptors (bus clients, WAL handles) survive a test,
  with a target-pattern allowlist for interpreter/test-infra plumbing.
  Disarmed under ``VTPU_RACE`` (the race detector pins tracked
  instances alive, so their fds outlive tests by design).
* **Lock-order verifier** (opt-in, ``VTPU_LOCK_ORDER=1``) — wraps every
  lock volcano_tpu creates in the instrumented proxy from
  ``volcano_tpu.analysis.lock_order``, records the cross-thread
  acquisition graph, fails the leaking test on any ABBA inversion, and
  fails the session if the final graph has a cycle.  CI runs the chaos
  and commit-plane suites under it; ``VTPU_LOCK_ORDER_REPORT=<path>``
  additionally dumps the acquisition graph as JSON.
* **Happens-before race detector** (opt-in, ``VTPU_RACE=1``) — the
  enforcement layer over the ``# guarded-by:`` declarations: installs
  before any volcano_tpu import (lock factories + thread/queue/event
  patches + tracking descriptors on every declared attribute), fails
  the test that recorded a fresh race and the session on any race;
  ``VTPU_RACE_REPORT=<path>`` dumps the full report.  CI runs the
  chaos, commit-plane, federation and bus-HA suites under it.
"""

import json
import os
import threading
import time

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# must precede any volcano_tpu import so every lock construction runs
# through the patched factories
_LOCK_ORDER = os.environ.get("VTPU_LOCK_ORDER") == "1"
if _LOCK_ORDER:
    from volcano_tpu.analysis import lock_order

    lock_order.install()

# the happens-before race detector rides the same proxies (it installs
# them itself when the lock-order verifier is off) and additionally
# wraps every `# guarded-by:`-declared attribute in the tree — so the
# install AND the class instrumentation must both precede the system
# under test's imports/instance construction
_RACE = os.environ.get("VTPU_RACE") == "1"
if _RACE:
    from volcano_tpu.analysis import race

    race.install()
    _RACE_INSTRUMENTATION = race.instrument_package()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# ---- thread-leak guard ----

#: non-daemon threads these names (prefixes) are allowed to outlive a
#: test: pytest/session infrastructure only.  Project threads are all
#: daemon=True by convention — anything non-daemon left running is a
#: shutdown bug (the exact class this guard exists for: abandoned
#: watchdog / bind-worker threads used to wedge interpreter exit).
_LEAK_ALLOWLIST = (
    "MainThread",
    "pytest_timeout",      # pytest-timeout watcher, when installed
    "ThreadPoolExecutor",  # joined at interpreter exit by concurrent.futures
)
_LEAK_GRACE_S = 2.0


def _leaked_nondaemon(before):
    return [
        t for t in threading.enumerate()
        if t not in before
        and t.is_alive()
        and not t.daemon
        and not t.name.startswith(_LEAK_ALLOWLIST)
    ]


@pytest.fixture(autouse=True)
def _identity_label_guard():
    """Daemons started inside a test stamp process-global identity
    labels (metrics.set_identity) that would re-label every series a
    LATER test renders — clear just the identity (never the counters,
    which tests manage themselves) so cross-test isolation matches the
    pre-identity-label world."""
    yield
    from volcano_tpu.metrics import metrics as _metrics

    _metrics.registry.set_identity()


@pytest.fixture(autouse=True)
def _thread_leak_guard():
    before = set(threading.enumerate())
    yield
    leaked = _leaked_nondaemon(before)
    if leaked:
        # teardown finalizers may still be joining — give them a moment
        deadline = time.monotonic() + _LEAK_GRACE_S
        while leaked and time.monotonic() < deadline:
            time.sleep(0.05)
            leaked = _leaked_nondaemon(before)
    assert not leaked, (
        "test leaked non-daemon thread(s): "
        + ", ".join(sorted(t.name for t in leaked))
        + " — stop/join them in the test (or daemonize them if they are "
        "genuinely fire-and-forget)"
    )


# ---- fd/socket-leak guard (the thread guard's twin) ----

#: fd targets these substrings match may survive a test: interpreter /
#: test-infra machinery only.  Project sockets and files (bus
#: connections, WAL handles, journals) must be closed by the test that
#: opened them — an unclosed WAL handle or bus socket is the shutdown
#: bug class the thread guard catches, one layer down.
_FD_ALLOWLIST = (
    "/dev/",            # urandom, null, tty — interpreter plumbing
    "/proc/",
    "/sys/",
    "pipe:",            # pytest capture + subprocess plumbing
    "anon_inode:",      # epoll/eventfd (asyncio, JAX runtime)
    "/memfd",
    "(deleted)",        # unlinked tempfiles (pytest capsys machinery)
    "/usr/",            # stdlib/site-packages handles (zipimport etc.)
    ".local/lib",       # pip --user site-packages, same class
)


def _fd_table():
    """fd → readlink target, or None where /proc is unavailable (the
    guard silently disarms off-Linux)."""
    try:
        entries = os.listdir("/proc/self/fd")
    except OSError:
        return None
    table = {}
    for e in entries:
        try:
            table[int(e)] = os.readlink(f"/proc/self/fd/{e}")
        except (OSError, ValueError):
            continue  # closed between listdir and readlink
    return table


def _leaked_fds(before):
    now = _fd_table()
    if now is None:
        return []
    return sorted(
        (fd, target) for fd, target in now.items()
        if before.get(fd) != target
        and (target.startswith("socket:") or target.startswith("/"))
        and not any(pat in target for pat in _FD_ALLOWLIST)
    )


@pytest.fixture(autouse=True)
def _fd_leak_guard():
    if _RACE:
        # the race detector pins every tracked instance alive (shadow
        # state is keyed by id(); releasing an object would let a
        # recycled id inherit dead epochs), so sockets those instances
        # hold outlive their tests by design — the leak signal is
        # meaningless under VTPU_RACE.  The plain tier-1 job keeps the
        # guard armed.
        yield
        return
    before = _fd_table()
    if before is None:
        yield
        return
    yield
    leaked = _leaked_fds(before)
    if leaked:
        # a client abandoned inside an exception traceback sits in a
        # reference cycle — its socket closes only when the cycle
        # collector runs, so force that before calling it a leak.
        # INSIDE the grace loop: a daemon thread exiting during the
        # wait can drop the cycle's last external reference, so one
        # up-front collect would miss it
        import gc

        # daemon teardown may still be closing — same grace as threads
        deadline = time.monotonic() + _LEAK_GRACE_S
        while leaked and time.monotonic() < deadline:
            gc.collect()
            leaked = _leaked_fds(before)
            if leaked:
                time.sleep(0.05)
    assert not leaked, (
        "test leaked file descriptor(s): "
        + ", ".join(f"fd {fd} -> {t}" for fd, t in leaked)
        + " — close them in the test (bus clients, WAL stores and "
        "exporters all have close()/stop())"
    )


# ---- lock-order verifier + race detector wiring ----

if _LOCK_ORDER:

    @pytest.fixture(autouse=True)
    def _lock_order_guard():
        """Fail the test that CLOSED a lock-order cycle — per-test
        attribution beats one opaque session-end failure."""
        n_before = len(lock_order.violations())
        yield
        fresh = lock_order.violations()[n_before:]
        assert not fresh, (
            "lock-order inversion(s) recorded during this test:\n"
            + "\n".join(v.render() for v in fresh)
        )


if _RACE:

    @pytest.fixture(autouse=True)
    def _race_guard():
        """Fail the test whose schedule exposed a data race — the
        lock-order guard's per-test attribution, for the HB engine."""
        n_before = len(race.races())
        yield
        fresh = race.races()[n_before:]
        assert not fresh, (
            "happens-before race(s) recorded during this test:\n"
            + "\n".join(r.render() for r in fresh)
        )


if _LOCK_ORDER or _RACE:

    def pytest_sessionfinish(session, exitstatus):
        failed = False
        if _LOCK_ORDER:
            report = lock_order.report()
            path = os.environ.get("VTPU_LOCK_ORDER_REPORT")
            if path:
                with open(path, "w", encoding="utf-8") as f:
                    json.dump(report, f, indent=2)
                    f.write("\n")
            failed = failed or bool(report["violations"])
        if _RACE:
            path = os.environ.get("VTPU_RACE_REPORT")
            if path:
                race.dump_report(
                    path, extra={"instrumentation": _RACE_INSTRUMENTATION}
                )
            failed = failed or bool(race.races())
        if failed:
            session.exitstatus = 3

    def pytest_terminal_summary(terminalreporter):
        if _LOCK_ORDER:
            report = lock_order.report()
            terminalreporter.write_line(
                f"lock-order verifier: {report['locks']} instrumented "
                f"locks, {len(report['edges'])} acquisition edges, "
                f"{len(report['violations'])} violation(s)"
            )
            for v in report["violations"]:
                terminalreporter.write_line(v)
        if _RACE:
            rep = race.report()
            terminalreporter.write_line(
                f"race detector: {rep['accesses']} tracked accesses over "
                f"{rep['tracked_vars']} guarded variables "
                f"({_RACE_INSTRUMENTATION['instrumented_attrs']} "
                f"instrumented attrs, "
                f"{len(_RACE_INSTRUMENTATION['waived'])} waived), "
                f"{len(rep['races'])} race(s)"
            )
            for r in race.races():
                terminalreporter.write_line(r.render())
