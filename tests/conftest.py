"""Test env: force JAX onto CPU with 8 virtual devices so multi-chip
sharding paths are exercised without TPU hardware.

The axon TPU plugin (when present) registers itself via sitecustomize and
overrides JAX_PLATFORMS, so the env var alone is not enough — the config
update after import is what actually pins the CPU backend.

Two suite-wide guards live here too:

* **Thread-leak guard** — every test asserts it left no new
  *non-daemon* threads behind (a small named allowlist excepted).  An
  abandoned bind worker or watchdog thread fails the test that leaked
  it, loudly and with the thread names, instead of wedging the exit of
  some unrelated later test.
* **Lock-order verifier** (opt-in, ``VTPU_LOCK_ORDER=1``) — wraps every
  lock volcano_tpu creates in the instrumented proxy from
  ``volcano_tpu.analysis.lock_order``, records the cross-thread
  acquisition graph, fails the leaking test on any ABBA inversion, and
  fails the session if the final graph has a cycle.  CI runs the chaos
  and commit-plane suites under it; ``VTPU_LOCK_ORDER_REPORT=<path>``
  additionally dumps the acquisition graph as JSON.
"""

import json
import os
import threading
import time

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# must precede any volcano_tpu import so every lock construction runs
# through the patched factories
_LOCK_ORDER = os.environ.get("VTPU_LOCK_ORDER") == "1"
if _LOCK_ORDER:
    from volcano_tpu.analysis import lock_order

    lock_order.install()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# ---- thread-leak guard ----

#: non-daemon threads these names (prefixes) are allowed to outlive a
#: test: pytest/session infrastructure only.  Project threads are all
#: daemon=True by convention — anything non-daemon left running is a
#: shutdown bug (the exact class this guard exists for: abandoned
#: watchdog / bind-worker threads used to wedge interpreter exit).
_LEAK_ALLOWLIST = (
    "MainThread",
    "pytest_timeout",      # pytest-timeout watcher, when installed
    "ThreadPoolExecutor",  # joined at interpreter exit by concurrent.futures
)
_LEAK_GRACE_S = 2.0


def _leaked_nondaemon(before):
    return [
        t for t in threading.enumerate()
        if t not in before
        and t.is_alive()
        and not t.daemon
        and not t.name.startswith(_LEAK_ALLOWLIST)
    ]


@pytest.fixture(autouse=True)
def _identity_label_guard():
    """Daemons started inside a test stamp process-global identity
    labels (metrics.set_identity) that would re-label every series a
    LATER test renders — clear just the identity (never the counters,
    which tests manage themselves) so cross-test isolation matches the
    pre-identity-label world."""
    yield
    from volcano_tpu.metrics import metrics as _metrics

    _metrics.registry.set_identity()


@pytest.fixture(autouse=True)
def _thread_leak_guard():
    before = set(threading.enumerate())
    yield
    leaked = _leaked_nondaemon(before)
    if leaked:
        # teardown finalizers may still be joining — give them a moment
        deadline = time.monotonic() + _LEAK_GRACE_S
        while leaked and time.monotonic() < deadline:
            time.sleep(0.05)
            leaked = _leaked_nondaemon(before)
    assert not leaked, (
        "test leaked non-daemon thread(s): "
        + ", ".join(sorted(t.name for t in leaked))
        + " — stop/join them in the test (or daemonize them if they are "
        "genuinely fire-and-forget)"
    )


# ---- lock-order verifier wiring ----

if _LOCK_ORDER:

    @pytest.fixture(autouse=True)
    def _lock_order_guard():
        """Fail the test that CLOSED a lock-order cycle — per-test
        attribution beats one opaque session-end failure."""
        n_before = len(lock_order.violations())
        yield
        fresh = lock_order.violations()[n_before:]
        assert not fresh, (
            "lock-order inversion(s) recorded during this test:\n"
            + "\n".join(v.render() for v in fresh)
        )

    def pytest_sessionfinish(session, exitstatus):
        report = lock_order.report()
        path = os.environ.get("VTPU_LOCK_ORDER_REPORT")
        if path:
            with open(path, "w", encoding="utf-8") as f:
                json.dump(report, f, indent=2)
                f.write("\n")
        if report["violations"]:
            session.exitstatus = 3

    def pytest_terminal_summary(terminalreporter):
        report = lock_order.report()
        terminalreporter.write_line(
            f"lock-order verifier: {report['locks']} instrumented locks, "
            f"{len(report['edges'])} acquisition edges, "
            f"{len(report['violations'])} violation(s)"
        )
        for v in report["violations"]:
            terminalreporter.write_line(v)
