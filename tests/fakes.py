"""Fake side-effect executors for scheduler tests.

Mirrors pkg/scheduler/util/test_utils.go FakeBinder/FakeEvictor/
FakeStatusUpdater: binds/evictions land in in-memory lists the tests
assert on (the Go versions push to channels).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from volcano_tpu.api import TaskInfo
from volcano_tpu.apis import scheduling
from volcano_tpu.cache.interface import Binder, Evictor, StatusUpdater


class FakeBinder(Binder):
    """test_utils.go:94-110."""

    def __init__(self):
        self.lock = threading.Lock()
        self.binds: Dict[str, str] = {}

    def bind(self, task: TaskInfo, hostname: str) -> None:
        with self.lock:
            self.binds[f"{task.namespace}/{task.name}"] = hostname

    @property
    def length(self) -> int:
        return len(self.binds)


class FakeEvictor(Evictor):
    """test_utils.go:117-140."""

    def __init__(self):
        self.lock = threading.Lock()
        self.evicts: List[str] = []

    def evict(self, task: TaskInfo) -> None:
        with self.lock:
            self.evicts.append(f"{task.namespace}/{task.name}")


class FakeStatusUpdater(StatusUpdater):
    """test_utils.go:147-159 — does nothing, like the reference fake."""

    def __init__(self):
        self.pod_conditions: List[tuple] = []
        self.pod_groups: List[scheduling.PodGroup] = []

    def update_pod_condition(self, task: TaskInfo, reason: str, message: str) -> None:
        self.pod_conditions.append((f"{task.namespace}/{task.name}", reason, message))

    def update_pod_group(self, pg: scheduling.PodGroup) -> Optional[scheduling.PodGroup]:
        self.pod_groups.append(pg)
        return pg
