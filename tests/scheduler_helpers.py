"""Shared scaffolding for scheduler action/plugin tests.

Mirrors the reference's unit-test pattern (allocate_test.go:155-222):
build a real SchedulerCache without informers by calling event handlers
directly, inject fakes for side effects, open a real session with explicit
tiers, run the real action, assert on the binds the fake binder received.
"""

from __future__ import annotations

from typing import List

import volcano_tpu.actions  # noqa: F401 — registers actions
import volcano_tpu.plugins  # noqa: F401 — registers plugin builders
from volcano_tpu.cache import SchedulerCache
from volcano_tpu.conf import PluginOption, Tier
from volcano_tpu.framework import close_session, open_session

from tests.fakes import FakeBinder, FakeEvictor, FakeStatusUpdater


def make_cache(
    nodes=(),
    pods=(),
    pod_groups=(),
    queues=(),
    priority_classes=(),
) -> SchedulerCache:
    cache = SchedulerCache(
        binder=FakeBinder(),
        evictor=FakeEvictor(),
        status_updater=FakeStatusUpdater(),
    )
    for node in nodes:
        cache.add_node(node)
    for pod in pods:
        cache.add_pod(pod)
    for pg in pod_groups:
        cache.add_pod_group(pg)
    for q in queues:
        cache.add_queue(q)
    for pc in priority_classes:
        cache.add_priority_class(pc)
    return cache


def tiers(*plugin_name_groups: List[str]) -> List[Tier]:
    return [
        Tier(plugins=[PluginOption(name=n) for n in group])
        for group in plugin_name_groups
    ]


def run_actions(cache: SchedulerCache, actions, tier_conf, configurations=None):
    """Open a session, run the actions, close it; return the session."""
    ssn = open_session(cache, tier_conf, configurations or [])
    try:
        for action in actions:
            action.execute(ssn)
    finally:
        close_session(ssn)
    return ssn
