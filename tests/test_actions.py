"""Behavioral tests for the preempt / reclaim / enqueue / backfill
actions — table cases mirroring the reference suites
(pkg/scheduler/actions/preempt/preempt_test.go,
reclaim/reclaim_test.go, enqueue/enqueue_test.go) on the
fake-binder/evictor harness."""

from __future__ import annotations

from volcano_tpu.actions.allocate import AllocateAction
from volcano_tpu.actions.backfill import BackfillAction
from volcano_tpu.actions.enqueue import EnqueueAction
from volcano_tpu.actions.preempt import PreemptAction
from volcano_tpu.actions.reclaim import ReclaimAction
from volcano_tpu.apis import scheduling
from volcano_tpu.conf import Configuration
from volcano_tpu.framework.arguments import Arguments

from tests.builders import build_node, build_pod, build_pod_group, build_queue
from tests.scheduler_helpers import make_cache, run_actions, tiers


# ---- preempt (preempt_test.go cases) ----


def _preempt_tiers():
    return tiers(["conformance", "gang"])


def test_preempt_no_eviction_when_idle_suffices():
    """preempt_test.go 'do not preempt if there are enough idle
    resources' — gang also vetoes same-job victims below minAvailable."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "10", "memory": "10G"})],
        pods=[
            build_pod("c1", "preemptee1", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "preemptee2", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "preemptor1", "", {"cpu": "1", "memory": "1G"},
                      group="pg1"),
        ],
        pod_groups=[build_pod_group("c1", "pg1", 3, queue="q1")],
        queues=[build_queue("q1", weight=1)],
    )
    run_actions(cache, [PreemptAction()], _preempt_tiers())
    assert cache.evictor.evicts == []


def test_preempt_no_eviction_when_jobs_pipelined():
    """preempt_test.go 'do not preempt if job is pipelined'."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "3", "memory": "3G"})],
        pods=[
            build_pod("c1", "preemptee1", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "preemptee2", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "preemptee3", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg2"),
            build_pod("c1", "preemptor2", "", {"cpu": "1", "memory": "1G"},
                      group="pg2"),
        ],
        pod_groups=[
            build_pod_group("c1", "pg1", 1, queue="q1"),
            build_pod_group("c1", "pg2", 1, queue="q1"),
        ],
        queues=[build_queue("q1", weight=1)],
    )
    run_actions(cache, [PreemptAction()], _preempt_tiers())
    assert cache.evictor.evicts == []


def test_preempt_one_task_of_other_job():
    """preempt_test.go 'preempt one task of different job to fit both
    jobs on one node'."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "2", "memory": "2G"})],
        pods=[
            build_pod("c1", "preemptee1", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "preemptee2", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "preemptor1", "", {"cpu": "1", "memory": "1G"},
                      group="pg2"),
            build_pod("c1", "preemptor2", "", {"cpu": "1", "memory": "1G"},
                      group="pg2"),
        ],
        pod_groups=[
            build_pod_group("c1", "pg1", 1, queue="q1"),
            build_pod_group("c1", "pg2", 1, queue="q1"),
        ],
        queues=[build_queue("q1", weight=1)],
    )
    run_actions(cache, [PreemptAction()], _preempt_tiers())
    assert len(cache.evictor.evicts) == 1
    assert cache.evictor.evicts[0].startswith("c1/preemptee")


def test_preempt_enough_victims_for_large_task():
    """preempt_test.go 'preempt enough tasks to fit large task of
    different job' — 3 idle + 2 evictions cover the 5-cpu preemptor."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "6", "memory": "6G"})],
        pods=[
            build_pod("c1", "preemptee1", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "preemptee2", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "preemptee3", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "preemptor1", "", {"cpu": "5", "memory": "5G"},
                      group="pg2"),
        ],
        pod_groups=[
            build_pod_group("c1", "pg1", 1, queue="q1"),
            build_pod_group("c1", "pg2", 1, queue="q1"),
        ],
        queues=[build_queue("q1", weight=1)],
    )
    run_actions(cache, [PreemptAction()], _preempt_tiers())
    assert len(cache.evictor.evicts) == 2


# ---- reclaim (reclaim_test.go case + guards) ----


def _reclaim_tiers():
    return tiers(["conformance", "gang"])


def test_reclaim_from_overusing_queue():
    """reclaim_test.go 'Two Queue with one Queue overusing resource,
    should reclaim'."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "3", "memory": "3Gi"})],
        pods=[
            build_pod("c1", "preemptee1", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "preemptee2", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "preemptee3", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "preemptor1", "", {"cpu": "1", "memory": "1G"},
                      group="pg2"),
        ],
        pod_groups=[
            build_pod_group("c1", "pg1", 0, queue="q1"),
            build_pod_group("c1", "pg2", 0, queue="q2"),
        ],
        queues=[build_queue("q1", weight=1), build_queue("q2", weight=1)],
    )
    run_actions(cache, [ReclaimAction()], _reclaim_tiers())
    assert len(cache.evictor.evicts) == 1


def test_reclaim_skips_same_queue_victims():
    """No cross-queue victims → nothing reclaimed (reclaim only evicts
    tasks whose job sits in a different queue)."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "3", "memory": "3Gi"})],
        pods=[
            build_pod("c1", "preemptee1", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "preemptee2", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "preemptee3", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "preemptor1", "", {"cpu": "1", "memory": "1G"},
                      group="pg2"),
        ],
        pod_groups=[
            build_pod_group("c1", "pg1", 0, queue="q1"),
            build_pod_group("c1", "pg2", 0, queue="q1"),
        ],
        queues=[build_queue("q1", weight=1)],
    )
    run_actions(cache, [ReclaimAction()], _reclaim_tiers())
    assert cache.evictor.evicts == []


def test_reclaim_requires_enough_victim_resources():
    """Victim total below the reclaimer's request → no eviction
    (reclaim.go:155-163 validation)."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "2", "memory": "2Gi"})],
        pods=[
            build_pod("c1", "small", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "big", "", {"cpu": "2", "memory": "2G"},
                      group="pg2"),
        ],
        pod_groups=[
            build_pod_group("c1", "pg1", 0, queue="q1"),
            build_pod_group("c1", "pg2", 0, queue="q2"),
        ],
        queues=[build_queue("q1", weight=1), build_queue("q2", weight=1)],
    )
    run_actions(cache, [ReclaimAction()], _reclaim_tiers())
    assert cache.evictor.evicts == []


# ---- enqueue ----


def _last_pg_phase(cache):
    """Phase the session wrote back through the status updater, falling
    back to the cache's stored pod group when no write happened."""
    if cache.status_updater.pod_groups:
        return cache.status_updater.pod_groups[-1].status.phase
    return next(iter(cache.snapshot().jobs.values())).pod_group.status.phase


def _pending_group(ns, name, queue, min_resources):
    return build_pod_group(
        ns, name, 1, queue=queue,
        phase=scheduling.POD_GROUP_PENDING,
        min_resources=min_resources,
    )


def test_enqueue_flips_pending_group_within_headroom():
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "4", "memory": "8G"})],
        pods=[build_pod("c1", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg1")],
        pod_groups=[_pending_group("c1", "pg1", "q1", {"cpu": "1", "memory": "1G"})],
        queues=[build_queue("q1", weight=1)],
    )
    run_actions(cache, [EnqueueAction()], tiers(["proportion"]))
    assert _last_pg_phase(cache) == scheduling.POD_GROUP_INQUEUE


def test_enqueue_keeps_pending_beyond_headroom():
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "2", "memory": "2G"})],
        pods=[build_pod("c1", "p1", "", {"cpu": "8", "memory": "8G"}, group="pg1")],
        pod_groups=[_pending_group("c1", "pg1", "q1", {"cpu": "8", "memory": "8G"})],
        queues=[build_queue("q1", weight=1)],
    )
    run_actions(cache, [EnqueueAction()], tiers(["proportion"]))
    assert _last_pg_phase(cache) == scheduling.POD_GROUP_PENDING


def test_enqueue_overcommit_factor_argument():
    """enqueue_test.go: the per-action overcommit-factor configuration
    widens the headroom gate."""
    def mk():
        return make_cache(
            nodes=[build_node("n1", {"cpu": "2", "memory": "2G"})],
            pods=[build_pod("c1", "p1", "", {"cpu": "3", "memory": "3G"}, group="pg1")],
            pod_groups=[_pending_group("c1", "pg1", "q1", {"cpu": "3", "memory": "3G"})],
            queues=[build_queue("q1", weight=1)],
        )

    cache = mk()
    run_actions(cache, [EnqueueAction()], tiers(["proportion"]))
    assert _last_pg_phase(cache) == scheduling.POD_GROUP_PENDING

    wide = [Configuration(name="enqueue",
                          arguments=Arguments({"overcommit-factor": "2.0"}))]
    cache = mk()
    run_actions(cache, [EnqueueAction()], tiers(["proportion"]), wide)
    assert _last_pg_phase(cache) == scheduling.POD_GROUP_INQUEUE


# ---- backfill ----


def test_backfill_places_besteffort_on_full_node():
    """Best-effort (empty resreq) tasks land even when the node has no
    idle resources (backfill.go:61-75)."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "1", "memory": "1G"})],
        pods=[
            build_pod("c1", "filler", "n1", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1"),
            build_pod("c1", "be1", "", {}, group="pg2"),
        ],
        pod_groups=[
            build_pod_group("c1", "pg1", 0, queue="q1"),
            build_pod_group("c1", "pg2", 0, queue="q1"),
        ],
        queues=[build_queue("q1", weight=1)],
    )
    run_actions(cache, [BackfillAction()], tiers(["gang"]))
    assert cache.binder.binds == {"c1/be1": "n1"}


def test_backfill_ignores_resourced_tasks():
    """Tasks with a real request are allocate's business, not
    backfill's."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "4", "memory": "4G"})],
        pods=[build_pod("c1", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg1")],
        pod_groups=[build_pod_group("c1", "pg1", 0, queue="q1")],
        queues=[build_queue("q1", weight=1)],
    )
    run_actions(cache, [BackfillAction()], tiers(["gang"]))
    assert cache.binder.binds == {}


def test_backfill_after_allocate_fills_leftovers():
    """allocate then backfill: the resourced pod binds via allocate, the
    best-effort pod via backfill."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "1", "memory": "1G"})],
        pods=[
            build_pod("c1", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
            build_pod("c1", "be1", "", {}, group="pg1"),
        ],
        pod_groups=[build_pod_group("c1", "pg1", 0, queue="q1")],
        queues=[build_queue("q1", weight=1)],
    )
    run_actions(
        cache,
        [AllocateAction(), BackfillAction()],
        tiers(["gang"], ["drf", "proportion"]),
    )
    assert cache.binder.binds == {"c1/p1": "n1", "c1/be1": "n1"}
