"""Admission webhook + CLI tests (reference: admit_job_test.go,
mutate_job_test.go, pkg/cli tests)."""

from __future__ import annotations

import io

import pytest

from volcano_tpu.admission import mutate_job, register_webhooks, validate_job
from volcano_tpu.admission.pods import validate_pod
from volcano_tpu.apis import batch, core, scheduling
from volcano_tpu.cli import main as vtctl
from volcano_tpu.client import AdmissionError, APIServer, VolcanoClient


def base_job(**spec_kw):
    defaults = dict(
        min_available=1,
        tasks=[
            batch.TaskSpec(
                name="worker",
                replicas=1,
                template=core.PodTemplateSpec(spec=core.PodSpec(containers=[core.Container(image="busybox")])),
            )
        ],
    )
    defaults.update(spec_kw)
    return batch.Job(
        metadata=core.ObjectMeta(name="j", namespace="ns"),
        spec=batch.JobSpec(**defaults),
    )


class TestValidateJob:
    def test_valid_job_passes(self):
        validate_job(base_job())

    def test_min_available_zero_denied(self):
        with pytest.raises(AdmissionError, match="minAvailable"):
            validate_job(base_job(min_available=0))

    def test_negative_max_retry_denied(self):
        with pytest.raises(AdmissionError, match="maxRetry"):
            validate_job(base_job(max_retry=-1))

    def test_no_tasks_denied(self):
        with pytest.raises(AdmissionError, match="No task specified"):
            validate_job(base_job(tasks=[]))

    def test_duplicate_task_names_denied(self):
        job = base_job()
        job.spec.tasks.append(job.spec.tasks[0])
        with pytest.raises(AdmissionError, match="duplicated task name"):
            validate_job(job)

    def test_invalid_dns_name_denied(self):
        job = base_job()
        job.spec.tasks[0].name = "Invalid_Name"
        with pytest.raises(AdmissionError, match="DNS-1123"):
            validate_job(job)

    def test_min_available_exceeds_replicas_denied(self):
        with pytest.raises(AdmissionError, match="total replicas"):
            validate_job(base_job(min_available=5))

    def test_bad_policy_event_denied(self):
        job = base_job(policies=[batch.LifecyclePolicy(event="NoSuchEvent", action=batch.RESTART_JOB_ACTION)])
        with pytest.raises(AdmissionError, match="invalid event"):
            validate_job(job)

    def test_exit_code_zero_denied(self):
        job = base_job(policies=[batch.LifecyclePolicy(exit_code=0, action=batch.ABORT_JOB_ACTION)])
        with pytest.raises(AdmissionError, match="not a valid error code"):
            validate_job(job)

    def test_unknown_plugin_denied(self):
        with pytest.raises(AdmissionError, match="unable to find job plugin"):
            validate_job(base_job(plugins={"nope": []}))

    def test_missing_queue_denied(self):
        api = APIServer()
        with pytest.raises(AdmissionError, match="unable to find job queue"):
            validate_job(base_job(queue="ghost"), api)

    def test_existing_queue_allowed(self):
        api = APIServer()
        VolcanoClient(api).create_queue(
            scheduling.Queue(metadata=core.ObjectMeta(name="default", namespace=""))
        )
        validate_job(base_job(), api)


class TestMutateJob:
    def test_defaults_queue_and_task_names(self):
        job = base_job()
        job.spec.queue = ""
        job.spec.tasks[0].name = ""
        mutate_job(job)
        assert job.spec.queue == "default"
        assert job.spec.tasks[0].name == "default0"


class TestPodGate:
    def test_pod_blocked_until_podgroup_inqueue(self):
        api = APIServer()
        vc = VolcanoClient(api)
        pod = core.Pod(
            metadata=core.ObjectMeta(
                name="p", namespace="ns",
                annotations={scheduling.GROUP_NAME_ANNOTATION_KEY: "pg1"},
            ),
            spec=core.PodSpec(scheduler_name="volcano-tpu"),
        )
        with pytest.raises(AdmissionError, match="cannot find PodGroup"):
            validate_pod(pod, api)
        vc.create_pod_group(
            scheduling.PodGroup(
                metadata=core.ObjectMeta(name="pg1", namespace="ns"),
                status=scheduling.PodGroupStatus(phase=scheduling.POD_GROUP_PENDING),
            )
        )
        with pytest.raises(AdmissionError, match="is Pending"):
            validate_pod(pod, api)
        pg = vc.get_pod_group("ns", "pg1")
        pg.status.phase = scheduling.POD_GROUP_INQUEUE
        vc.update_pod_group(pg)
        validate_pod(pod, api)  # allowed now

    def test_foreign_scheduler_pod_allowed(self):
        pod = core.Pod(spec=core.PodSpec(scheduler_name="default-scheduler"))
        validate_pod(pod, APIServer())


class TestRegisteredWebhooks:
    def test_create_invalid_job_through_api_denied(self):
        api = APIServer()
        register_webhooks(api)
        vc = VolcanoClient(api)
        vc.create_queue(scheduling.Queue(metadata=core.ObjectMeta(name="default", namespace="")))
        with pytest.raises(AdmissionError):
            vc.create_job(base_job(min_available=0))
        # valid one mutates defaults in
        job = base_job()
        job.spec.tasks[0].name = ""
        created = vc.create_job(job)
        assert created.spec.tasks[0].name == "default0"


class TestCLI:
    def _api(self):
        api = APIServer()
        register_webhooks(api)
        VolcanoClient(api).create_queue(
            scheduling.Queue(metadata=core.ObjectMeta(name="default", namespace=""))
        )
        return api

    def test_job_run_list_view_delete(self):
        api = self._api()
        out = io.StringIO()
        assert vtctl(["job", "run", "-N", "myjob", "-r", "2", "--min", "1"], api, out) == 0
        assert "run job myjob successfully" in out.getvalue()

        out = io.StringIO()
        assert vtctl(["job", "list"], api, out) == 0
        assert "myjob" in out.getvalue()

        out = io.StringIO()
        assert vtctl(["job", "view", "-N", "myjob"], api, out) == 0
        assert "minAvailable" in out.getvalue()

        out = io.StringIO()
        assert vtctl(["job", "delete", "-N", "myjob"], api, out) == 0
        assert VolcanoClient(api).list_jobs() == []

    def test_job_suspend_emits_command(self):
        api = self._api()
        out = io.StringIO()
        vtctl(["job", "run", "-N", "j1"], api, out)
        assert vtctl(["job", "suspend", "-N", "j1"], api, out) == 0
        cmds = VolcanoClient(api).list_commands()
        assert len(cmds) == 1 and cmds[0].action == batch.ABORT_JOB_ACTION

    def test_queue_lifecycle(self):
        api = self._api()
        out = io.StringIO()
        assert vtctl(["queue", "create", "-N", "q1", "-w", "5"], api, out) == 0
        out = io.StringIO()
        assert vtctl(["queue", "get", "-N", "q1"], api, out) == 0
        assert "q1" in out.getvalue()
        out = io.StringIO()
        assert vtctl(["queue", "operate", "-N", "q1", "-a", "close"], api, out) == 0
        cmds = VolcanoClient(api).list_commands()
        assert any(c.action == "CloseQueue" for c in cmds)
        out = io.StringIO()
        assert vtctl(["queue", "delete", "-N", "q1"], api, out) == 0

    def test_job_run_from_yaml(self, tmp_path):
        api = self._api()
        yaml_file = tmp_path / "job.yaml"
        yaml_file.write_text(
            """
apiVersion: batch.volcano-tpu.io/v1alpha1
kind: Job
metadata:
  name: yamljob
  namespace: default
spec:
  minAvailable: 2
  tasks:
  - name: worker
    replicas: 2
    template:
      spec:
        containers:
        - name: main
          image: busybox
          resources:
            requests:
              cpu: "1"
"""
        )
        out = io.StringIO()
        assert vtctl(["job", "run", "-f", str(yaml_file)], api, out) == 0
        job = VolcanoClient(api).get_job("default", "yamljob")
        assert job is not None and job.spec.min_available == 2


def _job_with_template(container=None, restart_policy="OnFailure"):
    return batch.Job(
        metadata=core.ObjectMeta(name="j", namespace="ns"),
        spec=batch.JobSpec(
            min_available=1,
            tasks=[
                batch.TaskSpec(
                    name="worker",
                    replicas=1,
                    template=core.PodTemplateSpec(
                        spec=core.PodSpec(
                            containers=[container or core.Container(image="busybox")],
                            restart_policy=restart_policy,
                        )
                    ),
                )
            ],
        ),
    )


class TestValidateTaskTemplate:
    """admit_job.go:194+ — the k8s pod-template validator depth
    (admit_job_test.go template cases)."""

    def test_invalid_container_name_denied(self):
        job = _job_with_template(core.Container(name="Bad_Name"))
        with pytest.raises(AdmissionError, match="DNS-1123"):
            validate_job(job)

    def test_duplicate_container_names_denied(self):
        job = _job_with_template()
        job.spec.tasks[0].template.spec.containers = [
            core.Container(name="main"),
            core.Container(name="main"),
        ]
        with pytest.raises(AdmissionError, match="duplicate container name"):
            validate_job(job)

    def test_bad_quantity_denied(self):
        job = _job_with_template(
            core.Container(resources={"requests": {"cpu": "not-a-cpu"}})
        )
        with pytest.raises(AdmissionError, match="invalid quantity"):
            validate_job(job)

    def test_requests_exceed_limits_denied(self):
        job = _job_with_template(
            core.Container(
                resources={"requests": {"cpu": "2"}, "limits": {"cpu": "1"}}
            )
        )
        with pytest.raises(AdmissionError, match="less than or equal to the limit"):
            validate_job(job)

    def test_requests_within_limits_allowed(self):
        validate_job(
            _job_with_template(
                core.Container(
                    image="busybox",
                    resources={
                        "requests": {"cpu": "500m", "memory": "1Gi"},
                        "limits": {"cpu": "1", "memory": "2Gi"},
                    }
                )
            )
        )

    def test_bad_restart_policy_denied(self):
        job = _job_with_template(restart_policy="WheneverConvenient")
        with pytest.raises(AdmissionError, match="restartPolicy"):
            validate_job(job)

    def test_missing_image_denied(self):
        """k8s ValidateContainers: image is required — an imageless
        template previously failed only at pod-creation time, far from
        the submitter (admit_job.go:194+)."""
        job = _job_with_template(core.Container(name="main"))
        with pytest.raises(AdmissionError, match="image: required"):
            validate_job(job)
        # init containers are held to the same requirement
        job = _job_with_template()
        job.spec.tasks[0].template.spec.init_containers = [
            core.Container(name="init")
        ]
        with pytest.raises(AdmissionError, match="initContainers.*image: required"):
            validate_job(job)

    def test_port_out_of_range_denied(self):
        job = _job_with_template(
            core.Container(ports=[core.ContainerPort(container_port=70000)])
        )
        with pytest.raises(AdmissionError, match="between 1 and 65535"):
            validate_job(job)

    def test_duplicate_ports_denied(self):
        job = _job_with_template(
            core.Container(
                ports=[
                    core.ContainerPort(container_port=8080),
                    core.ContainerPort(container_port=8080),
                ]
            )
        )
        with pytest.raises(AdmissionError, match="duplicate port"):
            validate_job(job)

    def test_duplicate_port_names_denied(self):
        job = _job_with_template(
            core.Container(
                ports=[
                    core.ContainerPort(container_port=80, name="web"),
                    core.ContainerPort(container_port=81, name="web"),
                ]
            )
        )
        with pytest.raises(AdmissionError, match="duplicate port name"):
            validate_job(job)

    def test_bad_protocol_denied(self):
        job = _job_with_template(
            core.Container(
                ports=[core.ContainerPort(container_port=80, protocol="HTTPish")]
            )
        )
        with pytest.raises(AdmissionError, match="unsupported protocol"):
            validate_job(job)

    def test_init_container_bad_quantity_denied(self):
        job = _job_with_template()
        job.spec.tasks[0].template.spec.init_containers = [
            core.Container(name="init", resources={"requests": {"cpu": "oops"}})
        ]
        with pytest.raises(AdmissionError, match="initContainers.*invalid quantity"):
            validate_job(job)

    def test_same_port_in_different_containers_allowed(self):
        """k8s allows two containers to declare the same containerPort —
        only duplicates within one container are denied."""
        job = _job_with_template()
        job.spec.tasks[0].template.spec.containers = [
            core.Container(name="app", image="busybox",
                           ports=[core.ContainerPort(container_port=8080)]),
            core.Container(name="metrics", image="busybox",
                           ports=[core.ContainerPort(container_port=8080)]),
        ]
        validate_job(job)


class TestValidateTemplateIdentity:
    """The round-5 validator widening: env names, volume mounts, pod
    volumes, hostname/subdomain (k8s ValidatePodSpec subset)."""

    def test_bad_env_name_denied(self):
        job = _job_with_template(
            core.Container(env=[core.EnvVar(name="1BAD", value="x")])
        )
        with pytest.raises(AdmissionError, match="environment variable name"):
            validate_job(job)

    def test_duplicate_env_name_allowed(self):
        # k8s validation.ValidateEnv admits duplicates (last entry wins
        # at runtime); the subset must not deny what the reference admits
        job = _job_with_template(
            core.Container(image="busybox",
                           env=[core.EnvVar(name="A", value="1"),
                                core.EnvVar(name="A", value="2")])
        )
        validate_job(job)

    def test_mount_without_declared_volume_denied(self):
        job = _job_with_template(
            core.Container(volume_mounts=[
                core.VolumeMount(name="data", mount_path="/data")])
        )
        with pytest.raises(AdmissionError, match="not declared in spec.volumes"):
            validate_job(job)

    def test_mount_with_declared_volume_allowed(self):
        job = _job_with_template(
            core.Container(image="busybox", volume_mounts=[
                core.VolumeMount(name="data", mount_path="/data")])
        )
        job.spec.tasks[0].template.spec.volumes = [
            core.Volume(name="data", source={"emptyDir": {}})
        ]
        validate_job(job)

    def test_duplicate_mount_path_denied(self):
        job = _job_with_template(
            core.Container(volume_mounts=[
                core.VolumeMount(name="data", mount_path="/data"),
                core.VolumeMount(name="data2", mount_path="/data"),
            ])
        )
        job.spec.tasks[0].template.spec.volumes = [
            core.Volume(name="data", source={"emptyDir": {}}),
            core.Volume(name="data2", source={"emptyDir": {}}),
        ]
        with pytest.raises(AdmissionError, match="duplicate mount path"):
            validate_job(job)

    def test_duplicate_pod_volume_denied(self):
        job = _job_with_template()
        job.spec.tasks[0].template.spec.volumes = [
            core.Volume(name="v", source={"emptyDir": {}}),
            core.Volume(name="v", source={"emptyDir": {}}),
        ]
        with pytest.raises(AdmissionError, match="duplicate volume name"):
            validate_job(job)

    def test_bad_hostname_denied(self):
        job = _job_with_template()
        job.spec.tasks[0].template.spec.hostname = "Bad_Host"
        with pytest.raises(AdmissionError, match="hostname"):
            validate_job(job)

    def test_valid_identity_fields_allowed(self):
        job = _job_with_template(
            core.Container(image="busybox",
                           env=[core.EnvVar(name="VC_TASK_INDEX", value="0")])
        )
        spec = job.spec.tasks[0].template.spec
        spec.hostname = "worker-0"
        spec.subdomain = "j-svc"
        validate_job(job)
