"""Allocate action tests — reference cases from
pkg/scheduler/actions/allocate/allocate_test.go plus gang semantics."""

from __future__ import annotations

from volcano_tpu.actions.allocate import AllocateAction

from tests.builders import build_node, build_pod, build_pod_group, build_queue
from tests.scheduler_helpers import make_cache, run_actions, tiers


def test_one_job_two_pods_on_one_node():
    """allocate_test.go 'one Job with two Pods on one node'."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "2", "memory": "4Gi"})],
        pods=[
            build_pod("c1", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
            build_pod("c1", "p2", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
        ],
        pod_groups=[build_pod_group("c1", "pg1", 0, queue="c1")],
        queues=[build_queue("c1", weight=1)],
    )
    run_actions(cache, [AllocateAction()], tiers(["drf", "proportion"]))
    assert cache.binder.binds == {"c1/p1": "n1", "c1/p2": "n1"}


def test_two_jobs_on_one_node_namespace_balanced():
    """allocate_test.go 'two Jobs on one node' — DRF namespace balancing
    gives one pod to each namespace when only two fit."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "2", "memory": "4G"})],
        pods=[
            build_pod("c1", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
            build_pod("c1", "p2", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
            build_pod("c2", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg2"),
            build_pod("c2", "p2", "", {"cpu": "1", "memory": "1G"}, group="pg2"),
        ],
        pod_groups=[
            build_pod_group("c1", "pg1", 0, queue="c1"),
            build_pod_group("c2", "pg2", 0, queue="c2"),
        ],
        queues=[build_queue("c1", weight=1), build_queue("c2", weight=1)],
    )
    run_actions(cache, [AllocateAction()], tiers(["drf", "proportion"]))
    assert cache.binder.binds == {"c1/p1": "n1", "c2/p1": "n1"}


def test_gang_all_or_nothing_discards_partial():
    """A gang job whose minMember cannot be satisfied binds nothing."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "1", "memory": "2G"})],
        pods=[
            build_pod("c1", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
            build_pod("c1", "p2", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
        ],
        pod_groups=[build_pod_group("c1", "pg1", 2, queue="c1")],
        queues=[build_queue("c1")],
    )
    run_actions(
        cache, [AllocateAction()], tiers(["priority", "gang"], ["drf", "proportion"])
    )
    assert cache.binder.binds == {}


def test_gang_binds_all_when_min_member_fits():
    cache = make_cache(
        nodes=[
            build_node("n1", {"cpu": "1", "memory": "2G"}),
            build_node("n2", {"cpu": "1", "memory": "2G"}),
        ],
        pods=[
            build_pod("c1", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
            build_pod("c1", "p2", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
        ],
        pod_groups=[build_pod_group("c1", "pg1", 2, queue="c1")],
        queues=[build_queue("c1")],
    )
    run_actions(
        cache, [AllocateAction()], tiers(["priority", "gang"], ["drf", "proportion"])
    )
    assert set(cache.binder.binds) == {"c1/p1", "c1/p2"}
    assert set(cache.binder.binds.values()) == {"n1", "n2"}


def test_pending_pod_group_is_skipped():
    """PodGroupPending jobs are not allocated (allocate.go:61-63)."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "2", "memory": "4G"})],
        pods=[build_pod("c1", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg1")],
        pod_groups=[build_pod_group("c1", "pg1", 0, queue="c1", phase="Pending")],
        queues=[build_queue("c1")],
    )
    run_actions(cache, [AllocateAction()], tiers(["drf", "proportion"]))
    assert cache.binder.binds == {}


def test_best_effort_tasks_skipped_by_allocate():
    """Zero-request tasks are backfill's job, not allocate's
    (allocate.go:158-167)."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "2", "memory": "4G"})],
        pods=[build_pod("c1", "p1", "", {}, group="pg1")],
        pod_groups=[build_pod_group("c1", "pg1", 0, queue="c1")],
        queues=[build_queue("c1")],
    )
    run_actions(cache, [AllocateAction()], tiers(["drf", "proportion"]))
    assert cache.binder.binds == {}


def test_node_selector_predicate_filters_nodes():
    cache = make_cache(
        nodes=[
            build_node("n1", {"cpu": "2", "memory": "4G"}, labels={"disk": "hdd"}),
            build_node("n2", {"cpu": "2", "memory": "4G"}, labels={"disk": "ssd"}),
        ],
        pods=[
            build_pod(
                "c1", "p1", "", {"cpu": "1", "memory": "1G"},
                group="pg1", selector={"disk": "ssd"},
            )
        ],
        pod_groups=[build_pod_group("c1", "pg1", 0, queue="c1")],
        queues=[build_queue("c1")],
    )
    run_actions(
        cache, [AllocateAction()], tiers(["gang"], ["drf", "predicates", "proportion"])
    )
    assert cache.binder.binds == {"c1/p1": "n2"}


def test_taints_respected():
    from volcano_tpu.apis import core

    cache = make_cache(
        nodes=[
            build_node(
                "n1",
                {"cpu": "2", "memory": "4G"},
                taints=[core.Taint(key="dedicated", value="infra", effect="NoSchedule")],
            ),
            build_node("n2", {"cpu": "2", "memory": "4G"}),
        ],
        pods=[build_pod("c1", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg1")],
        pod_groups=[build_pod_group("c1", "pg1", 0, queue="c1")],
        queues=[build_queue("c1")],
    )
    run_actions(
        cache, [AllocateAction()], tiers(["gang"], ["drf", "predicates", "proportion"])
    )
    assert cache.binder.binds == {"c1/p1": "n2"}


class TestNodeSubsampling:
    """Host-fallback node subsampling (options.go:38-40 +
    scheduler_helper.go:42-61): the allocate host predicate loop stops
    scanning once the feasible-node budget is met, so the no-TPU path
    copes with large node counts.  Wired from the vtpu-scheduler flags
    --percentage-nodes-to-find / --minimum-feasible-nodes."""

    def _with_opts(self, **kw):
        from volcano_tpu.scheduler import util as sched_util

        saved = sched_util.server_opts
        sched_util.server_opts = sched_util.ServerOpts(**kw)
        return saved

    def _restore(self, saved):
        from volcano_tpu.scheduler import util as sched_util

        sched_util.server_opts = saved

    def test_budget_formula_matches_reference(self):
        from volcano_tpu.scheduler.util import (
            calculate_num_of_feasible_nodes_to_find,
        )

        saved = self._with_opts(min_nodes_to_find=100,
                                min_percentage_of_nodes_to_find=5,
                                percentage_of_nodes_to_find=100)
        try:
            # percentage 100 → scan everything regardless of size
            assert calculate_num_of_feasible_nodes_to_find(5000) == 5000
        finally:
            self._restore(saved)
        saved = self._with_opts(min_nodes_to_find=100,
                                min_percentage_of_nodes_to_find=5,
                                percentage_of_nodes_to_find=10)
        try:
            # small clusters never subsample; large ones take the
            # percentage with the absolute floor
            assert calculate_num_of_feasible_nodes_to_find(50) == 50
            assert calculate_num_of_feasible_nodes_to_find(5000) == 500
            assert calculate_num_of_feasible_nodes_to_find(600) == 100
        finally:
            self._restore(saved)
        saved = self._with_opts(min_nodes_to_find=100,
                                min_percentage_of_nodes_to_find=5,
                                percentage_of_nodes_to_find=0)
        try:
            # adaptive mode: 50 - n/125, floored at the min percentage
            # (scheduler_helper.go:50-55)
            assert calculate_num_of_feasible_nodes_to_find(1000) == 420
            assert calculate_num_of_feasible_nodes_to_find(6000) == 300
        finally:
            self._restore(saved)

    def test_predicate_loop_honors_budget(self):
        """predicate_nodes stops after finding the budgeted number of
        feasible nodes — the scan visits a strict subset."""
        from volcano_tpu.scheduler.util import predicate_nodes

        nodes = [build_node(f"n{i:04d}", {"cpu": "8", "memory": "16Gi"})
                 for i in range(200)]
        from volcano_tpu.api import Resource, TaskInfo
        task = TaskInfo(uid="t1", job="j1", name="p", namespace="ns",
                        resreq=Resource.from_resource_list({"cpu": "1"}))

        visited = []

        def fn(t, n):
            visited.append(n.name)

        from volcano_tpu.api import NodeInfo
        node_infos = [NodeInfo(n) for n in nodes]

        saved = self._with_opts(min_nodes_to_find=10,
                                min_percentage_of_nodes_to_find=5,
                                percentage_of_nodes_to_find=10)
        try:
            found, _ = predicate_nodes(task, node_infos, fn)
            # budget = max(200*10//100, 10) = 20 of 200 nodes
            assert len(found) == 20
            assert len(visited) == 20
        finally:
            self._restore(saved)

    def test_allocate_still_binds_under_subsampling(self):
        """End to end through the host allocate action: with an
        aggressive budget the gang still binds (fewer nodes scanned,
        same correctness)."""
        saved = self._with_opts(min_nodes_to_find=2,
                                min_percentage_of_nodes_to_find=1,
                                percentage_of_nodes_to_find=1)
        try:
            cache = make_cache(
                nodes=[build_node(f"n{i}", {"cpu": "2", "memory": "4G"})
                       for i in range(50)],
                pods=[
                    build_pod("c1", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
                    build_pod("c1", "p2", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
                ],
                pod_groups=[build_pod_group("c1", "pg1", 2, queue="c1")],
                queues=[build_queue("c1")],
            )
            run_actions(cache, [AllocateAction()],
                        tiers(["gang"], ["drf", "predicates", "proportion"]))
            assert len(cache.binder.binds) == 2
        finally:
            self._restore(saved)

    def test_scheduler_flags_set_server_opts(self):
        """vtpu-scheduler --percentage-nodes-to-find /
        --minimum-feasible-nodes land in scheduler.util.server_opts."""
        import argparse

        from volcano_tpu.cmd.scheduler import add_common_args

        # replicate the main() parser wiring without starting the daemon
        parser = argparse.ArgumentParser()
        parser.add_argument("--percentage-nodes-to-find", type=int, default=100)
        parser.add_argument("--minimum-feasible-nodes", type=int, default=100)
        parser.add_argument("--minimum-percentage-nodes-to-find", type=int, default=5)
        add_common_args(parser)
        args = parser.parse_args([
            "--percentage-nodes-to-find", "10",
            "--minimum-feasible-nodes", "50",
        ])
        assert args.percentage_nodes_to_find == 10
        assert args.minimum_feasible_nodes == 50
