"""Allocate action tests — reference cases from
pkg/scheduler/actions/allocate/allocate_test.go plus gang semantics."""

from __future__ import annotations

from volcano_tpu.actions.allocate import AllocateAction

from tests.builders import build_node, build_pod, build_pod_group, build_queue
from tests.scheduler_helpers import make_cache, run_actions, tiers


def test_one_job_two_pods_on_one_node():
    """allocate_test.go 'one Job with two Pods on one node'."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "2", "memory": "4Gi"})],
        pods=[
            build_pod("c1", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
            build_pod("c1", "p2", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
        ],
        pod_groups=[build_pod_group("c1", "pg1", 0, queue="c1")],
        queues=[build_queue("c1", weight=1)],
    )
    run_actions(cache, [AllocateAction()], tiers(["drf", "proportion"]))
    assert cache.binder.binds == {"c1/p1": "n1", "c1/p2": "n1"}


def test_two_jobs_on_one_node_namespace_balanced():
    """allocate_test.go 'two Jobs on one node' — DRF namespace balancing
    gives one pod to each namespace when only two fit."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "2", "memory": "4G"})],
        pods=[
            build_pod("c1", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
            build_pod("c1", "p2", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
            build_pod("c2", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg2"),
            build_pod("c2", "p2", "", {"cpu": "1", "memory": "1G"}, group="pg2"),
        ],
        pod_groups=[
            build_pod_group("c1", "pg1", 0, queue="c1"),
            build_pod_group("c2", "pg2", 0, queue="c2"),
        ],
        queues=[build_queue("c1", weight=1), build_queue("c2", weight=1)],
    )
    run_actions(cache, [AllocateAction()], tiers(["drf", "proportion"]))
    assert cache.binder.binds == {"c1/p1": "n1", "c2/p1": "n1"}


def test_gang_all_or_nothing_discards_partial():
    """A gang job whose minMember cannot be satisfied binds nothing."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "1", "memory": "2G"})],
        pods=[
            build_pod("c1", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
            build_pod("c1", "p2", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
        ],
        pod_groups=[build_pod_group("c1", "pg1", 2, queue="c1")],
        queues=[build_queue("c1")],
    )
    run_actions(
        cache, [AllocateAction()], tiers(["priority", "gang"], ["drf", "proportion"])
    )
    assert cache.binder.binds == {}


def test_gang_binds_all_when_min_member_fits():
    cache = make_cache(
        nodes=[
            build_node("n1", {"cpu": "1", "memory": "2G"}),
            build_node("n2", {"cpu": "1", "memory": "2G"}),
        ],
        pods=[
            build_pod("c1", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
            build_pod("c1", "p2", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
        ],
        pod_groups=[build_pod_group("c1", "pg1", 2, queue="c1")],
        queues=[build_queue("c1")],
    )
    run_actions(
        cache, [AllocateAction()], tiers(["priority", "gang"], ["drf", "proportion"])
    )
    assert set(cache.binder.binds) == {"c1/p1", "c1/p2"}
    assert set(cache.binder.binds.values()) == {"n1", "n2"}


def test_pending_pod_group_is_skipped():
    """PodGroupPending jobs are not allocated (allocate.go:61-63)."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "2", "memory": "4G"})],
        pods=[build_pod("c1", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg1")],
        pod_groups=[build_pod_group("c1", "pg1", 0, queue="c1", phase="Pending")],
        queues=[build_queue("c1")],
    )
    run_actions(cache, [AllocateAction()], tiers(["drf", "proportion"]))
    assert cache.binder.binds == {}


def test_best_effort_tasks_skipped_by_allocate():
    """Zero-request tasks are backfill's job, not allocate's
    (allocate.go:158-167)."""
    cache = make_cache(
        nodes=[build_node("n1", {"cpu": "2", "memory": "4G"})],
        pods=[build_pod("c1", "p1", "", {}, group="pg1")],
        pod_groups=[build_pod_group("c1", "pg1", 0, queue="c1")],
        queues=[build_queue("c1")],
    )
    run_actions(cache, [AllocateAction()], tiers(["drf", "proportion"]))
    assert cache.binder.binds == {}


def test_node_selector_predicate_filters_nodes():
    cache = make_cache(
        nodes=[
            build_node("n1", {"cpu": "2", "memory": "4G"}, labels={"disk": "hdd"}),
            build_node("n2", {"cpu": "2", "memory": "4G"}, labels={"disk": "ssd"}),
        ],
        pods=[
            build_pod(
                "c1", "p1", "", {"cpu": "1", "memory": "1G"},
                group="pg1", selector={"disk": "ssd"},
            )
        ],
        pod_groups=[build_pod_group("c1", "pg1", 0, queue="c1")],
        queues=[build_queue("c1")],
    )
    run_actions(
        cache, [AllocateAction()], tiers(["gang"], ["drf", "predicates", "proportion"])
    )
    assert cache.binder.binds == {"c1/p1": "n2"}


def test_taints_respected():
    from volcano_tpu.apis import core

    cache = make_cache(
        nodes=[
            build_node(
                "n1",
                {"cpu": "2", "memory": "4G"},
                taints=[core.Taint(key="dedicated", value="infra", effect="NoSchedule")],
            ),
            build_node("n2", {"cpu": "2", "memory": "4G"}),
        ],
        pods=[build_pod("c1", "p1", "", {"cpu": "1", "memory": "1G"}, group="pg1")],
        pod_groups=[build_pod_group("c1", "pg1", 0, queue="c1")],
        queues=[build_queue("c1")],
    )
    run_actions(
        cache, [AllocateAction()], tiers(["gang"], ["drf", "predicates", "proportion"])
    )
    assert cache.binder.binds == {"c1/p1": "n2"}
