"""Static-analysis suite coverage: every pass gets positive (finding
expected) and negative (clean) fixture snippets, the baseline
suppression machinery round-trips, the serde-drift pass catches
registry drift, and the runtime lock-order verifier detects a contrived
ABBA interleave while staying quiet on consistent ordering.

The last class pins the whole-tree contract the CI `analysis` job
enforces: `python -m volcano_tpu.analysis` over this repo exits 0 —
which also pins every genuine violation this PR fixed (unlocked
guarded-attribute accesses in trace/recorder, bus/remote,
client/apiserver, serving/compute_plane, cache/cache; the serde
round-trip registry) against regression: reverting any fix re-raises
its finding and fails the suite.
"""

from __future__ import annotations

import io
import json
import threading

import pytest

from volcano_tpu.analysis import determinism, jit_safety, lock_discipline
from volcano_tpu.analysis import lock_order, serde_drift
from volcano_tpu.analysis.__main__ import find_root, main as analysis_main
from volcano_tpu.analysis.core import Baseline, Finding, SourceFile


def _src(text: str, rel: str = "volcano_tpu/fixture.py") -> SourceFile:
    return SourceFile("<fixture>", rel, text)


def _codes(findings):
    return [f.code for f in findings]


# ---- lock discipline ----


class TestLockDiscipline:
    def test_unlocked_write_and_read_flagged(self):
        findings = lock_discipline.check_file(_src(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []  # guarded-by: self._lock\n"
            "    def bad_write(self):\n"
            "        self._items.append(1)\n"
            "    def bad_read(self):\n"
            "        return len(self._items)\n"
            "    def good(self):\n"
            "        with self._lock:\n"
            "            self._items.clear()\n"
        ))
        assert _codes(findings) == ["LCK001", "LCK001"]
        assert {f.symbol for f in findings} == {
            "C.bad_write:_items", "C.bad_read:_items",
        }

    def test_locked_access_and_init_are_clean(self):
        findings = lock_discipline.check_file(_src(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []  # guarded-by: self._lock\n"
            "        self._items.append(0)\n"  # construction is exempt
            "    def good(self):\n"
            "        with self._lock:\n"
            "            self._items.append(1)\n"
            "            return list(self._items)\n"
        ))
        assert findings == []

    def test_requires_lock_helper_trusted(self):
        findings = lock_discipline.check_file(_src(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded-by: self._lock\n"
            "    def _bump(self):\n"
            "        # requires-lock: self._lock\n"
            "        self._n += 1\n"
            "    def caller(self):\n"
            "        with self._lock:\n"
            "            self._bump()\n"
        ))
        assert findings == []

    def test_closure_resets_held_scope(self):
        # the with-scope does NOT extend into a nested def: the closure
        # runs later, when the lock has long been released
        findings = lock_discipline.check_file(_src(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded-by: self._lock\n"
            "    def make(self):\n"
            "        with self._lock:\n"
            "            def cb():\n"
            "                return self._n\n"
            "            return cb\n"
        ))
        assert _codes(findings) == ["LCK001"]
        assert findings[0].symbol == "C.make.cb:_n"

    def test_unlocked_ok_waiver(self):
        findings = lock_discipline.check_file(_src(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._flag = False  # guarded-by: self._lock\n"
            "    def peek(self):\n"
            "        return self._flag  # unlocked-ok: benign flag read\n"
            "    def set(self):\n"
            "        with self._lock:\n"
            "            self._flag = True\n"
        ))
        assert findings == []

    def test_module_global_guard_and_global_stmt_write(self):
        findings = lock_discipline.check_file(_src(
            "import threading\n"
            "_lock = threading.Lock()\n"
            "_state = {}  # guarded-by: _lock\n"
            "def bad_write(v):\n"
            "    global _state\n"
            "    _state = v\n"
            "def good(v):\n"
            "    with _lock:\n"
            "        _state[1] = v\n"
            "def shadow():\n"
            "    _state = {}\n"  # local binding — not the global
            "    return _state\n"
        ))
        assert _codes(findings) == ["LCK001"]
        assert findings[0].symbol == "bad_write:_state"

    def test_stale_annotation_dead_lock_flagged(self):
        findings = lock_discipline.check_file(_src(
            "class C:\n"
            "    def __init__(self):\n"
            "        self._n = 0  # guarded-by: self._never_taken\n"
        ))
        assert _codes(findings) == ["LCK002"]


# ---- determinism ----


class TestDeterminism:
    def test_wall_clock_and_global_rng_flagged(self):
        findings = determinism.check_file(_src(
            "import random\n"
            "import time\n"
            "def decide():\n"
            "    if random.random() < 0.5:\n"
            "        return time.time()\n"
        ))
        assert sorted(_codes(findings)) == ["DET001", "DET002"]

    def test_seeded_rng_and_monotonic_are_clean(self):
        findings = determinism.check_file(_src(
            "import random\n"
            "import time\n"
            "import numpy as np\n"
            "def decide(seed):\n"
            "    rng = random.Random(seed)\n"
            "    st = np.random.RandomState(seed)\n"
            "    t0 = time.monotonic()\n"
            "    return rng.random() + st.rand() + time.perf_counter() - t0\n"
        ))
        assert findings == []

    def test_set_iteration_order_escape(self):
        findings = determinism.check_file(_src(
            "def leak(xs):\n"
            "    out = []\n"
            "    for x in set(xs):\n"
            "        out.append(x)\n"
            "    return out + list({1, 2})\n"
        ))
        assert _codes(findings) == ["DET003", "DET003"]

    def test_sorted_set_is_the_blessed_fix(self):
        findings = determinism.check_file(_src(
            "def ok(xs):\n"
            "    return [x for x in sorted(set(xs))]\n"
        ))
        assert findings == []

    def test_det_marker_waives(self):
        findings = determinism.check_file(_src(
            "import time\n"
            "def stamp():\n"
            "    return time.time()  # det: journal timestamp\n"
        ))
        assert findings == []

    def test_uuid_entropy_flagged(self):
        findings = determinism.check_file(_src(
            "import uuid\n"
            "def ident():\n"
            "    return uuid.uuid4().hex\n"
        ))
        assert _codes(findings) == ["DET004"]


# ---- jit safety ----


class TestJitSafety:
    def test_item_and_concretize_inside_jit(self):
        findings = jit_safety.check_file(_src(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    v = x.sum().item()\n"
            "    return float(x[0]) + v\n"
        ))
        assert sorted(_codes(findings)) == ["JIT001", "JIT002"]

    def test_tracer_branch_flagged_static_allowed(self):
        findings = jit_safety.check_file(_src(
            "import functools\n"
            "import jax\n"
            "@functools.partial(jax.jit, static_argnames=('k',))\n"
            "def f(x, k):\n"
            "    if k > 2:\n"          # static — allowed
            "        return x * 2\n"
            "    if x.shape[0] > 4:\n"  # shape is static — allowed
            "        return x\n"
            "    if x > 0:\n"           # tracer value — flagged
            "        return x + 1\n"
            "    return x\n"
        ))
        assert _codes(findings) == ["JIT003"]
        assert findings[0].symbol == "f:x"

    def test_outside_jit_is_not_flagged(self):
        findings = jit_safety.check_file(_src(
            "def host(x):\n"
            "    return float(x[0].item())\n"
        ))
        assert findings == []

    def test_jit_wrapped_local_def_checked(self):
        findings = jit_safety.check_file(_src(
            "import jax\n"
            "def factory():\n"
            "    def inner(x):\n"
            "        return int(x.sum())\n"
            "    return jax.jit(inner)\n"
        ))
        assert _codes(findings) == ["JIT002"]

    def test_donated_buffer_reuse_flagged(self):
        findings = jit_safety.check_file(_src(
            "import jax\n"
            "def scatter(buf, rows, vals):\n"
            "    return buf.at[rows].set(vals)\n"
            "g = jax.jit(scatter, donate_argnums=(0,))\n"
            "def use(buf, rows, vals):\n"
            "    out = g(buf, rows, vals)\n"
            "    return out + buf\n"  # buf was donated — invalid
        ))
        assert _codes(findings) == ["JIT004"]
        assert findings[0].symbol == "use:buf"

    def test_donated_rebind_is_clean(self):
        findings = jit_safety.check_file(_src(
            "import jax\n"
            "def scatter(buf, rows, vals):\n"
            "    return buf.at[rows].set(vals)\n"
            "g = jax.jit(scatter, donate_argnums=(0,))\n"
            "def use(buf, rows, vals):\n"
            "    buf = g(buf, rows, vals)\n"  # rebound — fresh buffer
            "    return buf\n"
        ))
        assert findings == []


# ---- serde drift ----


class TestSerdeDrift:
    def test_real_tree_is_drift_free(self):
        assert serde_drift.run(find_root()) == []

    def test_unregistered_kind_missing_exemplar(self, monkeypatch):
        from volcano_tpu.bus import protocol

        monkeypatch.setitem(protocol.KINDS, "Phantom", object)
        findings = serde_drift.run(find_root())
        assert [f.code for f in findings] == ["SRD001"]
        assert findings[0].symbol == "Phantom"

    def test_server_op_without_version_registration(self, monkeypatch):
        from volcano_tpu.bus import protocol

        trimmed = dict(protocol.OP_VERSIONS)
        del trimmed["commit_batch"]
        monkeypatch.setattr(protocol, "OP_VERSIONS", trimmed)
        findings = serde_drift.run(find_root())
        assert [f.code for f in findings] == ["SRD002"]
        assert findings[0].symbol == "commit_batch"

    def test_post_v1_op_declared_but_unhandled_is_drift(self, monkeypatch):
        from volcano_tpu.bus import protocol

        grown = dict(protocol.OP_VERSIONS)
        # a fictional future op — registered but dispatched nowhere
        # (watch_batch, the old fixture name here, became a REAL v3 op)
        grown["evict_batch"] = 4
        monkeypatch.setattr(protocol, "OP_VERSIONS", grown)
        findings = serde_drift.run(find_root())
        assert [f.code for f in findings] == ["SRD004"]
        assert findings[0].symbol == "evict_batch"


# ---- baseline machinery ----


class TestBaseline:
    def _finding(self, symbol="C.bad:_x"):
        return Finding("lock", "LCK001", "volcano_tpu/m.py", 7, symbol, "msg")

    def test_round_trip_suppresses_by_key_not_line(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        Baseline.write(path, [self._finding()])
        data = json.load(open(path))
        # reasons are mandatory: the writer emits a TODO the author edits
        assert data["suppressions"][0]["reason"].startswith("TODO")
        data["suppressions"][0]["reason"] = "known benign"
        json.dump(data, open(path, "w"))
        bl = Baseline.load(path)
        moved = Finding("lock", "LCK001", "volcano_tpu/m.py", 99,
                        "C.bad:_x", "msg")  # line drifted — still matches
        unsup, sup, stale = bl.split([moved])
        assert unsup == [] and sup == [moved] and stale == []

    def test_stale_entry_reported(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        Baseline.write(path, [self._finding()])
        data = json.load(open(path))
        data["suppressions"][0]["reason"] = "obsolete"
        json.dump(data, open(path, "w"))
        unsup, sup, stale = Baseline.load(path).split([])
        assert stale and stale[0]["symbol"] == "C.bad:_x"

    def test_missing_reason_rejected(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        json.dump({"suppressions": [{
            "pass": "lock", "code": "LCK001", "file": "f.py",
            "symbol": "s", "reason": "",
        }]}, open(path, "w"))
        with pytest.raises(ValueError, match="reason"):
            Baseline.load(path)


# ---- runtime lock-order verifier ----


class TestLockOrder:
    def _graph(self):
        return lock_order._Graph()

    def _run_in_thread(self, fn):
        t = threading.Thread(target=fn)
        t.start()
        t.join(5)
        assert not t.is_alive()

    def test_abba_interleave_detected(self):
        g = self._graph()
        g.register(1, "a.py:10")
        g.register(2, "b.py:20")
        self._run_in_thread(lambda: (
            g.acquired(1), g.acquired(2), g.released(2), g.released(1),
        ))
        assert g.violations == []  # one order alone is fine
        self._run_in_thread(lambda: (
            g.acquired(2), g.acquired(1), g.released(1), g.released(2),
        ))
        assert len(g.violations) == 1
        rendered = g.violations[0].render()
        assert "a.py:10" in rendered and "b.py:20" in rendered

    def test_consistent_order_stays_acyclic(self):
        g = self._graph()
        for lid in (1, 2, 3):
            g.register(lid, f"l{lid}.py:1")
        for _ in range(3):
            self._run_in_thread(lambda: (
                g.acquired(1), g.acquired(2), g.acquired(3),
                g.released(3), g.released(2), g.released(1),
            ))
        assert g.violations == []
        assert g.report()["violations"] == []

    def test_rlock_reentry_is_not_an_edge(self):
        g = self._graph()
        g.register(1, "a.py:1")
        self._run_in_thread(lambda: (
            g.acquired(1), g.acquired(1), g.released(1), g.released(1),
        ))
        assert g.edges == {} and g.violations == []

    def test_transitive_cycle_detected(self):
        g = self._graph()
        for lid in (1, 2, 3):
            g.register(lid, f"l{lid}.py:1")
        self._run_in_thread(lambda: (
            g.acquired(1), g.acquired(2), g.released(2), g.released(1),
        ))
        self._run_in_thread(lambda: (
            g.acquired(2), g.acquired(3), g.released(3), g.released(2),
        ))
        assert g.violations == []
        self._run_in_thread(lambda: (
            g.acquired(3), g.acquired(1), g.released(1), g.released(3),
        ))
        assert len(g.violations) == 1  # 1→2→3→1

    def test_instrumented_lock_supports_condition_wait(self):
        """The _release_save/_acquire_restore forwarding keeps
        Condition.wait working over an instrumented RLock, and the
        held-stack stays balanced across the wait."""
        g = self._graph()
        old = lock_order._graph
        lock_order._graph = g
        try:
            inner = threading.RLock()
            lk = lock_order._InstrumentedLock(inner, "fixture.py:1")
            cv = threading.Condition(lk)
            fired = []

            def waiter():
                with cv:
                    got = cv.wait(timeout=5)
                    fired.append(got)
                assert g.held() == []

            t = threading.Thread(target=waiter)
            t.start()
            import time as _t

            deadline = _t.monotonic() + 5
            while not cv._waiters and _t.monotonic() < deadline:
                _t.sleep(0.01)  # until the waiter parks in wait()
            with cv:
                cv.notify_all()
            t.join(5)
            assert fired == [True]
            assert g.held() == []  # this thread's stack balanced too
        finally:
            lock_order._graph = old


# ---- the whole-tree gate (pins every fixed violation) ----


class TestRepoTree:
    def test_analysis_suite_is_green_on_this_tree(self):
        out = io.StringIO()
        rc = analysis_main([], out=out)
        assert rc == 0, f"analysis found regressions:\n{out.getvalue()}"

    def test_partial_run_ignores_other_passes_baseline(self):
        out = io.StringIO()
        rc = analysis_main(["--pass", "det"], out=out)
        assert rc == 0, out.getvalue()

    def test_report_artifact_shape(self, tmp_path):
        report = tmp_path / "findings.json"
        rc = analysis_main(["--report", str(report)], out=io.StringIO())
        assert rc == 0
        data = json.loads(report.read_text())
        assert set(data) == {"findings", "suppressed",
                             "stale_baseline_entries"}
        assert data["findings"] == []
        # the one reasoned suppression (faults/watchdog fast-path read)
        assert [s["symbol"] for s in data["suppressed"]] == [
            "begin_cycle:_deadline_s"
        ]
