"""Static-analysis suite coverage: every pass gets positive (finding
expected) and negative (clean) fixture snippets, the baseline
suppression machinery round-trips, the serde-drift pass catches
registry drift, and the runtime lock-order verifier detects a contrived
ABBA interleave while staying quiet on consistent ordering.

The last class pins the whole-tree contract the CI `analysis` job
enforces: `python -m volcano_tpu.analysis` over this repo exits 0 —
which also pins every genuine violation this PR fixed (unlocked
guarded-attribute accesses in trace/recorder, bus/remote,
client/apiserver, serving/compute_plane, cache/cache; the serde
round-trip registry) against regression: reverting any fix re-raises
its finding and fails the suite.
"""

from __future__ import annotations

import io
import json
import threading

import pytest

from volcano_tpu.analysis import determinism, jit_safety, lock_discipline
from volcano_tpu.analysis import lock_order, serde_drift
from volcano_tpu.analysis.__main__ import find_root, main as analysis_main
from volcano_tpu.analysis.core import Baseline, Finding, SourceFile


def _src(text: str, rel: str = "volcano_tpu/fixture.py") -> SourceFile:
    return SourceFile("<fixture>", rel, text)


def _codes(findings):
    return [f.code for f in findings]


# ---- lock discipline ----


class TestLockDiscipline:
    def test_unlocked_write_and_read_flagged(self):
        findings = lock_discipline.check_file(_src(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []  # guarded-by: self._lock\n"
            "    def bad_write(self):\n"
            "        self._items.append(1)\n"
            "    def bad_read(self):\n"
            "        return len(self._items)\n"
            "    def good(self):\n"
            "        with self._lock:\n"
            "            self._items.clear()\n"
        ))
        assert _codes(findings) == ["LCK001", "LCK001"]
        assert {f.symbol for f in findings} == {
            "C.bad_write:_items", "C.bad_read:_items",
        }

    def test_locked_access_and_init_are_clean(self):
        findings = lock_discipline.check_file(_src(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []  # guarded-by: self._lock\n"
            "        self._items.append(0)\n"  # construction is exempt
            "    def good(self):\n"
            "        with self._lock:\n"
            "            self._items.append(1)\n"
            "            return list(self._items)\n"
        ))
        assert findings == []

    def test_requires_lock_helper_trusted(self):
        findings = lock_discipline.check_file(_src(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded-by: self._lock\n"
            "    def _bump(self):\n"
            "        # requires-lock: self._lock\n"
            "        self._n += 1\n"
            "    def caller(self):\n"
            "        with self._lock:\n"
            "            self._bump()\n"
        ))
        assert findings == []

    def test_closure_resets_held_scope(self):
        # the with-scope does NOT extend into a nested def: the closure
        # runs later, when the lock has long been released
        findings = lock_discipline.check_file(_src(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded-by: self._lock\n"
            "    def make(self):\n"
            "        with self._lock:\n"
            "            def cb():\n"
            "                return self._n\n"
            "            return cb\n"
        ))
        assert _codes(findings) == ["LCK001"]
        assert findings[0].symbol == "C.make.cb:_n"

    def test_unlocked_ok_waiver(self):
        findings = lock_discipline.check_file(_src(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._flag = False  # guarded-by: self._lock\n"
            "    def peek(self):\n"
            "        return self._flag  # unlocked-ok: benign flag read\n"
            "    def set(self):\n"
            "        with self._lock:\n"
            "            self._flag = True\n"
        ))
        assert findings == []

    def test_module_global_guard_and_global_stmt_write(self):
        findings = lock_discipline.check_file(_src(
            "import threading\n"
            "_lock = threading.Lock()\n"
            "_state = {}  # guarded-by: _lock\n"
            "def bad_write(v):\n"
            "    global _state\n"
            "    _state = v\n"
            "def good(v):\n"
            "    with _lock:\n"
            "        _state[1] = v\n"
            "def shadow():\n"
            "    _state = {}\n"  # local binding — not the global
            "    return _state\n"
        ))
        assert _codes(findings) == ["LCK001"]
        assert findings[0].symbol == "bad_write:_state"

    def test_stale_annotation_dead_lock_flagged(self):
        findings = lock_discipline.check_file(_src(
            "class C:\n"
            "    def __init__(self):\n"
            "        self._n = 0  # guarded-by: self._never_taken\n"
        ))
        assert _codes(findings) == ["LCK002"]


# ---- determinism ----


class TestDeterminism:
    def test_wall_clock_and_global_rng_flagged(self):
        findings = determinism.check_file(_src(
            "import random\n"
            "import time\n"
            "def decide():\n"
            "    if random.random() < 0.5:\n"
            "        return time.time()\n"
        ))
        assert sorted(_codes(findings)) == ["DET001", "DET002"]

    def test_seeded_rng_and_monotonic_are_clean(self):
        findings = determinism.check_file(_src(
            "import random\n"
            "import time\n"
            "import numpy as np\n"
            "def decide(seed):\n"
            "    rng = random.Random(seed)\n"
            "    st = np.random.RandomState(seed)\n"
            "    t0 = time.monotonic()\n"
            "    return rng.random() + st.rand() + time.perf_counter() - t0\n"
        ))
        assert findings == []

    def test_set_iteration_order_escape(self):
        findings = determinism.check_file(_src(
            "def leak(xs):\n"
            "    out = []\n"
            "    for x in set(xs):\n"
            "        out.append(x)\n"
            "    return out + list({1, 2})\n"
        ))
        assert _codes(findings) == ["DET003", "DET003"]

    def test_sorted_set_is_the_blessed_fix(self):
        findings = determinism.check_file(_src(
            "def ok(xs):\n"
            "    return [x for x in sorted(set(xs))]\n"
        ))
        assert findings == []

    def test_det_marker_waives(self):
        findings = determinism.check_file(_src(
            "import time\n"
            "def stamp():\n"
            "    return time.time()  # det: journal timestamp\n"
        ))
        assert findings == []

    def test_uuid_entropy_flagged(self):
        findings = determinism.check_file(_src(
            "import uuid\n"
            "def ident():\n"
            "    return uuid.uuid4().hex\n"
        ))
        assert _codes(findings) == ["DET004"]


# ---- jit safety ----


class TestJitSafety:
    def test_item_and_concretize_inside_jit(self):
        findings = jit_safety.check_file(_src(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    v = x.sum().item()\n"
            "    return float(x[0]) + v\n"
        ))
        assert sorted(_codes(findings)) == ["JIT001", "JIT002"]

    def test_tracer_branch_flagged_static_allowed(self):
        findings = jit_safety.check_file(_src(
            "import functools\n"
            "import jax\n"
            "@functools.partial(jax.jit, static_argnames=('k',))\n"
            "def f(x, k):\n"
            "    if k > 2:\n"          # static — allowed
            "        return x * 2\n"
            "    if x.shape[0] > 4:\n"  # shape is static — allowed
            "        return x\n"
            "    if x > 0:\n"           # tracer value — flagged
            "        return x + 1\n"
            "    return x\n"
        ))
        assert _codes(findings) == ["JIT003"]
        assert findings[0].symbol == "f:x"

    def test_outside_jit_is_not_flagged(self):
        findings = jit_safety.check_file(_src(
            "def host(x):\n"
            "    return float(x[0].item())\n"
        ))
        assert findings == []

    def test_jit_wrapped_local_def_checked(self):
        findings = jit_safety.check_file(_src(
            "import jax\n"
            "def factory():\n"
            "    def inner(x):\n"
            "        return int(x.sum())\n"
            "    return jax.jit(inner)\n"
        ))
        assert _codes(findings) == ["JIT002"]

    def test_donated_buffer_reuse_flagged(self):
        findings = jit_safety.check_file(_src(
            "import jax\n"
            "def scatter(buf, rows, vals):\n"
            "    return buf.at[rows].set(vals)\n"
            "g = jax.jit(scatter, donate_argnums=(0,))\n"
            "def use(buf, rows, vals):\n"
            "    out = g(buf, rows, vals)\n"
            "    return out + buf\n"  # buf was donated — invalid
        ))
        assert _codes(findings) == ["JIT004"]
        assert findings[0].symbol == "use:buf"

    def test_donated_rebind_is_clean(self):
        findings = jit_safety.check_file(_src(
            "import jax\n"
            "def scatter(buf, rows, vals):\n"
            "    return buf.at[rows].set(vals)\n"
            "g = jax.jit(scatter, donate_argnums=(0,))\n"
            "def use(buf, rows, vals):\n"
            "    buf = g(buf, rows, vals)\n"  # rebound — fresh buffer
            "    return buf\n"
        ))
        assert findings == []


# ---- serde drift ----


class TestSerdeDrift:
    def test_real_tree_is_drift_free(self):
        assert serde_drift.run(find_root()) == []

    def test_unregistered_kind_missing_exemplar(self, monkeypatch):
        from volcano_tpu.bus import protocol

        monkeypatch.setitem(protocol.KINDS, "Phantom", object)
        findings = serde_drift.run(find_root())
        assert [f.code for f in findings] == ["SRD001"]
        assert findings[0].symbol == "Phantom"

    def test_server_op_without_version_registration(self, monkeypatch):
        from volcano_tpu.bus import protocol

        trimmed = dict(protocol.OP_VERSIONS)
        del trimmed["commit_batch"]
        monkeypatch.setattr(protocol, "OP_VERSIONS", trimmed)
        findings = serde_drift.run(find_root())
        assert [f.code for f in findings] == ["SRD002"]
        assert findings[0].symbol == "commit_batch"

    def test_post_v1_op_declared_but_unhandled_is_drift(self, monkeypatch):
        from volcano_tpu.bus import protocol

        grown = dict(protocol.OP_VERSIONS)
        # a fictional future op — registered but dispatched nowhere
        # (watch_batch, the old fixture name here, became a REAL v3 op)
        grown["evict_batch"] = 4
        monkeypatch.setattr(protocol, "OP_VERSIONS", grown)
        findings = serde_drift.run(find_root())
        # the phantom op draws BOTH halves of the discipline: nobody
        # dispatches it (SRD004) and the README ladder never names it
        # (SRD005)
        assert sorted(f.code for f in findings) == ["SRD004", "SRD005"]
        assert {f.symbol for f in findings} == {"evict_batch"}


# ---- baseline machinery ----


class TestBaseline:
    def _finding(self, symbol="C.bad:_x"):
        return Finding("lock", "LCK001", "volcano_tpu/m.py", 7, symbol, "msg")

    def test_round_trip_suppresses_by_key_not_line(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        Baseline.write(path, [self._finding()])
        data = json.load(open(path))
        # reasons are mandatory: the writer emits a TODO the author edits
        assert data["suppressions"][0]["reason"].startswith("TODO")
        data["suppressions"][0]["reason"] = "known benign"
        json.dump(data, open(path, "w"))
        bl = Baseline.load(path)
        moved = Finding("lock", "LCK001", "volcano_tpu/m.py", 99,
                        "C.bad:_x", "msg")  # line drifted — still matches
        unsup, sup, stale = bl.split([moved])
        assert unsup == [] and sup == [moved] and stale == []

    def test_stale_entry_reported(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        Baseline.write(path, [self._finding()])
        data = json.load(open(path))
        data["suppressions"][0]["reason"] = "obsolete"
        json.dump(data, open(path, "w"))
        unsup, sup, stale = Baseline.load(path).split([])
        assert stale and stale[0]["symbol"] == "C.bad:_x"

    def test_missing_reason_rejected(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        json.dump({"suppressions": [{
            "pass": "lock", "code": "LCK001", "file": "f.py",
            "symbol": "s", "reason": "",
        }]}, open(path, "w"))
        with pytest.raises(ValueError, match="reason"):
            Baseline.load(path)


# ---- runtime lock-order verifier ----


class TestLockOrder:
    def _graph(self):
        return lock_order._Graph()

    def _run_in_thread(self, fn):
        t = threading.Thread(target=fn)
        t.start()
        t.join(5)
        assert not t.is_alive()

    def test_abba_interleave_detected(self):
        g = self._graph()
        g.register(1, "a.py:10")
        g.register(2, "b.py:20")
        self._run_in_thread(lambda: (
            g.acquired(1), g.acquired(2), g.released(2), g.released(1),
        ))
        assert g.violations == []  # one order alone is fine
        self._run_in_thread(lambda: (
            g.acquired(2), g.acquired(1), g.released(1), g.released(2),
        ))
        assert len(g.violations) == 1
        rendered = g.violations[0].render()
        assert "a.py:10" in rendered and "b.py:20" in rendered

    def test_consistent_order_stays_acyclic(self):
        g = self._graph()
        for lid in (1, 2, 3):
            g.register(lid, f"l{lid}.py:1")
        for _ in range(3):
            self._run_in_thread(lambda: (
                g.acquired(1), g.acquired(2), g.acquired(3),
                g.released(3), g.released(2), g.released(1),
            ))
        assert g.violations == []
        assert g.report()["violations"] == []

    def test_rlock_reentry_is_not_an_edge(self):
        g = self._graph()
        g.register(1, "a.py:1")
        self._run_in_thread(lambda: (
            g.acquired(1), g.acquired(1), g.released(1), g.released(1),
        ))
        assert g.edges == {} and g.violations == []

    def test_transitive_cycle_detected(self):
        g = self._graph()
        for lid in (1, 2, 3):
            g.register(lid, f"l{lid}.py:1")
        self._run_in_thread(lambda: (
            g.acquired(1), g.acquired(2), g.released(2), g.released(1),
        ))
        self._run_in_thread(lambda: (
            g.acquired(2), g.acquired(3), g.released(3), g.released(2),
        ))
        assert g.violations == []
        self._run_in_thread(lambda: (
            g.acquired(3), g.acquired(1), g.released(1), g.released(3),
        ))
        assert len(g.violations) == 1  # 1→2→3→1

    def test_instrumented_lock_supports_condition_wait(self):
        """The _release_save/_acquire_restore forwarding keeps
        Condition.wait working over an instrumented RLock, and the
        held-stack stays balanced across the wait."""
        g = self._graph()
        old = lock_order._graph
        lock_order._graph = g
        try:
            inner = threading.RLock()
            lk = lock_order._InstrumentedLock(inner, "fixture.py:1")
            cv = threading.Condition(lk)
            fired = []

            def waiter():
                with cv:
                    got = cv.wait(timeout=5)
                    fired.append(got)
                assert g.held() == []

            t = threading.Thread(target=waiter)
            t.start()
            import time as _t

            deadline = _t.monotonic() + 5
            while not cv._waiters and _t.monotonic() < deadline:
                _t.sleep(0.01)  # until the waiter parks in wait()
            with cv:
                cv.notify_all()
            t.join(5)
            assert fired == [True]
            assert g.held() == []  # this thread's stack balanced too
        finally:
            lock_order._graph = old


# ---- the whole-tree gate (pins every fixed violation) ----


class TestRepoTree:
    def test_analysis_suite_is_green_on_this_tree(self):
        out = io.StringIO()
        rc = analysis_main([], out=out)
        assert rc == 0, f"analysis found regressions:\n{out.getvalue()}"

    def test_partial_run_ignores_other_passes_baseline(self):
        out = io.StringIO()
        rc = analysis_main(["--pass", "det"], out=out)
        assert rc == 0, out.getvalue()

    def test_report_artifact_shape(self, tmp_path):
        report = tmp_path / "findings.json"
        rc = analysis_main(["--report", str(report)], out=io.StringIO())
        assert rc == 0
        data = json.loads(report.read_text())
        assert set(data) == {"findings", "suppressed",
                             "stale_baseline_entries"}
        assert data["findings"] == []
        # the one reasoned suppression (faults/watchdog fast-path read)
        assert [s["symbol"] for s in data["suppressed"]] == [
            "begin_cycle:_deadline_s"
        ]


# ---- happens-before race detector (ISSUE 13) ----


class TestRaceDetector:
    """Drive a private Detector engine directly — the global install is
    exercised by the CI suites under VTPU_RACE=1; these pin the vector-
    clock semantics themselves."""

    def _det(self):
        from volcano_tpu.analysis import race

        return race.Detector(restrict_to_pkg=False)

    def _run_in_thread(self, fn):
        t = threading.Thread(target=fn)
        t.start()
        t.join()

    def test_planted_unlocked_write_is_a_race(self):
        import sys

        det = self._det()
        obj = object()

        def writer():
            det.record(obj, "fixture.C.x", True, sys._getframe())

        det.record(obj, "fixture.C.x", True, sys._getframe())
        self._run_in_thread(writer)
        kinds = {r.kind for r in det.reports}
        assert kinds == {"write-write"}, [r.render() for r in det.reports]

    def test_lock_ordered_accesses_stay_clean(self):
        import sys

        det = self._det()
        obj = object()
        lock_id = 7001

        def locked(is_write):
            det.recv(lock_id)  # acquire
            det.record(obj, "fixture.C.x", is_write, sys._getframe())
            det.send(lock_id)  # release

        locked(True)
        self._run_in_thread(lambda: locked(True))
        self._run_in_thread(lambda: locked(False))
        assert det.reports == [], [r.render() for r in det.reports]

    def test_read_write_race_detected_and_read_clear(self):
        import sys

        det = self._det()
        obj = object()
        lock_id = 7002

        det.record(obj, "fixture.C.y", False, sys._getframe())

        def racing_write():
            det.record(obj, "fixture.C.y", True, sys._getframe())
            det.send(lock_id)  # release: publish for the next thread

        self._run_in_thread(racing_write)
        assert [r.kind for r in det.reports] == ["read-write"]

        # FastTrack read-clear: the racing write RESET the read set and
        # became the variable's write epoch.  A third thread's write
        # ordered after it (lock edge) has NO happens-before path to
        # the main thread's stale read — an engine that kept the read
        # set would re-report that read here.  Exactly one report, and
        # the two write sites differ so site-key dedup cannot mask a
        # cascade.
        def ordered_write():
            det.recv(lock_id)  # acquire: join the racing write's clock
            det.record(obj, "fixture.C.y", True, sys._getframe())

        self._run_in_thread(ordered_write)
        assert [r.kind for r in det.reports] == ["read-write"], (
            [r.render() for r in det.reports]
        )

    def test_scan_guarded_finds_declarations_and_waivers(self):
        from volcano_tpu.analysis import race

        decls = race.scan_guarded(find_root())
        symbols = {d.symbol for d in decls}
        # the first real race this detector caught, now lock-published
        assert "volcano_tpu.faults.plane:FaultPlane._points" in symbols
        assert "volcano_tpu.bus.replication:" \
               "ReplicationCoordinator._records" in symbols
        # every declaration names its lock
        assert all(d.lock for d in decls)

    def test_fault_plane_publication_race_fixed_and_pinned(self):
        """The first real race the HB detector caught on this tree:
        ``FaultPlane.__init__`` populated ``_points`` without the lock
        ``should()`` readers take, and ``get_plane()``'s fast path
        publishes the instance without synchronization.  Run the real
        instrumentation in a subprocess (the install patches process
        globals): the FIXED constructor is clean, and a racy twin that
        reverts the fix is flagged — the revert cannot land silently."""
        import subprocess
        import sys

        code = (
            "import sys, time\n"
            "from volcano_tpu.analysis import race\n"
            "race.install(restrict_to_pkg=False)\n"
            "# restrict off: the racy twin's constructor lives in this\n"
            "# script, not under volcano_tpu/\n"
            "import threading\n"
            "from volcano_tpu.faults import plane as plane_mod\n"
            "spec = plane_mod.parse_faults('seed=1;x.y=0.5')\n"
            "def publish_to_preexisting_reader(cls):\n"
            "    # the get_plane() shape: a reader thread ALIVE BEFORE\n"
            "    # construction picks the instance up through an\n"
            "    # unsynchronized global — only the lock inside the\n"
            "    # constructor can order the _points write before the\n"
            "    # reader's locked access\n"
            "    holder = {}\n"
            "    def reader():\n"
            "        while 'p' not in holder:\n"
            "            time.sleep(0.001)\n"
            "        holder['p'].should('x.y')\n"
            "    t = threading.Thread(target=reader)\n"
            "    t.start()\n"
            "    holder['p'] = cls(spec)\n"
            "    t.join()\n"
            "race.instrument_class(\n"
            "    race.get_detector(), plane_mod.FaultPlane, ['_points'],\n"
            "    'volcano_tpu.faults.plane.FaultPlane')\n"
            "publish_to_preexisting_reader(plane_mod.FaultPlane)\n"
            "fixed_clean = not race.report()['races']\n"
            "class RacyPlane(plane_mod.FaultPlane):\n"
            "    def __init__(self, spec):\n"
            "        self.spec = spec\n"
            "        self._lock = threading.Lock()\n"
            "        self._points = {}  # the pre-fix unlocked publication\n"
            "        for point in spec.rules:\n"
            "            self._points[point] = plane_mod._PointState(\n"
            "                __import__('random').Random(1))\n"
            "race.instrument_class(\n"
            "    race.get_detector(), RacyPlane, ['_points'],\n"
            "    'volcano_tpu.faults.plane.RacyPlane')\n"
            "publish_to_preexisting_reader(RacyPlane)\n"
            "racy_flagged = any(\n"
            "    'RacyPlane._points' in r['symbol']\n"
            "    for r in race.report()['races'])\n"
            "sys.exit(0 if (fixed_clean and racy_flagged) else\n"
            "         (1 if not fixed_clean else 2))\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=120,
        )
        assert proc.returncode == 0, (
            f"rc={proc.returncode} (1=fixed ctor raced, 2=racy twin "
            f"missed)\n{proc.stdout}\n{proc.stderr}"
        )

    def test_instrumented_class_attribute_round_trips(self):
        from volcano_tpu.analysis import race

        det = self._det()

        class Fixture:
            def __init__(self):
                self.val = 1

        n = race.instrument_class(det, Fixture, ["val"], "fixture.Fixture")
        assert n == 1
        f = Fixture()
        f.val = 5
        assert f.val == 5
        assert hasattr(f, "val")
        del f.val
        assert not hasattr(f, "val")
        assert det.n_accesses >= 3


# ---- deterministic interleaving explorer (ISSUE 13) ----


class TestExplorer:
    def test_schedule_systematic_prefixes_are_distinct(self):
        from volcano_tpu.analysis.explore import Schedule

        seen = set()
        for sid in range(16):
            s = Schedule(sid, systematic_below=16)
            digits = []
            while True:
                digits.append(s.choose(2))
                if s._forced is None:
                    break  # systematic prefix exhausted; random tail
            seen.add(tuple(digits))
        # the mixed-radix digits reconstruct the sid: every systematic
        # seed walks a distinct node of the decision tree
        assert len(seen) == 16

    def test_clean_protocols_hold_across_schedules(self):
        from volcano_tpu.analysis import explore

        results = explore.explore(
            ["election", "lease", "gang"], schedules=40
        )
        for name, r in results.items():
            assert r["violations"] == [], (name, r["violations"])
        assert sum(r["schedules"] for r in results.values()) == 120

    def test_planted_stale_election_is_caught(self):
        from volcano_tpu.analysis import explore

        r = explore.explore(
            ["election"], schedules=100, plant="stale-election"
        )["election"]
        assert r["violations"], "stale-election plant went undetected"
        v = r["violations"][0]
        assert "leader" in v["invariant"] or "acked" in v["invariant"]

    def test_planted_partial_commit_is_caught(self):
        from volcano_tpu.analysis import explore

        r = explore.explore(
            ["gang"], schedules=100, plant="partial-commit"
        )["gang"]
        assert r["violations"], "partial-commit plant went undetected"
        assert "partial gang" in r["violations"][0]["invariant"]

    def test_planted_lease_steal_is_caught(self):
        from volcano_tpu.analysis import explore

        r = explore.explore(
            ["lease"], schedules=60, plant="lease-steal"
        )["lease"]
        assert r["violations"], "lease-steal plant went undetected"
        assert "doubly owned" in r["violations"][0]["invariant"]

    def test_violating_schedule_replays_from_its_seed(self):
        from volcano_tpu.analysis import explore

        r = explore.explore(
            ["gang"], schedules=100, plant="partial-commit"
        )["gang"]
        v = r["violations"][0]
        replays = [
            explore.run_schedule(
                explore.GangMachine(), v["sid"], plant="partial-commit"
            )[0]
            for _ in range(2)
        ]
        for rv in replays:
            assert rv is not None
            assert rv.trace == v["trace"]      # bit-identical schedule
            assert rv.step == v["step"]
        # the same seed WITHOUT the plant holds the invariant
        clean, _steps = explore.run_schedule(explore.GangMachine(), v["sid"])
        assert clean is None

    def test_lease_machine_restores_patched_module_state(self):
        import time as real_time

        from volcano_tpu.analysis import explore
        from volcano_tpu.federation import leases

        explore.explore(["lease"], schedules=3, plant="lease-steal")
        assert leases.time is real_time
        # _expired is back to the real staticmethod semantics
        assert leases.ShardLeaseManager._expired(
            {"renewTime": 0.0, "leaseDurationSeconds": 1e12},
            real_time.time(),
        ) is False

    def test_vtctl_explore_quick_meets_the_schedule_floor(self):
        from volcano_tpu.cli.vtctl import main as vtctl_main

        out = io.StringIO()
        rc = vtctl_main(
            ["explore", "--quick", "--max-steps", "30"], out=out
        )
        text = out.getvalue()
        assert rc == 0, text
        total = int(text.rsplit("explore: ", 1)[1].split()[0])
        assert total >= 200  # the acceptance floor

    def test_explore_report_artifact_shape(self, tmp_path):
        from volcano_tpu.analysis.explore import main as explore_main

        report = tmp_path / "explore.json"
        rc = explore_main(
            ["--machine", "gang", "--schedules", "10",
             "--report", str(report)],
            out=io.StringIO(),
        )
        assert rc == 0
        data = json.loads(report.read_text())
        assert set(data) == {"gang"}
        assert data["gang"]["schedules"] == 10
        assert data["gang"]["violations"] == []


# ---- SRD005: README version-ladder doc drift ----


class TestVersionLadderDrift:
    def _ops(self):
        return {"create": 1, "commit_batch": 2, "txn_commit": 6}

    def test_stale_declared_version_flagged(self):
        readme = (
            "The wire protocol is at **VBUS version 3**: `create`, "
            "`commit_batch`, `txn_commit`.\n\n## Next\n"
        )
        findings = serde_drift._check_ladder(readme, self._ops())
        assert [f.code for f in findings] == ["SRD005"]
        assert "version 3" in findings[0].message
        assert "v6" in findings[0].message

    def test_unmentioned_op_flagged(self):
        readme = (
            "The wire protocol is at **VBUS version 6**: `create` and "
            "`commit_batch`.\n\n## Next\n"
        )
        findings = serde_drift._check_ladder(readme, self._ops())
        assert [f.symbol for f in findings] == ["txn_commit"]

    def test_mention_outside_the_ladder_section_does_not_count(self):
        readme = (
            "`txn_commit` is great.\n\n"
            "The wire protocol is at **VBUS version 6**: `create` and "
            "`commit_batch`.\n\n## Next\n"
        )
        findings = serde_drift._check_ladder(readme, self._ops())
        assert [f.symbol for f in findings] == ["txn_commit"]

    def test_missing_ladder_paragraph_flagged(self):
        findings = serde_drift._check_ladder("# hi\n", self._ops())
        assert [f.symbol for f in findings] == ["version-ladder"]

    def test_complete_ladder_is_clean(self):
        readme = (
            "The wire protocol is at **VBUS version 6**: `create`, "
            "`commit_batch` and `txn_commit`.\n\n## Next\n"
        )
        assert serde_drift._check_ladder(readme, self._ops()) == []

    def test_fenced_comment_does_not_end_the_section(self):
        # a `# comment` inside a ```bash example is not a heading: ops
        # named after the code block still count as in-section, and the
        # section still ends at the next REAL heading
        readme = (
            "The wire protocol is at **VBUS version 6**: `create` and "
            "`commit_batch`.\n\n"
            "```bash\n# a shell comment, not a heading\nvtctl bus "
            "status\n```\n\n"
            "`txn_commit` rides v6.\n\n## Next\n\n`unrelated` here.\n"
        )
        assert serde_drift._check_ladder(readme, self._ops()) == []
        ops = dict(self._ops(), unrelated=6)
        findings = serde_drift._check_ladder(readme, ops)
        assert [f.symbol for f in findings] == ["unrelated"]


# ---- conftest fd/socket-leak guard ----


class TestFdLeakGuard:
    def test_leaked_socket_is_flagged_and_close_clears_it(self):
        import socket

        from tests.conftest import _fd_table, _leaked_fds

        before = _fd_table()
        if before is None:
            pytest.skip("no /proc/self/fd on this platform")
        s = socket.socket()
        try:
            leaked = _leaked_fds(before)
            assert any(t.startswith("socket:") for _fd, t in leaked), leaked
        finally:
            s.close()
        assert _leaked_fds(before) == []

    def test_leaked_file_is_flagged(self, tmp_path):
        from tests.conftest import _fd_table, _leaked_fds

        before = _fd_table()
        if before is None:
            pytest.skip("no /proc/self/fd on this platform")
        f = open(tmp_path / "wal.log", "w")
        try:
            leaked = _leaked_fds(before)
            assert any(t.endswith("wal.log") for _fd, t in leaked), leaked
        finally:
            f.close()
        assert _leaked_fds(before) == []
