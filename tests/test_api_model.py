"""Job/Task/Node/Queue info model tests.

Mirrors pkg/scheduler/api/{job_info,node_info,namespace_info}_test.go.
"""

import pytest

from volcano_tpu.api import (
    JobInfo,
    NamespaceCollection,
    new_task_info,
    NodeInfo,
    TaskStatus,
)
from tests.builders import build_node, build_pod


class TestTaskInfo:
    def test_new_task_info_requests(self):
        pod = build_pod("ns1", "p1", "", {"cpu": "1", "memory": "1Gi"})
        task = new_task_info(pod)
        assert task.resreq.milli_cpu == 1000
        assert task.resreq.memory == 1024**3
        assert task.status == TaskStatus.Pending
        assert not task.best_effort

    def test_status_mapping(self):
        running = build_pod("ns1", "p1", "n1", {"cpu": "1"}, phase="Running")
        assert new_task_info(running).status == TaskStatus.Running
        bound = build_pod("ns1", "p2", "n1", {"cpu": "1"}, phase="Pending")
        assert new_task_info(bound).status == TaskStatus.Bound
        pending = build_pod("ns1", "p3", "", {"cpu": "1"}, phase="Pending")
        assert new_task_info(pending).status == TaskStatus.Pending

    def test_job_id_from_annotation(self):
        pod = build_pod("ns1", "p1", "", {"cpu": "1"}, group="pg1")
        assert new_task_info(pod).job == "ns1/pg1"


class TestJobInfo:
    def _job_with_tasks(self, statuses):
        job = JobInfo("ns1/j1", "j1", "ns1")
        job.min_available = 2
        for i, status in enumerate(statuses):
            pod = build_pod("ns1", f"p{i}", "n1" if status != TaskStatus.Pending else "", {"cpu": "1"})
            task = new_task_info(pod)
            task.status = status
            job.add_task_info(task)
        return job

    def test_add_task_updates_rollups(self):
        job = self._job_with_tasks([TaskStatus.Pending, TaskStatus.Running])
        assert job.allocated.milli_cpu == 1000  # only Running is occupied
        assert job.total_request.milli_cpu == 2000

    def test_ready_and_pipelined(self):
        job = self._job_with_tasks([TaskStatus.Running, TaskStatus.Running])
        assert job.ready()
        job2 = self._job_with_tasks([TaskStatus.Running, TaskStatus.Pipelined])
        assert not job2.ready()
        assert job2.pipelined()

    def test_valid_task_num_excludes_failed(self):
        job = self._job_with_tasks(
            [TaskStatus.Pending, TaskStatus.Failed, TaskStatus.Succeeded]
        )
        assert job.valid_task_num() == 2

    def test_update_task_status_moves_buckets(self):
        job = self._job_with_tasks([TaskStatus.Pending])
        task = next(iter(job.tasks.values()))
        job.update_task_status(task, TaskStatus.Allocated)
        assert TaskStatus.Pending not in job.task_status_index
        assert job.allocated.milli_cpu == 1000

    def test_delete_task(self):
        job = self._job_with_tasks([TaskStatus.Running])
        task = next(iter(job.tasks.values()))
        job.delete_task_info(task)
        assert not job.tasks
        assert job.allocated.milli_cpu == 0


class TestNodeInfo:
    def test_add_remove_task_accounting(self):
        ni = NodeInfo(build_node("n1", {"cpu": "4", "memory": "8Gi"}))
        pod = build_pod("ns1", "p1", "n1", {"cpu": "1", "memory": "1Gi"})
        task = new_task_info(pod)
        task.status = TaskStatus.Running
        ni.add_task(task)
        assert ni.idle.milli_cpu == 3000
        assert ni.used.milli_cpu == 1000
        ni.remove_task(task)
        assert ni.idle.milli_cpu == 4000
        assert ni.used.milli_cpu == 0

    def test_releasing_and_pipelined_future_idle(self):
        ni = NodeInfo(build_node("n1", {"cpu": "4", "memory": "8Gi"}))
        releasing = new_task_info(build_pod("ns1", "r", "n1", {"cpu": "2"}))
        releasing.status = TaskStatus.Releasing
        ni.add_task(releasing)
        pipelined = new_task_info(build_pod("ns1", "q", "n1", {"cpu": "1"}))
        pipelined.status = TaskStatus.Pipelined
        ni.add_task(pipelined)
        # idle=2, releasing=2, pipelined=1 → future idle cpu = 3
        assert ni.idle.milli_cpu == 2000
        assert ni.future_idle().milli_cpu == 3000

    def test_over_allocate_marks_not_ready(self):
        ni = NodeInfo(build_node("n1", {"cpu": "1", "memory": "1Gi"}))
        big = new_task_info(build_pod("ns1", "big", "n1", {"cpu": "2"}))
        big.status = TaskStatus.Running
        with pytest.raises(ValueError):
            ni.add_task(big)
        assert not ni.ready()

    def test_duplicate_add_rejected(self):
        ni = NodeInfo(build_node("n1", {"cpu": "4", "memory": "8Gi"}))
        task = new_task_info(build_pod("ns1", "p1", "n1", {"cpu": "1"}))
        ni.add_task(task)
        with pytest.raises(ValueError):
            ni.add_task(task)


def test_namespace_collection_weight():
    col = NamespaceCollection("ns1")
    assert col.snapshot().get_weight() == 1
    col.update("quota-a", 5)
    col.update("quota-b", 3)
    assert col.snapshot().get_weight() == 5
    col.delete("quota-a")
    assert col.snapshot().get_weight() == 3
