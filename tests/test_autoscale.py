"""SLO-driven shard autoscaling (ISSUE 14).

The controller half of "elastic operations": the member holding shard
0's lease windows the fleet's submit→bind p99 and pending depth (both
piggybacked on the lease-map heartbeats) and CASes one-step shard-count
changes into the map with hysteresis, sustain, and cooldown; every
member's lease manager then ADOPTS the map's count (elastic mode)
through the same absorb/shed machinery every rebalance uses.

Pinned here: the pure decision function's hysteresis band, the
windowed-latency discipline (an old spike can never hold the fleet
scaled up), sustain/cooldown damping, the CAS commit's exact map
mutation (grown slices start unheld, shrunk slices disappear), elastic
adoption end-to-end over a real in-process lease plane, the metrics
export, and the `vtctl shards` autoscale line.  The full OS-process
drill is `bench/loadgen.py --ramp` (the `elastic-slo` CI artifact).
"""

import io
import json
import threading
import time

import pytest

from volcano_tpu.apis import core
from volcano_tpu.client.apiserver import APIServer
from volcano_tpu.federation.autoscale import (
    AutoscalePolicy,
    ShardAutoscaler,
    decide,
    delta_histogram,
    latency_snapshot,
)
from volcano_tpu.federation.leases import (
    NAMESPACE,
    SHARD_MAP_KEY,
    SHARD_MAP_NAME,
    ShardLeaseManager,
    read_shard_map,
)
from volcano_tpu.metrics import metrics
from volcano_tpu.metrics.scrape import histogram_quantile, merge_histograms


def _wait(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _map_cm(rec):
    return core.ConfigMap(
        metadata=core.ObjectMeta(name=SHARD_MAP_NAME,
                                 namespace=NAMESPACE),
        data={SHARD_MAP_KEY: json.dumps(rec)},
    )


def _rec(n_shards=1, members=("m0",), stats=None, autoscale=None):
    shards = {
        str(i): {"holder": "m0", "renewTime": time.time(),
                 "leaseDurationSeconds": 2.0}
        for i in range(n_shards)
    }
    rec = {
        "nShards": n_shards,
        "members": {m: {"heartbeat": time.time(),
                        "leaseDurationSeconds": 2.0} for m in members},
        "shards": shards,
        "stats": stats or {},
    }
    if autoscale is not None:
        rec["autoscale"] = autoscale
    return rec


def _latency(count, le_ms, total_ms):
    """A cumulative snapshot whose observations all sit in the
    (le_ms/10, le_ms] bucket — p99 lands inside that bucket."""
    return {
        "buckets": [(str(le_ms / 10), 0.0), (str(le_ms), float(count)),
                    ("+Inf", float(count))],
        "sum": float(total_ms),
        "count": float(count),
    }


class _State:
    """state stub: owns_shard(0) answers the controller-placement rule."""

    def __init__(self, owns=True):
        self.owns = owns

    def owns_shard(self, shard):
        return self.owns and shard == 0


POLICY = AutoscalePolicy(
    min_shards=1, max_shards=4, up_p99_ms=500.0, up_pending=16,
    down_p99_ms=50.0, down_pending=4, sustain=2, cooldown_s=0.0,
    eval_period_s=0.05,
)


class TestDecide:
    def test_up_on_p99_breach(self):
        assert decide(POLICY, 1, 900.0, 0, True) == "up"

    def test_up_on_pending_breach_without_latency(self):
        # queue depth catches the saturated-but-not-yet-slow ramp
        assert decide(POLICY, 1, 0.0, 17, False) == "up"

    def test_pending_bar_is_per_shard(self):
        assert decide(POLICY, 2, 0.0, 17, False) is None
        assert decide(POLICY, 2, 0.0, 40, False) == "up"

    def test_hysteresis_band_holds(self):
        # between the bars: no decision in either direction
        assert decide(POLICY, 2, 200.0, 8, True) is None

    def test_down_needs_both_signals_low(self):
        assert decide(POLICY, 2, 30.0, 2, True) == "down"
        # pending above the DOWN bar (but under the up bar): hold
        assert decide(POLICY, 2, 30.0, 10, True) is None
        assert decide(POLICY, 2, 200.0, 2, True) is None   # p99 not low

    def test_idle_fleet_scales_down(self):
        # no latency window at all + nothing pending IS the idle case
        assert decide(POLICY, 2, 0.0, 0, False) == "down"

    def test_min_max_clamps(self):
        assert decide(POLICY, POLICY.max_shards, 900.0, 999, True) is None
        assert decide(POLICY, POLICY.min_shards, 0.0, 0, False) is None

    def test_policy_validates_bounds(self):
        with pytest.raises(ValueError):
            AutoscalePolicy(min_shards=0)
        with pytest.raises(ValueError):
            AutoscalePolicy(min_shards=3, max_shards=2)


class TestWindowedLatency:
    def test_delta_is_pointwise_difference(self):
        prev = _latency(10, 1000, 9000)
        cur = {
            "buckets": [("100", 5.0), ("1000", 15.0), ("+Inf", 15.0)],
            "sum": 9400.0,
            "count": 15.0,
        }
        win = delta_histogram(prev, cur)
        assert win["count"] == 5.0
        assert win["sum"] == 400.0
        assert dict(win["buckets"])["100"] == 5.0
        # the delta'd window is scrape-shaped: the shared quantile
        # helpers consume it unchanged
        assert histogram_quantile(merge_histograms([win]), 0.99) <= 100.0

    def test_first_sight_is_full_window(self):
        cur = _latency(10, 1000, 9000)
        assert delta_histogram(None, cur) == cur

    def test_member_restart_resets_window(self):
        prev = _latency(100, 1000, 90000)
        cur = _latency(3, 1000, 2700)  # counter went BACKWARD: restart
        assert delta_histogram(prev, cur) == cur

    def test_latency_snapshot_matches_scrape_shape(self):
        metrics.observe_submit_to_bind(12.5)
        snap = latency_snapshot()
        assert snap is not None and snap["count"] >= 1
        assert snap["buckets"][-1][0] == "+Inf"
        assert histogram_quantile(snap, 0.5) > 0


class TestOwnedPending:
    def test_per_member_reports_partition_the_backlog(self):
        """At n_shards == 1 every member's raw pending view IS the
        whole fleet backlog (the filter forwards everything) — the
        published signal must be scoped to OWNED home shards so a
        pre-provisioned standby reports 0 and summing per-member
        reports never multiplies the backlog."""
        from volcano_tpu.federation.autoscale import owned_pending
        from volcano_tpu.federation.sharding import home_shard

        view = [
            {"job_id": f"ns/job{i}", "tasks": [object()] * 2}
            for i in range(8)
        ]
        # one shard: the holder reports everything, a standby nothing
        assert owned_pending(view, {0}, 1) == 16
        assert owned_pending(view, set(), 1) == 0
        # two shards: the two members' reports partition the total
        a = owned_pending(view, {0}, 2)
        b = owned_pending(view, {1}, 2)
        assert a + b == 16
        assert a == sum(
            2 for i in range(8) if home_shard("ns", f"job{i}", 2) == 0
        )


class TestAutoscalerTick:
    def _scaler(self, api, policy=POLICY, owns=True):
        return ShardAutoscaler(api, _State(owns), "m0", policy=policy)

    def test_sustained_pending_breach_commits_one_step_up(self):
        api = APIServer()
        api.create(_map_cm(_rec(
            stats={"m0": {"pendingTasks": 40}},
        )))
        sc = self._scaler(api)
        sc._tick()  # streak 1 of 2: no commit yet (sustain damping)
        assert read_shard_map(api)["nShards"] == 1
        sc._tick()  # streak 2: commit
        rec = read_shard_map(api)
        assert rec["nShards"] == 2
        # the grown slice starts UNHELD at renewTime 0 — infinitely
        # orphaned, so the expiry backstop deals it out within a TTL
        assert rec["shards"]["1"] == {
            "holder": "", "renewTime": 0.0, "leaseDurationSeconds": 0.0,
        }
        blob = rec["autoscale"]
        assert blob["direction"] == "up" and blob["target"] == 2
        assert blob["decisions"] == 1
        assert sc.counters() == {"up": 1}
        assert ('volcano_shard_autoscale_decisions_total'
                '{direction="up"}') in metrics.registry.render()

    def test_p99_breach_scales_up_and_window_resets(self):
        api = APIServer()
        api.create(_map_cm(_rec(
            stats={"m0": {"pendingTasks": 0,
                          "latency": _latency(50, 1000, 45000)}},
        )))
        sc = self._scaler(api)
        sc._tick()  # first sight: a full 50-obs slow window, streak 1
        # load continues — the member's CUMULATIVE histogram advances,
        # so the next delta is another 50 slow observations
        cm = api.get("ConfigMap", NAMESPACE, SHARD_MAP_NAME)
        rec = json.loads(cm.data[SHARD_MAP_KEY])
        rec["stats"]["m0"]["latency"] = _latency(100, 1000, 90000)
        cm.data = {SHARD_MAP_KEY: json.dumps(rec)}
        api.compare_and_update(cm, cm.metadata.resource_version)
        sc._tick()  # streak 2: commit up
        assert read_shard_map(api)["nShards"] == 2
        # the stream stops: the SAME cumulative snapshot deltas to an
        # EMPTY window — the stale spike cannot hold the fleet up, and
        # with pending at 0 the idle fleet walks back DOWN
        sc._tick()
        sc._tick()
        assert read_shard_map(api)["nShards"] == 1
        assert sc.counters() == {"up": 1, "down": 1}

    def test_down_removes_the_shrunk_slice(self):
        api = APIServer()
        api.create(_map_cm(_rec(
            n_shards=2, stats={"m0": {"pendingTasks": 0}},
        )))
        sc = self._scaler(api)
        sc._tick()
        sc._tick()
        rec = read_shard_map(api)
        assert rec["nShards"] == 1
        assert "1" not in rec["shards"]
        assert rec["autoscale"]["direction"] == "down"

    def test_cooldown_blocks_consecutive_changes(self):
        api = APIServer()
        api.create(_map_cm(_rec(stats={"m0": {"pendingTasks": 40}})))
        policy = AutoscalePolicy(
            min_shards=1, max_shards=4, up_pending=16, sustain=1,
            cooldown_s=60.0,
        )
        sc = self._scaler(api, policy=policy)
        sc._tick()
        assert read_shard_map(api)["nShards"] == 2  # first change free
        sc._tick()
        sc._tick()
        assert read_shard_map(api)["nShards"] == 2  # cooldown holds
        # the stamp lives IN THE MAP: a migrated controller (fresh
        # object, same map) keeps the cooldown
        sc2 = self._scaler(api, policy=policy)
        sc2._tick()
        assert read_shard_map(api)["nShards"] == 2

    def test_non_holder_is_inert_and_drops_streak(self):
        api = APIServer()
        api.create(_map_cm(_rec(stats={"m0": {"pendingTasks": 40}})))
        sc = self._scaler(api, owns=False)
        sc._tick()
        sc._tick()
        assert read_shard_map(api)["nShards"] == 1
        # a controller that migrates HERE must earn a fresh sustain
        # window, not inherit a half-counted one
        assert sc._streak == 0 and sc._streak_dir is None

    def test_dead_member_stats_are_not_load(self):
        api = APIServer()
        api.create(_map_cm(_rec(
            members=("m0",),
            stats={"m0": {"pendingTasks": 0},
                   "ghost": {"pendingTasks": 999}},
        )))
        sc = self._scaler(api)
        sig = sc._signals(read_shard_map(api))
        assert sig["pending"] == 0

    def test_commit_traces_a_span_when_recorder_on(self):
        from volcano_tpu import obs

        api = APIServer()
        api.create(_map_cm(_rec(stats={"m0": {"pendingTasks": 40}})))
        policy = AutoscalePolicy(min_shards=1, max_shards=4,
                                 up_pending=16, sustain=1,
                                 cooldown_s=0.0)
        sc = self._scaler(api, policy=policy)
        obs.enable(api, identity="autoscale-test")
        try:
            sc._tick()
        finally:
            obs.disable()
        assert read_shard_map(api)["nShards"] == 2

    def test_lost_cas_is_one_retry_tick(self):
        api = APIServer()
        api.create(_map_cm(_rec(stats={"m0": {"pendingTasks": 40}})))
        policy = AutoscalePolicy(min_shards=1, max_shards=4,
                                 up_pending=16, sustain=1, cooldown_s=0.0)
        sc = self._scaler(api, policy=policy)
        real_cau = api.compare_and_update
        calls = []

        def racing_cau(obj, rv):
            if not calls:
                calls.append(1)
                from volcano_tpu.client.apiserver import ConflictError

                raise ConflictError("lease renewal won the rv")
            return real_cau(obj, rv)

        api.compare_and_update = racing_cau
        sc._tick()
        assert read_shard_map(api)["nShards"] == 1  # lost the race
        sc._tick()
        assert read_shard_map(api)["nShards"] == 2  # next tick lands


class TestElasticAdoption:
    def test_members_adopt_a_grown_map_and_hold_every_slice(self):
        """End-to-end over a real in-process lease plane: two elastic
        members form a 1-shard federation; a committed autoscale
        decision grows the map to 2; both members re-key and the grown
        slice is absorbed — every slice held, by distinct members."""
        api = APIServer()
        resizes = []
        mgrs = [
            ShardLeaseManager(
                api, f"m{i}", 1, lease_duration=0.8, retry_period=0.1,
                elastic=True,
                on_resize=lambda n, i=i: resizes.append((i, n)),
            )
            for i in range(2)
        ]
        try:
            for m in mgrs:
                m.start()
            assert _wait(lambda: (read_shard_map(api) or {}).get(
                "shards", {}).get("0", {}).get("holder"), timeout=10.0)

            # a committed scale-up: nShards 2, grown slice unheld (the
            # exact mutation TestAutoscalerTick pins on the controller)
            def grow():
                cm = api.get("ConfigMap", NAMESPACE, SHARD_MAP_NAME)
                rec = json.loads(cm.data[SHARD_MAP_KEY])
                rec["nShards"] = 2
                rec["shards"]["1"] = {
                    "holder": "", "renewTime": 0.0,
                    "leaseDurationSeconds": 0.0,
                }
                rec["autoscale"] = {"enabled": True, "target": 2,
                                    "lastChange": time.time(),
                                    "direction": "up", "reason": "test",
                                    "decisions": 1}
                cm.data = {SHARD_MAP_KEY: json.dumps(rec, sort_keys=True)}
                from volcano_tpu.client.apiserver import ConflictError

                try:
                    api.compare_and_update(
                        cm, cm.metadata.resource_version
                    )
                    return True
                except ConflictError:
                    return False

            assert _wait(grow, timeout=5.0)

            def both_held():
                rec = read_shard_map(api) or {}
                shards = rec.get("shards", {})
                if rec.get("nShards") != 2 or len(shards) != 2:
                    return False
                holders = {e.get("holder") for e in shards.values()}
                return (
                    all(h for h in holders)
                    and holders == {"m0", "m1"}
                )

            assert _wait(both_held, timeout=15.0), read_shard_map(api)
            assert any(n == 2 for _, n in resizes)
        finally:
            for m in mgrs:
                m.stop(release=True)


class TestElasticRekeyUnderChurn:
    def test_no_job_lost_across_a_scale_up_rekey(self, tmp_path):
        """The in-process half of the ``loadgen --ramp`` drill: two
        FEDERATED members (real caches, filters, leases, spillover)
        over a real TCP bus; the shard map grows 1 -> 2 (the exact
        mutation the autoscaler commits) WHILE jobs keep arriving.
        Both members release-and-re-key; every job submitted before,
        during, and after the re-key still binds — the relist-on-
        acquire discipline covers the windows where a member owns
        nothing."""
        from volcano_tpu.bus.remote import RemoteAPIServer
        from volcano_tpu.bus.server import BusServer
        from volcano_tpu.client import KubeClient, VolcanoClient
        from volcano_tpu.federation import FederatedScheduler
        from tests.builders import (
            build_node,
            build_pod,
            build_pod_group,
            build_queue,
        )

        conf = tmp_path / "conf.yaml"
        conf.write_text(
            'actions: "enqueue, allocate"\n'
            "tiers:\n"
            "- plugins:\n"
            "  - name: priority\n"
            "  - name: gang\n"
            "- plugins:\n"
            "  - name: drf\n"
            "  - name: predicates\n"
            "  - name: proportion\n"
            "  - name: nodeorder\n"
            "  - name: binpack\n"
        )
        api = APIServer()
        bus = BusServer(api).start()
        kube = KubeClient(api)
        vc = VolcanoClient(api)
        vc.create_queue(build_queue("default"))
        for k in range(8):
            kube.create_node(build_node(f"n{k:03d}",
                                        {"cpu": "4", "memory": "64Gi"}))
        # autoscale present (=> elastic leases) but the controller is
        # inert: the test drives the map transition deterministically
        inert = AutoscalePolicy(up_pending=10**6, up_p99_ms=10**9,
                                down_pending=0, sustain=10**6)
        remotes, feds = [], []
        submitted = [0]

        def submit(name):
            vc.create_pod_group(build_pod_group("ns", name, 1))
            kube.create_pod(build_pod(
                "ns", f"{name}-t0", "",
                {"cpu": "1", "memory": "1Gi"}, group=name,
            ))
            submitted[0] += 1

        try:
            for i in range(2):
                r = RemoteAPIServer(f"tcp://127.0.0.1:{bus.port}",
                                    timeout=5.0)
                assert r.wait_ready(10)
                remotes.append(r)
                feds.append(FederatedScheduler(
                    r, f"m{i}", 1, scheduler_conf_path=str(conf),
                    lease_duration=2.0, lease_retry_period=0.2,
                    spill_after=1, autoscale=inert,
                ).start())

            def cycle():
                for f in feds:
                    try:
                        f.scheduler.run_once()
                    except Exception:  # noqa: BLE001 — daemon loops log
                        pass

            assert _wait(lambda: (read_shard_map(api) or {}).get(
                "shards", {}).get("0", {}).get("holder"), timeout=10.0)
            for i in range(4):
                submit(f"pre{i}")
            assert _wait(
                lambda: (cycle() or True) and all(
                    p.spec.node_name for p in kube.list_pods("ns")
                ),
                timeout=30.0, interval=0.05,
            )

            # arrivals keep landing while the map grows
            stop = threading.Event()

            def churn():
                i = 0
                while not stop.is_set() and i < 16:
                    submit(f"mid{i}")
                    i += 1
                    time.sleep(0.05)

            t = threading.Thread(target=churn, daemon=True)
            t.start()

            def grow():
                cm = api.get("ConfigMap", NAMESPACE, SHARD_MAP_NAME)
                rec = json.loads(cm.data[SHARD_MAP_KEY])
                rec["nShards"] = 2
                rec["shards"]["1"] = {"holder": "", "renewTime": 0.0,
                                      "leaseDurationSeconds": 0.0}
                cm.data = {SHARD_MAP_KEY: json.dumps(rec,
                                                     sort_keys=True)}
                from volcano_tpu.client.apiserver import ApiError

                try:
                    api.compare_and_update(
                        cm, cm.metadata.resource_version
                    )
                    return True
                except ApiError:
                    return False

            assert _wait(grow, timeout=5.0)
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline:
                cycle()
                time.sleep(0.02)
            stop.set()
            t.join(timeout=5)
            submit("post0")

            def all_placed():
                cycle()
                pods = kube.list_pods("ns")
                return len(pods) == submitted[0] and all(
                    p.spec.node_name for p in pods
                )

            assert _wait(all_placed, timeout=60.0, interval=0.05), (
                [p.metadata.name for p in kube.list_pods("ns")
                 if not p.spec.node_name],
                read_shard_map(api),
            )
            # both members ended re-keyed: the map's two slices held
            rec = read_shard_map(api)
            assert rec["nShards"] == 2
        finally:
            for f in feds:
                try:
                    f.stop()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            for r in remotes:
                r.close()
            bus.stop()


class TestVtctlAutoscaleLine:
    def test_shards_renders_last_decision(self):
        from volcano_tpu.cli.vtctl import main as vtctl_main

        api = APIServer()
        api.create(_map_cm(_rec(
            n_shards=2,
            autoscale={"enabled": True, "target": 2,
                       "lastChange": 1000.0, "direction": "up",
                       "reason": "p99=900ms pending=40 members=2",
                       "decisions": 3},
        )))
        out = io.StringIO()
        assert vtctl_main(["shards"], api=api, out=out) == 0
        assert ("Autoscale:          target 2 (up: "
                "p99=900ms pending=40 members=2; decisions 3)"
                in out.getvalue())
