"""Blocked kernel equivalence: schedule_pass_blocked must reproduce the
plain sequential scan's assignments exactly — including tie-breaks,
gang discards, taints/labels, and capacity-pressure stop/fallback paths."""

from __future__ import annotations

import pytest

from volcano_tpu.ops.blocked import run_packed_blocked
from volcano_tpu.ops.kernels import run_packed
from volcano_tpu.ops.synthetic import generate_snapshot


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_blocked_matches_plain_random(seed):
    snap = generate_snapshot(n_tasks=300, n_nodes=50, gang_size=4, seed=seed)
    assert (run_packed(snap) == run_packed_blocked(snap, block_size=16, top_k=4)).all()


def test_blocked_matches_plain_with_predicates():
    snap = generate_snapshot(
        n_tasks=256, n_nodes=64, gang_size=8, seed=3,
        label_classes=4, taint_fraction=0.25,
    )
    assert (run_packed(snap) == run_packed_blocked(snap, block_size=32, top_k=4)).all()


def test_blocked_matches_plain_capacity_pressure():
    """Tight capacity: many infeasible tasks, gang discards, and frequent
    candidate-set misses (stop/full-step fallbacks)."""
    snap = generate_snapshot(
        n_tasks=400, n_nodes=16, gang_size=5, seed=4,
        node_cpu_milli=16_000, node_mem_mib=32_768,
    )
    plain = run_packed(snap)
    blocked = run_packed_blocked(snap, block_size=32, top_k=2)  # tiny K forces stops
    assert (plain == blocked).all()
    assert (plain == -1).any()  # pressure actually discards gangs


def test_blocked_matches_plain_single_node():
    snap = generate_snapshot(n_tasks=64, n_nodes=1, gang_size=2, seed=5)
    assert (run_packed(snap) == run_packed_blocked(snap, block_size=8, top_k=2)).all()
