"""Bus conformance + resilience: the in-process ↔ remote backend swap.

The contract: ``bus.RemoteAPIServer`` against a ``bus.BusServer`` is
indistinguishable from the in-process ``client.apiserver.APIServer`` —
same CRUD/CAS/list semantics, same watch event streams, same
owner-reference cascade, same admission chain.  The conformance suite
runs every assertion over BOTH backends; the resilience suite covers
what only exists across a network: reconnect with resume, server
restart with 410-Gone relist (no missed or duplicated events), backlog
overflow, bookmarks, and cross-process leader election.
"""

from __future__ import annotations

import time

import pytest

from volcano_tpu.apis import batch, core, scheduling
from volcano_tpu.bus import BusError, BusServer, parse_bus_url, RemoteAPIServer
from volcano_tpu.client.apiserver import (
    AdmissionError,
    AlreadyExistsError,
    APIServer,
    ConflictError,
    NotFoundError,
)
from volcano_tpu.metrics import metrics


def _wait(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _counter(name_suffix: str) -> float:
    with metrics.registry._lock:
        return sum(
            v for (name, _labels), v in metrics.registry._counters.items()
            if name.endswith(name_suffix)
        )


def _cm(name, ns="ns", data=None):
    return core.ConfigMap(
        metadata=core.ObjectMeta(name=name, namespace=ns), data=data or {}
    )


class _Backend:
    """One bus backend under test: the authoritative store plus the
    client-side view (identical for in-process; TCP for remote)."""

    def __init__(self, kind: str):
        self.kind = kind
        self.api = APIServer()
        self.server = None
        self._clients = []
        if kind == "remote":
            self.server = BusServer(self.api, bookmark_interval=0.1).start()
            self.client = self.new_client()
        else:
            self.client = self.api

    def new_client(self):
        """A fresh connection (the same store for in-process)."""
        if self.kind != "remote":
            return self.api
        c = RemoteAPIServer(
            f"tcp://127.0.0.1:{self.server.port}", timeout=5,
            reconnect_min=0.02,
        )
        assert c.wait_ready(5)
        self._clients.append(c)
        return c

    def settle(self, pred, timeout=10.0) -> bool:
        """Wait until ``pred()`` holds — immediate for in-process, a
        network round-trip plus dispatch for remote."""
        return _wait(pred, timeout=timeout)

    def close(self):
        for c in self._clients:
            c.close()
        if self.server is not None:
            self.server.stop()


@pytest.fixture(params=["in-process", "remote"])
def backend(request):
    b = _Backend(request.param)
    yield b
    b.close()


class TestBusConformance:
    def test_create_get_list_delete_roundtrip(self, backend):
        api = backend.client
        api.create(_cm("a", data={"k": "v"}))
        api.create(_cm("b"))
        api.create(_cm("other-ns", ns="ns2"))

        got = api.get("ConfigMap", "ns", "a")
        assert got.data == {"k": "v"}
        assert got.metadata.resource_version == 1
        assert got.metadata.creation_timestamp > 0
        assert api.get("ConfigMap", "ns", "missing") is None

        assert [o.metadata.name for o in api.list("ConfigMap", "ns")] == ["a", "b"]
        assert len(api.list("ConfigMap")) == 3

        with pytest.raises(AlreadyExistsError):
            api.create(_cm("a"))

        old = api.delete("ConfigMap", "ns", "a")
        assert old.data == {"k": "v"}
        with pytest.raises(NotFoundError):
            api.delete("ConfigMap", "ns", "a")
        # the authoritative store agrees with the client's view
        assert backend.api.get("ConfigMap", "ns", "a") is None

    def test_update_cas_semantics(self, backend):
        api = backend.client
        api.create(_cm("x", data={"n": "0"}))
        got = api.get("ConfigMap", "ns", "x")
        rv0 = got.metadata.resource_version
        got.data = {"n": "1"}
        updated = api.compare_and_update(got, rv0)
        assert updated.metadata.resource_version > rv0

        # stale CAS loses — the invariant leader election rides on
        stale = api.get("ConfigMap", "ns", "x")
        stale.data = {"n": "2"}
        with pytest.raises(ConflictError):
            api.compare_and_update(stale, rv0)

        with pytest.raises(NotFoundError):
            api.update(_cm("never-created"))

        # unconditional update + status subresource
        fresh = api.get("ConfigMap", "ns", "x")
        fresh.data = {"n": "3"}
        api.update(fresh)
        fresh = api.get("ConfigMap", "ns", "x")
        api.update_status(fresh)
        assert backend.api.get("ConfigMap", "ns", "x").data == {"n": "3"}

    def test_watch_initial_and_live_events(self, backend):
        api = backend.client
        api.create(_cm("pre"))
        events = []
        api.watch("ConfigMap",
                  lambda e, o, n: events.append((e, (n or o).metadata.name)))
        assert backend.settle(lambda: ("ADDED", "pre") in events)

        api.create(_cm("live"))
        got = api.get("ConfigMap", "ns", "live")
        got.data = {"touched": "yes"}
        api.update(got)
        api.delete("ConfigMap", "ns", "live")
        expected = [("ADDED", "pre"), ("ADDED", "live"),
                    ("MODIFIED", "live"), ("DELETED", "live")]
        assert backend.settle(lambda: events == expected), events

    def test_watch_without_initial(self, backend):
        api = backend.client
        api.create(_cm("pre"))
        events = []
        api.watch("ConfigMap",
                  lambda e, o, n: events.append((e, (n or o).metadata.name)),
                  send_initial=False)
        api.create(_cm("post"))
        assert backend.settle(lambda: ("ADDED", "post") in events)
        assert ("ADDED", "pre") not in events

    def test_owner_reference_cascade(self, backend):
        """Deleting an owner takes controller-owned children with it,
        with DELETED notifications for every casualty — identically
        through both backends (the GC semantics controllers rely on)."""
        api = backend.client
        job = batch.Job(
            metadata=core.ObjectMeta(name="own", namespace="ns", uid="uid-own"),
            spec=batch.JobSpec(min_available=1),
        )
        api.create(job)
        ref = core.OwnerReference(kind="Job", name="own", uid="uid-own",
                                  controller=True)
        pod = core.Pod(
            metadata=core.ObjectMeta(name="own-p0", namespace="ns",
                                     owner_references=[ref]),
            spec=core.PodSpec(containers=[core.Container(image="busybox")]),
        )
        api.create(pod)
        pg = scheduling.PodGroup(
            metadata=core.ObjectMeta(name="own", namespace="ns",
                                     owner_references=[ref]),
        )
        api.create(pg)

        deleted = []
        api.watch("Pod", lambda e, o, n: deleted.append(("Pod", o.metadata.name))
                  if e == "DELETED" else None, send_initial=False)
        api.watch("PodGroup",
                  lambda e, o, n: deleted.append(("PodGroup", o.metadata.name))
                  if e == "DELETED" else None, send_initial=False)

        api.delete("Job", "ns", "own")
        assert backend.settle(
            lambda: api.get("Pod", "ns", "own-p0") is None
            and api.get("PodGroup", "ns", "own") is None
        )
        assert backend.settle(
            lambda: set(deleted) == {("Pod", "own-p0"), ("PodGroup", "own")}
        ), deleted

    def test_admission_mutate_and_deny(self, backend):
        """The admission chain runs wherever it is registered: in-process
        hooks for the local store, review round-trips over the wire for
        the remote backend (the webhook deployment)."""
        reviewer = backend.new_client()

        def hook(operation, cm):
            if cm.metadata.name == "forbidden":
                raise AdmissionError("name is forbidden")
            cm.data["admitted-by"] = "hook"
            return cm

        reviewer.register_admission("ConfigMap", "CREATE", hook)
        if backend.kind == "remote":
            # registration is async relative to other connections: wait
            # until the server forwards reviews before asserting
            assert _wait(lambda: (backend.server._admission.get(
                ("ConfigMap", "CREATE")) or []) != [], 5)

        api = backend.client
        api.create(_cm("fine"))
        assert backend.api.get("ConfigMap", "ns", "fine").data["admitted-by"] == "hook"
        with pytest.raises(AdmissionError, match="forbidden"):
            api.create(_cm("forbidden"))
        assert backend.api.get("ConfigMap", "ns", "forbidden") is None


class TestBusResilience:
    """Remote-only semantics: what the network adds."""

    def test_parse_bus_url(self):
        assert parse_bus_url("tcp://10.0.0.1:7180") == ("10.0.0.1", 7180)
        assert parse_bus_url("localhost:99") == ("localhost", 99)
        with pytest.raises(ValueError):
            parse_bus_url("http://x:1")
        with pytest.raises(ValueError):
            parse_bus_url("tcp://no-port")

    def test_unreachable_bus_raises_bus_error(self):
        c = RemoteAPIServer("tcp://127.0.0.1:1", timeout=0.3,
                            reconnect_min=0.05)
        try:
            with pytest.raises(BusError):
                c.get("ConfigMap", "ns", "x")
        finally:
            c.close()

    def test_reconnect_resumes_watch_without_relist(self):
        """A connection blip replays the missed suffix from the server
        backlog: no relist, no duplicates, nothing missed."""
        api = APIServer()
        srv = BusServer(api, bookmark_interval=0.1).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5,
                                 reconnect_min=0.02)
        try:
            events = []
            client.watch("ConfigMap",
                         lambda e, o, n: events.append((e, (n or o).metadata.name)))
            client.create(_cm("a"))
            assert _wait(lambda: len(events) == 1)

            relists_before = _counter("bus_relists_total")
            reconnects_before = _counter("bus_reconnects_total")
            client._sock.close()  # the blip
            api.create(_cm("b"))  # mutation while the client is dark
            assert _wait(lambda: ("ADDED", "b") in events, 8), events
            assert events == [("ADDED", "a"), ("ADDED", "b")], events
            assert _counter("bus_relists_total") == relists_before
            assert _counter("bus_reconnects_total") > reconnects_before
        finally:
            client.close()
            srv.stop()

    def test_server_restart_relists_no_missed_no_duplicated(self):
        """Kill-and-resume: the server dies mid-stream, the store
        mutates while it is down, a new incarnation (new epoch) comes up
        on the same port.  The client's resume is answered 410-Gone, it
        relists, and the handler sees exactly the missed deltas — no
        duplicates, no gaps — with bus_relists_total incremented."""
        api = APIServer()
        srv = BusServer(api, bookmark_interval=0.1).start()
        port = srv.port
        client = RemoteAPIServer(f"tcp://127.0.0.1:{port}", timeout=5,
                                 reconnect_min=0.02)
        try:
            events = []
            client.watch("ConfigMap",
                         lambda e, o, n: events.append((e, (n or o).metadata.name)))
            client.create(_cm("keep"))
            client.create(_cm("doomed"))
            assert _wait(lambda: len(events) == 2)

            relists_before = _counter("bus_relists_total")
            srv.stop()
            # history the client must reconstruct without having seen it
            api.create(_cm("born-in-the-dark"))
            api.delete("ConfigMap", "ns", "doomed")
            srv2 = BusServer(api, host="127.0.0.1", port=port,
                             bookmark_interval=0.1).start()
            try:
                assert _wait(lambda: ("ADDED", "born-in-the-dark") in events
                             and ("DELETED", "doomed") in events, 15), events
                assert sorted(events) == sorted([
                    ("ADDED", "keep"), ("ADDED", "doomed"),
                    ("ADDED", "born-in-the-dark"), ("DELETED", "doomed"),
                ]), events
                assert _counter("bus_relists_total") > relists_before
                # and the stream is live again post-relist
                client.create(_cm("after"))
                assert _wait(lambda: ("ADDED", "after") in events), events
            finally:
                srv2.stop()
        finally:
            client.close()

    def test_backlog_overflow_forces_relist(self):
        """A resume older than the backlog window is answered 410-Gone;
        the relist converges with no duplicates."""
        api = APIServer()
        srv = BusServer(api, backlog_size=3, bookmark_interval=0.1).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5,
                                 reconnect_min=0.02)
        try:
            events = []
            client.watch("ConfigMap",
                         lambda e, o, n: events.append((e, (n or o).metadata.name)))
            client.create(_cm("z0"))
            assert _wait(lambda: len(events) == 1)
            relists_before = _counter("bus_relists_total")
            client._sock.close()
            for i in range(1, 8):  # >> backlog_size while disconnected
                api.create(_cm(f"z{i}"))
            assert _wait(lambda: len(events) == 8, 10), events
            assert sorted(events) == sorted(
                ("ADDED", f"z{i}") for i in range(8)), events
            assert _counter("bus_relists_total") > relists_before
        finally:
            client.close()
            srv.stop()

    def test_bookmarks_advance_resume_point(self):
        """Bookmarks carry the bus sequence through quiet periods, so a
        kind with no traffic of its own still resumes instead of
        relisting after churn in other kinds."""
        api = APIServer()
        srv = BusServer(api, backlog_size=4, bookmark_interval=0.05).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5,
                                 reconnect_min=0.02)
        try:
            events = []
            client.watch("ConfigMap",
                         lambda e, o, n: events.append((e, (n or o).metadata.name)))
            assert _wait(
                lambda: client._watches["ConfigMap"].last_seq is not None, 5
            )
            # churn another kind past the backlog depth; bookmarks keep
            # the ConfigMap cursor fresh the whole time
            for i in range(10):
                api.create(core.Secret(metadata=core.ObjectMeta(
                    name=f"s{i}", namespace="ns")))
            assert _wait(
                lambda: (client._watches["ConfigMap"].last_seq or 0) >= 10, 5
            )
            relists_before = _counter("bus_relists_total")
            client._sock.close()
            api.create(_cm("fresh"))
            assert _wait(lambda: ("ADDED", "fresh") in events, 8), events
            assert _counter("bus_relists_total") == relists_before, (
                "bookmarked cursor should resume, not relist"
            )
        finally:
            client.close()
            srv.stop()

    def test_leader_election_across_connections_with_crash_takeover(self):
        """Cross-process HA in miniature: two electors on two bus
        connections — one lease winner; a crashed leader (no release)
        is succeeded after expiry."""
        from volcano_tpu.serving import LeaderElector

        api = APIServer()
        srv = BusServer(api, bookmark_interval=0.1).start()
        c1 = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5,
                             reconnect_min=0.02)
        c2 = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5,
                             reconnect_min=0.02)
        e1 = LeaderElector(c1, "lock", "id-1", lease_duration=0.5,
                           retry_period=0.05).start()
        e2 = LeaderElector(c2, "lock", "id-2", lease_duration=0.5,
                           retry_period=0.05).start()
        try:
            assert _wait(lambda: e1.is_leader or e2.is_leader, 10)
            for _ in range(10):
                assert not (e1.is_leader and e2.is_leader)
                time.sleep(0.02)
            leader, standby = (e1, e2) if e1.is_leader else (e2, e1)
            leader.stop(release=False)  # crash: lease left to expire
            assert _wait(lambda: standby.is_leader, 10), (
                "standby never took over through the bus"
            )
        finally:
            e1.stop()
            e2.stop()
            c1.close()
            c2.close()
            srv.stop()


class TestBusReviewHardening:
    """Regression tests for review findings."""

    def test_admission_review_on_the_same_connection(self):
        """One shared connection acting as BOTH the webhook endpoint and
        the submitter (vtpu-local-up --bus shares one RemoteAPIServer
        among all daemons): the server must answer the review forwarded
        to the very connection that issued the create — requests are
        handled off the reader thread, so the T_ADMIT_RESP can be read
        while the create is parked in its review."""
        api = APIServer()
        srv = BusServer(api, bookmark_interval=0.2, admission_timeout=5).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=8,
                                 reconnect_min=0.02)
        try:
            def hook(operation, cm):
                # read back through the SAME connection mid-review (the
                # validate_job queue-existence pattern)
                assert client.get("ConfigMap", "ns", "never") is None
                if cm.metadata.name == "bad":
                    raise AdmissionError("nope")
                cm.data["reviewed"] = "yes"
                return cm

            client.register_admission("ConfigMap", "CREATE", hook)
            assert _wait(lambda: (srv._admission.get(
                ("ConfigMap", "CREATE")) or []) != [], 5)

            start = time.monotonic()
            client.create(_cm("good"))
            # the pre-fix behavior was a 5s admission timeout + denial
            assert time.monotonic() - start < 3.0, "review round-trip stalled"
            assert api.get("ConfigMap", "ns", "good").data["reviewed"] == "yes"
            with pytest.raises(AdmissionError, match="nope"):
                client.create(_cm("bad"))
        finally:
            client.close()
            srv.stop()

    def test_leader_survives_transient_renew_failure_within_lease(self):
        """A single dropped bus request must not flap leadership: the
        lease is still provably held until it expires, so the elector
        keeps leading through transient errors and only steps down when
        failures outlast the lease duration."""
        from volcano_tpu.client.apiserver import ApiError
        from volcano_tpu.serving import LeaderElector

        api = APIServer()

        class FlakyApi:
            """Proxy that fails every call while .down is True."""

            def __init__(self, inner):
                self._inner = inner
                self.down = False

            def __getattr__(self, name):
                attr = getattr(self._inner, name)
                if not callable(attr):
                    return attr

                def call(*a, **kw):
                    if self.down:
                        raise ApiError("bus unreachable")
                    return attr(*a, **kw)

                return call

        flaky = FlakyApi(api)
        e = LeaderElector(flaky, "lock", "id-1", lease_duration=1.0,
                          retry_period=0.05).start()
        try:
            assert _wait(lambda: e.is_leader, 5)
            flaky.down = True
            time.sleep(0.3)  # several failed renews, well inside the lease
            assert e.is_leader, "transient renew failure flapped leadership"
            # outage outlasting the lease: now leadership must drop
            assert _wait(lambda: not e.is_leader, 5), (
                "leadership survived past lease expiry with the bus down"
            )
            # bus back: leadership is re-acquired
            flaky.down = False
            assert _wait(lambda: e.is_leader, 5)
        finally:
            e.stop()

    def test_unwatch_tears_down_server_subscription(self):
        """Removing the last handler must fully detach, like the
        in-process unwatch: the server stops streaming the kind and the
        client drops its shadow state (no perpetual decode of events
        nobody reads)."""
        api = APIServer()
        srv = BusServer(api, bookmark_interval=0.2).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5,
                                 reconnect_min=0.02)
        try:
            events = []
            handler = lambda e, o, n: events.append((n or o).metadata.name)
            client.watch("ConfigMap", handler)
            client.create(_cm("seen"))
            assert _wait(lambda: "seen" in events)
            assert _wait(lambda: sum(
                len(s) for s in srv._subs.values()) == 1)

            client.unwatch("ConfigMap", handler)
            assert _wait(lambda: sum(
                len(s) for s in srv._subs.values()) == 0), (
                "server subscription survived unwatch"
            )
            assert _wait(lambda: "ConfigMap" not in client._watches)
            client.create(_cm("unseen"))
            time.sleep(0.3)
            assert "unseen" not in events

            # re-watching after teardown works from scratch
            events2 = []
            client.watch("ConfigMap",
                         lambda e, o, n: events2.append((n or o).metadata.name))
            assert _wait(lambda: {"seen", "unseen"} <= set(events2))
        finally:
            client.close()
            srv.stop()


# ---- VBUS serde round-trip coverage (the serde-drift lint contract) ----
#
# Every kind registered in bus/protocol.py::KINDS must have an exemplar
# here — volcano_tpu/analysis/serde_drift.py (SRD001) fails the lint on
# any registry entry missing from this mapping, and the test below
# round-trips each exemplar through the wire encode/decode so a field
# added to a dataclass without to_dict/from_dict support is caught the
# day it lands.  Exemplars deliberately carry NON-default field values:
# a round-trip that only ships defaults proves nothing about the serde.

from volcano_tpu.apis import bus as apis_bus
from volcano_tpu.apis import scheme
from volcano_tpu.bus import protocol

from tests.builders import (
    build_node,
    build_pod,
    build_pod_group,
    build_priority_class,
    build_queue,
)


def _meta(name, ns="ns"):
    return core.ObjectMeta(
        name=name, namespace=ns, uid=f"uid-{name}",
        labels={"app": name}, annotations={"note": "serde"},
        resource_version=7, creation_timestamp=123.5,
        owner_references=[core.OwnerReference(
            kind="Job", name="owner", uid="uid-owner", controller=True,
        )],
    )


SERDE_EXEMPLARS = {
    "Pod": lambda: build_pod(
        "ns", "p0", "n0", {"cpu": "500m", "memory": "1Gi"},
        group="pg0", labels={"tier": "web"},
        selector={"disk": "ssd"},
        tolerations=[core.Toleration(key="gpu", operator="Exists",
                                     effect="NoSchedule")],
        priority=10, ports=[8080],
    ),
    "Node": lambda: build_node(
        "n0", {"cpu": "8", "memory": "16Gi"}, labels={"zone": "a"},
        taints=[core.Taint(key="dedicated", value="batch",
                           effect="NoSchedule")],
    ),
    "PriorityClass": lambda: build_priority_class("high", 1000),
    "ConfigMap": lambda: core.ConfigMap(
        metadata=_meta("cm0"), data={"k": "v"},
    ),
    "Secret": lambda: core.Secret(
        metadata=_meta("sec0"), data={"token": "c2VjcmV0"},
        type="kubernetes.io/ssh-auth",
    ),
    "Service": lambda: core.Service(
        metadata=_meta("svc0"),
        spec=core.ServiceSpec(
            selector={"app": "svc0"}, cluster_ip="None",
            ports=[core.ServicePort(name="ssh", port=22)],
        ),
    ),
    "PersistentVolumeClaim": lambda: core.PersistentVolumeClaim(
        metadata=_meta("pvc0"),
        spec={"storageClassName": "fast", "volumeName": "pv-1"},
        status={"phase": "Bound"},
    ),
    "NetworkPolicy": lambda: core.NetworkPolicy(
        metadata=_meta("np0"), spec={"podSelector": {"app": "web"}},
    ),
    "Event": lambda: core.Event(
        metadata=_meta("ev0"),
        involved_object={"kind": "Pod", "namespace": "ns", "name": "p0"},
        type="Warning", reason="Unschedulable",
        message="0/1 nodes available", count=3,
    ),
    "Job": lambda: batch.Job(
        metadata=_meta("job0"),
        spec=batch.JobSpec(
            min_available=2, queue="q0", max_retry=5,
            priority_class_name="high",
            plugins={"ssh": [], "env": []},
            tasks=[batch.TaskSpec(name="worker", replicas=2)],
        ),
        status=batch.JobStatus(running=1, pending=1, version=4),
    ),
    "PodGroup": lambda: build_pod_group(
        "ns", "pg0", 2, queue="q0",
        min_resources={"cpu": "2"}, priority_class_name="high",
    ),
    "Queue": lambda: build_queue("q0", weight=4, capability={"cpu": "32"}),
    "PodGroupV1alpha1": lambda: scheme.PodGroupV1alpha1(
        metadata=_meta("pg1"),
        spec=scheduling.PodGroupSpec(min_member=3, queue="q1"),
        status=scheduling.PodGroupStatus(
            phase=scheduling.POD_GROUP_INQUEUE, running=1,
        ),
    ),
    "QueueV1alpha1": lambda: scheme.QueueV1alpha1(
        metadata=_meta("q1", ns=""),
        spec=scheme.QueueSpecV1alpha1(weight=2, capability={"cpu": "4"}),
        status=scheme.QueueStatusV1alpha1(pending=2, running=1),
    ),
    "Command": lambda: apis_bus.Command(
        metadata=_meta("cmd0"),
        action="AbortJob",
        target_object=core.OwnerReference(
            kind="Job", name="job0", uid="uid-job0", controller=True,
        ),
        reason="UserRequest", message="abort requested",
    ),
}


class TestSerdeRoundTrip:
    def test_every_registered_kind_has_an_exemplar(self):
        """The drift gate both ways: a kind added to protocol.KINDS
        without an exemplar, or a dead exemplar for an unregistered
        kind, fails here (and SRD001 fails the lint for the former)."""
        assert set(SERDE_EXEMPLARS) == set(protocol.KINDS)

    @pytest.mark.parametrize("kind", sorted(protocol.KINDS))
    def test_wire_round_trip_is_lossless(self, kind):
        obj = SERDE_EXEMPLARS[kind]()
        assert obj.kind == kind, (
            f"exemplar for {kind} built a {obj.kind}"
        )
        data = protocol.encode_obj(obj)
        back = protocol.decode_obj(data)
        assert type(back) is type(obj)
        assert back == obj, f"{kind} serde round-trip lost fields"
        # a second trip through the already-decoded object must be
        # stable too (decode must not normalize fields differently)
        assert protocol.decode_obj(protocol.encode_obj(back)) == obj

    @pytest.mark.parametrize("kind", sorted(protocol.KINDS))
    def test_round_trip_through_json_wire_bytes(self, kind):
        """The actual frame path: dict → JSON bytes → dict → object,
        which is what send_frame/recv_frame do to the payload."""
        import json as _json

        obj = SERDE_EXEMPLARS[kind]()
        wire = _json.dumps(protocol.encode_obj(obj),
                           separators=(",", ":")).encode()
        assert protocol.decode_obj(_json.loads(wire.decode())) == obj

    @pytest.mark.parametrize("kind", sorted(protocol.KINDS))
    def test_round_trip_through_binary_wire_bytes(self, kind):
        """The v8 frame path: dict → msgpack bytes → dict → object.
        Every kind in SERDE_EXEMPLARS must survive CODEC_BINARY exactly
        as it survives JSON — this is the test SRD006 requires, and it
        would catch a kind whose encoded form only JSON can carry."""
        if not protocol.HAS_BINARY:
            pytest.skip("msgpack unavailable — binary framing disabled")
        obj = SERDE_EXEMPLARS[kind]()
        wire = protocol.encode_payload(
            protocol.encode_obj(obj), codec=protocol.CODEC_BINARY
        )
        back = protocol.decode_payload(wire, codec=protocol.CODEC_BINARY)
        assert protocol.decode_obj(back) == obj
        # both framings must decode to the SAME dict — byte-level
        # conformance of the payload contents across codecs
        assert back == protocol.decode_payload(
            protocol.encode_payload(protocol.encode_obj(obj)),
        )


class TestWatchBatch:
    """Protocol v3 coalesced watch delivery: the writer thread batches
    consecutive watch frames into one T_WATCH_BATCH frame for
    connections that established their watches via the ``watch_batch``
    op — closing the known-gap where a commit_batch transaction (ONE
    store lock hold) still fanned out one T_WATCH_EVENT frame per
    object per subscriber."""

    @staticmethod
    def _entry(seq, name):
        return {
            "seq": seq, "kind": "ConfigMap", "event": "ADDED",
            "old": None, "new": protocol.encode_obj(_cm(name)), "ts": 0.0,
        }

    def test_writer_coalesces_queued_watch_frames(self):
        """Deterministic writer-level check: frames already queued when
        the writer wakes ship as ONE batch frame, and a non-watch frame
        (bookmark) acts as an ordering barrier sent right after."""
        import socket
        import threading as _threading

        from volcano_tpu.bus.server import _Conn

        s1, s2 = socket.socketpair()
        try:
            conn = _Conn(s1, peer="test")
            conn.batch_watch = True
            for i in range(5):
                conn.outbound.put(
                    (protocol.T_WATCH_EVENT, 7, self._entry(i + 1, f"c{i}"))
                )
            conn.outbound.put((protocol.T_BOOKMARK, 7, {"seq": 5, "ts": 0.0}))
            t = _threading.Thread(target=conn.write_loop, daemon=True)
            t.start()
            mtype, corr_id, payload = protocol.recv_frame(s2)
            assert mtype == protocol.T_WATCH_BATCH
            events = payload["events"]
            assert [e["seq"] for e in events] == [1, 2, 3, 4, 5]
            assert all(e["watch_id"] == 7 for e in events)
            mtype, corr_id, payload = protocol.recv_frame(s2)
            assert mtype == protocol.T_BOOKMARK and corr_id == 7
            conn.kill()
            t.join(timeout=5)
        finally:
            for s in (s1, s2):
                try:
                    s.close()
                except OSError:
                    pass

    def test_writer_keeps_per_object_frames_without_opt_in(self):
        """A connection whose watches came through the plain ``watch``
        op (an old client) must never see a T_WATCH_BATCH frame."""
        import socket
        import threading as _threading

        from volcano_tpu.bus.server import _Conn

        s1, s2 = socket.socketpair()
        try:
            conn = _Conn(s1, peer="test")
            for i in range(3):
                conn.outbound.put(
                    (protocol.T_WATCH_EVENT, 9, self._entry(i + 1, f"c{i}"))
                )
            t = _threading.Thread(target=conn.write_loop, daemon=True)
            t.start()
            for i in range(3):
                mtype, corr_id, payload = protocol.recv_frame(s2)
                assert mtype == protocol.T_WATCH_EVENT and corr_id == 9
                assert payload["seq"] == i + 1
            conn.kill()
            t.join(timeout=5)
        finally:
            for s in (s1, s2):
                try:
                    s.close()
                except OSError:
                    pass

    def test_commit_burst_delivers_batched_in_order(self):
        """End-to-end over TCP: a commit_batch burst reaches a watching
        RemoteAPIServer exactly once each, in store order, and the
        server records coalesced batch frames (the watcher's dispatch
        is indistinguishable from per-object delivery)."""

        def _batch_total():
            with metrics.registry._lock:
                return sum(
                    h.total
                    for (name, _l), h in metrics.registry._histograms.items()
                    if name.endswith("bus_watch_batch_size")
                )

        api = APIServer()
        srv = BusServer(api, bookmark_interval=5.0).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5,
                                 reconnect_min=0.02)
        try:
            assert client.wait_ready(5)
            seen = []
            client.watch(
                "Event",
                lambda e, o, n: seen.append((n or o).metadata.name),
                send_initial=False,
            )
            assert _wait(lambda: any(
                c.batch_watch for c in srv._conns
            )), "watch_batch establishment did not mark the connection"
            before = _batch_total()
            # one store transaction, many notifications: the canonical
            # burst the coalescing exists for
            events = [
                {
                    "namespace": "ns",
                    "involved": {"kind": "Pod", "namespace": "ns",
                                 "name": f"p{i:03d}"},
                    "type": "Normal", "reason": f"R{i}", "message": "m",
                }
                for i in range(40)
            ]
            results = api.commit_batch(events=events)
            assert all(e is None for e in results["events"])
            assert _wait(lambda: len(seen) == 40), f"saw {len(seen)}/40"
            # store order preserved through the batch frame(s)
            assert [n.split(".")[0] for n in seen] == [
                f"p{i:03d}" for i in range(40)
            ]
            assert len(set(seen)) == 40, "duplicate delivery"
            assert _batch_total() > before, (
                "burst shipped but no batch frame was recorded"
            )
        finally:
            client.close()
            srv.stop()

    def test_old_server_falls_back_to_per_object_watch(self, monkeypatch):
        """A v1/v2 server answers `unknown bus op` for watch_batch — the
        client degrades to the plain watch op once, permanently for the
        connection, and the stream still works."""
        from volcano_tpu.client.apiserver import ApiError

        real_execute = BusServer._execute

        def v2_execute(self, conn, req_id, payload, op):
            if op == "watch_batch":
                raise ApiError("unknown bus op 'watch_batch'")
            return real_execute(self, conn, req_id, payload, op)

        monkeypatch.setattr(BusServer, "_execute", v2_execute)
        api = APIServer()
        srv = BusServer(api, bookmark_interval=0.1).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5,
                                 reconnect_min=0.02)
        try:
            assert client.wait_ready(5)
            seen = []
            client.watch("ConfigMap",
                         lambda e, o, n: seen.append((e, (n or o).metadata.name)))
            client.create(_cm("a"))
            client.create(_cm("b"))
            assert _wait(lambda: len(seen) == 2), seen
            assert seen == [("ADDED", "a"), ("ADDED", "b")]
            assert client._no_watch_batch is True
            assert not any(c.batch_watch for c in srv._conns)
        finally:
            client.close()
            srv.stop()


class TestCasBind:
    """Protocol v4 ``cas_bind``: one optimistic binding write — the
    federation spillover primitive.  Conflicts are typed and identical
    over both backends; a pre-v4 server degrades the client to the
    get + CAS-update equivalent."""

    @staticmethod
    def _pod(name, ns="ns"):
        return core.Pod(
            metadata=core.ObjectMeta(name=name, namespace=ns),
            spec=core.PodSpec(),
            status=core.PodStatus(phase="Pending"),
        )

    def test_cas_bind_over_the_wire(self):
        api = APIServer()
        srv = BusServer(api).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5)
        try:
            assert client.wait_ready(5)
            pod = client.create(self._pod("p1"))
            bound = client.cas_bind(
                "ns", "p1", "n1",
                expected_rv=pod.metadata.resource_version,
            )
            assert bound.spec.node_name == "n1"
            assert api.get("Pod", "ns", "p1").spec.node_name == "n1"
            with pytest.raises(ConflictError):
                client.cas_bind("ns", "p1", "n2")
            with pytest.raises(NotFoundError):
                client.cas_bind("ns", "nope", "n1")
        finally:
            client.close()
            srv.stop()

    def test_cas_bind_stale_rv_conflicts(self):
        api = APIServer()
        srv = BusServer(api).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5)
        try:
            assert client.wait_ready(5)
            pod = client.create(self._pod("p1"))
            stale = pod.metadata.resource_version
            pod.metadata.labels["x"] = "y"
            client.update(pod)
            with pytest.raises(ConflictError):
                client.cas_bind("ns", "p1", "n1", expected_rv=stale)
        finally:
            client.close()
            srv.stop()

    def test_old_server_falls_back_to_get_plus_cas_update(self, monkeypatch):
        """A pre-v4 server answers `unknown bus op` for cas_bind — the
        client degrades permanently (per connection) to get + CAS
        update, with identical conflict semantics."""
        from volcano_tpu.client.apiserver import ApiError

        real_execute = BusServer._execute

        def v3_execute(self, conn, req_id, payload, op):
            if op == "cas_bind":
                raise ApiError("unknown bus op 'cas_bind'")
            return real_execute(self, conn, req_id, payload, op)

        monkeypatch.setattr(BusServer, "_execute", v3_execute)
        api = APIServer()
        srv = BusServer(api).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5)
        try:
            assert client.wait_ready(5)
            pod = client.create(self._pod("p1"))
            bound = client.cas_bind(
                "ns", "p1", "n1",
                expected_rv=pod.metadata.resource_version,
            )
            assert bound.spec.node_name == "n1"
            assert client._no_cas_bind is True
            assert api.get("Pod", "ns", "p1").spec.node_name == "n1"
            # conflict semantics survive the fallback
            client.create(self._pod("p2"))
            api.cas_bind("ns", "p2", "elsewhere")
            with pytest.raises(ConflictError):
                client.cas_bind("ns", "p2", "n1")
        finally:
            client.close()
            srv.stop()


class TestSerdeOncePerEvent:
    """The fan-out serde hot path (ISSUE 9 satellite): a watch event's
    frame body is serialized once per EVENT, no matter how many
    subscribers receive it — the named prerequisite for multi-scheduler
    federation (ROADMAP item 4's serde note)."""

    def test_event_encodes_once_for_many_subscribers(self, monkeypatch):
        from volcano_tpu.bus import server as server_mod

        counts = {"encodes": 0, "calls": 0}
        original_raw = server_mod._CachedPayload.raw
        original_raw_bin = server_mod._CachedPayload.raw_bin

        def counting_raw(self):
            counts["calls"] += 1
            if self._raw is None:
                counts["encodes"] += 1
            return original_raw(self)

        def counting_raw_bin(self):
            # v8 connections cache binary bodies instead — the
            # once-per-event invariant covers BOTH codecs
            counts["calls"] += 1
            if self._raw_bin is None:
                counts["encodes"] += 1
            return original_raw_bin(self)

        monkeypatch.setattr(server_mod._CachedPayload, "raw", counting_raw)
        monkeypatch.setattr(server_mod._CachedPayload, "raw_bin",
                            counting_raw_bin)
        api = APIServer()
        srv = BusServer(api, bookmark_interval=3600).start()
        clients, seen = [], []
        try:
            for i in range(3):
                c = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}",
                                    timeout=5)
                assert c.wait_ready(5)
                n_seen = [0]
                seen.append(n_seen)
                c.watch("ConfigMap",
                        lambda e, o, n, s=n_seen: s.__setitem__(
                            0, s[0] + 1),
                        send_initial=False)
                clients.append(c)
            counts["encodes"] = counts["calls"] = 0
            for i in range(10):
                api.create(_cm(f"c{i}"))
            assert _wait(lambda: all(s[0] == 10 for s in seen)), seen
            # 10 events × 3 subscribers: ≥30 raw() fan-out calls but
            # exactly 10 serializations
            assert counts["encodes"] == 10, counts
            assert counts["calls"] >= 30, counts
        finally:
            for c in clients:
                c.close()
            srv.stop()

    def test_batch_splice_produces_equivalent_json(self):
        """The watch_batch byte-splice must decode to exactly what the
        old per-entry re-encode produced."""
        import json as _json

        from volcano_tpu.bus.server import _CachedPayload, _splice_watch_id

        entry = {"seq": 42, "kind": "Pod", "event": "ADDED",
                 "old": None, "new": {"kind": "Pod", "metadata": {}},
                 "ts": 1.5}
        cached = _CachedPayload(entry)
        spliced = _splice_watch_id(cached.raw(), 7)
        assert _json.loads(spliced) == dict(entry, watch_id=7)
