"""VBUS v8 interop matrix: binary framing × JSON peers × transports.

The tentpole contract is *negotiated, never assumed*: msgpack bodies
flow only after a ``bus_hello`` exchange both ends answered ``binary``,
and every degenerate pairing — binary client on a v7 server, JSON
client on a binary-default server, a mixed replication group, a torn
binary frame, a full shm ring — must keep working with JSON/TCP
semantics, never error.  The matrix here pins each cell:

* binary client ↔ JSON-only (pre-v8) server: full conformance over
  JSON, exactly one ``volcano_bus_codec_fallbacks_total`` increment;
* JSON client ↔ binary-default server: full conformance, the server
  keeps that connection on JSON;
* mixed replication group: a binary-records leader replicating to
  JSON-pinned followers stores byte-identical WAL records (the CRC
  chain covers payload bytes, so byte fidelity IS correctness);
* torn / undecodable binary frames kill one connection, not the bus;
* the shm ring transport carries identical frames through repeated
  ring wraparound and falls back to TCP when attach fails;
* WAL twins: the same op sequence under either record codec produces
  the same store digest and recovers across codec switches.
"""

from __future__ import annotations

import os
import socket
import struct
import time

import pytest

from volcano_tpu.apis import core
from volcano_tpu.bus import protocol, shm
from volcano_tpu.bus.remote import RemoteAPIServer
from volcano_tpu.bus.replication import ReplicaManager, _RawClient
from volcano_tpu.bus.server import (
    BusServer,
    _batch_body_bin,
    _splice_watch_id_bin,
)
from volcano_tpu.bus.wal import (
    PersistentAPIServer,
    read_records,
    store_digest,
)
from volcano_tpu.client.apiserver import ApiError, APIServer
from volcano_tpu.metrics import metrics

needs_msgpack = pytest.mark.skipif(
    not protocol.HAS_BINARY, reason="msgpack unavailable"
)
needs_shm = pytest.mark.skipif(
    not shm._HAS_EVENTFD, reason="no eventfd/fd-passing on this platform"
)


def _wait(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _counter(name_suffix: str) -> float:
    with metrics.registry._lock:
        return sum(
            v for (name, _labels), v in metrics.registry._counters.items()
            if name.endswith(name_suffix)
        )


def _cm(name, ns="ns", data=None):
    return core.ConfigMap(
        metadata=core.ObjectMeta(name=name, namespace=ns), data=data or {}
    )


def _pod(name, ns="ns"):
    return core.Pod(
        metadata=core.ObjectMeta(name=name, namespace=ns),
        spec=core.PodSpec(),
        status=core.PodStatus(phase="Pending"),
    )


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _conformance_pass(client, api):
    """The cross-codec conformance core: CRUD + list + a live watch,
    asserted against the authoritative store."""
    seen = []
    client.watch("ConfigMap",
                 lambda e, o, n: seen.append((e, (n or o).metadata.name)),
                 send_initial=False)
    created = client.create(_cm("a", data={"k": "v"}))
    assert created.data == {"k": "v"}
    created.data["k2"] = "v2"
    client.update(created)
    assert api.get("ConfigMap", "ns", "a").data["k2"] == "v2"
    assert [o.metadata.name for o in client.list("ConfigMap")] == ["a"]
    client.delete("ConfigMap", "ns", "a")
    assert api.get("ConfigMap", "ns", "a") is None
    assert _wait(lambda: len(seen) == 3), seen
    assert seen == [("ADDED", "a"), ("MODIFIED", "a"), ("DELETED", "a")]


class TestCodecNegotiation:
    @needs_msgpack
    def test_binary_negotiated_by_default_and_conformant(self):
        api = APIServer()
        srv = BusServer(api, bookmark_interval=0.1).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5)
        try:
            assert client.wait_ready(5)
            assert _wait(lambda: client.codec == protocol.CODEC_BINARY)
            _conformance_pass(client, api)
            with srv._conns_lock:
                codecs = [c.codec for c in srv._conns]
            assert protocol.CODEC_BINARY in codecs
        finally:
            client.close()
            srv.stop()

    def test_json_only_server_full_conformance_with_fallback(
        self, monkeypatch
    ):
        """A v7 server answers `unknown bus op` for bus_hello — the
        client degrades to JSON for the connection's life, completes
        the full conformance pass, and the degradation is observable
        on the fallback counter."""
        real_execute = BusServer._execute

        def v7_execute(self, conn, req_id, payload, op):
            if op == "bus_hello":
                raise ApiError("unknown bus op 'bus_hello'")
            return real_execute(self, conn, req_id, payload, op)

        monkeypatch.setattr(BusServer, "_execute", v7_execute)
        before = _counter("bus_codec_fallbacks_total")
        api = APIServer()
        srv = BusServer(api, bookmark_interval=0.1).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5)
        try:
            assert client.wait_ready(5)
            _conformance_pass(client, api)
            assert client.codec == protocol.CODEC_JSON
            if protocol.HAS_BINARY:
                assert client._no_bus_hello is True
                assert _counter("bus_codec_fallbacks_total") == before + 1
        finally:
            client.close()
            srv.stop()

    def test_json_client_against_binary_default_server(self, monkeypatch):
        """The other direction: a client that never offers binary (a
        pre-v8 build) gets plain JSON from a binary-capable server —
        the server must never push msgpack at a peer that did not ask."""
        monkeypatch.setattr(
            RemoteAPIServer, "_negotiate_codec", lambda self: None
        )
        api = APIServer()
        srv = BusServer(api, bookmark_interval=0.1).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5)
        try:
            assert client.wait_ready(5)
            _conformance_pass(client, api)
            assert client.codec == protocol.CODEC_JSON
            with srv._conns_lock:
                codecs = [c.codec for c in srv._conns]
            assert codecs and all(
                c == protocol.CODEC_JSON for c in codecs
            )
        finally:
            client.close()
            srv.stop()

    @needs_msgpack
    def test_codec_gauge_tracks_connections(self):
        api = APIServer()
        srv = BusServer(api).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5)
        try:
            assert client.wait_ready(5)
            assert _wait(lambda: client.codec == protocol.CODEC_BINARY)

            def binary_conns():
                with metrics.registry._lock:
                    return sum(
                        v for (name, labels), v in
                        metrics.registry._gauges.items()
                        if name.endswith("bus_codec")
                        and ("codec", "binary") in labels
                    )

            assert _wait(lambda: binary_conns() >= 1)
        finally:
            client.close()
            srv.stop()


class TestTornBinaryFrames:
    @needs_msgpack
    def test_truncated_binary_frame_kills_one_conn_not_the_bus(self):
        """A peer that dies mid-frame (the torn-write shape on the
        wire) costs its own connection; the server keeps serving."""
        api = APIServer()
        srv = BusServer(api).start()
        torn = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        client = None
        try:
            body = protocol.encode_payload(
                {"op": "list", "kind": "ConfigMap"},
                codec=protocol.CODEC_BINARY,
            )
            header = struct.pack(
                "<4sHHII", b"VBUS", protocol.VERSION, protocol.T_REQ,
                1, len(body),
            )
            torn.sendall(header + body[: len(body) // 2])
            torn.close()  # EOF mid-body
            client = RemoteAPIServer(
                f"tcp://127.0.0.1:{srv.port}", timeout=5
            )
            assert client.wait_ready(5)
            client.create(_cm("alive"))
            assert api.get("ConfigMap", "ns", "alive") is not None
        finally:
            if client is not None:
                client.close()
            srv.stop()

    @needs_msgpack
    def test_undecodable_binary_body_is_one_dead_conn(self):
        """A frame stamped v8 whose body is NOT valid msgpack draws a
        connection-level error, never a crash or a JSON mis-decode."""
        api = APIServer()
        srv = BusServer(api).start()
        bad = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        client = None
        try:
            body = b"\xc1\xc1\xc1\xc1"  # 0xc1 is the one never-used marker
            bad.sendall(struct.pack(
                "<4sHHII", b"VBUS", protocol.VERSION, protocol.T_REQ,
                1, len(body),
            ) + body)
            # the server closes the offending connection
            bad.settimeout(5)
            assert _wait(lambda: not _alive(bad), timeout=5)
            client = RemoteAPIServer(
                f"tcp://127.0.0.1:{srv.port}", timeout=5
            )
            assert client.wait_ready(5)
            assert client.list("ConfigMap") == []
        finally:
            bad.close()
            if client is not None:
                client.close()
            srv.stop()


def _alive(sock: socket.socket) -> bool:
    try:
        return sock.recv(1) != b""
    except socket.timeout:
        return True
    except OSError:
        return False


class TestShmTransport:
    @needs_shm
    def test_conformance_over_shm_with_ring_wraparound(
        self, tmp_path, monkeypatch
    ):
        """Frames over the ring are the identical byte stream TCP would
        carry; a small ring forces the positions to wrap several times
        mid-suite, and watch pushes ride the same rings."""
        monkeypatch.setenv("VTPU_BUS_SHM", "1")
        monkeypatch.setenv("VTPU_BUS_SHM_DIR", str(tmp_path / "shm"))
        monkeypatch.setattr(shm, "DEFAULT_RING_BYTES", 64 * 1024)
        api = APIServer()
        srv = BusServer(api, bookmark_interval=0.1).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5)
        try:
            assert srv._shm_listener is not None
            assert client.wait_ready(5)
            assert _wait(lambda: isinstance(client._sock, shm.ShmSocket))
            _conformance_pass(client, api)
            # > 4x the ring capacity of payload in each direction
            blob = "x" * 8192
            seen = []
            client.watch("ConfigMap",
                         lambda e, o, n: seen.append((n or o).metadata.name),
                         send_initial=False)
            for i in range(40):
                client.create(_cm(f"big-{i:03d}", data={"blob": blob}))
            assert _wait(lambda: len(seen) == 40), len(seen)
            got = client.get("ConfigMap", "ns", "big-039")
            assert got.data["blob"] == blob
        finally:
            client.close()
            srv.stop()

    @needs_shm
    def test_attach_failure_falls_back_to_tcp(self, tmp_path, monkeypatch):
        """The env is set but no listener rendezvouses in the directory
        (a TCP-only server): the client silently lands on TCP."""
        api = APIServer()
        srv = BusServer(api).start()  # started BEFORE the env flips on
        monkeypatch.setenv("VTPU_BUS_SHM", "1")
        monkeypatch.setenv("VTPU_BUS_SHM_DIR", str(tmp_path / "nowhere"))
        client = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=5)
        try:
            assert srv._shm_listener is None
            assert client.wait_ready(5)
            assert isinstance(client._sock, socket.socket)
            _conformance_pass(client, api)
        finally:
            client.close()
            srv.stop()


class TestMixedReplicationGroup:
    def _group(self, tmp_path, n=3, lease=1.0):
        ports = [_free_port() for _ in range(n)]
        endpoints = [f"tcp://127.0.0.1:{p}" for p in ports]
        replicas = []
        for i in range(n):
            store = PersistentAPIServer(str(tmp_path / f"r{i}"),
                                        snapshot_every=10_000)
            mgr = ReplicaManager(store, endpoints, i, lease_ttl=lease)
            bus = BusServer(store, port=ports[i], replica=mgr)
            bus.start()
            mgr.start()
            replicas.append((store, mgr, bus))
        return endpoints, replicas

    @staticmethod
    def _teardown(replicas, *clients):
        for c in clients:
            if c is not None:
                c.close()
        for _store, mgr, bus in replicas:
            try:
                mgr.stop()
                bus.stop()
                _store.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    def _run_writes_and_check_bytes(self, tmp_path, replicas, endpoints):
        cli = None
        try:
            assert _wait(
                lambda: [m.role for _s, m, _b in replicas].count("leader")
                == 1,
                timeout=20.0,
            )
            lidx = next(i for i, (_s, m, _b) in enumerate(replicas)
                        if m.role == "leader")
            cli = RemoteAPIServer(endpoints[lidx], timeout=10)
            assert cli.wait_ready(10)
            for i in range(5):
                cli.create(_pod(f"p{i}"))
            pod = cli.get("Pod", "ns", "p0")
            cli.cas_bind("ns", "p0", "n0",
                         expected_rv=pod.metadata.resource_version)

            def replicated():
                return all(
                    s.get("Pod", "ns", "p4") is not None
                    and (s.get("Pod", "ns", "p0") or _pod("x")).spec.node_name
                    == "n0"
                    for s, _m, _b in replicas
                )

            assert _wait(replicated, timeout=10.0)
            # byte fidelity: every replica's WAL holds the LEADER's
            # record bytes verbatim (the chain CRCs make anything else
            # a resync loop, so this is the replication invariant)
            wals = [
                read_records(str(tmp_path / f"r{i}" / "wal.log"))[0]
                for i in range(len(replicas))
            ]
            # followers may trail by in-flight records; compare the
            # common prefix, which must cover the writes above
            common = min(len(w) for w in wals)
            assert common >= 6
            for w in wals[1:]:
                assert w[:common] == wals[0][:common]
            digests = {store_digest(s) for s, _m, _b in replicas}
            assert len(digests) == 1
        finally:
            self._teardown(replicas, cli)

    @needs_msgpack
    def test_binary_group_ships_record_bytes_verbatim(self, tmp_path):
        endpoints, replicas = self._group(tmp_path)
        self._run_writes_and_check_bytes(tmp_path, replicas, endpoints)

    @needs_msgpack
    def test_json_followers_of_binary_leader_stay_byte_identical(
        self, tmp_path, monkeypatch
    ):
        """The mixed group: followers pull over JSON connections (as a
        pre-v8 build would), the leader's records are msgpack — the
        base64 leg must still deliver byte-identical records."""
        monkeypatch.setattr(
            _RawClient, "_negotiate_codec", lambda self: None
        )
        endpoints, replicas = self._group(tmp_path)
        self._run_writes_and_check_bytes(tmp_path, replicas, endpoints)


class TestWalCodecTwins:
    def _drive(self, data_dir, monkeypatch, codec):
        if codec:
            monkeypatch.setenv("VTPU_WAL_CODEC", codec)
        else:
            monkeypatch.delenv("VTPU_WAL_CODEC", raising=False)
        api = PersistentAPIServer(data_dir, snapshot_every=10_000)
        try:
            for i in range(4):
                pod = _pod(f"p{i}")
                # pin the only clock-derived field so the twin runs are
                # byte-comparable (the chaos harness does the same)
                pod.metadata.creation_timestamp = 1.0
                api.create(pod)
            pod = api.get("Pod", "ns", "p0")
            api.cas_bind("ns", "p0", "n0",
                         expected_rv=pod.metadata.resource_version)
            api.delete("Pod", "ns", "p3")
            return store_digest(api)
        finally:
            api.close()

    @needs_msgpack
    def test_same_ops_either_codec_same_digest(self, tmp_path, monkeypatch):
        """The chaos-twin anchor: the store digest is canonical-JSON
        over object STATE, so twin runs with different record codecs
        stay bit-identical — WAL encoding is an implementation detail
        of durability, never of meaning."""
        d_json = self._drive(str(tmp_path / "json"), monkeypatch, "json")
        d_bin = self._drive(str(tmp_path / "bin"), monkeypatch, "binary")
        assert d_json == d_bin
        # and the bytes on disk really differ (JSON vs msgpack)
        j = read_records(str(tmp_path / "json" / "wal.log"))[0]
        b = read_records(str(tmp_path / "bin" / "wal.log"))[0]
        assert all(p[:1] == b"{" for p in j)
        assert all(p[:1] != b"{" for p in b)

    @needs_msgpack
    def test_recovery_across_codec_switch(self, tmp_path, monkeypatch):
        """A log whose records alternate codecs (an upgrade boundary)
        replays whole: decode_record sniffs per record."""
        d = str(tmp_path / "mixed")
        monkeypatch.setenv("VTPU_WAL_CODEC", "json")
        api = PersistentAPIServer(d, snapshot_every=10_000)
        api.create(_pod("old"))
        api.close()
        monkeypatch.setenv("VTPU_WAL_CODEC", "binary")
        api = PersistentAPIServer(d, snapshot_every=10_000)
        assert api.get("Pod", "ns", "old") is not None
        api.create(_pod("new"))
        api.close()
        monkeypatch.delenv("VTPU_WAL_CODEC", raising=False)
        api = PersistentAPIServer(d, snapshot_every=10_000)
        try:
            assert api.get("Pod", "ns", "old") is not None
            assert api.get("Pod", "ns", "new") is not None
        finally:
            api.close()

    @needs_msgpack
    def test_torn_binary_tail_truncates_to_last_whole_record(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("VTPU_WAL_CODEC", "binary")
        d = str(tmp_path / "torn")
        api = PersistentAPIServer(d, snapshot_every=10_000)
        api.create(_pod("kept"))
        api.create(_pod("torn"))
        api.close()
        wal = os.path.join(d, "wal.log")
        size = os.path.getsize(wal)
        with open(wal, "r+b") as f:
            f.truncate(size - 7)  # mid-record, mid-msgpack-body
        monkeypatch.delenv("VTPU_WAL_CODEC", raising=False)
        api = PersistentAPIServer(d, snapshot_every=10_000)
        try:
            assert api.recovered["torn"] is True
            assert api.get("Pod", "ns", "kept") is not None
            assert api.get("Pod", "ns", "torn") is None
        finally:
            api.close()


@needs_msgpack
class TestBinarySplice:
    """The zero-copy byte surgery must be indistinguishable from a
    decode → mutate → re-encode round trip, across every map-header
    width the splice special-cases."""

    def test_splice_watch_id_equals_reencode(self):
        import msgpack

        for nkeys in (0, 1, 14, 15, 16, 70_000):
            entry = {f"k{i}": i for i in range(nkeys)}
            body = msgpack.packb(entry, use_bin_type=True)
            spliced = msgpack.unpackb(
                _splice_watch_id_bin(body, 42), raw=False
            )
            assert spliced == {"watch_id": 42, **entry}

    def test_batch_body_equals_reencode(self):
        import msgpack

        for n in (1, 15, 16, 300):
            entries = [{"seq": i, "watch_id": 1} for i in range(n)]
            parts = [
                msgpack.packb(e, use_bin_type=True) for e in entries
            ]
            assert msgpack.unpackb(_batch_body_bin(parts), raw=False) == {
                "events": entries
            }
