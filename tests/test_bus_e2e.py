"""Control plane over the out-of-process bus.

Fast (tier-1): the three daemons as threads, each on its OWN
``RemoteAPIServer`` connection to a ``BusServer`` — the socket-pair
smoke test proving the full scheduling loop works over TCP and produces
bindings identical to the in-process bus.

Slow: the real thing — ``vtpu-apiserver`` + admission + controllers +
two leader-elected schedulers as separate OS processes; SIGKILL of the
active scheduler mid-run leads to standby takeover via bus-based leader
election.
"""

from __future__ import annotations

import signal
import time

import pytest

from volcano_tpu.apis import batch, core, scheduling
from volcano_tpu.bus import BusServer, RemoteAPIServer
from volcano_tpu.client import APIServer, VolcanoClient
from volcano_tpu.cmd import AdmissionDaemon, ControllersDaemon, SchedulerDaemon
from volcano_tpu.cmd.local_up import seed_cluster, wait_for_admission


def _wait(pred, timeout=20.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _gang_job(name: str, replicas: int = 3):
    task = batch.TaskSpec(
        name="worker",
        replicas=replicas,
        template=core.PodTemplateSpec(
            spec=core.PodSpec(
                containers=[core.Container(
                    image="registry.k8s.io/pause:3.9",
                    resources={"requests": {"cpu": "1", "memory": "1Gi"}})]
            )
        ),
    )
    return batch.Job(
        metadata=core.ObjectMeta(name=name, namespace="default"),
        spec=batch.JobSpec(min_available=replicas, tasks=[task]),
    )


def _bindings(api, prefix: str):
    return {
        p.metadata.name: p.spec.node_name
        for p in api.list("Pod", "default")
        if p.metadata.name.startswith(prefix)
    }


def _run_inprocess_reference(job_name: str):
    """The same workload through the in-process bus (threaded daemons),
    returning its bindings — the equivalence baseline."""
    api = APIServer()
    admission = AdmissionDaemon(api).start()
    seed_cluster(api, nodes=3, node_cpu="8", node_mem="16Gi")
    controllers = ControllersDaemon(api, period=0.05).start()
    scheduler = SchedulerDaemon(api, schedule_period=0.05).start()
    try:
        VolcanoClient(api).create_job(_gang_job(job_name))
        assert _wait(lambda: len([
            n for n in _bindings(api, job_name).values() if n
        ]) == 3), "in-process reference never bound"
        return _bindings(api, job_name)
    finally:
        scheduler.stop()
        controllers.stop()
        admission.stop()


def test_control_plane_over_bus_binds_identically():
    """Socket-pair smoke: scheduler, controllers, and admission each on
    their own bus connection; the workload binds, and the bindings are
    identical to the in-process bus for the same workload."""
    reference = _run_inprocess_reference("smoke-job")

    store = APIServer()
    srv = BusServer(store, bookmark_interval=0.2).start()
    url = f"tcp://127.0.0.1:{srv.port}"
    conns = [RemoteAPIServer(url, timeout=5, reconnect_min=0.02)
             for _ in range(4)]
    admission = controllers = scheduler = None
    try:
        for c in conns:
            assert c.wait_ready(5)
        admission = AdmissionDaemon(conns[0]).start()
        seed_cluster(conns[3], nodes=3, node_cpu="8", node_mem="16Gi")
        controllers = ControllersDaemon(conns[1], period=0.05).start()
        scheduler = SchedulerDaemon(conns[2], schedule_period=0.05).start()

        assert wait_for_admission(conns[3], timeout=20), (
            "remote admission webhook never answered"
        )
        VolcanoClient(conns[3]).create_job(_gang_job("smoke-job"))
        assert _wait(lambda: len([
            n for n in _bindings(conns[3], "smoke-job").values() if n
        ]) == 3), "job never bound over the bus"

        assert _bindings(conns[3], "smoke-job") == reference, (
            "bus topology must bind identically to the in-process bus"
        )
        # the authoritative store saw exactly what the clients saw
        assert _bindings(store, "smoke-job") == reference

        # admission really ran remotely: the mutating webhook defaulted
        # the queue on its way through the review channel
        job = conns[3].get("Job", "default", "smoke-job")
        assert job.spec.queue == "default"
    finally:
        for d in (scheduler, controllers, admission):
            if d is not None:
                d.stop()
        for c in conns:
            c.close()
        srv.stop()


@pytest.mark.slow
def test_multiprocess_deployment_with_scheduler_sigkill_takeover():
    """The acceptance e2e: apiserver + admission + controllers + two
    leader-elected schedulers as real OS processes over TCP.  The
    workload binds identically to the in-process bus; SIGKILL of the
    active scheduler leads to standby takeover and the next workload
    still binds."""
    from volcano_tpu.cmd.local_up import multiproc_up, shutdown_procs
    from volcano_tpu.serving.leader import LEASE_KEY

    reference = _run_inprocess_reference("mp-job")

    api, procs = multiproc_up(
        nodes=3, node_cpu="8", node_mem="16Gi",
        standby_scheduler=True, schedule_period=0.1,
    )
    try:
        assert wait_for_admission(api, timeout=120), (
            "admission daemon never registered over the bus"
        )
        VolcanoClient(api).create_job(_gang_job("mp-job"))
        assert _wait(lambda: len([
            n for n in _bindings(api, "mp-job").values() if n
        ]) == 3, timeout=120), "multi-process topology never bound the job"
        assert _bindings(api, "mp-job") == reference

        # find the active scheduler via the bus-held lease and SIGKILL it
        import json

        def _holder():
            cm = api.get("ConfigMap", "volcano-system", "vtpu-scheduler")
            if cm is None:
                return None
            return json.loads(cm.data.get(LEASE_KEY, "{}")).get("holderIdentity")

        assert _wait(lambda: _holder() in ("sched-0", "sched-1"), 60)
        active = _holder()
        # scheduler procs are the last two spawned, ids sched-0/sched-1
        sched_procs = {f"sched-{i}": p for i, p in enumerate(procs[-2:])}
        sched_procs[active].send_signal(signal.SIGKILL)

        standby = "sched-1" if active == "sched-0" else "sched-0"
        assert _wait(lambda: _holder() == standby, 60), (
            "standby scheduler never took over after SIGKILL"
        )

        VolcanoClient(api).create_job(_gang_job("mp-job-2"))
        assert _wait(lambda: len([
            n for n in _bindings(api, "mp-job-2").values() if n
        ]) == 3, timeout=120), "standby scheduler never bound the next job"
    finally:
        api.close()
        shutdown_procs(procs)
