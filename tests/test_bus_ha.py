"""Replicated persistent bus (ISSUE 10): WAL + snapshot durability and
leader/follower apiserver HA.

Four acceptance pins live here:

* **Torn-write recovery property** — the WAL truncated at EVERY byte
  offset of the final record recovers to exactly the prefix store, no
  exception (`TestWalRecovery.test_truncation_at_every_byte_yields_prefix`).
* **Crash-at-fault-point sweep** — each ``wal.*`` injection point fires
  mid-workload; recovery equals the acknowledged-write prefix.
* **Restart-resume canary** — SIGKILL-equivalent apiserver restart with
  the same data dir: store digest preserved, and a live watch client
  RESUMES with ``bus_relists_total`` unchanged (no 410 storm).
* **Leader-kill chaos smoke** — 3 replicas, leader killed mid-write-
  stream: a follower promotes within one lease TTL, zero duplicate or
  lost acknowledged writes, surviving stores bit-identical; the slow
  soak extends this to rolling leader kills across real OS processes.
"""

import io
import os
import shutil
import socket
import tempfile
import threading
import time

import pytest

from volcano_tpu import faults
from volcano_tpu.apis import core
from volcano_tpu.bus.remote import RemoteAPIServer
from volcano_tpu.bus.replication import ReplicaManager, probe_status
from volcano_tpu.bus.server import BusServer
from volcano_tpu.bus.wal import (
    PersistentAPIServer,
    WalError,
    append_record,
    read_records,
    store_digest,
)
from volcano_tpu.client.apiserver import ApiError
from volcano_tpu.metrics import metrics


def _wait(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _counter(name_suffix: str) -> float:
    total = 0.0
    with metrics.registry._lock:
        for (name, _labels), v in metrics.registry._counters.items():
            if name.endswith(name_suffix):
                total += v
    return total


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _cm(name, ns="ns", data=None):
    return core.ConfigMap(
        metadata=core.ObjectMeta(name=name, namespace=ns),
        data=data or {"k": name},
    )


def _pod(name, ns="ns"):
    return core.Pod(
        metadata=core.ObjectMeta(name=name, namespace=ns),
        spec=core.PodSpec(
            containers=[core.Container(name="c", image="img")]
        ),
        status=core.PodStatus(phase="Pending"),
    )


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.configure(None)
    yield
    faults.configure(None)


# ---- WAL framing + recovery ----


class TestWalRecovery:
    def test_record_framing_roundtrip(self, tmp_path):
        path = str(tmp_path / "wal.log")
        payloads = [b'{"a":1}', b'{"b":' + b"x" * 300 + b'}', b"{}"]
        with open(path, "wb") as f:
            for p in payloads:
                append_record(f, p)
        got, valid, torn = read_records(path)
        assert got == payloads
        assert valid == os.path.getsize(path)
        assert not torn

    def test_crc_corruption_ends_the_scan(self, tmp_path):
        path = str(tmp_path / "wal.log")
        with open(path, "wb") as f:
            append_record(f, b'{"a":1}')
            append_record(f, b'{"b":2}')
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.seek(size - 2)
            f.write(b"\xff")
        got, valid, torn = read_records(path)
        assert got == [b'{"a":1}']
        assert torn

    def test_recovery_restores_store_seq_and_epoch(self, tmp_path):
        d = str(tmp_path / "data")
        api = PersistentAPIServer(d)
        api.create(_cm("a"))
        api.create(_pod("p0"))
        cm = api.get("ConfigMap", "ns", "a")
        cm.data = {"k": "a2"}
        api.update(cm)
        api.cas_bind("ns", "p0", "node-1")
        api.create(_pod("p1"))
        api.commit_batch(binds=[{"namespace": "ns", "name": "p1",
                                 "hostname": "node-2"}])
        api.delete("ConfigMap", "ns", "a")
        digest, seq, epoch = store_digest(api), api.event_seq, api.epoch
        api.close()

        rec = PersistentAPIServer(d)
        assert store_digest(rec) == digest
        assert rec.event_seq == seq
        assert rec.epoch == epoch
        assert rec.recovered["wal_records"] > 0 and not rec.recovered["torn"]
        assert rec.get("Pod", "ns", "p0").spec.node_name == "node-1"
        assert rec.get("Pod", "ns", "p1").spec.node_name == "node-2"
        assert rec.get("ConfigMap", "ns", "a") is None
        # recent-event ring (the resume backlog) survived too
        assert [e["seq"] for e in rec.recent_events()] == list(
            range(1, seq + 1)
        )
        rec.close()

    def test_transactions_are_single_records(self, tmp_path):
        """commit_batch and cas_bind land as ONE WAL record each."""
        d = str(tmp_path / "data")
        api = PersistentAPIServer(d)
        api.create(_pod("p0"))
        api.create(_pod("p1"))
        api.commit_batch(binds=[
            {"namespace": "ns", "name": "p0", "hostname": "n0"},
            {"namespace": "ns", "name": "p1", "hostname": "n1"},
        ])
        api.close()
        payloads, _, _ = read_records(os.path.join(d, "wal.log"))
        assert len(payloads) == 3  # 2 creates + 1 batch
        from volcano_tpu.bus import protocol

        batch = protocol.decode_record(payloads[-1])
        assert len(batch["events"]) == 2  # both binds in one record

    def test_snapshot_rotation_and_recovery(self, tmp_path):
        d = str(tmp_path / "data")
        api = PersistentAPIServer(d, snapshot_every=3)
        for i in range(8):
            api.create(_cm(f"c{i}"))
        digest = store_digest(api)
        api.close()
        assert os.path.exists(os.path.join(d, "snapshot.json"))
        rec = PersistentAPIServer(d, snapshot_every=3)
        assert rec.recovered["snapshot"]
        assert store_digest(rec) == digest
        assert rec.event_seq == 8
        rec.close()

    def test_truncation_at_every_byte_yields_prefix(self, tmp_path):
        """THE torn-write property: truncate the WAL at every byte
        offset of the final record → recovery yields exactly the
        prefix store, never an exception."""
        d = str(tmp_path / "data")
        api = PersistentAPIServer(d)
        api.create(_cm("a"))
        api.create(_cm("b"))
        api.create(_cm("c", data={"k": "x" * 64}))
        full_digest = store_digest(api)
        api.close()
        wal = os.path.join(d, "wal.log")
        payloads, total, _ = read_records(wal)
        assert len(payloads) == 3
        # byte offset where the final record begins
        with open(wal, "rb") as f:
            blob = f.read()
        final_start = total - (8 + len(payloads[-1]))  # header + payload

        # expected prefix state: recover from a clean 2-record log
        ref = str(tmp_path / "ref")
        shutil.copytree(d, ref)
        with open(os.path.join(ref, "wal.log"), "r+b") as f:
            f.truncate(final_start)
        ref_api = PersistentAPIServer(ref)
        prefix_digest = store_digest(ref_api)
        assert ref_api.event_seq == 2
        ref_api.close()

        for offset in range(final_start, total + 1):
            case = str(tmp_path / f"case{offset}")
            shutil.copytree(d, case)
            with open(os.path.join(case, "wal.log"), "r+b") as f:
                f.truncate(offset)
            rec = PersistentAPIServer(case)
            got = store_digest(rec)
            if offset == total:
                assert got == full_digest
            else:
                assert got == prefix_digest, f"offset {offset}"
                assert rec.event_seq == 2
            rec.close()
            shutil.rmtree(case)


# ---- fault-point recovery sweep ----


class TestWalFaults:
    def _acked_workload(self, api):
        """Apply writes until one raises; returns the digest after the
        last ACKED write."""
        digest = store_digest(api)
        try:
            for i in range(10):
                api.create(_cm(f"w{i}"))
                digest = store_digest(api)
        except ApiError:
            pass
        return digest

    @pytest.mark.parametrize("point", ["wal.write_fail", "wal.torn_tail"])
    def test_crash_at_fault_point_recovers_acked_prefix(
        self, tmp_path, point
    ):
        d = str(tmp_path / point.replace(".", "_"))
        api = PersistentAPIServer(d)
        faults.configure(f"seed=7;{point}=1:count=1:after=4")
        acked_digest = self._acked_workload(api)
        faults.configure(None)
        # the LIVE store rolled the failed write back too — reads and
        # AlreadyExists-based retries never observe an unacked write
        assert store_digest(api) == acked_digest
        # crash: no clean close, no snapshot — recovery sees exactly
        # what hit disk
        rec = PersistentAPIServer(d)
        assert store_digest(rec) == acked_digest
        if point == "wal.torn_tail":
            assert rec.recovered["torn"]
        rec.close()
        api.close()

    def test_fsync_delay_still_acks(self, tmp_path):
        api = PersistentAPIServer(str(tmp_path / "d"))
        faults.configure("seed=1;wal.fsync_delay=1:count=2:ms=30")
        t0 = time.perf_counter()
        api.create(_cm("slow"))
        assert time.perf_counter() - t0 >= 0.025
        assert api.get("ConfigMap", "ns", "slow") is not None
        api.close()

    def test_leader_kill_hook_fires(self, tmp_path):
        api = PersistentAPIServer(str(tmp_path / "d"))
        fired = []
        api.kill_hook = lambda: fired.append(True)
        faults.configure("seed=1;bus.leader_kill=1:count=1")
        api.create(_cm("boom"))
        assert fired == [True]
        api.close()

    def test_wal_write_fail_is_not_acked(self, tmp_path):
        api = PersistentAPIServer(str(tmp_path / "d"))
        faults.configure("seed=1;wal.write_fail=1:count=1")
        with pytest.raises(WalError):
            api.create(_cm("lost"))
        api.close()


# ---- restart-resume: the bus_relists_total canary ----


class TestRestartResume:
    def test_restart_with_data_dir_resumes_watches_no_relist(self, tmp_path):
        """Kill-and-restart the apiserver (new process ≡ new store
        object recovered from the same data dir, new BusServer on the
        same port): a live client's watch RESUMES — every event exactly
        once, ``bus_relists_total`` unchanged."""
        d = str(tmp_path / "data")
        port = _free_port()
        api = PersistentAPIServer(d)
        bus = BusServer(api, port=port).start()
        cli = RemoteAPIServer(f"tcp://127.0.0.1:{port}")
        assert cli.wait_ready(10)
        events = []
        lock = threading.Lock()

        def on_event(event, old, new):
            with lock:
                events.append((event, new.metadata.name if new else None))

        cli.watch("ConfigMap", on_event, send_initial=False)
        for i in range(3):
            cli.create(_cm(f"pre{i}"))
        assert _wait(lambda: len(events) == 3)
        relists_before = _counter("bus_relists_total")
        digest_before = store_digest(api)

        # SIGKILL-equivalent: the process dies — in-memory store state
        # is lost, only the data dir survives
        bus.stop()
        api.close()
        api2 = PersistentAPIServer(d)
        assert store_digest(api2) == digest_before
        bus2 = BusServer(api2, port=port).start()
        try:
            # the client reconnects and RESUMES (same epoch from the
            # data-dir meta, sequence + backlog restored)
            assert _wait(lambda: cli.health(), timeout=15.0)
            for i in range(2):
                cli.create(_cm(f"post{i}"))
            assert _wait(lambda: len(events) == 5, timeout=15.0), events
            with lock:
                names = [n for _e, n in events]
            assert names == ["pre0", "pre1", "pre2", "post0", "post1"]
            assert _counter("bus_relists_total") == relists_before, (
                "a relist fired — the restart forced a 410 storm"
            )
        finally:
            cli.close()
            bus2.stop()
            api2.close()

    def test_volatile_store_restart_still_relists(self, tmp_path):
        """Contrast pin: WITHOUT a data dir the old behavior stands —
        a restarted incarnation mints a new epoch and resumes are
        rejected (this is exactly what the WAL removes)."""
        from volcano_tpu.client.apiserver import APIServer

        port = _free_port()
        api = APIServer()
        bus = BusServer(api, port=port).start()
        cli = RemoteAPIServer(f"tcp://127.0.0.1:{port}")
        assert cli.wait_ready(10)
        seen = []
        cli.watch("ConfigMap", lambda e, o, n: seen.append(e),
                  send_initial=False)
        cli.create(_cm("x"))
        assert _wait(lambda: len(seen) == 1)
        relists_before = _counter("bus_relists_total")
        bus.stop()
        bus2 = BusServer(APIServer(), port=port).start()
        try:
            assert _wait(
                lambda: _counter("bus_relists_total") > relists_before,
                timeout=15.0,
            )
        finally:
            cli.close()
            bus2.stop()


# ---- leader/follower replication ----


class _Replica:
    def __init__(self, data_dir, endpoints, index, port, lease_ttl=1.0):
        self.store = PersistentAPIServer(data_dir)
        self.mgr = ReplicaManager(self.store, endpoints, index,
                                  lease_ttl=lease_ttl)
        self.bus = BusServer(self.store, port=port, replica=self.mgr)

    def start(self):
        self.bus.start()
        self.mgr.start()
        return self

    def kill(self):
        """Crash-stop: server + manager die, memory state is gone.
        The manager stops first — its coordinator shutdown aborts any
        commit parked on the quorum, which would otherwise hold the
        store lock (and block this teardown) for the full timeout."""
        self.mgr.stop()
        self.bus.stop()
        self.store.close()

    def stop(self):
        self.kill()


def _spawn_group(tmp_path, n=3, lease_ttl=1.0):
    ports = [_free_port() for _ in range(n)]
    endpoints = [f"tcp://127.0.0.1:{p}" for p in ports]
    replicas = [
        _Replica(str(tmp_path / f"r{i}"), endpoints, i, ports[i],
                 lease_ttl=lease_ttl).start()
        for i in range(n)
    ]
    return replicas, endpoints


def _roles(replicas, skip=()):
    return [r.mgr.role for i, r in enumerate(replicas) if i not in skip]


class TestReplicationSmoke:
    def test_leader_kill_promotes_within_ttl_no_lost_or_dup_writes(
        self, tmp_path
    ):
        """The chaos smoke: 3 replicas, a client streaming writes
        through a FOLLOWER connection, the leader SIGKILLed mid-stream.
        A follower promotes within one lease TTL (of detection), every
        acknowledged write survives exactly once, surviving stores are
        bit-identical, and the follower-connected client's watch never
        relists."""
        ttl = 1.0
        replicas, endpoints = _spawn_group(tmp_path, 3, lease_ttl=ttl)
        cli = None
        try:
            assert _wait(
                lambda: _roles(replicas).count("leader") == 1
                and _roles(replicas).count("follower") == 2,
                timeout=15.0,
            ), _roles(replicas)
            lidx = [i for i, r in enumerate(replicas)
                    if r.mgr.role == "leader"][0]
            fidx = (lidx + 1) % 3

            cli = RemoteAPIServer(endpoints[fidx])
            assert cli.wait_ready(10)
            watched = []
            cli.watch("ConfigMap", lambda e, o, n: watched.append(e),
                      send_initial=False)

            acked = []
            stop_writes = threading.Event()
            failures = []

            def writer():
                i = 0
                while not stop_writes.is_set():
                    name = f"w{i}"
                    try:
                        cli.create(_cm(name))
                        acked.append(name)
                    except ApiError:
                        failures.append(name)  # NOT acked — may be lost
                    i += 1
                    time.sleep(0.01)

            t = threading.Thread(target=writer, daemon=True)
            t.start()
            assert _wait(lambda: len(acked) >= 5, timeout=10.0)
            relists_before = _counter("bus_relists_total")

            killed_at = time.monotonic()
            replicas[lidx].kill()
            assert _wait(
                lambda: _roles(replicas, skip=(lidx,)).count("leader") == 1,
                timeout=20.0,
            ), "no follower promoted"
            promotion_s = time.monotonic() - killed_at
            # detection (pull failure persisting one TTL) + election
            # probes; typical is ~1.2×TTL (see the drill logs) — the
            # bound here carries slack for core-starved CI interpreters
            # where 1.5s status probes stack up
            assert promotion_s <= ttl * 10 + 5.0, promotion_s

            # writes keep landing through the surviving connection
            n_before = len(acked)
            assert _wait(lambda: len(acked) >= n_before + 3, timeout=15.0)
            stop_writes.set()
            t.join(timeout=5)

            survivors = [r for i, r in enumerate(replicas) if i != lidx]
            # every ACKED write exists exactly once on every survivor
            def converged():
                for r in survivors:
                    names = {o.metadata.name
                             for o in r.store.list("ConfigMap")}
                    if not set(acked) <= names:
                        return False
                return True

            assert _wait(converged, timeout=10.0), "acked write lost"
            digests = {store_digest(r.store) for r in survivors}
            assert len(digests) == 1, "surviving stores diverged"
            # the follower-connected client's watch cursor survived:
            # no relist anywhere
            assert _counter("bus_relists_total") == relists_before
        finally:
            if cli is not None:
                cli.close()
            for i, r in enumerate(replicas):
                try:
                    r.stop()
                except Exception:
                    pass

    def test_follower_proxies_writes_and_serves_reads(self, tmp_path):
        replicas, endpoints = _spawn_group(tmp_path, 3, lease_ttl=1.0)
        cli = None
        try:
            assert _wait(
                lambda: _roles(replicas).count("leader") == 1
                and _roles(replicas).count("follower") == 2,
                timeout=15.0,
            ), _roles(replicas)
            fidx = [i for i, r in enumerate(replicas)
                    if r.mgr.role == "follower"][0]
            cli = RemoteAPIServer(endpoints[fidx])
            assert cli.wait_ready(10)
            st = cli.bus_status()
            assert st["role"] == "follower"
            created = cli.create(_cm("via-follower"))
            assert created.metadata.resource_version > 0
            # read-your-write through the same follower (get proxies)
            assert cli.get("ConfigMap", "ns", "via-follower") is not None
            # the local list catches up via replication
            assert _wait(
                lambda: any(
                    o.metadata.name == "via-follower"
                    for o in cli.list("ConfigMap")
                ),
                timeout=5.0,
            )
        finally:
            if cli is not None:
                cli.close()
            for r in replicas:
                r.stop()

    def test_rejoining_old_leader_demotes_and_resyncs(self, tmp_path):
        ttl = 0.8
        replicas, endpoints = _spawn_group(tmp_path, 3, lease_ttl=ttl)
        cli = None
        try:
            assert _wait(
                lambda: _roles(replicas).count("leader") == 1
                and _roles(replicas).count("follower") == 2,
                timeout=15.0,
            ), _roles(replicas)
            lidx = [i for i, r in enumerate(replicas)
                    if r.mgr.role == "leader"][0]
            fidx = (lidx + 1) % 3
            cli = RemoteAPIServer(endpoints[fidx])
            assert cli.wait_ready(10)
            cli.create(_cm("before-kill"))
            old_dir = replicas[lidx].store.data_dir
            old_port = int(endpoints[lidx].rsplit(":", 1)[1])
            replicas[lidx].kill()
            assert _wait(
                lambda: _roles(replicas, skip=(lidx,)).count("leader") == 1,
                timeout=15.0,
            )
            # writes land while the old leader is down
            for attempt in range(40):
                try:
                    cli.create(_cm("while-down"))
                    break
                except ApiError:
                    time.sleep(0.2)
            # the old leader restarts from its data dir: it must DEMOTE
            # (higher term exists) and catch up, not split the brain
            reborn = _Replica(old_dir, endpoints, lidx, old_port,
                              lease_ttl=ttl).start()
            replicas[lidx] = reborn
            assert _wait(
                lambda: reborn.mgr.role == "follower", timeout=15.0
            ), reborn.mgr.role
            assert _wait(
                lambda: reborn.store.get("ConfigMap", "ns", "while-down")
                is not None,
                timeout=10.0,
            )
            assert _roles(replicas).count("leader") == 1
            digests = {store_digest(r.store) for r in replicas}
            assert _wait(
                lambda: len({store_digest(r.store) for r in replicas}) == 1,
                timeout=10.0,
            ), digests
        finally:
            if cli is not None:
                cli.close()
            for r in replicas:
                try:
                    r.stop()
                except Exception:
                    pass


# ---- vtctl bus status ----


class TestVtctlBusStatus:
    def test_byte_identical_over_both_backends(self, tmp_path):
        from volcano_tpu.cli.vtctl import main as vtctl_main

        api = PersistentAPIServer(str(tmp_path / "d"))
        api.create(_cm("s"))
        port = _free_port()
        bus = BusServer(api, port=port).start()
        try:
            buf_local = io.StringIO()
            assert vtctl_main(["bus", "status"], api=api,
                              out=buf_local) == 0
            buf_remote = io.StringIO()
            assert vtctl_main(
                ["--bus", f"tcp://127.0.0.1:{port}", "bus", "status"],
                out=buf_remote,
            ) == 0
            assert buf_local.getvalue() == buf_remote.getvalue()
            text = buf_local.getvalue()
            assert "Role:" in text and "WAL:" in text
            assert "Applied seq:        1" in text
        finally:
            bus.stop()
            api.close()

    def test_standalone_store_renders(self):
        from volcano_tpu.cli.vtctl import main as vtctl_main
        from volcano_tpu.client.apiserver import APIServer

        buf = io.StringIO()
        assert vtctl_main(["bus", "status"], api=APIServer(), out=buf) == 0
        assert "standalone" in buf.getvalue()
        assert "Persistent:         false" in buf.getvalue()

    def test_leader_status_shows_followers_and_lag(self, tmp_path):
        replicas, endpoints = _spawn_group(tmp_path, 2, lease_ttl=1.0)
        try:
            assert _wait(
                lambda: _roles(replicas).count("leader") == 1
                and _roles(replicas).count("follower") == 1,
                timeout=15.0,
            )
            leader = [r for r in replicas if r.mgr.role == "leader"][0]
            leader.store.create(_cm("lag"))
            status = probe_status(
                endpoints[replicas.index(leader)]
            )
            assert status["role"] == "leader"
            assert status["quorum"] == 2
            assert _wait(
                lambda: any(
                    f["acked_seq"] >= 1
                    for f in (probe_status(
                        endpoints[replicas.index(leader)]
                    ) or {}).get("followers", {}).values()
                ),
                timeout=10.0,
            )
        finally:
            for r in replicas:
                r.stop()


# ---- dynamic membership: WAL records, add/remove, pre-vote ----


class TestMembershipWal:
    def test_membership_epoch_recovered_alongside_term_seq_backlog(
        self, tmp_path
    ):
        """A membership-config record consumes ONE synthetic slot in
        the event-seq space (cursors move past it, the CRC chain covers
        it) and the epoch recovers next to term/seq/backlog."""
        d = str(tmp_path / "data")
        api = PersistentAPIServer(d)
        api.create(_cm("a"))
        seq1 = api.log_membership(
            {"epoch": 1, "endpoints": ["tcp://h:1", "tcp://h:2"]}
        )
        api.create(_cm("b"))
        api.log_membership(
            {"epoch": 2,
             "endpoints": ["tcp://h:1", "tcp://h:2", "tcp://h:3"]}
        )
        api.set_term(4)
        digest, seq, chain = store_digest(api), api.event_seq, api.chain
        api.close()

        rec = PersistentAPIServer(d)
        assert store_digest(rec) == digest
        assert rec.event_seq == seq
        assert rec.chain == chain
        assert rec.term == 4
        cfg = rec.membership_config()
        assert cfg == {"epoch": 2, "endpoints":
                       ["tcp://h:1", "tcp://h:2", "tcp://h:3"]}
        # the backlog (resume surface) skips the config records' seqs —
        # no watcher ever saw an event there
        backlog_seqs = [e["seq"] for e in rec.recent_events()]
        assert backlog_seqs == [1, 3]
        assert seq1 == 2
        rec.close()

    def test_membership_survives_snapshot_rotation(self, tmp_path):
        d = str(tmp_path / "data")
        api = PersistentAPIServer(d, snapshot_every=2)
        api.log_membership({"epoch": 5, "endpoints": ["tcp://h:1"]})
        for i in range(6):
            api.create(_cm(f"c{i}"))
        api.close()
        assert os.path.exists(os.path.join(d, "snapshot.json"))
        rec = PersistentAPIServer(d, snapshot_every=2)
        assert rec.recovered["snapshot"]
        assert rec.membership_config() == {
            "epoch": 5, "endpoints": ["tcp://h:1"],
        }
        rec.close()

    def test_truncation_at_every_byte_of_membership_record(self, tmp_path):
        """The torn-tail property sweep extended to membership-config
        records: a WAL whose FINAL record is a config change, truncated
        at every byte offset of that record, recovers to exactly the
        prefix (prior epoch, prior seq) — never an exception, never a
        half-applied config."""
        d = str(tmp_path / "data")
        api = PersistentAPIServer(d)
        api.create(_cm("a"))
        api.log_membership({"epoch": 1, "endpoints": ["tcp://h:1"]})
        api.create(_cm("b"))
        api.log_membership(
            {"epoch": 2, "endpoints": ["tcp://h:1", "tcp://h:2"]}
        )
        full_digest, full_seq = store_digest(api), api.event_seq
        api.close()
        wal = os.path.join(d, "wal.log")
        payloads, total, _ = read_records(wal)
        assert len(payloads) == 4
        final_start = total - (8 + len(payloads[-1]))

        for offset in range(final_start, total + 1):
            case = str(tmp_path / f"case{offset}")
            shutil.copytree(d, case)
            with open(os.path.join(case, "wal.log"), "r+b") as f:
                f.truncate(offset)
            rec = PersistentAPIServer(case)
            if offset == total:
                assert store_digest(rec) == full_digest
                assert rec.event_seq == full_seq
                assert rec.membership_config()["epoch"] == 2
            else:
                # the torn config record applied NOTHING: the prior
                # epoch survives whole
                assert store_digest(rec) == full_digest  # objects same
                assert rec.event_seq == full_seq - 1
                assert rec.membership_config() == {
                    "epoch": 1, "endpoints": ["tcp://h:1"],
                }, f"offset {offset}"
            rec.close()
            shutil.rmtree(case)


class TestDynamicMembership:
    def test_add_replica_learner_catch_up_then_commit(self, tmp_path):
        """Grow 3 -> 4 while running: the joiner attaches as a learner
        (started with --replicas listing the whole new group, itself
        last), bootstraps, and the membership record commits once its
        lag has closed.  The new member then replicates writes."""
        replicas, endpoints = _spawn_group(tmp_path, 3, lease_ttl=1.0)
        cli = None
        joiner = None
        try:
            assert _wait(
                lambda: _roles(replicas).count("leader") == 1
                and _roles(replicas).count("follower") == 2,
                timeout=15.0,
            ), _roles(replicas)
            lidx = _roles(replicas).index("leader")
            cli = RemoteAPIServer(endpoints[(lidx + 1) % 3])
            assert cli.wait_ready(10)
            cli.create(_cm("w0"))
            # the first leader seeded epoch 1 (the static list) into
            # the log — the base every later change is a delta against
            assert _wait(
                lambda: all(r.store.membership_config() is not None
                            for r in replicas),
                timeout=10.0,
            )
            assert replicas[lidx].store.membership_config()["epoch"] == 1

            port = _free_port()
            url = f"tcp://127.0.0.1:{port}"
            joiner = _Replica(str(tmp_path / "r3"), endpoints + [url],
                              3, port, lease_ttl=1.0).start()
            # the operator surface end-to-end: vtctl parser → remote
            # client → follower proxy → leader catch-up gate → commit
            from volcano_tpu.cli.vtctl import main as vtctl_main

            out = io.StringIO()
            assert vtctl_main(
                ["--bus", endpoints[(lidx + 1) % 3],
                 "bus", "add-replica", url],
                out=out,
            ) == 0
            assert "membership change committed" in out.getvalue()
            assert "(epoch 2)" in out.getvalue()
            assert url in out.getvalue()
            # a retry of the SAME add is cleanly refused (idempotence
            # surface the loadgen drill's ambiguous retries lean on)
            with pytest.raises(ApiError, match="already a member"):
                cli.bus_add_replica(url)
            assert _wait(lambda: joiner.mgr.role == "follower",
                         timeout=10.0), joiner.mgr.role
            cli.create(_cm("w1"))
            assert _wait(
                lambda: joiner.store.get("ConfigMap", "ns", "w1")
                is not None,
                timeout=10.0,
            )
            st = probe_status(url)
            assert st["membership_epoch"] == 2
            assert sorted(endpoints + [url]) == st["membership"]
        finally:
            if cli is not None:
                cli.close()
            if joiner is not None:
                joiner.stop()
            for r in replicas:
                try:
                    r.stop()
                except Exception:
                    pass

    def test_remove_replica_stands_down_and_group_commits(self, tmp_path):
        replicas, endpoints = _spawn_group(tmp_path, 3, lease_ttl=1.0)
        cli = None
        try:
            assert _wait(
                lambda: _roles(replicas).count("leader") == 1
                and _roles(replicas).count("follower") == 2,
                timeout=15.0,
            ), _roles(replicas)
            lidx = _roles(replicas).index("leader")
            cidx = (lidx + 1) % 3
            victim = next(i for i in range(3)
                          if i not in (lidx, cidx))
            cli = RemoteAPIServer(endpoints[cidx])
            assert cli.wait_ready(10)
            cli.create(_cm("w0"))
            res = cli.bus_remove_replica(endpoints[victim])
            assert res["committed"] and res["epoch"] == 2
            assert endpoints[victim] not in res["endpoints"]
            # the retired replica stands down: alive, never pulls or
            # elects (a restart re-admits it as a learner)
            assert _wait(
                lambda: replicas[victim].mgr.role == "removed",
                timeout=15.0,
            ), replicas[victim].mgr.role
            # the shrunk group still commits (quorum of 2 = 2)
            cli.create(_cm("w1"))
            live = [r for i, r in enumerate(replicas) if i != victim]
            assert _wait(
                lambda: all(
                    r.store.get("ConfigMap", "ns", "w1") is not None
                    for r in live
                ),
                timeout=10.0,
            )
            cfgs = {
                tuple(r.store.membership_config()["endpoints"])
                for r in live
            }
            assert len(cfgs) == 1
        finally:
            if cli is not None:
                cli.close()
            for r in replicas:
                try:
                    r.stop()
                except Exception:
                    pass

    def test_removal_guards(self, tmp_path):
        """Removal is refused aimed at the leader, refused when the
        shrunk group could not commit, and a second change is refused
        while the first is in flight."""
        replicas, endpoints = _spawn_group(tmp_path, 3, lease_ttl=1.0)
        try:
            assert _wait(
                lambda: _roles(replicas).count("leader") == 1
                and _roles(replicas).count("follower") == 2,
                timeout=15.0,
            ), _roles(replicas)
            lidx = _roles(replicas).index("leader")
            leader = replicas[lidx].mgr
            with pytest.raises(ApiError,
                               match="cannot remove the current leader"):
                leader.remove_replica(endpoints[lidx])
            # kill one follower: removing the OTHER (live) follower
            # would leave [leader, corpse] — a group that cannot commit
            dead = (lidx + 1) % 3
            live = (lidx + 2) % 3
            replicas[dead].kill()
            with pytest.raises(ApiError, match="removal refused"):
                leader.remove_replica(endpoints[live])
            # the single-change discipline, tested at the seam
            leader._begin_change("add tcp://x:1")
            with pytest.raises(ApiError, match="already in flight"):
                leader._begin_change("add tcp://y:1")
            leader._end_change()
            # removing the CORPSE is allowed: [leader, live] commits —
            # with the flight recorder on, so the repl:membership span
            # seam runs (zero-cost-off everywhere else)
            from volcano_tpu import obs

            obs.enable(replicas[lidx].store, identity="membership-test")
            try:
                res = leader.remove_replica(endpoints[dead])
            finally:
                obs.disable()
            assert res["committed"]
            assert endpoints[dead] not in res["endpoints"]
        finally:
            for i, r in enumerate(replicas):
                try:
                    r.stop()
                except Exception:
                    pass

    def test_uncommitted_change_keeps_latch_until_commit(self, tmp_path):
        """Appended-but-uncommitted keeps the single-change latch HELD
        (a second change must not stack on an uncommitted base); once
        the record commits, the next change request resolves the latch
        and proceeds."""
        replicas, endpoints = _spawn_group(tmp_path, 3, lease_ttl=1.0)
        joiner = None
        try:
            assert _wait(
                lambda: _roles(replicas).count("leader") == 1
                and _roles(replicas).count("follower") == 2,
                timeout=15.0,
            ), _roles(replicas)
            lidx = _roles(replicas).index("leader")
            leader = replicas[lidx].mgr
            assert _wait(
                lambda: all(r.store.membership_config() is not None
                            for r in replicas),
                timeout=10.0,
            )
            port = _free_port()
            url = f"tcp://127.0.0.1:{port}"
            joiner = _Replica(str(tmp_path / "r3"), endpoints + [url],
                              3, port, lease_ttl=1.0).start()
            assert _wait(
                lambda: leader.coordinator.catch_up_lag(url) == 0,
                timeout=10.0,
            )
            # drop config shipments and shrink the commit wait so the
            # add APPENDS but times out uncommitted
            leader.coordinator.commit_timeout = 1.0
            faults.configure("repl.config_drop=1")
            with pytest.raises(ApiError, match="not yet committed"):
                leader.add_replica(url)
            # the latch survives the failed request: a second change is
            # refused, not stacked on the uncommitted epoch-2 base
            with pytest.raises(ApiError, match="already in flight"):
                leader.remove_replica(endpoints[(lidx + 1) % 3])
            # heal: shipments flow, the record commits, and the next
            # change request resolves the latch against the commit
            # point — a repeat add now reports "already a member"
            # (the epoch-2 record committed; it is not re-appended)
            faults.configure(None)
            assert _wait(
                lambda: leader.coordinator.commit_seq()
                >= replicas[lidx].store.event_seq,
                timeout=10.0,
            )
            with pytest.raises(ApiError, match="already a member"):
                leader.add_replica(url)
        finally:
            faults.configure(None)
            if joiner is not None:
                joiner.stop()
            for r in replicas:
                try:
                    r.stop()
                except Exception:
                    pass

    def test_nonmember_replica_never_elects(self, tmp_path):
        """A replica whose own log says it is not a voting member — a
        learner awaiting admission, or a removed replica restarted with
        its stale --replicas list — must never promote, even with the
        leader dead and a probe majority visible (the zombie-leader
        case)."""
        store = PersistentAPIServer(str(tmp_path / "d"))
        try:
            mgr = ReplicaManager(
                store,
                ["tcp://127.0.0.1:1", "tcp://127.0.0.1:2",
                 "tcp://127.0.0.1:3"],
                0, lease_ttl=1.0,
            )
            # the committed config does NOT list this replica's url
            store.log_membership({
                "epoch": 2,
                "endpoints": ["tcp://127.0.0.1:2", "tcp://127.0.0.1:3"],
            })
            assert mgr._elect() is None
            assert mgr.role != "leader"
        finally:
            store.close()

    def test_url_less_follower_votes_under_dynamic_config(self):
        """Rolling-upgrade rule: a follower that never reported a url
        (a pre-v7 peer) VOTES even once a membership config is adopted
        — excluding it would wedge the quorum for the whole upgrade.
        A follower with a KNOWN non-member url (learner) still never
        counts."""
        from volcano_tpu.bus.replication import ReplicationCoordinator

        coord = ReplicationCoordinator(3, "leader", 0, 0)
        coord.set_group(3, ["tcp://a:1", "tcp://b:1", "tcp://c:1"])
        coord.leader_append(5, 1, 0, b"{}", 0.0)
        assert coord.commit_seq() == 0
        # a v7 learner (known url outside the config) acks: no commit
        coord.ack("learner", 5, url="tcp://learner:1")
        assert coord.commit_seq() == 0
        # a pre-v7 follower (no url) acks: quorum of 2 reached
        coord.ack("old-peer", 5)
        assert coord.commit_seq() == 5
        coord.shutdown()

    def test_proxy_budget_covers_membership_ops(self):
        """A follower's per-hop proxy budget for the membership ops
        must cover the leader's legitimate catch-up + commit waits
        (the remote client's own 30s budget) — the 4s election-scale
        cap made a proxied add-replica time out while the change went
        on to COMMIT at the leader."""
        from volcano_tpu.bus.replication import proxy_timeout

        assert proxy_timeout("bus_add_replica", 1.0) >= 30.0
        assert proxy_timeout("bus_remove_replica", 1.0) >= 30.0
        # ordinary writes keep the election-timescale bound
        assert proxy_timeout("create", 1.0) == 4.0
        assert proxy_timeout("create", 100.0) == 15.0

    def test_removal_via_snapshot_stands_down(self, tmp_path):
        """_note_shipped_config applies the SAME rule to records and
        snapshots: a config that no longer lists a once-member replica
        ends its follow episode (a removal can arrive via the snapshot
        bootstrap — a down member removed while its log diverged — and
        on a write-idle group no record would ever re-run the check)."""
        store = PersistentAPIServer(str(tmp_path / "d"))
        try:
            mgr = ReplicaManager(
                store,
                ["tcp://127.0.0.1:1", "tcp://127.0.0.1:2"],
                0, lease_ttl=1.0,
            )
            # admitted once...
            store.log_membership({
                "epoch": 1,
                "endpoints": ["tcp://127.0.0.1:1", "tcp://127.0.0.1:2"],
            })
            assert mgr._note_shipped_config() is False
            with mgr._lock:
                assert mgr._was_member
            # ...then a shipped config (record or snapshot) drops us
            store.log_membership({
                "epoch": 2, "endpoints": ["tcp://127.0.0.1:2"],
            })
            assert mgr._note_shipped_config() is True
        finally:
            store.close()

    def test_lost_leader_clears_recorded_view(self, tmp_path):
        """When a follow episode ends because the leader is provably
        lost (unreachable past the TTL), the recorded leader view is
        CLEARED — so proxies answer "no leader elected" and /healthz
        degrades to below-quorum while the election runs, instead of
        answering "ok" with a dead leader url."""
        ttl = 0.8
        replicas, endpoints = _spawn_group(tmp_path, 3, lease_ttl=ttl)
        try:
            assert _wait(
                lambda: _roles(replicas).count("leader") == 1
                and _roles(replicas).count("follower") == 2,
                timeout=15.0,
            ), _roles(replicas)
            lidx = _roles(replicas).index("leader")
            followers = [r for i, r in enumerate(replicas) if i != lidx]
            # kill the leader AND one follower: the survivor cannot
            # elect (no majority) and must clear its leader view
            replicas[lidx].kill()
            followers[0].kill()
            survivor = followers[1]
            assert _wait(
                lambda: survivor.mgr.leader_url is None,
                timeout=ttl * 10 + 10.0,
            ), survivor.mgr.leader_url
            assert survivor.mgr.role != "leader"
        finally:
            for r in replicas:
                try:
                    r.stop()
                except Exception:
                    pass

    def test_add_refuses_url_that_never_catches_up(self, tmp_path):
        replicas, endpoints = _spawn_group(tmp_path, 3, lease_ttl=1.0)
        try:
            assert _wait(
                lambda: _roles(replicas).count("leader") == 1,
                timeout=15.0,
            ), _roles(replicas)
            lidx = _roles(replicas).index("leader")
            with pytest.raises(ApiError, match="never caught up"):
                replicas[lidx].mgr.add_replica(
                    f"tcp://127.0.0.1:{_free_port()}",
                    catch_up_timeout=1.0,
                )
            # the refused change left NO config behind and cleared the
            # in-flight latch (a retry is allowed)
            assert replicas[lidx].store.membership_config()["epoch"] == 1
            assert replicas[lidx].mgr._change_inflight is None
        finally:
            for r in replicas:
                try:
                    r.stop()
                except Exception:
                    pass


class TestMembershipChaos:
    def test_leader_killed_mid_config_change_one_surviving_config(
        self, tmp_path
    ):
        """THE membership chaos drill: the leader is SIGKILLed while a
        config change is appended-but-uncommitted (its shipment dropped
        by ``repl.config_drop``).  The surviving majority elects, the
        elected most-advanced log decides, and exactly ONE config
        survives everywhere — with zero lost acknowledged writes."""
        ttl = 1.0
        replicas, endpoints = _spawn_group(tmp_path, 3, lease_ttl=ttl)
        cli = None
        joiner = None
        lidx = -1
        try:
            assert _wait(
                lambda: _roles(replicas).count("leader") == 1
                and _roles(replicas).count("follower") == 2,
                timeout=15.0,
            ), _roles(replicas)
            lidx = _roles(replicas).index("leader")
            fidx = (lidx + 1) % 3
            cli = RemoteAPIServer(endpoints[fidx])
            assert cli.wait_ready(10)
            cli.create(_cm("acked-before"))
            # wait for the epoch-1 seed to ship BEFORE arming the drop
            # (a dropped seed would wedge every follower's cursor)
            assert _wait(
                lambda: all(r.store.membership_config() is not None
                            for r in replicas),
                timeout=10.0,
            )

            port = _free_port()
            url = f"tcp://127.0.0.1:{port}"
            joiner = _Replica(str(tmp_path / "r3"), endpoints + [url],
                              3, port, lease_ttl=ttl).start()
            # wait until the learner has pulled level (lag provable 0)
            # so add_replica passes the catch-up gate immediately and
            # parks on the COMMIT wait — the window we kill into
            assert _wait(
                lambda: replicas[lidx].mgr.coordinator is not None
                and replicas[lidx].mgr.coordinator.catch_up_lag(url) == 0,
                timeout=10.0,
            )
            faults.configure("repl.config_drop=1")
            add_err = []

            def _add():
                try:
                    replicas[lidx].mgr.add_replica(url)
                except ApiError as e:
                    add_err.append(str(e))

            t = threading.Thread(target=_add, daemon=True)
            t.start()
            # the config record is appended (epoch 2 on the leader) but
            # its shipments are dropped — no follower holds it
            assert _wait(
                lambda: replicas[lidx].store.membership_config()["epoch"]
                == 2,
                timeout=10.0,
            )
            replicas[lidx].kill()
            faults.configure(None)
            t.join(timeout=15)
            assert t.is_alive() is False

            # a survivor of the OLD config promotes (2/3 majority)
            assert _wait(
                lambda: _roles(replicas, skip=(lidx,)).count("leader")
                == 1,
                timeout=25.0,
            ), _roles(replicas, skip=(lidx,))
            survivors = [r for i, r in enumerate(replicas) if i != lidx]
            # exactly one surviving config: the uncommitted epoch-2
            # record died with the leader's log — every live replica
            # (joiner included) agrees on epoch 1
            def one_config():
                cfgs = {
                    tuple(r.store.membership_config()["endpoints"])
                    for r in survivors + [joiner]
                    if r.store.membership_config() is not None
                }
                return len(cfgs) == 1
            assert _wait(one_config, timeout=15.0)
            cfg = survivors[0].store.membership_config()
            assert cfg["epoch"] == 1
            assert cfg["endpoints"] == endpoints
            # zero lost acknowledged writes, no split-brain
            for r in survivors:
                assert _wait(
                    lambda r=r: r.store.get(
                        "ConfigMap", "ns", "acked-before"
                    ) is not None,
                    timeout=10.0,
                )
            assert _roles(replicas, skip=(lidx,)).count("leader") == 1
        finally:
            faults.configure(None)
            if cli is not None:
                cli.close()
            if joiner is not None:
                joiner.stop()
            for i, r in enumerate(replicas):
                if i == lidx:
                    continue
                try:
                    r.stop()
                except Exception:
                    pass


class TestPreVote:
    def test_partitioned_rejoiner_cannot_depose_stable_leader(
        self, tmp_path
    ):
        """THE pre-vote pin: a follower partitioned from the leader —
        but NOT from the other follower (the asymmetric case the
        reachable-majority floor cannot catch) — probes, collects
        denials, and goes back to retrying WITHOUT incrementing the
        term.  The stable leader's term never advances; the healed
        rejoiner re-attaches at the same term."""
        ttl = 0.8
        replicas, endpoints = _spawn_group(tmp_path, 3, lease_ttl=ttl)
        cli = None
        try:
            assert _wait(
                lambda: _roles(replicas).count("leader") == 1
                and _roles(replicas).count("follower") == 2,
                timeout=15.0,
            ), _roles(replicas)
            lidx = _roles(replicas).index("leader")
            term0 = replicas[lidx].store.term
            cli = RemoteAPIServer(endpoints[lidx])
            assert cli.wait_ready(10)
            cli.create(_cm("w0"))

            vidx = (lidx + 1) % 3
            replicas[vidx].mgr.block_peer(endpoints[lidx])
            replicas[lidx].mgr.block_peer(endpoints[vidx])

            # hold the partition for several TTLs of election attempts
            # while writes keep landing through the leader
            t_end = time.monotonic() + ttl * 4
            i = 1
            while time.monotonic() < t_end:
                cli.create(_cm(f"w{i}"))
                i += 1
                time.sleep(0.1)

            assert replicas[lidx].mgr.role == "leader"
            assert replicas[lidx].store.term == term0, (
                f"stable leader's term advanced {term0} -> "
                f"{replicas[lidx].store.term}"
            )
            assert replicas[vidx].mgr.role != "leader"

            # heal: the rejoiner re-attaches and catches up, SAME term
            replicas[vidx].mgr.unblock_peer(endpoints[lidx])
            replicas[lidx].mgr.unblock_peer(endpoints[vidx])
            assert _wait(
                lambda: replicas[vidx].mgr.role == "follower"
                and replicas[vidx].store.get("ConfigMap", "ns", "w1")
                is not None,
                timeout=15.0,
            )
            assert replicas[lidx].store.term == term0
        finally:
            if cli is not None:
                cli.close()
            for r in replicas:
                try:
                    r.stop()
                except Exception:
                    pass

    def test_prevote_answer_semantics(self, tmp_path):
        """handle_prevote grants only to (not-leader, no proven leader
        contact within TTL, candidate log >= mine)."""
        store = PersistentAPIServer(str(tmp_path / "d"))
        try:
            mgr = ReplicaManager(
                store, ["tcp://127.0.0.1:1", "tcp://127.0.0.1:2"], 1,
                lease_ttl=1.0,
            )
            store.create(_cm("x"))  # seq 1
            # no leader contact ever, candidate at least as advanced
            # (the election's candidate_rank ordering: lowest index
            # wins ties): grant
            resp = mgr.handle_prevote(
                {"term": 0, "seq": store.event_seq, "index": 0}
            )
            assert resp["granted"] is True
            # a candidate with a SHORTER log is denied (its promotion
            # would erase what we hold)
            resp = mgr.handle_prevote({"term": 0, "seq": 0, "index": 0})
            assert resp["granted"] is False
            # proven leader contact within the TTL: deny everyone —
            # this is the clause that stops a partitioned rejoiner
            with mgr._lock:
                mgr._leader_heard = time.monotonic()
            resp = mgr.handle_prevote(
                {"term": 9, "seq": 99, "index": 0}
            )
            assert resp["granted"] is False
            # a leader always denies
            with mgr._lock:
                mgr._leader_heard = 0.0
                mgr.role = "leader"
            resp = mgr.handle_prevote({"term": 9, "seq": 99, "index": 0})
            assert resp["granted"] is False
        finally:
            store.close()


class TestLeaderHint:
    def test_not_leader_error_round_trips_with_hint(self):
        from volcano_tpu.bus.protocol import (
            NotLeaderError,
            error_payload,
            raise_error,
        )

        payload = error_payload(
            NotLeaderError("not leader", leader="tcp://h:7180")
        )
        assert payload["error"] == "NotLeaderError"
        assert payload["leader"] == "tcp://h:7180"
        with pytest.raises(NotLeaderError) as ei:
            raise_error(payload)
        assert ei.value.leader == "tcp://h:7180"
        # hint-less form stays a plain ApiError payload (no key)
        assert "leader" not in error_payload(ApiError("boom"))

    def test_client_knowing_only_follower_lands_leader_op(self, tmp_path):
        """The redial pin: a client whose endpoint list holds ONLY a
        follower registers an admission hook (a leader-only op).  The
        follower's ``not leader`` answer carries the leader endpoint;
        the client steers its cursor there, redials DIRECTLY, and the
        resync replays the registration at the leader."""
        replicas, endpoints = _spawn_group(tmp_path, 3, lease_ttl=1.0)
        cli = None
        try:
            assert _wait(
                lambda: _roles(replicas).count("leader") == 1
                and _roles(replicas).count("follower") == 2,
                timeout=15.0,
            ), _roles(replicas)
            lidx = _roles(replicas).index("leader")
            fidx = (lidx + 1) % 3
            cli = RemoteAPIServer(endpoints[fidx])  # follower ONLY
            assert cli.wait_ready(10)

            from volcano_tpu.client.apiserver import AdmissionError

            def deny(operation, obj):
                raise AdmissionError("denied by hook")

            cli.register_admission("ConfigMap", "CREATE", deny)
            # the hint appended the leader endpoint and the redial
            # landed there — the registration is live group-wide
            assert _wait(
                lambda: endpoints[lidx] in cli.endpoints,
                timeout=10.0,
            ), cli.endpoints
            def denied():
                try:
                    cli.create(_cm("should-deny"))
                    return False
                except ApiError as e:
                    return "denied by hook" in str(e)
            assert _wait(denied, timeout=15.0)
        finally:
            if cli is not None:
                cli.close()
            for r in replicas:
                try:
                    r.stop()
                except Exception:
                    pass


class TestHealthzDegradedReplication:
    def _daemon(self, tmp_path, n=3):
        from volcano_tpu.cmd.apiserver import ApiServerDaemon

        endpoints = [f"tcp://127.0.0.1:{7180 + i}" for i in range(n)]
        return ApiServerDaemon(
            data_dir=str(tmp_path / "d"),
            replicas=endpoints,
            replica_index=0,
            repl_lease_ttl=1.0,
        ), endpoints

    def test_below_quorum_and_replica_lagging(self, tmp_path):
        from volcano_tpu.bus.replication import ReplicationCoordinator

        daemon, endpoints = self._daemon(tmp_path)
        try:
            rep = daemon.replica
            # follower that cannot name a leader: below-quorum
            with rep._lock:
                rep.role = "follower"
                rep.leader_url = None
            assert daemon._degraded() == "below-quorum"
            # leader with no live voter: below-quorum
            coord = ReplicationCoordinator(3, "apiserver-0", 0, 0)
            with rep._lock:
                rep.role = "leader"
                rep.coordinator = coord
            assert daemon._degraded() == "below-quorum"
            # quorum holds, worst live voter lags past the bar
            coord.leader_append(1000, 1, 0, b"{}", 0.0)
            coord.ack("apiserver-1", 1000 - 600, url=endpoints[1])
            assert daemon._degraded() == "replica-lagging"
            # healthy: quorum + bounded lag -> None
            coord.ack("apiserver-1", 1000, url=endpoints[1])
            assert daemon._degraded() is None
            coord.shutdown()
        finally:
            daemon.api.close()


class TestHaMetrics:
    def test_wal_and_repl_metrics_export(self, tmp_path):
        api = PersistentAPIServer(str(tmp_path / "d"))
        api.create(_cm("m"))
        api.close()
        PersistentAPIServer(str(tmp_path / "d")).close()
        metrics.update_repl_role("leader")
        metrics.update_repl_lag(3)
        text = metrics.registry.render()
        assert "volcano_wal_fsync_latency_milliseconds_count" in text
        assert "volcano_wal_size_bytes" in text
        assert "volcano_repl_lag_entries 3" in text
        assert 'volcano_repl_role{role="leader"} 1' in text
        assert 'volcano_bus_recoveries_total{kind="wal_tail"}' in text

    def test_membership_epoch_exports(self, tmp_path):
        d = str(tmp_path / "d")
        api = PersistentAPIServer(d)
        api.log_membership({"epoch": 3, "endpoints": ["tcp://h:1"]})
        text = metrics.registry.render()
        assert "volcano_repl_membership_epoch 3" in text
        api.close()
        # recovery re-exports the recovered epoch
        metrics.update_membership_epoch(0)
        rec = PersistentAPIServer(d)
        assert "volcano_repl_membership_epoch 3" in metrics.registry.render()
        rec.close()
        # the "removed" role is part of the bounded one-hot vocabulary
        metrics.update_repl_role("removed")
        assert ('volcano_repl_role{role="removed"} 1'
                in metrics.registry.render())
        metrics.update_repl_role("init")


# ---- slow: rolling leader kills across real OS processes ----


@pytest.mark.slow
class TestRollingLeaderKillSoak:
    def test_rolling_leader_kills_with_rejoin(self, tmp_path):
        """Real ``vtpu-apiserver`` OS processes: kill the leader, let a
        follower promote, restart the corpse from its data dir, repeat.
        Every acknowledged write must exist exactly once at the end."""
        import subprocess
        import sys

        n = 3
        ports = [_free_port() for _ in range(n)]
        endpoints = [f"tcp://127.0.0.1:{p}" for p in ports]
        bus_url = ",".join(endpoints)
        ttl = 1.0

        def spawn(i):
            return subprocess.Popen(
                [sys.executable, "-m", "volcano_tpu.cmd.apiserver",
                 "--listen-host", "127.0.0.1", "--port", str(ports[i]),
                 "--listen-port", "0",
                 "--data-dir", str(tmp_path / f"r{i}"),
                 "--replicas", bus_url,
                 "--replica-index", str(i),
                 "--repl-lease-ttl", str(ttl)],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                env=dict(os.environ),
            )

        procs = [spawn(i) for i in range(n)]
        cli = None
        try:
            def leader_index():
                for i, url in enumerate(endpoints):
                    if procs[i].poll() is not None:
                        continue
                    st = probe_status(url)
                    if st is not None and st.get("role") == "leader":
                        return i
                return None

            assert _wait(lambda: leader_index() is not None, timeout=60.0)
            cli = RemoteAPIServer(bus_url)
            assert cli.wait_ready(30)
            acked = []

            def write_some(tag, k=5):
                from volcano_tpu.client.apiserver import AlreadyExistsError

                for j in range(k):
                    name = f"{tag}-{j}"
                    last = None
                    for attempt in range(80):
                        try:
                            cli.create(_cm(name))
                            acked.append(name)
                            break
                        except AlreadyExistsError:
                            # an earlier attempt that LOOKED failed
                            # (timeout mid-failover) actually committed
                            # — at-least-once retry semantics
                            acked.append(name)
                            break
                        except ApiError as e:
                            last = e
                            time.sleep(0.25)
                    else:
                        raise AssertionError(
                            f"write {name} never acked (last: {last})"
                        )

            write_some("round0")
            for round_i in range(1, 3):
                lidx = leader_index()
                assert lidx is not None
                procs[lidx].kill()
                procs[lidx].wait(timeout=10)
                assert _wait(
                    lambda: leader_index() is not None,
                    timeout=ttl * 6 + 20.0,
                ), "no promotion after leader kill"
                write_some(f"round{round_i}")
                procs[lidx] = spawn(lidx)  # the corpse rejoins
                assert _wait(
                    lambda: probe_status(endpoints[lidx]) is not None,
                    timeout=30.0,
                )
            # final truth: every acked write exactly once
            state = {}

            def all_present():
                try:
                    names = [o.metadata.name
                             for o in cli.list("ConfigMap")]
                except ApiError as e:
                    state["err"] = str(e)
                    return False
                state["missing"] = sorted(set(acked) - set(names))
                state["dups"] = len(names) - len(set(names))
                return not state["missing"] and state["dups"] == 0

            assert _wait(all_present, timeout=30.0), state
        finally:
            if cli is not None:
                cli.close()
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except Exception:
                    p.kill()
