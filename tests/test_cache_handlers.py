"""Cache event-handler coverage — the table-driven style of the
reference's event_handlers_test.go (1,141 LoC): pod/node/podgroup/queue
transitions through the handler surface and their effect on cache
state, node accounting, and snapshots."""

from __future__ import annotations


from volcano_tpu.api import TaskStatus
from volcano_tpu.apis import scheduling

from tests.builders import (
    build_node,
    build_pod,
    build_pod_group,
    build_priority_class,
    build_queue,
)
from tests.scheduler_helpers import make_cache


def _cache(**kw):
    defaults = dict(
        nodes=[build_node("n0", {"cpu": "8", "memory": "16G"})],
        pods=[], pod_groups=[], queues=[build_queue("q")],
    )
    defaults.update(kw)
    return make_cache(**defaults)


class TestPodHandlers:
    def test_pending_pod_joins_job_as_pending(self):
        cache = _cache(pod_groups=[build_pod_group("ns", "pg", 1, queue="q")])
        cache.add_pod(build_pod("ns", "p", "", {"cpu": "1", "memory": "1G"}, group="pg"))
        job = next(iter(cache.jobs.values()))
        assert TaskStatus.Pending in job.task_status_index

    def test_running_pod_charges_node(self):
        cache = _cache()
        cache.add_pod(build_pod("ns", "p", "n0", {"cpu": "2", "memory": "4G"},
                                phase="Running"))
        node = cache.nodes["n0"]
        assert node.used.milli_cpu == 2000
        assert node.idle.milli_cpu == 6000

    def test_update_pod_phase_transition_moves_status(self):
        cache = _cache(pod_groups=[build_pod_group("ns", "pg", 1, queue="q")])
        pod = build_pod("ns", "p", "n0", {"cpu": "1", "memory": "1G"},
                        phase="Running", group="pg")
        cache.add_pod(pod)
        done = pod.clone()
        done.status.phase = "Succeeded"
        cache.update_pod(pod, done)
        job = next(iter(cache.jobs.values()))
        assert TaskStatus.Succeeded in job.task_status_index
        assert TaskStatus.Running not in job.task_status_index
        # succeeded pods release node resources (node accounting)
        assert cache.nodes["n0"].used.milli_cpu == 0

    def test_update_pod_gains_node_assignment(self):
        """Pending → bound elsewhere (another scheduler instance won):
        the task moves onto the node's books."""
        cache = _cache(pod_groups=[build_pod_group("ns", "pg", 1, queue="q")])
        pod = build_pod("ns", "p", "", {"cpu": "1", "memory": "1G"}, group="pg")
        cache.add_pod(pod)
        bound = pod.clone()
        bound.spec.node_name = "n0"
        bound.status.phase = "Running"
        cache.update_pod(pod, bound)
        assert cache.nodes["n0"].used.milli_cpu == 1000

    def test_delete_pod_releases_node(self):
        cache = _cache()
        pod = build_pod("ns", "p", "n0", {"cpu": "2", "memory": "4G"}, phase="Running")
        cache.add_pod(pod)
        cache.delete_pod(pod)
        assert cache.nodes["n0"].used.milli_cpu == 0

    def test_foreign_scheduler_pending_pod_still_charges_when_running(self):
        """Pods of other schedulers participate in node accounting once
        placed (the cache mirrors cluster truth), but their pending pods
        are not scheduling work for this scheduler."""
        cache = _cache()
        pod = build_pod("ns", "p", "n0", {"cpu": "1", "memory": "1G"}, phase="Running")
        pod.spec.scheduler_name = "other-scheduler"
        cache.add_pod(pod)
        assert cache.nodes["n0"].used.milli_cpu == 1000


class TestNodeHandlers:
    def test_update_node_alloc_change(self):
        cache = _cache()
        new = build_node("n0", {"cpu": "16", "memory": "32G"})
        cache.update_node(None, new)
        assert cache.nodes["n0"].allocatable.milli_cpu == 16000

    def test_delete_node_removes_from_cache(self):
        cache = _cache()
        cache.delete_node(cache.nodes["n0"].node)
        assert "n0" not in cache.nodes

    def test_unschedulable_node_vetoed_by_predicates_not_snapshot(self):
        """cordoned nodes stay in the snapshot (cluster truth) — the
        predicates plugin is what refuses placements on them."""
        cache = _cache()
        bad = build_node("n1", {"cpu": "4", "memory": "8G"}, unschedulable=True)
        cache.add_node(bad)
        snap = cache.snapshot()
        assert "n1" in snap.nodes
        assert snap.nodes["n1"].node.spec.unschedulable

    def test_over_allocated_node_excluded_from_snapshot(self):
        cache = _cache()
        cache.add_pod(build_pod("ns", "big", "n0", {"cpu": "100", "memory": "1G"},
                                phase="Running"))
        snap = cache.snapshot()
        assert "n0" not in snap.nodes  # not ready() → filtered


class TestSnapshotFiltering:
    def test_job_without_podgroup_excluded(self):
        cache = _cache()
        cache.add_pod(build_pod("ns", "p", "", {"cpu": "1", "memory": "1G"},
                                group="orphan-pg"))
        snap = cache.snapshot()
        assert not snap.jobs  # no scheduling spec → not schedulable

    def test_job_with_unknown_queue_excluded(self):
        cache = _cache(pod_groups=[build_pod_group("ns", "pg", 1, queue="ghost")])
        cache.add_pod(build_pod("ns", "p", "", {"cpu": "1", "memory": "1G"}, group="pg"))
        snap = cache.snapshot()
        assert not snap.jobs

    def test_priority_class_resolution(self):
        cache = _cache(
            pod_groups=[build_pod_group("ns", "pg", 1, queue="q",
                                        priority_class_name="high")],
            priority_classes=[build_priority_class("high", 500)],
        )
        cache.add_pod(build_pod("ns", "p", "", {"cpu": "1", "memory": "1G"}, group="pg"))
        snap = cache.snapshot()
        job = next(iter(snap.jobs.values()))
        assert job.priority == 500

    def test_global_default_priority_class(self):
        pc = build_priority_class("std", 7)
        pc.global_default = True
        cache = _cache(
            pod_groups=[build_pod_group("ns", "pg", 1, queue="q")],
            priority_classes=[pc],
        )
        cache.add_pod(build_pod("ns", "p", "", {"cpu": "1", "memory": "1G"}, group="pg"))
        snap = cache.snapshot()
        assert next(iter(snap.jobs.values())).priority == 7


class TestPodGroupQueueHandlers:
    def test_delete_pod_group_drops_empty_job(self):
        cache = _cache(pod_groups=[build_pod_group("ns", "pg", 1, queue="q")])
        pg = next(iter(cache.jobs.values())).pod_group
        cache.delete_pod_group(pg)
        assert not cache.jobs

    def test_delete_pod_group_keeps_job_with_tasks(self):
        cache = _cache(pod_groups=[build_pod_group("ns", "pg", 1, queue="q")])
        cache.add_pod(build_pod("ns", "p", "", {"cpu": "1", "memory": "1G"}, group="pg"))
        pg = next(iter(cache.jobs.values())).pod_group
        cache.delete_pod_group(pg)
        job = next(iter(cache.jobs.values()))
        assert job.pod_group is None and job.tasks

    def test_queue_update_reflects_weight(self):
        cache = _cache()
        q = build_queue("q", weight=6)
        cache.update_queue(None, q)
        assert cache.queues["q"].weight == 6
