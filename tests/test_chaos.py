"""Chaos: seeded randomized fault schedules over multi-cycle e2e runs.

The proof for the fault plane (ISSUE 5): the full control loop — store
→ BusServer → RemoteAPIServer informers → SchedulerCache → jax-allocate
→ compute-plane sidecar → bind effects — runs for many cycles while the
seeded plane fires faults at every seam at once (bus drops/partitions/
relist storms, sidecar crashes/corrupt frames/forced session loss,
device lowering failures, bind-failure bursts feeding the resync
queue), and the run must end with

  * zero duplicate binds (no pod ever re-bound at the store),
  * zero lost jobs (every pod bound + running once faults stop),
  * store/cache coherence (node-held task sets equal API truth),
  * for the selector-pinned workload, a binding map BIT-IDENTICAL to
    the fault-free twin run on the same workload.

The tier-1 smoke runs a short mixed schedule; the ≥200-cycle soak and
the rolling-workload convergence runs are marked ``slow``.
"""

from __future__ import annotations

import time
from collections import defaultdict

import pytest

from volcano_tpu import faults, trace
from volcano_tpu.bus.remote import RemoteAPIServer
from volcano_tpu.bus.server import BusServer
from volcano_tpu.cache import SchedulerCache
from volcano_tpu.client import (
    ADDED,
    APIServer,
    KubeClient,
    MODIFIED,
    SchedulerClient,
    VolcanoClient,
)
from volcano_tpu.scheduler.scheduler import Scheduler
from volcano_tpu.serving.compute_plane import ComputePlaneServer

from tests.builders import build_node, build_pod, build_pod_group, build_queue

CONF = """
actions: "enqueue, jax-allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.configure(None)
    faults.reset_breakers()
    faults.configure_deadline(None)
    yield
    faults.configure(None)
    faults.reset_breakers()
    faults.configure_deadline(None)
    from volcano_tpu.ops import executor

    executor.configure(None)
    trace.disable()


def _wait(pred, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class ChaosCluster:
    """The full control loop in one harness, every seam real: informers
    over a TCP bus, kernels behind the compute-plane socket, binds
    through the bus client.  The store-side audit watch records every
    bind transition from API truth (it runs on the in-process server,
    outside fault injection)."""

    def __init__(self, tmp_path, name, n_nodes=8, node_cpu="4",
                 compute_plane=True):
        self.api = APIServer()
        self.bus = BusServer(self.api).start()
        self.remote = RemoteAPIServer(
            f"tcp://127.0.0.1:{self.bus.port}", timeout=5.0
        )
        assert self.remote.wait_ready(10.0)
        self.kube = KubeClient(self.api)
        self.vc = VolcanoClient(self.api)
        self.vc.create_queue(build_queue("default"))
        self.n_nodes = n_nodes
        for i in range(n_nodes):
            self.kube.create_node(build_node(
                f"n{i}", {"cpu": node_cpu, "memory": "64Gi"},
                labels={"slot": f"s{i}"},
            ))

        #: ns/name → node, from store truth; rebind = duplicate bind
        self.bound = {}
        self.rebinds = []
        self._kubelet_pending = []
        self.api.watch("Pod", self._audit, send_initial=False)

        self.client = SchedulerClient(self.remote)
        #: ns/name → successful bind calls — a second successful bind
        #: for one pod is a duplicate even if it picked the same node
        #: (the k8s binding subresource would 409).  Both bind paths are
        #: counted: per-object bind_pod AND binds riding the coalesced
        #: commit_batch frame (the pipelined plane's fast path).
        self.bind_calls = defaultdict(int)
        original_bind = self.client.bind_pod

        def counted_bind(namespace, name, hostname):
            original_bind(namespace, name, hostname)
            self.bind_calls[f"{namespace}/{name}"] += 1

        self.client.bind_pod = counted_bind
        original_commit = self.client.commit_batch

        def counted_commit(binds=(), evicts=(), events=(), conditions=(),
                           pod_groups=()):
            binds = list(binds)
            results = original_commit(
                binds=binds, evicts=evicts, events=events,
                conditions=conditions, pod_groups=pod_groups,
            )
            for b, err in zip(binds, results.get("binds", ())):
                if err is None:
                    self.bind_calls[f"{b['namespace']}/{b['name']}"] += 1
            return results

        self.client.commit_batch = counted_commit

        # the chaos loop runs with the PIPELINED commit plane on —
        # faults fire while commits are in flight, and the acceptance
        # bar (no dup binds, no lost jobs, coherence, bit-identical
        # pinned map vs the fault-free twin) must hold regardless
        self.cache = SchedulerCache(
            client=self.client, scheduler_name="volcano-tpu",
            pipelined_commit=True,
        )
        # chaos-rate timing: resync retries and quarantine re-entry
        # collapse from seconds to cycle-scale
        self.cache._RESYNC_BACKOFF_BASE = 0.01
        self.cache._QUARANTINE_COOLDOWN = 0.1
        conf = tmp_path / f"{name}-conf.yaml"
        conf.write_text(CONF)
        self.scheduler = Scheduler(self.cache, scheduler_conf_path=str(conf))
        self.cp_path = str(tmp_path / f"{name}-cp.sock")
        self.cp = None
        from volcano_tpu.ops import executor

        if compute_plane:
            self.cp = ComputePlaneServer(self.cp_path).start()
            executor.configure(self.cp_path)
        else:
            executor.configure(None)
        self.cache.run()
        self.cycle_errors = 0

    # ---- store-truth watchers ----

    def _audit(self, event, old, new):
        if event not in (ADDED, MODIFIED) or new is None:
            return
        key = f"{new.metadata.namespace}/{new.metadata.name}"
        node = new.spec.node_name
        if not node:
            return
        prev = self.bound.get(key)
        if prev is None:
            self.bound[key] = node
        elif prev != node:
            self.rebinds.append((key, prev, node))
        if new.status.phase == "Pending":
            self._kubelet_pending.append((new.metadata.namespace,
                                          new.metadata.name))

    def _kubelet_drain(self):
        while self._kubelet_pending:
            namespace, name = self._kubelet_pending.pop()
            pod = self.kube.get_pod(namespace, name)
            if pod is not None and pod.spec.node_name and \
                    pod.status.phase == "Pending":
                pod.status.phase = "Running"
                self.kube.update_pod_status(pod)

    # ---- workload ----

    def submit(self, name, replicas=3, cpu="1", pin_slots=None):
        """One gang job: a PodGroup with min_member=replicas plus its
        pods.  ``pin_slots`` gives each pod a node selector to a unique
        slot label — the workload whose final binding map is forced,
        hence comparable bit-for-bit across runs."""
        self.vc.create_pod_group(build_pod_group("ns", name, replicas))
        for i in range(replicas):
            selector = None
            if pin_slots is not None:
                selector = {"slot": f"s{pin_slots[i] % self.n_nodes}"}
            self.kube.create_pod(build_pod(
                "ns", f"{name}-t{i}", "", {"cpu": cpu, "memory": "1Gi"},
                group=name, selector=selector,
            ))

    def finish(self, name, replicas):
        for i in range(replicas):
            pod = self.kube.get_pod("ns", f"{name}-t{i}")
            if pod is not None and pod.status.phase == "Running":
                pod.status.phase = "Succeeded"
                self.kube.update_pod_status(pod)

    # ---- the loop ----

    def cycle(self):
        try:
            self.scheduler.run_once()
        except Exception:  # noqa: BLE001 — a partitioned cycle fails fast,
            # exactly like BaseDaemon._loop logs and retries in prod
            self.cycle_errors += 1
        self._kubelet_drain()

    def run_cycles(self, n, pause=0.01):
        for _ in range(n):
            self.cycle()
            time.sleep(pause)  # let watch frames propagate off-thread

    # ---- assertions ----

    def pods(self):
        return self.kube.list_pods("ns")

    def all_placed(self):
        pods = self.pods()
        return bool(pods) and all(p.spec.node_name for p in pods)

    def assert_no_duplicate_binds(self):
        assert self.rebinds == [], f"store saw rebinds: {self.rebinds}"
        dupes = {k: c for k, c in self.bind_calls.items() if c > 1}
        assert not dupes, f"duplicate successful bind calls: {dupes}"

    def assert_coherent(self):
        """Cache node accounting == API truth (non-terminated pods with
        a node), after the informers settle."""
        def check():
            truth = defaultdict(set)
            for pod in self.pods():
                if pod.spec.node_name and pod.status.phase in (
                    "Pending", "Running",
                ):
                    truth[pod.spec.node_name].add(pod.metadata.uid)
            with self.cache._mutex:
                for name in truth:
                    node = self.cache.nodes.get(name)
                    if node is None or set(node.tasks) != truth[name]:
                        return False
                for name, node in self.cache.nodes.items():
                    if name not in truth and node.tasks:
                        return False
            return True

        assert _wait(check, timeout=15.0), "cache diverged from store truth"

    def binding_map(self):
        return dict(self.bound)

    def close(self):
        from volcano_tpu.ops import executor

        executor.configure(None)
        self.cache.stop_commit_plane()
        if self.cp is not None:
            self.cp.stop()
        self.remote.close()
        self.bus.stop()


#: the mixed schedule of the acceptance criterion: bus drops + sidecar
#: crash + device failures + bind bursts, all bounded by count so the
#: settle phase converges
MIXED_FAULTS = (
    "seed={seed};"
    "bus.disconnect=0.03:count=4;"
    "bus.drop_event=0.02:count=4;"
    "bus.force_relist=0.3:count=4;"
    "bus.delay=0.05:count=6:ms=5;"
    "bus.client_drop=0.03:count=3;"
    "compute.crash=0.12:count=3;"
    "compute.corrupt=0.1:count=2;"
    "compute.need_full=0.2:count=3;"
    "compute.timeout=0.08:count=2;"
    "device.lowering=0.1:count=2;"
    "cache.bind_fail=0.12:count=5;"
    "cache.resync_fail=0.3:count=3;"
    "commit.fail=0.15:count=4;"
    "commit.delay=0.2:count=6:ms=30"
)


def _submit_mixed_workload(cluster):
    cluster.submit("free-a", replicas=3)
    cluster.submit("free-b", replicas=3)
    cluster.submit("free-c", replicas=2)
    cluster.submit("pinned", replicas=4, pin_slots=[4, 5, 6, 7])


class TestChaosSmoke:
    def test_mixed_fault_schedule_converges(self, tmp_path):
        """Tier-1 chaos smoke: every seam faulted at once over a
        multi-cycle run; convergence, no-dup, no-loss, coherence, and
        the pinned workload bit-identical to a fault-free twin."""
        faulty = ChaosCluster(tmp_path, "faulty")
        try:
            _submit_mixed_workload(faulty)
            faults.configure(MIXED_FAULTS.format(seed=1234))
            plane = faults.get_plane()
            faulty.run_cycles(25)
            fired = plane.fired()
            faults.configure(None)
            # settle: faults off, the loop must converge
            assert _wait(
                lambda: (faulty.cycle() or True) and faulty.all_placed(),
                timeout=30.0, interval=0.05,
            ), f"pods still unplaced; faults fired: {fired}"
            assert len(faulty.pods()) == 12
            faulty.assert_no_duplicate_binds()
            faulty.assert_coherent()
            # the schedule actually exercised multiple seams
            assert len(fired) >= 4, f"schedule barely fired: {fired}"
            faulty_map = faulty.binding_map()
        finally:
            faulty.close()
            faults.configure(None)
            faults.reset_breakers()

        clean = ChaosCluster(tmp_path, "clean")
        try:
            _submit_mixed_workload(clean)
            assert _wait(
                lambda: (clean.cycle() or True) and clean.all_placed(),
                timeout=30.0, interval=0.05,
            )
            clean.assert_no_duplicate_binds()
            clean_map = clean.binding_map()
        finally:
            clean.close()

        # pinned workload: bit-identical bindings vs the fault-free run
        pinned = {k: v for k, v in faulty_map.items() if "pinned" in k}
        pinned_clean = {k: v for k, v in clean_map.items() if "pinned" in k}
        assert pinned == pinned_clean and len(pinned) == 4
        # free jobs: same placement count either way (no lost pods)
        assert set(faulty_map) == set(clean_map)

    def test_chaos_run_is_journaled(self, tmp_path):
        """Fault firings land in the PR-1 trace journal — the chaos run
        is replayable forensics.  CI points VTPU_CHAOS_JOURNAL_DIR at a
        stable path and uploads it as a build artifact."""
        import os

        jdir = os.environ.get("VTPU_CHAOS_JOURNAL_DIR") or str(
            tmp_path / "journal"
        )
        rec = trace.enable(jdir)
        cluster = ChaosCluster(tmp_path, "journaled")
        try:
            cluster.submit("j0", replicas=2)
            faults.configure(
                "seed=7;cache.bind_fail=1:count=2;compute.crash=1:count=1"
            )
            cluster.run_cycles(6)
            faults.configure(None)
            _wait(lambda: (cluster.cycle() or True) and cluster.all_placed(),
                  timeout=20.0)
        finally:
            cluster.close()
            trace.disable()
        journal = trace.Journal(jdir)
        fault_events = []
        for cid in journal.cycles():
            record = journal.read_cycle(cid)
            fault_events += [
                e["name"] for e in record.get("events", [])
                if e["name"].startswith("fault:")
            ]
        assert any(e == "fault:cache.bind_fail" for e in fault_events)
        assert any(e == "fault:compute.crash" for e in fault_events)


class TestKillRecovery:
    def test_kill_sidecar_mid_run_recovers_within_a_cycle(self, tmp_path):
        """Acceptance: kill-the-sidecar mid-cycle → the very next device
        phase completes in-process, with the demotion visible in
        /healthz (degraded), metrics, and the breaker; a restarted
        sidecar is promoted back by the health re-probe."""
        from volcano_tpu.metrics import metrics
        from volcano_tpu.ops import executor

        cluster = ChaosCluster(tmp_path, "sidecar-kill")
        try:
            cluster.submit("k0", replicas=3)
            assert _wait(
                lambda: (cluster.cycle() or True) and cluster.all_placed(),
                timeout=30.0, interval=0.05,
            )
            # SIGKILL equivalent: the listener goes away AND every
            # established connection dies with the process (stop() only
            # closes the listener; a crash severs the accepted sockets
            # too, which is what the client actually observes)
            remote = executor._get_remote()
            cluster.cp.stop()
            if remote.client._sock is not None:
                remote.client._sock.close()
            cluster.submit("k1", replicas=3)
            assert _wait(
                lambda: (cluster.cycle() or True) and cluster.all_placed(),
                timeout=30.0, interval=0.05,
            )
            cluster.assert_no_duplicate_binds()
            br = faults.get_breaker("compute-plane")
            assert br.open
            assert any("compute-plane" in r for r in faults.degraded_reasons())
            key = ("volcano_executor_fallbacks_total",
                   (("cause", "error"), ("from", "remote"), ("to", "local")))
            assert metrics.registry._counters.get(key, 0) >= 1
            # restart on the same socket; collapse the probe window
            cluster.cp = ComputePlaneServer(cluster.cp_path).start()
            executor._get_remote().last_probe = 0.0
            cluster.submit("k2", replicas=2)
            assert _wait(
                lambda: (cluster.cycle() or True) and cluster.all_placed(),
                timeout=30.0, interval=0.05,
            )
            assert executor._last_route == "remote"
            assert not br.open
        finally:
            cluster.close()

    def test_kill_apiserver_mid_watch_recovers(self, tmp_path):
        """Acceptance: kill-the-apiserver mid-watch → the bus client
        redials the restarted incarnation, relists (new epoch), and the
        control loop converges with no duplicate binds."""
        from volcano_tpu.bus.server import BusServer as _BusServer

        cluster = ChaosCluster(tmp_path, "bus-kill")
        try:
            cluster.submit("b0", replicas=3)
            assert _wait(
                lambda: (cluster.cycle() or True) and cluster.all_placed(),
                timeout=30.0, interval=0.05,
            )
            port = cluster.bus.port
            cluster.bus.stop()
            # work submitted during the outage (store is still alive —
            # the bus is the watch/CRUD front door, not the store)
            cluster.submit("b1", replicas=3)
            cluster.run_cycles(3)  # these fail fast on BusError
            # restart on the same port, same store, NEW epoch → resume
            # tokens are rejected and every informer relists
            cluster.bus = _BusServer(
                cluster.api, port=port
            ).start()
            assert _wait(
                lambda: (cluster.cycle() or True) and cluster.all_placed(),
                timeout=45.0, interval=0.05,
            ), "control loop did not converge after apiserver restart"
            cluster.assert_no_duplicate_binds()
            cluster.assert_coherent()
        finally:
            cluster.close()

    def test_cycle_deadline_completes_on_host_path(self, tmp_path):
        """Acceptance: an overrunning device phase is abandoned by the
        cycle watchdog and the cycle completes on the host path — jobs
        still schedule, the demotion is counted."""
        from volcano_tpu.metrics import metrics

        cluster = ChaosCluster(tmp_path, "watchdog", compute_plane=False)
        try:
            faults.configure_deadline(250.0)
            # the device phase sleeps past the whole budget every time
            # it runs for the next few sessions
            faults.configure("seed=1;device.slow=1:count=3:ms=400")
            cluster.submit("w0", replicas=3)
            assert _wait(
                lambda: (cluster.cycle() or True) and cluster.all_placed(),
                timeout=40.0, interval=0.05,
            )
            cluster.assert_no_duplicate_binds()
            key = ("volcano_executor_fallbacks_total",
                   (("cause", "deadline"), ("from", "device"),
                    ("to", "host")))
            assert metrics.registry._counters.get(key, 0) >= 1
        finally:
            faults.configure_deadline(None)
            cluster.close()


@pytest.mark.slow
class TestChaosSoak:
    def test_soak_200_cycles_rolling_workload_bit_identical(self, tmp_path):
        """≥200 cycles under the mixed schedule with a rolling pinned
        workload (jobs arrive and complete throughout).  Ends with zero
        duplicate binds, zero lost jobs, coherence, and a binding map
        bit-identical to the fault-free twin on the same workload."""
        def drive(name, spec):
            cluster = ChaosCluster(tmp_path, name, n_nodes=8)
            submitted = []
            try:
                if spec:
                    faults.configure(spec)
                plane = faults.get_plane()
                for i in range(210):
                    if i % 7 == 0 and i // 7 < 24:
                        j = i // 7
                        jname = f"roll-{j}"
                        # 3 tasks pinned to a sliding slot window: jobs
                        # overlapping on slots serialize, completions
                        # free them — arrival/completion dynamics with a
                        # forced final map
                        cluster.submit(
                            jname, replicas=3,
                            pin_slots=[j, j + 1, j + 2],
                        )
                        submitted.append(jname)
                    if i % 7 == 5 and submitted:
                        # completions free the slots for the next wave
                        cluster.finish(submitted[0], 3)
                        submitted.pop(0)
                    cluster.cycle()
                    time.sleep(0.005)
                fired = dict(plane.fired()) if plane.enabled else {}
                faults.configure(None)
                assert _wait(
                    lambda: (cluster.cycle() or True) and cluster.all_placed(),
                    timeout=60.0, interval=0.05,
                ), f"lost pods after soak; fired: {fired}"
                assert len(cluster.pods()) == 24 * 3
                cluster.assert_no_duplicate_binds()
                cluster.assert_coherent()
                return cluster.binding_map(), fired
            finally:
                cluster.close()
                faults.configure(None)
                faults.reset_breakers()

        faulty_map, fired = drive("soak-faulty", MIXED_FAULTS.format(seed=77))
        assert len(fired) >= 5, f"soak schedule barely fired: {fired}"
        clean_map, _ = drive("soak-clean", "")
        assert faulty_map == clean_map
        assert len(faulty_map) == 24 * 3
