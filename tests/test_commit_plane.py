"""Pipelined commit plane equivalence suite (ISSUE 6).

The contract: with ``SchedulerCache(pipelined_commit=True)`` the commit
path (binder round trips, Scheduled/Evict/Unschedulable audit events,
pod conditions, PodGroup status writebacks) runs on background bind
workers coalesced into batched commit frames — and the RESULTING STORE
STATE is byte-identical to the synchronous path's, over both the
in-process backend and the real TCP bus, with a commit barrier at the
next snapshot keeping cache/store coherence.  "Byte-identical" is
modulo the fields that differ between ANY two runs (resourceVersions,
timestamps, the per-session condition transition_id): every
user-visible byte — node assignments, phases, condition
type/status/reason/message, Event type/reason/message/count, PodGroup
phase/counters — must match.

Also covered: multi-bind coalescing (one frame per cycle, not one per
pod), the VBUS v2 / old-peer per-object fallback, a mid-cycle apiserver
restart while the commit queue is non-empty, and the commit.fail /
commit.delay fault points.
"""

from __future__ import annotations

import socket
import time

import pytest

from volcano_tpu import faults
from volcano_tpu.bus import protocol
from volcano_tpu.bus.remote import RemoteAPIServer
from volcano_tpu.bus.server import BusServer
from volcano_tpu.cache import SchedulerCache
from volcano_tpu.client import (
    ADDED,
    APIServer,
    KubeClient,
    MODIFIED,
    SchedulerClient,
    VolcanoClient,
)
from volcano_tpu.client.apiserver import ApiError
from volcano_tpu.scheduler.scheduler import Scheduler

from tests.builders import build_node, build_pod, build_pod_group, build_queue

CONF_JAX = """
actions: "enqueue, jax-allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""

#: the host allocate action drives the Statement loop — covers the
#: batched Statement.commit path the kernel's fast-apply bypasses
CONF_HOST = CONF_JAX.replace("jax-allocate", "allocate")


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.configure(None)
    yield
    faults.configure(None)


def _wait(pred, timeout=20.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class MiniCluster:
    """One scheduler control loop over a seeded store — in-process or
    through the real TCP bus — with a store-truth rebind audit."""

    def __init__(self, tmp_path, name, backend="inproc", pipelined=False,
                 conf=CONF_JAX):
        self.api = APIServer()
        self.kube = KubeClient(self.api)
        self.vc = VolcanoClient(self.api)
        self.vc.create_queue(build_queue("default"))
        for i in range(4):
            self.kube.create_node(
                build_node(f"n{i}", {"cpu": "4", "memory": "16Gi"})
            )
        self.bus = self.remote = None
        if backend == "bus":
            self.bus = BusServer(self.api).start()
            self.remote = RemoteAPIServer(
                f"tcp://127.0.0.1:{self.bus.port}", timeout=5.0
            )
            assert self.remote.wait_ready(10.0)
            client_api = self.remote
        else:
            client_api = self.api
        self.bound = {}
        self.rebinds = []
        self.api.watch("Pod", self._audit, send_initial=False)
        self.client = SchedulerClient(client_api)
        self.cache = SchedulerCache(
            client=self.client, scheduler_name="volcano-tpu",
            pipelined_commit=pipelined,
        )
        conf_path = tmp_path / f"{name}-conf.yaml"
        conf_path.write_text(conf)
        self.scheduler = Scheduler(self.cache, scheduler_conf_path=str(conf_path))
        self.cache.run()

    def _audit(self, event, old, new):
        if event not in (ADDED, MODIFIED) or new is None:
            return
        key = f"{new.metadata.namespace}/{new.metadata.name}"
        node = new.spec.node_name
        if not node:
            return
        prev = self.bound.get(key)
        if prev is None:
            self.bound[key] = node
        elif prev != node:
            self.rebinds.append((key, prev, node))

    def submit_workload(self):
        """Three gang jobs + one provably-unschedulable job, so the run
        exercises binds, Scheduled events, and the full Unschedulable
        writeback (events + conditions + PodGroup condition)."""
        for jname, replicas, cpu in (
            ("g0", 3, "1"), ("g1", 2, "1"), ("big", 1, "100"),
        ):
            self.vc.create_pod_group(build_pod_group("ns", jname, replicas))
            for i in range(replicas):
                self.kube.create_pod(build_pod(
                    "ns", f"{jname}-t{i}", "", {"cpu": cpu, "memory": "1Gi"},
                    group=jname,
                ))

    def wait_synced(self, n_tasks):
        assert _wait(lambda: sum(
            len(j.tasks) for j in self.cache.jobs.values()
        ) >= n_tasks), "cache never saw the workload"

    def cycle(self):
        self.scheduler.run_once()

    def placed(self):
        return {
            f"{p.metadata.namespace}/{p.metadata.name}": p.spec.node_name
            for p in self.kube.list_pods("ns") if p.spec.node_name
        }

    def close(self):
        self.cache.stop_commit_plane()
        if self.remote is not None:
            self.remote.close()
        if self.bus is not None:
            self.bus.stop()


def store_digest(api, counts=True):
    """Every user-visible byte of the commit path's output — excludes
    only resourceVersions, timestamps, and the per-session
    transition_id, which differ between any two runs."""
    pods = {}
    for p in api.list("Pod"):
        pods[f"{p.metadata.namespace}/{p.metadata.name}"] = (
            p.spec.node_name,
            p.status.phase,
            tuple(sorted(
                (c.type, c.status, c.reason, c.message)
                for c in p.status.conditions
            )),
        )
    events = {}
    for e in api.list("Event"):
        key = (e.involved_object.get("name"), e.type, e.reason)
        events[key] = (e.count if counts else None, e.message)
    pgs = {}
    for g in api.list("PodGroup"):
        pgs[f"{g.metadata.namespace}/{g.metadata.name}"] = (
            g.status.phase, g.status.running, g.status.succeeded,
            g.status.failed,
            tuple(sorted(
                (c.type, c.status, c.reason, c.message)
                for c in g.status.conditions
            )),
        )
    return {"pods": pods, "events": events, "pod_groups": pgs}


@pytest.mark.parametrize("conf", [CONF_JAX, CONF_HOST],
                         ids=["jax-allocate", "host-allocate"])
def test_pipelined_matches_sync_inproc(tmp_path, conf):
    """In-process backend: fully deterministic, so the digests —
    including Event COUNTS — must be equal byte for byte."""
    digests = []
    for mode, pipelined in (("sync", False), ("pipe", True)):
        cluster = MiniCluster(tmp_path, f"{mode}-{conf[:20].strip()}",
                              pipelined=pipelined, conf=conf)
        try:
            cluster.submit_workload()
            cluster.wait_synced(6)
            for _ in range(3):
                cluster.cycle()
            cluster.cache.flush()
            assert cluster.rebinds == []
            digests.append(store_digest(cluster.api))
        finally:
            cluster.close()
    assert digests[0] == digests[1]
    # the workload actually exercised every commit section
    assert sum(1 for v in digests[0]["pods"].values() if v[0]) == 5
    assert ("big-t0", "Warning", "Unschedulable") in digests[0]["events"]
    assert any(c and c[0][0] == "PodScheduled"
               for _n, _p, c in digests[0]["pods"].values())


def test_pipelined_matches_sync_over_bus(tmp_path):
    """The same equivalence through the real TCP bus (coalesced VBUS
    commit_batch frames).  Watch echoes propagate asynchronously over
    the wire, so Event counts (which depend on how many cycles re-saw
    stale state) are excluded; everything else must match."""
    digests = []
    for mode, pipelined in (("sync", False), ("pipe", True)):
        cluster = MiniCluster(tmp_path, f"bus-{mode}", backend="bus",
                              pipelined=pipelined)
        try:
            cluster.submit_workload()
            cluster.wait_synced(6)
            assert _wait(
                lambda: (cluster.cycle() or True) and len(cluster.placed()) == 5,
                timeout=30.0, interval=0.05,
            )
            cluster.cache.flush()
            # settle: the Unschedulable writeback for "big" must land
            assert _wait(lambda: any(
                e.reason == "Unschedulable" for e in cluster.api.list("Event", "ns")
            ))
            assert cluster.rebinds == []
            digests.append(store_digest(cluster.api, counts=False))
        finally:
            cluster.close()
    assert digests[0] == digests[1]


def test_cycle_binds_coalesce_into_one_frame(tmp_path):
    """5 binds in a cycle must travel as ONE commit_batch frame, not 5
    round trips — the multi-bind coalescing claim, measured at the
    client boundary."""
    cluster = MiniCluster(tmp_path, "coalesce", pipelined=True)
    frames = []
    orig = cluster.client.commit_batch

    def counting(binds=(), evicts=(), events=(), conditions=(), pod_groups=()):
        frames.append({
            "binds": len(list(binds)), "evicts": len(list(evicts)),
            "events": len(list(events)), "conditions": len(list(conditions)),
            "pod_groups": len(list(pod_groups)),
        })
        return orig(binds=binds, evicts=evicts, events=events,
                    conditions=conditions, pod_groups=pod_groups)

    cluster.client.commit_batch = counting
    try:
        cluster.submit_workload()
        cluster.wait_synced(6)
        cluster.cycle()
        cluster.cache.flush()
        assert max(f["binds"] for f in frames) == 5, frames
        # the per-job status writebacks coalesced too (g0+g1+big → one
        # or two frames, never one per pod)
        status_frames = [f for f in frames if f["pod_groups"]]
        assert status_frames and len(status_frames) <= 2, frames
        from volcano_tpu.metrics.metrics import registry

        hist = registry._histograms.get(("volcano_bind_coalesce_size", ()))
        assert hist is not None and hist.total >= 5
    finally:
        cluster.close()


def test_commit_barrier_at_next_snapshot(tmp_path):
    """commit.delay keeps the queue observably non-empty after the
    action returns; the next snapshot's barrier must drain it before
    new state is read."""
    cluster = MiniCluster(tmp_path, "barrier", pipelined=True)
    try:
        cluster.submit_workload()
        cluster.wait_synced(6)
        faults.configure("seed=3;commit.delay=1:ms=150")
        cluster.cycle()
        plane = cluster.cache._commit_plane
        cluster.cache.snapshot()  # the barrier
        faults.configure(None)
        assert plane.depth == 0
        assert len(cluster.placed()) == 5  # landed BEFORE the snapshot
        assert plane.last_barrier["busy_ms"] > 0
    finally:
        cluster.close()


def test_commit_fail_takes_resync_path_no_duplicates(tmp_path):
    """Doomed commit items (commit.fail) route to the FailedScheduling +
    resync path; the loop converges with zero duplicate binds."""
    cluster = MiniCluster(tmp_path, "fail", pipelined=True)
    try:
        cluster.submit_workload()
        cluster.wait_synced(6)
        faults.configure("seed=9;commit.fail=1:count=3")
        cluster.cycle()
        faults.configure(None)
        assert _wait(
            lambda: (cluster.cycle() or True) and len(cluster.placed()) == 5,
            timeout=30.0, interval=0.05,
        )
        cluster.cache.flush()
        assert cluster.rebinds == []
        failed = [
            e for e in cluster.api.list("Event", "ns")
            if e.reason == "FailedScheduling"
            and "fault-injected commit failure" in e.message
        ]
        assert failed, "doomed items left no FailedScheduling audit trail"
    finally:
        cluster.close()


def test_midcycle_apiserver_restart_with_nonempty_queue(tmp_path):
    """Kill the apiserver while the commit queue holds binds in flight;
    the restarted incarnation (same store, new epoch) must end with
    every pod bound exactly once."""
    cluster = MiniCluster(tmp_path, "restart", backend="bus", pipelined=True)
    try:
        cluster.submit_workload()
        cluster.wait_synced(6)
        faults.configure("seed=5;commit.delay=1:ms=400")
        cluster.cycle()
        plane = cluster.cache._commit_plane
        assert plane.depth > 0, "commit queue drained before the kill"
        port = cluster.bus.port
        cluster.bus.stop()
        faults.configure(None)
        cluster.bus = BusServer(cluster.api, port=port).start()
        # barrier + resync at the next snapshots; the loop must converge
        assert _wait(
            lambda: (cluster.cycle() or True) and len(cluster.placed()) == 5,
            timeout=45.0, interval=0.05,
        ), "control loop did not converge after apiserver restart"
        cluster.cache.flush()
        assert cluster.rebinds == []
        assert plane.depth == 0
    finally:
        cluster.close()


def test_evict_through_commit_plane(tmp_path):
    """Evictions ride the plane too: pod deleted at the store, the
    Evict audit event recorded, identical to the synchronous path."""
    results = []
    for pipelined in (False, True):
        api = APIServer()
        kube, vc = KubeClient(api), VolcanoClient(api)
        vc.create_queue(build_queue("default"))
        kube.create_node(build_node("n0", {"cpu": "4", "memory": "16Gi"}))
        vc.create_pod_group(build_pod_group("ns", "v0", 1))
        kube.create_pod(build_pod(
            "ns", "v0-t0", "n0", {"cpu": "1", "memory": "1Gi"},
            phase="Running", group="v0",
        ))
        cache = SchedulerCache(client=SchedulerClient(api),
                               pipelined_commit=pipelined)
        cache.run()
        job = next(iter(cache.jobs.values()))
        task = next(iter(job.tasks.values()))
        cache.evict(task, "preempt")
        cache.flush()
        results.append((
            kube.get_pod("ns", "v0-t0") is None,
            [(e.reason, e.message) for e in api.list("Event", "ns")],
        ))
        cache.stop_commit_plane()
    assert results[0] == results[1]
    assert results[0][0] is True
    assert ("Evict", "Evicted ns/v0-t0: preempt") in results[0][1]


def test_old_peer_fallback_per_object_binds(tmp_path):
    """A v1 server that rejects the commit_batch op degrades the client
    to per-object binds — permanently flagged, still correct."""

    class V1BusServer(BusServer):
        def _execute(self, conn, req_id, payload, op):
            if op == "commit_batch":
                raise ApiError(f"unknown bus op {op!r}")
            return super()._execute(conn, req_id, payload, op)

    api = APIServer()
    kube = KubeClient(api)
    kube.create_pod(build_pod("ns", "p0", "", {"cpu": "1", "memory": "1Gi"}))
    bus = V1BusServer(api).start()
    remote = RemoteAPIServer(f"tcp://127.0.0.1:{bus.port}", timeout=5.0)
    try:
        assert remote.wait_ready(10.0)
        results = remote.commit_batch(binds=[{
            "namespace": "ns", "name": "p0", "hostname": "n0",
            "event": {"type": "Normal", "reason": "Scheduled",
                      "message": "Successfully assigned ns/p0 to n0"},
        }])
        assert results["binds"] == [None]
        assert remote._no_commit_batch is True
        assert kube.get_pod("ns", "p0").spec.node_name == "n0"
        assert any(e.reason == "Scheduled" for e in api.list("Event", "ns"))
        # the fallback sticks — no second rejected frame
        results = remote.commit_batch(binds=[{
            "namespace": "ns", "name": "p0", "hostname": "n0",
        }])
        assert results["binds"] == [None]
    finally:
        remote.close()
        bus.stop()


def test_v1_frames_still_decode():
    """The VBUS version bump keeps v1 frames decodable (MIN_VERSION),
    so a skewed peer's frames are not rejected at the framing layer."""
    a, b = socket.socketpair()
    try:
        body = b'{"op":"get"}'
        a.sendall(protocol._HEADER.pack(
            protocol.MAGIC, 1, protocol.T_REQ, 7, len(body)) + body)
        mtype, corr_id, payload = protocol.recv_frame(b)
        assert (mtype, corr_id, payload) == (protocol.T_REQ, 7, {"op": "get"})
        a.sendall(protocol._HEADER.pack(
            protocol.MAGIC, protocol.VERSION + 1, protocol.T_REQ, 7, 0))
        with pytest.raises(ValueError):
            protocol.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_overlap_metrics_exported(tmp_path):
    """The satellite metrics: queue depth gauge, coalesce histogram,
    overlap ratio — all present after a pipelined run."""
    from volcano_tpu.metrics.metrics import registry

    cluster = MiniCluster(tmp_path, "metrics", pipelined=True)
    try:
        cluster.submit_workload()
        cluster.wait_synced(6)
        cluster.cycle()
        cluster.cache.snapshot()
        rendered = registry.render()
        assert "volcano_commit_queue_depth 0" in rendered
        assert "volcano_bind_coalesce_size_count" in rendered
        assert "volcano_commit_overlap_ratio" in rendered
    finally:
        cluster.close()


def test_failed_status_writeback_counts_error_schedule_attempt(tmp_path):
    """README known-gap closed (ISSUE 7): with the commit plane on, a
    failed status writeback must count in
    ``schedule_attempts_total{result="error"}`` — one per affected JOB —
    not only in ``volcano_commit_failures_total{status}``.  The
    synchronous path gets this via JobUpdater's exception handler; the
    async path has already returned success by the time the worker sees
    the failure, so the plane itself must account it."""
    from volcano_tpu.api import new_task_info
    from volcano_tpu.metrics.metrics import registry

    def _attempts(result):
        return registry._counters.get(
            ("volcano_schedule_attempts_total", (("result", result),)), 0.0
        )

    def _status_failures():
        return registry._counters.get(
            ("volcano_commit_failures_total", (("kind", "status"),)), 0.0
        )

    live_task = new_task_info(
        build_pod("ns", "present", "", {"cpu": "100m"}, group="pg-a")
    )
    ghost_task = new_task_info(
        build_pod("ns", "missing", "", {"cpu": "100m"}, group="pg-b")
    )

    # ---- fast path: one coalesced frame, per-row errors attributed
    # back to jobs (two payloads, only the second one's Event rows are
    # rejected → exactly one error attempt, not one per failed row) ----
    from volcano_tpu.client.apiserver import AdmissionError

    api = APIServer()
    api.create(build_pod("ns", "present", "", {"cpu": "100m"}, group="pg-a"))

    def deny_ghost_events(op, obj):
        if obj.involved_object.get("name") == "missing":
            raise AdmissionError("audit quota exceeded")

    api.register_admission("Event", "CREATE", deny_ghost_events)
    cache = SchedulerCache(
        client=SchedulerClient(api), pipelined_commit=True,
    )
    try:
        assert cache._fast_status, "fixture must exercise the frame path"
        ok_payload = {
            "events": [(live_task, "Warning", "Unschedulable", "no fit")],
            "conditions": [(live_task, "Unschedulable", "no fit")],
            "pod_group": None,
        }
        bad_payload = {
            "events": [
                (ghost_task, "Warning", "Unschedulable", "no fit"),
                (ghost_task, "Warning", "Unschedulable", "still none"),
            ],
            "conditions": [(ghost_task, "Unschedulable", "no fit")],
            "pod_group": None,
        }
        err0, cf0 = _attempts("error"), _status_failures()
        cache._run_status_items([(ok_payload, None), (bad_payload, None)])
        assert _status_failures() == cf0 + 2  # both rejected rows counted
        assert _attempts("error") == err0 + 1  # but ONE failed job
    finally:
        cache.stop_commit_plane()

    # ---- slow path: a custom (non-default) updater that fails ----
    class FailingUpdater:
        def update_pod_condition(self, task, reason, message):
            raise RuntimeError("writeback rejected")

        def update_pod_group(self, pg):
            raise RuntimeError("writeback rejected")

    cache = SchedulerCache(
        status_updater=FailingUpdater(), pipelined_commit=True,
    )
    try:
        assert not cache._fast_status
        err0 = _attempts("error")
        cache._run_status_items([(dict(ok_payload), None)])
        assert _attempts("error") == err0 + 1
        # a doomed (fault-injected) payload counts too
        err0 = _attempts("error")
        cache._run_status_items([
            (dict(ok_payload), RuntimeError("fault-injected")),
        ])
        assert _attempts("error") == err0 + 1
    finally:
        cache.stop_commit_plane()
