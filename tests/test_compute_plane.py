"""Compute-plane boundary: wire round-trips, sidecar-served sessions
identical to in-process, and fallback-to-in-process when the sidecar
dies (the north-star process separation, SURVEY §7)."""

from __future__ import annotations

import os

import numpy as np
import pytest

from volcano_tpu.ops import executor as executor_mod
from volcano_tpu.ops.dispatch import run_packed_auto
from volcano_tpu.ops.synthetic import generate_preempt_packed, generate_snapshot
from volcano_tpu.serving.compute_plane import (
    ComputePlaneClient,
    ComputePlaneServer,
    deserialize_preempt,
    deserialize_snapshot,
    serialize_preempt,
    serialize_snapshot,
)


@pytest.fixture
def sock_path(tmp_path):
    return str(tmp_path / "cp.sock")


@pytest.fixture
def sidecar(sock_path):
    server = ComputePlaneServer(sock_path).start()
    yield server
    server.stop()


@pytest.fixture(autouse=True)
def _reset_executor():
    yield
    executor_mod.configure(None)


def test_snapshot_serialization_roundtrip():
    snap = generate_snapshot(n_tasks=200, n_nodes=50, gang_size=4, seed=1,
                             label_classes=3, taint_fraction=0.2)
    back = deserialize_snapshot(serialize_snapshot(snap))
    assert back.n_tasks == snap.n_tasks and back.n_jobs == snap.n_jobs
    assert back.resource_names == snap.resource_names
    np.testing.assert_array_equal(back.task_resreq, snap.task_resreq)
    np.testing.assert_array_equal(back.node_taint_bits, snap.node_taint_bits)
    assert (run_packed_auto(back) == run_packed_auto(snap)).all()


def test_preempt_serialization_roundtrip():
    from volcano_tpu.ops.preempt_pack import preempt_dense

    pk = generate_preempt_packed(n_victims=400, n_nodes=40, n_preemptors=60)
    back = deserialize_preempt(serialize_preempt(pk))
    ev_a, pipe_a = preempt_dense(pk)
    ev_b, pipe_b = preempt_dense(back)
    np.testing.assert_array_equal(ev_a, ev_b)
    np.testing.assert_array_equal(pipe_a, pipe_b)


def test_sidecar_allocate_identical(sidecar, sock_path):
    client = ComputePlaneClient(sock_path)
    assert client.health()
    snap = generate_snapshot(n_tasks=300, n_nodes=60, gang_size=4, seed=2)
    remote = client.allocate(snap)
    local = run_packed_auto(snap)
    np.testing.assert_array_equal(remote, local)
    client.close()


def test_sidecar_preempt_identical(sidecar, sock_path):
    from volcano_tpu.ops.preempt_pack import preempt_dense

    client = ComputePlaneClient(sock_path)
    pk = generate_preempt_packed(n_victims=300, n_nodes=30, n_preemptors=50)
    ev_r, pipe_r = client.preempt(pk)
    ev_l, pipe_l = preempt_dense(pk)
    np.testing.assert_array_equal(ev_r, ev_l)
    np.testing.assert_array_equal(pipe_r, pipe_l)
    client.close()


def test_executor_uses_sidecar_then_falls_back(sidecar, sock_path):
    """The e2e fallback contract: sessions flow through the sidecar while
    it lives; killing it degrades to in-process with identical results
    and NO error escaping the action."""
    executor_mod.configure(sock_path)
    snap = generate_snapshot(n_tasks=256, n_nodes=40, gang_size=4, seed=3)
    via_sidecar = executor_mod.execute_allocate(snap)
    local = run_packed_auto(snap)
    np.testing.assert_array_equal(via_sidecar, local)

    sidecar.stop()  # sidecar dies mid-life
    after_death = executor_mod.execute_allocate(snap)
    np.testing.assert_array_equal(after_death, local)


def test_action_through_sidecar_binds_identically(sidecar, sock_path, tmp_path):
    """Full framework path over the boundary: the jax-allocate action
    with the kernel executed in the SIDECAR process boundary produces
    bindings identical to the in-process run."""
    import copy
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
    from tests.builders import build_node, build_pod, build_pod_group, build_queue
    from tests.scheduler_helpers import make_cache, run_actions, tiers
    from volcano_tpu.actions.jax_allocate import JaxAllocateAction

    def cluster():
        nodes = [build_node(f"n{i}", {"cpu": "8", "memory": "32Gi"}) for i in range(4)]
        pods, pgs = [], []
        for j in range(5):
            pgs.append(build_pod_group("ns", f"pg{j}", 3, queue="q"))
            for i in range(3):
                pods.append(
                    build_pod("ns", f"j{j}-t{i}", "", {"cpu": "1", "memory": "2Gi"}, group=f"pg{j}")
                )
        return dict(nodes=nodes, pods=pods, pod_groups=pgs, queues=[build_queue("q")])

    c = cluster()
    tier_conf = tiers(["priority", "gang"],
                      ["drf", "predicates", "proportion", "nodeorder", "binpack"])

    executor_mod.configure(sock_path)
    cache_remote = make_cache(**copy.deepcopy(c))
    run_actions(cache_remote, [JaxAllocateAction()], tier_conf)

    executor_mod.configure(None)
    cache_local = make_cache(**copy.deepcopy(c))
    run_actions(cache_local, [JaxAllocateAction()], tier_conf)

    assert dict(cache_remote.binder.binds) == dict(cache_local.binder.binds)
    assert len(cache_remote.binder.binds) == 15
