"""Compute-plane boundary: wire round-trips, sidecar-served sessions
identical to in-process, and fallback-to-in-process when the sidecar
dies (the north-star process separation, SURVEY §7)."""

from __future__ import annotations

import os

import numpy as np
import pytest

from volcano_tpu.ops import executor as executor_mod
from volcano_tpu.ops.dispatch import run_packed_auto
from volcano_tpu.ops.synthetic import generate_preempt_packed, generate_snapshot
from volcano_tpu.serving.compute_plane import (
    ComputePlaneClient,
    ComputePlaneServer,
    deserialize_preempt,
    deserialize_snapshot,
    serialize_preempt,
    serialize_snapshot,
)


@pytest.fixture
def sock_path(tmp_path):
    return str(tmp_path / "cp.sock")


@pytest.fixture
def sidecar(sock_path):
    server = ComputePlaneServer(sock_path).start()
    yield server
    server.stop()


@pytest.fixture(autouse=True)
def _reset_executor():
    yield
    executor_mod.configure(None)


def test_snapshot_serialization_roundtrip():
    snap = generate_snapshot(n_tasks=200, n_nodes=50, gang_size=4, seed=1,
                             label_classes=3, taint_fraction=0.2)
    back, _ = deserialize_snapshot(serialize_snapshot(snap))
    assert back.n_tasks == snap.n_tasks and back.n_jobs == snap.n_jobs
    assert back.resource_names == snap.resource_names
    np.testing.assert_array_equal(back.task_resreq, snap.task_resreq)
    np.testing.assert_array_equal(back.node_taint_bits, snap.node_taint_bits)
    assert (run_packed_auto(back) == run_packed_auto(snap)).all()


def test_preempt_serialization_roundtrip():
    from volcano_tpu.ops.preempt_pack import preempt_dense

    pk = generate_preempt_packed(n_victims=400, n_nodes=40, n_preemptors=60)
    back = deserialize_preempt(serialize_preempt(pk))
    ev_a, pipe_a = preempt_dense(pk)
    ev_b, pipe_b = preempt_dense(back)
    np.testing.assert_array_equal(ev_a, ev_b)
    np.testing.assert_array_equal(pipe_a, pipe_b)


def test_sidecar_allocate_identical(sidecar, sock_path):
    client = ComputePlaneClient(sock_path)
    assert client.health()
    snap = generate_snapshot(n_tasks=300, n_nodes=60, gang_size=4, seed=2)
    remote = client.allocate(snap)
    local = run_packed_auto(snap)
    np.testing.assert_array_equal(remote, local)
    client.close()


def test_sidecar_preempt_identical(sidecar, sock_path):
    from volcano_tpu.ops.preempt_pack import preempt_dense

    client = ComputePlaneClient(sock_path)
    pk = generate_preempt_packed(n_victims=300, n_nodes=30, n_preemptors=50)
    ev_r, pipe_r = client.preempt(pk)
    ev_l, pipe_l = preempt_dense(pk)
    np.testing.assert_array_equal(ev_r, ev_l)
    np.testing.assert_array_equal(pipe_r, pipe_l)
    client.close()


def test_executor_uses_sidecar_then_falls_back(sidecar, sock_path):
    """The e2e fallback contract: sessions flow through the sidecar while
    it lives; killing it degrades to in-process with identical results
    and NO error escaping the action."""
    executor_mod.configure(sock_path)
    snap = generate_snapshot(n_tasks=256, n_nodes=40, gang_size=4, seed=3)
    via_sidecar = executor_mod.execute_allocate(snap)
    local = run_packed_auto(snap)
    np.testing.assert_array_equal(via_sidecar, local)

    sidecar.stop()  # sidecar dies mid-life
    after_death = executor_mod.execute_allocate(snap)
    np.testing.assert_array_equal(after_death, local)


def test_action_through_sidecar_binds_identically(sidecar, sock_path, tmp_path):
    """Full framework path over the boundary: the jax-allocate action
    with the kernel executed in the SIDECAR process boundary produces
    bindings identical to the in-process run."""
    import copy
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
    from tests.builders import build_node, build_pod, build_pod_group, build_queue
    from tests.scheduler_helpers import make_cache, run_actions, tiers
    from volcano_tpu.actions.jax_allocate import JaxAllocateAction

    def cluster():
        nodes = [build_node(f"n{i}", {"cpu": "8", "memory": "32Gi"}) for i in range(4)]
        pods, pgs = [], []
        for j in range(5):
            pgs.append(build_pod_group("ns", f"pg{j}", 3, queue="q"))
            for i in range(3):
                pods.append(
                    build_pod("ns", f"j{j}-t{i}", "", {"cpu": "1", "memory": "2Gi"}, group=f"pg{j}")
                )
        return dict(nodes=nodes, pods=pods, pod_groups=pgs, queues=[build_queue("q")])

    c = cluster()
    tier_conf = tiers(["priority", "gang"],
                      ["drf", "predicates", "proportion", "nodeorder", "binpack"])

    executor_mod.configure(sock_path)
    cache_remote = make_cache(**copy.deepcopy(c))
    run_actions(cache_remote, [JaxAllocateAction()], tier_conf)

    executor_mod.configure(None)
    cache_local = make_cache(**copy.deepcopy(c))
    run_actions(cache_local, [JaxAllocateAction()], tier_conf)

    assert dict(cache_remote.binder.binds) == dict(cache_local.binder.binds)
    assert len(cache_remote.binder.binds) == 15


def test_delta_serialize_apply_roundtrip():
    """serialize_delta → apply_delta reproduces the new snapshot from the
    server-held base, plane by plane (no socket involved)."""
    import copy as _copy

    from volcano_tpu.ops.pack_cache import PackCache
    from volcano_tpu.serving.compute_plane import (
        _unpack_arrays,
        apply_delta,
        serialize_delta,
    )
    from tests.test_pack_cache import _base_cluster, _pack_both
    from tests.scheduler_helpers import make_cache
    from volcano_tpu.framework import close_session

    rng = np.random.RandomState(21)
    cache = make_cache(**_base_cluster(rng, n_jobs=4, gang=2, n_nodes=5))
    pc = PackCache(cache)
    ssn, snap1, _ = _pack_both(cache, pc)
    close_session(ssn)
    base = _copy.deepcopy(snap1)

    # churn: bind one task (node delta) + a spec change (task delta)
    for job in cache.jobs.values():
        for t in list(job.tasks.values()):
            if not t.node_name:
                cache.bind(t, sorted(cache.nodes)[0])
                break
        break
    ssn, snap2, _ = _pack_both(cache, pc)
    close_session(ssn)
    assert snap2.delta is not None and snap2.delta.base_rev == snap1.rev

    meta, arrays = _unpack_arrays(serialize_delta(snap2))
    rebuilt = apply_delta(base, meta, arrays)
    from volcano_tpu.serving.compute_plane import _SNAP_ARRAYS

    for name in _SNAP_ARRAYS:
        np.testing.assert_array_equal(
            getattr(rebuilt, name), getattr(snap2, name), err_msg=name
        )
    assert rebuilt.needs_host_validation == snap2.needs_host_validation
    assert rebuilt.memory_exact == snap2.memory_exact


def test_sidecar_delta_frames_identical(sidecar, sock_path):
    """Warm sessions ship delta frames: the sidecar applies the scatter
    to its held snapshot and returns assignments identical to the local
    kernel; a revision mismatch degrades to a full frame (T_NEED_FULL),
    never a wrong answer."""
    from volcano_tpu.framework import close_session
    from volcano_tpu.ops.pack_cache import PackCache
    from tests.test_pack_cache import _base_cluster, _pack_both
    from tests.scheduler_helpers import make_cache

    rng = np.random.RandomState(22)
    cache = make_cache(**_base_cluster(rng, n_jobs=5, gang=3, n_nodes=6))
    pc = PackCache(cache)
    client = ComputePlaneClient(sock_path)

    ssn, snap1, _ = _pack_both(cache, pc)
    close_session(ssn)
    np.testing.assert_array_equal(client.allocate(snap1), run_packed_auto(snap1))
    assert client._acked[pc.key] == snap1.rev  # server seeded

    # warm cycle: churn then delta frame
    for job in cache.jobs.values():
        for t in list(job.tasks.values()):
            if not t.node_name:
                cache.bind(t, sorted(cache.nodes)[1])
                break
        break
    ssn, snap2, _ = _pack_both(cache, pc)
    close_session(ssn)
    assert snap2.delta is not None
    np.testing.assert_array_equal(client.allocate(snap2), run_packed_auto(snap2))
    assert client._acked[pc.key] == snap2.rev

    # revision-mismatch path: claim a base the server does not hold
    ssn, snap3, _ = _pack_both(cache, pc)
    close_session(ssn)
    client._acked[pc.key] = snap3.delta.base_rev + 1000  # force skew...
    # ...which suppresses the delta attempt; instead, force a delta send
    # against a wrong server-side revision:
    client._acked[pc.key] = snap3.delta.base_rev
    from volcano_tpu.serving import compute_plane as cp

    cp._session_store.put(pc.key, snap3.delta.base_rev - 1, snap2)
    np.testing.assert_array_equal(client.allocate(snap3), run_packed_auto(snap3))
    assert client._acked[pc.key] == snap3.rev
    client.close()
