"""Controller job-cache table — the reference's
pkg/controllers/cache/cache_test.go pattern: add/update/delete job and
pod interleavings, shell entries (pods before job), GC of drained
shells, and TaskCompleted rollups."""

from __future__ import annotations

import pytest

from volcano_tpu.apis import batch, core
from volcano_tpu.controllers.cache import JobCache

from tests.builders import build_pod


def _job(name="j1", ns="ns"):
    return batch.Job(
        metadata=core.ObjectMeta(name=name, namespace=ns),
        spec=batch.JobSpec(
            min_available=1,
            tasks=[batch.TaskSpec(name="worker", replicas=2)],
        ),
    )


def _pod(name, job="j1", task="worker", phase="Pending", ns="ns"):
    pod = build_pod(ns, name, "", {"cpu": "1", "memory": "1G"}, phase=phase)
    pod.metadata.annotations[batch.JOB_NAME_KEY] = job
    pod.metadata.annotations[batch.TASK_SPEC_KEY] = task
    return pod


class TestJobCacheLifecycle:
    def test_add_get_clone_map_isolated(self):
        """Reference Clone contract (apis/job_info.go:37-52): the pods
        MAP is copied (mutations don't leak back) while the Job object
        itself is shared by reference."""
        cache = JobCache()
        cache.add(_job())
        cache.add_pod(_pod("j1-worker-0"))
        info = cache.get("ns/j1")
        assert info is not None and info.job.metadata.name == "j1"
        info.pods["worker"].clear()
        assert "j1-worker-0" in cache.get("ns/j1").pods["worker"]

    def test_duplicate_add_rejected(self):
        cache = JobCache()
        cache.add(_job())
        with pytest.raises(ValueError, match="duplicated job"):
            cache.add(_job())

    def test_pods_before_job_shell_entry(self):
        """cache.go: pod events can arrive before the job object — a
        shell entry accumulates them and the late Add fills the job."""
        cache = JobCache()
        cache.add_pod(_pod("j1-worker-0"))
        info = cache.get("ns/j1")
        assert info is not None and info.job is None
        assert "j1-worker-0" in info.pods["worker"]
        cache.add(_job())  # late add onto the shell: not a duplicate
        info = cache.get("ns/j1")
        assert info.job is not None
        assert "j1-worker-0" in info.pods["worker"]

    def test_delete_pod_gcs_drained_shell(self):
        cache = JobCache()
        pod = _pod("j1-worker-0")
        cache.add_pod(pod)
        cache.delete_pod(pod)
        assert cache.get("ns/j1") is None  # shell drained → GC'd

    def test_delete_pod_keeps_entry_with_job(self):
        cache = JobCache()
        cache.add(_job())
        pod = _pod("j1-worker-0")
        cache.add_pod(pod)
        cache.delete_pod(pod)
        info = cache.get("ns/j1")
        assert info is not None and info.job is not None

    def test_update_upserts(self):
        cache = JobCache()
        job = _job()
        cache.update(job)  # update-before-add upserts (resync path)
        assert cache.get("ns/j1") is not None
        job2 = _job()
        job2.spec.max_retry = 7
        cache.update(job2)
        assert cache.get("ns/j1").job.spec.max_retry == 7

    def test_delete_job(self):
        cache = JobCache()
        cache.add(_job())
        cache.delete(_job())
        assert cache.get("ns/j1") is None


class TestTaskCompleted:
    def test_all_succeeded(self):
        cache = JobCache()
        cache.add(_job())
        for i in range(2):
            cache.add_pod(_pod(f"j1-worker-{i}", phase="Succeeded"))
        assert cache.task_completed("ns/j1", "worker")

    def test_partial_not_completed(self):
        cache = JobCache()
        cache.add(_job())
        cache.add_pod(_pod("j1-worker-0", phase="Succeeded"))
        cache.add_pod(_pod("j1-worker-1", phase="Running"))
        assert not cache.task_completed("ns/j1", "worker")

    def test_pod_phase_update_flips_completion(self):
        cache = JobCache()
        cache.add(_job())
        p0 = _pod("j1-worker-0", phase="Succeeded")
        p1 = _pod("j1-worker-1", phase="Running")
        cache.add_pod(p0)
        cache.add_pod(p1)
        assert not cache.task_completed("ns/j1", "worker")
        p1done = p1.clone()
        p1done.status.phase = "Succeeded"
        cache.update_pod(p1done)
        assert cache.task_completed("ns/j1", "worker")

    def test_unknown_job_or_empty_task(self):
        cache = JobCache()
        assert not cache.task_completed("ns/ghost", "worker")
        cache.add(_job())
        assert not cache.task_completed("ns/j1", "worker")  # no pods yet
