"""Deploy packaging tests (the helm-chart-equivalent renderer).

Reference: installer/helm/chart/volcano/values.yaml + templates/ — the
chart parametrizes image names/tags, pull secret, and the scheduler
policy file, and stamps out one Deployment per daemon; these tests pin
the same parametrization surface on the renderer in
volcano_tpu/deploy/package.py, now rendering the multi-process bus
topology (vtpu-apiserver + scheduler + controllers + admission, all
wired with --bus).
"""

import yaml

from volcano_tpu.deploy.package import (
    apply_set,
    DEFAULT_VALUES,
    load_values,
    merge_values,
    render,
    render_yaml,
)

BUS_URL = "tcp://volcano-tpu-apiserver.volcano-tpu-system.svc:7180"


def _by_name(manifests):
    return {m["metadata"]["name"] + "/" + m["kind"]: m for _, m in manifests}


def _deployment(manifests, daemon):
    return _by_name(manifests)[f"volcano-tpu-{daemon}/Deployment"]


def _container(manifests, daemon, name=None):
    containers = _deployment(manifests, daemon)["spec"]["template"]["spec"]["containers"]
    if name is None:
        return containers[0]
    return next(c for c in containers if c["name"] == name)


def test_default_render_manifest_set():
    manifests = render(DEFAULT_VALUES)
    names = [fname for fname, _ in manifests]
    assert names == [
        "00-namespace.yaml", "10-scheduler-configmap.yaml",
        "20-apiserver-deployment.yaml", "21-apiserver-service.yaml",
        "30-scheduler-deployment.yaml", "31-controllers-deployment.yaml",
        "32-admission-deployment.yaml",
    ]
    # kubectl apply -f DIR walks lexically; apply order must survive it:
    # namespace first, then the apiserver before the daemons dialing it
    assert names == sorted(names)
    by_name = _by_name(manifests)
    assert by_name["volcano-tpu-system/Namespace"]
    for daemon in ("apiserver", "scheduler", "controllers", "admission"):
        dep = _deployment(manifests, daemon)
        assert dep["metadata"]["namespace"] == "volcano-tpu-system"
    # every manifest round-trips through YAML
    for _, m in manifests:
        assert yaml.safe_load(yaml.safe_dump(m)) == m


def test_every_daemon_dials_the_bus():
    """The topology claim: one apiserver serving the bus; scheduler,
    controllers, and admission all wired to it with --bus."""
    manifests = render(DEFAULT_VALUES)
    api = _container(manifests, "apiserver")
    assert api["command"][0] == "vtpu-apiserver"
    assert api["command"][api["command"].index("--port") + 1] == "7180"
    svc = _by_name(manifests)["volcano-tpu-apiserver/Service"]
    assert {"name": "bus", "port": 7180} in svc["spec"]["ports"]

    for daemon, binary in (("scheduler", "vtpu-scheduler"),
                           ("controllers", "vtpu-controllers"),
                           ("admission", "vtpu-admission")):
        cmd = _container(manifests, daemon)["command"]
        assert cmd[0] == binary
        assert cmd[cmd.index("--bus") + 1] == BUS_URL


def test_ha_replicas_get_leader_election():
    # controllers default to 2 leader-elected replicas (no accelerator
    # demand, HA is free); the scheduler defaults to 1 because every
    # scheduler pod holds a full TPU slice — a default standby would sit
    # Pending on a single-slice cluster
    manifests = render(DEFAULT_VALUES)
    dep = _deployment(manifests, "controllers")
    assert dep["spec"]["replicas"] == 2
    assert "--leader-elect" in _container(manifests, "controllers")["command"]
    assert _deployment(manifests, "scheduler")["spec"]["replicas"] == 1
    assert "--leader-elect" not in _container(manifests, "scheduler")["command"]
    # opting into scheduler HA (spare slices exist) wires the lease
    values = merge_values(DEFAULT_VALUES, {"scheduler": {"replicas": 2}})
    manifests = render(values)
    assert _deployment(manifests, "scheduler")["spec"]["replicas"] == 2
    assert "--leader-elect" in _container(manifests, "scheduler")["command"]


def test_apiserver_seeds_synthetic_nodes():
    manifests = render(DEFAULT_VALUES)
    cmd = _container(manifests, "apiserver")["command"]
    assert cmd[cmd.index("--seed-nodes") + 1] == "8"


def test_configmap_inlines_default_scheduler_conf():
    manifests = render(DEFAULT_VALUES)
    cm = _by_name(manifests)["volcano-tpu-scheduler-configmap/ConfigMap"]
    conf_text = cm["data"]["volcano-scheduler.conf"]
    parsed = yaml.safe_load(conf_text)
    assert "allocate" in parsed["actions"]
    assert parsed["tiers"]


def test_configmap_inlines_custom_conf_file(tmp_path):
    conf = tmp_path / "policy.conf"
    conf.write_text("actions: \"enqueue, allocate\"\ntiers: []\n")
    values = merge_values(
        DEFAULT_VALUES, {"basic": {"scheduler_config_file": str(conf)}})
    manifests = render(values)
    cm = _by_name(manifests)["volcano-tpu-scheduler-configmap/ConfigMap"]
    assert cm["data"]["volcano-scheduler.conf"] == conf.read_text()


def test_compute_plane_sidecar_wiring():
    manifests = render(DEFAULT_VALUES)
    spec = _deployment(manifests, "scheduler")["spec"]["template"]["spec"]
    sched, sidecar = spec["containers"]
    socket = "/run/vtpu/compute-plane.sock"
    # the scheduler points at the socket; sidecar serves it; both mount
    # the shared emptyDir volume
    assert {"name": "VTPU_COMPUTE_PLANE", "value": socket} in sched["env"]
    assert sidecar["command"][:3] == ["vtpu-compute-plane", "--socket", socket]
    assert "--warmup" in sidecar["command"]
    assert sidecar["resources"]["limits"]["google.com/tpu"] == "8"
    mounts = {v["name"] for v in spec["volumes"]}
    assert "compute-plane-socket" in mounts
    for c in (sched, sidecar):
        assert any(m["name"] == "compute-plane-socket" for m in c["volumeMounts"])


def test_compute_plane_disabled():
    values = merge_values(DEFAULT_VALUES, {"compute_plane": {"enabled": False}})
    manifests = render(values)
    spec = _deployment(manifests, "scheduler")["spec"]["template"]["spec"]
    assert [c["name"] for c in spec["containers"]] == ["scheduler"]
    assert "env" not in spec["containers"][0]
    assert all(v["name"] != "compute-plane-socket" for v in spec["volumes"])
    # in-process kernels still need the device: the TPU limit moves onto
    # the scheduler container instead of vanishing with the sidecar
    assert spec["containers"][0]["resources"]["limits"]["google.com/tpu"] == "8"


def test_null_scalar_keeps_default():
    values = load_values("scheduler:\n  port:\n  nodes: 4\n")
    assert values["scheduler"]["port"] == 8080
    assert values["scheduler"]["nodes"] == 4
    render(values)


def test_values_file_merge_and_image_pull_secret():
    values = load_values(yaml.safe_dump({
        "basic": {
            "release_name": "vt-prod",
            "namespace": "prod",
            "image_tag_version": "v1.2.3",
            "image_pull_secret": "regcred",
        },
    }))
    # untouched defaults survive the merge
    assert values["scheduler"]["port"] == 8080
    manifests = render(values)
    by_name = _by_name(manifests)
    dep = by_name["vt-prod-scheduler/Deployment"]
    spec = dep["spec"]["template"]["spec"]
    assert spec["containers"][0]["image"] == "volcano-tpu:v1.2.3"
    cmd = spec["containers"][0]["command"]
    assert cmd[cmd.index("--bus") + 1] == "tcp://vt-prod-apiserver.prod.svc:7180"
    # every daemon pod can pull from the private registry
    for daemon in ("apiserver", "scheduler", "controllers", "admission"):
        d = by_name[f"vt-prod-{daemon}/Deployment"]
        assert d["spec"]["template"]["spec"]["imagePullSecrets"] == [
            {"name": "regcred"}]
    assert by_name["vt-prod-apiserver/Service"]["metadata"]["namespace"] == "prod"


def test_set_overrides_with_coercion():
    values = DEFAULT_VALUES
    for assignment in ("scheduler.port=9090",
                       "bus.port=7777",
                       "prometheus.scrape=false",
                       "compute_plane.tpu_chips=4",
                       "basic.image_tag_version=nightly"):
        values = apply_set(values, assignment)
    assert values["scheduler"]["port"] == 9090
    assert values["prometheus"]["scrape"] is False
    manifests = render(values)
    sched = _container(manifests, "scheduler")
    meta = _deployment(manifests, "scheduler")["spec"]["template"]["metadata"]
    assert "annotations" not in meta
    assert sched["image"] == "volcano-tpu:nightly"
    assert sched["livenessProbe"]["httpGet"]["port"] == 9090
    cmd = sched["command"]
    assert cmd[cmd.index("--bus") + 1].endswith(":7777")
    sidecar = _container(manifests, "scheduler", "compute-plane")
    assert sidecar["resources"]["limits"]["google.com/tpu"] == "4"


def test_set_rejects_malformed():
    import pytest

    with pytest.raises(ValueError):
        apply_set(DEFAULT_VALUES, "no-equals-sign")
    with pytest.raises(ValueError):
        apply_set(DEFAULT_VALUES, "=value")
    # a path traversing through an existing scalar is a typo, caught at
    # parse time rather than as a render-time TypeError
    with pytest.raises(ValueError, match="is a value, not a section"):
        apply_set(DEFAULT_VALUES, "scheduler.port.http=9090")


def test_set_string_skips_coercion():
    values = apply_set(DEFAULT_VALUES, "basic.image_tag_version=20260730",
                       coerce=False)
    assert values["basic"]["image_tag_version"] == "20260730"
    # the CLI surface: --set-string renders the tag as a string
    from volcano_tpu.cmd.package import main

    import io
    import contextlib

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert main(["template", "--set-string",
                     "basic.image_tag_version=20260730"]) == 0
    docs = list(yaml.safe_load_all(buf.getvalue()))
    dep = next(d for d in docs if d["kind"] == "Deployment")
    image = dep["spec"]["template"]["spec"]["containers"][0]["image"]
    assert image == "volcano-tpu:20260730"


def test_deployment_rollout_strategies():
    # Recreate only where forced: apiserver (two concurrent stores
    # behind one Service would split clients between divergent stores)
    # and scheduler (a surge pod can't place while the old pod holds
    # the TPU chips).  Controllers/admission roll normally — Recreate
    # there would guarantee a full outage on every image upgrade.
    manifests = render(DEFAULT_VALUES)
    for daemon in ("apiserver", "scheduler"):
        dep = _deployment(manifests, daemon)
        assert dep["spec"]["strategy"] == {"type": "Recreate"}
    for daemon in ("controllers", "admission"):
        dep = _deployment(manifests, daemon)
        assert dep["spec"]["strategy"] == {"type": "RollingUpdate"}


def test_render_yaml_stream_parses():
    docs = list(yaml.safe_load_all(render_yaml(DEFAULT_VALUES)))
    assert [d["kind"] for d in docs] == [
        "Namespace", "ConfigMap", "Deployment", "Service",
        "Deployment", "Deployment", "Deployment"]


def test_empty_section_header_keeps_defaults():
    # "compute_plane:" with nothing under it parses as null; the merge
    # must keep the section's defaults, not crash render()
    values = load_values("compute_plane:\nbasic:\n  release_name: x\n")
    assert values["compute_plane"] == DEFAULT_VALUES["compute_plane"]
    assert values["basic"]["release_name"] == "x"
    render(values)


def test_static_manifest_commands_parse():
    # the hand-written deploy/kubernetes manifest must stay parseable by
    # the real daemon argument parsers (a flag rename would otherwise
    # ship CrashLooping pods while all renderer tests stay green)
    import os

    path = os.path.join(os.path.dirname(__file__), "..",
                        "deploy", "kubernetes", "volcano-tpu.yaml")
    with open(path, "r", encoding="utf-8") as fh:
        docs = [d for d in yaml.safe_load_all(fh) if d]
    deployments = [d for d in docs if d["kind"] == "Deployment"]
    assert len(deployments) == 4
    seen = set()
    for dep in deployments:
        for c in dep["spec"]["template"]["spec"]["containers"]:
            binary = c["command"][0]
            seen.add(binary)
            if binary == "vtpu-apiserver":
                known = {"--listen-host", "--port", "--listen-port",
                         "--backlog-size", "--bookmark-interval",
                         "--enable-debug-stacks", "--seed-nodes",
                         "--seed-node-cpu", "--seed-node-mem",
                         "--data-dir", "--snapshot-every", "--replicas",
                         "--replica-index", "--repl-lease-ttl"}
            elif binary == "vtpu-scheduler":
                known = {"--bus", "--listen-host", "--listen-port",
                         "--leader-elect", "--leader-elect-id",
                         "--scheduler-conf", "--schedule-period",
                         "--scheduler-name", "--gc-quiesce-period",
                         "--snapshot-reuse", "--warmup",
                         "--micro-cycles", "--micro-debounce-ms",
                         "--percentage-nodes-to-find",
                         "--minimum-feasible-nodes",
                         "--minimum-percentage-nodes-to-find",
                         "--enable-debug-stacks"}
            elif binary == "vtpu-controllers":
                known = {"--bus", "--listen-host", "--listen-port",
                         "--leader-elect", "--leader-elect-id", "--period",
                         "--enable-debug-stacks"}
            elif binary == "vtpu-admission":
                known = {"--bus", "--listen-host", "--listen-port",
                         "--leader-elect", "--leader-elect-id",
                         "--gate-pods", "--enable-debug-stacks"}
            elif binary == "vtpu-compute-plane":
                continue
            else:
                raise AssertionError(f"unexpected binary {binary}")
            flags = {a for a in c["command"][1:] if a.startswith("--")}
            assert flags <= known, (binary, flags - known)
    assert {"vtpu-apiserver", "vtpu-scheduler", "vtpu-controllers",
            "vtpu-admission"} <= seen


def test_static_manifest_matches_renderer():
    # deploy/kubernetes/volcano-tpu.yaml IS the rendered default output
    # (plus the header comment) — regenerate it when values change:
    #   python -m volcano_tpu.cmd.package template > ...
    import os

    path = os.path.join(os.path.dirname(__file__), "..",
                        "deploy", "kubernetes", "volcano-tpu.yaml")
    with open(path, "r", encoding="utf-8") as fh:
        static = [d for d in yaml.safe_load_all(fh) if d]
    rendered = [m for _, m in render(DEFAULT_VALUES)]
    assert static == rendered


def test_chart_values_file_matches_defaults():
    # deploy/chart/values.yaml documents the defaults; merging it over
    # DEFAULT_VALUES must be a no-op or the two sources have drifted
    import os

    path = os.path.join(os.path.dirname(__file__), "..",
                        "deploy", "chart", "values.yaml")
    with open(path, "r", encoding="utf-8") as fh:
        assert load_values(fh.read()) == DEFAULT_VALUES


def test_rendered_scheduler_command_parses():
    # the scheduler Deployment command must be accepted verbatim by the
    # real vtpu-scheduler argument parser and carry the mounted conf +
    # the same port the probe points at
    import argparse

    from volcano_tpu.cmd.scheduler import add_common_args

    manifests = render(
        merge_values(DEFAULT_VALUES, {"scheduler": {"replicas": 2}}))
    container = _container(manifests, "scheduler")
    cmd = container["command"]
    assert cmd[0] == "vtpu-scheduler"

    parser = argparse.ArgumentParser()
    parser.add_argument("--scheduler-conf", default="")
    parser.add_argument("--schedule-period", type=float, default=1.0)
    parser.add_argument("--micro-cycles", action="store_true")
    add_common_args(parser)
    args = parser.parse_args(cmd[1:])
    assert args.micro_cycles is True  # the deployed default is event-driven
    assert args.bus == BUS_URL
    assert args.listen_host == "0.0.0.0"
    assert args.listen_port == 8080
    assert args.leader_elect is True
    assert args.scheduler_conf == "/etc/volcano-tpu/volcano-scheduler.conf"
    # the conf path the command reads is inside the ConfigMap mount
    mount = next(m for m in container["volumeMounts"]
                 if m["name"] == "scheduler-config")
    assert args.scheduler_conf.startswith(mount["mountPath"] + "/")
    # probe port agrees with the port the process actually binds
    assert container["livenessProbe"]["httpGet"]["port"] == args.listen_port


class TestShardedFederationRendering:
    def test_shards_renders_pinned_members_no_leader_election(self):
        values = apply_set(DEFAULT_VALUES, "scheduler.shards=3")
        manifests = dict(render(values))
        # the leader-elected pair is REPLACED by three pinned members
        assert "30-scheduler-deployment.yaml" not in manifests
        for i in range(3):
            dep = manifests[f"30-scheduler-{i}-deployment.yaml"]
            assert dep["spec"]["replicas"] == 1
            cmd = dep["spec"]["template"]["spec"]["containers"][0]["command"]
            assert "--leader-elect" not in cmd
            assert cmd[cmd.index("--shards") + 1] == "3"
            assert (
                cmd[cmd.index("--shard-identity") + 1]
                == f"volcano-tpu-scheduler-{i}"
            )
            assert "--shard-lease-duration" in cmd
            # every member still carries the compute-plane sidecar
            names = [c["name"] for c in
                     dep["spec"]["template"]["spec"]["containers"]]
            assert names == ["scheduler", "compute-plane"]

    def test_shard_member_commands_parse(self):
        import argparse

        from volcano_tpu.cmd.scheduler import add_common_args

        values = apply_set(DEFAULT_VALUES, "scheduler.shards=2")
        dep = dict(render(values))["30-scheduler-1-deployment.yaml"]
        cmd = dep["spec"]["template"]["spec"]["containers"][0]["command"]
        parser = argparse.ArgumentParser()
        parser.add_argument("--scheduler-conf", default="")
        parser.add_argument("--micro-cycles", action="store_true")
        parser.add_argument("--shards", type=int, default=0)
        parser.add_argument("--shard-identity", default="")
        parser.add_argument("--shard-lease-duration", type=float,
                            default=2.0)
        add_common_args(parser)
        args = parser.parse_args(cmd[1:])
        assert args.shards == 2
        assert args.shard_identity == "volcano-tpu-scheduler-1"
        assert args.bus == BUS_URL

    def test_shard_autoscale_flag_renders_on_every_member(self):
        values = apply_set(DEFAULT_VALUES, "scheduler.shards=2")
        values = apply_set(values, "scheduler.shard_autoscale=true")
        manifests = dict(render(values))
        for i in range(2):
            dep = manifests[f"30-scheduler-{i}-deployment.yaml"]
            cmd = dep["spec"]["template"]["spec"]["containers"][0]["command"]
            assert cmd[cmd.index("--shard-autoscale") + 1] == "on"
        # off by default: the static fleet stays static
        plain = dict(render(apply_set(DEFAULT_VALUES,
                                      "scheduler.shards=2")))
        for i in range(2):
            dep = plain[f"30-scheduler-{i}-deployment.yaml"]
            cmd = dep["spec"]["template"]["spec"]["containers"][0]["command"]
            assert "--shard-autoscale" not in cmd

    def test_shards_off_output_unchanged(self):
        # shards=0 (the default) must render exactly the classic
        # topology — the pinned static manifest stays valid
        assert dict(render(DEFAULT_VALUES)).keys() == dict(
            render(apply_set(DEFAULT_VALUES, "scheduler.shards=0"))
        ).keys()
        assert "30-scheduler-deployment.yaml" in dict(
            render(DEFAULT_VALUES))


class TestReplicatedApiserverRendering:
    def test_default_single_apiserver_is_durable(self):
        # apiserver.replicas=1 keeps the classic one-Deployment shape,
        # now with a WAL data dir (emptyDir) so container restarts
        # resume watch cursors instead of forcing a 410 relist storm
        manifests = dict(render(DEFAULT_VALUES))
        dep = manifests["20-apiserver-deployment.yaml"]
        c = dep["spec"]["template"]["spec"]["containers"][0]
        cmd = c["command"]
        assert cmd[cmd.index("--data-dir") + 1] == "/var/lib/vtpu"
        assert "--replicas" not in cmd
        mount = next(m for m in c["volumeMounts"] if m["name"] == "bus-data")
        assert mount["mountPath"] == "/var/lib/vtpu"
        assert {"name": "bus-data", "emptyDir": {}} in (
            dep["spec"]["template"]["spec"]["volumes"]
        )
        assert "21-apiserver-service.yaml" in manifests

    def test_replicas_render_per_replica_deployments_and_services(self):
        values = apply_set(DEFAULT_VALUES, "apiserver.replicas=3")
        manifests = dict(render(values))
        assert "20-apiserver-deployment.yaml" not in manifests
        expected_list = ",".join(
            f"tcp://volcano-tpu-apiserver-{i}.volcano-tpu-system.svc:7180"
            for i in range(3)
        )
        for i in range(3):
            dep = manifests[f"20-apiserver-{i}-deployment.yaml"]
            assert dep["spec"]["replicas"] == 1
            cmd = dep["spec"]["template"]["spec"]["containers"][0]["command"]
            assert cmd[cmd.index("--replicas") + 1] == expected_list
            assert cmd[cmd.index("--replica-index") + 1] == str(i)
            assert "--repl-lease-ttl" in cmd
            svc = manifests[f"21-apiserver-{i}-service.yaml"]
            assert svc["spec"]["selector"] == {
                "app": f"volcano-tpu-apiserver-{i}"
            }
        # every daemon dials the FULL endpoint list
        for fname, m in manifests.items():
            if m.get("kind") != "Deployment" or "apiserver" in fname:
                continue
            for c in m["spec"]["template"]["spec"]["containers"]:
                cmd = c["command"]
                if "--bus" in cmd:
                    assert cmd[cmd.index("--bus") + 1] == expected_list, fname

    def test_replicated_apiserver_command_parses(self):
        # the rendered replica command must be accepted verbatim by the
        # REAL vtpu-apiserver argument parser (a flag rename would
        # otherwise ship CrashLooping pods while renderer tests stay
        # green)
        values = apply_set(DEFAULT_VALUES, "apiserver.replicas=3")
        dep = dict(render(values))["20-apiserver-1-deployment.yaml"]
        cmd = dep["spec"]["template"]["spec"]["containers"][0]["command"]
        assert cmd[0] == "vtpu-apiserver"
        ns = _parse_apiserver_cmd(cmd[1:])
        assert ns.replica_index == 1
        assert ns.data_dir == "/var/lib/vtpu"
        assert len(ns.replicas.split(",")) == 3
        assert ns.repl_lease_ttl == 2.0


def _parse_apiserver_cmd(argv):
    """Parse argv with vtpu-apiserver's REAL parser: main() builds a
    plain ArgumentParser inline, so spy on parse_args and stop main()
    before it would start the daemon."""
    import argparse
    from unittest import mock

    from volcano_tpu.cmd import apiserver as apiserver_cmd

    captured = {}
    real_parse = argparse.ArgumentParser.parse_args

    def spy(self, args=None, namespace=None):
        ns = real_parse(self, args, namespace)
        captured["ns"] = ns
        raise SystemExit(0)

    with mock.patch.object(argparse.ArgumentParser, "parse_args", spy):
        try:
            apiserver_cmd.main(argv)
        except SystemExit:
            pass
    return captured["ns"]
