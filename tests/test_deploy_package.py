"""Deploy packaging tests (the helm-chart-equivalent renderer).

Reference: installer/helm/chart/volcano/values.yaml + templates/ — the
chart parametrizes image names/tags, pull secret, and the scheduler
policy file; these tests pin the same parametrization surface on the
renderer in volcano_tpu/deploy/package.py.
"""

import yaml

from volcano_tpu.deploy.package import (
    DEFAULT_VALUES,
    apply_set,
    load_values,
    merge_values,
    render,
    render_yaml,
)


def _by_kind(manifests):
    return {m["kind"]: m for _, m in manifests}


def test_default_render_manifest_set():
    manifests = render(DEFAULT_VALUES)
    names = [fname for fname, _ in manifests]
    assert names == ["00-namespace.yaml", "10-scheduler-configmap.yaml",
                     "20-deployment.yaml", "30-service.yaml"]
    # kubectl apply -f DIR walks lexically; apply order must survive it
    assert names == sorted(names)
    kinds = _by_kind(manifests)
    assert kinds["Namespace"]["metadata"]["name"] == "volcano-tpu-system"
    dep = kinds["Deployment"]
    assert dep["metadata"]["namespace"] == "volcano-tpu-system"
    containers = dep["spec"]["template"]["spec"]["containers"]
    assert [c["name"] for c in containers] == ["control-plane", "compute-plane"]
    # every manifest round-trips through YAML
    for _, m in manifests:
        assert yaml.safe_load(yaml.safe_dump(m)) == m


def test_configmap_inlines_default_scheduler_conf():
    kinds = _by_kind(render(DEFAULT_VALUES))
    conf_text = kinds["ConfigMap"]["data"]["volcano-scheduler.conf"]
    parsed = yaml.safe_load(conf_text)
    assert "allocate" in parsed["actions"]
    assert parsed["tiers"]


def test_configmap_inlines_custom_conf_file(tmp_path):
    conf = tmp_path / "policy.conf"
    conf.write_text("actions: \"enqueue, allocate\"\ntiers: []\n")
    values = merge_values(
        DEFAULT_VALUES, {"basic": {"scheduler_config_file": str(conf)}})
    kinds = _by_kind(render(values))
    assert kinds["ConfigMap"]["data"]["volcano-scheduler.conf"] == conf.read_text()


def test_compute_plane_sidecar_wiring():
    kinds = _by_kind(render(DEFAULT_VALUES))
    spec = kinds["Deployment"]["spec"]["template"]["spec"]
    cp, sidecar = spec["containers"]
    socket = "/run/vtpu/compute-plane.sock"
    # control plane points at the socket; sidecar serves it; both mount
    # the shared emptyDir volume
    assert {"name": "VTPU_COMPUTE_PLANE", "value": socket} in cp["env"]
    assert sidecar["command"][:3] == ["vtpu-compute-plane", "--socket", socket]
    assert "--warmup" in sidecar["command"]
    assert sidecar["resources"]["limits"]["google.com/tpu"] == "8"
    mounts = {v["name"] for v in spec["volumes"]}
    assert "compute-plane-socket" in mounts
    for c in (cp, sidecar):
        assert any(m["name"] == "compute-plane-socket" for m in c["volumeMounts"])


def test_compute_plane_disabled():
    values = merge_values(DEFAULT_VALUES, {"compute_plane": {"enabled": False}})
    kinds = _by_kind(render(values))
    spec = kinds["Deployment"]["spec"]["template"]["spec"]
    assert [c["name"] for c in spec["containers"]] == ["control-plane"]
    assert "env" not in spec["containers"][0]
    assert all(v["name"] != "compute-plane-socket" for v in spec["volumes"])
    # in-process kernels still need the device: the TPU limit moves onto
    # the control-plane container instead of vanishing with the sidecar
    assert spec["containers"][0]["resources"]["limits"]["google.com/tpu"] == "8"


def test_null_scalar_keeps_default():
    values = load_values("scheduler:\n  port:\n  nodes: 4\n")
    assert values["scheduler"]["port"] == 8080
    assert values["scheduler"]["nodes"] == 4
    render(values)


def test_values_file_merge_and_image_pull_secret():
    values = load_values(yaml.safe_dump({
        "basic": {
            "release_name": "vt-prod",
            "namespace": "prod",
            "image_tag_version": "v1.2.3",
            "image_pull_secret": "regcred",
        },
    }))
    # untouched defaults survive the merge
    assert values["scheduler"]["port"] == 8080
    kinds = _by_kind(render(values))
    dep = kinds["Deployment"]
    assert dep["metadata"]["name"] == "vt-prod"
    spec = dep["spec"]["template"]["spec"]
    assert spec["containers"][0]["image"] == "volcano-tpu:v1.2.3"
    assert spec["imagePullSecrets"] == [{"name": "regcred"}]
    assert kinds["Service"]["metadata"]["namespace"] == "prod"


def test_set_overrides_with_coercion():
    values = DEFAULT_VALUES
    for assignment in ("scheduler.port=9090",
                      "prometheus.scrape=false",
                      "compute_plane.tpu_chips=4",
                      "basic.image_tag_version=nightly"):
        values = apply_set(values, assignment)
    assert values["scheduler"]["port"] == 9090
    assert values["prometheus"]["scrape"] is False
    kinds = _by_kind(render(values))
    dep = kinds["Deployment"]
    meta = dep["spec"]["template"]["metadata"]
    assert "annotations" not in meta
    spec = dep["spec"]["template"]["spec"]
    assert spec["containers"][0]["image"] == "volcano-tpu:nightly"
    assert spec["containers"][1]["resources"]["limits"]["google.com/tpu"] == "4"
    assert {"containerPort": 9090, "name": "scheduler"} in spec["containers"][0]["ports"]


def test_set_rejects_malformed():
    import pytest

    with pytest.raises(ValueError):
        apply_set(DEFAULT_VALUES, "no-equals-sign")
    with pytest.raises(ValueError):
        apply_set(DEFAULT_VALUES, "=value")
    # a path traversing through an existing scalar is a typo, caught at
    # parse time rather than as a render-time TypeError
    with pytest.raises(ValueError, match="is a value, not a section"):
        apply_set(DEFAULT_VALUES, "scheduler.port.http=9090")


def test_set_string_skips_coercion():
    values = apply_set(DEFAULT_VALUES, "basic.image_tag_version=20260730",
                       coerce=False)
    assert values["basic"]["image_tag_version"] == "20260730"
    # the CLI surface: --set-string renders the tag as a string
    from volcano_tpu.cmd.package import main

    import io
    import contextlib

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert main(["template", "--set-string",
                     "basic.image_tag_version=20260730"]) == 0
    docs = list(yaml.safe_load_all(buf.getvalue()))
    dep = next(d for d in docs if d["kind"] == "Deployment")
    image = dep["spec"]["template"]["spec"]["containers"][0]["image"]
    assert image == "volcano-tpu:20260730"


def test_deployment_recreate_strategy():
    kinds = _by_kind(render(DEFAULT_VALUES))
    assert kinds["Deployment"]["spec"]["strategy"] == {"type": "Recreate"}


def test_render_yaml_stream_parses():
    docs = list(yaml.safe_load_all(render_yaml(DEFAULT_VALUES)))
    assert [d["kind"] for d in docs] == [
        "Namespace", "ConfigMap", "Deployment", "Service"]


def test_empty_section_header_keeps_defaults():
    # "compute_plane:" with nothing under it parses as null; the merge
    # must keep the section's defaults, not crash render()
    values = load_values("compute_plane:\nbasic:\n  release_name: x\n")
    assert values["compute_plane"] == DEFAULT_VALUES["compute_plane"]
    assert values["basic"]["release_name"] == "x"
    render(values)


def test_static_manifest_command_parses():
    # the hand-written deploy/kubernetes manifest must stay parseable by
    # the real vtpu-local-up parser (a flag rename would otherwise ship
    # a CrashLooping pod while all renderer tests stay green)
    import os

    from volcano_tpu.cmd.local_up import build_parser

    path = os.path.join(os.path.dirname(__file__), "..",
                        "deploy", "kubernetes", "volcano-tpu.yaml")
    with open(path, "r", encoding="utf-8") as fh:
        docs = [d for d in yaml.safe_load_all(fh) if d]
    dep = next(d for d in docs if d["kind"] == "Deployment")
    cmd = dep["spec"]["template"]["spec"]["containers"][0]["command"]
    assert cmd[0] == "vtpu-local-up"
    args = build_parser().parse_args(cmd[1:])
    assert args.serve is True
    assert args.listen_host == "0.0.0.0"


def test_chart_values_file_matches_defaults():
    # deploy/chart/values.yaml documents the defaults; merging it over
    # DEFAULT_VALUES must be a no-op or the two sources have drifted
    import os

    path = os.path.join(os.path.dirname(__file__), "..",
                        "deploy", "chart", "values.yaml")
    with open(path, "r", encoding="utf-8") as fh:
        assert load_values(fh.read()) == DEFAULT_VALUES


def test_rendered_command_parses_and_serves():
    # the Deployment command must be accepted verbatim by the real
    # vtpu-local-up argument parser and carry serve mode + the mounted
    # conf + the same ports the probe/Service/annotations point at
    from volcano_tpu.cmd.local_up import build_parser

    kinds = _by_kind(render(DEFAULT_VALUES))
    container = kinds["Deployment"]["spec"]["template"]["spec"]["containers"][0]
    cmd = container["command"]
    assert cmd[0] == "vtpu-local-up"

    args = build_parser().parse_args(cmd[1:])
    assert args.serve is True
    assert args.listen_host == "0.0.0.0"
    assert args.scheduler_port == 8080
    assert args.scheduler_conf == "/etc/volcano-tpu/volcano-scheduler.conf"
    # the conf path the command reads is inside the ConfigMap mount
    mount = next(m for m in container["volumeMounts"]
                 if m["name"] == "scheduler-config")
    assert args.scheduler_conf.startswith(mount["mountPath"] + "/")
    # probe port agrees with the port the process actually binds
    probe = container["livenessProbe"]["httpGet"]["port"]
    assert probe == args.scheduler_port


def test_local_up_fixed_ports_and_conf(tmp_path):
    # local_up() must honor fixed ports (probes depend on them) and
    # thread the conf path into the scheduler's hot-reload loop
    import socket
    import urllib.request

    from volcano_tpu.cmd.local_up import local_up

    # a genuinely fixed port (probes depend on the kwarg being honored;
    # port 0 would pass even if the kwarg were dropped)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        fixed_port = s.getsockname()[1]

    conf = tmp_path / "policy.yaml"
    conf.write_text("actions: \"enqueue, allocate\"\ntiers: []\n")
    api, daemons = local_up(
        nodes=1, scheduler_conf=str(conf),
        admission_port=0, controllers_port=0, scheduler_port=fixed_port,
    )
    try:
        admission, controllers, scheduler = daemons
        assert scheduler.scheduler.scheduler_conf_path == str(conf)
        assert scheduler.serving.port == fixed_port
        # every daemon's /healthz answers on its reported port
        for d in daemons:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{d.serving.port}/healthz", timeout=5) as r:
                assert r.status == 200
    finally:
        for d in daemons:
            d.stop()


def test_cli_render_and_template(tmp_path, capsys):
    from volcano_tpu.cmd.package import main

    out = tmp_path / "out"
    rc = main(["render", "-o", str(out), "--set", "basic.namespace=ns2"])
    assert rc == 0
    files = sorted(p.name for p in out.iterdir())
    assert files == ["00-namespace.yaml", "10-scheduler-configmap.yaml",
                     "20-deployment.yaml", "30-service.yaml"]
    dep = yaml.safe_load((out / "20-deployment.yaml").read_text())
    assert dep["metadata"]["namespace"] == "ns2"
    capsys.readouterr()

    rc = main(["template"])
    assert rc == 0
    docs = list(yaml.safe_load_all(capsys.readouterr().out))
    assert len(docs) == 4

    rc = main(["values"])
    assert rc == 0
    assert yaml.safe_load(capsys.readouterr().out) == DEFAULT_VALUES
