"""End-to-end: the full control loop in one process.

The reference proves integration on a kind cluster (hack/run-e2e-kind.sh +
test/e2e suites); the standalone equivalent wires every component through
the in-process API server: admission webhooks → job controller → podgroup/
queue controllers → scheduler (cache + session + actions) → binder → fake
kubelet → pod phases → lifecycle policies → job completion.
"""

from __future__ import annotations

import pytest

from volcano_tpu.admission import register_webhooks
from volcano_tpu.apis import batch, core, scheduling
from volcano_tpu.cli import main as vtctl
from volcano_tpu.client import ADDED, APIServer, KubeClient, MODIFIED, SchedulerClient, VolcanoClient
from volcano_tpu.cache import SchedulerCache
from volcano_tpu.controllers import (
    GarbageCollector,
    JobController,
    PodGroupController,
    QueueController,
)
from volcano_tpu.scheduler.scheduler import Scheduler

from tests.builders import build_node


class FakeKubelet:
    """Runs bound pods: Pending+node → Running.  Completion is driven by
    tests via succeed()/fail() (the e2e suites' pod-kill analogue)."""

    def __init__(self, api: APIServer):
        self.api = api
        self.kube = KubeClient(api)
        self._pending = []
        api.watch("Pod", self._on_pod)

    def _on_pod(self, event, old, new) -> None:
        if event in (ADDED, MODIFIED) and new is not None:
            if new.spec.node_name and new.status.phase == "Pending":
                self._pending.append((new.metadata.namespace, new.metadata.name))

    def drain(self) -> None:
        while self._pending:
            namespace, name = self._pending.pop()
            pod = self.kube.get_pod(namespace, name)
            if pod is not None and pod.spec.node_name and pod.status.phase == "Pending":
                pod.status.phase = "Running"
                self.kube.update_pod_status(pod)

    def finish(self, namespace: str, name: str, phase: str = "Succeeded", exit_code=None) -> None:
        pod = self.kube.get_pod(namespace, name)
        pod.status.phase = phase
        pod.status.exit_code = exit_code
        self.kube.update_pod_status(pod)


class Cluster:
    """All binaries in one harness."""

    def __init__(self, nodes=3, node_cpu="8", node_mem="16Gi", gate_pods=False):
        self.api = APIServer()
        register_webhooks(self.api, gate_pods=gate_pods)
        self.kube = KubeClient(self.api)
        self.vc = VolcanoClient(self.api)

        for i in range(nodes):
            self.kube.create_node(build_node(f"node-{i}", {"cpu": node_cpu, "memory": node_mem}))
        self.vc.create_queue(
            scheduling.Queue(metadata=core.ObjectMeta(name="default", namespace=""))
        )

        self.job_controller = JobController(self.api)
        self.queue_controller = QueueController(self.api)
        self.podgroup_controller = PodGroupController(self.api)
        self.gc = GarbageCollector(self.api)
        self.kubelet = FakeKubelet(self.api)

        client = SchedulerClient(self.api)
        self.cache = SchedulerCache(client=client, scheduler_name="volcano-tpu")
        self.scheduler = Scheduler(self.cache)
        self.cache.run()

    def tick(self, rounds: int = 3) -> None:
        """One converging settle: controllers → scheduler → kubelet."""
        for _ in range(rounds):
            self.job_controller.drain()
            self.podgroup_controller.drain()
            self.scheduler.run_once()
            self.kubelet.drain()
            self.queue_controller.drain()
        self.job_controller.drain()


def submit(cluster: Cluster, name="e2e-job", replicas=3, min_available=3, **spec_kw):
    task = batch.TaskSpec(
        name="worker",
        replicas=replicas,
        template=core.PodTemplateSpec(
            spec=core.PodSpec(
                containers=[core.Container(resources={"requests": {"cpu": "1", "memory": "1Gi"}})]
            )
        ),
    )
    job = batch.Job(
        metadata=core.ObjectMeta(name=name, namespace="default"),
        spec=batch.JobSpec(min_available=min_available, tasks=[task], **spec_kw),
    )
    return cluster.vc.create_job(job)


class TestE2EJobLifecycle:
    def test_job_schedules_and_runs(self):
        """test/e2e job_scheduling.go 'schedule job when resources are enough'."""
        cluster = Cluster()
        submit(cluster)
        cluster.tick()

        job = cluster.vc.get_job("default", "e2e-job")
        assert job.status.state.phase == batch.JOB_RUNNING
        assert job.status.running == 3
        pods = cluster.kube.list_pods("default")
        assert all(p.spec.node_name for p in pods)
        pg = cluster.vc.get_pod_group("default", "e2e-job")
        assert pg.status.phase == scheduling.POD_GROUP_RUNNING

    def test_gang_job_stays_pending_when_oversized(self):
        """job_scheduling.go gang cases: nothing binds when the gang
        can't fit."""
        cluster = Cluster(nodes=1, node_cpu="2")
        submit(cluster, replicas=4, min_available=4)
        cluster.tick()

        job = cluster.vc.get_job("default", "e2e-job")
        assert job.status.state.phase == batch.JOB_PENDING
        pods = cluster.kube.list_pods("default")
        assert all(not p.spec.node_name for p in pods)
        pg = cluster.vc.get_pod_group("default", "e2e-job")
        conds = [c for c in pg.status.conditions if c.type == "Unschedulable"]
        assert conds and "gang" in conds[0].message

    def test_job_completes_and_gc_reaps(self):
        """job_lifecycle.go completion + TTL."""
        cluster = Cluster()
        submit(cluster, name="done-job", ttl_seconds_after_finished=0)
        cluster.tick()
        for i in range(3):
            cluster.kubelet.finish("default", f"done-job-worker-{i}")
        cluster.tick()
        job = cluster.vc.get_job("default", "done-job")
        assert job.status.state.phase == batch.JOB_COMPLETED
        assert cluster.gc.process_expired() == 1
        assert cluster.vc.get_job("default", "done-job") is None

    def test_pod_failure_restart_policy(self):
        """job_error_handling.go 'restart job when pod is failed'."""
        cluster = Cluster()
        submit(
            cluster,
            name="flaky",
            policies=[
                batch.LifecyclePolicy(event=batch.POD_FAILED_EVENT, action=batch.RESTART_JOB_ACTION)
            ],
        )
        cluster.tick()
        cluster.kubelet.finish("default", "flaky-worker-1", phase="Failed", exit_code=137)
        cluster.tick(rounds=4)
        job = cluster.vc.get_job("default", "flaky")
        assert job.status.retry_count >= 1
        # job recovered: pods recreated and running again
        assert job.status.state.phase == batch.JOB_RUNNING

    def test_suspend_resume_via_cli(self):
        """command.go suspend/resume through vcctl-equivalent."""
        cluster = Cluster()
        submit(cluster, name="pausable")
        cluster.tick()
        assert vtctl(["job", "suspend", "-N", "pausable", "-n", "default"], cluster.api) == 0
        cluster.tick()
        job = cluster.vc.get_job("default", "pausable")
        assert job.status.state.phase in (batch.JOB_ABORTING, batch.JOB_ABORTED)

        assert vtctl(["job", "resume", "-N", "pausable", "-n", "default"], cluster.api) == 0
        cluster.tick(rounds=4)
        job = cluster.vc.get_job("default", "pausable")
        assert job.status.state.phase == batch.JOB_RUNNING

    def test_fair_share_between_queues(self):
        """job_scheduling.go proportion cases: two queues with 1:1 weight
        split a saturated cluster evenly."""
        cluster = Cluster(nodes=2, node_cpu="4", node_mem="16Gi")
        for qname in ("qa", "qb"):
            cluster.vc.create_queue(
                scheduling.Queue(metadata=core.ObjectMeta(name=qname, namespace=""))
            )
        # 8 cpu total; each queue requests 8 → deserved 4 each.
        submit(cluster, name="job-a", replicas=8, min_available=1, queue="qa")
        submit(cluster, name="job-b", replicas=8, min_available=1, queue="qb")
        cluster.tick(rounds=5)
        ja = cluster.vc.get_job("default", "job-a")
        jb = cluster.vc.get_job("default", "job-b")
        assert ja.status.running == 4
        assert jb.status.running == 4

    def test_delay_pod_creation_gate(self):
        """admission.go + delay-pod-creation design: with the pod gate on,
        pods stay uncreated until enqueue moves the PodGroup to Inqueue
        (driven by minResources alone), then the job runs normally."""
        cluster = Cluster(gate_pods=True)
        submit(cluster, name="gated")
        cluster.job_controller.drain()
        assert cluster.kube.list_pods("default") == []  # gated while PG Pending
        cluster.tick(rounds=4)
        job = cluster.vc.get_job("default", "gated")
        assert job.status.state.phase == batch.JOB_RUNNING
        assert job.status.running == 3

    def test_normal_pod_gets_podgroup(self):
        """pg_controller.go: a plain pod using our scheduler gets an
        auto-created singleton PodGroup and schedules."""
        cluster = Cluster()
        pod = core.Pod(
            metadata=core.ObjectMeta(name="loner", namespace="default", uid="uid-loner"),
            spec=core.PodSpec(
                scheduler_name="volcano-tpu",
                containers=[core.Container(resources={"requests": {"cpu": "1"}})],
            ),
        )
        cluster.kube.create_pod(pod)
        cluster.tick()
        pg = cluster.vc.get_pod_group("default", "podgroup-uid-loner")
        assert pg is not None and pg.spec.min_member == 1
        stored = cluster.kube.get_pod("default", "loner")
        assert stored.spec.node_name  # scheduled as a gang of one


PREEMPT_CONF = """
actions: "enqueue, allocate, preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
configurations:
- name: enqueue
  arguments:
    overcommit-factor: "2.0"
"""


class TestE2EPreemption:
    def test_high_priority_job_preempts_low(self, tmp_path):
        """e2e preemption: a saturated node, then a higher-priority job in
        the same queue — preempt evicts a low-priority victim, the job
        controller recreates it pending, and the preemptor runs."""
        conf = tmp_path / "scheduler.yaml"
        conf.write_text(PREEMPT_CONF)

        cluster = Cluster(nodes=1, node_cpu="2", node_mem="4Gi")
        cluster.scheduler.scheduler_conf_path = str(conf)
        cluster.kube.create_priority_class(
            core.PriorityClass(metadata=core.ObjectMeta(name="high"), value=1000)
        )

        submit(cluster, name="low-job", replicas=2, min_available=1)
        cluster.tick()
        assert cluster.vc.get_job("default", "low-job").status.running == 2

        submit(
            cluster,
            name="high-job",
            replicas=1,
            min_available=1,
            priority_class_name="high",
        )
        cluster.tick(rounds=6)

        high = cluster.vc.get_job("default", "high-job")
        low = cluster.vc.get_job("default", "low-job")
        assert high.status.running == 1
        # One victim was evicted; the controller recreated it, and it now
        # waits pending (the node is full again).
        assert low.status.running == 1
        assert low.status.pending == 1
