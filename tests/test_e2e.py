"""End-to-end: the full control loop in one process.

The reference proves integration on a kind cluster (hack/run-e2e-kind.sh +
test/e2e suites); the standalone equivalent wires every component through
the in-process API server: admission webhooks → job controller → podgroup/
queue controllers → scheduler (cache + session + actions) → binder → fake
kubelet → pod phases → lifecycle policies → job completion.
"""

from __future__ import annotations


from volcano_tpu.admission import register_webhooks
from volcano_tpu.apis import batch, core, scheduling
from volcano_tpu.cache import SchedulerCache
from volcano_tpu.cli import main as vtctl
from volcano_tpu.client import ADDED, APIServer, KubeClient, MODIFIED, SchedulerClient, VolcanoClient
from volcano_tpu.controllers import (
    GarbageCollector,
    JobController,
    PodGroupController,
    QueueController,
)
from volcano_tpu.scheduler.scheduler import Scheduler

from tests.builders import build_node


class FakeKubelet:
    """Runs bound pods: Pending+node → Running.  Completion is driven by
    tests via succeed()/fail() (the e2e suites' pod-kill analogue)."""

    def __init__(self, api: APIServer):
        self.api = api
        self.kube = KubeClient(api)
        self._pending = []
        api.watch("Pod", self._on_pod)

    def _on_pod(self, event, old, new) -> None:
        if event in (ADDED, MODIFIED) and new is not None:
            if new.spec.node_name and new.status.phase == "Pending":
                self._pending.append((new.metadata.namespace, new.metadata.name))

    def drain(self) -> None:
        while self._pending:
            namespace, name = self._pending.pop()
            pod = self.kube.get_pod(namespace, name)
            if pod is not None and pod.spec.node_name and pod.status.phase == "Pending":
                pod.status.phase = "Running"
                self.kube.update_pod_status(pod)

    def finish(self, namespace: str, name: str, phase: str = "Succeeded", exit_code=None) -> None:
        pod = self.kube.get_pod(namespace, name)
        pod.status.phase = phase
        pod.status.exit_code = exit_code
        self.kube.update_pod_status(pod)


class Cluster:
    """All binaries in one harness."""

    def __init__(self, nodes=3, node_cpu="8", node_mem="16Gi", gate_pods=False):
        self.api = APIServer()
        register_webhooks(self.api, gate_pods=gate_pods)
        self.kube = KubeClient(self.api)
        self.vc = VolcanoClient(self.api)

        for i in range(nodes):
            self.kube.create_node(build_node(f"node-{i}", {"cpu": node_cpu, "memory": node_mem}))
        self.vc.create_queue(
            scheduling.Queue(metadata=core.ObjectMeta(name="default", namespace=""))
        )

        self.job_controller = JobController(self.api)
        self.queue_controller = QueueController(self.api)
        self.podgroup_controller = PodGroupController(self.api)
        self.gc = GarbageCollector(self.api)
        self.kubelet = FakeKubelet(self.api)

        client = SchedulerClient(self.api)
        self.cache = SchedulerCache(client=client, scheduler_name="volcano-tpu")
        self.scheduler = Scheduler(self.cache)
        self.cache.run()

    def tick(self, rounds: int = 3) -> None:
        """One converging settle: controllers → scheduler → kubelet."""
        for _ in range(rounds):
            self.job_controller.drain()
            self.podgroup_controller.drain()
            self.scheduler.run_once()
            self.kubelet.drain()
            self.queue_controller.drain()
        self.job_controller.drain()


def submit(cluster: Cluster, name="e2e-job", replicas=3, min_available=3, **spec_kw):
    task = batch.TaskSpec(
        name="worker",
        replicas=replicas,
        template=core.PodTemplateSpec(
            spec=core.PodSpec(
                containers=[core.Container(
                    image="registry.k8s.io/pause:3.9",
                    resources={"requests": {"cpu": "1", "memory": "1Gi"}})]
            )
        ),
    )
    job = batch.Job(
        metadata=core.ObjectMeta(name=name, namespace="default"),
        spec=batch.JobSpec(min_available=min_available, tasks=[task], **spec_kw),
    )
    return cluster.vc.create_job(job)


class TestE2EJobLifecycle:
    def test_job_schedules_and_runs(self):
        """test/e2e job_scheduling.go 'schedule job when resources are enough'."""
        cluster = Cluster()
        submit(cluster)
        cluster.tick()

        job = cluster.vc.get_job("default", "e2e-job")
        assert job.status.state.phase == batch.JOB_RUNNING
        assert job.status.running == 3
        pods = cluster.kube.list_pods("default")
        assert all(p.spec.node_name for p in pods)
        pg = cluster.vc.get_pod_group("default", "e2e-job")
        assert pg.status.phase == scheduling.POD_GROUP_RUNNING

    def test_gang_job_stays_pending_when_oversized(self):
        """job_scheduling.go gang cases: nothing binds when the gang
        can't fit."""
        cluster = Cluster(nodes=1, node_cpu="2")
        submit(cluster, replicas=4, min_available=4)
        cluster.tick()

        job = cluster.vc.get_job("default", "e2e-job")
        assert job.status.state.phase == batch.JOB_PENDING
        pods = cluster.kube.list_pods("default")
        assert all(not p.spec.node_name for p in pods)
        pg = cluster.vc.get_pod_group("default", "e2e-job")
        conds = [c for c in pg.status.conditions if c.type == "Unschedulable"]
        assert conds and "gang" in conds[0].message

    def test_job_completes_and_gc_reaps(self):
        """job_lifecycle.go completion + TTL."""
        cluster = Cluster()
        submit(cluster, name="done-job", ttl_seconds_after_finished=0)
        cluster.tick()
        for i in range(3):
            cluster.kubelet.finish("default", f"done-job-worker-{i}")
        cluster.tick()
        job = cluster.vc.get_job("default", "done-job")
        assert job.status.state.phase == batch.JOB_COMPLETED
        assert cluster.gc.process_expired() == 1
        assert cluster.vc.get_job("default", "done-job") is None

    def test_pod_failure_restart_policy(self):
        """job_error_handling.go 'restart job when pod is failed'."""
        cluster = Cluster()
        submit(
            cluster,
            name="flaky",
            policies=[
                batch.LifecyclePolicy(event=batch.POD_FAILED_EVENT, action=batch.RESTART_JOB_ACTION)
            ],
        )
        cluster.tick()
        cluster.kubelet.finish("default", "flaky-worker-1", phase="Failed", exit_code=137)
        cluster.tick(rounds=4)
        job = cluster.vc.get_job("default", "flaky")
        assert job.status.retry_count >= 1
        # job recovered: pods recreated and running again
        assert job.status.state.phase == batch.JOB_RUNNING

    def test_suspend_resume_via_cli(self):
        """command.go suspend/resume through vcctl-equivalent."""
        cluster = Cluster()
        submit(cluster, name="pausable")
        cluster.tick()
        assert vtctl(["job", "suspend", "-N", "pausable", "-n", "default"], cluster.api) == 0
        cluster.tick()
        job = cluster.vc.get_job("default", "pausable")
        assert job.status.state.phase in (batch.JOB_ABORTING, batch.JOB_ABORTED)

        assert vtctl(["job", "resume", "-N", "pausable", "-n", "default"], cluster.api) == 0
        cluster.tick(rounds=4)
        job = cluster.vc.get_job("default", "pausable")
        assert job.status.state.phase == batch.JOB_RUNNING

    def test_fair_share_between_queues(self):
        """job_scheduling.go proportion cases: two queues with 1:1 weight
        split a saturated cluster evenly."""
        cluster = Cluster(nodes=2, node_cpu="4", node_mem="16Gi")
        for qname in ("qa", "qb"):
            cluster.vc.create_queue(
                scheduling.Queue(metadata=core.ObjectMeta(name=qname, namespace=""))
            )
        # 8 cpu total; each queue requests 8 → deserved 4 each.
        submit(cluster, name="job-a", replicas=8, min_available=1, queue="qa")
        submit(cluster, name="job-b", replicas=8, min_available=1, queue="qb")
        cluster.tick(rounds=5)
        ja = cluster.vc.get_job("default", "job-a")
        jb = cluster.vc.get_job("default", "job-b")
        assert ja.status.running == 4
        assert jb.status.running == 4

    def test_delay_pod_creation_gate(self):
        """admission.go + delay-pod-creation design: with the pod gate on,
        pods stay uncreated until enqueue moves the PodGroup to Inqueue
        (driven by minResources alone), then the job runs normally."""
        cluster = Cluster(gate_pods=True)
        submit(cluster, name="gated")
        cluster.job_controller.drain()
        assert cluster.kube.list_pods("default") == []  # gated while PG Pending
        cluster.tick(rounds=4)
        job = cluster.vc.get_job("default", "gated")
        assert job.status.state.phase == batch.JOB_RUNNING
        assert job.status.running == 3

    def test_normal_pod_gets_podgroup(self):
        """pg_controller.go: a plain pod using our scheduler gets an
        auto-created singleton PodGroup and schedules."""
        cluster = Cluster()
        pod = core.Pod(
            metadata=core.ObjectMeta(name="loner", namespace="default", uid="uid-loner"),
            spec=core.PodSpec(
                scheduler_name="volcano-tpu",
                containers=[core.Container(resources={"requests": {"cpu": "1"}})],
            ),
        )
        cluster.kube.create_pod(pod)
        cluster.tick()
        pg = cluster.vc.get_pod_group("default", "podgroup-uid-loner")
        assert pg is not None and pg.spec.min_member == 1
        stored = cluster.kube.get_pod("default", "loner")
        assert stored.spec.node_name  # scheduled as a gang of one


PREEMPT_CONF = """
actions: "enqueue, allocate, preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
configurations:
- name: enqueue
  arguments:
    overcommit-factor: "2.0"
"""


class TestE2EPreemption:
    def test_high_priority_job_preempts_low(self, tmp_path):
        """e2e preemption: a saturated node, then a higher-priority job in
        the same queue — preempt evicts a low-priority victim, the job
        controller recreates it pending, and the preemptor runs."""
        conf = tmp_path / "scheduler.yaml"
        conf.write_text(PREEMPT_CONF)

        cluster = Cluster(nodes=1, node_cpu="2", node_mem="4Gi")
        cluster.scheduler.scheduler_conf_path = str(conf)
        cluster.kube.create_priority_class(
            core.PriorityClass(metadata=core.ObjectMeta(name="high"), value=1000)
        )

        submit(cluster, name="low-job", replicas=2, min_available=1)
        cluster.tick()
        assert cluster.vc.get_job("default", "low-job").status.running == 2

        submit(
            cluster,
            name="high-job",
            replicas=1,
            min_available=1,
            priority_class_name="high",
        )
        cluster.tick(rounds=6)

        high = cluster.vc.get_job("default", "high-job")
        low = cluster.vc.get_job("default", "low-job")
        assert high.status.running == 1
        # One victim was evicted; the controller recreated it, and it now
        # waits pending (the node is full again).
        assert low.status.running == 1
        assert low.status.pending == 1


class TestE2EEventAuditTrail:
    """VERDICT r2 #5: Events recorded through the bus on bind/evict/
    unschedulable (cache.go:600-610, 832-867) and surfaced in
    `vtctl job view`."""

    def test_bind_records_scheduled_events(self):
        cluster = Cluster()
        submit(cluster)
        cluster.tick()
        events = cluster.api.list("Event", "default")
        scheduled = [e for e in events if e.reason == "Scheduled"]
        assert len(scheduled) == 3
        assert all("Successfully assigned" in e.message for e in scheduled)
        assert {e.involved_object["name"] for e in scheduled} == {
            f"e2e-job-worker-{i}" for i in range(3)
        }

    def test_gang_discard_records_unschedulable_events(self):
        cluster = Cluster(nodes=1, node_cpu="2")
        submit(cluster, replicas=4, min_available=4)
        cluster.tick()
        events = cluster.api.list("Event", "default")
        unsched = [e for e in events if e.reason == "Unschedulable"]
        assert unsched, "gang discard must leave an Unschedulable audit trail"
        assert all(e.type == "Warning" for e in unsched)

    def test_preemption_records_evict_events(self, tmp_path):
        conf = tmp_path / "scheduler.yaml"
        conf.write_text(PREEMPT_CONF)
        cluster = Cluster(nodes=1, node_cpu="2", node_mem="4Gi")
        cluster.scheduler.scheduler_conf_path = str(conf)
        cluster.kube.create_priority_class(
            core.PriorityClass(metadata=core.ObjectMeta(name="high"), value=1000)
        )
        submit(cluster, name="low-job", replicas=2, min_available=1)
        cluster.tick()
        submit(cluster, name="high-job", replicas=1, min_available=1,
               priority_class_name="high")
        cluster.tick(rounds=6)

        events = cluster.api.list("Event", "default")
        evicts = [e for e in events if e.reason == "Evict"]
        assert evicts, "preemption must leave an Evict audit trail"
        assert any("preempt" in e.message for e in evicts)
        assert all(e.involved_object["name"].startswith("low-job-") for e in evicts)

    def test_vtctl_job_view_shows_events(self):
        import io

        cluster = Cluster()
        submit(cluster)
        cluster.tick()
        out = io.StringIO()
        rc = vtctl(["job", "view", "-N", "e2e-job", "-n", "default"],
                   api=cluster.api, out=out)
        assert rc == 0
        text = out.getvalue()
        assert "Events:" in text
        assert "Scheduled" in text and "Successfully assigned" in text


class TestE2EVolumeBinding:
    """VERDICT r2 #7: real allocate/bind volumes against PVC objects on
    the bus, gating bind (cache.go:243-258, 557-615)."""

    def test_pod_waits_on_unbound_pvc_then_binds(self):
        """A job whose PVC is Pending with no storage class (static
        binding, nothing to bind to) must NOT bind; once an admin binds
        the PVC, the job schedules."""
        cluster = Cluster()
        cluster.kube.create_pvc(
            core.PersistentVolumeClaim(
                metadata=core.ObjectMeta(name="data", namespace="default"),
                spec={},  # no storageClassName → immediate/static binding
                status={"phase": "Pending"},
            )
        )
        submit(
            cluster,
            name="vol-job",
            volumes=[batch.VolumeSpec(mount_path="/data", volume_claim_name="data")],
        )
        cluster.tick()
        pods = cluster.kube.list_pods("default")
        assert pods and all(not p.spec.node_name for p in pods), (
            "pods must wait on the unbound PVC"
        )
        events = cluster.api.list("Event", "default")
        assert any(
            "PersistentVolumeClaims" in e.message for e in events
        ), "unschedulable reason must mention the unbound PVC"

        # admin binds the PVC (static PV provisioned out of band)
        pvc = cluster.kube.get_pvc("default", "data")
        pvc.status["phase"] = "Bound"
        cluster.kube.update_pvc(pvc)
        cluster.tick()
        pods = cluster.kube.list_pods("default")
        assert all(p.spec.node_name for p in pods)

    def test_dynamic_provisioning_binds_and_stamps_pvc(self):
        """A PVC with a storage class is provisionable: the scheduler
        binds the pods and bind_volumes stamps the PVC Bound with the
        selected node."""
        cluster = Cluster()
        cluster.kube.create_pvc(
            core.PersistentVolumeClaim(
                metadata=core.ObjectMeta(name="dyn", namespace="default"),
                spec={"storageClassName": "standard"},
                status={"phase": "Pending"},
            )
        )
        submit(
            cluster,
            name="dyn-job",
            volumes=[batch.VolumeSpec(mount_path="/data", volume_claim_name="dyn")],
        )
        cluster.tick()
        pods = cluster.kube.list_pods("default")
        assert all(p.spec.node_name for p in pods)
        pvc = cluster.kube.get_pvc("default", "dyn")
        assert pvc.status["phase"] == "Bound"
        assert pvc.spec["volumeName"] == "pv-dyn"
        assert pvc.metadata.annotations["volume.kubernetes.io/selected-node"]

    def test_missing_pvc_gates_at_controller(self):
        """A job naming a PVC that doesn't exist is held by the job
        controller itself (createJobIOIfNotExist validation) — no pods
        are created until the claim appears."""
        cluster = Cluster()
        submit(
            cluster,
            name="miss-job",
            volumes=[batch.VolumeSpec(mount_path="/d", volume_claim_name="nope")],
        )
        cluster.tick()
        assert not cluster.kube.list_pods("default")

        # scheduler-level gate for an already-created pod whose PVC
        # vanishes: create the claim, let pods appear, then delete it
        cluster.kube.create_pvc(
            core.PersistentVolumeClaim(
                metadata=core.ObjectMeta(name="nope", namespace="default"),
                spec={"storageClassName": "standard"},
                status={"phase": "Pending"},
            )
        )
        # re-trigger the sync (the reference requeues with backoff; here
        # a spec touch raises OutOfSync deterministically)
        job = cluster.vc.get_job("default", "miss-job")
        job.spec.max_retry = (job.spec.max_retry or 3) + 1
        cluster.vc.update_job(job)
        cluster.job_controller.drain()
        assert cluster.kube.list_pods("default"), "pods should exist now"
        cluster.api.delete("PersistentVolumeClaim", "default", "nope")
        cluster.tick()
        pods = cluster.kube.list_pods("default")
        assert pods and all(not p.spec.node_name for p in pods), (
            "pods referencing a vanished PVC must not bind"
        )


class TestE2EEventAggregation:
    def test_repeated_unschedulable_stays_bounded(self):
        """Cycling a stuck job must not mint new Event objects per cycle
        (the job updater's status-diff gate plus the recorder's
        correlator keep the store bounded)."""
        cluster = Cluster(nodes=1, node_cpu="2")
        submit(cluster, replicas=4, min_available=4)
        cluster.tick(rounds=8)
        events = [
            e for e in cluster.api.list("Event", "default")
            if e.reason == "Unschedulable"
        ]
        names = [e.involved_object["name"] for e in events]
        assert names and len(names) == len(set(names)), "one Event object per pod"

    def test_recorder_aggregates_repeats(self):
        """k8s correlator behavior: the same (object, reason, message)
        bumps count instead of creating a new Event."""
        cluster = Cluster()
        client = SchedulerClient(cluster.api)
        for _ in range(5):
            client.record_event(
                "default", {"kind": "Pod", "name": "p1"}, "Warning",
                "Unschedulable", "0/1 nodes available",
            )
        events = cluster.api.list("Event", "default")
        assert len(events) == 1
        assert events[0].count == 5


class TestE2EErrorHandlingMatrix:
    """job_error_handling.go restart/abort/terminate/complete/exit-code
    policy matrix (VERDICT r2 #8) — each case drives the full loop."""

    def _run_with_policy(self, policies, fail_pod=None,
                         phase="Failed", exit_code=None, name="mx"):
        cluster = Cluster()
        submit(cluster, name=name, policies=policies)
        cluster.tick()
        assert cluster.vc.get_job("default", name).status.running == 3
        cluster.kubelet.finish("default", fail_pod or f"{name}-worker-1",
                               phase=phase, exit_code=exit_code)
        cluster.tick(rounds=5)
        return cluster, cluster.vc.get_job("default", name)

    def test_abort_job_on_pod_failed(self):
        cluster, job = self._run_with_policy(
            [batch.LifecyclePolicy(event=batch.POD_FAILED_EVENT,
                                   action=batch.ABORT_JOB_ACTION)]
        )
        assert job.status.state.phase == batch.JOB_ABORTED
        # aborted (PodRetainPhaseSoft): running pods retained, none bound anew
        assert job.status.running == 0

    def test_terminate_job_on_pod_failed(self):
        cluster, job = self._run_with_policy(
            [batch.LifecyclePolicy(event=batch.POD_FAILED_EVENT,
                                   action=batch.TERMINATE_JOB_ACTION)]
        )
        assert job.status.state.phase == batch.JOB_TERMINATED

    def test_restart_job_on_pod_evicted(self):
        cluster = Cluster()
        submit(cluster, name="evct", policies=[
            batch.LifecyclePolicy(event=batch.POD_EVICTED_EVENT,
                                  action=batch.RESTART_JOB_ACTION)
        ])
        cluster.tick()
        # evict = delete a running pod out from under the job
        cluster.kube.delete_pod("default", "evct-worker-0")
        cluster.tick(rounds=5)
        job = cluster.vc.get_job("default", "evct")
        assert job.status.retry_count >= 1
        assert job.status.state.phase == batch.JOB_RUNNING

    def test_complete_job_on_task_completed(self):
        cluster = Cluster()
        submit(cluster, name="cmp", min_available=1, policies=[
            batch.LifecyclePolicy(event=batch.TASK_COMPLETED_EVENT,
                                  action=batch.COMPLETE_JOB_ACTION)
        ])
        cluster.tick()
        for i in range(3):
            cluster.kubelet.finish("default", f"cmp-worker-{i}")
        cluster.tick(rounds=5)
        job = cluster.vc.get_job("default", "cmp")
        assert job.status.state.phase == batch.JOB_COMPLETED

    def test_exit_code_policy_matches_specific_code(self):
        cluster, job = self._run_with_policy(
            [batch.LifecyclePolicy(exit_code=3, action=batch.ABORT_JOB_ACTION)],
            exit_code=3,
        )
        assert job.status.state.phase == batch.JOB_ABORTED

    def test_exit_code_policy_ignores_other_codes(self):
        cluster, job = self._run_with_policy(
            [batch.LifecyclePolicy(exit_code=3, action=batch.ABORT_JOB_ACTION)],
            exit_code=137, name="mx2",
        )
        # 137 doesn't match the 3-policy → default handling (no abort)
        assert job.status.state.phase != batch.JOB_ABORTED

    def test_task_level_policy_overrides_job_level(self):
        """applyPolicies: task-level policy wins over job-level
        (job_controller_util.go:123-179)."""
        cluster = Cluster()
        task = batch.TaskSpec(
            name="worker",
            replicas=3,
            policies=[batch.LifecyclePolicy(event=batch.POD_FAILED_EVENT,
                                            action=batch.RESTART_JOB_ACTION)],
            template=core.PodTemplateSpec(
                spec=core.PodSpec(
                    containers=[core.Container(
                        image="registry.k8s.io/pause:3.9",
                        resources={"requests": {"cpu": "1", "memory": "1Gi"}})]
                )
            ),
        )
        job = batch.Job(
            metadata=core.ObjectMeta(name="ovr", namespace="default"),
            spec=batch.JobSpec(
                min_available=3,
                tasks=[task],
                policies=[batch.LifecyclePolicy(event=batch.POD_FAILED_EVENT,
                                                action=batch.ABORT_JOB_ACTION)],
            ),
        )
        cluster.vc.create_job(job)
        cluster.tick()
        cluster.kubelet.finish("default", "ovr-worker-1", phase="Failed",
                               exit_code=1)
        cluster.tick(rounds=5)
        got = cluster.vc.get_job("default", "ovr")
        # task policy (RestartJob) applied, not the job-level AbortJob
        assert got.status.state.phase == batch.JOB_RUNNING
        assert got.status.retry_count >= 1


class TestE2EDistributedWorkloads:
    """The reference's real-workload e2e suites (test/e2e/mpi.go,
    tensorflow.go): multi-task gang jobs with the ssh/svc/env plugin
    set, master/worker topology, stable FQDNs, full lifecycle."""

    def _submit_distributed(self, cluster, name, master_replicas=1,
                            worker_replicas=3, plugins=None):
        def task(task_name, replicas, cmd):
            return batch.TaskSpec(
                name=task_name,
                replicas=replicas,
                template=core.PodTemplateSpec(
                    spec=core.PodSpec(
                        containers=[core.Container(
                            name="main",
                            image="registry.k8s.io/pause:3.9",
                            command=cmd,
                            resources={"requests": {"cpu": "1", "memory": "1Gi"}},
                        )]
                    )
                ),
            )

        job = batch.Job(
            metadata=core.ObjectMeta(name=name, namespace="default"),
            spec=batch.JobSpec(
                min_available=master_replicas + worker_replicas,
                plugins=plugins or {"ssh": [], "svc": [], "env": []},
                tasks=[
                    task("mpimaster", master_replicas, ["mpiexec", "--hostfile",
                                                       "/etc/volcano/mpiworker.host"]),
                    task("mpiworker", worker_replicas, ["sshd", "-D"]),
                ],
            ),
        )
        return cluster.vc.create_job(job)

    def test_mpi_style_job_runs_with_stable_fqdns(self):
        """mpi.go: master + workers gang-scheduled, ssh keys shared,
        hostfile ConfigMap carries every worker's stable FQDN."""
        cluster = Cluster(nodes=4)
        self._submit_distributed(cluster, "lm-mpi-job")
        cluster.tick()

        job = cluster.vc.get_job("default", "lm-mpi-job")
        assert job.status.state.phase == batch.JOB_RUNNING
        assert job.status.running == 4

        # gang: ALL pods bound (no partial MPI ring)
        pods = cluster.kube.list_pods("default")
        assert len(pods) == 4 and all(p.spec.node_name for p in pods)

        # svc: headless service + hosts configmap with worker FQDNs
        svc = cluster.kube.get_service("default", "lm-mpi-job")
        assert svc is not None and svc.spec.cluster_ip == "None"
        cm = cluster.kube.get_config_map("default", "lm-mpi-job-svc")
        hosts = cm.data["VC_TASK_HOSTS"]
        for i in range(3):
            assert f"lm-mpi-job-mpiworker-{i}.lm-mpi-job" in hosts

        # ssh: one job-keyed RSA keypair mounted everywhere
        secret = cluster.kube.get_secret("default", "lm-mpi-job-ssh")
        assert secret is not None and "id_rsa" in secret.data
        for p in pods:
            mounts = [m.mount_path for m in p.spec.containers[0].volume_mounts]
            assert "/root/.ssh" in mounts and "/etc/volcano" in mounts
            assert p.spec.subdomain == "lm-mpi-job"

        # env: worker indices stable
        for i in range(3):
            pod = cluster.kube.get_pod("default", f"lm-mpi-job-mpiworker-{i}")
            envs = {e.name: e.value for e in pod.spec.containers[0].env}
            assert envs["VC_TASK_INDEX"] == str(i)

    def test_tensorflow_style_job_completes_on_chief(self):
        """tensorflow.go dist-mnist shape: ps + worker tasks; job
        completes when all finish."""
        cluster = Cluster(nodes=4)
        job = batch.Job(
            metadata=core.ObjectMeta(name="tf-dist", namespace="default"),
            spec=batch.JobSpec(
                min_available=4,
                plugins={"svc": [], "env": []},
                policies=[batch.LifecyclePolicy(
                    event=batch.TASK_COMPLETED_EVENT,
                    action=batch.COMPLETE_JOB_ACTION)],
                tasks=[
                    batch.TaskSpec(
                        name="ps", replicas=2,
                        template=core.PodTemplateSpec(spec=core.PodSpec(
                            containers=[core.Container(
                                image="registry.k8s.io/pause:3.9",
                                resources={"requests": {"cpu": "1", "memory": "1Gi"}})])),
                    ),
                    batch.TaskSpec(
                        name="worker", replicas=2,
                        template=core.PodTemplateSpec(spec=core.PodSpec(
                            containers=[core.Container(
                                image="registry.k8s.io/pause:3.9",
                                resources={"requests": {"cpu": "1", "memory": "1Gi"}})])),
                    ),
                ],
            ),
        )
        cluster.vc.create_job(job)
        cluster.tick()
        assert cluster.vc.get_job("default", "tf-dist").status.state.phase == batch.JOB_RUNNING

        # workers finish (dist-mnist completes) → TaskCompleted → CompleteJob
        for i in range(2):
            cluster.kubelet.finish("default", f"tf-dist-worker-{i}")
        cluster.tick(rounds=5)
        job = cluster.vc.get_job("default", "tf-dist")
        assert job.status.state.phase == batch.JOB_COMPLETED

    def test_gang_holds_back_partial_distributed_job(self):
        """mpi.go gang case: a distributed job larger than the cluster
        binds NOTHING (no partial ring)."""
        cluster = Cluster(nodes=1, node_cpu="2")
        self._submit_distributed(cluster, "big-mpi", worker_replicas=7)
        cluster.tick()
        pods = cluster.kube.list_pods("default")
        assert all(not p.spec.node_name for p in pods)
        assert cluster.vc.get_job("default", "big-mpi").status.state.phase == batch.JOB_PENDING


def test_scheduler_gc_quiesce_period():
    """--gc-quiesce-period N: every N cycles the loop thaws, collects,
    and freezes survivors; scheduling results are unaffected."""
    import gc

    cluster = Cluster()
    cluster.scheduler.gc_quiesce_period = 2
    submit(cluster)
    frozen_before = gc.get_freeze_count()
    try:
        cluster.tick(rounds=4)  # ≥2 quiesce points
        assert gc.get_freeze_count() > frozen_before
        pods = cluster.kube.list_pods("default")
        assert pods and all(p.spec.node_name for p in pods)
    finally:
        # leave no frozen state behind for other tests
        gc.unfreeze()


def test_job_delete_cascades_to_pods_and_podgroup():
    """Deleting a Job must take its Pods and PodGroup with it (the k8s
    owner-reference GC the reference relies on) and release the
    scheduler cache's node accounting — the soak leak: before the
    cascade, deleted jobs pinned their bound pods forever and the
    cluster filled up."""
    cluster = Cluster()
    submit(cluster, name="cascade", replicas=3, min_available=3)
    cluster.tick()
    pods = [p for p in cluster.kube.list_pods("default")
            if p.metadata.name.startswith("cascade-")]
    assert pods and all(p.spec.node_name for p in pods)
    held0 = sum(len(n.tasks) for n in cluster.cache.nodes.values())
    assert held0 == 3

    cluster.vc.delete_job("default", "cascade")
    cluster.tick()

    assert not [p for p in cluster.kube.list_pods("default")
                if p.metadata.name.startswith("cascade-")]
    assert all(pg.metadata.name != "cascade"
               for pg in cluster.api.list("PodGroup", "default"))
    assert sum(len(n.tasks) for n in cluster.cache.nodes.values()) == 0
    # and the freed capacity is actually reusable
    submit(cluster, name="cascade2", replicas=3, min_available=3)
    cluster.tick()
    pods2 = [p for p in cluster.kube.list_pods("default")
             if p.metadata.name.startswith("cascade2-")]
    assert pods2 and all(p.spec.node_name for p in pods2)
