"""Device-derived scheduling explainability (ISSUE 4).

The acceptance contract: for a snapshot where a task is unschedulable,
the device-derived ``FitErrors.error()`` message is byte-identical to
the host path's message on the same snapshot; the synthesized errors
feed the existing Unschedulable event + pod-condition writeback
unchanged; and the surfaces (``/explain``, ``vtctl describe``, metrics,
trace summaries, the bus correlation id) all render from them.
"""

from __future__ import annotations

import json
import urllib.request

import numpy as np
import pytest

from volcano_tpu.actions.allocate import AllocateAction
from volcano_tpu.actions.backfill import BackfillAction
from volcano_tpu.actions.jax_allocate import JaxAllocateAction
from volcano_tpu.api import FitError
from volcano_tpu.api import unschedule_info as reasons
from volcano_tpu.api.unschedule_info import (
    FitErrors,
    format_fit_errors,
    parse_fit_errors,
)
from volcano_tpu.apis import core, scheduling
from volcano_tpu.cache import SchedulerCache
from volcano_tpu.client import APIServer, SchedulerClient
from volcano_tpu.framework import close_session, open_session

from tests.builders import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)
from tests.scheduler_helpers import make_cache, run_actions, tiers

TIERS = tiers(
    ["priority", "gang", "conformance"],
    ["drf", "predicates", "proportion", "nodeorder", "binpack"],
)


# ---- unschedule_info unit surface ----


class TestFitErrorsFormat:
    def test_histogram_render_matches_per_node_render(self):
        per_node = FitErrors()
        per_node.set_node_error("n1", FitError("t", "n1", reasons.NODE_RESOURCE_FIT_FAILED))
        per_node.set_node_error("n2", FitError("t", "n2", reasons.NODE_TAINT_UNTOLERATED))
        per_node.set_node_error("n3", FitError("t", "n3", reasons.NODE_TAINT_UNTOLERATED))
        synthesized = FitErrors()
        synthesized.set_histogram(3, {
            reasons.NODE_RESOURCE_FIT_FAILED: 1,
            reasons.NODE_TAINT_UNTOLERATED: 2,
        })
        assert per_node.error() == synthesized.error()
        assert per_node.histogram() == synthesized.histogram()

    def test_parse_is_inverse_of_format(self):
        hist = {
            reasons.NODE_SELECTOR_MISMATCH: 4,
            reasons.NODE_POD_NUMBER_EXCEEDED: 2,
        }
        msg = format_fit_errors(6, hist)
        assert parse_fit_errors(msg) == (6, hist)

    def test_parse_rejects_non_aggregate_messages(self):
        assert parse_fit_errors("pod group is not ready, 3 Pending.") is None
        assert parse_fit_errors("") is None


# ---- the equivalence acceptance criterion ----


def _mixed_reason_objects():
    """One stuck task vs five nodes, each failing a DIFFERENT first
    predicate in host order: resource fit, pod count, unschedulable,
    selector, taint."""
    nodes = [
        # too small → resource fit (checked before everything else)
        build_node("n-small", {"cpu": "1", "memory": "1Gi"},
                   labels={"accel": "tpu"}),
        # roomy but zero pod slots → pod number exceeded
        build_node("n-full", {"cpu": "32", "memory": "32Gi", "pods": 0},
                   labels={"accel": "tpu"}),
        # cordoned → unschedulable
        build_node("n-cordon", {"cpu": "32", "memory": "32Gi"},
                   labels={"accel": "tpu"}, unschedulable=True),
        # missing the selector label → selector mismatch
        build_node("n-other", {"cpu": "32", "memory": "32Gi"}),
        # labeled but tainted → taint untolerated
        build_node(
            "n-taint", {"cpu": "32", "memory": "32Gi"},
            labels={"accel": "tpu"},
            taints=[core.Taint(key="dedicated", value="x",
                               effect="NoSchedule")],
        ),
    ]
    pods = [
        build_pod("ns", "stuck-0", "", {"cpu": "4", "memory": "4Gi"},
                  group="pg-stuck", selector={"accel": "tpu"}),
    ]
    pgs = [build_pod_group("ns", "pg-stuck", 1, queue="q1")]
    queues = [build_queue("q1", weight=1)]
    return nodes, pods, pgs, queues


def _fit_error_map(ssn):
    """(namespace/name) → (message, was_synthesized) over all jobs."""
    out = {}
    for job in ssn.jobs.values():
        for uid, fe in job.nodes_fit_errors.items():
            task = job.tasks[uid]
            out[f"{task.namespace}/{task.name}"] = (
                fe.error(), fe._histogram is not None
            )
    return out


def _run_capture(cache, actions, tier_conf):
    """Run the actions and capture the fit-error map BEFORE close_session
    empties the session maps."""
    ssn = open_session(cache, tier_conf, [])
    try:
        for action in actions:
            action.execute(ssn)
        return _fit_error_map(ssn)
    finally:
        close_session(ssn)


class TestDeviceHostEquivalence:
    def test_mixed_reasons_byte_identical(self):
        """The acceptance pin: five nodes, five distinct first-failure
        reasons — the device-synthesized message equals the host sweep's
        byte for byte, and the device path really synthesized (no host
        sweep ran for it)."""
        host = _run_capture(
            make_cache(*_mixed_reason_objects()), [AllocateAction()], TIERS
        )
        dev = _run_capture(
            make_cache(*_mixed_reason_objects()),
            [JaxAllocateAction(explain=True)],
            TIERS,
        )

        assert set(host) == set(dev) == {"ns/stuck-0"}
        host_msg, host_synth = host["ns/stuck-0"]
        dev_msg, dev_synth = dev["ns/stuck-0"]
        assert not host_synth and dev_synth
        assert dev_msg == host_msg
        # every reason plane shows up exactly once
        total, hist = parse_fit_errors(dev_msg)
        assert total == 5
        assert hist == {
            reasons.NODE_RESOURCE_FIT_FAILED: 1,
            reasons.NODE_POD_NUMBER_EXCEEDED: 1,
            reasons.NODE_UNSCHEDULABLE: 1,
            reasons.NODE_SELECTOR_MISMATCH: 1,
            reasons.NODE_TAINT_UNTOLERATED: 1,
        }

    def test_randomized_stuck_cluster_equivalence(self):
        """Label/taint-rich synthetic cluster where nothing fits: the
        device path's recorded messages equal the host path's for every
        task, across many tasks and mixed reasons."""
        from volcano_tpu.ops.synthetic import generate_cluster_objects

        def fresh():
            nodes, pods, pgs, queues = generate_cluster_objects(
                n_tasks=48, n_nodes=12, gang_size=4, seed=3,
                label_classes=3, taint_fraction=0.4,
                node_cpu_milli=100, node_mem_mib=64,  # nothing ever fits
            )
            cache = make_cache(nodes=nodes, pods=pods, pod_groups=pgs,
                               queues=queues)
            return cache

        host = _run_capture(fresh(), [AllocateAction()], TIERS)
        dev = _run_capture(fresh(), [JaxAllocateAction(explain=True)], TIERS)
        assert host and set(host) == set(dev)
        synthesized = 0
        for key, (host_msg, _) in host.items():
            dev_msg, dev_synth = dev[key]
            assert dev_msg == host_msg, key
            synthesized += dev_synth
        # tasks the ORDER replay pruned (the tiny queue goes overused
        # mid-replay) aren't in the packed session and correctly take
        # the host sweep; the in-session ones must have synthesized
        assert synthesized >= 1

    def test_explain_off_still_records_via_host_sweep(self):
        fe_map = _run_capture(
            make_cache(*_mixed_reason_objects()),
            [JaxAllocateAction(explain=False)],
            TIERS,
        )
        msg, synth = fe_map["ns/stuck-0"]
        assert not synth and "0/5 nodes are available" in msg

    def test_synthesis_refused_after_placements_still_correct(self):
        """A placeable job ahead of the stuck one: placements touch node
        state, the synthesis gate closes, and the stuck task takes the
        host sweep — message still present and well-formed."""
        nodes, pods, pgs, queues = _mixed_reason_objects()
        pods = pods + [
            build_pod("ns", "easy-0", "", {"cpu": "1", "memory": "1Gi"},
                      group="pg-easy"),
        ]
        pgs = pgs + [build_pod_group("ns", "pg-easy", 1, queue="q1")]
        cache = make_cache(nodes, pods, pgs, queues)
        fe_map = _run_capture(cache, [JaxAllocateAction(explain=True)], TIERS)
        assert cache.binder.binds  # the easy pod placed
        msg, synth = fe_map["ns/stuck-0"]
        assert not synth  # gate closed — host sweep ran
        assert parse_fit_errors(msg) is not None

    def test_plane_retention_attributes_per_node(self):
        ssn = run_actions(
            make_cache(*_mixed_reason_objects()),
            [JaxAllocateAction(explain=True, explain_planes=True)],
            TIERS,
        )
        from volcano_tpu.ops.explain import last_explain

        info = last_explain()
        assert info is not None and len(info["tasks"]) == 1
        (detail,) = info["tasks"].values()
        assert detail["nodes"] == {
            "n-small": reasons.NODE_RESOURCE_FIT_FAILED,
            "n-full": reasons.NODE_POD_NUMBER_EXCEEDED,
            "n-cordon": reasons.NODE_UNSCHEDULABLE,
            "n-other": reasons.NODE_SELECTOR_MISMATCH,
            "n-taint": reasons.NODE_TAINT_UNTOLERATED,
        }

    def test_pressure_predicates_close_the_synthesis_gate(self):
        """Opt-in pressure predicates insert host failure reasons the
        device planes cannot see — synthesis must refuse and take the
        host sweep (still correct messages, just not device-derived)."""
        from volcano_tpu.conf import PluginOption, Tier
        from volcano_tpu.framework.arguments import Arguments

        pressure_tiers = [
            Tier(plugins=[
                PluginOption(name=n)
                for n in ("priority", "gang", "conformance")
            ]),
            Tier(plugins=[
                PluginOption(
                    name="predicates",
                    arguments=Arguments(
                        {"predicate.MemoryPressureEnable": "true"}
                    ),
                ),
                *[PluginOption(name=n)
                  for n in ("drf", "proportion", "nodeorder", "binpack")],
            ]),
        ]
        fe_map = _run_capture(
            make_cache(*_mixed_reason_objects()),
            [JaxAllocateAction(explain=True)],
            pressure_tiers,
        )
        msg, synth = fe_map["ns/stuck-0"]
        assert not synth  # gate closed
        assert parse_fit_errors(msg) is not None

    def test_stale_last_explain_cleared(self):
        """A later cycle with nothing to explain clears the /explain
        surface instead of serving the previous cycle's explanation."""
        from volcano_tpu.ops.explain import last_explain

        run_actions(
            make_cache(*_mixed_reason_objects()),
            [JaxAllocateAction(explain=True)],
            TIERS,
        )
        assert last_explain() is not None
        # a cycle where everything places
        run_actions(
            make_cache(
                nodes=[build_node("n1", {"cpu": "8", "memory": "8Gi"})],
                pods=[build_pod("ns", "easy-0", "",
                                {"cpu": "1", "memory": "1Gi"}, group="pg1")],
                pod_groups=[build_pod_group("ns", "pg1", 1, queue="q1")],
                queues=[build_queue("q1", weight=1)],
            ),
            [JaxAllocateAction(explain=True)],
            TIERS,
        )
        assert last_explain() is None

    def test_reason_metric_label_cardinality_bounded(self):
        from volcano_tpu.metrics import metrics

        metrics.registry.reset()
        metrics.register_unschedulable_reason(
            'persistentvolumeclaim "ns/claim-42" not found'
        )
        metrics.register_unschedulable_reason(
            'persistentvolumeclaim "ns/claim-43" not found'
        )
        metrics.register_unschedulable_reason(reasons.NODE_NOT_READY)
        text = metrics.registry.render()
        assert 'volcano_unschedulable_task_reasons{reason="other"} 2' in text
        assert "claim-42" not in text

    def test_unschedulable_reason_metric_recorded(self):
        from volcano_tpu.metrics import metrics

        metrics.registry.reset()
        run_actions(
            make_cache(*_mixed_reason_objects()),
            [JaxAllocateAction(explain=True)],
            TIERS,
        )
        text = metrics.registry.render()
        assert (
            'volcano_unschedulable_task_reasons{reason="'
            + reasons.NODE_TAINT_UNTOLERATED + '"} 1'
        ) in text
        assert "volcano_explain_latency_milliseconds_count" in text


# ---- no-victim preempt/reclaim explanations ----


class TestNoVictimExplain:
    def test_jax_preempt_no_victim_synthesizes(self):
        from volcano_tpu.actions.jax_preempt import JaxPreemptAction

        cache = make_cache(
            nodes=[build_node("n1", {"cpu": "4", "memory": "4Gi"})],
            pods=[
                build_pod("ns", "victim", "n1", {"cpu": "2", "memory": "2Gi"},
                          phase="Running", group="pg1", priority=0),
                # can never fit, even with every victim evicted
                build_pod("ns", "preemptor", "", {"cpu": "8", "memory": "2Gi"},
                          group="pg2", priority=10),
            ],
            pod_groups=[
                build_pod_group("ns", "pg1", 1, queue="q1"),
                build_pod_group("ns", "pg2", 1, queue="q1"),
            ],
            queues=[build_queue("q1", weight=1)],
        )
        fe_map = _run_capture(cache, [JaxPreemptAction()], TIERS)
        assert cache.evictor.evicts == []
        msg, synth = fe_map["ns/preemptor"]
        assert synth
        assert msg == format_fit_errors(
            1, {reasons.NODE_RESOURCE_FIT_FAILED: 1}
        )


# ---- events + pod conditions writeback (satellite 3) ----


def _writeback_cluster():
    """Cache wired to a real API server so the status writeback records
    Events and pod conditions; one tainted node, one intolerant task."""
    api = APIServer()
    node = build_node(
        "n1", {"cpu": "8", "memory": "8Gi"},
        taints=[core.Taint(key="dedicated", value="x", effect="NoSchedule")],
    )
    pod = build_pod("ns", "pg1-stuck-0", "",
                    {"cpu": "1", "memory": "1Gi"}, group="pg1")
    pg = build_pod_group("ns", "pg1", 1, queue="q1")
    queue = build_queue("q1", weight=1)
    for obj in (node, pod, pg, queue):
        api.create(obj)
    cache = SchedulerCache(client=SchedulerClient(api))
    cache.add_node(node)
    cache.add_pod(pod)
    cache.add_pod_group(pg)
    cache.add_queue(queue)
    return api, cache


EXPECTED_TAINT_MESSAGE = format_fit_errors(
    1, {reasons.NODE_TAINT_UNTOLERATED: 1}
)


class TestUnschedulableWriteback:
    @pytest.mark.parametrize("action_cls", [AllocateAction, JaxAllocateAction])
    def test_one_event_and_condition_per_cycle(self, action_cls):
        api, cache = _writeback_cluster()
        run_actions(cache, [action_cls()], TIERS)

        events = [
            e for e in api.list("Event", "ns")
            if e.reason == "Unschedulable"
        ]
        assert len(events) == 1
        (ev,) = events
        assert ev.type == "Warning" and ev.count == 1
        assert ev.message == EXPECTED_TAINT_MESSAGE
        assert ev.involved_object["name"] == "pg1-stuck-0"

        pod = api.get("Pod", "ns", "pg1-stuck-0")
        conds = [c for c in pod.status.conditions if c.type == "PodScheduled"]
        assert len(conds) == 1
        assert conds[0].status == "False"
        assert conds[0].reason == "Unschedulable"
        assert conds[0].message == EXPECTED_TAINT_MESSAGE

        # a second identical stuck cycle must NOT duplicate anything:
        # the pod-group status is unchanged, so the writeback gate
        # (is_pod_group_status_updated) suppresses a re-record — still
        # exactly one Event row, count untouched, one condition
        run_actions(cache, [action_cls()], TIERS)
        events = [
            e for e in api.list("Event", "ns")
            if e.reason == "Unschedulable"
        ]
        assert len(events) == 1 and events[0].count == 1
        pod = api.get("Pod", "ns", "pg1-stuck-0")
        assert len([c for c in pod.status.conditions
                    if c.type == "PodScheduled"]) == 1

    def test_unschedulable_digest_parked_and_cleared(self):
        api, cache = _writeback_cluster()
        run_actions(cache, [AllocateAction()], TIERS)
        assert len(cache.unschedulable_digest) == 1
        (digest,) = cache.unschedulable_digest.values()
        assert digest["name"] == "pg1" and digest["namespace"] == "ns"
        (task,) = digest["tasks"].values()
        assert task["message"] == EXPECTED_TAINT_MESSAGE

        # untaint the node → task schedules → digest clears
        node = build_node("n2", {"cpu": "8", "memory": "8Gi"})
        api.create(node)
        cache.add_node(node)
        run_actions(cache, [AllocateAction()], TIERS)
        assert cache.unschedulable_digest == {}


# ---- cache event client handling (satellite 2) ----


class TestRecordEventClients:
    def test_remote_api_server_records_events_over_bus(self):
        from volcano_tpu.bus import BusServer, RemoteAPIServer

        api = APIServer()
        server = BusServer(api).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{server.port}", timeout=5)
        try:
            assert client.wait_ready(5)
            involved = {"kind": "Pod", "namespace": "ns", "name": "p1"}
            client.record_event("ns", involved, "Warning", "Unschedulable", "m1")
            client.record_event("ns", involved, "Warning", "Unschedulable", "m2")
            events = api.list("Event", "ns")
            assert len(events) == 1
            assert events[0].count == 2 and events[0].message == "m2"

            # the cache path accepts a bare RemoteAPIServer as client
            cache = SchedulerCache(client=client)
            task = next(
                iter(
                    make_cache(
                        pods=[build_pod("ns", "p1", "", {"cpu": "1"},
                                        group="pg1")],
                        pod_groups=[build_pod_group("ns", "pg1", 1)],
                    ).jobs.values()
                )
            ).tasks
            cache._record_event(
                next(iter(task.values())), "Warning", "FailedScheduling", "x"
            )
            assert any(
                e.reason == "FailedScheduling" for e in api.list("Event", "ns")
            )
        finally:
            client.close()
            server.stop()

    def test_capability_less_client_warns_once(self, caplog):
        class NoEvents:
            pass

        cache = SchedulerCache(client=NoEvents())
        pod = build_pod("ns", "p1", "", {"cpu": "1"}, group="pg1")
        cache.add_pod(pod)
        task = next(iter(next(iter(cache.jobs.values())).tasks.values()))
        with caplog.at_level("WARNING"):
            cache._record_event(task, "Warning", "Unschedulable", "m")
            cache._record_event(task, "Warning", "Unschedulable", "m")
        warnings = [
            r for r in caplog.records if "cannot record events" in r.message
        ]
        assert len(warnings) == 1


# ---- backfill reason propagation (satellite 1) ----


class TestBackfillReasons:
    def test_allocate_fit_error_keeps_bare_reasons(self, monkeypatch):
        cache = make_cache(
            nodes=[build_node("n1", {"cpu": "2", "memory": "2Gi"})],
            pods=[build_pod("ns", "be-0", "", {}, group="pg1")],
            pod_groups=[build_pod_group("ns", "pg1", 1, queue="q1")],
            queues=[build_queue("q1", weight=1)],
        )
        ssn = open_session(cache, TIERS, [])
        try:
            def boom(task, hostname):
                raise FitError(task, ssn.nodes[hostname],
                               reasons.NODE_PORT_CONFLICT)

            monkeypatch.setattr(ssn, "allocate", boom)
            BackfillAction().execute(ssn)
            (job,) = [j for j in ssn.jobs.values() if j.nodes_fit_errors]
            (fe,) = job.nodes_fit_errors.values()
            # the bare reason — not "task X on node Y: ..." — lands in
            # the histogram
            assert fe.histogram() == {reasons.NODE_PORT_CONFLICT: 1}
            assert fe.error() == format_fit_errors(
                1, {reasons.NODE_PORT_CONFLICT: 1}
            )
        finally:
            close_session(ssn)


# ---- executor / compute-plane plumbing ----


class TestExplainPlumbing:
    def _stuck_snapshot(self):
        from volcano_tpu.ops.synthetic import generate_snapshot

        snap = generate_snapshot(n_tasks=32, n_nodes=8, gang_size=4, seed=5)
        snap.task_resreq[:, 0] = 1e9  # nothing fits anywhere
        return snap

    def test_executor_counts_lazy(self):
        from volcano_tpu.ops import executor
        from volcano_tpu.ops.synthetic import generate_snapshot

        executor.configure(None)
        placed = generate_snapshot(n_tasks=16, n_nodes=8, gang_size=4, seed=0)
        executor.execute_allocate(placed, explain=True)
        assert executor.last_explain_counts() is None  # everything placed

        snap = self._stuck_snapshot()
        executor.execute_allocate(snap, explain=True)
        counts = executor.last_explain_counts()
        assert counts is not None and counts.shape == (snap.n_tasks, 5)
        assert (counts.sum(axis=1) == snap.n_nodes).all()

    def test_compute_plane_returns_reason_counts(self, tmp_path):
        from volcano_tpu.ops.explain import run_explain
        from volcano_tpu.serving.compute_plane import (
            ComputePlaneClient,
            ComputePlaneServer,
        )

        path = str(tmp_path / "cp.sock")
        server = ComputePlaneServer(path).start()
        try:
            client = ComputePlaneClient(path, timeout=60)
            snap = self._stuck_snapshot()
            assignment = client.allocate(snap, explain=True)
            assert (assignment[: snap.n_tasks] < 0).all()
            remote_counts = client.last_reason_counts
            assert remote_counts is not None
            unplaced = np.arange(snap.n_tasks)
            local = run_explain(snap, task_rows=unplaced).counts
            assert np.array_equal(remote_counts, local)

            # without the flag the response carries no counts
            client.allocate(snap, explain=False)
            assert client.last_reason_counts is None
        finally:
            server.stop()

    def test_task_row_subset_matches_full(self):
        from volcano_tpu.ops.explain import run_explain
        from volcano_tpu.ops.synthetic import generate_snapshot

        snap = generate_snapshot(n_tasks=32, n_nodes=8, gang_size=4, seed=7)
        snap.task_resreq[::3, 0] = 1e9
        full = run_explain(snap)
        rows = np.arange(0, snap.n_tasks, 3)
        subset = run_explain(snap, task_rows=rows)
        assert np.array_equal(full.counts[rows], subset.counts[rows])
        off_rows = np.setdiff1d(np.arange(snap.n_tasks), rows)
        assert (subset.counts[off_rows] == 0).all()


# ---- /explain endpoint ----


class TestExplainEndpoint:
    def test_endpoint_serves_digest(self):
        from volcano_tpu.serving.explain import explain_jobs
        from volcano_tpu.serving.http import ServingServer

        api, cache = _writeback_cluster()
        run_actions(cache, [JaxAllocateAction(explain=True)], TIERS)

        server = ServingServer(
            port=0,
            explain_source=lambda ns, job: explain_jobs(cache, ns, job),
        ).start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/explain"
            ) as resp:
                data = json.loads(resp.read())
            assert len(data["jobs"]) == 1
            (job,) = data["jobs"]
            assert job["name"] == "pg1"
            (task,) = job["unschedulable"]
            assert task["message"] == EXPECTED_TAINT_MESSAGE
            assert task["reasons"] == {reasons.NODE_TAINT_UNTOLERATED: 1}
            assert data["last_cycle"]["reasons"] == {
                reasons.NODE_TAINT_UNTOLERATED: 1
            }

            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/explain?namespace=ns&job=pg1"
            ) as resp:
                assert json.loads(resp.read())["jobs"]

            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/explain?job=missing"
                )
            assert e.value.code == 404
            e.value.close()  # the HTTPError holds the response socket
        finally:
            server.stop()

    def test_endpoint_404_without_source(self):
        from volcano_tpu.serving.http import ServingServer

        server = ServingServer(port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/explain"
                )
            assert e.value.code == 404
            e.value.close()  # the HTTPError holds the response socket
        finally:
            server.stop()


# ---- vtctl describe over both backends (acceptance) ----


def _scheduled_api_with_stuck_job():
    """Drive a REAL scheduling cycle against an API server so the
    Unschedulable event/condition/podgroup-condition exist, then
    describe through it."""
    from volcano_tpu.apis import batch

    api, cache = _writeback_cluster()
    # a vcjob whose name matches the podgroup, as the job controller
    # lays them out, so `describe job` joins them
    job = batch.Job(
        metadata=core.ObjectMeta(name="pg1", namespace="ns"),
        spec=batch.JobSpec(
            min_available=1, queue="q1",
            tasks=[batch.TaskSpec(name="stuck", replicas=1)],
        ),
    )
    api.create(job)
    run_actions(cache, [JaxAllocateAction(explain=True)], TIERS)
    return api


class TestVtctlDescribe:
    def _run(self, argv, api):
        import io

        from volcano_tpu.cli.vtctl import main

        out = io.StringIO()
        rc = main(argv, api=api, out=out)
        return rc, out.getvalue()

    def test_describe_podgroup_in_process(self):
        api = _scheduled_api_with_stuck_job()
        rc, text = self._run(
            ["describe", "podgroup", "-N", "pg1", "-n", "ns"], api
        )
        assert rc == 0
        assert "Unschedulable" in text
        assert EXPECTED_TAINT_MESSAGE in text
        assert f"1       {reasons.NODE_TAINT_UNTOLERATED}" in text

    def test_describe_job_both_backends(self):
        from volcano_tpu.bus import BusServer

        api = _scheduled_api_with_stuck_job()
        rc, local = self._run(["describe", "job", "-N", "pg1", "-n", "ns"], api)
        assert rc == 0
        assert "Unschedulable" in local  # the Event row
        assert EXPECTED_TAINT_MESSAGE in local

        server = BusServer(api).start()
        try:
            from volcano_tpu.cli.vtctl import main

            import io

            out = io.StringIO()
            rc = main(
                ["--bus", f"tcp://127.0.0.1:{server.port}",
                 "describe", "job", "-N", "pg1", "-n", "ns"],
                out=out,
            )
            remote = out.getvalue()
            assert rc == 0
            assert remote == local  # byte-identical over the bus
        finally:
            server.stop()

    def test_describe_missing(self):
        rc, text = self._run(
            ["describe", "job", "-N", "nope", "-n", "ns"], APIServer()
        )
        assert rc == 1 and "not found" in text


# ---- trace journal + cross-process correlation ----


class TestExplainTrace:
    def test_explain_summary_journaled(self, tmp_path):
        from volcano_tpu import trace

        rec = trace.enable(str(tmp_path / "journal"), snapshot_every=0)
        try:
            cid = rec.begin_cycle()
            run_actions(
                make_cache(*_mixed_reason_objects()),
                [JaxAllocateAction(explain=True)],
                TIERS,
            )
            rec.end_cycle(duration_s=0.01)
            record = rec.journal.read_cycle(cid)
            (summary,) = [
                e for e in record["events"] if e["name"] == "explain-summary"
            ]
            assert summary["args"]["tasks"] == 1
            assert summary["args"]["reasons"] == {
                reasons.NODE_RESOURCE_FIT_FAILED: 1,
                reasons.NODE_POD_NUMBER_EXCEEDED: 1,
                reasons.NODE_UNSCHEDULABLE: 1,
                reasons.NODE_SELECTOR_MISMATCH: 1,
                reasons.NODE_TAINT_UNTOLERATED: 1,
            }
        finally:
            trace.disable()

    def test_scheduler_sets_cycle_correlation_id(self):
        from volcano_tpu import trace
        from volcano_tpu.scheduler.scheduler import Scheduler

        cache = make_cache(queues=[build_queue("q1", weight=1)])
        sched = Scheduler(cache)
        sched.run_once()
        first = trace.current_cycle()
        sched.run_once()
        assert trace.current_cycle() == first + 1

    def test_bus_request_carries_cycle_id(self):
        from volcano_tpu import trace
        from volcano_tpu.bus import BusServer, RemoteAPIServer

        api = APIServer()
        server = BusServer(api).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{server.port}", timeout=5)
        rec = trace.TraceRecorder()
        trace.set_recorder(rec)
        try:
            assert client.wait_ready(5)
            trace.set_current_cycle(41)
            rec.begin_cycle()
            client.create(build_queue("qx", weight=1))
            rec.end_cycle()
            events = [
                e for e in rec.last_cycle()["events"]
                if e["name"] == "bus:create"
            ]
            assert events and events[0]["args"]["cycle"] == 41
        finally:
            trace.set_current_cycle(-1)
            trace.disable()
            client.close()
            server.stop()
