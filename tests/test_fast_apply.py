"""fast_apply equivalence: the bulk commit must leave session + cache
state identical to the slow drive_allocate_loop/Statement path — exact
floats, dict contents and insertion orders, binder calls, plugin state."""

from __future__ import annotations

import copy

import numpy as np
import pytest

import volcano_tpu.actions.jax_allocate as ja
from volcano_tpu.actions.fast_apply import try_fast_apply
from volcano_tpu.actions.jax_allocate import JaxAllocateAction
from volcano_tpu.api import TaskStatus
from volcano_tpu.framework import close_session, open_session

from tests.builders import build_node, build_pod, build_pod_group, build_queue
from tests.scheduler_helpers import make_cache, tiers

STANDARD = lambda: tiers(
    ["priority", "gang"],
    ["drf", "predicates", "proportion", "nodeorder", "binpack"],
)


def _cluster(n_jobs=6, gang=4, min_avail=None, n_nodes=6, seed=0, queues=None):
    rng = np.random.RandomState(seed)
    nodes = [build_node(f"n{i}", {"cpu": "16", "memory": "64Gi"}) for i in range(n_nodes)]
    pods, pgs = [], []
    qnames = [q.metadata.name for q in (queues or [build_queue("q")])]
    for j in range(n_jobs):
        pgs.append(
            build_pod_group("ns", f"pg{j}", min_avail or gang,
                            queue=qnames[j % len(qnames)])
        )
        for i in range(gang):
            cpu = ["500m", "1", "2"][rng.randint(3)]
            pods.append(
                build_pod("ns", f"j{j}-t{i}", "", {"cpu": cpu, "memory": "1Gi"},
                          group=f"pg{j}")
            )
    return dict(nodes=nodes, pods=pods, pod_groups=pgs,
                queues=queues or [build_queue("q")])


def _run(cluster, force_slow, monkeypatch=None):
    cache = make_cache(**copy.deepcopy(cluster))
    ssn = open_session(cache, STANDARD(), [])
    engaged = {"fast": False}
    if force_slow:
        orig_import = ja.__dict__
        import volcano_tpu.actions.fast_apply as fa

        real = fa.try_fast_apply
        fa.try_fast_apply = lambda *a, **k: False
        try:
            JaxAllocateAction().execute(ssn)
        finally:
            fa.try_fast_apply = real
    else:
        import volcano_tpu.actions.fast_apply as fa

        real = fa.try_fast_apply

        def spy(*a, **k):
            engaged["fast"] = real(*a, **k)
            return engaged["fast"]

        fa.try_fast_apply = spy
        try:
            JaxAllocateAction().execute(ssn)
        finally:
            fa.try_fast_apply = real
    return cache, ssn, engaged["fast"]


def _assert_state_equal(a, b):
    cache_a, ssn_a = a
    cache_b, ssn_b = b
    assert cache_a.binder.binds == cache_b.binder.binds

    assert set(ssn_a.jobs) == set(ssn_b.jobs)
    for uid in ssn_a.jobs:
        ja_, jb = ssn_a.jobs[uid], ssn_b.jobs[uid]
        assert ja_.allocated.milli_cpu == jb.allocated.milli_cpu, uid
        assert ja_.allocated.memory == jb.allocated.memory, uid
        assert ja_.total_request.milli_cpu == jb.total_request.milli_cpu, uid
        assert list(ja_.tasks) == list(jb.tasks), uid  # insertion order
        assert {
            s: set(ts) for s, ts in ja_.task_status_index.items()
        } == {s: set(ts) for s, ts in jb.task_status_index.items()}, uid
        for t_uid, ta in ja_.tasks.items():
            tb = jb.tasks[t_uid]
            assert ta.status == tb.status
            assert ta.node_name == tb.node_name
            assert ta.volume_ready == tb.volume_ready

    assert set(ssn_a.nodes) == set(ssn_b.nodes)
    for name in ssn_a.nodes:
        na, nb = ssn_a.nodes[name], ssn_b.nodes[name]
        assert na.idle.milli_cpu == nb.idle.milli_cpu, name
        assert na.idle.memory == nb.idle.memory, name
        assert na.used.milli_cpu == nb.used.milli_cpu, name
        assert na.used.memory == nb.used.memory, name
        assert list(na.tasks) == list(nb.tasks), name
        for t_uid, ca in na.tasks.items():
            cb = nb.tasks[t_uid]
            assert ca.status == cb.status and ca.node_name == cb.node_name

    # plugin internal state (consumed by later actions in the session)
    for pname in ("drf", "proportion"):
        pa, pb = ssn_a.plugins[pname], ssn_b.plugins[pname]
        if pname == "drf":
            assert set(pa.job_attrs) == set(pb.job_attrs)
            for uid in pa.job_attrs:
                assert pa.job_attrs[uid].share == pb.job_attrs[uid].share, uid
                assert (
                    pa.job_attrs[uid].allocated.milli_cpu
                    == pb.job_attrs[uid].allocated.milli_cpu
                )
            assert set(pa.namespace_opts) == set(pb.namespace_opts)
            for ns in pa.namespace_opts:
                assert pa.namespace_opts[ns].share == pb.namespace_opts[ns].share
        else:
            assert set(pa.queue_opts) == set(pb.queue_opts)
            for q in pa.queue_opts:
                assert pa.queue_opts[q].share == pb.queue_opts[q].share, q
                assert (
                    pa.queue_opts[q].allocated.milli_cpu
                    == pb.queue_opts[q].allocated.milli_cpu
                )

    # cache-side state
    for uid in cache_a.jobs:
        ca, cb = cache_a.jobs[uid], cache_b.jobs[uid]
        assert {s: set(ts) for s, ts in ca.task_status_index.items()} == {
            s: set(ts) for s, ts in cb.task_status_index.items()
        }
    for name in cache_a.nodes:
        na, nb = cache_a.nodes[name], cache_b.nodes[name]
        assert na.idle.milli_cpu == nb.idle.milli_cpu
        assert na.used.milli_cpu == nb.used.milli_cpu
        assert list(na.tasks) == list(nb.tasks)


@pytest.mark.parametrize("kwargs", [
    dict(),                                   # simple gangs, one queue
    dict(min_avail=2, gang=5),                # post-ready single-task episodes
    dict(n_jobs=9, gang=3,
         queues=[build_queue("qa", weight=3), build_queue("qb", weight=1)]),
])
def test_fast_apply_matches_slow_path(kwargs):
    cluster = _cluster(**kwargs)
    cache_f, ssn_f, engaged = _run(cluster, force_slow=False)
    assert engaged, "fast apply did not engage on an exact fully-placed session"
    cache_s, ssn_s, _ = _run(cluster, force_slow=True)
    _assert_state_equal((cache_f, ssn_f), (cache_s, ssn_s))
    close_session(ssn_f)
    close_session(ssn_s)
    # post-close status writeback must agree too
    assert {
        (uid, j.pod_group.status.phase)
        for uid, j in cache_f.jobs.items()
        if j.pod_group is not None
    } == {
        (uid, j.pod_group.status.phase)
        for uid, j in cache_s.jobs.items()
        if j.pod_group is not None
    }


def test_fast_apply_fractional_cpu_bit_identity():
    """Fractional cpu milli-values make the per-lane float sequences
    round-sensitive: the bulk path must follow the slow path's EPISODE
    op structure (all allocates then all commits per gang episode), not a
    per-task interleave, for job.allocated/total_request to stay
    bit-identical."""
    rng = np.random.RandomState(7)
    nodes = [build_node(f"n{i}", {"cpu": "16", "memory": "64Gi"}) for i in range(4)]
    pods, pgs = [], []
    cpus = ["0.1003", "0.2507", "0.4701"]
    for j in range(5):
        pgs.append(build_pod_group("ns", f"pg{j}", 3, queue="q"))
        for i in range(3):
            pods.append(
                build_pod("ns", f"j{j}-t{i}", "",
                          {"cpu": cpus[rng.randint(3)], "memory": "1Gi"},
                          group=f"pg{j}")
            )
    cluster = dict(nodes=nodes, pods=pods, pod_groups=pgs,
                   queues=[build_queue("q")])
    cache_f, ssn_f, engaged = _run(cluster, force_slow=False)
    cache_s, ssn_s, _ = _run(cluster, force_slow=True)
    if engaged:  # identical bindings required for a meaningful comparison
        _assert_state_equal((cache_f, ssn_f), (cache_s, ssn_s))
    close_session(ssn_f)
    close_session(ssn_s)


def test_fast_apply_refuses_partial_placement():
    # one tiny node: most gangs cannot place -> partial -> refuse
    cluster = _cluster(n_jobs=6, gang=4, n_nodes=1)
    cluster["nodes"] = [build_node("n0", {"cpu": "4", "memory": "8Gi"})]
    cache, ssn, engaged = _run(cluster, force_slow=False)
    assert not engaged
    close_session(ssn)


def test_fast_apply_refuses_pvc_pods():
    cluster = _cluster(n_jobs=2, gang=2)
    pod = cluster["pods"][0]
    from volcano_tpu.apis import core

    pod.spec.volumes = [
        core.Volume(name="v", source={"persistentVolumeClaim": {"claimName": "c"}})
    ]
    cache, ssn, engaged = _run(cluster, force_slow=False)
    assert not engaged
    close_session(ssn)


def test_fast_apply_refuses_preassigned_anti_affinity():
    """A RUNNING pod with required anti-affinity makes the host symmetry
    predicate load-bearing for every placement; the packer cannot see it
    (needs_host_validation covers only packed pending tasks), so the
    bulk path must refuse and the slow path must enforce the spread."""
    anti = {"podAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [
        {"labelSelector": {"matchLabels": {"app": "x"}},
         "topologyKey": "kubernetes.io/hostname"}]}}
    nodes = [
        build_node(f"n{i}", {"cpu": "8", "memory": "16Gi"},
                   labels={"kubernetes.io/hostname": f"n{i}"})
        for i in range(2)
    ]
    pods = [
        build_pod("ns", "guard", "n0", {"cpu": "1", "memory": "1Gi"},
                  phase="Running", group="pgr", affinity=anti),
        build_pod("ns", "t0", "", {"cpu": "1", "memory": "1Gi"}, group="pg",
                  labels={"app": "x"}),
        build_pod("ns", "t1", "", {"cpu": "1", "memory": "1Gi"}, group="pg",
                  labels={"app": "x"}),
    ]
    pgs = [build_pod_group("ns", "pgr", 1, queue="q"),
           build_pod_group("ns", "pg", 1, queue="q")]
    cluster = dict(nodes=nodes, pods=pods, pod_groups=pgs, queues=[build_queue("q")])
    cache, ssn, engaged = _run(cluster, force_slow=False)
    assert not engaged
    binds = dict(cache.binder.binds)
    # slow path places the app=x pods only away from the guard's node
    assert all(v != "n0" for k, v in binds.items() if k in ("ns/t0", "ns/t1"))
    close_session(ssn)


def test_fast_apply_refuses_unknown_plugin():
    cluster = _cluster(n_jobs=2, gang=2)
    cache = make_cache(**copy.deepcopy(cluster))
    ssn = open_session(cache, STANDARD(), [])
    try:
        ssn.plugins["mystery"] = object()
        ordered = ja.compute_task_order(ssn)
        proposals, snap = JaxAllocateAction()._kernel_proposals(ssn, ordered)
        assert snap is None or not try_fast_apply(ssn, ordered, proposals, snap)
    finally:
        del ssn.plugins["mystery"]
        close_session(ssn)


def test_ready_counter_invariant_through_fast_apply():
    """job.ready_num (the O(1) counter behind ready_task_num) must equal
    the recomputed bucket sum after the bulk path's direct status-index
    surgery, session- and cache-side."""
    from volcano_tpu.api.job_info import _READY_STATUSES

    def recount(job):
        return sum(
            len(tasks)
            for status, tasks in job.task_status_index.items()
            if status in _READY_STATUSES
        )

    cluster = _cluster()
    cache, ssn, engaged = _run(cluster, force_slow=False)
    assert engaged
    for job in list(ssn.jobs.values()) + list(cache.jobs.values()):
        assert job.ready_task_num() == recount(job), job.uid
        assert job.ready_task_num() > 0  # the session placed everything
    close_session(ssn)


def test_ready_counter_immune_to_double_add():
    """A watch-echo double add (cache._add_task racing its own bind echo)
    must not inflate ready_num: the bucket write is idempotent, so the
    counter has to be as well."""
    from volcano_tpu.api import JobInfo, Resource, TaskInfo, TaskStatus

    job = JobInfo("j1")
    t = TaskInfo(uid="t1", job="j1", name="t1", namespace="ns",
                 resreq=Resource(), status=TaskStatus.Running)
    job.add_task_info(t)
    job.add_task_info(t)  # echo
    assert job.ready_task_num() == 1
    job.delete_task_info(t)
    assert job.ready_task_num() == 0


def _residual_cluster(kind: str):
    """Clean gang jobs plus ONE residual job (created last → processed
    last by the drive loop's creation-timestamp order, so bulk-then-slow
    equals the pure-slow processing order)."""
    cluster = _cluster(n_jobs=4, gang=3)
    if kind == "preference":
        extra = build_pod(
            "ns", "odd-t0", "", {"cpu": "1", "memory": "1Gi"}, group="pgodd",
            affinity={"nodeAffinity": {
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {"weight": 1, "preference": {"matchExpressions": [
                        {"key": "zone", "operator": "In", "values": ["z1"]}]}}]}},
        )
    else:  # pvc
        from volcano_tpu.apis import core

        extra = build_pod("ns", "odd-t0", "", {"cpu": "1", "memory": "1Gi"},
                          group="pgodd")
        extra.spec.volumes = [
            core.Volume(name="v",
                        source={"persistentVolumeClaim": {"claimName": "c"}})
        ]
    cluster["pods"].append(extra)
    cluster["pod_groups"].append(build_pod_group("ns", "pgodd", 1, queue="q"))
    if kind == "pvc":
        from volcano_tpu.apis import core

        cluster["pvcs"] = [core.PersistentVolumeClaim(
            metadata=core.ObjectMeta(name="c", namespace="ns"),
            spec={"storageClassName": "std"},
            status={"phase": "Bound"},
        )]
    return cluster


def _make_cache_with_pvcs(cluster):
    pvcs = cluster.pop("pvcs", [])
    cache = make_cache(**copy.deepcopy(cluster))
    for pvc in pvcs:
        cache.add_pvc(pvc)
    cluster["pvcs"] = pvcs
    return cache


@pytest.mark.parametrize("kind", ["preference", "pvc"])
def test_partial_bulk_apply_matches_slow_path(kind):
    """One odd task (preference terms / PVC volume) no longer forces the
    whole session onto the Statement loop: clean jobs bulk-commit, the
    residual runs host-side, and the final session + cache state equals
    the pure-slow path's."""
    import volcano_tpu.actions.fast_apply as fa

    cluster = _residual_cluster(kind)

    # fast (partial) run, counting what the bulk path actually committed
    cache_f = _make_cache_with_pvcs(cluster)
    ssn_f = open_session(cache_f, STANDARD(), [])
    batches = []
    orig_bind_batch = cache_f.bind_batch
    cache_f.bind_batch = lambda pairs: (batches.append(len(pairs)),
                                        orig_bind_batch(pairs))[1]
    engaged = {}
    real = fa.try_fast_apply
    fa.try_fast_apply = lambda *a, **k: engaged.setdefault("r", real(*a, **k))
    try:
        JaxAllocateAction().execute(ssn_f)
    finally:
        fa.try_fast_apply = real
        cache_f.bind_batch = orig_bind_batch
    assert engaged["r"] is False  # residual present → not fully applied
    assert batches and batches[0] == 12  # the 4 clean gangs bulk-committed

    # pure slow run
    cache_s = _make_cache_with_pvcs(cluster)
    ssn_s = open_session(cache_s, STANDARD(), [])
    fa.try_fast_apply = lambda *a, **k: False
    try:
        JaxAllocateAction().execute(ssn_s)
    finally:
        fa.try_fast_apply = real

    # everything — including the residual task — got placed identically
    assert dict(cache_f.binder.binds) == dict(cache_s.binder.binds)
    assert len(cache_f.binder.binds) == 13
    _assert_state_equal((cache_f, ssn_f), (cache_s, ssn_s))
    close_session(ssn_f)
    close_session(ssn_s)
