"""fast_order equivalence: the episode-level ORDER simulation must
reproduce the exact replay's pop order on every session shape it claims
(and refuse the shapes it cannot model)."""

from __future__ import annotations

import numpy as np

from volcano_tpu.actions.fast_order import try_compute_task_order
from volcano_tpu.actions.jax_allocate import compute_task_order_replay
from volcano_tpu.framework import close_session, open_session

from tests.builders import (
    build_node,
    build_pod,
    build_pod_group,
    build_priority_class,
    build_queue,
)
from tests.scheduler_helpers import make_cache, tiers

STANDARD = lambda: tiers(
    ["priority", "gang"],
    ["drf", "predicates", "proportion", "nodeorder", "binpack"],
)


def _uids(order):
    return [t.uid for t in order]


def _assert_equal_order(cluster, tier_conf=None):
    cache = make_cache(**cluster)
    ssn = open_session(cache, tier_conf or STANDARD(), [])
    try:
        fast = try_compute_task_order(ssn)
        assert fast is not None, "fast path refused a standard session"
        replay = compute_task_order_replay(ssn)
        assert _uids(fast) == _uids(replay)
        # the replay unwinds itself; running it after the simulation also
        # proves the simulation touched no session state
        assert _uids(compute_task_order_replay(ssn)) == _uids(replay)
    finally:
        close_session(ssn)
    return len(_uids(fast := fast))


def _gang_cluster(n_jobs=6, gang=4, min_avail=None, n_nodes=4, seed=0):
    rng = np.random.RandomState(seed)
    nodes = [build_node(f"n{i}", {"cpu": "16", "memory": "64G"}) for i in range(n_nodes)]
    pods, pgs = [], []
    for j in range(n_jobs):
        pgs.append(
            build_pod_group("ns", f"pg{j}", min_avail or gang, queue="q")
        )
        for i in range(gang):
            cpu = ["500m", "1", "2"][rng.randint(3)]
            pods.append(
                build_pod("ns", f"j{j}-t{i}", "", {"cpu": cpu, "memory": "1G"}, group=f"pg{j}")
            )
    return dict(nodes=nodes, pods=pods, pod_groups=pgs, queues=[build_queue("q")])


def test_simple_gangs():
    _assert_equal_order(_gang_cluster())


def test_min_available_below_gang_size():
    # phase B (one task per episode after readiness) is exercised
    _assert_equal_order(_gang_cluster(n_jobs=5, gang=6, min_avail=2))


def test_multi_queue_weights():
    cluster = _gang_cluster(n_jobs=8, gang=3, min_avail=2)
    queues = [build_queue("qa", weight=3), build_queue("qb", weight=1)]
    for i, pg in enumerate(cluster["pod_groups"]):
        pg.spec.queue = "qa" if i % 2 == 0 else "qb"
    cluster["queues"] = queues
    _assert_equal_order(cluster)


def test_multi_namespace():
    nodes = [build_node(f"n{i}", {"cpu": "8", "memory": "16G"}) for i in range(3)]
    pods, pgs = [], []
    for ns in ("alpha", "beta", "gamma"):
        pgs.append(build_pod_group(ns, "pg", 2, queue="q"))
        for i in range(4):
            pods.append(
                build_pod(ns, f"t{i}", "", {"cpu": "1", "memory": "1G"}, group="pg")
            )
    _assert_equal_order(
        dict(nodes=nodes, pods=pods, pod_groups=pgs, queues=[build_queue("q")])
    )


def test_priorities_and_preallocated():
    nodes = [build_node(f"n{i}", {"cpu": "16", "memory": "32G"}) for i in range(4)]
    pcs = [build_priority_class("high", 1000), build_priority_class("low", 10)]
    pods, pgs = [], []
    # one job already partially running (nonzero initial drf share)
    pgs.append(build_pod_group("ns", "warm", 2, queue="q"))
    pods.append(
        build_pod("ns", "warm-r0", "n0", {"cpu": "2", "memory": "2G"},
                  phase="Running", group="warm")
    )
    for i in range(3):
        pods.append(
            build_pod("ns", f"warm-t{i}", "", {"cpu": "1", "memory": "1G"}, group="warm")
        )
    for j, pc in [(0, "high"), (1, "low"), (2, "high")]:
        pg = build_pod_group("ns", f"pg{j}", 2, queue="q", priority_class_name=pc)
        pgs.append(pg)
        for i in range(3):
            pods.append(
                build_pod("ns", f"j{j}-t{i}", "", {"cpu": "1", "memory": "1G"}, group=f"pg{j}")
            )
    _assert_equal_order(
        dict(nodes=nodes, pods=pods, pod_groups=pgs, queues=[build_queue("q")],
             priority_classes=pcs)
    )


def test_best_effort_tasks_skipped():
    nodes = [build_node("n0", {"cpu": "8", "memory": "16G"})]
    pods, pgs = [], []
    pgs.append(build_pod_group("ns", "pg", 1, queue="q"))
    pods.append(build_pod("ns", "be", "", {}, group="pg"))  # empty resreq
    pods.append(build_pod("ns", "real", "", {"cpu": "1", "memory": "1G"}, group="pg"))
    _assert_equal_order(
        dict(nodes=nodes, pods=pods, pod_groups=pgs, queues=[build_queue("q")])
    )


def test_seeded_fuzz_sessions():
    for seed in range(6):
        rng = np.random.RandomState(seed)
        n_jobs = int(rng.randint(3, 12))
        gang = int(rng.randint(1, 6))
        min_avail = int(rng.randint(1, gang + 1))
        _assert_equal_order(
            _gang_cluster(n_jobs=n_jobs, gang=gang, min_avail=min_avail, seed=seed)
        )


def test_refuses_unknown_order_plugin():
    """A session with a job-order comparator outside the modeled set must
    return None (fall back to the replay), not guess."""
    cache = make_cache(**_gang_cluster(n_jobs=2))
    ssn = open_session(cache, STANDARD(), [])
    try:
        ssn.add_job_order_fn("custom", lambda l, r: 0)
        ssn.tiers[0].plugins[0].name = "custom"  # masquerade an unknown name
        # rebuild chain caches
        ssn._ordered_chains.clear()
        assert try_compute_task_order(ssn) is None
    finally:
        close_session(ssn)


def test_order_used_by_action_is_identical():
    from volcano_tpu.actions.jax_allocate import compute_task_order

    cache = make_cache(**_gang_cluster(n_jobs=4, gang=3, min_avail=2))
    ssn = open_session(cache, STANDARD(), [])
    try:
        assert _uids(compute_task_order(ssn)) == _uids(compute_task_order_replay(ssn))
    finally:
        close_session(ssn)
