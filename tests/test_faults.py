"""Fault plane + graceful degradation units: spec parser round-trip,
deterministic firing streams, circuit-breaker state machine, cycle
watchdog, executor degradation ladder, compute-plane session-loss
recovery, /healthz degraded reporting, and the bounded resync queue's
poison-task quarantine.  The multi-seam integration runs live in
tests/test_chaos.py."""

from __future__ import annotations

import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

from volcano_tpu import faults
from volcano_tpu.faults.breaker import CircuitBreaker, CLOSED, HALF_OPEN, OPEN
from volcano_tpu.faults.watchdog import CycleDeadlineExceeded
from volcano_tpu.metrics import metrics

from tests.builders import build_node, build_pod, build_pod_group, build_queue
from tests.scheduler_helpers import make_cache


@pytest.fixture(autouse=True)
def _clean_faults():
    """Every test starts and ends with the plane disabled and the
    breaker registry empty — faults are process-global state."""
    faults.configure(None)
    faults.reset_breakers()
    faults.configure_deadline(None)
    yield
    faults.configure(None)
    faults.reset_breakers()
    faults.configure_deadline(None)
    from volcano_tpu.ops import executor

    executor.configure(None)


def _counter(name, **labels):
    key = (f"volcano_{name}", tuple(sorted(labels.items())))
    return metrics.registry._counters.get(key, 0.0)


# ---- spec parser ----


class TestFaultSpec:
    def test_round_trip(self):
        spec = faults.parse_faults(
            "seed=42;bus.disconnect=0.05;compute.crash=0.1:count=2;"
            "device.slow=1:ms=50:after=3"
        )
        assert spec.seed == 42
        assert spec.rules["bus.disconnect"].probability == 0.05
        assert spec.rules["compute.crash"].count == 2
        assert spec.rules["device.slow"].ms == 50.0
        assert spec.rules["device.slow"].after == 3
        assert faults.parse_faults(spec.format()) == spec

    def test_round_trip_is_fixpoint(self):
        spec = faults.parse_faults("seed=7;cache.bind_fail=0.25:count=10")
        assert faults.parse_faults(spec.format()).format() == spec.format()

    def test_empty_spec(self):
        spec = faults.parse_faults("")
        assert spec.seed == 0 and not spec.rules

    @pytest.mark.parametrize("bad", [
        "bogus",
        "p=1.5",
        "p=-0.1",
        "p=0.5:count=-1",
        "p=0.5:unknown=3",
        "p=0.5:count",
        "seed=x",
        "seed=42:count=2",
        "seed=42:bus.disconnect=0.05",
        "a=0.5;a=0.6",
    ])
    def test_malformed_rejected(self, bad):
        with pytest.raises(ValueError):
            faults.parse_faults(bad)

    def test_deterministic_across_planes(self):
        spec = "seed=99;x.y=0.3;a.b=0.7"
        p1 = faults.FaultPlane(faults.parse_faults(spec))
        p2 = faults.FaultPlane(faults.parse_faults(spec))
        s1 = [p1.should("x.y") for _ in range(50)]
        # interleave another point's evaluations on the second plane —
        # per-point streams are independent, so x.y must not shift
        s2 = []
        for _ in range(50):
            p2.should("a.b")
            s2.append(p2.should("x.y"))
        assert s1 == s2
        assert any(s1) and not all(s1)

    def test_count_and_after(self):
        plane = faults.FaultPlane(
            faults.parse_faults("seed=1;p.q=1:count=2:after=3")
        )
        fires = [plane.should("p.q") for _ in range(10)]
        assert fires == [False] * 3 + [True, True] + [False] * 5
        assert plane.fired() == {"p.q": 2}

    def test_unknown_point_never_fires(self):
        plane = faults.FaultPlane(faults.parse_faults("seed=1;p.q=1"))
        assert plane.should("other.point") is False

    def test_configure_installs_and_clears(self):
        faults.configure("seed=3;x.x=1")
        assert faults.get_plane().enabled
        assert faults.get_plane().should("x.x")
        faults.configure(None)
        assert not faults.get_plane().enabled

    def test_firing_counts_metric(self):
        before = _counter("faults_injected_total", point="m.n")
        faults.configure("seed=1;m.n=1")
        faults.get_plane().should("m.n")
        assert _counter("faults_injected_total", point="m.n") == before + 1


# ---- circuit breaker ----


class TestCircuitBreaker:
    def test_trips_after_threshold(self):
        br = CircuitBreaker("t", failure_threshold=3, cooldown_s=60)
        assert br.state == CLOSED
        br.record_failure("e1")
        br.record_failure("e2")
        assert br.state == CLOSED and br.allow()
        br.record_failure("e3")
        assert br.state == OPEN
        assert not br.allow()

    def test_success_resets_failure_count(self):
        br = CircuitBreaker("t", failure_threshold=2, cooldown_s=60)
        br.record_failure("e")
        br.record_success()
        br.record_failure("e")
        assert br.state == CLOSED  # the streak was broken

    def test_half_open_single_probe_then_promote(self):
        br = CircuitBreaker("t", failure_threshold=1, cooldown_s=0.05)
        br.record_failure("down")
        assert not br.allow()
        time.sleep(0.06)
        assert br.allow()  # the one half-open probe
        assert br.state == HALF_OPEN
        assert not br.allow()  # everyone else keeps falling back
        br.record_success()
        assert br.state == CLOSED and br.allow()

    def test_half_open_failure_reopens(self):
        br = CircuitBreaker("t", failure_threshold=1, cooldown_s=0.05)
        br.record_failure("down")
        time.sleep(0.06)
        assert br.allow()
        br.record_failure("still down")
        assert br.state == OPEN
        assert not br.allow()  # cooldown restarted

    def test_registry_and_degraded_reasons(self):
        br = faults.get_breaker("exec-a", failure_threshold=1)
        assert faults.get_breaker("exec-a") is br
        assert faults.degraded_reasons() == []
        br.record_failure("kaboom")
        reasons = faults.degraded_reasons()
        assert len(reasons) == 1
        assert "exec-a" in reasons[0] and "kaboom" in reasons[0]

    def test_state_gauge(self):
        br = faults.get_breaker("exec-g", failure_threshold=1)
        br.record_failure("x")
        key = ("volcano_circuit_breaker_open", (("executor", "exec-g"),))
        assert metrics.registry._gauges[key] == 1.0
        br.record_success()
        assert metrics.registry._gauges[key] == 0.0


# ---- cycle watchdog ----


class TestWatchdog:
    def test_disabled_runs_inline(self):
        # no deadline → same thread, no worker
        tid = {}
        out = faults.run_with_deadline(
            lambda: tid.setdefault("t", threading.get_ident()) and 41 + 1,
            None, "test",
        )
        assert out == 42 and tid["t"] == threading.get_ident()

    def test_result_and_exception_passthrough(self):
        assert faults.run_with_deadline(lambda: "ok", 5.0, "t") == "ok"
        with pytest.raises(KeyError):
            faults.run_with_deadline(
                lambda: (_ for _ in ()).throw(KeyError("boom")), 5.0, "t"
            )

    def test_overrun_raises(self):
        with pytest.raises(CycleDeadlineExceeded):
            faults.run_with_deadline(lambda: time.sleep(1.0), 0.05, "t")

    def test_exhausted_budget_raises_immediately(self):
        with pytest.raises(CycleDeadlineExceeded):
            faults.run_with_deadline(lambda: "never", 0.0, "t")

    def test_cycle_budget_accounting(self):
        faults.configure_deadline(100.0)  # 100 ms
        faults.begin_cycle()
        r1 = faults.remaining_s()
        assert r1 is not None and 0 < r1 <= 0.1
        time.sleep(0.03)
        r2 = faults.remaining_s()
        assert r2 < r1
        faults.configure_deadline(None)
        assert faults.remaining_s() is None


# ---- executor degradation ladder (dispatch) ----


def _small_snapshot():
    from __graft_entry__ import _tiny_snapshot

    return _tiny_snapshot()


class TestDispatchDegradation:
    def _force_pallas(self, monkeypatch):
        from volcano_tpu.ops import dispatch

        monkeypatch.setattr(
            dispatch, "select_executor", lambda snap, weights=None: "pallas"
        )

    def test_injected_lowering_failure_degrades_exactly(self, monkeypatch):
        from volcano_tpu.ops import dispatch
        from volcano_tpu.ops.kernels import run_packed

        snap = _small_snapshot()
        reference = run_packed(snap)
        self._force_pallas(monkeypatch)
        faults.configure("seed=1;device.lowering=1:count=1")
        before = _counter("executor_fallbacks_total",
                          **{"from": "pallas", "to": "blocked",
                             "cause": "error"})
        out = dispatch.run_packed_auto(snap)
        np.testing.assert_array_equal(out, reference)
        assert dispatch.last_executor() == "blocked"
        assert _counter("executor_fallbacks_total",
                        **{"from": "pallas", "to": "blocked",
                           "cause": "error"}) == before + 1
        assert faults.get_breaker("pallas").state == CLOSED  # 1 < threshold

    def test_breaker_trips_and_skips_the_broken_rung(self, monkeypatch):
        from volcano_tpu.ops import dispatch

        snap = _small_snapshot()
        self._force_pallas(monkeypatch)
        faults.configure("seed=1;device.lowering=1:count=3")
        for _ in range(3):
            dispatch.run_packed_auto(snap)
        assert faults.get_breaker("pallas").state == OPEN
        # 4th call: the rung is skipped WITHOUT attempting (the
        # injection budget is exhausted, so an attempt would succeed —
        # the circuit-open fallback proves it was never tried)
        before = _counter("executor_fallbacks_total",
                          **{"from": "pallas", "to": "blocked",
                             "cause": "circuit-open"})
        dispatch.run_packed_auto(snap)
        assert _counter("executor_fallbacks_total",
                        **{"from": "pallas", "to": "blocked",
                           "cause": "circuit-open"}) == before + 1
        assert faults.degraded_reasons()  # visible to /healthz

    def test_corrupt_output_caught_by_validity_gate(self, monkeypatch):
        from volcano_tpu.ops import dispatch, pallas_session
        from volcano_tpu.ops.kernels import run_packed

        snap = _small_snapshot()
        reference = run_packed(snap)
        self._force_pallas(monkeypatch)
        # the kernel "succeeds" but NaN score planes argmax'd to garbage
        monkeypatch.setattr(
            pallas_session, "run_packed_pallas",
            lambda s, weights=None, gang_rounds=3: np.zeros(
                s.task_resreq.shape[0], dtype=np.int32
            ),
        )
        faults.configure("seed=1;device.nan=1:count=1")
        out = dispatch.run_packed_auto(snap)
        np.testing.assert_array_equal(out, reference)
        assert _counter("executor_fallbacks_total",
                        **{"from": "pallas", "to": "blocked",
                           "cause": "corrupt-output"}) >= 1

    def test_assignment_validity_gate(self):
        from volcano_tpu.ops.dispatch import _assignment_valid

        snap = _small_snapshot()
        good = np.full(snap.task_resreq.shape[0], -1, dtype=np.int32)
        assert _assignment_valid(snap, good)
        bad = good.copy()
        bad[0] = snap.n_nodes  # out of range
        assert not _assignment_valid(snap, bad)
        assert not _assignment_valid(snap, good[:2])  # truncated
        assert not _assignment_valid(snap, np.zeros((4, 4)))  # wrong rank

    def test_abandoned_worker_skips_fallback_and_state_writes(
        self, monkeypatch
    ):
        """A device phase the watchdog abandoned must not, when it
        finally fails, record a breaker verdict, count a fallback, or
        run the full fallback allocate against the next live cycle."""
        from volcano_tpu.ops import blocked, dispatch, pallas_session

        snap = _small_snapshot()
        self._force_pallas(monkeypatch)

        def slow_then_fail(s, weights=None, gang_rounds=3):
            time.sleep(0.2)
            raise RuntimeError("late lowering failure")

        ran_fallback = []
        monkeypatch.setattr(pallas_session, "run_packed_pallas",
                            slow_then_fail)
        monkeypatch.setattr(
            blocked, "run_packed_blocked",
            lambda *a, **k: ran_fallback.append(1) or
            np.full(snap.task_resreq.shape[0], -1, dtype=np.int32),
        )
        before = _counter("executor_fallbacks_total",
                          **{"from": "pallas", "to": "blocked",
                             "cause": "error"})
        with pytest.raises(CycleDeadlineExceeded):
            faults.run_with_deadline(
                lambda: dispatch.run_packed_auto(snap), 0.05, "t"
            )
        time.sleep(0.3)  # let the abandoned worker hit its failure
        assert ran_fallback == []
        assert faults.get_breaker("pallas").state == CLOSED
        assert _counter("executor_fallbacks_total",
                        **{"from": "pallas", "to": "blocked",
                           "cause": "error"}) == before

    def test_device_slow_injects_latency(self):
        from volcano_tpu.ops import dispatch

        snap = _small_snapshot()
        baseline = dispatch.run_packed_auto(snap)  # warm the jit cache
        faults.configure("seed=1;device.slow=1:count=1:ms=120")
        t0 = time.monotonic()
        out = dispatch.run_packed_auto(snap)
        assert time.monotonic() - t0 >= 0.12
        np.testing.assert_array_equal(out, baseline)


# ---- compute-plane session loss + recovery ----


class TestComputePlaneRecovery:
    @pytest.fixture()
    def plane(self, tmp_path):
        from volcano_tpu.ops import executor
        from volcano_tpu.serving.compute_plane import ComputePlaneServer

        path = str(tmp_path / "cp.sock")
        server = ComputePlaneServer(path).start()
        executor.configure(path)
        yield server, path
        server.stop()
        executor.configure(None)

    def test_sidecar_crash_falls_back_and_recovers(self, plane):
        from volcano_tpu.ops import executor

        server, path = plane
        snap = _small_snapshot()
        reference = executor.execute_allocate(snap)
        assert executor._last_route == "remote"

        # crash the sidecar for exactly one request
        faults.configure("seed=1;compute.crash=1:count=1")
        out = executor.execute_allocate(snap)
        np.testing.assert_array_equal(out, reference)
        assert executor._last_route == "local"
        br = faults.get_breaker("compute-plane")
        assert br.state == OPEN
        assert faults.degraded_reasons()

        # recovery: force the next-session probe window open and watch
        # the route promote back (kill-the-sidecar recovers within one
        # probe period — here collapsed for the test)
        faults.configure(None)
        remote = executor._get_remote()
        remote.last_probe = 0.0
        out = executor.execute_allocate(snap)
        np.testing.assert_array_equal(out, reference)
        assert executor._last_route == "remote"
        assert br.state == CLOSED
        assert not faults.degraded_reasons()

    def test_corrupt_frame_and_timeout_degrade(self, plane):
        from volcano_tpu.ops import executor

        server, path = plane
        snap = _small_snapshot()
        reference = executor.execute_allocate(snap)
        for spec in ("seed=1;compute.corrupt=1:count=1",
                     "seed=1;compute.timeout=1:count=1"):
            faults.configure(spec)
            out = executor.execute_allocate(snap)
            np.testing.assert_array_equal(out, reference)
            assert executor._last_route == "local"
            faults.configure(None)
            executor._get_remote().last_probe = 0.0
            out = executor.execute_allocate(snap)
            assert executor._last_route == "remote"
            np.testing.assert_array_equal(out, reference)

    def test_session_loss_clears_acked_revisions(self, plane):
        from volcano_tpu.ops import executor

        server, path = plane
        remote = executor._get_remote()
        remote.client._acked["some-key"] = 7
        remote.mark_unhealthy("test")
        # a restarted sidecar shares no session state: the client must
        # re-handshake with a full frame, not trust dead acks
        assert remote.client._acked == {}

    def test_stale_ack_after_close_is_discarded(self, plane):
        """An allocate() abandoned by the watchdog may complete AFTER a
        close() cleared the acks; its late write must not resurrect a
        session the restarted sidecar does not hold."""
        from volcano_tpu.ops import executor

        client = executor._get_remote().client
        gen = client._session_gen
        client.close()
        client._ack(gen, "k", 5)  # the abandoned worker's late write
        assert client._acked == {}
        client._ack(client._session_gen, "k", 5)  # a live round trip acks
        assert client._acked == {"k": 5}

    def test_forced_need_full_reseeds(self, plane):
        """compute.need_full answers a delta frame with T_NEED_FULL; the
        client transparently re-sends the full snapshot — same
        assignment, session store re-seeded."""
        from volcano_tpu.ops import executor
        from volcano_tpu.ops.pack_cache import PackDelta

        server, path = plane
        snap = _small_snapshot()
        snap.cache_key = "chaos-key"
        snap.rev = 1
        snap.delta = None
        first = executor.execute_allocate(snap)
        assert executor._last_route == "remote"
        # second session: a delta frame against rev 1
        snap2 = _small_snapshot()
        snap2.cache_key = "chaos-key"
        snap2.rev = 2
        snap2.delta = PackDelta(base_rev=1, planes={})
        faults.configure("seed=1;compute.need_full=1:count=1")
        out = executor.execute_allocate(snap2)
        np.testing.assert_array_equal(out, first)
        assert executor._last_route == "remote"


# ---- /healthz degraded ----


class TestHealthzDegraded:
    def test_degraded_reason_in_body(self):
        from volcano_tpu.serving.http import ServingServer

        server = ServingServer(host="127.0.0.1", port=0).start()
        try:
            url = f"http://127.0.0.1:{server.port}/healthz"
            assert urllib.request.urlopen(url).read() == b"ok"
            faults.get_breaker("pallas").record_failure("vmem overflow")
            faults.get_breaker("pallas").record_failure("vmem overflow")
            faults.get_breaker("pallas").record_failure("vmem overflow")
            body = urllib.request.urlopen(url).read().decode()
            assert body.startswith("degraded: ")
            assert "pallas" in body and "vmem overflow" in body
            faults.get_breaker("pallas").record_success()
            assert urllib.request.urlopen(url).read() == b"ok"
        finally:
            server.stop()


# ---- resync queue: bounded retry + poison quarantine ----


class _FlakyClient:
    """get_pod fails ``fail_times`` times, then serves ``pod``."""

    def __init__(self, pod=None, fail_times=10**9):
        self.pod = pod
        self.fail_times = fail_times
        self.calls = 0

    def get_pod(self, namespace, name):
        self.calls += 1
        if self.calls <= self.fail_times:
            raise ConnectionError("apiserver unreachable")
        return self.pod

    def watch(self, cache):
        pass


def _cache_with_bound_pod(client):
    node = build_node("n0", {"cpu": "8", "memory": "16Gi"})
    pod = build_pod("ns", "p0", "", {"cpu": "1", "memory": "1Gi"}, group="pg")
    cache = make_cache(
        nodes=[node], pods=[pod],
        pod_groups=[build_pod_group("ns", "pg", 1)],
        queues=[build_queue("default")],
    )
    cache.client = client
    task = next(iter(cache.jobs.values())).tasks[pod.metadata.uid]
    return cache, task, pod


class TestResyncQuarantine:
    def test_dedupe(self):
        cache, task, _ = _cache_with_bound_pod(_FlakyClient())
        cache._RESYNC_BACKOFF_BASE = 0.0
        cache.resync_task(task)
        cache.resync_task(task)
        assert len(cache.err_tasks) + (task.uid in cache.quarantined_tasks) == 1

    def test_bounded_retries_then_quarantine(self):
        client = _FlakyClient()
        cache, task, _ = _cache_with_bound_pod(client)
        cache._RESYNC_BACKOFF_BASE = 0.0
        cache.resync_task(task)  # attempt 1 happens inline
        for _ in range(10):
            cache.process_due_resyncs()
        assert client.calls == cache._RESYNC_MAX_RETRIES
        assert task.uid in cache.quarantined_tasks
        assert cache.err_tasks == []
        key = ("volcano_resync_quarantined_tasks", ())
        assert metrics.registry._gauges[key] >= 1.0
        # quarantined: further resync_task calls don't requeue
        cache.resync_task(task)
        assert cache.err_tasks == []

    def test_fresh_truth_clears_quarantine(self):
        client = _FlakyClient()
        cache, task, pod = _cache_with_bound_pod(client)
        cache._RESYNC_BACKOFF_BASE = 0.0
        cache.resync_task(task)
        for _ in range(10):
            cache.process_due_resyncs()
        assert task.uid in cache.quarantined_tasks
        # the pod's watch event is the quarantine's exit
        cache.update_pod(pod, pod)
        assert task.uid not in cache.quarantined_tasks
        key = ("volcano_resync_quarantined_tasks", ())
        assert metrics.registry._gauges[key] == 0.0

    def test_quarantine_cooldown_reenters_the_queue(self):
        """An unchanged pod never produces the watch event that is the
        quarantine's fast exit — after the cooldown the task re-enters
        the queue with a fresh attempt budget (slow retry lane)."""
        pod = build_pod("ns", "p0", "n0", {"cpu": "1", "memory": "1Gi"},
                        group="pg")
        client = _FlakyClient(pod=pod, fail_times=5)
        cache, task, _ = _cache_with_bound_pod(client)
        cache._RESYNC_BACKOFF_BASE = 0.0
        cache._QUARANTINE_COOLDOWN = 0.05
        cache.resync_task(task)
        for _ in range(10):
            cache.process_due_resyncs()
        assert task.uid in cache.quarantined_tasks
        time.sleep(0.06)
        for _ in range(3):
            cache.process_due_resyncs()
        assert task.uid not in cache.quarantined_tasks
        assert cache.err_tasks == []  # the retry after cooldown succeeded
        assert client.calls == 6

    def test_transient_failure_recovers_before_quarantine(self):
        pod = build_pod("ns", "p0", "n0", {"cpu": "1", "memory": "1Gi"},
                        group="pg")
        client = _FlakyClient(pod=pod, fail_times=2)
        cache, task, _ = _cache_with_bound_pod(client)
        cache._RESYNC_BACKOFF_BASE = 0.0
        cache.resync_task(task)
        for _ in range(5):
            cache.process_due_resyncs()
        assert task.uid not in cache.quarantined_tasks
        assert cache.err_tasks == []
        assert client.calls == 3  # 2 failures + the success

    def test_injected_bind_failure_feeds_resync(self):
        cache, task, _ = _cache_with_bound_pod(_FlakyClient())
        cache.client = None  # keep resync queued, not processed
        faults.configure("seed=1;cache.bind_fail=1:count=1")
        cache.bind(task, "n0")
        cache.flush()
        assert cache.binder.binds == {}  # the injection fired pre-binder
        assert len(cache.err_tasks) == 1

    def test_resync_marks_row_dirty_on_success(self):
        pod = build_pod("ns", "p0", "", {"cpu": "1", "memory": "1Gi"},
                        group="pg")
        client = _FlakyClient(pod=pod, fail_times=0)
        cache, task, _ = _cache_with_bound_pod(client)
        cache.resync_task(task)
        assert task.uid in cache._dirty_tasks


# ---- bus injection points ----


class TestBusFaults:
    def test_force_relist_recovers_via_reconcile(self):
        """A 410-storm (every resume refused) degrades to relists — the
        informer caches still converge, with the relist counter as the
        audit trail."""
        from volcano_tpu.bus.remote import RemoteAPIServer
        from volcano_tpu.bus.server import BusServer
        from volcano_tpu.client import APIServer

        api = APIServer()
        server = BusServer(api).start()
        client = RemoteAPIServer(f"tcp://127.0.0.1:{server.port}")
        try:
            assert client.wait_ready(10)
            seen = []
            client.watch("Node", lambda e, old, new: seen.append(e))
            api.create(build_node("n0", {"cpu": "1", "memory": "1Gi"}))
            deadline = time.monotonic() + 5
            while len(seen) < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert seen  # stream live
            before = _counter("bus_relists_total", kind="Node")
            faults.configure("seed=1;bus.force_relist=1:count=1")
            # break the connection so the watch re-establishes (resume →
            # forced 410 → relist); a raw shutdown (not teardown) lets
            # the reader thread observe the loss and trigger reconnect
            client._sock.shutdown(socket.SHUT_RDWR)
            api.create(build_node("n1", {"cpu": "1", "memory": "1Gi"}))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if _counter("bus_relists_total", kind="Node") > before and \
                        len(seen) >= 2:
                    break
                time.sleep(0.02)
            assert _counter("bus_relists_total", kind="Node") > before
            assert len(seen) == 2  # no duplicates, no losses
        finally:
            client.close()
            server.stop()
