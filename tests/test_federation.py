"""Sharded scheduler federation (ISSUE 9).

Covers the four load-bearing claims:

* **Leases** — claim/renew/absorb-on-expiry/release-on-join over the
  CAS shard map; a crashed member's slices are re-owned within one
  lease TTL.
* **Filtering** — each member's cache holds only its owned slice
  (O(nodes/N)); foreign pods bound onto owned nodes are accounted but
  never scheduled; ownership moves replay state correctly.
* **Spillover** — home-shard-stuck tasks CAS-bind onto foreign nodes;
  conflicts resolve at the store; gang semantics stay within home
  shards.
* **Equivalence** — ``--shards 1`` is bit-identical to the plain
  scheduler (binding maps + ``trace.replay.verify``); multi-shard runs
  pass the policy-equivalence checker.

The tier-1 chaos smoke runs three federated members over a real TCP
bus and SIGKILLs one mid-cycle via the deterministic fault plane
(``shard.kill``); the soak variant is marked ``slow``.
"""

from __future__ import annotations

import os
import time
from collections import defaultdict

import pytest

from volcano_tpu import faults, trace
from volcano_tpu.bus.remote import RemoteAPIServer
from volcano_tpu.bus.server import BusServer
from volcano_tpu.cache import SchedulerCache
from volcano_tpu.client import (
    ADDED,
    APIServer,
    KubeClient,
    MODIFIED,
    SchedulerClient,
    VolcanoClient,
)
from volcano_tpu.client.apiserver import ConflictError
from volcano_tpu.federation import (
    FederatedScheduler,
    read_shard_map,
    SketchSolicitor,
    verify_federation,
)
from volcano_tpu.federation.filter import ShardInformerFilter
from volcano_tpu.federation.leases import ShardLeaseManager
from volcano_tpu.federation.sharding import (
    home_shard,
    shard_of_node,
    ShardState,
)
from volcano_tpu.scheduler.scheduler import Scheduler

from tests.builders import build_node, build_pod, build_pod_group, build_queue

CONF = """
actions: "enqueue, jax-allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


# Wall-clock budgets stretch under the happens-before race detector the
# way TSAN suites scale their timeouts: tracked attribute accesses cost
# ~4x, so a sub-second lease TTL starts missing renewals on a loaded
# 2-core CI runner and the lease plane churns (slices expire under
# their live holders) instead of converging.  Only TIME budgets scale —
# every safety assertion (no dup binds, no partial gang, policy
# equivalence, absorb-within-one-TTL *in TTL units*) stays exact.
_TIME_SCALE = 3.0 if os.environ.get("VTPU_RACE") == "1" else 1.0


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.configure(None)
    yield
    faults.configure(None)
    trace.disable()


def _wait(pred, timeout=20.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _conf(tmp_path, name="conf"):
    p = tmp_path / f"{name}.yaml"
    p.write_text(CONF)
    return str(p)


def _names_for_shard(shard: int, n_shards: int, count: int, prefix="job"):
    """Job names whose home shard is exactly ``shard`` (deterministic
    search over the hash)."""
    out, k = [], 0
    while len(out) < count:
        name = f"{prefix}{k}"
        k += 1
        if home_shard("ns", name, n_shards) == shard:
            out.append(name)
    return out


class TestSharding:
    def test_deterministic_and_in_range(self):
        for n in (1, 2, 4, 7):
            for i in range(64):
                s = shard_of_node(f"node-{i}", n)
                assert 0 <= s < n
                assert s == shard_of_node(f"node-{i}", n)
                h = home_shard("ns", f"job-{i}", n)
                assert 0 <= h < n

    def test_single_shard_collapses_to_zero(self):
        assert shard_of_node("anything", 1) == 0
        assert home_shard("ns", "job", 1) == 0

    def test_spreads_across_shards(self):
        hits = {shard_of_node(f"n{i:04d}", 4) for i in range(64)}
        assert hits == {0, 1, 2, 3}


class TestShardLeases:
    def test_two_members_split_the_map(self):
        api = APIServer()
        owned = {0: set(), 1: set()}
        mgrs = [
            ShardLeaseManager(
                api, f"m{i}", 4, lease_duration=0.6, retry_period=0.03,
                on_acquire=owned[i].add, on_release=owned[i].discard,
            ).start()
            for i in range(2)
        ]
        try:
            assert _wait(
                lambda: len(owned[0]) == 2 and len(owned[1]) == 2
                and owned[0] | owned[1] == {0, 1, 2, 3}
                and not (owned[0] & owned[1])
            ), f"never balanced: {owned}"
            rec = read_shard_map(api)
            assert set(rec["members"]) == {"m0", "m1"}
        finally:
            for m in mgrs:
                m.stop()

    def test_crash_absorbed_within_one_ttl(self):
        api = APIServer()
        ttl = 0.5
        owned = {i: set() for i in range(3)}
        mgrs = [
            ShardLeaseManager(
                api, f"m{i}", 3, lease_duration=ttl, retry_period=0.03,
                on_acquire=owned[i].add, on_release=owned[i].discard,
            ).start()
            for i in range(3)
        ]
        try:
            assert _wait(
                lambda: all(len(owned[i]) == 1 for i in range(3))
            ), f"never settled 1:1:1: {owned}"
            victim = next(
                i for i in range(3)
                if read_shard_map(api)["shards"]["0"]["holder"] == f"m{i}"
            )
            mgrs[victim].stop(release=False)  # crash: lease left to expire
            t0 = time.monotonic()
            survivors = [i for i in range(3) if i != victim]
            assert _wait(
                lambda: owned[survivors[0]] | owned[survivors[1]]
                == {0, 1, 2},
                timeout=ttl * 4 + 2.0,
            ), f"orphaned shard never absorbed: {owned}"
            # absorbed within one TTL of the lease EXPIRING (the lease
            # was still valid when the crash happened)
            assert time.monotonic() - t0 <= ttl + ttl + 1.0
        finally:
            for m in mgrs:
                m.stop()

    def test_joiner_gets_a_released_share(self):
        api = APIServer()
        first, second = set(), set()
        m0 = ShardLeaseManager(
            api, "m0", 4, lease_duration=0.6, retry_period=0.03,
            on_acquire=first.add, on_release=first.discard,
        ).start()
        try:
            assert _wait(lambda: first == {0, 1, 2, 3})
            m1 = ShardLeaseManager(
                api, "m1", 4, lease_duration=0.6, retry_period=0.03,
                on_acquire=second.add, on_release=second.discard,
            ).start()
            try:
                assert _wait(
                    lambda: len(first) == 2 and len(second) == 2
                ), f"join never rebalanced: {first} {second}"
            finally:
                m1.stop()
        finally:
            m0.stop()

    def test_nshards_mismatch_refuses_to_participate(self):
        api = APIServer()
        good = set()
        m0 = ShardLeaseManager(
            api, "m0", 2, lease_duration=0.6, retry_period=0.03,
            on_acquire=good.add, on_release=good.discard,
        ).start()
        try:
            assert _wait(lambda: good == {0, 1})
            bad = set()
            m1 = ShardLeaseManager(
                api, "m1", 3, lease_duration=0.6, retry_period=0.03,
                on_acquire=bad.add, on_release=bad.discard,
            ).start()
            try:
                time.sleep(0.4)
                assert bad == set()  # never claimed against a 2-shard map
                rec = read_shard_map(api)
                assert int(rec["nShards"]) == 2
            finally:
                m1.stop()
        finally:
            m0.stop()

    def test_graceful_stop_releases_immediately(self):
        api = APIServer()
        owned = set()
        m = ShardLeaseManager(
            api, "m0", 2, lease_duration=5.0, retry_period=0.03,
            on_acquire=owned.add, on_release=owned.discard,
        ).start()
        assert _wait(lambda: owned == {0, 1})
        m.stop(release=True)
        rec = read_shard_map(api)
        assert all(
            not e.get("holder") for e in rec["shards"].values()
        ), rec["shards"]
        assert "m0" not in rec.get("members", {})


class _FilterRig:
    """Cache + state + filter, no lease manager — ownership flipped by
    hand so the forwarding rules are tested in isolation."""

    def __init__(self, n_shards=2, api=None):
        self.api = api or APIServer()
        self.cache = SchedulerCache(
            client=SchedulerClient(self.api), scheduler_name="volcano-tpu"
        )
        self.state = ShardState(n_shards)
        self.filter = ShardInformerFilter(
            self.cache, self.state, lister=self.api
        )
        self.cache.set_informer_sink(self.filter)
        self.cache.run()

    def own(self, shard):
        self.state.acquire(shard)
        self.filter.on_acquire(shard)

    def disown(self, shard):
        self.state.release(shard)
        self.filter.on_release(shard)


def _nodes_for_shard(shard, n_shards, count, cpu="8"):
    out, k = [], 0
    while len(out) < count:
        name = f"n{k:03d}"
        k += 1
        if shard_of_node(name, n_shards) == shard:
            out.append(build_node(name, {"cpu": cpu, "memory": "64Gi"}))
    return out


class TestShardFilter:
    def test_cache_holds_only_owned_nodes(self):
        rig = _FilterRig()
        rig.own(0)
        kube = KubeClient(rig.api)
        for shard in (0, 1):
            for node in _nodes_for_shard(shard, 2, 3):
                kube.create_node(node)
        owned = {
            n for n in rig.cache.nodes if shard_of_node(n, 2) == 0
        }
        assert set(rig.cache.nodes) == owned and len(owned) == 3

    def test_foreign_bound_pod_is_accounting_only(self):
        rig = _FilterRig()
        rig.own(0)
        kube = KubeClient(rig.api)
        vc = VolcanoClient(rig.api)
        vc.create_queue(build_queue("default"))
        node = _nodes_for_shard(0, 2, 1)[0]
        kube.create_node(node)
        # a job homed on shard 1 (foreign) whose pod lands on OUR node
        # — another member's spillover, observed through the watch
        jname = _names_for_shard(1, 2, 1)[0]
        vc.create_pod_group(build_pod_group("ns", jname, 1))
        kube.create_pod(build_pod(
            "ns", f"{jname}-t0", node.metadata.name,
            {"cpu": "1", "memory": "1Gi"}, group=jname,
        ))
        ninfo = rig.cache.nodes[node.metadata.name]
        assert len(ninfo.tasks) == 1  # node accounting present
        job = rig.cache.jobs.get(f"ns/{jname}")
        assert job is not None and job.pod_group is None  # inert: the
        # foreign PodGroup was filtered, so snapshots never schedule it
        assert not rig.cache.has_schedulable_pending()

    def test_acquire_replays_and_release_drops(self):
        rig = _FilterRig()
        rig.own(0)
        kube = KubeClient(rig.api)
        vc = VolcanoClient(rig.api)
        vc.create_queue(build_queue("default"))
        for shard in (0, 1):
            for node in _nodes_for_shard(shard, 2, 2):
                kube.create_node(node)
        jname = _names_for_shard(1, 2, 1)[0]
        vc.create_pod_group(build_pod_group("ns", jname, 1))
        kube.create_pod(build_pod(
            "ns", f"{jname}-t0", "", {"cpu": "1", "memory": "1Gi"},
            group=jname,
        ))
        assert f"ns/{jname}" not in rig.cache.jobs
        assert len(rig.cache.nodes) == 2
        rig.own(1)  # absorb: relist must deliver shard 1's world
        assert len(rig.cache.nodes) == 4
        job = rig.cache.jobs[f"ns/{jname}"]
        assert job.pod_group is not None and len(job.tasks) == 1
        assert rig.cache.has_schedulable_pending()
        rig.disown(1)  # shed it again
        assert len(rig.cache.nodes) == 2
        assert f"ns/{jname}" not in rig.cache.jobs

    def test_single_shard_passes_everything(self):
        rig = _FilterRig(n_shards=1)
        rig.own(0)
        kube = KubeClient(rig.api)
        for i in range(5):
            kube.create_node(build_node(f"x{i}", {"cpu": "4",
                                                  "memory": "8Gi"}))
        assert len(rig.cache.nodes) == 5


class TestCasBind:
    def test_cas_bind_binds_once(self):
        api = APIServer()
        kube = KubeClient(api)
        kube.create_node(build_node("n1", {"cpu": "4", "memory": "8Gi"}))
        pod = kube.create_pod(build_pod("ns", "p1", "",
                                        {"cpu": "1", "memory": "1Gi"}))
        bound = api.cas_bind("ns", "p1", "n1",
                             expected_rv=pod.metadata.resource_version)
        assert bound.spec.node_name == "n1"
        with pytest.raises(ConflictError):
            api.cas_bind("ns", "p1", "n2")

    def test_cas_bind_detects_rv_race(self):
        api = APIServer()
        kube = KubeClient(api)
        pod = kube.create_pod(build_pod("ns", "p1", "",
                                        {"cpu": "1", "memory": "1Gi"}))
        stale = pod.metadata.resource_version
        pod.metadata.labels["touched"] = "yes"
        api.update(pod)  # rv moves
        with pytest.raises(ConflictError):
            api.cas_bind("ns", "p1", "n1", expected_rv=stale)


def _make_fed(api, ident, n_shards, conf, ttl=0.8, spill_after=1,
              gang_broker=True, gang_assemble_after=1):
    fed = FederatedScheduler(
        api, ident, n_shards, scheduler_conf_path=conf,
        lease_duration=ttl, lease_retry_period=0.04,
        spill_after=spill_after,
        gang_broker=gang_broker,
        gang_assemble_after=gang_assemble_after,
    )
    return fed.start()


class TestSpillover:
    def test_home_shard_full_spills_to_foreign(self, tmp_path):
        api = APIServer()
        kube, vc = KubeClient(api), VolcanoClient(api)
        vc.create_queue(build_queue("default"))
        # shard 1 nodes are tiny; shard 0 nodes have room
        for node in _nodes_for_shard(0, 2, 3, cpu="16"):
            kube.create_node(node)
        for node in _nodes_for_shard(1, 2, 3, cpu="1"):
            kube.create_node(node)
        feds = [
            _make_fed(api, f"s{i}", 2, _conf(tmp_path)) for i in range(2)
        ]
        try:
            for f in feds:
                assert f.wait_owned(10.0)
            _wait(lambda: sum(len(f.state.owned()) for f in feds) == 2)
            spiller = next(f for f in feds if f.state.owns_shard(1))
            for jname in _names_for_shard(1, 2, 3, prefix="big"):
                vc.create_pod_group(build_pod_group("ns", jname, 1))
                kube.create_pod(build_pod(
                    "ns", f"{jname}-t0", "",
                    {"cpu": "2", "memory": "1Gi"}, group=jname,
                ))

            def all_bound():
                for f in feds:
                    f.scheduler.run_once()
                return all(
                    p.spec.node_name for p in kube.list_pods("ns")
                )

            assert _wait(all_bound, timeout=30.0, interval=0.05)
            for p in kube.list_pods("ns"):
                assert shard_of_node(p.spec.node_name, 2) == 0, (
                    "spill landed on the full home shard?!"
                )
            assert spiller.spillover.counters().get("bound", 0) == 3
            report = verify_federation(api, 2)
            assert report["ok"], report["violations"]
        finally:
            for f in feds:
                f.stop()

    def test_unsatisfied_gang_assembles_cross_shard(self, tmp_path):
        """THE new behavior pin (replacing the PR 9 refusal pin
        ``test_unsatisfied_gang_never_spills``): a gang whose home
        shard cannot fit ``minMember`` no longer stays Pending — the
        gang broker assembles a full-gang placement (home fills first,
        foreign claims the remainder) and commits it via ONE atomic
        ``txn_commit``, so the gang binds across ≥2 shards with the
        no-partial invariant provable from API truth throughout."""
        api = APIServer()
        kube, vc = KubeClient(api), VolcanoClient(api)
        vc.create_queue(build_queue("default"))
        # home shard 1: one 2-cpu node (fits ONE 2-cpu task — below the
        # gang minimum); shard 0 has room for the rest
        for node in _nodes_for_shard(0, 2, 3, cpu="16"):
            kube.create_node(node)
        for node in _nodes_for_shard(1, 2, 1, cpu="2"):
            kube.create_node(node)
        feds = [
            _make_fed(api, f"s{i}", 2, _conf(tmp_path)) for i in range(2)
        ]
        try:
            for f in feds:
                assert f.wait_owned(10.0)
            _wait(lambda: sum(len(f.state.owned()) for f in feds) == 2)
            jname = _names_for_shard(1, 2, 1, prefix="gang")[0]
            vc.create_pod_group(build_pod_group("ns", jname, 3))
            for i in range(3):
                kube.create_pod(build_pod(
                    "ns", f"{jname}-t{i}", "",
                    {"cpu": "2", "memory": "1Gi"}, group=jname,
                ))

            def all_bound():
                for f in feds:
                    f.scheduler.run_once()
                pods = kube.list_pods("ns")
                # the invariant holds at EVERY observation: the gang is
                # never visible partially placed below minMember
                bound = sum(1 for p in pods if p.spec.node_name)
                assert bound == 0 or bound >= 3, (
                    f"partial gang observed: {bound}/3 bound"
                )
                return bound == 3

            assert _wait(all_bound, timeout=30.0, interval=0.05), (
                "gang never assembled across shards"
            )
            spanned = {
                shard_of_node(p.spec.node_name, 2)
                for p in kube.list_pods("ns")
            }
            assert spanned == {0, 1}, (
                f"expected a cross-shard assembly, got shards {spanned}"
            )
            homer = next(f for f in feds if f.state.owns_shard(1))
            assert homer.broker.counters().get("committed", 0) == 1
            report = verify_federation(api, 2)
            assert report["ok"], report["violations"]
            assert report["checked"]["cross_shard_gangs"] == 1
        finally:
            for f in feds:
                f.stop()

    def test_gang_broker_off_keeps_refusal(self, tmp_path):
        """The degraded-mode refusal pin: with ``--gang-broker off``
        (and equally on a pre-v6 bus, where the old-peer txn_commit
        fallback is an abort) the PR 9 semantics hold exactly — a gang
        below minMember at home stays Pending, honestly, and never
        partially escapes its shard."""
        api = APIServer()
        kube, vc = KubeClient(api), VolcanoClient(api)
        vc.create_queue(build_queue("default"))
        for node in _nodes_for_shard(0, 2, 3, cpu="16"):
            kube.create_node(node)
        for node in _nodes_for_shard(1, 2, 1, cpu="1"):
            kube.create_node(node)
        feds = [
            _make_fed(api, f"s{i}", 2, _conf(tmp_path), gang_broker=False)
            for i in range(2)
        ]
        try:
            for f in feds:
                assert f.wait_owned(10.0)
            _wait(lambda: sum(len(f.state.owned()) for f in feds) == 2)
            jname = _names_for_shard(1, 2, 1, prefix="gang")[0]
            vc.create_pod_group(build_pod_group("ns", jname, 3))
            for i in range(3):
                kube.create_pod(build_pod(
                    "ns", f"{jname}-t{i}", "",
                    {"cpu": "2", "memory": "1Gi"}, group=jname,
                ))
            for _ in range(6):
                for f in feds:
                    f.scheduler.run_once()
                time.sleep(0.02)
            assert all(
                not p.spec.node_name for p in kube.list_pods("ns")
            ), "gang task escaped its home shard below minMember"
            spiller = next(f for f in feds if f.state.owns_shard(1))
            assert spiller.spillover.counters().get("bound", 0) == 0
            assert spiller.broker is None
        finally:
            for f in feds:
                f.stop()

    def test_lost_race_is_detected_at_the_store(self, tmp_path):
        api = APIServer()
        kube, vc = KubeClient(api), VolcanoClient(api)
        vc.create_queue(build_queue("default"))
        for node in _nodes_for_shard(0, 2, 2, cpu="16"):
            kube.create_node(node)
        for node in _nodes_for_shard(1, 2, 1, cpu="1"):
            kube.create_node(node)
        feds = [
            _make_fed(api, f"s{i}", 2, _conf(tmp_path)) for i in range(2)
        ]
        try:
            for f in feds:
                assert f.wait_owned(10.0)
            _wait(lambda: sum(len(f.state.owned()) for f in feds) == 2)
            spiller = next(f for f in feds if f.state.owns_shard(1))
            jname = _names_for_shard(1, 2, 1, prefix="race")[0]
            vc.create_pod_group(build_pod_group("ns", jname, 1))
            kube.create_pod(build_pod(
                "ns", f"{jname}-t0", "", {"cpu": "2", "memory": "1Gi"},
                group=jname,
            ))
            spiller.scheduler.run_once()
            task = spiller.cache.pending_spill_view()[0]["tasks"][0]
            # another scheduler wins the pod at the store an instant
            # before our spill pass acts on its (now stale) view
            foreign = next(
                n.metadata.name for n in api.list("Node")
                if shard_of_node(n.metadata.name, 2) == 0
            )
            api.cas_bind("ns", f"{jname}-t0", foreign)
            assert spiller.spillover._spill_one(task) is False
            c = spiller.spillover.counters()
            assert c.get("bound", 0) == 0
            assert c.get("lost-race", 0) == 1
        finally:
            for f in feds:
                f.stop()


class TestGangBroker:
    """Unit pins for the assembly machinery below the end-to-end pin:
    the ledger plan (home-first, claim accounting, sketch gating), the
    sketch solicitation filter, and the broker's discard-whole /
    park-on-unsupported behavior."""

    @staticmethod
    def _task(name, cpu="2", ns="ns"):
        from volcano_tpu.api.job_info import new_task_info

        return new_task_info(build_pod(
            ns, name, "", {"cpu": cpu, "memory": "1Gi"},
        ))

    def _rig(self):
        rig = _FilterRig(n_shards=2)
        rig.own(0)
        return rig

    def test_capacity_sketch_tracks_free_capacity(self):
        rig = self._rig()
        node = _nodes_for_shard(0, 2, 1, cpu="4")[0]
        rig.filter.add_node(node)
        sketch = rig.filter.capacity_sketch()
        assert sketch["freeSlots"] == 1
        assert sketch["maxFreeCpuMilli"] == 4000
        # a 3-cpu resident shrinks the sketch
        rig.filter.add_pod(build_pod(
            "ns", "resident", node.metadata.name,
            {"cpu": "3", "memory": "1Gi"},
        ))
        sketch = rig.filter.capacity_sketch()
        assert sketch["maxFreeCpuMilli"] == 1000
        # foreign nodes never contribute — the sketch is the OWNED slice
        rig.filter.add_node(_nodes_for_shard(1, 2, 1, cpu="64")[0])
        assert rig.filter.capacity_sketch()["maxFreeCpuMilli"] == 1000

    def test_solicitable_shards_prunes_by_sketch(self):
        from volcano_tpu.federation import solicitable_shards

        rec = {
            "shards": {"0": {"holder": "m0"}, "1": {"holder": "m1"},
                       "2": {"holder": "m2"}, "3": {"holder": ""}},
            "stats": {
                "m1": {"sketch": {"freeSlots": 0, "maxFreeCpuMilli": 9000,
                                  "maxFreeMemory": 1 << 40}},
                "m2": {"sketch": {"freeSlots": 3, "maxFreeCpuMilli": 4000,
                                  "maxFreeMemory": 1 << 40}},
            },
        }
        want = self._task("t", cpu="2").resreq
        ok = solicitable_shards(
            rec, 4, want.get("cpu"), want.get("memory"), own_shards={0}
        )
        # m1 has no pod slots left; m2 fits; shard 3 has no holder (no
        # sketch signal) so it stays solicitable — the sketch only
        # prunes, never gates correctness
        assert ok == {2, 3}
        # a claim too big for every sketch prunes down to the unknowns
        big = self._task("big", cpu="8").resreq
        assert solicitable_shards(
            rec, 4, big.get("cpu"), big.get("memory"), own_shards={0}
        ) == {3}

    def test_solicitation_minima_are_component_wise(self):
        """A heterogeneous gang's prune keys are the component-wise
        minima across tasks, NOT one task's full resreq: keying on the
        min-CPU task (which may carry the gang's LARGEST memory ask)
        would prune the only shard able to host a high-cpu/low-memory
        member."""
        from volcano_tpu.federation import solicitable_shards

        # shard 1's slice: lots of cpu, little memory — it can host the
        # gang's big-cpu/small-mem member but not its small-cpu/big-mem
        # member.  Component-wise minima (cpu=1000, mem=1Gi) keep it
        # solicitable; the min-CPU task's FULL resreq (cpu=1000,
        # mem=10Gi) would wrongly prune it.
        rec = {
            "shards": {"1": {"holder": "m1"}},
            "stats": {"m1": {"sketch": {
                "freeSlots": 2, "maxFreeCpuMilli": 16000,
                "maxFreeMemory": 2 << 30,
            }}},
        }
        assert solicitable_shards(
            rec, 2, 1000.0, float(1 << 30), own_shards={0}
        ) == {1}
        assert solicitable_shards(
            rec, 2, 1000.0, float(10 << 30), own_shards={0}
        ) == set()

    def test_plan_fills_home_first_and_accounts_claims(self):
        from volcano_tpu.federation.sketches import entry_from_sketch

        rig = self._rig()
        home = _nodes_for_shard(0, 2, 1, cpu="4")[0]
        rig.filter.add_node(home)
        # foreign capacity arrives as a sketch topNodes entry — the
        # ledger never holds foreign nodes anymore
        foreign = entry_from_sketch({
            "name": "foreign-n0", "freeCpuMilli": 16000.0,
            "freeMemory": float(64 << 30), "slots": 8,
        })
        tasks = [self._task(f"t{i}", cpu="3") for i in range(3)]
        plan = rig.filter.plan_gang_assembly(
            tasks, foreign_entries=[foreign]
        )
        assert len(plan) == 3
        hosts = [h for _t, h in plan]
        # home fits exactly ONE 3-cpu claim (4 cpu total): the plan
        # debits its own claims, so the second task must go foreign
        assert hosts[0] == home.metadata.name
        assert hosts.count(home.metadata.name) == 1
        assert hosts.count("foreign-n0") == 2

    def test_plan_without_foreign_entries_is_home_only(self):
        rig = self._rig()
        rig.filter.add_node(_nodes_for_shard(0, 2, 1, cpu="2")[0])
        # a foreign node on the watch feed is NOT a candidate source:
        # the owned-slice ledger drops it, and with no sketch entries
        # passed in the plan is home-only — one task stays unplaced
        rig.filter.add_node(_nodes_for_shard(1, 2, 1, cpu="16")[0])
        tasks = [self._task(f"t{i}", cpu="2") for i in range(2)]
        plan = rig.filter.plan_gang_assembly(tasks)
        assert len(plan) == 1
        assert plan[0][1] in {
            n.metadata.name for n in _nodes_for_shard(0, 2, 1)
        }

    def test_foreign_entries_respect_shard_gate(self):
        rig = self._rig()
        sol = SketchSolicitor(rig.api, rig.state)
        name = _nodes_for_shard(1, 2, 1)[0].metadata.name
        rec = {
            "shards": {"1": {"holder": "m1"}},
            "stats": {"m1": {"sketch": {"topNodes": [{
                "name": name, "freeCpuMilli": 16000.0,
                "freeMemory": float(64 << 30), "slots": 8,
            }]}}},
        }
        assert len(sol.foreign_entries(rec)) == 1
        # the broker's solicitable_shards gate prunes the whole shard
        # before its topNodes are even materialized
        assert sol.foreign_entries(rec, shard_ok=lambda s: False) == []

    def _broker(self, rig, api=None):
        from volcano_tpu.federation import GangBroker

        return GangBroker(rig.cache, rig.state, rig.filter,
                          api or rig.api, assemble_after=0)

    def _entry(self, tasks, mm):
        return {"job_id": "ns/g", "min_member": mm, "ready": 0,
                "tasks": tasks}

    def test_stale_claim_discards_assembly_whole(self):
        rig = self._rig()
        kube = KubeClient(rig.api)
        for shard, cpu in ((0, "16"), (1, "16")):
            kube.create_node(_nodes_for_shard(shard, 2, 1, cpu=cpu)[0])
        tasks = []
        for i in range(2):
            kube.create_pod(build_pod(
                "ns", f"g-t{i}", "", {"cpu": "2", "memory": "1Gi"},
            ))
            tasks.append(self._task(f"g-t{i}"))
        # a foreign racer wins one member between plan and commit
        rig.api.cas_bind("ns", "g-t1", "raced-elsewhere")
        broker = self._broker(rig)
        assert broker._assemble_one(self._entry(tasks, 2), None) is False
        assert broker.counters() == {"conflict": 1}
        assert broker._backoff.get("ns/g", 0) > 0  # bounded backoff armed
        # discarded WHOLE: the placeable member did not bind alone
        assert rig.api.get("Pod", "ns", "g-t0").spec.node_name == ""

    def test_unsupported_bus_parks_the_broker(self):
        """The pre-v6 degraded mode: an `unsupported` txn_commit result
        (the old-peer abort fallback) parks the broker permanently —
        the honest refusal semantics, with zero binds issued."""
        rig = self._rig()
        kube = KubeClient(rig.api)
        kube.create_node(_nodes_for_shard(0, 2, 1, cpu="16")[0])
        tasks = []
        for i in range(2):
            kube.create_pod(build_pod(
                "ns", f"g-t{i}", "", {"cpu": "2", "memory": "1Gi"},
            ))
            tasks.append(self._task(f"g-t{i}"))

        class PreV6(object):
            def __init__(self, inner):
                self._inner = inner

            def __getattr__(self, name):
                return getattr(self._inner, name)

            def txn_commit(self, binds=()):
                return {"committed": False, "objects": [],
                        "results": ["unsupported"] * len(list(binds)),
                        "reason": "unsupported"}

        broker = self._broker(rig, api=PreV6(rig.api))
        assert broker._assemble_one(self._entry(tasks, 2), None) is False
        assert broker.disabled is True
        assert broker.run_once() == 0  # parked for good
        assert all(
            not p.spec.node_name for p in KubeClient(rig.api).list_pods("ns")
        )

    def test_halted_broker_assembles_nothing_further(self):
        """Crash-mode kill semantics: once ``gang.kill_mid_assembly``
        fires, the member is dead — it must not go on planning or
        committing OTHER gangs later in the same pass (a SIGKILLed
        process would not)."""
        rig = self._rig()
        kube = KubeClient(rig.api)
        kube.create_node(_nodes_for_shard(0, 2, 2, cpu="16")[1])
        entries = []
        for g in ("ga", "gb"):
            tasks = []
            for i in range(2):
                kube.create_pod(build_pod(
                    "ns", f"{g}-t{i}", "", {"cpu": "2", "memory": "1Gi"},
                ))
                tasks.append(self._task(f"{g}-t{i}"))
            entries.append({"job_id": f"ns/{g}", "min_member": 2,
                            "ready": 0, "tasks": tasks})
        rig.state.owns_job_id = lambda _jid: True
        broker = self._broker(rig)
        faults.configure("seed=2;gang.kill_mid_assembly=1:count=1")
        assert broker.run_once(view=entries) == 0
        assert broker._halted is True
        assert all(
            not p.spec.node_name
            for p in KubeClient(rig.api).list_pods("ns")
        ), "a dead member issued binds"
        # and it stays dead across passes
        faults.configure(None)
        assert broker.run_once(view=entries) == 0

    def test_infeasible_counts_and_defers(self):
        rig = self._rig()
        kube = KubeClient(rig.api)
        kube.create_node(_nodes_for_shard(0, 2, 1, cpu="1")[0])
        tasks = []
        for i in range(2):
            kube.create_pod(build_pod(
                "ns", f"g-t{i}", "", {"cpu": "8", "memory": "1Gi"},
            ))
            tasks.append(self._task(f"g-t{i}", cpu="8"))
        broker = self._broker(rig)
        assert broker._assemble_one(self._entry(tasks, 2), None) is False
        assert broker.counters() == {"infeasible": 1}
        assert all(
            not p.spec.node_name for p in KubeClient(rig.api).list_pods("ns")
        )


class TestSingleShardEquivalence:
    WORKLOAD = (("a", 3), ("b", 2), ("c", 4))

    def _seed(self, api):
        kube, vc = KubeClient(api), VolcanoClient(api)
        vc.create_queue(build_queue("default"))
        for i in range(6):
            kube.create_node(build_node(
                f"n{i}", {"cpu": "8", "memory": "64Gi"},
                labels={"slot": f"s{i}"},
            ))
        for name, replicas in self.WORKLOAD:
            vc.create_pod_group(build_pod_group("ns", name, replicas))
            for i in range(replicas):
                kube.create_pod(build_pod(
                    "ns", f"{name}-t{i}", "",
                    {"cpu": "1", "memory": "1Gi"}, group=name,
                    selector={"slot": f"s{(i * 2) % 6}"},
                ))
        return kube

    def test_shards_1_bindings_bit_identical(self, tmp_path):
        # plain scheduler
        api_plain = APIServer()
        kube_plain = self._seed(api_plain)
        cache = SchedulerCache(
            client=SchedulerClient(api_plain), scheduler_name="volcano-tpu"
        )
        sched = Scheduler(cache, scheduler_conf_path=_conf(tmp_path))
        cache.run()
        for _ in range(3):
            sched.run_once()
        plain = {
            p.metadata.name: p.spec.node_name
            for p in kube_plain.list_pods("ns")
        }
        assert all(plain.values()), plain

        # single-shard federation over an identical store
        api_fed = APIServer()
        kube_fed = self._seed(api_fed)
        fed = _make_fed(api_fed, "solo", 1, _conf(tmp_path, "fedconf"))
        try:
            assert fed.wait_owned(10.0)
            for _ in range(3):
                fed.scheduler.run_once()
            feder = {
                p.metadata.name: p.spec.node_name
                for p in kube_fed.list_pods("ns")
            }
        finally:
            fed.stop()
        assert feder == plain

    def test_shards_1_replay_verifies(self, tmp_path):
        """trace.replay.verify over a cycle recorded INSIDE single-shard
        federation mode: replaying the captured packed session through
        the kernel reproduces the recorded bindings exactly — federation
        plumbing adds nothing to the device path."""
        jdir = str(tmp_path / "journal")
        api = APIServer()
        self._seed(api)
        trace.enable(jdir, snapshot_every=1)
        fed = _make_fed(api, "solo", 1, _conf(tmp_path))
        try:
            assert fed.wait_owned(10.0)
            fed.scheduler.run_once()
        finally:
            fed.stop()
            trace.disable()
        result = trace.replay.verify(jdir, executor="jax")
        assert result.match, result.summary()


class FederationCluster:
    """Three federated members over one real TCP bus, with a
    store-truth audit watch (dup-bind detection) — the ChaosCluster
    pattern, federated."""

    def __init__(self, tmp_path, name, n_shards=3, n_nodes=9,
                 node_cpu="4", ttl=0.8):
        ttl *= _TIME_SCALE
        self.api = APIServer()
        self.bus = BusServer(self.api).start()
        self.kube = KubeClient(self.api)
        self.vc = VolcanoClient(self.api)
        self.vc.create_queue(build_queue("default"))
        self.n_shards = n_shards
        self.ttl = ttl
        made, k = 0, 0
        while made < n_nodes:
            nname = f"n{k:03d}"
            k += 1
            self.kube.create_node(build_node(
                nname, {"cpu": node_cpu, "memory": "64Gi"}
            ))
            made += 1
        self.bound = {}
        self.rebinds = []
        self.api.watch("Pod", self._audit, send_initial=False)
        conf = tmp_path / f"{name}-conf.yaml"
        conf.write_text(CONF)
        self.remotes = []
        self.feds = []
        for i in range(n_shards):
            remote = RemoteAPIServer(
                f"tcp://127.0.0.1:{self.bus.port}", timeout=5.0
            )
            assert remote.wait_ready(10.0)
            self.remotes.append(remote)
            fed = FederatedScheduler(
                remote, f"m{i}", n_shards,
                scheduler_conf_path=str(conf),
                lease_duration=ttl, lease_retry_period=0.04,
                spill_after=1, gang_assemble_after=1,
            ).start()
            self.feds.append(fed)

    def _audit(self, event, old, new):
        if event not in (ADDED, MODIFIED) or new is None:
            return
        if not new.spec.node_name:
            return
        key = f"{new.metadata.namespace}/{new.metadata.name}"
        prev = self.bound.get(key)
        if prev is None:
            self.bound[key] = new.spec.node_name
        elif prev != new.spec.node_name:
            self.rebinds.append((key, prev, new.spec.node_name))

    def submit(self, name, replicas=1, cpu="1", min_member=None):
        self.vc.create_pod_group(build_pod_group(
            "ns", name, replicas if min_member is None else min_member
        ))
        for i in range(replicas):
            self.kube.create_pod(build_pod(
                "ns", f"{name}-t{i}", "", {"cpu": cpu, "memory": "1Gi"},
                group=name,
            ))

    def cycle(self):
        for fed in self.feds:
            if fed._crashed:
                continue
            try:
                fed.scheduler.run_once()
            except Exception:  # noqa: BLE001 — daemon loops log + retry
                pass

    def all_placed(self):
        pods = self.kube.list_pods("ns")
        return bool(pods) and all(p.spec.node_name for p in pods)

    def live_holders(self):
        rec = read_shard_map(self.api) or {}
        now = time.time()
        out = {}
        for i, e in rec.get("shards", {}).items():
            holder = e.get("holder") or ""
            expired = now - float(e.get("renewTime", 0.0)) > float(
                e.get("leaseDurationSeconds", 0.0) or 0.0
            )
            out[i] = holder if holder and not expired else None
        return out

    def close(self):
        for fed in self.feds:
            fed.stop()
        for remote in self.remotes:
            remote.close()
        self.bus.stop()


class TestFederationChaosSmoke:
    def test_shard_kill_rebalances_no_dup_no_loss(self, tmp_path):
        """Tier-1 acceptance: SIGKILL one of three federated schedulers
        mid-cycle via the fault plane (``shard.kill``); the orphaned
        slices are re-owned within one lease TTL, every job still binds
        exactly once, and the run is policy-equivalent."""
        cluster = FederationCluster(tmp_path, "kill", ttl=0.8)
        try:
            for fed in cluster.feds:
                assert fed.wait_owned(15.0)
            assert _wait(
                lambda: sum(
                    len(f.state.owned()) for f in cluster.feds
                ) == 3,
                timeout=10.0,
            )
            for i in range(6):
                cluster.submit(f"pre{i}", replicas=1)
            assert _wait(
                lambda: (cluster.cycle() or True) and cluster.all_placed(),
                timeout=30.0, interval=0.05,
            )
            # the deterministic kill: first post-cycle evaluation fires
            faults.configure("seed=9;shard.kill=1:count=1")
            cluster.cycle()
            faults.configure(None)
            dead = [f for f in cluster.feds if f._crashed]
            assert len(dead) == 1, "shard.kill should take exactly one"
            dead_ident = dead[0].identity
            expire_by = time.monotonic() + cluster.ttl
            # work submitted while the member is down — its home-shard
            # jobs must be absorbed along with its nodes
            for i in range(6):
                cluster.submit(f"post{i}", replicas=1)
            # orphaned slices re-owned within one TTL of lease expiry
            assert _wait(
                lambda: (cluster.cycle() or True) and all(
                    h is not None and h != dead_ident
                    for h in cluster.live_holders().values()
                ),
                timeout=cluster.ttl * 2 + 3.0, interval=0.05,
            ), f"holders: {cluster.live_holders()}"
            absorb_lag = time.monotonic() - expire_by
            assert absorb_lag <= cluster.ttl + 1.0 * _TIME_SCALE, (
                f"absorb took {absorb_lag:.2f}s past expiry "
                f"(TTL {cluster.ttl}s)"
            )
            assert _wait(
                lambda: (cluster.cycle() or True) and cluster.all_placed(),
                timeout=30.0, interval=0.05,
            ), "jobs lost after shard kill"
            assert cluster.rebinds == [], (
                f"duplicate binds: {cluster.rebinds}"
            )
            assert len(cluster.bound) == 12  # zero lost
            report = verify_federation(cluster.api, cluster.n_shards)
            assert report["ok"], report["violations"]
            # survivors really did absorb: the dead member's cache slice
            # now lives in a survivor
            survivor_nodes = set()
            for fed in cluster.feds:
                if not fed._crashed:
                    survivor_nodes |= set(fed.cache.nodes)
            assert len(survivor_nodes) == 9
        finally:
            cluster.close()


class TestGangAssemblyChaos:
    """The SIGKILL-mid-assembly drill: a member dies between building a
    cross-shard gang assembly and committing it — the widest window in
    which a non-atomic protocol would strand a partial gang.  The pin:
    the orphaned assembly is discarded whole (zero binds — the
    transaction was never issued) or committed whole (txn atomicity),
    NEVER partial; survivors absorb the dead member's slices within one
    lease TTL and the gang still assembles, policy-equivalent."""

    def test_shard_kill_mid_assembly_discards_or_commits_whole(
        self, tmp_path
    ):
        cluster = FederationCluster(tmp_path, "midkill", ttl=0.8)
        try:
            for fed in cluster.feds:
                assert fed.wait_owned(15.0)
            assert _wait(
                lambda: sum(
                    len(f.state.owned()) for f in cluster.feds
                ) == 3,
                timeout=10.0,
            )
            # a gang larger than ANY single shard: tasks take a full
            # node each, minMember = (biggest shard's node count) + 1 —
            # no slice can ever host it alone (not even a survivor that
            # absorbed the dead member's home shard), so ANY full
            # placement necessarily spans ≥ 2 shards
            per_shard = {}
            for node in cluster.api.list("Node"):
                s = shard_of_node(node.metadata.name, cluster.n_shards)
                per_shard[s] = per_shard.get(s, 0) + 1
            home = min(per_shard, key=lambda s: (per_shard[s], s))
            mm = max(per_shard.values()) + 1
            jname = _names_for_shard(
                home, cluster.n_shards, 1, prefix="bigg"
            )[0]
            cluster.submit(jname, replicas=mm, cpu="4")
            gang_keys = [f"ns/{jname}-t{i}" for i in range(mm)]

            def gang_bound():
                return sum(
                    1 for p in cluster.kube.list_pods("ns")
                    if f"ns/{p.metadata.name}" in
                    {k for k in gang_keys} and p.spec.node_name
                )

            # the deterministic kill: the first assembly attempt dies
            # between planning and committing
            faults.configure("seed=3;gang.kill_mid_assembly=1:count=1")
            assert _wait(
                lambda: (cluster.cycle() or True)
                and any(f._crashed for f in cluster.feds),
                timeout=20.0 * _TIME_SCALE, interval=0.05,
            ), "mid-assembly kill never fired"
            faults.configure(None)
            dead = [f for f in cluster.feds if f._crashed]
            assert len(dead) == 1
            # the orphaned assembly was discarded WHOLE: the dying
            # member never issued the transaction, so zero gang binds
            assert gang_bound() == 0, (
                "partial gang escaped a mid-assembly crash"
            )
            # survivors absorb within one TTL of expiry and the gang
            # still assembles — whole, never partial, at every sample
            dead_ident = dead[0].identity

            def recovered_and_assembled():
                cluster.cycle()
                bound = gang_bound()
                assert bound == 0 or bound >= mm, (
                    f"partial gang observed during recovery: "
                    f"{bound}/{mm} bound"
                )
                holders = cluster.live_holders()
                return bound >= mm and all(
                    h is not None and h != dead_ident
                    for h in holders.values()
                )

            assert _wait(
                recovered_and_assembled,
                timeout=cluster.ttl * 3 + 30.0, interval=0.05,
            ), (
                f"gang never reassembled after the kill "
                f"(bound {gang_bound()}/{mm})"
            )
            assert cluster.rebinds == [], cluster.rebinds
            report = verify_federation(cluster.api, cluster.n_shards)
            assert report["ok"], report["violations"]
            assert report["checked"]["cross_shard_gangs"] >= 1, (
                "the gang should span shards — its home could not "
                "fit minMember"
            )
        finally:
            cluster.close()


@pytest.mark.slow
class TestFederationSoak:
    def test_rolling_kills_and_rejoins(self, tmp_path):
        """Slow soak: rolling workload over a 3-member federation while
        members are killed and replaced; ends converged, no dup binds,
        policy-equivalent."""
        cluster = FederationCluster(tmp_path, "soak", ttl=0.6)
        conf = str(tmp_path / "soak-conf.yaml")
        try:
            for fed in cluster.feds:
                assert fed.wait_owned(15.0)
            submitted = 0
            for round_i in range(3):
                for j in range(4):
                    # min_member=1: a home shard that fills up must be
                    # escapable via spillover, and gangs deliberately
                    # never spill below their minimum (the known-gaps
                    # restriction) — a full-shard gang would starve by
                    # design, which is not what this soak probes
                    cluster.submit(f"r{round_i}x{j}", replicas=2,
                                   min_member=1)
                    submitted += 2
                assert _wait(
                    lambda: (cluster.cycle() or True)
                    and cluster.all_placed(),
                    timeout=40.0, interval=0.05,
                ), f"round {round_i} never converged"
                victim = round_i % 3
                cluster.feds[victim].crash()
                assert _wait(
                    lambda: (cluster.cycle() or True) and all(
                        h is not None
                        for h in cluster.live_holders().values()
                    ),
                    timeout=cluster.ttl * 3 + 3.0, interval=0.05,
                )
                # replacement member joins under a fresh identity
                remote = RemoteAPIServer(
                    f"tcp://127.0.0.1:{cluster.bus.port}", timeout=5.0
                )
                assert remote.wait_ready(10.0)
                cluster.remotes.append(remote)
                fed = FederatedScheduler(
                    remote, f"m{3 + round_i}", cluster.n_shards,
                    scheduler_conf_path=conf,
                    lease_duration=cluster.ttl, lease_retry_period=0.04,
                    spill_after=1,
                ).start()
                cluster.feds[victim] = fed
                assert fed.wait_owned(15.0)
            assert _wait(
                lambda: (cluster.cycle() or True) and cluster.all_placed(),
                timeout=40.0, interval=0.05,
            )
            assert cluster.rebinds == []
            assert len(cluster.bound) == submitted
            report = verify_federation(cluster.api, cluster.n_shards)
            assert report["ok"], report["violations"]
        finally:
            cluster.close()


class TestVtctlShards:
    def test_shards_output_byte_identical_over_backends(self, tmp_path):
        """`vtctl shards` renders from the shard-map ConfigMap alone, so
        the same store state renders identically in-process and over
        --bus."""
        import io
        import json as _json

        from volcano_tpu.apis import core
        from volcano_tpu.cli.vtctl import main as vtctl_main
        from volcano_tpu.federation.leases import (
            SHARD_MAP_KEY,
            SHARD_MAP_NAME,
        )

        api = APIServer()
        rec = {
            "nShards": 2,
            "autoscale": {"enabled": True, "target": 2,
                          "lastChange": 1000.0, "direction": "up",
                          "reason": "p99=900ms pending=40 members=1",
                          "decisions": 1},
            "members": {"m0": {"heartbeat": 1000.0,
                               "leaseDurationSeconds": 2.0}},
            "shards": {
                "0": {"holder": "m0", "renewTime": 1000.0,
                      "leaseDurationSeconds": 2.0},
                "1": {"holder": "", "renewTime": 0.0,
                      "leaseDurationSeconds": 2.0},
            },
            "stats": {"m0": {"nodesOwned": 4, "rebalances": 1,
                             "spillover": {"bound": 2, "conflict": 1},
                             "sketch": {"freeCpuMilli": 16000,
                                        "freeSlots": 4},
                             "sketchChecks": {"stale": 1, "verified": 3},
                             "gangAssembly": {"committed": 1,
                                              "conflict": 2}}},
        }
        api.create(core.ConfigMap(
            metadata=core.ObjectMeta(name=SHARD_MAP_NAME,
                                     namespace="volcano-system"),
            data={SHARD_MAP_KEY: _json.dumps(rec)},
        ))
        direct = io.StringIO()
        assert vtctl_main(["shards"], api=api, out=direct) == 0
        bus = BusServer(api).start()
        try:
            remote = io.StringIO()
            assert vtctl_main(
                ["--bus", f"tcp://127.0.0.1:{bus.port}", "shards"],
                out=remote,
            ) == 0
        finally:
            bus.stop()
        assert direct.getvalue() == remote.getvalue()
        assert "m0" in direct.getvalue()
        assert "<unheld>" in direct.getvalue()
        # the gang-assembly line renders from the stats blob alone
        assert "gang-assembly: committed=1 conflict=2" in direct.getvalue()
        # sketch freshness: age measured against the newest renew tick
        # ON the map (stored fields only, part of the byte-identity
        # assertion above), never a call-time clock
        assert "sketch: slots=4 topNodes=0 age=0s/ttl=2s (fresh)" \
            in direct.getvalue()
        assert "sketch-checks: stale=1 verified=3" in direct.getvalue()
        # the autoscale line renders from stored fields alone — it is
        # part of the byte-identity assertion above
        assert "Autoscale:          target 2 (up:" in direct.getvalue()

    def test_shards_without_map(self):
        import io

        from volcano_tpu.cli.vtctl import main as vtctl_main

        out = io.StringIO()
        assert vtctl_main(["shards"], api=APIServer(), out=out) == 1
        assert "no shard map" in out.getvalue()


class TestPolicyChecker:
    def test_flags_overcommit_and_partial_gang(self):
        api = APIServer()
        kube, vc = KubeClient(api), VolcanoClient(api)
        kube.create_node(build_node("n1", {"cpu": "2", "memory": "4Gi"}))
        # overcommit: two 2-cpu pods on a 2-cpu node
        for i in range(2):
            kube.create_pod(build_pod(
                "ns", f"o{i}", "n1", {"cpu": "2", "memory": "1Gi"},
            ))
        # partial gang: 1 of 3 bound, 2 pending
        vc.create_pod_group(build_pod_group("ns", "g", 3))
        kube.create_pod(build_pod(
            "ns", "g-t0", "n1", {"cpu": "0", "memory": "0"}, group="g"))
        for i in (1, 2):
            kube.create_pod(build_pod(
                "ns", f"g-t{i}", "", {"cpu": "0", "memory": "0"},
                group="g"))
        report = verify_federation(api, 2)
        assert not report["ok"]
        kinds = "\n".join(report["violations"])
        assert "overcommitted" in kinds
        assert "partially placed" in kinds

    def test_clean_store_passes(self):
        api = APIServer()
        kube = KubeClient(api)
        kube.create_node(build_node("n1", {"cpu": "4", "memory": "8Gi"}))
        kube.create_pod(build_pod(
            "ns", "p", "n1", {"cpu": "1", "memory": "1Gi"}))
        assert verify_federation(api, 1)["ok"]


class TestSketchSpillCandidates:
    """The sketch is the ONLY foreign state: the owner's published
    capacity sketch shrinks and grows with its bound pods, and a
    foreign member solicits spill candidates from that sketch alone —
    the per-node foreign mirror no longer exists."""

    def test_sketch_tracks_bound_and_released_capacity(self):
        from volcano_tpu.api.job_info import new_task_info

        api = APIServer()
        # the OWNER of shard 1 maintains the owned-slice ledger the
        # sketch is cut from
        owner_state = ShardState(2)
        owner_state.acquire(1)
        owner = ShardInformerFilter(
            SchedulerCache(scheduler_name="volcano-tpu"), owner_state
        )
        node = _nodes_for_shard(1, 2, 1, cpu="4")[0]
        KubeClient(api).create_node(node)  # store truth for verify_node
        owner.add_node(node)
        pod = build_pod("ns", "p1", node.metadata.name,
                        {"cpu": "3", "memory": "1Gi"})
        owner.add_pod(pod)

        def rec():
            # what the lease heartbeat would publish on the shard map
            return {"shards": {"1": {"holder": "m1"}},
                    "stats": {"m1": {"sketch": owner.capacity_sketch()}}}

        # a FOREIGN member (owning shard 0) solicits from the sketch
        state = ShardState(2)
        state.acquire(0)
        sol = SketchSolicitor(api, state)
        big = new_task_info(build_pod("ns", "want", "",
                                      {"cpu": "2", "memory": "1Gi"}))
        # 3 of 4 cpus used: a 2-cpu task no longer fits by the sketch
        assert sol.spill_candidates(big, rec()) == []
        done = pod.clone()
        done.status.phase = "Succeeded"
        owner.update_pod(pod, done)
        assert sol.spill_candidates(big, rec()) == [node.metadata.name]
        # bind-time truth: the node exists and is schedulable
        assert sol.verify_node(node.metadata.name)
        assert sol.counters() == {"verified": 1}
        # a vanished node reads stale — a pruning event, not an error
        assert not sol.verify_node("no-such-node")
        assert sol.counters() == {"verified": 1, "stale": 1}
