"""Incremental-session plane (ISSUE 18).

The contracts under test:

* **Binding equivalence (the property test)** — restricted sessions
  (O(pending) micro-sessions over the share ledger's schedulable set)
  bind EXACTLY what full sessions bind, across randomized churn:
  bind/complete/join interleavings with gang and non-gang jobs mixed,
  with the shadow full-session cross-check running on every cycle
  (``shadow_every=1``) and recording zero divergence.
* **Ledger exactness** — the incrementally-maintained per-queue /
  per-namespace totals equal a from-scratch sweep of the resident jobs
  bit-for-bit after arbitrary churn (the property that lets proportion
  and DRF seed from the ledger instead of sweeping).
* **The checker catches a broken ledger** — a planted read-time
  corruption (``ShareLedger.plant_divergence``) makes the very next
  shadow cross-check flag a divergence (and raise in strict mode);
  clearing the plant heals the plane and the skipped work lands on the
  following cycle.
* **O(1) wake gate** — an idle wake (capacity freed with nothing
  schedulable) opens NO session: the loop consults the ledger's
  schedulable counter instead of rescanning every resident job, and a
  subsequent real arrival still binds through the event wake.
* **Metrics** — the four incremental-plane series export with their
  pinned label vocabularies: ``volcano_resident_jobs`` /
  ``volcano_schedulable_jobs`` gauges,
  ``volcano_session_scope_total{mode}``, and
  ``volcano_share_ledger_drift_checks_total{result}``.
* **Federation mix** — restricted sessions stay divergence-free with
  spillover and the cross-shard gang broker active on a 2-shard
  federation (the ISSUE's "gang + spillover mixed" leg).
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from volcano_tpu.api import TaskStatus
from volcano_tpu.api.resource import empty_resource
from volcano_tpu.cache import SchedulerCache
from volcano_tpu.client import APIServer, KubeClient, SchedulerClient, VolcanoClient
from volcano_tpu.incremental import subgraph
from volcano_tpu.incremental.shares import (
    PLANT_DROP_SCHEDULABLE,
    PLANT_INFLATE_ALLOCATED,
)
from volcano_tpu.metrics import metrics
from volcano_tpu.scheduler.scheduler import Scheduler

from tests.builders import build_node, build_pod, build_pod_group, build_queue

CONF = """
actions: "enqueue, jax-allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def _wait(pred, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _counter(suffix: str, **labels) -> float:
    want = tuple(sorted(labels.items()))
    with metrics.registry._lock:
        return sum(
            v for (name, lbl), v in metrics.registry._counters.items()
            if name.endswith(suffix) and (not want or lbl == want)
        )


class IncCluster:
    """One scheduler over an in-process store, with the restricted
    incremental-session plane switchable per instance.  Restricted
    instances shadow-check EVERY cycle (``shadow_every=1``) — the test
    posture the ISSUE pins, vs sampled in production."""

    def __init__(self, tmp_path, name, restricted=True, shadow_every=1,
                 n_nodes=6, node_cpu="32", period=30.0):
        self.api = APIServer()
        self.kube = KubeClient(self.api)
        self.vc = VolcanoClient(self.api)
        self.vc.create_queue(build_queue("default"))
        self.n_nodes = n_nodes
        for i in range(n_nodes):
            self.kube.create_node(build_node(
                f"n{i}", {"cpu": node_cpu, "memory": "64Gi"},
            ))
        self.cache = SchedulerCache(
            client=SchedulerClient(self.api), scheduler_name="volcano-tpu",
        )
        conf = tmp_path / f"{name}-conf.yaml"
        conf.write_text(CONF)
        self.scheduler = Scheduler(
            self.cache, scheduler_conf_path=str(conf), period=period,
            micro_cycles=True, micro_debounce_ms=5.0,
            restricted_sessions=restricted, shadow_every=shadow_every,
        )
        self.cache.run()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self.scheduler.run, name="inc-scheduler", daemon=True
        )
        self._thread.start()
        assert _wait(lambda: self.scheduler.full_cycles_run >= 1)
        return self

    def submit(self, name, replicas=1, cpu="1", gang=False):
        self.vc.create_pod_group(
            build_pod_group("ns", name, replicas if gang else 1)
        )
        for i in range(replicas):
            self.kube.create_pod(build_pod(
                "ns", f"{name}-t{i}", "", {"cpu": cpu, "memory": "1Gi"},
                group=name,
            ))

    def complete(self, name, replicas):
        """Job departure, loadgen-reaper style: pods then the group."""
        for i in range(replicas):
            self.kube.delete_pod("ns", f"{name}-t{i}")
        self.vc.delete_pod_group("ns", name)

    def binding_map(self):
        return {
            f"{p.metadata.namespace}/{p.metadata.name}": p.spec.node_name
            for p in self.kube.list_pods("ns")
            if p.spec.node_name
        }

    def all_placed(self):
        pods = self.kube.list_pods("ns")
        return all(p.spec.node_name for p in pods)

    def close(self):
        self.scheduler.stop()
        if self._thread is not None:
            self._thread.join(timeout=10)
            assert not self._thread.is_alive()
        self.cache.stop_commit_plane()


class TestRestrictedEquivalence:
    @pytest.mark.parametrize("seed", [11, 29, 47])
    def test_randomized_churn_binding_identical(self, tmp_path, seed):
        """Drive a restricted cluster and a full cluster through the
        same randomized op sequence — joins (gang and non-gang),
        per-round cycles, completions of previously-bound jobs — and
        require identical binding maps at every step.  Every restricted
        cycle is also shadow cross-checked against a full session over
        the same snapshot: the zero-divergence count is the per-cycle
        equivalence evidence, the cross-cluster map compare the
        end-to-end one."""
        restricted = IncCluster(tmp_path, f"re-{seed}", restricted=True)
        full = IncCluster(tmp_path, f"fu-{seed}", restricted=False)
        rng = random.Random(seed)
        live = []  # (name, replicas) submitted and expected bound
        try:
            for round_i in range(6):
                for _ in range(rng.randint(1, 3)):
                    name = f"j{round_i}-{rng.randrange(1 << 16):04x}"
                    replicas = rng.randint(1, 3)
                    gang = rng.random() < 0.4
                    cpu = rng.choice(["500m", "1", "2"])
                    for c in (restricted, full):
                        c.submit(name, replicas=replicas, cpu=cpu, gang=gang)
                    live.append((name, replicas))
                if len(live) > 2 and rng.random() < 0.6:
                    name, replicas = live.pop(rng.randrange(len(live)))
                    for c in (restricted, full):
                        c.complete(name, replicas)
                restricted.scheduler.run_once(trigger="task")
                full.scheduler.run_once()
                assert _wait(
                    lambda: restricted.binding_map() == full.binding_map()
                    and restricted.all_placed() and full.all_placed(),
                    timeout=15.0,
                ), (
                    f"round {round_i}: restricted={restricted.binding_map()} "
                    f"full={full.binding_map()}"
                )
            s = restricted.scheduler
            assert s.restricted_cycles_run == 6
            # shadow_every=1: every restricted cycle was cross-checked
            assert s.shadow_checks_run == s.restricted_cycles_run
            assert s.shadow_divergences == 0
            # the gauges track the ledger's truth after every cycle
            resident, schedulable = restricted.cache.ledger_counts()
            assert resident == len(live)
            assert schedulable == 0
        finally:
            restricted.close()
            full.close()

    def test_ledger_totals_match_full_sweep_after_churn(self, tmp_path):
        """The exactness claim behind seeding proportion/DRF from the
        ledger: after arbitrary churn, the incremental per-queue and
        per-namespace totals equal a from-scratch sweep of the resident
        JobInfos — equality, not tolerance."""
        cluster = IncCluster(tmp_path, "sweep", restricted=True)
        rng = random.Random(7)
        live = []
        try:
            for round_i in range(5):
                name = f"s{round_i}"
                replicas = rng.randint(1, 4)
                cluster.submit(name, replicas=replicas,
                               cpu=rng.choice(["1", "2"]),
                               gang=rng.random() < 0.5)
                live.append((name, replicas))
                cluster.scheduler.run_once(trigger="task")
                if rng.random() < 0.5 and len(live) > 1:
                    gone, n = live.pop(0)
                    cluster.complete(gone, n)
            cache = cluster.cache
            with cache._mutex:
                seed = cache.share_ledger.seed()
                # the sweep the plugins used to do on every open
                want_q, want_ns = {}, {}
                for job in cache.jobs.values():
                    if job.pod_group is None:
                        continue
                    alloc = job.allocated.clone()
                    req = job.allocated.clone()
                    pending = job.task_status_index.get(TaskStatus.Pending)
                    for t in (pending or {}).values():
                        req.add(t.resreq)
                    qa, qr = want_q.setdefault(
                        job.queue, (empty_resource(), empty_resource())
                    )
                    qa.add(alloc)
                    qr.add(req)
                    want_ns.setdefault(
                        job.namespace, empty_resource()
                    ).add(alloc)
            assert set(seed.queues) == set(want_q)
            for q, (alloc, req) in want_q.items():
                assert seed.queues[q][0] == alloc, f"queue {q} allocated"
                assert seed.queues[q][1] == req, f"queue {q} request"
            assert set(seed.namespaces) == set(want_ns)
            for ns, alloc in want_ns.items():
                assert seed.namespaces[ns] == alloc, f"namespace {ns}"
        finally:
            cluster.close()


class TestDivergencePlant:
    def test_planted_ledger_corruption_is_flagged_and_heals(self, tmp_path):
        """A ledger that UNDER-reports schedulable work (the plant drops
        one uid at read time) makes the restricted session skip a job
        the shadow full session binds — the cross-check must flag it.
        Clearing the plant heals the plane: the next cycle binds the
        skipped job with the cross-check green again."""
        cluster = IncCluster(tmp_path, "plant", restricted=True)
        div_before = _counter(
            "share_ledger_drift_checks_total", result="divergence"
        )
        ok_before = _counter("share_ledger_drift_checks_total", result="ok")
        try:
            cluster.submit("p0", replicas=2)
            cluster.cache.share_ledger.plant_divergence(
                PLANT_DROP_SCHEDULABLE
            )
            cluster.scheduler.run_once(trigger="task")
            assert cluster.scheduler.shadow_divergences == 1
            assert _counter(
                "share_ledger_drift_checks_total", result="divergence"
            ) == div_before + 1
            # the restricted session never saw p0, so nothing bound
            assert cluster.binding_map() == {}
            cluster.cache.share_ledger.clear_plant()
            cluster.scheduler.run_once(trigger="task")
            assert _wait(cluster.all_placed, timeout=10.0)
            assert cluster.scheduler.shadow_divergences == 1
            assert _counter(
                "share_ledger_drift_checks_total", result="ok"
            ) == ok_before + 1
        finally:
            cluster.close()

    def test_strict_mode_raises_on_divergence(self, tmp_path):
        cluster = IncCluster(tmp_path, "strict", restricted=True)
        cluster.scheduler.shadow_strict = True
        try:
            cluster.submit("x0", replicas=1)
            cluster.cache.share_ledger.plant_divergence(
                PLANT_DROP_SCHEDULABLE
            )
            with pytest.raises(subgraph.ShadowDivergence):
                cluster.scheduler.run_once(trigger="task")
        finally:
            cluster.close()

    def test_inflated_allocated_plant_corrupts_the_seed(self, tmp_path):
        """The other plant kind: an inflated per-queue allocated total
        shows up in the seed the sessions consume — and only there (the
        stored ledger stays exact, so clearing heals it)."""
        cluster = IncCluster(tmp_path, "inflate", restricted=True)
        try:
            cluster.submit("q0", replicas=1)
            cluster.scheduler.run_once(trigger="task")
            ledger = cluster.cache.share_ledger
            clean = ledger.seed()
            ledger.plant_divergence(PLANT_INFLATE_ALLOCATED)
            planted = ledger.seed()
            q = sorted(clean.queues)[0]
            assert planted.queues[q][0] != clean.queues[q][0]
            ledger.clear_plant()
            healed = ledger.seed()
            assert healed.queues[q][0] == clean.queues[q][0]
        finally:
            cluster.close()


class TestWakeGate:
    def test_idle_wake_opens_no_session(self, tmp_path):
        """A capacity-freed wake with nothing schedulable must cost
        ZERO sessions: the loop answers ``has_schedulable_pending``
        from the ledger's O(1) counter and goes back to sleep.  A real
        arrival afterwards proves the loop is still event-driven, not
        wedged."""
        cluster = IncCluster(tmp_path, "wake", period=30.0).start()
        try:
            cluster.submit("w0", replicas=2)
            assert _wait(cluster.all_placed, timeout=10.0)
            # quiesce: the submit's own micro-cycle(s) finish counting
            settle = time.monotonic()
            last = -1
            while time.monotonic() - settle < 5.0:
                n = cluster.scheduler.sessions_opened
                if n != last:
                    last, settle = n, time.monotonic()
                elif time.monotonic() - settle >= 0.5:
                    break
                time.sleep(0.05)
            assert not cluster.cache.has_schedulable_pending()
            opened = cluster.scheduler.sessions_opened
            # a bound pod departs: capacity freed, a "node" wake — but
            # nothing is pending, so no session may open on it
            cluster.kube.delete_pod("ns", "w0-t1")
            time.sleep(1.0)
            assert cluster.scheduler.sessions_opened == opened, (
                "idle capacity-freed wake opened a session"
            )
            # the gate only skips EMPTY wakes: a real arrival binds
            # promptly through the same event plumbing
            cluster.submit("w1", replicas=1)
            assert _wait(cluster.all_placed, timeout=10.0)
            assert cluster.scheduler.sessions_opened > opened
        finally:
            cluster.close()


class TestIncrementalMetrics:
    def test_export_shapes_and_label_vocabularies(self):
        """The four incremental-plane series render in exposition
        format with their pinned label sets."""
        metrics.registry.reset()
        try:
            metrics.update_resident_jobs(1000000)
            metrics.update_schedulable_jobs(42)
            metrics.register_session_scope("full")
            metrics.register_session_scope("restricted")
            metrics.register_session_scope("restricted")
            metrics.register_share_ledger_drift_check("ok")
            metrics.register_share_ledger_drift_check("divergence")
            out = metrics.registry.render()
            assert "volcano_resident_jobs 1000000" in out
            assert "volcano_schedulable_jobs 42" in out
            assert 'volcano_session_scope_total{mode="full"} 1' in out
            assert 'volcano_session_scope_total{mode="restricted"} 2' in out
            assert (
                'volcano_share_ledger_drift_checks_total{result="ok"} 1'
                in out
            )
            assert (
                'volcano_share_ledger_drift_checks_total{result="divergence"} 1'
                in out
            )
        finally:
            metrics.registry.reset()

    def test_gauges_track_ledger_after_each_cycle(self, tmp_path):
        cluster = IncCluster(tmp_path, "gauge", restricted=True)
        scope_before = _counter("session_scope_total", mode="restricted")
        try:
            cluster.submit("g0", replicas=2)
            cluster.submit("g1", replicas=1)
            cluster.scheduler.run_once(trigger="task")
            assert _wait(cluster.all_placed, timeout=10.0)
            resident, schedulable = cluster.cache.ledger_counts()
            assert resident == 2
            with metrics.registry._lock:
                gauges = {
                    name: v
                    for (name, _l), v in metrics.registry._gauges.items()
                }
            assert gauges.get("volcano_resident_jobs") == resident
            assert gauges.get("volcano_schedulable_jobs") == schedulable
            assert _counter(
                "session_scope_total", mode="restricted"
            ) == scope_before + 1
        finally:
            cluster.close()


class TestRestrictedFederation:
    def test_spillover_and_gang_mix_stays_divergence_free(self, tmp_path):
        """Restricted sessions on BOTH members of a 2-shard federation,
        every cycle shadow-checked, while the run exercises the two
        cross-shard paths at once: a gang that must assemble across
        shards (home fits one member) and singles that must spill (home
        capacity consumed).  Everything binds, no partial gang is ever
        observable, the policy checker passes, and neither member
        records a single divergence."""
        from volcano_tpu.federation import (
            FederatedScheduler,
            verify_federation,
        )
        from volcano_tpu.federation.sharding import home_shard, shard_of_node

        api = APIServer()
        kube, vc = KubeClient(api), VolcanoClient(api)
        vc.create_queue(build_queue("default"))

        def nodes_for(shard, count, cpu):
            out, k = [], 0
            while len(out) < count:
                name = f"n{k:03d}"
                k += 1
                if shard_of_node(name, 2) == shard:
                    out.append(build_node(
                        name, {"cpu": cpu, "memory": "64Gi"},
                    ))
            return out

        # shard 1 is nearly full: one 2-cpu node.  shard 0 has room.
        for node in nodes_for(0, 3, "16") + nodes_for(1, 1, "2"):
            kube.create_node(node)
        conf = tmp_path / "fed-conf.yaml"
        conf.write_text(CONF)
        feds = [
            FederatedScheduler(
                api, f"s{i}", 2, scheduler_conf_path=str(conf),
                lease_duration=0.8, lease_retry_period=0.04,
                spill_after=1, gang_broker=True, gang_assemble_after=1,
            ).start()
            for i in range(2)
        ]
        try:
            for f in feds:
                assert f.wait_owned(10.0)
            assert _wait(
                lambda: sum(len(f.state.owned()) for f in feds) == 2
            )
            for f in feds:
                f.scheduler.restricted_sessions = True
                f.scheduler.shadow_every = 1

            # deterministic shard-1-homed names
            def names_for(shard, count, prefix):
                out, k = [], 0
                while len(out) < count:
                    cand = f"{prefix}{k}"
                    k += 1
                    if home_shard("ns", cand, 2) == shard:
                        out.append(cand)
                return out

            gname = names_for(1, 1, "gang")[0]
            vc.create_pod_group(build_pod_group("ns", gname, 3))
            for i in range(3):
                kube.create_pod(build_pod(
                    "ns", f"{gname}-t{i}", "",
                    {"cpu": "2", "memory": "1Gi"}, group=gname,
                ))
            for jname in names_for(1, 2, "spill"):
                vc.create_pod_group(build_pod_group("ns", jname, 1))
                kube.create_pod(build_pod(
                    "ns", f"{jname}-t0", "",
                    {"cpu": "2", "memory": "1Gi"}, group=jname,
                ))

            def all_bound():
                for f in feds:
                    f.scheduler.run_once(trigger="task")
                pods = kube.list_pods("ns")
                gang_bound = sum(
                    1 for p in pods
                    if p.spec.node_name
                    and p.metadata.name.startswith(gname)
                )
                assert gang_bound == 0 or gang_bound >= 3, (
                    f"partial gang observed: {gang_bound}/3 bound"
                )
                return all(p.spec.node_name for p in pods)

            assert _wait(all_bound, timeout=30.0, interval=0.05)
            for f in feds:
                assert f.scheduler.restricted_cycles_run >= 1
                assert f.scheduler.shadow_checks_run >= 1
                assert f.scheduler.shadow_divergences == 0, (
                    f"{f.identity}: restricted/full divergence under "
                    "spillover + gang mix"
                )
            report = verify_federation(api, 2)
            assert report["ok"], report["violations"]
        finally:
            for f in feds:
                f.stop()
