"""jax-allocate equivalence: the device-backed action must produce
bindings identical to the host allocate action on the same snapshot —
the north-star contract (BASELINE.md: "identical bindings")."""

from __future__ import annotations

import pytest

from volcano_tpu.actions.allocate import AllocateAction
from volcano_tpu.actions.jax_allocate import (
    compute_task_order,
    JaxAllocateAction,
)
from volcano_tpu.framework import close_session, open_session

from tests.builders import build_node, build_pod, build_pod_group, build_queue
from tests.scheduler_helpers import make_cache, run_actions, tiers

TIERS = lambda: tiers(["priority", "gang"], ["drf", "predicates", "proportion", "nodeorder", "binpack"])


def _bindings(cache, action):
    run_actions(cache, [action], TIERS())
    return dict(cache.binder.binds)


def _case_multi_job_spread():
    nodes = [
        build_node(f"n{i}", {"cpu": str(4 + (i % 3) * 2), "memory": "16G"})
        for i in range(8)
    ]
    pods, pgs = [], []
    for j in range(5):
        pgs.append(build_pod_group("ns", f"pg{j}", 2, queue="q"))
        for i in range(3):
            pods.append(
                build_pod("ns", f"j{j}-t{i}", "", {"cpu": "2", "memory": "2G"}, group=f"pg{j}")
            )
    return dict(nodes=nodes, pods=pods, pod_groups=pgs, queues=[build_queue("q")])


def _case_multi_queue_fairshare():
    nodes = [build_node(f"n{i}", {"cpu": "8", "memory": "32G"}) for i in range(4)]
    pods, pgs = [], []
    queues = [build_queue("qa", weight=3), build_queue("qb", weight=1)]
    for j, q in [(0, "qa"), (1, "qa"), (2, "qb")]:
        pgs.append(build_pod_group("ns", f"pg{j}", 1, queue=q))
        for i in range(4):
            pods.append(
                build_pod("ns", f"j{j}-t{i}", "", {"cpu": "2", "memory": "4G"}, group=f"pg{j}")
            )
    return dict(nodes=nodes, pods=pods, pod_groups=pgs, queues=queues)


def _case_multi_namespace():
    nodes = [build_node(f"n{i}", {"cpu": "4", "memory": "8G"}) for i in range(3)]
    pods, pgs = [], []
    for ns in ("alpha", "beta"):
        pgs.append(build_pod_group(ns, "pg", 0, queue="q"))
        for i in range(3):
            pods.append(
                build_pod(ns, f"t{i}", "", {"cpu": "1", "memory": "1G"}, group="pg")
            )
    return dict(nodes=nodes, pods=pods, pod_groups=pgs, queues=[build_queue("q")])


def _case_gang_partial_discard():
    nodes = [build_node("n0", {"cpu": "4", "memory": "8G"})]
    pods, pgs = [], []
    pgs.append(build_pod_group("ns", "fits", 2, queue="q"))
    for i in range(2):
        pods.append(build_pod("ns", f"f{i}", "", {"cpu": "1", "memory": "1G"}, group="fits"))
    pgs.append(build_pod_group("ns", "toobig", 4, queue="q"))
    for i in range(4):
        pods.append(build_pod("ns", f"b{i}", "", {"cpu": "1", "memory": "1G"}, group="toobig"))
    return dict(nodes=nodes, pods=pods, pod_groups=pgs, queues=[build_queue("q")])


@pytest.mark.parametrize(
    "case",
    [
        _case_multi_job_spread,
        _case_multi_queue_fairshare,
        _case_multi_namespace,
        _case_gang_partial_discard,
    ],
)
def test_jax_allocate_bindings_match_host(case):
    args = case()
    host = _bindings(make_cache(**args), AllocateAction())
    dev = _bindings(make_cache(**args), JaxAllocateAction())
    assert host == dev
    # sanity: the scenario actually schedules something (except pure-discard)
    if case is not _case_gang_partial_discard:
        assert host


def test_compute_task_order_is_side_effect_free():
    """The order replay must leave session state untouched."""
    args = _case_multi_job_spread()
    cache = make_cache(**args)
    ssn = open_session(cache, TIERS(), [])
    try:
        before = {
            uid: {t.uid: t.status for t in job.tasks.values()}
            for uid, job in ssn.jobs.items()
        }
        order = compute_task_order(ssn)
        after = {
            uid: {t.uid: t.status for t in job.tasks.values()}
            for uid, job in ssn.jobs.items()
        }
        assert before == after
        assert len(order) == len({t.uid for t in order})
        # Interleave property: jobs are popped round-robin until gang-ready
        # (minAvailable=2 here), so each job's first two tasks must all
        # precede any job's third task — the first 2×5 entries cover every
        # job exactly twice.
        n_jobs = 5
        head = order[: 2 * n_jobs]
        counts = {}
        for t in head:
            counts[t.job] = counts.get(t.job, 0) + 1
        assert counts == {f"ns/pg{j}": 2 for j in range(n_jobs)}, counts
    finally:
        close_session(ssn)


def test_jax_allocate_with_predicates_case():
    from volcano_tpu.apis import core

    def mk():
        return make_cache(
            nodes=[
                build_node("n1", {"cpu": "8", "memory": "16G"}, labels={"zone": "a"}),
                build_node(
                    "n2", {"cpu": "8", "memory": "16G"},
                    taints=[core.Taint(key="dedicated", value="x", effect="NoSchedule")],
                ),
                build_node("n3", {"cpu": "8", "memory": "16G"}),
            ],
            pods=[
                build_pod("ns", "sel", "", {"cpu": "1", "memory": "1G"}, group="pg",
                          selector={"zone": "a"}),
                build_pod("ns", "tol", "", {"cpu": "1", "memory": "1G"}, group="pg",
                          tolerations=[core.Toleration(key="dedicated", value="x", effect="NoSchedule")]),
                build_pod("ns", "any", "", {"cpu": "1", "memory": "1G"}, group="pg"),
            ],
            pod_groups=[build_pod_group("ns", "pg", 0, queue="q")],
            queues=[build_queue("q")],
        )

    host = _bindings(mk(), AllocateAction())
    dev = _bindings(mk(), JaxAllocateAction())
    assert host == dev
    assert host["ns/sel"] == "n1"
