"""Job controller tests — reconcile loop, state machine, lifecycle
policies, plugins.  Mirrors the reference pattern (job_state_test.go,
job_controller_actions_test.go): fake clientset == in-process API server,
direct drain() instead of background workers."""

from __future__ import annotations

import pytest

from volcano_tpu.apis import batch, bus, core
from volcano_tpu.client import APIServer, KubeClient, VolcanoClient
from volcano_tpu.controllers import GarbageCollector, JobController, QueueController


def make_job(name="job1", namespace="ns", replicas=3, min_available=3, **spec_kw):
    task = batch.TaskSpec(
        name="worker",
        replicas=replicas,
        template=core.PodTemplateSpec(
            spec=core.PodSpec(
                containers=[core.Container(resources={"requests": {"cpu": "1", "memory": "1Gi"}})]
            )
        ),
    )
    return batch.Job(
        metadata=core.ObjectMeta(name=name, namespace=namespace, uid=f"uid-{name}"),
        spec=batch.JobSpec(min_available=min_available, tasks=[task], **spec_kw),
    )


@pytest.fixture
def env():
    api = APIServer()
    jc = JobController(api)
    return api, jc, KubeClient(api), VolcanoClient(api)


def set_pod_phase(kube, namespace, name, phase, exit_code=None):
    pod = kube.get_pod(namespace, name)
    pod.status.phase = phase
    pod.status.exit_code = exit_code
    kube.update_pod_status(pod)


class TestSyncJob:
    def test_create_job_fans_out_pods_and_podgroup(self, env):
        api, jc, kube, vc = env
        vc.create_job(make_job())
        jc.drain()

        pods = kube.list_pods("ns")
        assert {p.metadata.name for p in pods} == {
            "job1-worker-0", "job1-worker-1", "job1-worker-2"
        }
        # identity annotations (job_controller_util.go:102-105)
        pod = pods[0]
        assert pod.metadata.annotations[batch.JOB_NAME_KEY] == "job1"
        assert pod.metadata.annotations[batch.TASK_SPEC_KEY] == "worker"
        pg = vc.get_pod_group("ns", "job1")
        assert pg is not None
        assert pg.spec.min_member == 3
        assert pg.spec.min_resources["cpu"] == "3000m"
        job = vc.get_job("ns", "job1")
        assert job.status.state.phase == batch.JOB_PENDING
        assert job.status.pending == 3

    def test_pending_to_running_when_min_available_active(self, env):
        api, jc, kube, vc = env
        vc.create_job(make_job())
        jc.drain()
        for i in range(3):
            set_pod_phase(kube, "ns", f"job1-worker-{i}", "Running")
        jc.drain()
        job = vc.get_job("ns", "job1")
        assert job.status.state.phase == batch.JOB_RUNNING
        assert job.status.running == 3

    def test_running_to_completed_when_all_finish(self, env):
        api, jc, kube, vc = env
        vc.create_job(make_job())
        jc.drain()
        for i in range(3):
            set_pod_phase(kube, "ns", f"job1-worker-{i}", "Running")
        jc.drain()
        for i in range(3):
            set_pod_phase(kube, "ns", f"job1-worker-{i}", "Succeeded")
        jc.drain()
        job = vc.get_job("ns", "job1")
        assert job.status.state.phase == batch.JOB_COMPLETED
        assert job.status.succeeded == 3
        # podgroup deleted by the kill in finished state
        assert vc.get_pod_group("ns", "job1") is None


class TestLifecyclePolicies:
    def test_pod_failed_restart_policy(self, env):
        api, jc, kube, vc = env
        job = make_job(
            policies=[batch.LifecyclePolicy(event=batch.POD_FAILED_EVENT, action=batch.RESTART_JOB_ACTION)]
        )
        vc.create_job(job)
        jc.drain()
        for i in range(3):
            set_pod_phase(kube, "ns", f"job1-worker-{i}", "Running")
        jc.drain()
        set_pod_phase(kube, "ns", "job1-worker-1", "Failed")
        jc.drain()
        stored = vc.get_job("ns", "job1")
        # RestartJob: kill (version bump, retry count) then back through
        # Restarting → Pending → pods recreated.
        assert stored.status.retry_count >= 1
        assert stored.status.version >= 1
        assert stored.status.state.phase in (batch.JOB_RESTARTING, batch.JOB_PENDING, batch.JOB_RUNNING)
        # eventually pods exist again
        assert len(kube.list_pods("ns")) == 3

    def test_abort_action_via_command(self, env):
        api, jc, kube, vc = env
        vc.create_job(make_job())
        jc.drain()
        vc.create_command(
            bus.Command(
                metadata=core.ObjectMeta(name="cmd1", namespace="ns"),
                action=batch.ABORT_JOB_ACTION,
                target_object=core.OwnerReference(kind="Job", name="job1"),
            )
        )
        jc.drain()
        job = vc.get_job("ns", "job1")
        assert job.status.state.phase in (batch.JOB_ABORTING, batch.JOB_ABORTED)
        # command consumed
        assert vc.list_commands("ns") == []
        # pending pods killed (retain-soft keeps none since all Pending)
        assert kube.list_pods("ns") == []

    def test_stale_pod_event_fenced_by_version(self, env):
        api, jc, kube, vc = env
        job = make_job(
            policies=[batch.LifecyclePolicy(event=batch.POD_FAILED_EVENT, action=batch.ABORT_JOB_ACTION)]
        )
        vc.create_job(job)
        jc.drain()
        from volcano_tpu.controllers.apis import Request
        from volcano_tpu.controllers.job.job_controller import apply_policies

        stored = vc.get_job("ns", "job1")
        stored.status.version = 5
        # stale event carries version 2 < 5 → SyncJob, not Abort
        req = Request(namespace="ns", job_name="job1", event=batch.POD_FAILED_EVENT, job_version=2)
        assert apply_policies(stored, req) == batch.SYNC_JOB_ACTION

    def test_task_level_policy_overrides_job_level(self, env):
        api, jc, kube, vc = env
        job = make_job()
        job.spec.tasks[0].policies = [
            batch.LifecyclePolicy(event=batch.POD_FAILED_EVENT, action=batch.RESTART_TASK_ACTION)
        ]
        job.spec.policies = [
            batch.LifecyclePolicy(event=batch.POD_FAILED_EVENT, action=batch.ABORT_JOB_ACTION)
        ]
        from volcano_tpu.controllers.apis import Request
        from volcano_tpu.controllers.job.job_controller import apply_policies

        req = Request(
            namespace="ns", job_name="job1", task_name="worker", event=batch.POD_FAILED_EVENT
        )
        assert apply_policies(job, req) == batch.RESTART_TASK_ACTION


class TestJobPlugins:
    def test_svc_and_ssh_and_env_plugins(self, env):
        api, jc, kube, vc = env
        job = make_job(plugins={"env": [], "ssh": [], "svc": []})
        vc.create_job(job)
        jc.drain()

        # svc: headless service + hosts configmap
        svc = kube.get_service("ns", "job1")
        assert svc is not None and svc.spec.cluster_ip == "None"
        cm = kube.get_config_map("ns", "job1-svc")
        assert "job1-worker-0.job1" in cm.data["VC_TASK_HOSTS"]
        # ssh: keypair secret
        secret = kube.get_secret("ns", "job1-ssh")
        assert secret is not None and "id_rsa" in secret.data
        # env + mounts on pods
        pod = kube.get_pod("ns", "job1-worker-1")
        envs = {e.name: e.value for e in pod.spec.containers[0].env}
        assert envs["VK_TASK_INDEX"] == "1"
        assert pod.spec.hostname == "job1-worker-1"
        assert pod.spec.subdomain == "job1"
        mounts = [m.mount_path for m in pod.spec.containers[0].volume_mounts]
        assert "/root/.ssh" in mounts and "/etc/volcano" in mounts


class TestQueueController:
    def test_close_open_via_command(self, env):
        api, jc, kube, vc = env
        from volcano_tpu.apis import scheduling

        qc = QueueController(api)
        vc.create_queue(scheduling.Queue(metadata=core.ObjectMeta(name="q1", namespace="")))
        qc.drain()
        assert vc.get_queue("q1").status.state == scheduling.QUEUE_STATE_OPEN

        vc.create_command(
            bus.Command(
                metadata=core.ObjectMeta(name="close-q1", namespace=""),
                action="CloseQueue",
                target_object=core.OwnerReference(kind="Queue", name="q1"),
            )
        )
        qc.drain()
        q = vc.get_queue("q1")
        assert q.status.state == scheduling.QUEUE_STATE_CLOSED  # no podgroups → straight to Closed

        vc.create_command(
            bus.Command(
                metadata=core.ObjectMeta(name="open-q1", namespace=""),
                action="OpenQueue",
                target_object=core.OwnerReference(kind="Queue", name="q1"),
            )
        )
        qc.drain()
        assert vc.get_queue("q1").status.state == scheduling.QUEUE_STATE_OPEN

    def test_podgroup_counts(self, env):
        api, jc, kube, vc = env
        from volcano_tpu.apis import scheduling

        qc = QueueController(api)
        vc.create_queue(scheduling.Queue(metadata=core.ObjectMeta(name="q2", namespace="")))
        vc.create_job(make_job(name="jq", min_available=1, queue="q2"))
        jc.drain()
        qc.drain()
        q = vc.get_queue("q2")
        assert q.status.pending == 1


class TestGarbageCollector:
    def test_ttl_reaps_finished_job(self, env):
        import time as _time

        api, jc, kube, vc = env
        # Fake clock anchored to real time: state transition timestamps
        # come from time.time() inside the controller.
        now = [_time.time()]
        gc = GarbageCollector(api, clock=lambda: now[0])
        job = make_job(name="short", ttl_seconds_after_finished=10)
        vc.create_job(job)
        jc.drain()
        for i in range(3):
            set_pod_phase(kube, "ns", f"short-worker-{i}", "Succeeded")
        jc.drain()
        assert vc.get_job("ns", "short").status.state.phase == batch.JOB_COMPLETED
        assert gc.process_expired() == 0  # TTL not reached
        now[0] += 1e6
        assert gc.process_expired() == 1
        assert vc.get_job("ns", "short") is None
