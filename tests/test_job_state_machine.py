"""Job state-machine table — the reference's job_state_test.go pattern
(1,294 LoC of table-driven (state, action, status) → (operation, retain
set, next phase) cases), driven directly against the state classes with
stubbed SyncJob/KillJob."""

from __future__ import annotations

import pytest

from volcano_tpu.apis import batch, core
from volcano_tpu.controllers.apis import JobInfo
from volcano_tpu.controllers.job import state as jobstate


def _job_info(phase, min_available=2, replicas=3, max_retry=0,
              retry_count=0, running=0, pending=0, succeeded=0, failed=0,
              terminating=0):
    job = batch.Job(
        metadata=core.ObjectMeta(name="j", namespace="ns"),
        spec=batch.JobSpec(
            min_available=min_available,
            max_retry=max_retry,
            tasks=[batch.TaskSpec(name="t", replicas=replicas)],
        ),
    )
    job.status.state.phase = phase
    job.status.retry_count = retry_count
    job.status.running = running
    job.status.pending = pending
    job.status.succeeded = succeeded
    job.status.failed = failed
    job.status.terminating = terminating
    job.status.min_available = min_available
    ji = JobInfo()
    ji.job = job
    return ji


class Recorder:
    """Stub SyncJob/KillJob; applies the status callback to the job's
    own status so the table can assert the resulting phase."""

    def __init__(self, monkeypatch):
        self.ops = []
        monkeypatch.setattr(jobstate, "SyncJob", self._sync)
        monkeypatch.setattr(jobstate, "KillJob", self._kill)

    def _sync(self, ji, fn):
        changed = fn(ji.job.status) if fn else None
        self.ops.append(("sync", None, changed))

    def _kill(self, ji, retain, fn):
        changed = fn(ji.job.status) if fn else None
        self.ops.append(("kill", retain, changed))

    @property
    def last(self):
        return self.ops[-1]


SOFT = jobstate.POD_RETAIN_PHASE_SOFT
NONE = jobstate.POD_RETAIN_PHASE_NONE

# (start phase, status kwargs, action, expected op, expected retain,
#  expected end phase) — the job_state_test.go table shape
CASES = [
    # Pending
    (batch.JOB_PENDING, {}, batch.RESTART_JOB_ACTION, "kill", NONE, batch.JOB_RESTARTING),
    (batch.JOB_PENDING, {}, batch.ABORT_JOB_ACTION, "kill", SOFT, batch.JOB_ABORTING),
    (batch.JOB_PENDING, {}, batch.TERMINATE_JOB_ACTION, "kill", SOFT, batch.JOB_TERMINATING),
    (batch.JOB_PENDING, {}, batch.COMPLETE_JOB_ACTION, "kill", SOFT, batch.JOB_COMPLETING),
    (batch.JOB_PENDING, {"running": 0}, batch.SYNC_JOB_ACTION, "sync", None, batch.JOB_PENDING),
    (batch.JOB_PENDING, {"running": 2}, batch.SYNC_JOB_ACTION, "sync", None, batch.JOB_RUNNING),
    (batch.JOB_PENDING, {"succeeded": 1, "running": 1}, batch.SYNC_JOB_ACTION, "sync", None, batch.JOB_RUNNING),
    # Running
    (batch.JOB_RUNNING, {"running": 3}, batch.RESTART_JOB_ACTION, "kill", NONE, batch.JOB_RESTARTING),
    (batch.JOB_RUNNING, {"running": 3}, batch.ABORT_JOB_ACTION, "kill", SOFT, batch.JOB_ABORTING),
    (batch.JOB_RUNNING, {"running": 3}, batch.TERMINATE_JOB_ACTION, "kill", SOFT, batch.JOB_TERMINATING),
    (batch.JOB_RUNNING, {"running": 3}, batch.COMPLETE_JOB_ACTION, "kill", SOFT, batch.JOB_COMPLETING),
    (batch.JOB_RUNNING, {"running": 3}, batch.SYNC_JOB_ACTION, "sync", None, batch.JOB_RUNNING),
    (batch.JOB_RUNNING, {"succeeded": 3}, batch.SYNC_JOB_ACTION, "sync", None, batch.JOB_COMPLETED),
    (batch.JOB_RUNNING, {"succeeded": 2, "failed": 1}, batch.SYNC_JOB_ACTION, "sync", None, batch.JOB_COMPLETED),
    # Restarting
    (batch.JOB_RESTARTING, {"retry_count": 3}, batch.SYNC_JOB_ACTION, "kill", NONE, batch.JOB_FAILED),
    (batch.JOB_RESTARTING, {"retry_count": 1, "terminating": 0}, batch.SYNC_JOB_ACTION, "kill", NONE, batch.JOB_PENDING),
    (batch.JOB_RESTARTING, {"retry_count": 1, "terminating": 3}, batch.SYNC_JOB_ACTION, "kill", NONE, batch.JOB_RESTARTING),
    # Aborting
    (batch.JOB_ABORTING, {"running": 1}, batch.SYNC_JOB_ACTION, "kill", SOFT, batch.JOB_ABORTING),
    (batch.JOB_ABORTING, {}, batch.SYNC_JOB_ACTION, "kill", SOFT, batch.JOB_ABORTED),
    (batch.JOB_ABORTING, {}, batch.RESUME_JOB_ACTION, "kill", SOFT, batch.JOB_RESTARTING),
    # Aborted
    (batch.JOB_ABORTED, {}, batch.RESUME_JOB_ACTION, "kill", SOFT, batch.JOB_RESTARTING),
    (batch.JOB_ABORTED, {}, batch.SYNC_JOB_ACTION, "kill", SOFT, batch.JOB_ABORTED),
    # Terminating
    (batch.JOB_TERMINATING, {"terminating": 2}, batch.SYNC_JOB_ACTION, "kill", SOFT, batch.JOB_TERMINATING),
    (batch.JOB_TERMINATING, {}, batch.SYNC_JOB_ACTION, "kill", SOFT, batch.JOB_TERMINATED),
    # Completing
    (batch.JOB_COMPLETING, {"pending": 1}, batch.SYNC_JOB_ACTION, "kill", SOFT, batch.JOB_COMPLETING),
    (batch.JOB_COMPLETING, {}, batch.SYNC_JOB_ACTION, "kill", SOFT, batch.JOB_COMPLETED),
    # Finished states: always re-kill with soft retain, phase untouched
    (batch.JOB_COMPLETED, {}, batch.SYNC_JOB_ACTION, "kill", SOFT, batch.JOB_COMPLETED),
    (batch.JOB_TERMINATED, {}, batch.SYNC_JOB_ACTION, "kill", SOFT, batch.JOB_TERMINATED),
    (batch.JOB_FAILED, {}, batch.SYNC_JOB_ACTION, "kill", SOFT, batch.JOB_FAILED),
]


@pytest.mark.parametrize(
    "phase,status_kw,action,op,retain,end_phase", CASES,
    ids=[f"{c[0]}-{c[2]}-{i}" for i, c in enumerate(CASES)],
)
def test_state_action_table(monkeypatch, phase, status_kw, action, op,
                            retain, end_phase):
    rec = Recorder(monkeypatch)
    ji = _job_info(phase, **status_kw)
    jobstate.new_state(ji).execute(action)
    got_op, got_retain, _ = rec.last
    assert got_op == op
    if retain is not None:
        assert got_retain == retain
    assert ji.job.status.state.phase == end_phase


def test_restart_bumps_retry_count(monkeypatch):
    rec = Recorder(monkeypatch)
    ji = _job_info(batch.JOB_RUNNING, running=3)
    jobstate.new_state(ji).execute(batch.RESTART_JOB_ACTION)
    assert ji.job.status.retry_count == 1


def test_restarting_respects_custom_max_retry(monkeypatch):
    rec = Recorder(monkeypatch)
    ji = _job_info(batch.JOB_RESTARTING, max_retry=5, retry_count=4)
    jobstate.new_state(ji).execute(batch.SYNC_JOB_ACTION)
    assert ji.job.status.state.phase == batch.JOB_PENDING  # 4 < 5
    ji = _job_info(batch.JOB_RESTARTING, max_retry=5, retry_count=5)
    jobstate.new_state(ji).execute(batch.SYNC_JOB_ACTION)
    assert ji.job.status.state.phase == batch.JOB_FAILED


def test_unknown_phase_defaults_to_pending(monkeypatch):
    rec = Recorder(monkeypatch)
    ji = _job_info("SomethingNew")
    st = jobstate.new_state(ji)
    assert isinstance(st, jobstate.PendingState)
