"""Device kernel tests: score math goldens vs host plugins, and
bindings-equivalence of the packed session kernel vs the host allocate
path on identical snapshots (the north-star contract)."""

from __future__ import annotations

import numpy as np
import pytest

import volcano_tpu.scheduler.util as sched_util
from volcano_tpu.api import new_task_info, NodeInfo, TaskStatus
from volcano_tpu.ops import pack_session, run_packed, ScoreWeights
from volcano_tpu.ops.kernels import (
    balanced_resource_score,
    binpack_score,
    least_requested_score,
)
from volcano_tpu.plugins.binpack import bin_packing_score, PriorityWeight
from volcano_tpu.plugins.nodeorder import (
    balanced_resource_priority,
    least_requested_priority,
)

from tests.builders import build_node, build_pod, build_pod_group, build_queue
from tests.scheduler_helpers import make_cache, run_actions, tiers


def _host_score_inputs(ncpu, nmem, used_cpu, used_mem, req_cpu, req_mem):
    node = NodeInfo(build_node("n", {"cpu": str(ncpu), "memory": str(int(nmem))}))
    pod = build_pod("ns", "p", "", {"cpu": str(req_cpu), "memory": str(int(req_mem))})
    task = new_task_info(pod)
    if used_cpu or used_mem:
        filler = new_task_info(
            build_pod(
                "ns", "filler", "n",
                {"cpu": str(used_cpu), "memory": str(int(used_mem))},
                phase="Running",
            )
        )
        node.add_task(filler)
    return task, node


GI = 1024**3
MI = 1024**2


@pytest.mark.parametrize(
    "ncpu,nmem,used_cpu,used_mem,req_cpu,req_mem",
    [
        (4, 8 * GI, 0, 0, 1, 1 * GI),
        (4, 8 * GI, 2, 2 * GI, 1, 1 * GI),
        (16, 64 * GI, 7, 40 * GI, 3, 10 * GI),
        (2, 4 * GI, 1, 3 * GI, 1, 1 * GI),
        (8, 33 * GI + 512 * MI, 3, 7 * GI + 256 * MI, 1, 2 * GI + 128 * MI),
    ],
)
def test_score_goldens_match_host_plugins(ncpu, nmem, used_cpu, used_mem, req_cpu, req_mem):
    """Device closed-form scores == host plugin math on the same state.
    Device memory lanes are MiB-quantized (ops/packing.py), so the
    exactness contract covers MiB-aligned quantities."""
    task, node = _host_score_inputs(ncpu, nmem, used_cpu, used_mem, req_cpu, req_mem)

    resreq = np.array([[task.resreq.milli_cpu, task.resreq.memory / MI]], dtype=np.float32)
    used = np.array([[node.used.milli_cpu, node.used.memory / MI]], dtype=np.float32)
    alloc = np.array([[node.allocatable.milli_cpu, node.allocatable.memory / MI]], dtype=np.float32)

    host_bp = bin_packing_score(task, node, PriorityWeight())
    dev_bp = float(binpack_score(resreq, used, alloc, ScoreWeights())[0, 0])
    assert dev_bp == pytest.approx(host_bp, rel=1e-5)

    host_lr = least_requested_priority(
        node.used.milli_cpu + task.resreq.milli_cpu,
        node.used.memory + task.resreq.memory,
        node.allocatable.milli_cpu,
        node.allocatable.memory,
    )
    dev_lr = float(least_requested_score(resreq, used, alloc)[0, 0])
    assert dev_lr == host_lr

    host_ba = balanced_resource_priority(
        node.used.milli_cpu + task.resreq.milli_cpu,
        node.used.memory + task.resreq.memory,
        node.allocatable.milli_cpu,
        node.allocatable.memory,
    )
    dev_ba = float(balanced_resource_score(resreq, used, alloc)[0, 0])
    assert dev_ba == host_ba


def _host_bindings(cache):
    """Run the host allocate on the cache; return {task_key: node}."""
    from volcano_tpu.actions.allocate import AllocateAction

    sched_util._last_processed_node_index = 0
    run_actions(
        cache, [AllocateAction()], tiers(["gang"], ["drf", "predicates", "proportion", "nodeorder", "binpack"])
    )
    return dict(cache.binder.binds)


def _device_bindings(cache):
    """Pack the same snapshot, run the kernel, return {task_key: node}."""
    snapshot = cache.snapshot()
    jobs = sorted(snapshot.jobs.values(), key=lambda j: j.uid)
    tasks = []
    for job in jobs:
        pending = sorted(
            job.task_status_index.get(TaskStatus.Pending, {}).values(),
            key=lambda t: t.uid,
        )
        tasks.extend(t for t in pending if not t.resreq.is_empty())
    nodes = [snapshot.nodes[name] for name in sorted(snapshot.nodes)]
    snap = pack_session(tasks, jobs, nodes)
    assignment = run_packed(snap)
    out = {}
    for i, t in enumerate(tasks):
        if assignment[i] >= 0:
            out[f"{t.namespace}/{t.name}"] = nodes[assignment[i]].name
    return out


def _mk_case(nodes, pods, pod_groups, queues):
    return make_cache(nodes=nodes, pods=pods, pod_groups=pod_groups, queues=queues)


def test_kernel_matches_host_simple_fill():
    args = dict(
        nodes=[
            build_node("n1", {"cpu": "4", "memory": "8G"}),
            build_node("n2", {"cpu": "4", "memory": "8G"}),
        ],
        pods=[
            build_pod("ns", f"p{i}", "", {"cpu": "1", "memory": "1G"}, group="pg1")
            for i in range(4)
        ],
        pod_groups=[build_pod_group("ns", "pg1", 0, queue="q")],
        queues=[build_queue("q")],
    )
    host = _host_bindings(_mk_case(**args))
    dev = _device_bindings(_mk_case(**args))
    assert host == dev
    assert len(host) == 4


def test_kernel_matches_host_gang_discard():
    """Gang job that cannot fully fit must bind nothing on both paths."""
    args = dict(
        nodes=[build_node("n1", {"cpu": "2", "memory": "4G"})],
        pods=[
            build_pod("ns", f"p{i}", "", {"cpu": "1", "memory": "1G"}, group="pg1")
            for i in range(3)
        ],
        pod_groups=[build_pod_group("ns", "pg1", 3, queue="q")],
        queues=[build_queue("q")],
    )
    host = _host_bindings(_mk_case(**args))
    dev = _device_bindings(_mk_case(**args))
    assert host == dev == {}


def test_kernel_matches_host_selector_and_taints():
    from volcano_tpu.apis import core

    def mk():
        return _mk_case(
            nodes=[
                build_node("n1", {"cpu": "8", "memory": "16G"}, labels={"disk": "ssd"}),
                build_node(
                    "n2", {"cpu": "8", "memory": "16G"},
                    taints=[core.Taint(key="gpu", value="yes", effect="NoSchedule")],
                ),
                build_node("n3", {"cpu": "8", "memory": "16G"}),
            ],
            pods=[
                build_pod("ns", "pssd", "", {"cpu": "1", "memory": "1G"},
                          group="pg1", selector={"disk": "ssd"}),
                build_pod("ns", "ptol", "", {"cpu": "1", "memory": "1G"}, group="pg1",
                          tolerations=[core.Toleration(key="gpu", value="yes", effect="NoSchedule")]),
                build_pod("ns", "plain", "", {"cpu": "1", "memory": "1G"}, group="pg1"),
            ],
            pod_groups=[build_pod_group("ns", "pg1", 0, queue="q")],
            queues=[build_queue("q")],
        )

    host = _host_bindings(mk())
    dev = _device_bindings(mk())
    assert host == dev
    assert host["ns/pssd"] == "n1"


def test_kernel_matches_host_single_job_heterogeneous():
    """One job over heterogeneous nodes: static kernel order == host order.
    (Multi-job dynamic interleave equivalence is covered through the
    jax-allocate action in tests/test_jax_allocate.py, which feeds the
    kernel the replayed host order.)"""
    nodes = [
        build_node(f"n{i}", {"cpu": str(4 + (i % 3) * 2), "memory": "16G"})
        for i in range(8)
    ]
    pods = [
        build_pod("ns", f"t{i}", "", {"cpu": "2", "memory": "2G"}, group="pg0")
        for i in range(9)
    ]
    args = dict(
        nodes=nodes,
        pods=pods,
        pod_groups=[build_pod_group("ns", "pg0", 2, queue="q")],
        queues=[build_queue("q")],
    )
    host = _host_bindings(_mk_case(**args))
    dev = _device_bindings(_mk_case(**args))
    assert host == dev


def test_kernel_respects_existing_usage():
    """Nodes with running pods: used/idle packed correctly."""
    def mk():
        cache = _mk_case(
            nodes=[
                build_node("n1", {"cpu": "4", "memory": "8G"}),
                build_node("n2", {"cpu": "4", "memory": "8G"}),
            ],
            pods=[
                build_pod("ns", "running", "n1", {"cpu": "3", "memory": "6G"},
                          phase="Running", group="pg0"),
                build_pod("ns", "new1", "", {"cpu": "2", "memory": "2G"}, group="pg1"),
            ],
            pod_groups=[
                build_pod_group("ns", "pg0", 1, queue="q"),
                build_pod_group("ns", "pg1", 1, queue="q"),
            ],
            queues=[build_queue("q")],
        )
        return cache

    host = _host_bindings(mk())
    dev = _device_bindings(mk())
    assert host == dev == {"ns/new1": "n2"}


# ---- gang-fixpoint cascade depth (VERDICT weak #6) ----


def _cascade_snapshot(n_nodes: int = 2):
    """A session whose gang cascade does NOT settle in one round:

    scan order [b0, b1, a0]; job B = {b0, b1} with min_available=2 but
    b1 unplaceable, job A = {a0} with min_available=1; two identical
    one-task nodes (tie-break → n0 first).  Round 1 places b0@n0 and
    a0@n1, then discards job B (1 < 2 ready) — so round 2 would move a0
    onto the freed n0.  With gang_rounds=1 the bounded loop ships the
    round-1 commit (a0@n1), the documented deviation; the reference
    discards until stable (a0@n0)."""
    from volcano_tpu.ops.packing import PackedSnapshot

    snap = PackedSnapshot()
    snap.resource_names = ["cpu", "memory"]
    snap.tolerance = np.array([10.0, 10.0], dtype=np.float32)
    snap.n_tasks, snap.n_nodes, snap.n_jobs = 3, n_nodes, 2
    snap.task_resreq = np.array(
        [[1000.0, 2048.0], [50000.0, 99999.0], [1000.0, 2048.0]],
        dtype=np.float32,
    )
    snap.task_job = np.array([1, 1, 0], dtype=np.int32)
    snap.task_sel_bits = np.zeros((3, 2), dtype=np.uint32)
    snap.task_tol_bits = np.zeros((3, 2), dtype=np.uint32)
    snap.node_idle = np.tile(
        np.array([[1000.0, 2048.0]], dtype=np.float32), (n_nodes, 1)
    )
    snap.node_used = np.zeros((n_nodes, 2), dtype=np.float32)
    snap.node_alloc = snap.node_idle.copy()
    snap.node_label_bits = np.zeros((n_nodes, 2), dtype=np.uint32)
    snap.node_taint_bits = np.zeros((n_nodes, 2), dtype=np.uint32)
    snap.node_ok = np.ones(n_nodes, dtype=bool)
    snap.node_task_count = np.zeros(n_nodes, dtype=np.int32)
    snap.node_max_tasks = np.full(n_nodes, 110, dtype=np.int32)
    snap.job_min_available = np.array([1, 2], dtype=np.int32)
    snap.job_ready_count = np.zeros(2, dtype=np.int32)
    snap.task_has_preferences = np.zeros(3, dtype=bool)
    return snap


class TestGangCascadeDepth:
    def test_bounded_rounds_ship_last_commit(self):
        # the documented deviation: one round is not enough for the
        # cascade, and the bounded loop ships round 1's (valid) commit
        out = run_packed(_cascade_snapshot(), gang_rounds=1)
        np.testing.assert_array_equal(out, [-1, -1, 1])

    def test_enough_rounds_reach_the_fixpoint(self):
        out = run_packed(_cascade_snapshot(), gang_rounds=3)
        np.testing.assert_array_equal(out, [-1, -1, 0])

    def test_discard_until_stable_matches_reference_semantics(self):
        # statement.go:309-337: even with the round budget exhausted,
        # discard mode keeps discarding until the active set is stable
        out = run_packed(
            _cascade_snapshot(), gang_rounds=1, discard_unstable=True
        )
        np.testing.assert_array_equal(out, [-1, -1, 0])

    def test_blocked_formulation_same_cascade_semantics(self):
        from volcano_tpu.ops.blocked import run_packed_blocked

        # the blocked kernel's top-K tracking needs >= K nodes
        bounded = run_packed_blocked(_cascade_snapshot(n_nodes=9), gang_rounds=1)
        np.testing.assert_array_equal(bounded, [-1, -1, 1])
        stable = run_packed_blocked(
            _cascade_snapshot(n_nodes=9), gang_rounds=1, discard_unstable=True
        )
        np.testing.assert_array_equal(stable, [-1, -1, 0])

    def test_env_opt_in_routes_dispatch(self, monkeypatch):
        from volcano_tpu.ops import dispatch

        monkeypatch.setenv("VTPU_GANG_DISCARD_UNSTABLE", "1")
        assert dispatch.gang_discard_unstable()
        out = dispatch.run_packed_auto(_cascade_snapshot(), gang_rounds=1)
        np.testing.assert_array_equal(out, [-1, -1, 0])
        monkeypatch.setenv("VTPU_GANG_DISCARD_UNSTABLE", "0")
        assert not dispatch.gang_discard_unstable()
