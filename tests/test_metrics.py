"""Metric unit + catalog assertions (reference semantics:
pkg/scheduler/metrics/metrics.go:38-121).

The load-bearing one: ``*_latency_microseconds`` histograms must observe
MICROSECONDS — the first four releases observed milliseconds into them,
so every exported plugin/action/task latency was 1000× off.
"""

from __future__ import annotations

import pytest

from volcano_tpu.metrics import metrics


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.registry.reset()
    yield
    metrics.registry.reset()


def _sum_of(rendered: str, series: str) -> float:
    for line in rendered.splitlines():
        if line.startswith(series + " ") or (
            line.startswith(series) and "} " in line and line.split("{")[0] == series.split("{")[0]
        ):
            if line.split(" ")[0] == series or line.startswith(series):
                return float(line.rsplit(" ", 1)[1])
    raise AssertionError(f"{series} not rendered:\n{rendered}")


def test_microsecond_histograms_observe_microseconds():
    metrics.update_plugin_duration("drf", 0.002)       # 2 ms
    metrics.update_action_duration("allocate", 0.050)  # 50 ms
    metrics.update_task_schedule_duration(0.000090)    # 90 µs
    out = metrics.registry.render()
    assert (
        'volcano_plugin_scheduling_latency_microseconds_sum{plugin="drf"} 2000.0'
        in out
    )
    assert (
        'volcano_action_scheduling_latency_microseconds_sum{action="allocate"} 50000.0'
        in out
    )
    assert "volcano_task_scheduling_latency_microseconds_sum 90.0" in out


def test_millisecond_histograms_observe_milliseconds():
    metrics.update_e2e_duration(0.120)
    metrics.update_job_schedule_duration(1.5)
    out = metrics.registry.render()
    assert "volcano_e2e_scheduling_latency_milliseconds_sum 120.0" in out
    assert "volcano_e2e_job_scheduling_latency_milliseconds_sum 1500.0" in out


def test_microsecond_buckets_cover_action_scale():
    # a 100 ms action must land in a finite bucket, not only +Inf
    metrics.update_action_duration("allocate", 0.100)
    h = metrics.registry.histogram(
        "volcano_action_scheduling_latency_microseconds", {"action": "allocate"}
    )
    assert h.buckets[-1] >= 100_000
    assert sum(h.counts[:-1]) == 1, "observation fell into +Inf"


def test_schedule_attempts_counter():
    metrics.register_schedule_attempt("scheduled")
    metrics.register_schedule_attempt("scheduled")
    metrics.register_schedule_attempt("unschedulable")
    out = metrics.registry.render()
    assert 'volcano_schedule_attempts_total{result="scheduled"} 2.0' in out
    assert 'volcano_schedule_attempts_total{result="unschedulable"} 1.0' in out


def test_schedule_attempts_from_real_session():
    """close_session's job updater registers one attempt per considered
    job, bucketed by outcome."""
    from volcano_tpu.actions.jax_allocate import JaxAllocateAction
    from volcano_tpu.framework import close_session, open_session

    from tests.builders import build_node, build_pod, build_pod_group, build_queue
    from tests.scheduler_helpers import make_cache, tiers

    cache = make_cache(
        nodes=[build_node("n0", {"cpu": "8", "memory": "16Gi"})],
        pods=[
            build_pod("ns", "ok-t0", "", {"cpu": "1", "memory": "1Gi"}, group="ok"),
            # min_available 3 with one pod: never gang-ready
            build_pod("ns", "sad-t0", "", {"cpu": "1", "memory": "1Gi"}, group="sad"),
        ],
        pod_groups=[
            build_pod_group("ns", "ok", 1, queue="q"),
            build_pod_group("ns", "sad", 3, queue="q"),
        ],
        queues=[build_queue("q")],
    )
    ssn = open_session(
        cache, tiers(["priority", "gang"], ["drf", "predicates", "nodeorder"]), []
    )
    JaxAllocateAction().execute(ssn)
    close_session(ssn)
    out = metrics.registry.render()
    assert 'volcano_schedule_attempts_total{result="scheduled"} 1.0' in out
    assert 'volcano_schedule_attempts_total{result="unschedulable"} 1.0' in out


def test_reference_catalog_names_render():
    """Name-for-name audit against the reference metric catalog
    (metrics.go:38-121): every exported family renders under the
    expected name."""
    metrics.update_plugin_duration("drf", 0.001)
    metrics.update_action_duration("allocate", 0.001)
    metrics.update_e2e_duration(0.001)
    metrics.update_job_schedule_duration(0.001)
    metrics.update_task_schedule_duration(0.001)
    metrics.update_pod_schedule_status("success")
    metrics.update_preemption_victims_count(2)
    metrics.register_preemption_attempts()
    metrics.update_unschedule_task_count("j", 1)
    metrics.update_unschedule_job_count(1)
    metrics.register_job_retries("j")
    metrics.register_schedule_attempt("scheduled")
    metrics.update_kernel_duration("pack", 0.001)
    metrics.observe_wal_fsync(0.001)
    metrics.update_wal_size(1024)
    metrics.update_repl_lag(2)
    metrics.update_repl_role("leader")
    metrics.register_bus_recovery("snapshot")
    metrics.register_bus_recovery("wal_tail")
    out = metrics.registry.render()
    for name in (
        "volcano_wal_fsync_latency_milliseconds",
        "volcano_wal_size_bytes",
        "volcano_repl_lag_entries",
        "volcano_repl_role",
        "volcano_bus_recoveries_total",
        "volcano_plugin_scheduling_latency_microseconds",
        "volcano_action_scheduling_latency_microseconds",
        "volcano_e2e_scheduling_latency_milliseconds",
        "volcano_e2e_job_scheduling_latency_milliseconds",
        "volcano_task_scheduling_latency_microseconds",
        "volcano_pod_schedule_success",
        "volcano_total_preemption_victims",
        "volcano_total_preemption_attempts",
        "volcano_unschedule_task_count",
        "volcano_unschedule_job_count",
        "volcano_job_retry_counts",
        "volcano_schedule_attempts_total",
        "volcano_tpu_kernel_latency_milliseconds",
    ):
        assert name in out, name


def test_job_latency_buckets_cover_minutes_scale():
    # a 90 s job-scheduling latency must land in a finite bucket
    metrics.update_job_schedule_duration(90.0)
    h = metrics.registry.histogram(
        "volcano_e2e_job_scheduling_latency_milliseconds", {}
    )
    assert h.buckets[-1] >= 90_000
    assert sum(h.counts[:-1]) == 1, "observation fell into +Inf"
