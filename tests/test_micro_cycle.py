"""Event-driven incremental micro-cycles (ISSUE 8 tentpole).

The contracts under test:

* **Bit-identity** — a micro-cycle runs the same session machinery over
  the same snapshot as a full cycle, so for any store state the
  bindings are identical whether the cycle was event-triggered (micro,
  warm fresh-task pack) or periodic (full) — over the in-process AND
  the ``--bus`` backends, and through ``trace.replay.verify`` on a
  recorded micro-cycle.
* **Debounce** — an event storm coalesces into few micro-cycles, not
  one per event.
* **Full-cycle routing** — gang arrival and node-topology change route
  to an immediate full cycle (``volcano_full_cycle_fallbacks_total``);
  registry overflow during a micro-triggered cycle is attributed as a
  pack-level fallback cause.
* **Interruptible sleep** — shutdown and event arrival no longer wait
  out ``--schedule-period``.
* **Chaos smoke** — the mixed fault schedule stays green with
  micro-cycles on (no duplicate binds, no lost jobs, coherence, pinned
  workload lands on its forced slots).
"""

from __future__ import annotations

import threading
import time

import pytest

from volcano_tpu import faults, trace
from volcano_tpu.bus.remote import RemoteAPIServer
from volcano_tpu.bus.server import BusServer
from volcano_tpu.cache import SchedulerCache
from volcano_tpu.client import APIServer, KubeClient, SchedulerClient, VolcanoClient
from volcano_tpu.metrics import metrics
from volcano_tpu.scheduler.scheduler import Scheduler

from tests.builders import build_node, build_pod, build_pod_group, build_queue

CONF = """
actions: "enqueue, jax-allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def _wait(pred, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _counter(suffix: str, **labels) -> float:
    want = tuple(sorted(labels.items()))
    with metrics.registry._lock:
        return sum(
            v for (name, lbl), v in metrics.registry._counters.items()
            if name.endswith(suffix) and (not want or lbl == want)
        )


@pytest.fixture(autouse=True)
def _clean():
    faults.configure(None)
    yield
    faults.configure(None)
    trace.disable()


class MicroCluster:
    """One scheduler over a store, event-driven.  ``backend`` picks how
    the cache sees the store: directly in-process, or through the real
    TCP bus (informers, binds, and events all over the wire)."""

    def __init__(self, tmp_path, name, backend="in-process", n_nodes=6,
                 node_cpu="8", micro=True, period=30.0, debounce_ms=5.0):
        self.api = APIServer()
        self.backend = backend
        self.bus = None
        self.remote = None
        if backend == "bus":
            self.bus = BusServer(self.api).start()
            self.remote = RemoteAPIServer(
                f"tcp://127.0.0.1:{self.bus.port}", timeout=5.0
            )
            assert self.remote.wait_ready(10.0)
            client_api = self.remote
        else:
            client_api = self.api
        self.kube = KubeClient(self.api)
        self.vc = VolcanoClient(self.api)
        self.vc.create_queue(build_queue("default"))
        self.n_nodes = n_nodes
        for i in range(n_nodes):
            self.kube.create_node(build_node(
                f"n{i}", {"cpu": node_cpu, "memory": "64Gi"},
                labels={"slot": f"s{i}"},
            ))
        self.cache = SchedulerCache(
            client=SchedulerClient(client_api), scheduler_name="volcano-tpu",
        )
        conf = tmp_path / f"{name}-conf.yaml"
        conf.write_text(CONF)
        self.scheduler = Scheduler(
            self.cache, scheduler_conf_path=str(conf), period=period,
            micro_cycles=micro, micro_debounce_ms=debounce_ms,
        )
        self.cache.run()  # idempotent — scheduler.run() re-calls it
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self.scheduler.run, name="micro-scheduler", daemon=True
        )
        self._thread.start()
        # the window opener: one full cycle has run before we return, so
        # later binds are attributable to event wakes
        assert _wait(lambda: self.scheduler.full_cycles_run >= 1)
        return self

    def submit(self, name, replicas=1, cpu="1", pin_slots=None, gang=False):
        self.vc.create_pod_group(
            build_pod_group("ns", name, replicas if gang else 1)
        )
        for i in range(replicas):
            selector = None
            if pin_slots is not None:
                selector = {"slot": f"s{pin_slots[i] % self.n_nodes}"}
            self.kube.create_pod(build_pod(
                "ns", f"{name}-t{i}", "", {"cpu": cpu, "memory": "1Gi"},
                group=name, selector=selector,
            ))

    def binding_map(self):
        return {
            f"{p.metadata.namespace}/{p.metadata.name}": p.spec.node_name
            for p in self.kube.list_pods("ns")
            if p.spec.node_name
        }

    def all_placed(self):
        pods = self.kube.list_pods("ns")
        return bool(pods) and all(p.spec.node_name for p in pods)

    def close(self):
        self.scheduler.stop()
        if self._thread is not None:
            self._thread.join(timeout=10)
            assert not self._thread.is_alive(), (
                "scheduler.run did not exit after stop()"
            )
        self.cache.stop_commit_plane()
        if self.remote is not None:
            self.remote.close()
        if self.bus is not None:
            self.bus.stop()


WORKLOAD_ROUNDS = (
    # (name, replicas, cpu) batches — round 2 crosses the 64-row task
    # bucket when stacked on round 1's leftovers, so the fresh-task
    # micro pack path runs, not just the gather-warm path
    [("a", 3, "1"), ("b", 2, "2")],
    [("c", 4, "1"), ("d", 1, "500m")],
)


class TestBitIdentity:
    @pytest.mark.parametrize("backend", ["in-process", "bus"])
    def test_micro_equals_full_over_same_store_states(self, tmp_path, backend):
        """Drive two identical clusters through the same sequence of
        (submit batch, one cycle) steps — one cycling micro, one full.
        Every intermediate store state is identical, so the binding
        maps must be too."""
        micro = MicroCluster(tmp_path, f"mi-{backend}", backend=backend)
        full = MicroCluster(tmp_path, f"fu-{backend}", backend=backend)
        try:
            for round_i, batch in enumerate(WORKLOAD_ROUNDS):
                for name, replicas, cpu in batch:
                    micro.submit(f"{name}", replicas=replicas, cpu=cpu)
                    full.submit(f"{name}", replicas=replicas, cpu=cpu)
                if backend == "bus":
                    # informers settle before the cycle reads the cache
                    assert _wait(lambda: len(micro.cache.jobs) >= 1)
                    time.sleep(0.3)
                micro.scheduler.run_once(trigger="task")
                full.scheduler.run_once()
                assert _wait(
                    lambda: micro.binding_map() == full.binding_map()
                    and micro.all_placed(),
                    timeout=15.0,
                ), (
                    f"round {round_i}: micro={micro.binding_map()} "
                    f"full={full.binding_map()}"
                )
            assert micro.scheduler.micro_cycles_run == len(WORKLOAD_ROUNDS)
        finally:
            micro.close()
            full.close()

    def test_micro_cycle_replay_verifies(self, tmp_path):
        """trace.replay.verify over a RECORDED micro-cycle: re-running
        the captured packed session through the kernel reproduces the
        recorded bindings exactly — the standard equivalence harness
        every perf PR pins against, applied to the micro path."""
        jdir = str(tmp_path / "journal")
        trace.enable(jdir, snapshot_every=1)
        cluster = MicroCluster(tmp_path, "replay")
        try:
            cluster.submit("r0", replicas=3)
            cluster.scheduler.run_once()  # full, warms the pack cache
            cluster.submit("r1", replicas=2)
            cluster.scheduler.run_once(trigger="task")  # the micro cycle
            assert cluster.all_placed()
        finally:
            cluster.close()
            trace.disable()
        result = trace.replay.verify(jdir, executor="jax")
        assert result.match, result.summary()


class TestEventLoop:
    def test_event_wake_binds_long_before_period(self, tmp_path):
        """period=30s; a submitted pod binds within a couple of seconds
        because the watch event wakes the loop (satellite: the sleep is
        a condition wait, not a time.sleep)."""
        cluster = MicroCluster(tmp_path, "wake", period=30.0).start()
        try:
            t0 = time.monotonic()
            cluster.submit("w0", replicas=2)
            assert _wait(cluster.all_placed, timeout=10.0)
            assert time.monotonic() - t0 < 10.0
            assert cluster.scheduler.micro_cycles_run >= 1
        finally:
            t0 = time.monotonic()
            cluster.close()
            # shutdown did not wait out the 30s period either
            assert time.monotonic() - t0 < 10.0

    def test_debounce_coalesces_event_storm(self, tmp_path):
        """20 jobs land inside the debounce window(s): far fewer
        micro-cycles than events."""
        cluster = MicroCluster(
            tmp_path, "storm", period=30.0, debounce_ms=150.0,
            node_cpu="64",
        ).start()
        try:
            for i in range(20):
                cluster.submit(f"s{i}", replicas=1, cpu="100m")
            # the cycle's counter lands at cycle END (binds are visible
            # mid-cycle) — wait for both
            assert _wait(
                lambda: cluster.all_placed()
                and cluster.scheduler.micro_cycles_run >= 1,
                timeout=30.0,
            )
            ran = cluster.scheduler.micro_cycles_run
            assert ran <= 6, (
                f"storm of 20 arrivals should coalesce, ran {ran} cycles"
            )
        finally:
            cluster.close()

    def test_plain_mode_sleep_is_interruptible(self, tmp_path):
        """Non-micro loop: stop() returns immediately instead of
        sleeping out the period."""
        cluster = MicroCluster(tmp_path, "plain", micro=False, period=60.0)
        thread = threading.Thread(target=cluster.scheduler.run, daemon=True)
        thread.start()
        try:
            assert _wait(lambda: cluster.scheduler.full_cycles_run >= 1)
            t0 = time.monotonic()
            cluster.scheduler.stop()
            thread.join(timeout=10)
            assert not thread.is_alive()
            assert time.monotonic() - t0 < 5.0
        finally:
            cluster.close()


class TestFullCycleRouting:
    def test_gang_arrival_routes_to_full_cycle(self, tmp_path):
        before = _counter("full_cycle_fallbacks_total", cause="gang-arrival")
        cluster = MicroCluster(tmp_path, "gang", period=30.0).start()
        try:
            fulls0 = cluster.scheduler.full_cycles_run
            cluster.submit("g0", replicas=3, gang=True)
            assert _wait(cluster.all_placed, timeout=15.0)
            assert _wait(lambda: _counter(
                "full_cycle_fallbacks_total", cause="gang-arrival"
            ) > before)
            # polled, not asserted flat: binds land at store truth (and
            # the fallback counter registers at window start) while
            # run_once is still closing the session — full_cycles_run
            # increments only after the cycle returns
            assert _wait(lambda: cluster.scheduler.full_cycles_run > fulls0)
        finally:
            cluster.close()

    def test_topology_change_routes_to_full_cycle(self, tmp_path):
        before = _counter("full_cycle_fallbacks_total", cause="topology")
        cluster = MicroCluster(tmp_path, "topo", period=30.0).start()
        try:
            cluster.kube.create_node(
                build_node("late-node", {"cpu": "8", "memory": "64Gi"})
            )
            assert _wait(lambda: _counter(
                "full_cycle_fallbacks_total", cause="topology"
            ) > before, timeout=10.0)
        finally:
            cluster.close()

    def test_registry_overflow_attributed_during_micro_cycle(self, tmp_path):
        """A micro-triggered cycle whose pack had to go cold (registry
        overflow) counts the pack-level cause."""
        before = _counter("full_cycle_fallbacks_total",
                          cause="registry-overflow")
        cluster = MicroCluster(tmp_path, "overflow")
        try:
            cluster.submit("o0", replicas=2)
            cluster.scheduler.run_once()  # warm the pack cache
            cluster.cache.pack_cache.label_reg.overflow = True
            cluster.submit("o1", replicas=2)
            cluster.scheduler.run_once(trigger="task")
            assert cluster.all_placed()
            assert _counter(
                "full_cycle_fallbacks_total", cause="registry-overflow"
            ) > before
            # the cold rebuild recovered the registry
            assert not cluster.cache.pack_cache.label_reg.overflow
        finally:
            cluster.close()


class TestMicroMetrics:
    def test_micro_counters_and_submit_to_bind_histogram(self, tmp_path):
        cluster = MicroCluster(tmp_path, "metrics", period=30.0).start()
        try:
            micro_before = _counter("micro_cycles_total")

            def _hist_count(suffix):
                with metrics.registry._lock:
                    return sum(
                        h.total for (n, _l), h in
                        metrics.registry._histograms.items()
                        if n.endswith(suffix)
                    )

            s2b_before = _hist_count("submit_to_bind_latency_milliseconds")
            lat_before = _hist_count("micro_cycle_latency_milliseconds")
            # epoch-stamped pod: the store assigns creation_timestamp,
            # which is what the submit→bind histogram keys on
            cluster.vc.create_pod_group(build_pod_group("ns", "m0", 1))
            pod = build_pod("ns", "m0-t0", "", {"cpu": "1", "memory": "1Gi"},
                            group="m0")
            pod.metadata.creation_timestamp = 0.0
            cluster.kube.create_pod(pod)
            assert _wait(cluster.all_placed, timeout=15.0)
            assert _wait(lambda: _counter("micro_cycles_total") > micro_before)
            assert _hist_count("micro_cycle_latency_milliseconds") > lat_before
            assert _wait(lambda: _hist_count(
                "submit_to_bind_latency_milliseconds") > s2b_before)
        finally:
            cluster.close()


class TestChaosSmokeMicro:
    def test_mixed_faults_with_micro_cycles_on(self, tmp_path):
        """The chaos acceptance bar with the event-driven loop doing the
        scheduling: every seam faulted while micro-cycles fire; the run
        must converge with zero duplicate binds, zero lost jobs,
        cache/store coherence, and the selector-pinned workload on its
        forced slots."""
        from tests.test_chaos import ChaosCluster, MIXED_FAULTS

        cluster = ChaosCluster(tmp_path, "micro-chaos")
        # swap in an event-driven scheduler over the same cache/conf
        cluster.scheduler = Scheduler(
            cluster.scheduler.cache,
            scheduler_conf_path=cluster.scheduler.scheduler_conf_path,
            period=2.0, micro_cycles=True, micro_debounce_ms=5.0,
        )
        thread = threading.Thread(
            target=cluster.scheduler.run, daemon=True, name="chaos-micro"
        )
        try:
            faults.configure(MIXED_FAULTS.format(seed=4321))
            thread.start()
            cluster.submit("free-a", replicas=3)
            cluster.submit("free-b", replicas=2)
            cluster.submit("pinned", replicas=4, pin_slots=[4, 5, 6, 7])
            deadline = time.monotonic() + 25.0
            while time.monotonic() < deadline:
                cluster._kubelet_drain()
                time.sleep(0.05)
                if cluster.all_placed() and time.monotonic() > deadline - 20:
                    break
            faults.configure(None)
            assert _wait(
                lambda: (cluster._kubelet_drain() or True)
                and cluster.all_placed(),
                timeout=30.0, interval=0.05,
            ), "pods still unplaced with micro-cycles on"
            assert len(cluster.pods()) == 9
            cluster.assert_no_duplicate_binds()
            cluster.assert_coherent()
            # forced placements: the pinned job's selectors admit one
            # slot each, so convergence implies these exact bindings
            bmap = cluster.binding_map()
            for i, slot in enumerate([4, 5, 6, 7]):
                assert bmap[f"ns/pinned-t{i}"] == f"n{slot}"
            # a post-chaos arrival schedules promptly through the event
            # wake (period is 2 s — an unwoken loop would sit out most
            # of it), and at least one micro-cycle ran over the test
            cluster.submit("late", replicas=1)
            t0 = time.monotonic()
            assert _wait(
                lambda: (cluster._kubelet_drain() or True)
                and cluster.all_placed(),
                timeout=20.0, interval=0.05,
            )
            assert _wait(
                lambda: cluster.scheduler.micro_cycles_run >= 1,
                timeout=5.0,
            ), f"no micro-cycle ran (late bind took {time.monotonic()-t0:.2f}s)"
            cluster.assert_no_duplicate_binds()
        finally:
            cluster.scheduler.stop()
            thread.join(timeout=10)
            faults.configure(None)
            faults.reset_breakers()
            cluster.close()
