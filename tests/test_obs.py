"""Cluster-wide flight recorder (ISSUE 12): span contexts, the
drop-not-block telemetry channel, cross-process propagation over VBUS,
the vtctl trace/top surfaces, telemetry-under-faults, the MTR metric-
hygiene pass, identity labels, and the merged multi-process Chrome
export.

The tier-1 cross-process test runs the scheduler in THIS process
against a real persistent ``vtpu-apiserver`` OS process and a real
``vtpu-controllers`` OS process — three processes, one waterfall.  The
slow test runs the full federated topology (2 scheduler shards, a
2-replica apiserver group, controllers) and pins the cross-shard gang's
txn_commit / WAL-fsync / quorum-wait span chain."""

from __future__ import annotations

import io
import json
import os
import subprocess
import sys
import time

import pytest

from volcano_tpu import faults, obs
from volcano_tpu.apis import core
from volcano_tpu.client import APIServer, KubeClient, VolcanoClient
from volcano_tpu.metrics import metrics
from volcano_tpu.metrics import scrape as mscrape
from volcano_tpu.obs.channel import SpanExporter

from tests.builders import build_node, build_queue


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    metrics.registry.reset()
    yield
    obs.disable()
    metrics.registry.reset()
    faults.configure(None)


def _wait(pred, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ---- span contexts ----

class TestSpanContext:
    def test_trace_ids_stable_and_distinct(self):
        a = obs.trace_id_for_pod("ns", "p1")
        assert a == obs.trace_id_for_pod("ns", "p1")
        assert a != obs.trace_id_for_pod("ns", "p2")
        assert a != obs.trace_id_for_gang("ns2", "p1")
        assert obs.trace_id_for_gang("ns", "g") == obs.trace_id_for("ns", "g")

    def test_disabled_is_null_and_costless(self):
        assert not obs.enabled()
        with obs.span("x") as s:
            assert s.span_id == ""
            assert obs.current_wire() is None
        obs.complete("y", 0.1)  # no-op, no error

    def test_nesting_parents_and_wire(self):
        api = APIServer()
        exp = obs.enable(api, identity="t", flush_interval=3600)
        with obs.span("outer") as outer:
            with obs.span("inner",
                          trace_id=obs.trace_id_for_pod("ns", "p")) as inner:
                w = obs.current_wire()
                assert w == {"t": obs.trace_id_for_pod("ns", "p"),
                             "s": inner.span_id}
            assert obs.current_wire()["s"] == outer.span_id
        assert obs.current_wire() is None
        exp.flush_all()
        spans = {s["name"]: s for s in obs.collect_spans(api)}
        assert spans["inner"]["p"] == spans["outer"]["s"]
        # explicit trace_id re-roots the trace, keeps the parent link
        assert spans["inner"]["t"] == obs.trace_id_for_pod("ns", "p")
        assert spans["outer"]["t"] == ""

    def test_adopt_parents_to_remote_context(self):
        api = APIServer()
        exp = obs.enable(api, identity="t", flush_interval=3600)
        with obs.adopt({"t": "abcd1234", "s": "peer-7"}, "bus:create"):
            pass
        with obs.adopt(None, "local"):  # degraded: plain local span
            pass
        exp.flush_all()
        spans = {s["name"]: s for s in obs.collect_spans(api)}
        assert spans["bus:create"]["p"] == "peer-7"
        assert spans["bus:create"]["t"] == "abcd1234"
        assert spans["local"]["p"] == ""

    def test_suppression_blocks_emission(self):
        api = APIServer()
        exp = obs.enable(api, identity="t", flush_interval=3600)
        with obs.suppressed():
            assert not obs.enabled()
            with obs.span("hidden"):
                obs.complete("also-hidden", 0.01)
        assert exp.flush_all() == 0


# ---- telemetry channel ----

class TestChannel:
    def test_segments_land_and_rotate_bounded(self):
        api = APIServer()
        exp = SpanExporter(api, "d0", batch=2, segments=3,
                           flush_interval=3600)
        for i in range(10):
            exp.emit({"t": "", "s": f"s{i}", "p": "", "name": f"n{i}",
                      "ts": float(i), "dur": 1.0})
        exp.flush_all()
        segs = [cm for cm in api.list("ConfigMap", obs.NAMESPACE)]
        # 5 batches over 3 slots: retention is the slot ring, honestly
        assert len(segs) == 3
        spans = obs.collect_spans(api)
        assert 0 < len(spans) <= 10
        assert exp.exported == 10 and exp.dropped == 0

    def test_ring_full_drops_not_blocks(self):
        api = APIServer()
        exp = SpanExporter(api, "d0", ring=4, flush_interval=3600)
        t0 = time.perf_counter()
        for i in range(100):
            exp.emit({"s": f"s{i}", "name": "n", "ts": 0.0})
        assert time.perf_counter() - t0 < 1.0  # never blocked
        assert exp.dropped == 96
        r = metrics.registry.render()
        assert 'volcano_telemetry_dropped_total{reason="ring-full"} 96' in r

    def test_sampling_keeps_or_drops_whole_traces(self):
        api = APIServer()
        exp = SpanExporter(api, "d0", sample=0.5, flush_interval=3600)
        ids = [obs.trace_id_for_pod("ns", f"p{i}") for i in range(200)]
        kept = [t for t in ids if exp.keep(t)]
        assert 0 < len(kept) < len(ids)  # some of each
        # decision is a pure function of the id — every process agrees
        assert all(exp.keep(t) for t in kept)
        assert exp.keep("")  # process-scope spans always kept
        none = SpanExporter(api, "d1", sample=0.0, flush_interval=3600)
        assert not none.keep(ids[0]) and none.keep("")

    def test_sampled_out_trace_drops_whole_subtree(self):
        """Keep-or-drop-whole-traces: a sampled-out span still pushes
        its (dropped) context, so descendants inherit the dropped
        trace id and drop with it — on BOTH sides of the wire —
        instead of leaking into the enclosing process-scope trace."""
        api = APIServer()
        exp = obs.enable(api, identity="t", flush_interval=3600)
        dropped_tid = next(
            t for t in (obs.trace_id_for_pod("ns", f"g{i}")
                        for i in range(1000))
            if not SpanExporter(api, "x", sample=0.5,
                                flush_interval=3600).keep(t)
        )
        exp.sample = 0.5
        assert not exp.keep(dropped_tid)
        with obs.span("cycle"):  # kept: process scope
            with obs.span("gang:assemble", trace_id=dropped_tid):
                # descendants inherit the DROPPED id, not the cycle's
                w = obs.current_wire()
                assert w is not None and w["t"] == dropped_tid
                with obs.span("gang:txn_commit"):
                    obs.complete("wal:fsync", 0.001)
                # server side: adopting the dropped context drops too
                with obs.adopt(w, "bus:txn_commit"):
                    obs.complete("repl:quorum_wait", 0.001)
        exp.flush_all()
        names = {s["name"] for s in obs.collect_spans(api)}
        assert names == {"cycle"}, names

    def test_export_error_drops_and_counts(self):
        class DeadApi:
            def create(self, obj):
                raise RuntimeError("bus down")

        exp = SpanExporter(DeadApi(), "d0", flush_interval=3600)
        exp.emit({"s": "s1", "name": "n", "ts": 0.0})
        assert exp.flush() == 0  # dropped, never raised
        assert exp.dropped == 1
        r = metrics.registry.render()
        assert ('volcano_telemetry_dropped_total{reason="export-error"} 1'
                in r)


# ---- selection + rendering ----

def _mk(name, sid, parent="", trace="", daemon="d", pid=1, ts=0.0, dur=1.0,
        args=None):
    s = {"name": name, "s": sid, "p": parent, "t": trace, "daemon": daemon,
         "pid": pid, "ts": ts, "dur": dur, "tid": 1}
    if args:
        s["args"] = args
    return s


class TestSelectTrace:
    def test_closure_up_and_process_scope_down(self):
        t_p1 = obs.trace_id_for_pod("ns", "p1")
        t_p2 = obs.trace_id_for_pod("ns", "p2")
        spans = [
            _mk("cycle", "c1", ts=0.0, dur=10.0),
            _mk("kernel:execute", "k1", parent="c1", ts=1.0),
            _mk("bind:landed", "b1", parent="c1", trace=t_p1, ts=5.0),
            _mk("bind:landed", "b2", parent="c1", trace=t_p2, ts=6.0),
            _mk("unrelated", "u1", ts=7.0),
        ]
        sel = obs.select_trace(spans, "ns", "p1")
        names = {s["s"] for s in sel}
        # own span + ancestor cycle + the cycle's process-scope kernel —
        # but NOT the other pod's bind, and not the unrelated root
        assert names == {"c1", "k1", "b1"}

    def test_gang_arg_matches(self):
        tg = obs.trace_id_for_gang("ns", "g1")
        spans = [
            _mk("gang:assemble", "a1", trace=tg, args={"gang": "ns/g1"}),
            _mk("bind:landed", "b1", trace=obs.trace_id_for_pod("ns", "m0"),
                args={"gang": "ns/g1"}),
        ]
        sel = obs.select_trace(spans, "ns", "g1")
        assert {s["s"] for s in sel} == {"a1", "b1"}

    def test_waterfall_and_chrome_multiprocess(self):
        spans = [
            _mk("cycle", "c1", daemon="sched", pid=11, ts=0.0, dur=10.0),
            _mk("bus:create", "x1", parent="c1", daemon="apiserver",
                pid=22, ts=2.0, dur=3.0),
        ]
        out = io.StringIO()
        obs.render_waterfall(spans, out)
        text = out.getvalue()
        assert "cycle" in text and "bus:create" in text
        assert "2 daemon(s) / 2 process(es)" in text
        ch = obs.chrome_export(spans)
        pids = {e["pid"] for e in ch["traceEvents"] if e.get("ph") == "X"}
        assert len(pids) == 2
        names = {e["args"]["name"] for e in ch["traceEvents"]
                 if e.get("ph") == "M"}
        assert names == {"sched", "apiserver"}


# ---- cross-process: 3 OS processes, one waterfall (tier-1) ----

def _spawn(module, *args):
    return subprocess.Popen(
        [sys.executable, "-m", module, *args],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestCrossProcessWaterfall:
    def test_waterfall_spans_three_os_processes(self, tmp_path):
        """Scheduler (this process) + persistent vtpu-apiserver +
        vtpu-controllers, each a real OS process with the flight
        recorder on: `vtctl trace pod` renders one submit→bind
        waterfall whose spans come from all three, with the bus op and
        WAL fsync parented under the scheduler's cycle."""
        from volcano_tpu.bus import connect_bus
        from volcano_tpu.cache import SchedulerCache
        from volcano_tpu.cli.vtctl import main as vtctl_main
        from volcano_tpu.client import SchedulerClient
        from volcano_tpu.cmd.local_up import seed_cluster
        from volcano_tpu.scheduler.scheduler import Scheduler

        port = _free_port()
        bus_url = f"tcp://127.0.0.1:{port}"
        procs = [_spawn(
            "volcano_tpu.cmd.apiserver",
            "--port", str(port), "--listen-port", "0",
            "--data-dir", str(tmp_path / "wal"),
            "--flight-recorder",
        )]
        api = sched_remote = None
        cache = None
        try:
            api = connect_bus(bus_url, wait=30.0)
            seed_cluster(api, nodes=2, node_cpu="8", node_mem="16Gi")
            procs.append(_spawn(
                "volcano_tpu.cmd.controllers",
                "--bus", bus_url, "--listen-port", "0",
                "--period", "0.05", "--flight-recorder",
                "--leader-elect-id", "ctrl-0",
            ))
            sched_remote = connect_bus(bus_url, wait=10.0)
            obs.enable(sched_remote, identity="sched-0",
                       flush_interval=0.05)
            cache = SchedulerCache(client=SchedulerClient(sched_remote),
                                   scheduler_name="volcano-tpu")
            scheduler = Scheduler(cache, period=0.05)
            cache.run()
            cache.wait_for_cache_sync()

            from volcano_tpu.apis import batch

            VolcanoClient(api).create_job(batch.Job(
                metadata=core.ObjectMeta(name="wf", namespace="default"),
                spec=batch.JobSpec(
                    min_available=1, queue="default",
                    scheduler_name="volcano-tpu",
                    tasks=[batch.TaskSpec(
                        name="t", replicas=1,
                        template=core.PodTemplateSpec(spec=core.PodSpec(
                            containers=[core.Container(
                                name="c", image="busybox",
                                resources={"requests": {"cpu": "1",
                                                        "memory": "1Gi"}},
                            )],
                        )),
                    )],
                ),
            ))

            def pod_bound():
                scheduler.run_once()
                pod = api.get("Pod", "default", "wf-t-0")
                return pod is not None and bool(pod.spec.node_name)

            assert _wait(pod_bound, timeout=60.0, interval=0.1), (
                "pod never bound over the 3-process topology"
            )
            obs.get_exporter().flush_all()
            # controllers flush on their own interval; wait for their
            # spans to land as durable segments
            def _select(spans):
                return obs.select_union(
                    spans,
                    obs.related_identities(api, "default", "wf-t-0"),
                )

            def has_three_daemons():
                sel = _select(obs.collect_spans(api))
                return len({s.get("daemon") for s in sel}) >= 3

            assert _wait(has_three_daemons, timeout=20.0, interval=0.25), (
                "waterfall never spanned 3 daemons: "
                + str(sorted({s.get('daemon')
                              for s in obs.collect_spans(api)}))
            )

            spans = obs.collect_spans(api)
            sel = _select(spans)
            daemons = {s.get("daemon") for s in sel}
            pids = {s.get("pid") for s in sel}
            assert len(daemons) >= 3, daemons
            assert len(pids) >= 3, pids
            names = {s["name"] for s in sel}
            assert "bind:landed" in names
            assert any(n.startswith("cycle:") for n in names)
            assert any(n.startswith("bus:") for n in names)
            assert "wal:fsync" in names
            assert "controller:status" in names
            by_id = {s["s"]: s for s in sel}
            # the fsync parents into a bus op, the bus op into a span
            # recorded by ANOTHER process (the cross-process stitch)
            fsync = next(s for s in sel if s["name"] == "wal:fsync")
            busop = by_id[fsync["p"]]
            assert busop["name"].startswith("bus:")
            assert by_id[busop["p"]].get("daemon") != busop.get("daemon")

            # the vtctl surface renders it, over the bus backend
            out = io.StringIO()
            rc = vtctl_main(
                ["trace", "pod", "-n", "default", "-N", "wf-t-0"],
                api=api, out=out,
            )
            text = out.getvalue()
            assert rc == 0
            assert "bind:landed" in text and "wal:fsync" in text
            chrome_path = str(tmp_path / "merged.json")
            out = io.StringIO()
            rc = vtctl_main(
                ["trace", "pod", "-n", "default", "-N", "wf-t-0",
                 "--chrome", chrome_path],
                api=api, out=out,
            )
            assert rc == 0
            ch = json.load(open(chrome_path))
            chrome_pids = {e["pid"] for e in ch["traceEvents"]
                           if e.get("ph") == "X"}
            assert len(chrome_pids) >= 3
        finally:
            obs.disable()
            if cache is not None:
                cache.stop_commit_plane()
            if sched_remote is not None:
                sched_remote.close()
            if api is not None:
                api.close()
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


class TestFederatedGangWaterfall:
    def test_cross_shard_gang_txn_chain(self, tmp_path):
        """The acceptance waterfall: 2 scheduler-shard processes + a
        2-replica persistent apiserver group + controllers — a gang
        larger than any one shard binds via txn_commit, and its trace
        carries gang:txn_commit → bus:txn_commit → wal:fsync and the
        repl:quorum_wait span, correctly parented, across ≥3 OS
        processes."""
        from volcano_tpu.bus import connect_bus

        ports = [_free_port(), _free_port()]
        endpoints = ",".join(f"tcp://127.0.0.1:{p}" for p in ports)
        procs = []
        api = None
        try:
            for i, port in enumerate(ports):
                procs.append(_spawn(
                    "volcano_tpu.cmd.apiserver",
                    "--port", str(port), "--listen-port", "0",
                    "--data-dir", str(tmp_path / f"r{i}"),
                    "--replicas", endpoints, "--replica-index", str(i),
                    "--repl-lease-ttl", "1.0",
                    "--flight-recorder",
                ))
            api = connect_bus(endpoints, wait=60.0)
            kube = KubeClient(api)
            vc = VolcanoClient(api)
            vc.create_queue(build_queue("default"))
            # n0-n3 hash to shard 0, n4-n7 to shard 1 (crc32 % 2): four
            # single-gang-task nodes per shard
            for i in range(8):
                kube.create_node(build_node(f"n{i}", {"cpu": "4",
                                                      "memory": "16Gi"}))
            procs.append(_spawn(
                "volcano_tpu.cmd.controllers",
                "--bus", endpoints, "--listen-port", "0",
                "--period", "0.05", "--flight-recorder",
                "--leader-elect-id", "ctrl-0",
            ))
            for i in range(2):
                procs.append(_spawn(
                    "volcano_tpu.cmd.scheduler",
                    "--bus", endpoints, "--listen-port", "0",
                    "--shards", "2", "--shard-identity", f"shard-{i}",
                    "--shard-lease-duration", "1.5",
                    "--schedule-period", "0.2", "--micro-cycles",
                    "--gang-broker", "on", "--flight-recorder",
                ))

            # the federation must actually FORM first (two distinct
            # holders): a lone early member absorbs both shards and
            # would bind the gang locally, bypassing the broker
            from volcano_tpu.federation import read_shard_map

            def two_holders():
                rec = read_shard_map(api)
                if not rec:
                    return False
                holders = {
                    e.get("holder")
                    for e in rec.get("shards", {}).values()
                }
                holders.discard("")
                return len(holders) == 2

            assert _wait(two_holders, timeout=60.0, interval=0.25), (
                "federation never formed two distinct shard holders"
            )

            # a 5-member gang Job of node-sized tasks with 4 nodes per
            # shard: no shard can host it alone, so binding it
            # REQUIRES the cross-shard txn_commit assembly.  Submitted
            # as a Job so the CONTROLLERS process creates the PodGroup
            # and pods and writes the status back — its spans share
            # the "ns/gang" identity (the PodGroup is named after the
            # job), putting all three daemon kinds in one waterfall.
            from volcano_tpu.apis import batch

            vc.create_job(batch.Job(
                metadata=core.ObjectMeta(name="gang", namespace="ns"),
                spec=batch.JobSpec(
                    min_available=5, queue="default",
                    scheduler_name="volcano-tpu",
                    tasks=[batch.TaskSpec(
                        name="t", replicas=5,
                        template=core.PodTemplateSpec(spec=core.PodSpec(
                            containers=[core.Container(
                                name="c", image="busybox",
                                resources={"requests": {
                                    "cpu": "4", "memory": "1Gi"}},
                            )],
                        )),
                    )],
                ),
            ))

            def all_bound():
                pods = kube.list_pods("ns")
                return len(pods) == 5 and all(
                    p.spec.node_name for p in pods
                )

            assert _wait(all_bound, timeout=120.0, interval=0.25), (
                "gang never assembled across shards"
            )

            def chain_present():
                spans = obs.collect_spans(api)
                sel = obs.select_trace(spans, "ns", "gang")
                names = {s["name"] for s in sel}
                return {"gang:txn_commit", "bus:txn_commit",
                        "wal:fsync"} <= names
            assert _wait(chain_present, timeout=30.0, interval=0.5), (
                "txn span chain never landed: "
                + str({s['name'] for s in obs.select_trace(
                    obs.collect_spans(api), 'ns', 'gang')})
            )
            spans = obs.collect_spans(api)
            sel = obs.select_trace(spans, "ns", "gang")
            by_id = {s["s"]: s for s in sel}
            names = {s["name"] for s in sel}
            assert "repl:quorum_wait" in names, names
            bus_txn = next(s for s in sel if s["name"] == "bus:txn_commit")
            gang_txn = by_id[bus_txn["p"]]
            assert gang_txn["name"] == "gang:txn_commit"
            fsync = next(s for s in sel if s["name"] == "wal:fsync")
            assert by_id[fsync["p"]]["name"].startswith("bus:")
            quorum = next(s for s in sel
                          if s["name"] == "repl:quorum_wait")
            assert by_id[quorum["p"]]["name"].startswith("bus:")
            assert len({s.get("pid") for s in sel}) >= 3

            # CI artifact hook (the VTPU_CHAOS_JOURNAL_DIR discipline):
            # the merged multi-process timeline ships as the
            # `flight-recorder` artifact next to gang-slo
            art = os.environ.get("VTPU_FLIGHT_RECORDER_ARTIFACT")
            if art:
                os.makedirs(art, exist_ok=True)
                with open(os.path.join(art, "gang-waterfall.json"),
                          "w") as f:
                    json.dump(obs.chrome_export(sel), f, indent=1)
                out = io.StringIO()
                obs.render_waterfall(sel, out)
                with open(os.path.join(art, "gang-waterfall.txt"),
                          "w") as f:
                    f.write(out.getvalue())
        finally:
            if api is not None:
                api.close()
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


# ---- telemetry under faults (satellite) ----

class TestTelemetryUnderFaults:
    def test_bus_faults_drop_never_raise(self):
        """bus.disconnect / bus.delay against the export path: spans
        are dropped and counted, emission never raises and never
        blocks."""
        from volcano_tpu.bus.remote import RemoteAPIServer
        from volcano_tpu.bus.server import BusServer

        store = APIServer()
        srv = BusServer(store).start()
        remote = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}", timeout=1.0)
        try:
            assert remote.wait_ready(5)
            exp = SpanExporter(remote, "d0", flush_interval=3600)
            faults.configure("seed=3;bus.client_drop=1:count=50")
            for i in range(8):
                exp.emit({"s": f"s{i}", "name": "n", "ts": 0.0})
            t0 = time.perf_counter()
            exp.flush_all()
            assert time.perf_counter() - t0 < 5.0
            assert exp.dropped == 8 and exp.exported == 0
            r = metrics.registry.render()
            assert 'reason="export-error"' in r
        finally:
            faults.configure(None)
            remote.close()
            srv.stop()

    def test_wal_write_fail_drops_never_raises(self, tmp_path):
        from volcano_tpu.bus.wal import PersistentAPIServer

        api = PersistentAPIServer(str(tmp_path / "wal"))
        try:
            exp = SpanExporter(api, "d0", flush_interval=3600)
            faults.configure("seed=5;wal.write_fail=1:count=50")
            exp.emit({"s": "s1", "name": "n", "ts": 0.0})
            assert exp.flush() == 0
            assert exp.dropped == 1
        finally:
            faults.configure(None)
            api.close()

    def test_chaos_smoke_bit_identical_with_tracing_on(self, tmp_path):
        """The chaos twin with the flight recorder ON both sides: the
        pinned workload's binding map stays bit-identical, and the
        faulted run's telemetry dropped-never-blocked."""
        from tests.test_chaos import ChaosCluster, _submit_mixed_workload

        maps = {}
        for label, spec in (
            ("faulty", "seed=77;bus.disconnect=0.05:count=3;"
                       "bus.delay=0.08:count=5:ms=5;"
                       "bus.client_drop=0.05:count=4;"
                       "cache.bind_fail=0.1:count=3"),
            ("clean", None),
        ):
            cluster = ChaosCluster(tmp_path, f"obs-{label}",
                                   compute_plane=False)
            try:
                # the recorder rides the REMOTE client — exactly the
                # path the bus faults hit
                obs.enable(cluster.remote, identity=f"sched-{label}",
                           flush_interval=0.05)
                _submit_mixed_workload(cluster)
                faults.configure(spec)
                cluster.run_cycles(10)
                faults.configure(None)
                assert _wait(
                    lambda: (cluster.cycle() or True)
                    and cluster.all_placed(),
                    timeout=30.0, interval=0.05,
                ), f"{label}: pods still unplaced with tracing on"
                cluster.assert_no_duplicate_binds()
                assert cluster.cycle_errors == 0, (
                    "telemetry must never raise into the scheduler"
                )
                maps[label] = cluster.binding_map()
            finally:
                obs.disable()
                cluster.close()
                faults.configure(None)
                faults.reset_breakers()
        pinned = {k: v for k, v in maps["faulty"].items() if "pinned" in k}
        pinned_clean = {k: v for k, v in maps["clean"].items()
                        if "pinned" in k}
        assert pinned == pinned_clean and len(pinned) == 4
        assert set(maps["faulty"]) == set(maps["clean"])


# ---- vtctl top (federated metrics) ----

class TestVtctlTop:
    def test_aggregates_discovered_members(self):
        from volcano_tpu.cli.vtctl import main as vtctl_main
        from volcano_tpu.metrics.metrics import _Registry
        from volcano_tpu.serving.http import ServingServer

        # two fake members with their own registries and identities
        regs = []
        servers = []
        for i, ident in enumerate(("shard-a", "shard-b")):
            reg = _Registry()
            reg.set_identity(daemon="scheduler", shard=ident)
            h = reg.histogram(
                "volcano_submit_to_bind_latency_milliseconds", {},
                buckets=[5.0, 10.0, 20.0],
            )
            for v in (4.0, 8.0, 16.0 + i * 2):
                h.observe(v)
            reg.inc("volcano_pod_schedule_successes", {}, 3)
            regs.append(reg)
            servers.append(ServingServer(registry=reg).start())
        api = APIServer()
        # a shard map advertising both members' metrics addrs
        from volcano_tpu.federation.leases import (
            NAMESPACE as SM_NS,
            SHARD_MAP_KEY,
            SHARD_MAP_NAME,
        )

        rec = {
            "nShards": 2, "members": {}, "shards": {},
            "stats": {
                "shard-a": {"metricsAddr":
                            f"127.0.0.1:{servers[0].port}"},
                "shard-b": {"metricsAddr":
                            f"127.0.0.1:{servers[1].port}"},
            },
        }
        api.create(core.ConfigMap(
            metadata=core.ObjectMeta(name=SHARD_MAP_NAME, namespace=SM_NS),
            data={SHARD_MAP_KEY: json.dumps(rec)},
        ))
        try:
            out = io.StringIO()
            rc = vtctl_main(["top"], api=api, out=out)
            text = out.getvalue()
            assert rc == 0, text
            assert "shard-a" in text and "shard-b" in text
            assert "CLUSTER" in text
            # cluster BINDS column sums both members
            cluster_line = next(
                line for line in text.splitlines()
                if line.strip().startswith("CLUSTER")
            )
            assert " 6 " in " ".join(cluster_line.split()) + " "
        finally:
            for s in servers:
                s.stop()

    def test_no_targets_is_an_error(self):
        from volcano_tpu.cli.vtctl import main as vtctl_main

        out = io.StringIO()
        rc = vtctl_main(["top"], api=APIServer(), out=out)
        assert rc == 1
        assert "no scrape targets" in out.getvalue()


class TestScrapeParsing:
    def test_round_trip_and_quantile(self):
        reg_render = metrics.registry
        reg_render.reset()
        h = reg_render.histogram("volcano_x_milliseconds", {},
                                 buckets=[1.0, 2.0, 4.0])
        for v in (0.5, 1.5, 3.0, 3.5):
            h.observe(v)
        reg_render.inc("volcano_y_total", {"k": "a"}, 2)
        s = mscrape.parse_metrics(reg_render.render())
        assert s.value("volcano_y_total", k="a") == 2
        hist = s.histogram("volcano_x_milliseconds")
        assert hist["count"] == 4
        q = mscrape.histogram_quantile(hist, 0.5)
        assert 1.0 <= q <= 4.0
        d = mscrape.delta(s, s)
        assert d.value("volcano_y_total", k="a") == 0
        assert d.histogram("volcano_x_milliseconds")["count"] == 0


# ---- identity labels + build info (satellite) ----

class TestIdentityLabels:
    def test_identity_injected_into_every_series(self):
        metrics.registry.reset()
        metrics.registry.inc("volcano_things_total", {"kind": "a"})
        before = metrics.registry.render()
        assert 'daemon=' not in before  # unset: output unchanged
        metrics.set_identity(daemon="scheduler", shard="s0",
                             role="scheduler")
        after = metrics.registry.render()
        assert ('volcano_things_total{daemon="scheduler",kind="a",'
                'role="scheduler",shard="s0"} 1') in after
        assert 'volcano_build_info{' in after and 'version=' in after
        metrics.registry.reset()
        assert 'daemon=' not in metrics.registry.render()

    def test_role_vocabulary_bounded(self):
        metrics.set_identity(daemon="x", role="not-a-role")
        assert 'role="other"' in metrics.registry.render()

    def test_role_follows_replication_both_directions(self):
        """update_repl_role retags the identity role on promotion AND
        demotion — a deposed leader's series must stop claiming
        role="leader"."""
        metrics.set_identity(daemon="apiserver", replica_index="0",
                             role="follower")
        metrics.registry.inc("volcano_things_total", {})
        metrics.update_repl_role("leader")
        assert 'volcano_things_total{daemon="apiserver",' \
               'replica_index="0",role="leader"}' in \
               metrics.registry.render()
        metrics.update_repl_role("follower")  # deposed
        line = next(
            ln for ln in metrics.registry.render().splitlines()
            if ln.startswith("volcano_things_total")
        )
        assert 'role="follower"' in line and 'role="leader"' not in line

    def test_identity_unset_ignores_role_refresh(self):
        metrics.update_repl_role("leader")  # no identity installed
        assert "daemon=" not in metrics.registry.render().split(
            "volcano_repl_role"
        )[0]

    def test_bounded_label_caps_cardinality(self):
        from volcano_tpu.metrics.metrics import (
            _LABEL_CARDINALITY_CAP,
            bounded_label,
        )

        for i in range(_LABEL_CARDINALITY_CAP):
            assert bounded_label("m", "job", f"j{i}") == f"j{i}"
        assert bounded_label("m", "job", "overflow") == "other"
        assert bounded_label("m", "job", "j0") == "j0"  # seen: kept
        r = metrics.registry.render()
        assert 'volcano_metric_label_overflow_total{metric="m"} 1' in r


# ---- the MTR analysis pass (satellite) ----

_MTR_OK = '''
def register_result(result):
    """result ∈ {ok, error}."""
    registry.inc("volcano_r_total", {"result": result})


def register_kind(kind):
    # label-vocab: kind — the KINDS registry, a static set
    registry.inc("volcano_k_total", {"kind": kind})


def register_fixed():
    registry.inc("volcano_f_total", {"kind": "fixed"})
'''

_MTR_BAD = '''
def register_job(job_name):
    """No vocabulary declared anywhere."""
    registry.inc("volcano_j_total", {"job": job_name})
'''


class TestMetricHygienePass:
    def _run(self, tmp_path, body, fname="volcano_tpu/m.py"):
        from volcano_tpu.analysis import metric_hygiene

        path = tmp_path / fname
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(body)
        return metric_hygiene.run(str(tmp_path))

    def test_declared_vocabularies_pass(self, tmp_path):
        assert self._run(tmp_path, _MTR_OK) == []

    def test_undeclared_dynamic_label_flagged(self, tmp_path):
        findings = self._run(tmp_path, _MTR_BAD)
        assert len(findings) == 1
        f = findings[0]
        assert f.code == "MTR001" and f.symbol == "register_job.job"

    def test_inline_waiver(self, tmp_path):
        body = _MTR_BAD.replace(
            '{"job": job_name})',
            '{"job": job_name})  # mtr: fixture-only, reviewed',
        )
        assert self._run(tmp_path, body) == []

    def test_orphaned_helper_flagged(self, tmp_path):
        helper = (
            "registry = None\n\n\n"
            "def update_never_called(seconds):\n"
            "    registry.histogram('volcano_dead_ms', {}).observe(seconds)\n"
        )
        caller = "def other():\n    pass\n"
        root = tmp_path
        (root / "volcano_tpu/metrics").mkdir(parents=True)
        (root / "volcano_tpu/metrics/metrics.py").write_text(helper)
        (root / "volcano_tpu/product.py").write_text(caller)
        from volcano_tpu.analysis import metric_hygiene

        findings = metric_hygiene.run(str(root))
        assert [f.code for f in findings] == ["MTR002"]
        assert findings[0].symbol == "update_never_called"
        # wiring the helper clears the finding
        (root / "volcano_tpu/product.py").write_text(
            "def other():\n    update_never_called(1.0)\n"
        )
        assert metric_hygiene.run(str(root)) == []

    def test_real_tree_is_clean(self):
        from volcano_tpu.analysis import metric_hygiene
        from volcano_tpu.analysis.__main__ import find_root

        assert metric_hygiene.run(find_root()) == []


# ---- merged multi-process Chrome export (small fix) ----

class TestMergedChromeExport:
    def test_distinct_pids_shared_clock(self, tmp_path):
        from volcano_tpu import trace as _trace
        from volcano_tpu.trace.export import merge_chrome_traces

        # two per-process journals whose local epochs differ wildly
        records = []
        for i, (epoch_shift, wall) in enumerate(((0.0, 100.0),
                                                 (9000.0, 100.005))):
            records.append({
                "cycle": i,
                "start_us": epoch_shift,
                "duration_ms": 2.0,
                "wall_time": wall + 0.002,  # end-of-cycle wall stamp
                "events": [{
                    "name": f"action:p{i}", "cat": "action", "ph": "X",
                    "ts": epoch_shift + 500.0, "dur": 100.0, "tid": 1,
                }],
                "decisions": [],
            })
        merged = merge_chrome_traces(records, labels=["a", "b"])
        xs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
        assert {e["pid"] for e in xs} == {1, 2}
        # process b started 5ms after a on the wall clock; after the
        # per-process offset correction their events are ~5ms apart
        t_by_pid = {e["pid"]: e["ts"] for e in xs}
        assert abs((t_by_pid[2] - t_by_pid[1]) - 5000.0) < 1.0
        metas = [e for e in merged["traceEvents"] if e.get("ph") == "M"]
        assert len(metas) == 2

        # the vtctl path: two journals on disk, -d twice
        from volcano_tpu.cli.vtctl import main as vtctl_main

        dirs = []
        for i, rec in enumerate(records):
            j = _trace.Journal(str(tmp_path / f"j{i}"))
            j.write_cycle(rec)
            dirs.append(str(tmp_path / f"j{i}"))
        out_path = str(tmp_path / "merged.json")
        out = io.StringIO()
        rc = vtctl_main(
            ["trace", "export", "-d", dirs[0], "-d", dirs[1],
             "-o", out_path],
            api=APIServer(), out=out,
        )
        assert rc == 0
        data = json.load(open(out_path))
        assert {e["pid"] for e in data["traceEvents"]
                if e.get("ph") == "X"} == {1, 2}

    def test_single_dir_unchanged(self, tmp_path):
        from volcano_tpu import trace as _trace
        from volcano_tpu.cli.vtctl import main as vtctl_main

        j = _trace.Journal(str(tmp_path / "j"))
        j.write_cycle({"cycle": 0, "start_us": 0.0, "duration_ms": 1.0,
                       "wall_time": 1.0, "events": [], "decisions": []})
        out = io.StringIO()
        rc = vtctl_main(["trace", "export", "-d", str(tmp_path / "j")],
                        api=APIServer(), out=out)
        assert rc == 0
        data = json.loads(out.getvalue())
        assert data["metadata"]["cycle"] == 0


# ---- loadgen stage breakdown plumbing ----

class TestStageBreakdown:
    def test_attribution_from_spans(self):
        t1 = obs.trace_id_for_pod("ns", "p1")
        spans = [
            _mk("cycle:task", "c1", ts=0.0, dur=8000.0),
            _mk("kernel:execute", "k1", parent="c1", ts=1000.0, dur=2000.0),
            _mk("bind:landed", "b1", parent="c1", trace=t1, ts=7000.0,
                dur=0.0),
        ]
        out = obs.stage_breakdown(spans, [("ns", "p1"), ("ns", "absent")])
        assert out["pods_with_spans"] == 1
        assert out["stages"]["kernel:execute"]["mean_ms"] == 2.0
        assert out["stages"]["cycle:task"]["count"] == 1
