"""Anomaly-aware diagnostics plane (ISSUE 19): tail-based trace
retention, the SLO burn-rate watchdog over the in-process metrics
time-series, incident bundles + the cluster capture boost, clock-skew
correction from paired bus spans, and the vtctl top/incidents
surfaces.

The tier-1 cross-process pin runs the scheduler in THIS process
against a real persistent ``vtpu-apiserver`` (carrying a seeded
``bus.delay`` schedule) and a real ``vtpu-controllers`` OS process,
all tail-sampling at 1%: the bus.delay-anomalous trace is kept WHOLE
across all three processes while steady traces drop at the configured
rate, and the chaos twin stays bit-identical with tail mode on."""

from __future__ import annotations

import io
import json
import os
import subprocess
import sys
import time
import zlib

import pytest

from volcano_tpu import faults, obs
from volcano_tpu.apis import core
from volcano_tpu.client import APIServer, VolcanoClient
from volcano_tpu.metrics import metrics
from volcano_tpu.metrics import scrape as mscrape
from volcano_tpu.metrics.timeseries import TimeSeriesRing
from volcano_tpu.obs.channel import SpanExporter
from volcano_tpu.obs.incident import IncidentManager, set_capture_boost
from volcano_tpu.obs.slo import (
    Alert,
    BurnRateWatchdog,
    resolve_slos,
)
from volcano_tpu.obs.tail import TailConfig, TailSampler


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    metrics.registry.reset()
    yield
    obs.disable()
    metrics.registry.reset()
    faults.configure(None)


def _wait(pred, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _coin(tid: str, sample: float = 0.01) -> bool:
    """The channel's head coin, for picking names on a known side."""
    return (zlib.crc32(tid.encode()) % 10_000) < sample * 10_000


def _drop_name(prefix: str, ns: str = "default",
               sample: float = 0.01) -> str:
    """A pod name whose trace id the head coin DROPS at ``sample`` —
    any keep of it must come from anomaly evidence, not the coin."""
    for i in range(100_000):
        name = f"{prefix}{i}"
        if not _coin(obs.trace_id_for(ns, name), sample):
            return name
    raise AssertionError("no coin-dropped name found")


def _rec(tid, name="op", dur=1000.0, sid=None, root=False, args=None,
         ts=1e6):
    r = {"t": tid, "s": sid or f"{tid}:{name}:{dur}", "p": "",
         "name": name, "cat": "span", "ts": ts, "dur": dur, "tid": 1}
    if root:
        r["_root"] = True
    if args:
        r["args"] = dict(args)
    return r


# ---- tail sampler (unit) ----

class TestTailSampler:
    def test_error_tag_keeps_whole_buffer_immediately(self):
        ts = TailSampler(lambda t: False, TailConfig())
        assert ts.offer(_rec("aa", "op", 1000.0)) == []
        out = ts.offer(_rec("aa", "op", 1000.0, sid="a2",
                            args={"error": "RuntimeError"}))
        assert [r["s"] for r in out] == ["aa:op:1000.0", "a2"]
        assert ts.kept_traces == 1 and ts.anomaly_keeps == 1
        assert ts.keep("aa")
        # decided traces bypass the pool entirely from now on
        assert ts.offer(_rec("aa", "op", 5.0, sid="a3")) != []
        assert ts.drain_decisions() == {"aa": True}
        assert ts.drain_decisions() == {}
        r = metrics.registry.render()
        assert ('volcano_telemetry_tail_decisions_total'
                '{result="keep"} 1') in r

    def test_fallback_and_degraded_tags_are_anomalies(self):
        ts = TailSampler(lambda t: False, TailConfig())
        assert ts.offer(_rec("f1", args={"fallback": "gang-arrival"}))
        assert ts.offer(_rec("d1", args={"degraded": "breaker"}))
        assert ts.anomaly_keeps == 2

    def test_duration_floor_breach_keeps(self):
        ts = TailSampler(lambda t: False, TailConfig())  # floor 25 ms
        assert ts.offer(_rec("slow", "rpc", dur=30_000.0)) != []
        assert ts.offer(_rec("fast", "rpc", dur=10_000.0)) == []
        assert ts.anomaly_keeps == 1 and ts.pending_count() == 1

    def test_threshold_seeds_from_windowed_p99_per_kind(self):
        cfg = TailConfig(min_kind_samples=16)
        ts = TailSampler(lambda t: False, cfg)
        # 40 steady ~10ms observations of "rpc": once past the warmup
        # the threshold is 4 x p99 = 40ms, no longer the 25ms floor
        for i in range(40):
            assert ts.offer(_rec(f"w{i}", "rpc", dur=10_000.0)) == []
        assert ts.offer(_rec("x1", "rpc", dur=30_000.0)) == []  # < 4xp99
        assert ts.offer(_rec("x2", "rpc", dur=41_000.0)) != []  # breach
        # a different span kind still sits at its floor
        assert ts.offer(_rec("y1", "other", dur=30_000.0)) != []
        assert ts.anomaly_keeps == 2

    def test_coin_decides_at_settle(self):
        ts = TailSampler(lambda t: t == "keep", TailConfig(settle_s=0.0))
        ts.offer(_rec("keep", root=True))
        ts.offer(_rec("drop", root=True))
        out = ts.sweep()
        assert {r["t"] for r in out} == {"keep"}
        assert ts.kept_traces == 1 and ts.dropped_traces == 1
        assert ts.keep("keep") and not ts.keep("drop")
        # memoized DROP suppresses later spans of the trace
        assert ts.offer(_rec("drop", sid="late")) == []
        assert ts.drain_decisions() == {"keep": True, "drop": False}
        r = metrics.registry.render()
        assert 'volcano_telemetry_tail_decisions_total{result="drop"} 1' in r

    def test_rootless_trace_never_settles_by_coin(self):
        ts = TailSampler(lambda t: True, TailConfig(settle_s=0.0))
        ts.offer(_rec("orphan"))  # no root landed
        assert ts.sweep() == []
        assert ts.pending_count() == 1

    def test_runaway_trace_evicts_pool_full(self):
        cfg = TailConfig(max_spans_per_trace=4)
        ts = TailSampler(lambda t: False, cfg)
        for i in range(4):
            ts.offer(_rec("big", sid=f"b{i}"))
        assert ts.offer(_rec("big", sid="b4")) == []  # head coin drops
        assert ts.evicted_traces == 1 and not ts.keep("big")
        r = metrics.registry.render()
        assert ('volcano_telemetry_tail_evictions_total'
                '{reason="pool-full"} 1') in r

    def test_pool_overflow_evicts_oldest_with_head_decision(self):
        cfg = TailConfig(max_traces=2)
        keep_first = lambda t: t == "t1"  # noqa: E731
        ts = TailSampler(keep_first, cfg)
        ts.offer(_rec("t1", sid="s1"))
        ts.offer(_rec("t2", sid="s2"))
        out = ts.offer(_rec("t3", sid="s3"))
        # t1 was evicted for room — head decision kept its spans
        assert [r["s"] for r in out] == ["s1"]
        assert ts.pending_count() == 2 and ts.evicted_traces == 1
        assert ts.keep("t1")

    def test_never_completed_trace_times_out_to_head_decision(self):
        ts = TailSampler(lambda t: False,
                         TailConfig(pending_timeout_s=0.0))
        ts.offer(_rec("stuck"))
        assert ts.sweep() == []
        assert ts.evicted_traces == 1 and not ts.keep("stuck")
        r = metrics.registry.render()
        assert ('volcano_telemetry_tail_evictions_total'
                '{reason="timeout"} 1') in r

    def test_apply_remote_resolves_pending_both_ways(self):
        ts = TailSampler(lambda t: False, TailConfig())
        ts.offer(_rec("r1", sid="r1a"))
        out = ts.apply_remote({"r1": True})
        assert [r["s"] for r in out] == ["r1a"]
        assert ts.keep("r1")
        # remote decisions are memoized, never re-published (no echo)
        assert ts.drain_decisions() == {}
        ts.offer(_rec("r2"))
        assert ts.apply_remote({"r2": False}) == []
        assert not ts.keep("r2")

    def test_local_anomaly_keep_beats_remote_coin_drop(self):
        ts = TailSampler(lambda t: False, TailConfig())
        ts.offer(_rec("ev", args={"error": "X"}))
        assert ts.keep("ev")
        ts.apply_remote({"ev": False})
        assert ts.keep("ev"), "evidence-keep must survive a remote drop"

    def test_decision_memo_is_bounded(self):
        ts = TailSampler(lambda t: False, TailConfig(decision_memo=64))
        ts.apply_remote({f"m{i}": False for i in range(70)})
        assert len(ts._decided) == 64


# ---- exporter integration (tail mode on the real channel) ----

class TestTailExporter:
    def _tail_exporter(self, api, sample=0.01, **cfg):
        exp = obs.enable(api, identity="d0", sample=sample,
                         flush_interval=3600, tail=True)
        exp.tail = TailSampler(exp._coin, TailConfig(**cfg))
        return exp

    def test_steady_trace_dropped_anomalous_kept_whole(self):
        api = APIServer()
        exp = self._tail_exporter(api, settle_s=0.0)
        tid_s = obs.trace_id_for("default", _drop_name("steady-"))
        with obs.span("bind", cat="scheduler", trace_id=tid_s):
            with obs.span("child"):
                pass
        exp.tick()  # sweep settles by coin (drop), then flush
        assert tid_s not in {s.get("t") for s in obs.collect_spans(api)}
        assert exp.tail.dropped_traces == 1

        tid_a = obs.trace_id_for("default", _drop_name("anom-"))
        with pytest.raises(RuntimeError):
            with obs.span("bind", cat="scheduler", trace_id=tid_a):
                with obs.span("child"):
                    raise RuntimeError("boom")
        exp.tick()
        sel = [s for s in obs.collect_spans(api) if s.get("t") == tid_a]
        assert {s["name"] for s in sel} == {"bind", "child"}
        assert any(s.get("args", {}).get("error") == "RuntimeError"
                   for s in sel)
        # the transient completion marker never reaches the bus
        assert not any("_root" in s for s in obs.collect_spans(api))
        assert exp.tail.anomaly_keeps >= 1

    def test_slow_span_duration_is_an_anomaly(self):
        api = APIServer()
        exp = self._tail_exporter(api, settle_s=0.0, floor_ms=5.0)
        tid = obs.trace_id_for("default", _drop_name("slow-"))
        with obs.span("bind", cat="scheduler", trace_id=tid):
            time.sleep(0.02)
        exp.tick()
        assert tid in {s.get("t") for s in obs.collect_spans(api)}

    def test_decisions_propagate_between_exporters(self):
        api = APIServer()
        e1 = SpanExporter(api, "d1", sample=0.01, flush_interval=3600,
                          tail=True)
        e2 = SpanExporter(api, "d2", sample=0.01, flush_interval=3600,
                          tail=True)
        e1.tail = TailSampler(e1._coin, TailConfig(settle_s=0.0))
        e2.tail = TailSampler(e2._coin, TailConfig(settle_s=0.0))
        t_keep = obs.trace_id_for("default", _drop_name("xk-"))
        t_drop = obs.trace_id_for("default", _drop_name("xd-"))
        # d2 holds child spans it cannot decide (rootless, no anomaly)
        e2.emit(_rec(t_keep, "bus:bind", sid="d2-k"))
        e2.emit(_rec(t_drop, "wal:fsync", sid="d2-d"))
        # d1 holds the evidence for one and settles the other by coin
        e1.emit(_rec(t_keep, "bind", sid="d1-k", args={"error": "X"}))
        e1.emit(_rec(t_drop, "bind:landed", sid="d1-d", root=True))
        e1.tick()  # sweep + publish vtpu-tail-d1 + flush
        e2.tick()  # apply peer decisions, ship resolved spans
        sids = {s["s"] for s in obs.collect_spans(api)}
        assert {"d1-k", "d2-k"} <= sids
        assert "d2-d" not in sids and "d1-d" not in sids
        assert e2.tail.keep(t_keep) and not e2.tail.keep(t_drop)
        assert e2.tail.pending_count() == 0

    def test_capture_boost_keeps_everything_and_polls(self):
        api = APIServer()
        exp = self._tail_exporter(api, sample=0.0, settle_s=0.0)
        tid = obs.trace_id_for("default", "boosted-pod")
        exp.set_boost({"until": time.time() + 30, "by": "t",
                       "reason": "test", "ts": time.time()})
        assert exp.boost_active() and exp.keep(tid)
        assert "volcano_capture_boost_active 1" in metrics.registry.render()
        with obs.span("bind", cat="scheduler", trace_id=tid):
            pass
        # boosted spans bypass the pending pool entirely
        assert exp.tail.pending_count() == 0
        # the poll finds no cluster record backing the local boost —
        # the CM is authoritative, so the cache clears on the beat
        exp.tick()
        assert tid in {s.get("t") for s in obs.collect_spans(api)}
        assert not exp.boost_active()
        assert "volcano_capture_boost_active 0" in metrics.registry.render()
        with obs.span("bind2", cat="scheduler", trace_id=tid + "x"):
            pass
        assert exp.tail.pending_count() == 1  # back to buffering

    def test_flusher_poll_picks_up_cluster_boost_record(self):
        api = APIServer()
        exp = self._tail_exporter(api, sample=0.0)
        assert not exp.boost_active()
        set_capture_boost(api, "vtctl", "manual", ttl_s=30.0)
        exp.tick()  # poll beat
        assert exp.boost_active()
        rec = exp.boost_record()
        assert rec["reason"] == "manual" and rec["by"] == "vtctl"
        # an expired record ages out on the next poll
        cm = api.get("ConfigMap", obs.NAMESPACE, obs.BOOST_NAME)
        cm.data = {obs.BOOST_KEY: json.dumps(
            {"until": time.time() - 1, "by": "vtctl", "reason": "manual"})}
        api.update(cm)
        exp.tick()
        assert not exp.boost_active()


# ---- capture boost CAS ----

class TestCaptureBoostCAS:
    def test_never_shortens_a_live_boost(self):
        api = APIServer()
        b1 = set_capture_boost(api, "a", "r1", ttl_s=100.0, now=1000.0)
        assert b1["until"] == 1100.0
        # a shorter re-trigger keeps the existing record
        b2 = set_capture_boost(api, "b", "r2", ttl_s=10.0, now=1005.0)
        assert b2["by"] == "a" and b2["until"] == 1100.0
        # a later expiry extends it
        b3 = set_capture_boost(api, "b", "r2", ttl_s=300.0, now=1010.0)
        assert b3["until"] == 1310.0
        rec = json.loads(api.get(
            "ConfigMap", obs.NAMESPACE, obs.BOOST_NAME
        ).data[obs.BOOST_KEY])
        assert rec["until"] == 1310.0 and rec["by"] == "b"


# ---- metrics time-series ring ----

class TestTimeSeriesRing:
    def test_windowed_delta_and_dump(self):
        ring = TimeSeriesRing()
        metrics.register_commit_failure("io")
        ring.tick(now=1000.0)
        for _ in range(30):
            metrics.register_commit_failure("io")
        ring.tick(now=1030.0)
        w = ring.window(60.0, now=1030.0)
        assert w is not None
        assert w.value("volcano_commit_failures_total") == 30.0
        # no sample old enough inside a 10s window
        assert ring.window(10.0, now=1030.0) is None
        assert len(ring) == 2 and ring.span_seconds() == 30.0
        dump = ring.dump()
        assert len(dump) == 2
        assert "volcano_commit_failures_total" in dump[1][1]

    def test_single_sample_has_no_window(self):
        ring = TimeSeriesRing()
        ring.tick(now=1.0)
        assert ring.window(60.0, now=1.0) is None

    def test_capacity_bounds_the_ring(self):
        ring = TimeSeriesRing(capacity=4)
        for i in range(10):
            ring.tick(now=float(i))
        assert len(ring) == 4


# ---- burn-rate watchdog ----

class TestBurnRateWatchdog:
    def test_breach_fires_once_then_clears(self):
        fired = []
        ring = TimeSeriesRing()
        wd = BurnRateWatchdog(
            ring, slos=resolve_slos("submit-bind-p99=50"),
            fast_window_s=60.0, slow_window_s=300.0,
            on_breach=fired.append,
        )
        ring.tick(now=1000.0)
        for _ in range(50):
            metrics.observe_submit_to_bind(0.5)  # 500ms against 50ms
        alerts = wd.run_once(now=1030.0)
        assert [a.name for a in alerts] == ["submit-bind-p99"]
        assert len(fired) == 1
        assert fired[0].burn_fast >= 1.0 and fired[0].burn_slow >= 1.0
        assert fired[0].value > 50.0
        assert wd.degraded_reasons() == ["slo-burn:submit-bind-p99"]
        r = metrics.registry.render()
        assert 'volcano_slo_burn{slo="submit-bind-p99",window="fast"}' in r
        # still breaching: edge-triggered, no second capture
        wd.run_once(now=1035.0)
        assert len(fired) == 1 and wd.breaches == 1
        # signal stops: the fast window empties, the alert clears
        ring.tick(now=1100.0)
        assert wd.evaluate(now=1100.0) == []
        assert wd.active_alerts() == [] and wd.degraded_reasons() == []

    def test_fast_spike_without_slow_confirmation_is_noise(self):
        ring = TimeSeriesRing()
        slos = [s for s in resolve_slos("")
                if s.name == "commit-failures"]
        wd = BurnRateWatchdog(ring, slos=slos, fast_window_s=60.0,
                              slow_window_s=300.0)
        ring.tick(now=1000.0)
        for _ in range(30):
            metrics.register_commit_failure("io")
        ring.tick(now=1030.0)
        assert wd.evaluate(now=1030.0) == []
        s = mscrape.parse_metrics(metrics.registry.render())
        burns = {
            dict(ls)["window"]: v
            for (n, ls), v in s.series.items() if n == "volcano_slo_burn"
        }
        # 30 failures / 60s = 0.5/s -> burn 2.5 fast; /300s -> 0.5 slow
        assert burns["fast"] == pytest.approx(2.5)
        assert burns["slow"] == pytest.approx(0.5)

    def test_gauge_slo_takes_max_not_sum(self):
        from volcano_tpu.obs.slo import _gauge_max

        s = mscrape.parse_metrics(
            'volcano_circuit_breaker_open{name="a"} 0.5\n'
            'volcano_circuit_breaker_open{name="b"} 0.5\n'
        )
        # Scrape.value would sum to 1.0 and fake a tripped breaker
        assert s.value("volcano_circuit_breaker_open") == 1.0
        assert _gauge_max(s, "volcano_circuit_breaker_open", {}) == 0.5

    def test_resolve_slos_overrides_known_ignores_garbage(self):
        slos = {s.name: s for s in resolve_slos(
            "submit-bind-p99=50, bogus=1, micro-cycle-p99=abc")}
        assert slos["submit-bind-p99"].objective == 50.0
        assert slos["micro-cycle-p99"].objective == 250.0
        assert "bogus" not in slos
        assert set(slos) == {s.name for s in resolve_slos("")}

    def test_alert_to_dict_is_stored_fields_only(self):
        a = Alert("x", 1.23456, 2.0, 3.0, 4.0, 100.0)
        d = a.to_dict()
        assert d == {"name": "x", "burnFast": 1.2346, "burnSlow": 2.0,
                     "value": 3.0, "objective": 4.0, "since": 100.0}


# ---- incident manager ----

class TestIncidentManager:
    def _manager(self, api, tmp_path, **kw):
        ring = TimeSeriesRing()
        ring.tick(now=1.0)
        ring.tick(now=2.0)
        kw.setdefault("settle_s", 0.0)
        return IncidentManager(api, "d0", str(tmp_path / "inc"),
                               metrics_ring=ring, **kw)

    def _bundles(self, tmp_path):
        d = tmp_path / "inc"
        return sorted(p.name for p in d.iterdir()) if d.exists() else []

    def test_breach_writes_one_bundle_and_arms_the_boost(self, tmp_path):
        api = APIServer()
        exp = obs.enable(api, identity="d0", flush_interval=3600)
        with obs.span("bind:landed", cat="scheduler",
                      trace_id="ff00aa11"):
            pass
        exp.flush_all()
        mgr = self._manager(api, tmp_path, cooldown_s=60.0,
                            boost_ttl_s=30.0)
        alert = Alert("submit-bind-p99", 12.7, 3.2, 636.8, 50.0, 1030.0)
        mgr.on_alert(alert)  # settle 0 -> synchronous capture
        bundles = self._bundles(tmp_path)
        assert len(bundles) == 1
        assert not any(b.startswith(".tmp") for b in bundles)
        bdir = tmp_path / "inc" / bundles[0]
        meta = json.loads((bdir / "meta.json").read_text())
        assert meta["reason"] == "slo-burn:submit-bind-p99"
        assert meta["alerts"][0]["name"] == "submit-bind-p99"
        assert meta["boost"]["reason"] == "slo-burn:submit-bind-p99"
        assert meta["errors"] == {}
        assert {"spans.json", "bus_status.json", "shard_map.json",
                "metrics.jsonl", "meta.json"} <= set(meta["files"])
        spans = json.loads((bdir / "spans.json").read_text())
        assert any(s["name"] == "bind:landed" for s in spans)
        assert meta["spanCount"] == len(spans)
        # the boost record reached the bus; the local exporter boosted
        # without waiting a poll tick
        rec = json.loads(api.get(
            "ConfigMap", obs.NAMESPACE, obs.BOOST_NAME
        ).data[obs.BOOST_KEY])
        assert rec["reason"] == "slo-burn:submit-bind-p99"
        assert exp.boost_active()
        # a re-fire inside the cooldown re-arms the boost, no 2nd bundle
        mgr.on_alert(alert)
        assert len(self._bundles(tmp_path)) == 1
        assert mgr.captured == 1 and mgr.suppressed_triggers == 1
        r = metrics.registry.render()
        assert "volcano_incidents_captured_total" in r
        # the published summary is fleet-readable
        recs = obs.list_incidents(api)
        assert len(recs) == 1
        assert recs[0]["object"].startswith("vtpu-incident-d0-")
        assert recs[0]["meta"]["reason"] == meta["reason"]
        assert any(s["name"] == "bind:landed" for s in recs[0]["spans"])

    def test_distinct_triggers_are_independent_episodes(self, tmp_path):
        api = APIServer()
        mgr = self._manager(api, tmp_path, cooldown_s=60.0)
        mgr.trigger("breaker-open", sync=True)
        mgr.trigger("drift-divergence", sync=True)
        assert len(self._bundles(tmp_path)) == 2
        assert mgr.suppressed_triggers == 0

    def test_bundle_ring_prunes_oldest(self, tmp_path):
        api = APIServer()
        mgr = self._manager(api, tmp_path, ring=2, cooldown_s=0.0)
        for i in range(4):
            mgr.capture(f"t{i}")
        bundles = self._bundles(tmp_path)
        assert len(bundles) == 2
        assert bundles[-1].endswith("-t3")

    def test_capture_survives_missing_sources(self, tmp_path):
        class _BrokenAPI:
            def list(self, *a, **k):
                raise RuntimeError("bus down")

            def get(self, *a, **k):
                raise RuntimeError("bus down")

            def create(self, *a, **k):
                raise RuntimeError("bus down")

        mgr = IncidentManager(_BrokenAPI(), "d0", str(tmp_path / "inc"),
                              settle_s=0.0,
                              journal_dir=str(tmp_path / "nope"))
        path = mgr.capture("manual")
        meta = json.loads(
            (tmp_path / "inc" / os.path.basename(path) /
             "meta.json").read_text())
        assert "spans.json" in meta["errors"]
        assert meta["reason"] == "manual"


# ---- vtctl surfaces ----

class TestVtctlIncidents:
    def _seed_incident(self, api, tmp_path):
        exp = obs.enable(api, identity="d0", flush_interval=3600)
        with obs.span("bind:landed", cat="scheduler",
                      trace_id="ff00aa11"):
            pass
        exp.flush_all()
        mgr = IncidentManager(api, "d0", str(tmp_path / "inc"),
                              settle_s=0.0)
        mgr.capture("slo-burn:submit-bind-p99", alerts=[
            {"name": "submit-bind-p99", "burnFast": 2.0}])

    def test_list_show_collect(self, tmp_path):
        from volcano_tpu.cli.vtctl import main as vtctl_main

        api = APIServer()
        self._seed_incident(api, tmp_path)
        out = io.StringIO()
        assert vtctl_main(["incidents", "list"], api=api, out=out) == 0
        text = out.getvalue()
        assert "TRIGGER" in text and "slo-burn:submit-bind-p99" in text
        assert "d0" in text

        out = io.StringIO()
        assert vtctl_main(["incidents", "show"], api=api, out=out) == 0
        text = out.getvalue()
        assert '"reason": "slo-burn:submit-bind-p99"' in text
        assert "bind:landed" in text  # the breach-window waterfall

        out = io.StringIO()
        dest = tmp_path / "got"
        assert vtctl_main(
            ["incidents", "collect", "--out", str(dest)],
            api=api, out=out,
        ) == 0
        files = list(dest.iterdir())
        assert len(files) == 1
        rec = json.loads(files[0].read_text())
        assert rec["meta"]["identity"] == "d0"

        # the singular alias routes identically
        out = io.StringIO()
        assert vtctl_main(["incident", "list"], api=api, out=out) == 0
        assert "slo-burn:submit-bind-p99" in out.getvalue()

    def test_empty_store_list_ok_show_errors(self, tmp_path):
        from volcano_tpu.cli.vtctl import main as vtctl_main

        api = APIServer()
        out = io.StringIO()
        assert vtctl_main(["incidents", "list"], api=api, out=out) == 0
        assert "no incident bundles" in out.getvalue()
        out = io.StringIO()
        assert vtctl_main(["incidents", "show"], api=api, out=out) == 1

    def test_operator_capture_boosts_then_bundles(self, tmp_path):
        from volcano_tpu.cli.vtctl import main as vtctl_main

        api = APIServer()
        out = io.StringIO()
        rc = vtctl_main(
            ["incidents", "capture", "--dir", str(tmp_path / "inc"),
             "--settle", "0"],
            api=api, out=out,
        )
        assert rc == 0
        assert "bundle:" in out.getvalue()
        assert len(list((tmp_path / "inc").iterdir())) == 1
        rec = json.loads(api.get(
            "ConfigMap", obs.NAMESPACE, obs.BOOST_NAME
        ).data[obs.BOOST_KEY])
        assert rec["reason"] == "manual" and rec["by"] == "vtctl"
        # and it is now fleet-visible
        out = io.StringIO()
        assert vtctl_main(["incidents", "list"], api=api, out=out) == 0
        assert "manual" in out.getvalue()


class TestVtctlTopBurn:
    def _cluster(self, burn_a=0.4, burn_b=2.5):
        from volcano_tpu.federation.leases import (
            NAMESPACE as SM_NS,
            SHARD_MAP_KEY,
            SHARD_MAP_NAME,
        )
        from volcano_tpu.metrics.metrics import _Registry
        from volcano_tpu.serving.http import ServingServer

        servers = []
        for ident, burn in (("shard-a", burn_a), ("shard-b", burn_b)):
            reg = _Registry()
            reg.set_identity(daemon="scheduler", shard=ident)
            h = reg.histogram(
                "volcano_submit_to_bind_latency_milliseconds", {},
                buckets=[5.0, 10.0, 20.0],
            )
            for v in (4.0, 8.0, 16.0):
                h.observe(v)
            reg.inc("volcano_pod_schedule_successes", {}, 3)
            reg.set_gauge("volcano_slo_burn",
                          {"slo": "submit-bind-p99", "window": "fast"},
                          burn)
            reg.set_gauge("volcano_slo_burn",
                          {"slo": "submit-bind-p99", "window": "slow"},
                          burn * 10)  # slow must not leak into BURN
            servers.append(ServingServer(registry=reg).start())
        api = APIServer()
        rec = {
            "nShards": 2, "members": {}, "shards": {},
            "stats": {
                "shard-a": {"metricsAddr": f"127.0.0.1:{servers[0].port}"},
                "shard-b": {"metricsAddr": f"127.0.0.1:{servers[1].port}"},
            },
        }
        api.create(core.ConfigMap(
            metadata=core.ObjectMeta(name=SHARD_MAP_NAME, namespace=SM_NS),
            data={SHARD_MAP_KEY: json.dumps(rec)},
        ))
        return api, servers

    def test_burn_column_and_json(self):
        from volcano_tpu.cli.vtctl import main as vtctl_main

        api, servers = self._cluster()
        try:
            out = io.StringIO()
            assert vtctl_main(["top"], api=api, out=out) == 0
            text = out.getvalue()
            assert "BURN" in text
            line_b = next(l for l in text.splitlines() if "shard-b" in l)
            assert "2.50" in line_b

            out = io.StringIO()
            assert vtctl_main(["top", "--json"], api=api, out=out) == 0
            doc = json.loads(out.getvalue())
            members = doc["members"]
            assert members["shard-a"]["burn"] == pytest.approx(0.4)
            assert members["shard-b"]["burn"] == pytest.approx(2.5)
            # cluster burn is the fleet MAX (a sum would dilute or
            # double-count a single burning member)
            assert doc["cluster"]["burn"] == pytest.approx(2.5)
            assert doc["cluster"]["binds"] == 6
        finally:
            for s in servers:
                s.stop()

    def test_watch_emits_bounded_frames(self):
        from volcano_tpu.cli.vtctl import main as vtctl_main

        api, servers = self._cluster()
        try:
            out = io.StringIO()
            rc = vtctl_main(
                ["top", "--watch", "0.01", "--count", "2"],
                api=api, out=out,
            )
            assert rc == 0
            assert out.getvalue().count("CLUSTER") == 2
        finally:
            for s in servers:
                s.stop()


# ---- clock-skew correction ----

def _busspan(sid, name, daemon, pid, ts, dur, parent="", cat="bus",
             tid="tt00tt00"):
    return {"t": tid, "s": sid, "p": parent, "name": name, "cat": cat,
            "daemon": daemon, "pid": pid, "ts": ts, "dur": dur}


class TestClockSkew:
    def test_estimates_offset_from_rtt_midpoints(self):
        # the anchor is the earliest process in the trace: the client
        client = _busspan("A1", "bus:bind", "sched", 11,
                          1_000_000.0, 10_000.0)
        # server's clock runs 50ms AHEAD: symmetric rpc -> the span
        # midpoints name the same instant on two clocks
        server = _busspan("B1", "bus:bind", "api", 22,
                          1_052_000.0, 6_000.0, parent="A1")
        offs = obs.estimate_skew([client, server])
        assert offs[("sched", 11)] == 0.0
        assert offs[("api", 22)] == pytest.approx(-50_000.0)
        fixed = {s["s"]: s for s in obs.apply_skew([client, server], offs)}
        assert fixed["B1"]["ts"] == pytest.approx(1_002_000.0)
        assert fixed["A1"]["ts"] == 1_000_000.0

    def test_median_rejects_asymmetric_outlier(self):
        spans = [_busspan("A1", "bus:get", "sched", 11,
                          1_000_000.0, 10_000.0)]  # unpaired: ignored
        for i, off in enumerate((50_000.0, 50_000.0, 950_000.0)):
            spans.append(_busspan(f"C{i}", "bus:get", "sched", 11,
                                  1_000_000.0 + i, 10_000.0))
            spans.append(_busspan(f"S{i}", "bus:get", "api", 22,
                                  1_005_000.0 + i + off - 3_000.0,
                                  6_000.0, parent=f"C{i}"))
        offs = obs.estimate_skew(spans)
        assert offs[("api", 22)] == pytest.approx(-50_000.0)

    def test_chained_hops_propagate_from_anchor(self):
        spans = [
            _busspan("A1", "bus:bind", "sched", 11, 1_000_000.0, 10_000.0),
            # api runs 50ms ahead of the anchor
            _busspan("B1", "bus:bind", "api", 22, 1_052_000.0, 6_000.0,
                     parent="A1"),
            # api -> ctrl hop: ctrl runs a further 20ms ahead of api
            _busspan("B2", "bus:status", "api", 22, 1_060_000.0, 8_000.0),
            _busspan("D1", "bus:status", "ctrl", 33, 1_082_000.0, 4_000.0,
                     parent="B2"),
        ]
        offs = obs.estimate_skew(spans)
        assert offs[("sched", 11)] == 0.0
        assert offs[("api", 22)] == pytest.approx(-50_000.0)
        # ctrl offset composes through the api hop
        assert offs[("ctrl", 33)] == pytest.approx(-70_000.0)

    def test_no_pairs_no_correction(self):
        spans = [
            _busspan("A1", "cycle", "sched", 11, 0.0, 10.0, cat="scheduler"),
            _busspan("A2", "bind", "sched", 11, 1.0, 2.0, parent="A1",
                     cat="scheduler"),
        ]
        assert obs.estimate_skew(spans) == {}
        out = io.StringIO()
        obs.render_waterfall(spans, out)
        assert "clock skew corrected" not in out.getvalue()

    def test_waterfall_reports_and_applies_correction(self):
        client = _busspan("A1", "bus:bind", "sched", 11,
                          1_000_000.0, 10_000.0)
        server = _busspan("B1", "bus:bind", "api", 22,
                          1_052_000.0, 6_000.0, parent="A1")
        out = io.StringIO()
        obs.render_waterfall([client, server], out)
        text = out.getvalue()
        assert "clock skew corrected" in text
        assert "api/22 -50.00ms" in text
        # skew={} disables the estimate: raw wall clocks, no header
        out = io.StringIO()
        obs.render_waterfall([client, server], out, skew={})
        assert "clock skew corrected" not in out.getvalue()

    def test_remote_client_emits_paired_bus_span(self):
        from volcano_tpu.bus.remote import RemoteAPIServer
        from volcano_tpu.bus.server import BusServer

        store = APIServer()
        srv = BusServer(store).start()
        remote = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}",
                                 timeout=5.0)
        try:
            assert remote.wait_ready(5)
            exp = obs.enable(remote, identity="cli-0",
                             flush_interval=3600)
            with obs.span("outer", trace_id="aabbccdd") as outer:
                remote.get("Pod", "default", "nope")
            exp.flush_all()
            spans = [s for s in obs.collect_spans(remote)
                     if s["name"] == "bus:get"]
            # one client-side + one server-side span, linked, same name
            assert len(spans) == 2
            by_parent = {s["p"]: s for s in spans}
            client = by_parent[outer.span_id]
            server = by_parent[client["s"]]
            assert client["cat"] == "bus" and server["cat"] == "bus"
            assert client["t"] == server["t"] == "aabbccdd"
            assert client["dur"] >= server["dur"]
        finally:
            obs.disable()
            remote.close()
            srv.stop()

    def test_remote_client_span_records_error(self):
        from volcano_tpu.bus.remote import RemoteAPIServer
        from volcano_tpu.bus.server import BusServer

        store = APIServer()
        store.create(core.ConfigMap(metadata=core.ObjectMeta(
            name="dup", namespace="default")))
        srv = BusServer(store).start()
        remote = RemoteAPIServer(f"tcp://127.0.0.1:{srv.port}",
                                 timeout=5.0)
        try:
            assert remote.wait_ready(5)
            exp = obs.enable(remote, identity="cli-0",
                             flush_interval=3600)
            with obs.span("outer", trace_id="aabbccdd"):
                with pytest.raises(Exception):
                    remote.create(core.ConfigMap(metadata=core.ObjectMeta(
                        name="dup", namespace="default")))
            exp.flush_all()
            clients = [s for s in obs.collect_spans(remote)
                       if s["name"] == "bus:create"
                       and "error" in (s.get("args") or {})]
            assert clients, "client bus span must tag the failed rpc"
        finally:
            obs.disable()
            remote.close()
            srv.stop()


# ---- the 3-OS-process retention pin (tier-1) ----

def _spawn_env(extra_env, module, *args):
    return subprocess.Popen(
        [sys.executable, "-m", module, *args],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        env={**os.environ, **extra_env, "JAX_PLATFORMS": "cpu"},
    )


class TestTailRetentionThreeProcesses:
    def test_bus_delay_anomalous_trace_kept_whole(self, tmp_path,
                                                  monkeypatch):
        """Scheduler (this process) + persistent apiserver carrying a
        seeded ``bus.delay`` schedule + controllers, every exporter
        tail-sampling at 1%: the trace that catches a delayed rpc is
        kept WHOLE across all three processes (completion-time
        decisions propagate through ``vtpu-tail-*``), while steady
        traces drop at the configured rate."""
        from volcano_tpu.apis import batch
        from volcano_tpu.bus import connect_bus
        from volcano_tpu.cache import SchedulerCache
        from volcano_tpu.client import SchedulerClient
        from volcano_tpu.cmd.local_up import seed_cluster
        from volcano_tpu.scheduler.scheduler import Scheduler

        port = _free_port()
        bus_url = f"tcp://127.0.0.1:{port}"
        # children: tail on at 1%, 60ms duration floor.  Their pod
        # spans are ROOTLESS halves (adopt() never roots), so they hold
        # them pending and resolve via the root owner's published
        # decision; settle/timeout sit far beyond the test horizon so
        # the only decisions in play are evidence-driven
        child_env = {
            "VTPU_TELEMETRY_SAMPLE": "0.01",
            "VTPU_TELEMETRY_TAIL": "1",
            "VTPU_TAIL_FLOOR_MS": "60",
            "VTPU_TAIL_SETTLE": "3600",
            "VTPU_TAIL_TIMEOUT": "3600",
        }
        procs = [_spawn_env(
            child_env, "volcano_tpu.cmd.apiserver",
            "--port", str(port), "--listen-port", "0",
            "--data-dir", str(tmp_path / "wal"),
            "--flight-recorder",
            "--faults", "seed=5;bus.delay=0.25:ms=120",
        )]
        # this process: same floor, but never settle/evict locally —
        # only anomaly evidence (or a peer) may decide, so the probe
        # below is deterministic
        monkeypatch.setenv("VTPU_TAIL_FLOOR_MS", "60")
        monkeypatch.setenv("VTPU_TAIL_SETTLE", "3600")
        monkeypatch.setenv("VTPU_TAIL_TIMEOUT", "3600")
        api = sched_remote = None
        cache = None
        try:
            api = connect_bus(bus_url, wait=30.0)
            seed_cluster(api, nodes=2, node_cpu="16", node_mem="32Gi")
            procs.append(_spawn_env(
                child_env, "volcano_tpu.cmd.controllers",
                "--bus", bus_url, "--listen-port", "0",
                "--period", "0.05", "--flight-recorder",
                "--leader-elect-id", "ctrl-0",
            ))
            sched_remote = connect_bus(bus_url, wait=10.0)
            exp = obs.enable(sched_remote, identity="sched-0",
                             flush_interval=0.05, sample=0.01,
                             tail=True)
            cache = SchedulerCache(client=SchedulerClient(sched_remote),
                                   scheduler_name="volcano-tpu")
            scheduler = Scheduler(cache, period=0.05)
            cache.run()
            cache.wait_for_cache_sync()

            # a job whose every pod trace the 1% coin DROPS: any keep
            # below is anomaly-driven by construction
            replicas = 6
            job = next(
                f"st{i}" for i in range(100_000)
                if not any(_coin(obs.trace_id_for(
                    "default", f"st{i}-t-{k}")) for k in range(replicas))
            )
            VolcanoClient(api).create_job(batch.Job(
                metadata=core.ObjectMeta(name=job, namespace="default"),
                spec=batch.JobSpec(
                    min_available=replicas, queue="default",
                    scheduler_name="volcano-tpu",
                    tasks=[batch.TaskSpec(
                        name="t", replicas=replicas,
                        template=core.PodTemplateSpec(spec=core.PodSpec(
                            containers=[core.Container(
                                name="c", image="busybox",
                                resources={"requests": {
                                    "cpu": "1", "memory": "1Gi"}},
                            )],
                        )),
                    )],
                ),
            ))

            def all_bound():
                scheduler.run_once()
                return all(
                    (p := api.get("Pod", "default", f"{job}-t-{k}"))
                    is not None and bool(p.spec.node_name)
                    for k in range(replicas)
                )

            assert _wait(all_bound, timeout=90.0, interval=0.1), (
                "pods never bound over the faulted 3-process topology"
            )

            # probe a (still-undecided) trace with real rpcs until the
            # apiserver's seeded bus.delay lands one: the client-side
            # bus:get span then breaches the 60ms floor and the whole
            # pending buffer for that trace is kept + published
            anom = f"{job}-t-0"

            def probe_until_delayed(tid):
                deadline = time.monotonic() + 60.0
                while time.monotonic() < deadline:
                    t0 = time.perf_counter()
                    with obs.span("probe:get", cat="probe",
                                  trace_id=tid):
                        sched_remote.get("Pod", "default", anom)
                    if time.perf_counter() - t0 >= 0.1:
                        return True
                return False

            tid = obs.trace_id_for("default", anom)
            assert probe_until_delayed(tid), (
                "seeded bus.delay never landed on a probe rpc")
            assert _wait(lambda: exp.tail.anomaly_keeps >= 1,
                         timeout=10.0), "delayed rpc not flagged anomalous"

            # the pod trace converges: this process's bind-path spans
            # plus the apiserver's halves resolved via the published
            # vtpu-tail decision
            def trace_spans():
                return [s for s in obs.collect_spans(api)
                        if s.get("t") == tid]

            assert _wait(
                lambda: len({s.get("daemon") for s in trace_spans()}) >= 2,
                timeout=30.0, interval=0.25,
            ), ("anomalous pod trace never crossed processes: "
                + str(sorted({s.get("daemon") for s in trace_spans()})))
            sel = trace_spans()
            names = {s["name"] for s in sel}
            assert "bind:landed" in names, names
            assert any(s.get("cat") == "bus"
                       and s.get("dur", 0.0) >= 60_000.0
                       for s in sel), "kept trace lacks the slow rpc"
            assert not any("_root" in s for s in sel)

            # the controller leg rides the owning Job's identity
            # (controller:status) — flag that trace anomalous too and
            # the union waterfall spans all three daemons
            assert probe_until_delayed(obs.trace_id_for("default", job))
            idents = obs.related_identities(api, "default", anom)

            def union():
                return obs.select_union(obs.collect_spans(api), idents)

            assert _wait(
                lambda: len({s.get("daemon") for s in union()}) >= 3,
                timeout=30.0, interval=0.25,
            ), ("union waterfall never spanned 3 daemons: "
                + str(sorted({s.get("daemon") for s in union()})))
            assert "controller:status" in {s["name"] for s in union()}

            # steady traces: every one is coin-dropped and none grew
            # anomaly evidence -> absent from the durable segments
            steady = {obs.trace_id_for("default", f"{job}-t-{k}")
                      for k in range(1, replicas)}
            exported = {s.get("t") for s in obs.collect_spans(api)}
            assert steady.isdisjoint(exported), (
                "steady traces must drop at the configured rate"
            )
        finally:
            obs.disable()
            if cache is not None:
                cache.stop_commit_plane()
            if sched_remote is not None:
                sched_remote.close()
            if api is not None:
                api.close()
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---- chaos twin: bit-identical with tail mode ON ----

class TestChaosTwinWithTail:
    def test_binding_map_bit_identical_with_tail_on(self, tmp_path):
        """The PR 13 pin, upgraded: the chaos twin runs with TAIL mode
        on both sides (sample < 1 so the pending pool actually
        engages) — buffering and completion-time decisions must never
        perturb a scheduling outcome."""
        from tests.test_chaos import ChaosCluster, _submit_mixed_workload

        maps = {}
        for label, spec in (
            ("faulty", "seed=77;bus.disconnect=0.05:count=3;"
                       "bus.delay=0.08:count=5:ms=5;"
                       "bus.client_drop=0.05:count=4;"
                       "cache.bind_fail=0.1:count=3"),
            ("clean", None),
        ):
            cluster = ChaosCluster(tmp_path, f"tail-{label}",
                                   compute_plane=False)
            try:
                obs.enable(cluster.remote, identity=f"sched-{label}",
                           flush_interval=0.05, sample=0.05, tail=True)
                _submit_mixed_workload(cluster)
                faults.configure(spec)
                cluster.run_cycles(10)
                faults.configure(None)
                assert _wait(
                    lambda: (cluster.cycle() or True)
                    and cluster.all_placed(),
                    timeout=30.0, interval=0.05,
                ), f"{label}: pods still unplaced with tail mode on"
                cluster.assert_no_duplicate_binds()
                assert cluster.cycle_errors == 0
                maps[label] = cluster.binding_map()
            finally:
                obs.disable()
                cluster.close()
                faults.configure(None)
                faults.reset_breakers()
        pinned = {k: v for k, v in maps["faulty"].items()
                  if "pinned" in k}
        pinned_clean = {k: v for k, v in maps["clean"].items()
                        if "pinned" in k}
        assert pinned == pinned_clean and len(pinned) == 4
        assert set(maps["faulty"]) == set(maps["clean"])
