"""Warm-pack equivalence: N random cache mutations followed by a delta
pack must produce tensors (and kernel bindings) identical to a cold
``pack_session`` seeded with the same bit registries — the PackCache's
correctness contract (ISSUE 2 tentpole).

Also covers: the delta metadata (previous snapshot + delta rows
reconstruct the new snapshot), the device stager (staged buffers match
the numpy planes), dirty-tracking granularity (status churn keeps task
rows clean; spec changes don't), and the snapshot clone pool.
"""

from __future__ import annotations

import copy

import numpy as np
import pytest

from volcano_tpu.actions.jax_allocate import (
    compute_task_order,
    JaxAllocateAction,
)
from volcano_tpu.apis import core
from volcano_tpu.framework import close_session, open_session
from volcano_tpu.ops.pack_cache import (
    JOB_PLANES,
    NODE_DYNAMIC_PLANES,
    NODE_STATIC_PLANES,
    PackCache,
    TASK_PLANES,
)
from volcano_tpu.ops.packing import BitRegistry, pack_session

from tests.builders import build_node, build_pod, build_pod_group, build_queue
from tests.scheduler_helpers import make_cache, tiers

STANDARD = lambda: tiers(
    ["priority", "gang"],
    ["drf", "predicates", "proportion", "nodeorder", "binpack"],
)

ALL_PLANES = (
    TASK_PLANES
    + NODE_DYNAMIC_PLANES
    + NODE_STATIC_PLANES
    + JOB_PLANES
    + ("tolerance",)
)

META_FIELDS = (
    "n_tasks",
    "n_nodes",
    "n_jobs",
    "task_uids",
    "node_names",
    "job_uids",
    "resource_names",
    "needs_host_validation",
    "memory_exact",
)


def _copy_reg(reg: BitRegistry) -> BitRegistry:
    c = BitRegistry(reg.words)
    c.index = dict(reg.index)
    c.overflow = reg.overflow
    return c


def _session_inputs(ssn):
    ordered = compute_task_order(ssn)
    jobs = {}
    for t in ordered:
        j = ssn.jobs.get(t.job)
        if j is not None and j.uid not in jobs:
            jobs[j.uid] = j
    nodes = [ssn.nodes[name] for name in sorted(ssn.nodes)]
    return ordered, list(jobs.values()), nodes


def _pack_both(cache, pc):
    """One cycle: warm pack through the PackCache, then a cold pack
    seeded with the resulting registry dictionaries; returns (ssn, warm,
    cold).  Post-pack seeding makes the contract well-defined even when
    a cycle registers new pairs from both a dirty task and a dirty node
    (warm packs nodes first for relay overlap, cold packs tasks first —
    FIRST-registration order differs, the dictionary does not)."""
    ssn = open_session(cache, STANDARD(), [])
    ordered, jobs, nodes = _session_inputs(ssn)
    warm = pc.pack(ordered, jobs, nodes, ssn.pack_epoch, enforce_pod_count=True)
    cold = pack_session(
        ordered,
        jobs,
        nodes,
        label_registry=_copy_reg(pc.label_reg),
        taint_registry=_copy_reg(pc.taint_reg),
    )
    return ssn, warm, cold


def _assert_identical(warm, cold, ctx=""):
    for name in ALL_PLANES:
        a, b = getattr(warm, name), getattr(cold, name)
        assert np.array_equal(a, b), f"{ctx}: plane {name} diverged"
    for f in META_FIELDS:
        assert getattr(warm, f) == getattr(cold, f), f"{ctx}: {f}"


def _base_cluster(rng, n_jobs=8, gang=4, n_nodes=10):
    nodes = []
    for i in range(n_nodes):
        labels = {"zone": f"z{i % 3}"}
        if i % 4 == 0:
            labels["disk"] = "ssd"
        taints = (
            [core.Taint(key="dedicated", value="batch", effect="NoSchedule")]
            if i % 5 == 0
            else []
        )
        nodes.append(
            build_node(f"n{i:03d}", {"cpu": "32", "memory": "64Gi"},
                       labels=labels, taints=taints)
        )
    pods, pgs = [], []
    for j in range(n_jobs):
        pgs.append(build_pod_group("ns", f"pg{j}", gang, queue="q"))
        for i in range(gang):
            kwargs = {}
            if j % 3 == 0:
                kwargs["selector"] = {"zone": f"z{j % 3}"}
            if j % 4 == 0:
                kwargs["tolerations"] = [
                    core.Toleration(key="dedicated", operator="Exists")
                ]
            pods.append(
                build_pod("ns", f"j{j}-t{i}", "",
                          {"cpu": ["500m", "1", "2"][int(rng.randint(3))],
                           "memory": "1Gi"},
                          group=f"pg{j}", **kwargs)
            )
    return dict(nodes=nodes, pods=pods, pod_groups=pgs, queues=[build_queue("q")])


def _mutate(cache, rng, step):
    """One random pack-relevant mutation through the cache event API."""
    kind = rng.randint(7)
    if kind == 0:
        # new gang job, selector may introduce a NEW label pair that
        # existing nodes already carry (back-patch coupling)
        j = f"new{step}"
        cache.add_pod_group(build_pod_group("ns", f"pg-{j}", 2, queue="q"))
        sel = {"disk": "ssd"} if step % 2 else {"zone": "z1"}
        for i in range(2):
            cache.add_pod(
                build_pod("ns", f"{j}-t{i}", "", {"cpu": "1", "memory": "1Gi"},
                          group=f"pg-{j}", selector=sel)
            )
    elif kind == 1:
        # spec-relevant pod update: bump a pending pod's request
        for job in cache.jobs.values():
            for t in job.tasks.values():
                if t.pod is not None and not t.node_name:
                    new = copy.deepcopy(t.pod)
                    new.spec.containers[0].resources = {
                        "requests": {"cpu": "3", "memory": "2Gi"}
                    }
                    cache.update_pod(t.pod, new)
                    return
    elif kind == 2:
        # status-only pod update (the warm path must keep the row clean)
        for job in cache.jobs.values():
            for t in job.tasks.values():
                if t.pod is not None and not t.node_name:
                    new = copy.deepcopy(t.pod)
                    new.status.phase = "Pending"
                    cache.update_pod(t.pod, new)
                    return
    elif kind == 3:
        # node update: new taint (keyed-Exists re-resolution coupling)
        name = sorted(cache.nodes)[int(rng.randint(len(cache.nodes)))]
        node = cache.nodes[name].node
        if node is None:
            return
        new = copy.deepcopy(node)
        new.spec.taints = [
            core.Taint(key="dedicated", value=f"v{step}", effect="NoSchedule")
        ]
        cache.update_node(node, new)
    elif kind == 4:
        # node update: label flip
        name = sorted(cache.nodes)[int(rng.randint(len(cache.nodes)))]
        node = cache.nodes[name].node
        if node is None:
            return
        new = copy.deepcopy(node)
        new.metadata.labels = dict(new.metadata.labels)
        new.metadata.labels["zone"] = f"z{int(rng.randint(4))}"
        cache.update_node(node, new)
    elif kind == 5:
        # bind a pending task (node accounting changes, task row clean)
        for job in cache.jobs.values():
            for t in list(job.tasks.values()):
                if not t.node_name:
                    host = sorted(cache.nodes)[int(rng.randint(len(cache.nodes)))]
                    try:
                        cache.bind(t, host)
                    except Exception:
                        pass
                    return
    else:
        # topology change: a brand-new node (wholesale node invalidation)
        cache.add_node(
            build_node(f"nx{step}", {"cpu": "16", "memory": "32Gi"},
                       labels={"zone": "z9"})
        )


def test_pack_cache_property_random_mutations():
    """The headline contract: after every mutation batch, the delta pack
    is bit-identical to a seeded cold pack, and the kernel bindings are
    identical on both."""
    rng = np.random.RandomState(7)
    cache = make_cache(**_base_cluster(rng))
    pc = PackCache(cache)

    ssn, warm, cold = _pack_both(cache, pc)
    _assert_identical(warm, cold, "cycle 0 (cold)")
    close_session(ssn)

    from volcano_tpu.ops.kernels import run_packed

    for cycle in range(1, 9):
        for _ in range(int(rng.randint(1, 4))):
            _mutate(cache, rng, cycle * 10 + int(rng.randint(10)))
        ssn, warm, cold = _pack_both(cache, pc)
        _assert_identical(warm, cold, f"cycle {cycle}")
        if cycle in (3, 8) and warm.n_tasks:
            assert np.array_equal(run_packed(warm), run_packed(cold))
        close_session(ssn)


def test_pack_cache_warm_reuses_rows_after_bind_churn():
    """Bind + status-only revert churn: node planes go dirty, task rows
    stay cached — the steady-state warm cycle."""
    rng = np.random.RandomState(3)
    cache = make_cache(**_base_cluster(rng, n_jobs=4, gang=3, n_nodes=6))
    pc = PackCache(cache)
    ssn, warm, cold = _pack_both(cache, pc)
    close_session(ssn)
    assert pc.last_stats["mode"] == "cold"

    # status-only churn on every pending pod
    for job in list(cache.jobs.values()):
        for t in list(job.tasks.values()):
            if t.pod is not None and not t.node_name:
                new = copy.deepcopy(t.pod)
                cache.update_pod(t.pod, new)

    ssn, warm, cold = _pack_both(cache, pc)
    _assert_identical(warm, cold, "status churn")
    close_session(ssn)
    assert pc.last_stats["mode"] == "warm"
    assert pc.last_stats["repacked_tasks"] == 0
    assert pc.last_stats["reused_tasks"] == warm.n_tasks


def test_delta_reconstructs_snapshot():
    """prev snapshot + PackDelta rows == new snapshot, plane by plane —
    the contract the device stager and the sidecar delta frames rely
    on."""
    rng = np.random.RandomState(11)
    cache = make_cache(**_base_cluster(rng))
    pc = PackCache(cache)
    ssn, warm0, _ = _pack_both(cache, pc)
    close_session(ssn)
    prev = {name: np.copy(getattr(warm0, name)) for name in ALL_PLANES}

    _mutate(cache, rng, 1)  # kind varies with seed; any non-topology works
    for job in list(cache.jobs.values()):
        for t in list(job.tasks.values()):
            if not t.node_name:
                try:
                    cache.bind(t, sorted(cache.nodes)[0])
                except Exception:
                    pass
                break
        break

    ssn, warm1, _ = _pack_both(cache, pc)
    close_session(ssn)
    if warm1.delta is None:
        pytest.skip("mutation forced a wholesale pack on this seed")
    for name in ALL_PLANES:
        new = getattr(warm1, name)
        if name not in warm1.delta.planes:
            assert np.array_equal(prev[name], new), name
            continue
        rows = warm1.delta.planes[name]
        if rows is None:
            continue  # wholesale plane — nothing to reconstruct
        rebuilt = prev[name].copy()
        rebuilt[rows] = new[rows]
        assert np.array_equal(rebuilt, new), name


def test_device_stager_matches_numpy_planes():
    from volcano_tpu.ops.device_stage import STAGED_PLANES, get_stager

    rng = np.random.RandomState(5)
    cache = make_cache(**_base_cluster(rng, n_jobs=3, gang=2, n_nodes=5))
    pc = PackCache(cache)
    for cycle in range(3):
        if cycle:
            _mutate(cache, rng, cycle)
        ssn, warm, _ = _pack_both(cache, pc)
        close_session(ssn)
        staged = get_stager(pc.key).stage(warm)
        for name in STAGED_PLANES:
            assert np.array_equal(np.asarray(staged[name]), getattr(warm, name)), (
                cycle,
                name,
            )


def test_out_of_order_epoch_packs_one_shot():
    rng = np.random.RandomState(9)
    cache = make_cache(**_base_cluster(rng, n_jobs=2, gang=2, n_nodes=4))
    pc = PackCache(cache)
    ssn, warm, cold = _pack_both(cache, pc)
    close_session(ssn)
    consumed = pc._consumed_rev

    class StaleEpoch:
        rev = consumed - 1
        topology_rev = 0
        dirty_tasks = set()
        dirty_nodes = set()

    ssn = open_session(cache, STANDARD(), [])
    ordered, jobs, nodes = _session_inputs(ssn)
    snap = pc.pack(ordered, jobs, nodes, StaleEpoch())
    close_session(ssn)
    assert snap.cache_key is None  # one-shot: not cacheable downstream
    assert pc._consumed_rev == consumed  # state untouched


def test_dirty_tracking_granularity():
    """Status-only churn keeps task rows clean; spec changes dirty them;
    binds dirty nodes; node adds bump the topology revision."""
    rng = np.random.RandomState(2)
    cache = make_cache(**_base_cluster(rng, n_jobs=2, gang=2, n_nodes=3))
    task = next(
        t
        for job in cache.jobs.values()
        for t in job.tasks.values()
        if t.pod is not None and not t.node_name
    )

    cache.clear_dirty_through(cache.snapshot().pack_epoch)
    new = copy.deepcopy(task.pod)
    new.status.phase = "Pending"
    cache.update_pod(task.pod, new)
    assert task.uid not in cache._dirty_tasks

    stored = cache.jobs[task.job].tasks[task.uid]
    new2 = copy.deepcopy(stored.pod)
    new2.spec.containers[0].resources = {"requests": {"cpu": "7", "memory": "1Gi"}}
    cache.update_pod(stored.pod, new2)
    assert task.uid in cache._dirty_tasks

    topo0 = cache._topology_rev
    stored = cache.jobs[task.job].tasks[task.uid]
    cache.bind(stored, sorted(cache.nodes)[0])
    assert sorted(cache.nodes)[0] in cache._dirty_nodes
    assert cache._topology_rev == topo0

    cache.add_node(build_node("late", {"cpu": "4", "memory": "8Gi"}))
    assert cache._topology_rev > topo0


def _snapshot_state(snapshot):
    out = {}
    for uid, j in sorted(snapshot.jobs.items()):
        out[("job", uid)] = (
            j.allocated.milli_cpu,
            j.allocated.memory,
            j.total_request.milli_cpu,
            sorted(j.tasks),
            {s.name: sorted(ts) for s, ts in j.task_status_index.items()},
            j.priority,
        )
    for name, n in sorted(snapshot.nodes.items()):
        out[("node", name)] = (
            n.idle.milli_cpu,
            n.idle.memory,
            n.used.milli_cpu,
            sorted(n.tasks),
        )
    return out


def test_snapshot_clone_reuse_equivalence():
    """A snapshot_reuse=True cache must produce snapshots identical to a
    cold-cloning cache across scheduling cycles with binds and churn."""
    rng = np.random.RandomState(4)
    cluster = _base_cluster(rng, n_jobs=5, gang=3, n_nodes=6)
    cache_a = make_cache(**copy.deepcopy(cluster))
    cache_b = make_cache(**copy.deepcopy(cluster))
    cache_a.snapshot_reuse = True

    action = JaxAllocateAction()
    for cycle in range(3):
        ssn_a = open_session(cache_a, STANDARD(), [])
        ssn_b = open_session(cache_b, STANDARD(), [])
        assert _snapshot_state(ssn_a) == _snapshot_state(ssn_b), f"cycle {cycle}"
        action.execute(ssn_a)
        action.execute(ssn_b)
        close_session(ssn_a)
        close_session(ssn_b)
        # churn: one more pending job arriving between cycles (same uids
        # on both caches — the builders mint fresh ones per call)
        pg = build_pod_group("ns", f"late{cycle}", 1, queue="q")
        pod = build_pod("ns", f"late{cycle}-t0", "",
                        {"cpu": "1", "memory": "1Gi"}, group=f"late{cycle}")
        for c in (cache_a, cache_b):
            c.add_pod_group(copy.deepcopy(pg))
            c.add_pod(copy.deepcopy(pod))
    # final snapshots agree too
    ssn_a = open_session(cache_a, STANDARD(), [])
    ssn_b = open_session(cache_b, STANDARD(), [])
    assert _snapshot_state(ssn_a) == _snapshot_state(ssn_b)
    close_session(ssn_a)
    close_session(ssn_b)


def test_kernels_identical_with_staged_planes():
    """run_packed / run_packed_blocked consume staged device planes and
    must produce the same assignment as the pure-numpy path."""
    from volcano_tpu.ops.blocked import run_packed_blocked
    from volcano_tpu.ops.device_stage import get_stager
    from volcano_tpu.ops.kernels import run_packed

    rng = np.random.RandomState(13)
    cache = make_cache(**_base_cluster(rng, n_jobs=6, gang=3, n_nodes=8))
    pc = PackCache(cache)
    ssn, warm, _ = _pack_both(cache, pc)
    close_session(ssn)

    plain_scan = run_packed(warm)
    plain_blocked = run_packed_blocked(warm)
    warm.device_planes = get_stager(pc.key).stage(warm)
    np.testing.assert_array_equal(run_packed(warm), plain_scan)
    np.testing.assert_array_equal(run_packed_blocked(warm), plain_blocked)


def test_new_label_pair_back_patches_clean_nodes():
    """A dirty task registering a NEW selector pair must set the bit on
    every CLEAN node carrying that label — the cold pack's task-pass →
    node-pass ordering, reproduced via the inverted label index."""
    rng = np.random.RandomState(0)
    cluster = _base_cluster(rng, n_jobs=2, gang=2, n_nodes=8)
    cache = make_cache(**cluster)
    pc = PackCache(cache)
    ssn, _, _ = _pack_both(cache, pc)
    close_session(ssn)
    assert ("disk", "ssd") not in pc.label_reg.index  # nothing references it yet

    cache.add_pod_group(build_pod_group("ns", "ssdjob", 1, queue="q"))
    cache.add_pod(
        build_pod("ns", "ssdjob-t0", "", {"cpu": "1", "memory": "1Gi"},
                  group="ssdjob", selector={"disk": "ssd"})
    )
    ssn, warm, cold = _pack_both(cache, pc)
    close_session(ssn)
    _assert_identical(warm, cold, "label back-patch")
    assert pc.last_stats["mode"] == "warm"
    idx = pc.label_reg.index[("disk", "ssd")]
    word, bit = idx // 32, np.uint32(1 << (idx % 32))
    ssd_rows = [i for i, name in enumerate(warm.node_names) if i % 4 == 0]
    assert ssd_rows and all(
        warm.node_label_bits[i, word] & bit for i in ssd_rows
    )
    # and the patch is visible in the delta so device/sidecar copies heal
    assert warm.delta is not None
    rows = warm.delta.planes.get("node_label_bits")
    assert rows is None or set(ssd_rows) <= set(rows.tolist())


def test_new_taint_reresolves_clean_exists_tolerations():
    """A dirty node registering a NEW taint pair must reach CLEAN tasks
    holding keyed-Exists tolerations on that key."""
    rng = np.random.RandomState(0)
    cluster = _base_cluster(rng, n_jobs=4, gang=2, n_nodes=6)
    cache = make_cache(**cluster)
    pc = PackCache(cache)
    ssn, _, _ = _pack_both(cache, pc)
    close_session(ssn)

    node = cache.nodes[sorted(cache.nodes)[1]].node
    new = copy.deepcopy(node)
    new.spec.taints = [
        core.Taint(key="dedicated", value="fresh", effect="NoSchedule")
    ]
    cache.update_node(node, new)

    ssn, warm, cold = _pack_both(cache, pc)
    close_session(ssn)
    _assert_identical(warm, cold, "taint re-resolve")
    assert pc.last_stats["mode"] == "warm"
    idx = pc.taint_reg.index[("dedicated", "fresh", "NoSchedule")]
    word, bit = idx // 32, np.uint32(1 << (idx % 32))
    # every j%4==0 task tolerates Exists "dedicated" → bit must be set
    exists_rows = [
        i for i, uid in enumerate(warm.task_uids)
        if uid in pc._exists_uids
    ]
    assert exists_rows and all(
        warm.task_tol_bits[i, word] & bit for i in exists_rows
    )


def test_registry_overflow_recovers_via_cold_rebuild():
    """Pair churn across the cache lifetime must not permanently latch
    needs_host_validation: an overflowed registry forces one cold pack
    that rebuilds fresh registries from the live session."""
    rng = np.random.RandomState(17)
    cache = make_cache(**_base_cluster(rng, n_jobs=2, gang=2, n_nodes=4))
    pc = PackCache(cache)
    ssn, warm, _ = _pack_both(cache, pc)
    close_session(ssn)
    assert not warm.needs_host_validation

    # poison: registry saturated by pairs no live object references
    for i in range(pc.label_reg.words * 32 + 5):
        pc.label_reg.bit(("ghost", str(i)))
    assert pc.label_reg.overflow

    ssn, warm, cold = _pack_both(cache, pc)
    close_session(ssn)
    assert pc.last_stats["mode"] == "cold"  # overflow forced the rebuild
    assert not pc.label_reg.overflow
    assert not warm.needs_host_validation
    _assert_identical(warm, cold, "post-overflow rebuild")


def test_micro_pack_on_task_bucket_change():
    """Task-bucket crossings no longer force a cold pack: the micro
    path rebuilds only the task planes fresh (warm node planes,
    persistent registries) and stays bit-identical to a seeded cold
    pack — the subset-pack half of the event-driven micro-cycle
    (ISSUE 8)."""
    from volcano_tpu.ops.kernels import run_packed

    rng = np.random.RandomState(11)
    cache = make_cache(**_base_cluster(rng, n_jobs=8, gang=4))  # 32 pending
    pc = PackCache(cache)
    ssn, warm, cold = _pack_both(cache, pc)
    close_session(ssn)
    assert pc.last_stats["mode"] == "cold"
    assert pc.last_stats["cold_cause"] == "first-pack"

    # grow the pending set past the 64-row bucket: + 20 two-task jobs,
    # some with NEW label pairs (the back-patch coupling must reach the
    # warm node planes)
    for k in range(20):
        cache.add_pod_group(build_pod_group("ns", f"burst{k}", 2, queue="q"))
        sel = {"disk": "ssd"} if k % 3 == 0 else None
        for i in range(2):
            cache.add_pod(
                build_pod("ns", f"burst{k}-t{i}", "",
                          {"cpu": "1", "memory": "1Gi"},
                          group=f"burst{k}", selector=sel)
            )
    ssn, micro, cold = _pack_both(cache, pc)
    _assert_identical(micro, cold, "bucket grow (micro)")
    assert pc.last_stats["mode"] == "micro"
    assert micro.task_resreq.shape[0] == 128
    assert np.array_equal(run_packed(micro), run_packed(cold))
    close_session(ssn)

    # shrink back under the bucket (delete the burst) — micro again,
    # and the NEXT unchanged cycle is a plain warm pack over the
    # micro-produced base
    burst_pods = [
        t.pod
        for j in list(cache.jobs.values())
        for t in list(j.tasks.values())
        if t.name.startswith("burst") and t.pod is not None
    ]
    for pod in burst_pods:
        cache.delete_pod(pod)
    ssn, micro2, cold2 = _pack_both(cache, pc)
    _assert_identical(micro2, cold2, "bucket shrink (micro)")
    assert pc.last_stats["mode"] == "micro"
    assert micro2.task_resreq.shape[0] == 64
    close_session(ssn)

    ssn, warm2, cold3 = _pack_both(cache, pc)
    _assert_identical(warm2, cold3, "steady (warm over micro base)")
    assert pc.last_stats["mode"] == "warm"
    close_session(ssn)


def test_micro_pack_device_stager_consistency():
    """Staged device planes equal the numpy planes across a micro pack
    (task planes restaged wholesale at the new bucket, node planes
    delta-scattered through the padded-bucket scatter)."""
    import jax.numpy as jnp

    from volcano_tpu.ops.device_stage import get_stager, STAGED_PLANES

    rng = np.random.RandomState(13)
    cache = make_cache(**_base_cluster(rng, n_jobs=6, gang=4))
    pc = PackCache(cache)
    ssn, warm, _cold = _pack_both(cache, pc)
    stager = get_stager(pc.key)
    stager.stage(warm)
    close_session(ssn)

    for k in range(24):
        cache.add_pod_group(build_pod_group("ns", f"m{k}", 2, queue="q"))
        for i in range(2):
            cache.add_pod(
                build_pod("ns", f"m{k}-t{i}", "",
                          {"cpu": "1", "memory": "1Gi"}, group=f"m{k}")
            )
    ssn, micro, _cold = _pack_both(cache, pc)
    assert pc.last_stats["mode"] == "micro"
    planes = stager.stage(micro)
    for name in STAGED_PLANES:
        arr = getattr(micro, name)
        if arr is None:
            continue
        assert np.array_equal(np.asarray(planes[name]), arr), (
            f"staged plane {name} diverged after micro pack"
        )
    close_session(ssn)


def test_cold_cause_recorded():
    """PackCache.last_stats names why a pack went cold — the label the
    micro-cycle fallback counter attributes."""
    rng = np.random.RandomState(17)
    cache = make_cache(**_base_cluster(rng, n_jobs=4, gang=3, n_nodes=6))
    pc = PackCache(cache)
    ssn, _, _ = _pack_both(cache, pc)
    close_session(ssn)
    assert pc.last_stats["cold_cause"] == "first-pack"

    # registry overflow → cold with the overflow cause
    pc.label_reg.overflow = True
    ssn, warm, cold = _pack_both(cache, pc)
    _assert_identical(warm, cold, "overflow recovery")
    assert pc.last_stats["mode"] == "cold"
    assert pc.last_stats["cold_cause"] == "registry-overflow"
    close_session(ssn)

    # node topology change → cold with the topology cause
    cache.add_node(build_node("fresh-node", {"cpu": "8", "memory": "16Gi"}))
    ssn, warm, cold = _pack_both(cache, pc)
    _assert_identical(warm, cold, "topology rebuild")
    assert pc.last_stats["mode"] == "cold"
    assert pc.last_stats["cold_cause"] == "topology"
    close_session(ssn)
