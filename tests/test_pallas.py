"""Pallas session-kernel equivalence: the VMEM-resident full scan must
reproduce the plain XLA scan's assignments exactly — tie-breaks, gang
discards, taints/labels, capacity pressure — since it is the kernel the
TPU path actually runs (ops/dispatch.py).  CPU CI uses interpret mode."""

from __future__ import annotations

import numpy as np
import pytest

from volcano_tpu.ops.kernels import run_packed
from volcano_tpu.ops.pallas_session import run_packed_pallas
from volcano_tpu.ops.synthetic import generate_snapshot


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_pallas_matches_plain_random(seed):
    snap = generate_snapshot(n_tasks=300, n_nodes=150, gang_size=4, seed=seed)
    got = run_packed_pallas(snap, block_size=128, interpret=True)
    assert (run_packed(snap) == got).all()


def test_pallas_matches_plain_with_predicates():
    snap = generate_snapshot(
        n_tasks=256, n_nodes=130, gang_size=8, seed=3,
        label_classes=4, taint_fraction=0.25,
    )
    got = run_packed_pallas(snap, block_size=128, interpret=True)
    assert (run_packed(snap) == got).all()


def test_pallas_matches_plain_capacity_pressure():
    """Tight capacity: infeasible tasks, gang discards, multi-round
    fixpoint."""
    snap = generate_snapshot(
        n_tasks=400, n_nodes=16, gang_size=5, seed=4,
        node_cpu_milli=16_000, node_mem_mib=32_768,
    )
    plain = run_packed(snap)
    got = run_packed_pallas(snap, block_size=128, interpret=True)
    assert (plain == got).all()
    assert (plain == -1).any()  # pressure actually discards gangs


def test_pallas_matches_plain_single_node():
    snap = generate_snapshot(n_tasks=64, n_nodes=1, gang_size=2, seed=5)
    got = run_packed_pallas(snap, block_size=128, interpret=True)
    assert (run_packed(snap) == got).all()


def test_pallas_rejects_beyond_f32_envelope():
    snap = generate_snapshot(
        n_tasks=16, n_nodes=4, gang_size=2, seed=6,
        node_cpu_milli=2_000_000, node_mem_mib=4_000_000,
    )
    with pytest.raises(ValueError):
        run_packed_pallas(snap, block_size=128, interpret=True)


def test_auto_dispatch_small_native_matches_plain():
    """Small default-weight sessions route to the native C++ executor
    (select_executor → 'native'); its bindings must equal the XLA scan."""
    from volcano_tpu.ops.dispatch import run_packed_auto, select_executor

    snap = generate_snapshot(n_tasks=100, n_nodes=20, gang_size=4, seed=7)
    if select_executor(snap) != "native":
        pytest.skip("native executor unavailable (no g++)")
    assert (run_packed_auto(snap) == run_packed(snap)).all()


def test_auto_dispatch_small_custom_weights_uses_plain():
    """Non-default weights bypass the native executor (its weights are
    baked in) and take the XLA scan."""
    from volcano_tpu.ops.dispatch import run_packed_auto, select_executor
    from volcano_tpu.ops.kernels import ScoreWeights

    w = ScoreWeights(binpack_weight=2.0)
    snap = generate_snapshot(n_tasks=100, n_nodes=20, gang_size=4, seed=7)
    assert select_executor(snap, w) == "xla-scan"
    assert (run_packed_auto(snap, weights=w) == run_packed(snap, weights=w)).all()


def test_make_session_dispatch_prestaged_matches_wrapper():
    # the bench's compute probe (make_session_dispatch prestage=True)
    # must enqueue the SAME kernel as run_packed_pallas — prestaging only
    # moves the transfer, never the math
    from volcano_tpu.ops.pallas_session import make_session_dispatch

    snap = generate_snapshot(n_tasks=300, n_nodes=150, gang_size=4, seed=3)
    want = run_packed_pallas(snap, block_size=128, interpret=True)

    dispatch, T_act = make_session_dispatch(
        snap, block_size=128, interpret=True, prestage=True)
    out = np.asarray(dispatch())
    got = np.full(snap.n_tasks, -1, dtype=np.int32)
    n = min(snap.n_tasks, T_act)
    got[:n] = out[:n]
    assert (want == got).all()
    # repeated dispatches (the pipelined-slope probe) stay identical
    out2 = np.asarray(dispatch())
    assert (np.asarray(out) == np.asarray(out2)).all()


def test_warmup_kernels_runs_auto_path():
    # the --warmup flag on vtpu-scheduler / vtpu-compute-plane: compiles
    # whatever executor auto-dispatch selects for the bucket, and returns
    # its name
    from volcano_tpu.ops.dispatch import select_executor, warmup_kernels
    from volcano_tpu.ops.synthetic import generate_snapshot

    executor = warmup_kernels(n_tasks=256, n_nodes=64, gang_size=4)
    snap = generate_snapshot(n_tasks=256, n_nodes=64, gang_size=4)
    assert executor == select_executor(snap)
