"""Plugin score-math tables — the numeric-expectation style of the
reference's binpack_test.go plus drf/proportion cases: exact score and
share values for known (request, used, capacity) inputs."""

from __future__ import annotations

import pytest

from volcano_tpu.api import new_task_info
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.plugins.binpack import (
    bin_packing_score,
    PriorityWeight,
    resource_bin_packing_score,
)

from tests.builders import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)
from tests.scheduler_helpers import make_cache, tiers


def _task(cpu, mem):
    return new_task_info(build_pod("ns", "t", "", {"cpu": cpu, "memory": mem}))


def _node(cpu, mem, used_cpu="0", used_mem="0"):
    node = NodeInfo(build_node("bn", {"cpu": cpu, "memory": mem}))
    if used_cpu != "0" or used_mem != "0":
        t = new_task_info(
            build_pod("ns", "filler", "bn", {"cpu": used_cpu, "memory": used_mem},
                      phase="Running")
        )
        node.add_task(t)
    return node


class TestBinpackScoreTable:
    """binpack_test.go numeric cases: score = Σ lane((used+req)/alloc×w)
    / Σw × 10 × weight."""

    @pytest.mark.parametrize(
        "req_cpu,req_mem,used_cpu,used_mem,cap_cpu,cap_mem,expected",
        [
            # empty node, 1/8 cpu + 1/16 mem → ((0.125+0.0625)/2)*10 = 0.9375
            ("1", "1Gi", "0", "0", "8", "16Gi", 0.9375),
            # half-used node → ((5/8 + 9/16)/2)*10 = 5.9375
            ("1", "1Gi", "4", "8Gi", "8", "16Gi", 5.9375),
            # request overflows cpu → cpu lane 0, mem (1+8)/16/2*10 = 2.8125
            ("8", "1Gi", "4", "8Gi", "8", "16Gi", 2.8125),
            # perfect fill → ((8/8 + 16/16)/2)*10 = 10
            ("4", "8Gi", "4", "8Gi", "8", "16Gi", 10.0),
        ],
    )
    def test_default_weights(self, req_cpu, req_mem, used_cpu, used_mem,
                             cap_cpu, cap_mem, expected):
        score = bin_packing_score(
            _task(req_cpu, req_mem),
            _node(cap_cpu, cap_mem, used_cpu, used_mem),
            PriorityWeight(),
        )
        assert score == pytest.approx(expected, abs=1e-9)

    def test_weighted_lanes(self):
        """cpu weight 2, memory weight 1: ((2*5/8 + 9/16)/3)*10."""
        score = bin_packing_score(
            _task("1", "1Gi"),
            _node("8", "16Gi", "4", "8Gi"),
            PriorityWeight(weight=1, cpu=2, memory=1),
        )
        assert score == pytest.approx((2 * 5 / 8 + 9 / 16) / 3 * 10, abs=1e-9)

    def test_binpack_weight_scales_total(self):
        base = bin_packing_score(_task("1", "1Gi"), _node("8", "16Gi"), PriorityWeight())
        x5 = bin_packing_score(
            _task("1", "1Gi"), _node("8", "16Gi"), PriorityWeight(weight=5)
        )
        assert x5 == pytest.approx(5 * base, abs=1e-9)

    @pytest.mark.parametrize(
        "requested,capacity,used,weight,expected",
        [
            (1000, 0, 0, 1, 0.0),       # zero capacity
            (1000, 8000, 0, 0, 0.0),    # zero weight
            (5000, 8000, 4000, 1, 0.0), # overflow
            (1000, 8000, 3000, 2, 1.0), # (1000+3000)*2/8000
        ],
    )
    def test_lane_score(self, requested, capacity, used, weight, expected):
        assert resource_bin_packing_score(requested, capacity, used, weight) == expected


class TestDrfShares:
    def test_dominant_share_is_max_lane(self):
        """drf.go:299-311 — share = max(allocated_r / total_r)."""
        from volcano_tpu.plugins.drf import DrfPlugin
        from volcano_tpu.api.resource import Resource

        plugin = DrfPlugin({})
        plugin.total_resource = Resource(milli_cpu=10_000, memory=100 * 2**30)
        dominant, share = plugin._calculate_share(
            Resource(milli_cpu=2_000, memory=50 * 2**30),
            plugin.total_resource,
        )
        assert dominant == "memory" and share == pytest.approx(0.5)

    def test_job_order_prefers_lower_share(self):
        """Jobs with smaller dominant share schedule first (fairness)."""
        cache = make_cache(
            nodes=[build_node("n0", {"cpu": "10", "memory": "100G"})],
            pods=[
                build_pod("ns", "greedy-r", "n0", {"cpu": "1", "memory": "50G"},
                          phase="Running", group="greedy"),
                build_pod("ns", "greedy-p", "", {"cpu": "1", "memory": "1G"},
                          group="greedy"),
                build_pod("ns", "modest-p", "", {"cpu": "1", "memory": "1G"},
                          group="modest"),
            ],
            pod_groups=[
                build_pod_group("ns", "greedy", 1, queue="q"),
                build_pod_group("ns", "modest", 1, queue="q"),
            ],
            queues=[build_queue("q")],
        )
        from volcano_tpu.framework.framework import close_session, open_session

        ssn = open_session(
            cache, tiers(["priority", "gang", "conformance"], ["drf"]), []
        )
        greedy = next(j for j in ssn.jobs.values() if "greedy" in j.name)
        modest = next(j for j in ssn.jobs.values() if "modest" in j.name)
        # modest (share 0) orders before greedy (share 0.5)
        assert ssn.job_order_fn(modest, greedy)
        assert not ssn.job_order_fn(greedy, modest)
        close_session(ssn)


class TestProportionDeserved:
    def _session(self, weights, node_cpu="12", node_mem="12G"):
        pods, pgs, queues = [], [], []
        for i, w in enumerate(weights):
            queues.append(build_queue(f"q{i}", weight=w))
            pgs.append(build_pod_group("ns", f"pg{i}", 1, queue=f"q{i}"))
            pods.append(
                build_pod("ns", f"p{i}", "", {"cpu": "100", "memory": "1G"},
                          group=f"pg{i}")
            )
        cache = make_cache(
            nodes=[build_node("n0", {"cpu": node_cpu, "memory": node_mem})],
            pods=pods, pod_groups=pgs, queues=queues,
        )
        from volcano_tpu.framework.framework import open_session

        return open_session(
            cache, tiers(["priority", "gang", "conformance"], ["proportion"]), []
        )

    def test_water_filling_splits_by_weight(self):
        """proportion.go:104-157 — demand exceeds supply: deserved splits
        cpu 12 → 4/8 for weights 1:2 (both queues saturate their ask)."""
        ssn = self._session([1, 2])
        plugin = ssn.plugins["proportion"]
        attrs = {a.name: a for a in plugin.queue_opts.values()}
        assert attrs["q0"].deserved.milli_cpu == pytest.approx(4000)
        assert attrs["q1"].deserved.milli_cpu == pytest.approx(8000)

    def test_equal_weights_split_evenly(self):
        ssn = self._session([1, 1])
        plugin = ssn.plugins["proportion"]
        attrs = {a.name: a for a in plugin.queue_opts.values()}
        assert attrs["q0"].deserved.milli_cpu == pytest.approx(6000)
        assert attrs["q1"].deserved.milli_cpu == pytest.approx(6000)
