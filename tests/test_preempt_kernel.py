"""Preempt dense-formulation equivalence: the packed numpy reference
(ops/preempt_pack.py) must reproduce the host PreemptAction's evictions
and pipelined placements exactly on identical sessions — the same
bindings-equivalence discipline the allocate kernel has."""

from __future__ import annotations

import numpy as np
import pytest

from volcano_tpu.actions.preempt import PreemptAction
from volcano_tpu.api import TaskStatus
from volcano_tpu.framework.framework import close_session, open_session
from volcano_tpu.ops.preempt_pack import pack_preempt_session, preempt_dense

from tests.builders import (
    build_node,
    build_pod,
    build_pod_group,
    build_priority_class,
    build_queue,
)
from tests.scheduler_helpers import make_cache, tiers


FULL_TIERS = tiers(
    ["priority", "gang", "conformance"],
    ["drf", "predicates", "proportion", "nodeorder", "binpack"],
)


def _run_host(cache):
    """Host action → (evicted uid set, {preemptor uid: node}) read from
    the session before close."""
    ssn = open_session(cache, FULL_TIERS, [])
    # pack BEFORE the action mutates session state
    pk = pack_preempt_session(ssn)
    PreemptAction().execute(ssn)
    pipelined = {}
    for job in ssn.jobs.values():
        for t in job.task_status_index.get(TaskStatus.Pipelined, {}).values():
            pipelined[t.uid] = t.node_name
    close_session(ssn)
    return set(cache.evictor.evicts), pipelined, pk


def _dense_outcome(pk):
    evicted, pnode = preempt_dense(pk)
    ev_names = {pk.vic_names[i] for i in np.nonzero(evicted)[0]}
    pipelined = {
        pk.ptask_uids[p]: pk.node_names[pnode[p]]
        for p in range(pk.base.n_tasks)
        if pnode[p] >= 0
    }
    return ev_names, pipelined


def _pallas_outcome(pk):
    """Interpret-mode Pallas replay → same (evicted names, pipelined map)
    shape as _dense_outcome, so every case below proves host ≡ dense ≡
    pallas on identical sessions."""
    from volcano_tpu.ops.preempt_pallas import run_preempt_pallas

    evicted, pnode = run_preempt_pallas(pk, interpret=True)
    ev_names = {pk.vic_names[i] for i in np.nonzero(evicted)[0]}
    pipelined = {
        pk.ptask_uids[p]: pk.node_names[pnode[p]]
        for p in range(pk.base.n_tasks)
        if pnode[p] >= 0
    }
    return ev_names, pipelined


def _case_saturated(n_nodes=4, gangs=2, gang_size=2, seed=0):
    """Nodes saturated with low-priority runners; pending high-priority
    gangs that must preempt."""
    rng = np.random.RandomState(seed)
    nodes = [
        build_node(f"n{i:03d}", {"cpu": "4", "memory": "8G"}) for i in range(n_nodes)
    ]
    pods, pgs, queues = [], [], [build_queue("q1", weight=1)]
    # fillers: one job per node pair, priority 0, saturate cpu
    fid = 0
    for i in range(n_nodes):
        for k in range(4):
            pods.append(
                build_pod(
                    "ns", f"filler-{fid:03d}", f"n{i:03d}",
                    {"cpu": "1", "memory": str(1 + int(rng.randint(0, 2))) + "G"},
                    phase="Running", group=f"fpg{fid % 3}", priority=0,
                )
            )
            fid += 1
    for g in range(3):
        pgs.append(build_pod_group("ns", f"fpg{g}", 1, queue="q1"))
    # preemptors: high-priority gangs
    for g in range(gangs):
        pgs.append(build_pod_group("ns", f"hpg{g}", gang_size, queue="q1",
                                   priority_class_name="high"))
        for m in range(gang_size):
            pods.append(
                build_pod(
                    "ns", f"high-{g}-{m}", "",
                    {"cpu": "2", "memory": "2G"},
                    group=f"hpg{g}", priority=100,
                )
            )
    return make_cache(
        nodes=nodes, pods=pods, pod_groups=pgs, queues=queues,
        priority_classes=[build_priority_class("high", 100)],
    )


def _assert_case(cache):
    """host ≡ dense ≡ pallas on one session; returns the host outcome."""
    host_ev, host_pipe, pk = _run_host(cache)
    dense_ev, dense_pipe = _dense_outcome(pk)
    assert dense_ev == host_ev
    assert dense_pipe == host_pipe
    pallas_ev, pallas_pipe = _pallas_outcome(pk)
    assert pallas_ev == host_ev
    assert pallas_pipe == host_pipe
    return host_ev, host_pipe


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_dense_matches_host_saturated(seed):
    cache = _case_saturated(seed=seed)
    host_ev, host_pipe = _assert_case(cache)
    assert host_ev  # the scenario actually preempts


def test_dense_matches_host_idle_sufficient():
    """Enough idle resources → no evictions either way... but preempt
    still pipelines nothing (allocate would place them)."""
    cache = make_cache(
        nodes=[build_node("n000", {"cpu": "10", "memory": "10G"})],
        pods=[
            build_pod("ns", "r1", "n000", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1", priority=0),
            build_pod("ns", "h1", "", {"cpu": "1", "memory": "1G"},
                      group="pg2", priority=100),
        ],
        pod_groups=[
            build_pod_group("ns", "pg1", 1, queue="q1"),
            build_pod_group("ns", "pg2", 1, queue="q1",
                            priority_class_name="high"),
        ],
        queues=[build_queue("q1", weight=1)],
        priority_classes=[build_priority_class("high", 100)],
    )
    host_ev, host_pipe = _assert_case(cache)


def test_dense_matches_host_gang_guard():
    """Victim job at its minAvailable floor → gang vetoes, no preemption."""
    cache = make_cache(
        nodes=[build_node("n000", {"cpu": "2", "memory": "2G"})],
        pods=[
            build_pod("ns", "r1", "n000", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1", priority=0),
            build_pod("ns", "r2", "n000", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1", priority=0),
            build_pod("ns", "h1", "", {"cpu": "1", "memory": "1G"},
                      group="pg2", priority=100),
        ],
        pod_groups=[
            build_pod_group("ns", "pg1", 2, queue="q1"),
            build_pod_group("ns", "pg2", 1, queue="q1",
                            priority_class_name="high"),
        ],
        queues=[build_queue("q1", weight=1)],
        priority_classes=[build_priority_class("high", 100)],
    )
    host_ev, host_pipe = _assert_case(cache)
    assert host_ev == set()
    assert host_pipe == {}


def test_dense_matches_host_two_queues():
    """Preempt is in-queue only: victims in another queue are untouchable."""
    cache = make_cache(
        nodes=[build_node("n000", {"cpu": "2", "memory": "2G"})],
        pods=[
            build_pod("ns", "r1", "n000", {"cpu": "2", "memory": "2G"},
                      phase="Running", group="pg1", priority=0),
            build_pod("ns", "h1", "", {"cpu": "1", "memory": "1G"},
                      group="pg2", priority=100),
        ],
        pod_groups=[
            build_pod_group("ns", "pg1", 1, queue="q1"),
            build_pod_group("ns", "pg2", 1, queue="q2",
                            priority_class_name="high"),
        ],
        queues=[build_queue("q1", weight=1), build_queue("q2", weight=1)],
        priority_classes=[build_priority_class("high", 100)],
    )
    host_ev, host_pipe = _assert_case(cache)
    assert host_ev == set()
    assert host_pipe == {}


def test_dense_matches_host_mixed_priorities():
    """Victims with mixed priorities: eviction order must pick the
    lowest-priority ones first on the chosen node."""
    cache = make_cache(
        nodes=[build_node("n000", {"cpu": "3", "memory": "3G"})],
        pods=[
            build_pod("ns", "lo", "n000", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1", priority=0),
            build_pod("ns", "mid", "n000", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1", priority=10),
            build_pod("ns", "mid2", "n000", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1", priority=10),
            build_pod("ns", "h1", "", {"cpu": "1", "memory": "1G"},
                      group="pg2", priority=100),
        ],
        pod_groups=[
            build_pod_group("ns", "pg1", 1, queue="q1"),
            build_pod_group("ns", "pg2", 1, queue="q1",
                            priority_class_name="high"),
        ],
        queues=[build_queue("q1", weight=1)],
        priority_classes=[build_priority_class("high", 100)],
    )
    host_ev, host_pipe = _assert_case(cache)
    assert host_ev == {"ns/lo"}


def test_dense_matches_host_equal_priority_tie():
    """Equal-priority victims: both paths evict the youngest victim
    first (inverse task order — the task-order fallback is creation/uid
    ascending, so its inversion prefers the latest-created)."""
    cache = make_cache(
        nodes=[build_node("n000", {"cpu": "2", "memory": "2G"})],
        pods=[
            build_pod("ns", "va", "n000", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1", priority=0),
            build_pod("ns", "vb", "n000", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1", priority=0),
            build_pod("ns", "h1", "", {"cpu": "1", "memory": "1G"},
                      group="pg2", priority=100),
        ],
        pod_groups=[
            build_pod_group("ns", "pg1", 1, queue="q1"),
            build_pod_group("ns", "pg2", 1, queue="q1",
                            priority_class_name="high"),
        ],
        queues=[build_queue("q1", weight=1)],
        priority_classes=[build_priority_class("high", 100)],
    )
    host_ev, host_pipe = _assert_case(cache)
    assert host_ev == {"ns/vb"}


def test_dense_matches_host_pod_count_limit():
    """A node at its pod-count limit is rejected by the predicates
    plugin in both paths, even when resources would fit."""
    node = build_node("n000", {"cpu": "4", "memory": "4G"})
    node.status.allocatable["pods"] = "1"
    node.status.capacity["pods"] = "1"
    cache = make_cache(
        nodes=[node],
        pods=[
            build_pod("ns", "v1", "n000", {"cpu": "1", "memory": "1G"},
                      phase="Running", group="pg1", priority=0),
            build_pod("ns", "h1", "", {"cpu": "1", "memory": "1G"},
                      group="pg2", priority=100),
        ],
        pod_groups=[
            build_pod_group("ns", "pg1", 1, queue="q1"),
            build_pod_group("ns", "pg2", 1, queue="q1",
                            priority_class_name="high"),
        ],
        queues=[build_queue("q1", weight=1)],
        priority_classes=[build_priority_class("high", 100)],
    )
    host_ev, host_pipe = _assert_case(cache)
    assert host_pipe == {}


# ---- JaxPreemptAction: device-dispatched action ≡ host action ----


def _run_action(cache, action):
    """Run an action on a fresh session → (evicted set, pipelined map).
    Pipelined keys are ns/name (uids are a global counter, so they
    differ between two identically-built caches)."""
    ssn = open_session(cache, FULL_TIERS, [])
    action.execute(ssn)
    pipelined = {}
    for job in ssn.jobs.values():
        for t in job.task_status_index.get(TaskStatus.Pipelined, {}).values():
            pipelined[f"{t.namespace}/{t.name}"] = t.node_name
    close_session(ssn)
    return set(cache.evictor.evicts), pipelined


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_jax_preempt_action_matches_host(seed):
    """JaxPreemptAction on one cache ≡ PreemptAction on an identical
    cache: same evictions, same pipelined placements."""
    from volcano_tpu.actions.jax_preempt import JaxPreemptAction

    host_ev, host_pipe = _run_action(_case_saturated(seed=seed), PreemptAction())
    dev_ev, dev_pipe = _run_action(_case_saturated(seed=seed), JaxPreemptAction())
    assert dev_ev == host_ev
    assert dev_pipe == host_pipe
    assert host_ev  # scenario actually preempts


def test_jax_preempt_action_noop_when_nothing_starves():
    from volcano_tpu.actions.jax_preempt import JaxPreemptAction

    cache = make_cache(
        nodes=[build_node("n000", {"cpu": "4", "memory": "4G"})],
        pods=[build_pod("ns", "r1", "n000", {"cpu": "1", "memory": "1G"},
                        phase="Running", group="pg1", priority=0)],
        pod_groups=[build_pod_group("ns", "pg1", 1, queue="q1")],
        queues=[build_queue("q1", weight=1)],
    )
    ev, pipe = _run_action(cache, JaxPreemptAction())
    assert ev == set() and pipe == {}


def test_jax_preempt_action_tier_fallback():
    """A session whose preemptable tier differs from the supported
    intersection routes to the host action (pack refuses loudly)."""
    from volcano_tpu.actions.jax_preempt import JaxPreemptAction

    bad_tiers = tiers(["priority", "gang", "conformance", "drf"], [])
    cache = _case_saturated(seed=0)
    ssn = open_session(cache, bad_tiers, [])
    JaxPreemptAction().execute(ssn)  # must not raise
    close_session(ssn)
    host_cache = _case_saturated(seed=0)
    hssn = open_session(host_cache, bad_tiers, [])
    PreemptAction().execute(hssn)
    close_session(hssn)
    assert set(cache.evictor.evicts) == set(host_cache.evictor.evicts)


def _case_starving_victim_source():
    """Queue with TWO starving jobs where one of them also has Running
    tasks: evicting its task mid-phase flips its DRF share against the
    other starving job — the frozen pack-time job order cannot see that."""
    nodes = [build_node(f"n{i:03d}", {"cpu": "4", "memory": "8G"})
             for i in range(3)]
    pods, pgs = [], []
    queues = [build_queue("q1", weight=1)]
    # mixed job: running tasks (victim source) + pending (starving)
    pgs.append(build_pod_group("ns", "mixed", 4, queue="q1"))
    for i in range(3):
        pods.append(build_pod("ns", f"mix-r{i}", f"n{i:03d}",
                              {"cpu": "1", "memory": "1G"},
                              phase="Running", group="mixed", priority=0))
    for i in range(2):
        pods.append(build_pod("ns", f"mix-p{i}", "",
                              {"cpu": "2", "memory": "2G"},
                              group="mixed", priority=0))
    # filler job: pure victim source (low priority, min_available 1) so
    # the session really evicts through whatever path runs it
    pgs.append(build_pod_group("ns", "filler", 1, queue="q1"))
    for i in range(3):
        pods.append(build_pod("ns", f"fil-r{i}", f"n{i:03d}",
                              {"cpu": "2", "memory": "2G"},
                              phase="Running", group="filler", priority=0))
    # second starving job in the same queue, higher priority
    pgs.append(build_pod_group("ns", "hungry", 2, queue="q1",
                               priority_class_name="high"))
    for i in range(2):
        pods.append(build_pod("ns", f"hun-{i}", "",
                              {"cpu": "2", "memory": "2G"},
                              group="hungry", priority=100))
    return make_cache(
        nodes=nodes, pods=pods, pod_groups=pgs, queues=queues,
        priority_classes=[build_priority_class("high", 100)],
    )


def test_pack_refuses_starving_victim_source():
    """ADVICE r3 medium: the frozen starving-job order is unsound when a
    victim's job is itself a starving preemptor in a multi-job queue —
    pack must refuse (mirroring reclaim_pack's guard)."""
    cache = _case_starving_victim_source()
    ssn = open_session(cache, FULL_TIERS, [])
    with pytest.raises(ValueError, match="starving preemptor and victim"):
        pack_preempt_session(ssn)
    close_session(ssn)


def test_jax_preempt_action_starving_victim_fallback():
    """The refused session must route through the host action with
    identical evictions/placements."""
    from volcano_tpu.actions.jax_preempt import JaxPreemptAction

    cache = _case_starving_victim_source()
    ssn = open_session(cache, FULL_TIERS, [])
    JaxPreemptAction().execute(ssn)  # must not raise
    jax_pipe = {
        f"{t.namespace}/{t.name}": t.node_name
        for job in ssn.jobs.values()
        for t in job.task_status_index.get(TaskStatus.Pipelined, {}).values()
    }
    close_session(ssn)

    host_cache = _case_starving_victim_source()
    hssn = open_session(host_cache, FULL_TIERS, [])
    PreemptAction().execute(hssn)
    host_pipe = {
        f"{t.namespace}/{t.name}": t.node_name
        for job in hssn.jobs.values()
        for t in job.task_status_index.get(TaskStatus.Pipelined, {}).values()
    }
    close_session(hssn)

    assert set(cache.evictor.evicts) == set(host_cache.evictor.evicts)
    assert jax_pipe == host_pipe


def test_preempt_f32_gate_covers_victims_and_future_idle():
    """ADVICE r3: the pallas-eligibility exactness gate must examine the
    preempt-specific lanes (vic_resreq, node_fi0), not just pk.base."""
    from volcano_tpu.ops.dispatch import preempt_f32_exact
    from volcano_tpu.ops.synthetic import generate_preempt_packed

    pk = generate_preempt_packed(n_victims=100, n_nodes=10, n_preemptors=10)
    assert preempt_f32_exact(pk)
    big = 2**24  # beyond the f32 floor-division envelope
    saved = pk.vic_resreq[0, 0]
    pk.vic_resreq[0, 0] = big
    assert not preempt_f32_exact(pk)
    pk.vic_resreq[0, 0] = saved
    assert preempt_f32_exact(pk)
    pk.node_fi0[0, 0] = big
    assert not preempt_f32_exact(pk)


def test_sensitive_gang_allowance_flips_mid_pass():
    """A victim job with 1 < minAvailable < running-count loses victims
    until the gang floor, then its remaining victims become protected —
    the allowance refresh fires mid-pass and must invalidate the
    kernel's cached masked plane (identical gang-replica preemptor rows
    keep the incremental fast path active around the flip)."""
    nodes = [build_node(f"n{i:03d}", {"cpu": "4", "memory": "8G"})
             for i in range(4)]
    pods, pgs = [], []
    queues = [build_queue("q1", weight=1)]
    # victim job: 4 running tasks, minAvailable 2 -> exactly 2 evictable
    pgs.append(build_pod_group("ns", "vic", 2, queue="q1"))
    for i in range(4):
        pods.append(build_pod("ns", f"vic-r{i}", f"n{i:03d}",
                              {"cpu": "3", "memory": "3G"},
                              phase="Running", group="vic", priority=0))
    # preemptor gang: 4 identical tasks (fast-path rows) wanting 3 nodes
    pgs.append(build_pod_group("ns", "pre", 2, queue="q1",
                               priority_class_name="high"))
    for i in range(4):
        pods.append(build_pod("ns", f"pre-{i}", "",
                              {"cpu": "2", "memory": "2G"},
                              group="pre", priority=100))
    cache = make_cache(
        nodes=nodes, pods=pods, pod_groups=pgs, queues=queues,
        priority_classes=[build_priority_class("high", 100)],
    )
    host_ev, host_pipe = _assert_case(cache)
    assert len(host_ev) == 2, host_ev  # gang floor protects the other two


DRF_TIERS = tiers(
    ["drf", "gang", "conformance"],
    ["priority", "predicates", "proportion", "nodeorder", "binpack"],
)


def _run_host_tiers(cache, tier_conf):
    ssn = open_session(cache, tier_conf, [])
    pk = pack_preempt_session(ssn)
    PreemptAction().execute(ssn)
    pipelined = {}
    for job in ssn.jobs.values():
        for t in job.task_status_index.get(TaskStatus.Pipelined, {}).values():
            pipelined[t.uid] = t.node_name
    close_session(ssn)
    return set(cache.evictor.evicts), pipelined, pk


def _case_drf_imbalance(seed=0):
    """A fat job hogging the cluster vs a starving skinny job in the
    same queue: DRF admits the fat job's tasks as victims (victim share
    stays above the preemptor's), without any PriorityClass involved.
    ``seed`` offsets the object uid/ts counters (builders are global),
    exercising different tie-break landscapes."""
    nodes = [build_node(f"n{i:03d}", {"cpu": "8", "memory": "16G"})
             for i in range(4)]
    pods, pgs = [], []
    queues = [build_queue("q1", weight=1)]
    # fat job: 12 running tasks saturating the cluster
    pgs.append(build_pod_group("ns", "fat", 1, queue="q1"))
    for i in range(12):
        pods.append(build_pod("ns", f"fat-r{i:02d}", f"n{i % 4:03d}",
                              {"cpu": "2", "memory": "2G"},
                              phase="Running", group="fat", priority=0))
    # skinny pending gang
    pgs.append(build_pod_group("ns", "skinny", 2, queue="q1"))
    for i in range(3):
        pods.append(build_pod("ns", f"skin-{i}", "",
                              {"cpu": "2", "memory": "2G"},
                              group="skinny", priority=0))
    return make_cache(nodes=nodes, pods=pods, pod_groups=pgs, queues=queues)


def test_drf_preemptable_dense_matches_host():
    """VERDICT r4 item 7: DRF-preemptable tiers run the dense
    formulation (not host fallback) with evictions/placements identical
    to the host action."""
    cache = _case_drf_imbalance()
    host_ev, host_pipe, pk = _run_host_tiers(cache, DRF_TIERS)
    assert pk.use_drf and not pk.use_prio
    dense_ev, dense_pipe = _dense_outcome(pk)
    assert dense_ev == host_ev
    assert dense_pipe == host_pipe
    assert host_ev, "scenario must actually evict through DRF"


def test_drf_preemptable_mixed_with_priority():
    """priority+drf in one tier: both filters intersect."""
    both = tiers(
        ["priority", "drf", "gang", "conformance"],
        ["predicates", "proportion", "nodeorder", "binpack"],
    )
    cache = _case_drf_imbalance(seed=2)
    host_ev, host_pipe, pk = _run_host_tiers(cache, both)
    assert pk.use_drf and pk.use_prio
    dense_ev, dense_pipe = _dense_outcome(pk)
    assert dense_ev == host_ev
    assert dense_pipe == host_pipe


def test_drf_preemptable_routes_dense_not_pallas():
    from volcano_tpu.ops.dispatch import select_preempt_executor

    cache = _case_drf_imbalance()
    ssn = open_session(cache, DRF_TIERS, [])
    pk = pack_preempt_session(ssn)
    close_session(ssn)
    # force past the small-area gate by checking the flag logic directly
    pk.base.n_tasks, pk.base.n_nodes = 10_000, 10_000
    assert select_preempt_executor(pk) == "dense"


def test_drf_critical_victims_participate_in_subtraction():
    """Conformance removes critical tasks from the EVICTION intersection
    but the host's DRF plugin still subtracts them in its running
    share arithmetic (each plugin scans the full preemptees list) —
    the dense replay must match: host and dense agree even when the
    critical task's subtraction flips a DRF admission."""
    nodes = [build_node("n000", {"cpu": "8", "memory": "16G"})]
    pods, pgs = [], []
    queues = [build_queue("q1", weight=1)]
    pgs.append(build_pod_group("ns", "fat", 1, queue="q1"))
    pods.append(build_pod("ns", "fat-a-crit", "n000",
                          {"cpu": "4", "memory": "4G"},
                          phase="Running", group="fat", priority=0,
                          labels={}))
    # mark critical via the annotation conformance checks
    pods[-1].metadata.annotations["scheduler.alpha.kubernetes.io/critical-pod"] = ""
    pods.append(build_pod("ns", "fat-b", "n000", {"cpu": "4", "memory": "4G"},
                          phase="Running", group="fat", priority=0))
    pgs.append(build_pod_group("ns", "skinny", 1, queue="q1"))
    pods.append(build_pod("ns", "skin-0", "", {"cpu": "2", "memory": "2G"},
                          group="skinny", priority=0))
    cache = make_cache(nodes=nodes, pods=pods, pod_groups=pgs, queues=queues)
    host_ev, host_pipe, pk = _run_host_tiers(cache, DRF_TIERS)
    dense_ev, dense_pipe = _dense_outcome(pk)
    assert dense_ev == host_ev
    assert dense_pipe == host_pipe


def test_drf_preempt_wire_roundtrip():
    """DRF sessions crossing the compute-plane boundary must carry their
    filter flags and share state."""
    from volcano_tpu.ops.preempt_pack import preempt_dense
    from volcano_tpu.serving.compute_plane import (
        deserialize_preempt,
        serialize_preempt,
    )

    cache = _case_drf_imbalance(seed=3)
    ssn = open_session(cache, DRF_TIERS, [])
    pk = pack_preempt_session(ssn)
    close_session(ssn)
    back = deserialize_preempt(serialize_preempt(pk))
    assert back.use_drf and not back.use_prio
    ev_a, pipe_a = preempt_dense(pk)
    ev_b, pipe_b = preempt_dense(back)
    np.testing.assert_array_equal(ev_a, ev_b)
    np.testing.assert_array_equal(pipe_a, pipe_b)


def test_make_preempt_dispatch_prestaged_matches_wrapper():
    # bench compute probe path: prestaged dispatch ≡ run_preempt_pallas
    import numpy as np

    from volcano_tpu.ops.preempt_pallas import (
        make_preempt_dispatch,
        run_preempt_pallas,
    )
    from volcano_tpu.ops.synthetic import generate_preempt_packed

    pk = generate_preempt_packed(n_victims=300, n_nodes=64, n_preemptors=64)
    want_ev, want_pipe = run_preempt_pallas(pk, interpret=True)

    made = make_preempt_dispatch(pk, interpret=True, prestage=True)
    assert made is not None
    dispatch, dims, vic_slot = made
    out = np.asarray(dispatch())
    out2 = np.asarray(dispatch())
    assert (out == out2).all()

    # unpack exactly like run_preempt_pallas
    from volcano_tpu.ops.preempt_pallas import LANES

    K, NS = dims["K"], dims["NS"]
    ev_planes = out[: K * NS].reshape(K, NS, LANES)
    pipe_flat = out[K * NS:].reshape(-1)
    V, P = pk.n_victims, pk.base.n_tasks
    sub = pk.vic_node[:V] // LANES
    lane = pk.vic_node[:V] % LANES
    got_ev = ev_planes[vic_slot[:V], sub, lane] > 0
    got_pipe = pipe_flat[:P].astype(np.int32)
    assert (want_ev == got_ev).all()
    assert (want_pipe == got_pipe).all()
