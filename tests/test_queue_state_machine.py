"""Queue state-machine table — the reference's queue/state/*.go +
queue_controller_test.go pattern: (state, action, podgroup mix) →
(next state, status counts), driven through sync_queue and the
Command-CR channel."""

from __future__ import annotations

import pytest

from volcano_tpu.apis import bus, core, scheduling
from volcano_tpu.client import APIServer, VolcanoClient
from volcano_tpu.controllers.queue_controller import (
    CLOSE_QUEUE_ACTION,
    OPEN_QUEUE_ACTION,
    QueueController,
)

OPEN = scheduling.QUEUE_STATE_OPEN
CLOSED = scheduling.QUEUE_STATE_CLOSED
CLOSING = scheduling.QUEUE_STATE_CLOSING


def _env(queue_state="", podgroup_phases=()):
    api = APIServer()
    qc = QueueController(api)
    vc = VolcanoClient(api)
    vc.create_queue(
        scheduling.Queue(
            metadata=core.ObjectMeta(name="q", namespace=""),
            spec=scheduling.QueueSpec(weight=1, state=queue_state),
        )
    )
    from tests.builders import build_pod_group

    for i, phase in enumerate(podgroup_phases):
        vc.create_pod_group(build_pod_group("ns", f"pg{i}", 1, queue="q", phase=phase))
    qc.drain()  # consume creation events
    return api, qc, vc


P, R, I = (
    scheduling.POD_GROUP_PENDING,
    scheduling.POD_GROUP_RUNNING,
    scheduling.POD_GROUP_INQUEUE,
)

CASES = [
    # (start state, action, podgroup phases, expected end state)
    (OPEN, "", (P, R), OPEN),
    (OPEN, CLOSE_QUEUE_ACTION, (R,), CLOSING),   # drains first
    (OPEN, CLOSE_QUEUE_ACTION, (), CLOSED),      # nothing active → Closed
    (CLOSING, "", (), CLOSED),                   # drain completes
    (CLOSING, "", (R,), CLOSING),                # still active
    (CLOSING, "", (P,), CLOSING),                # pending also blocks
    (CLOSING, "", (I,), CLOSING),                # inqueue also blocks
    (CLOSED, OPEN_QUEUE_ACTION, (), OPEN),
    (CLOSING, OPEN_QUEUE_ACTION, (R,), OPEN),
    (CLOSED, "", (), CLOSED),
]


@pytest.mark.parametrize(
    "start,action,phases,end", CASES,
    ids=[f"{c[0]}-{c[1] or 'sync'}-{len(c[2])}pg" for c in CASES],
)
def test_queue_state_table(start, action, phases, end):
    api, qc, vc = _env(queue_state=start, podgroup_phases=phases)
    qc.sync_queue("q", action=action)
    queue = vc.get_queue("q")
    assert queue.spec.state == end
    assert queue.status.state == end


def test_status_counts_rollup():
    api, qc, vc = _env(podgroup_phases=(P, P, R, I))
    qc.sync_queue("q")
    st = vc.get_queue("q").status
    assert (st.pending, st.running, st.inqueue) == (2, 1, 1)


def test_command_cr_drives_close_then_reopen():
    """bus Command → controller consumes + deletes the CR, state moves
    (queue_controller.go:138-155 / vcctl queue operate)."""
    api, qc, vc = _env(podgroup_phases=(R,))
    vc.create_command(
        bus.Command(
            metadata=core.ObjectMeta(name="cmd1", namespace=""),
            action=CLOSE_QUEUE_ACTION,
            target_object=core.OwnerReference(kind="Queue", name="q"),
        )
    )
    qc.drain()
    assert vc.get_queue("q").spec.state == CLOSING
    assert not vc.list_commands()  # CR consumed and deleted

    # workload drains → Closed
    api.delete("PodGroup", "ns", "pg0")
    qc.drain()
    qc.sync_queue("q")
    assert vc.get_queue("q").spec.state == CLOSED

    vc.create_command(
        bus.Command(
            metadata=core.ObjectMeta(name="cmd2", namespace=""),
            action=OPEN_QUEUE_ACTION,
            target_object=core.OwnerReference(kind="Queue", name="q"),
        )
    )
    qc.drain()
    assert vc.get_queue("q").spec.state == OPEN
