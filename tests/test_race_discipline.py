"""Concurrency discipline — the `go test -race` analogue: hammer the
cache's handler surface, snapshots, and side effects from many threads
and assert state converges with no exceptions (the single-mutex +
immutable-snapshot invariant, cache.go:74).  Plus the env-gated
assertion helper (pkg/scheduler/util/assert)."""

from __future__ import annotations

import threading

import pytest

from volcano_tpu.api.resource import Resource
from volcano_tpu.utils import asserts

from tests.builders import build_node, build_pod, build_pod_group, build_queue
from tests.scheduler_helpers import make_cache


class TestAssertf:
    def test_lenient_by_default(self, monkeypatch, caplog):
        monkeypatch.delenv(asserts.ENV_PANIC, raising=False)
        asserts.assertf(False, "boom %d", 7)  # must not raise

    def test_fatal_when_env_set(self, monkeypatch):
        monkeypatch.setenv(asserts.ENV_PANIC, "1")
        with pytest.raises(AssertionError, match="boom 7"):
            asserts.assertf(False, "boom %d", 7)

    def test_resource_sub_is_env_gated(self, monkeypatch):
        monkeypatch.delenv(asserts.ENV_PANIC, raising=False)
        r = Resource(milli_cpu=100)
        r.sub(Resource(milli_cpu=500))  # logs, continues (reference default)
        assert r.milli_cpu == -400
        monkeypatch.setenv(asserts.ENV_PANIC, "1")
        with pytest.raises(AssertionError):
            Resource(milli_cpu=100).sub(Resource(milli_cpu=500))


class TestCacheConcurrency:
    def test_concurrent_handlers_and_snapshots_converge(self):
        """16 writer threads feeding pod/node events + 4 snapshot readers;
        no exceptions, final accounting exact."""
        cache = make_cache(
            nodes=[build_node(f"n{i}", {"cpu": "64", "memory": "128G"})
                   for i in range(8)],
            pods=[], pod_groups=[build_pod_group("ns", "pg", 1, queue="q")],
            queues=[build_queue("q")],
        )
        errors = []
        barrier = threading.Barrier(20)
        PODS_PER_WORKER = 25

        def writer(w):
            try:
                barrier.wait()
                for i in range(PODS_PER_WORKER):
                    pod = build_pod(
                        "ns", f"p-{w}-{i}", f"n{(w + i) % 8}",
                        {"cpu": "100m", "memory": "64Mi"},
                        phase="Running", group="pg",
                    )
                    cache.add_pod(pod)
                    if i % 3 == 0:
                        cache.delete_pod(pod)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def reader():
            try:
                barrier.wait()
                for _ in range(50):
                    snap = cache.snapshot()
                    # immutable-snapshot invariant: totals are coherent
                    for node in snap.nodes.values():
                        assert node.used.milli_cpu >= 0
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(w,)) for w in range(16)]
        threads += [threading.Thread(target=reader) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors

        kept = 16 * (PODS_PER_WORKER - -(-PODS_PER_WORKER // 3))
        total_used = sum(n.used.milli_cpu for n in cache.nodes.values())
        assert total_used == kept * 100

    def test_snapshot_isolated_from_later_mutation(self):
        cache = make_cache(
            nodes=[build_node("n0", {"cpu": "8", "memory": "16G"})],
            pods=[], pod_groups=[], queues=[build_queue("q")],
        )
        snap = cache.snapshot()
        before = snap.nodes["n0"].used.milli_cpu
        cache.add_pod(build_pod("ns", "p", "n0", {"cpu": "4", "memory": "1G"},
                                phase="Running"))
        assert snap.nodes["n0"].used.milli_cpu == before  # deep copy held
